"""Shared helpers for the runnable demos."""

import os
import subprocess
import sys
import time


def ensure_backend(timeout: float | None = None) -> None:
    """Make the demo runnable whatever backend the environment has.

    The ambient image configures an accelerator backend whose device
    claim goes through an external pool; when the pool is down, the
    FIRST jax operation hangs and the demo dies with ``Unable to
    initialize backend`` — so probe the claim in a subprocess with a
    watchdog (the same pattern ``bench.py`` uses) and fall back to a
    loudly-labelled CPU run instead. Call before any jax work; no-op
    when the process already runs on CPU.
    """
    from delta_crdt_ex_tpu.utils.devices import pin_cpu_platform

    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # even an explicit JAX_PLATFORMS=cpu needs the full pin: the
        # ambient boot hook reads its own pool var ahead of the env
        pin_cpu_platform()
        return
    if timeout is None:
        timeout = float(os.environ.get("EXAMPLES_CLAIM_TIMEOUT", "60"))
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout,
            capture_output=True,
        )
        if proc.returncode == 0:
            return
        reason = proc.stderr.decode(errors="replace").strip().splitlines()
        reason = reason[-1] if reason else f"exit {proc.returncode}"
    except subprocess.TimeoutExpired:
        reason = f"device claim probe hung >{timeout:.0f}s (pool down or wedged)"
    print(
        f"[demo] configured accelerator backend unreachable ({reason}) — "
        "running on CPU instead (labelled fallback)",
        flush=True,
    )
    pin_cpu_platform()


def wait_until(pred, what: str, timeout: float = 30.0) -> None:
    """Poll ``pred`` until true, or exit non-zero — a demo must never
    print success-shaped output for a run that failed to converge."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    sys.exit(f"FAILED: {what} did not happen within {timeout:.0f}s")
