"""Shared helper for the runnable demos."""

import sys
import time


def wait_until(pred, what: str, timeout: float = 30.0) -> None:
    """Poll ``pred`` until true, or exit non-zero — a demo must never
    print success-shaped output for a run that failed to converge."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    sys.exit(f"FAILED: {what} did not happen within {timeout:.0f}s")
