"""Replicas pinned to devices of one mesh: the device data plane.

Each replica's state lives on its own jax device; anti-entropy slices
between them are placed directly on the receiver's device
(`jax.device_put` — free on the same chip, ICI between chips) while the
control plane stays on host. On a CPU host this runs over virtual
devices; the same program on a TPU pod keeps slice bytes off the host
entirely.

Run: python examples/device_plane.py
(defaults to 4 virtual CPU devices; a pre-forced environment —
JAX_PLATFORMS/XLA_FLAGS already set — keeps its own devices)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from delta_crdt_ex_tpu.utils.devices import backend_initialised

if not backend_initialised(default=False):  # allow pre-forced environments
    from delta_crdt_ex_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(4)  # CPU demo default; real hardware uses its own devices

import jax

import delta_crdt_ex_tpu as dc
from examples._util import wait_until

devices = jax.devices()
print(f"mesh devices: {devices}")

replicas = [
    dc.start_link(
        dc.AWLWWMap,
        name=f"shard-{i}",
        sync_interval=0.02,
        capacity=256,
        tree_depth=6,
        device=d,
    )
    for i, d in enumerate(devices)
]
for r in replicas:
    dc.set_neighbours(r, [p for p in replicas if p is not r])

# every replica writes its own keys; the device plane moves the slices
for i, r in enumerate(replicas):
    for k in range(10):
        dc.mutate_async(r, "add", [f"d{i}/k{k}", (i, k)])

want = {f"d{i}/k{k}": (i, k) for i in range(len(replicas)) for k in range(10)}
wait_until(lambda: all(dc.read(r) == want for r in replicas),
           "all-device convergence", timeout=60)
print(f"converged: {len(want)} keys on all {len(replicas)} devices")
for r in replicas:
    assert r.state.leaf.devices() == {r.device}, "state strayed off its device"
    r.stop()
print("states stayed pinned — device plane ok")
