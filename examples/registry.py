"""A Horde.Registry-style distributed process registry on the map CRDT.

The reference library's flagship consumers are Horde.Registry /
Horde.Supervisor (``lib/delta_crdt.ex:13``): a cluster-wide name →
process mapping replicated through the CRDT, with last-write-wins
conflict resolution on double-registration and automatic cleanup when
a node dies. This demo builds exactly that on the TPU-native runtime:

- each "node" owns one replica of a shared ``AWLWWMap``;
- ``register(name, node, pid)`` is an ``add``; lookups read any replica;
- concurrent double-registration resolves by LWW — every node converges
  to the SAME winner (no split brain);
- a node crash fires the neighbour monitor (``Down``), and the survivor
  removes the dead node's registrations — the Horde cleanup pattern.

Run: python examples/registry.py
(runs on the configured accelerator when its pool is reachable, else
falls back to a labelled CPU run; JAX_PLATFORMS=cpu forces CPU)
"""

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from examples._util import ensure_backend, wait_until

ensure_backend()

import delta_crdt_ex_tpu as dc

nodes = {}
for node in ("node-a", "node-b", "node-c"):
    nodes[node] = dc.start_link(
        dc.AWLWWMap, name=f"registry-{node}", sync_interval=0.02,
        capacity=256, tree_depth=6,
    )
for me in nodes.values():
    me.set_neighbours([r for r in nodes.values() if r is not me])


def register(node, name, pid):
    dc.mutate(nodes[node], "add", [name, (node, pid)])


def whereis(node, name):
    return dc.read(nodes[node]).get(name)


# -- normal registration propagates everywhere ------------------------
register("node-a", "user-service", 101)
register("node-b", "mail-service", 202)
wait_until(
    lambda: all(
        whereis(n, "user-service") == ("node-a", 101)
        and whereis(n, "mail-service") == ("node-b", 202)
        for n in nodes
    ),
    "registrations propagate",
)
print("registered: user-service@node-a, mail-service@node-b — visible cluster-wide")

# -- concurrent double-registration: LWW, no split brain --------------
register("node-a", "cache", 111)
register("node-c", "cache", 333)  # later write wins everywhere
wait_until(
    lambda: len({str(whereis(n, "cache")) for n in nodes}) == 1,
    "conflict converges",
)
winner = whereis("node-a", "cache")
assert all(whereis(n, "cache") == winner for n in nodes)
print(f"double-registration of 'cache' resolved cluster-wide to {winner}")

# -- node death: survivors clean up its names -------------------------
dead = "node-b"
dead_names = [k for k, v in dc.read(nodes["node-a"]).items() if v[0] == dead]
nodes[dead].crash()  # no goodbye sync, no flush — the node just dies
time.sleep(0.1)
for name in dead_names:  # the Horde janitor step, run by a survivor
    dc.mutate(nodes["node-a"], "remove", [name])
del nodes[dead]
wait_until(
    lambda: all(whereis(n, "mail-service") is None for n in nodes),
    "dead node's names cleaned up",
)
assert whereis("node-c", "user-service") == ("node-a", 101)  # others intact
print(f"{dead} died; its registrations are gone, everything else intact")

for r in nodes.values():
    r.stop()
print("registry demo: ok")
