"""A two-"node" cluster over real sockets, with persistence and a diff
feed — the capabilities a reference user reaches for in production:
`{name, node}`-style remote addressing, `on_diffs` change feed,
`storage_module` crash recovery.

Run: python examples/tcp_cluster.py
(runs on the configured accelerator when its pool is reachable, else
falls back to a labelled CPU run; JAX_PLATFORMS=cpu forces CPU)
"""

import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from examples._util import ensure_backend, wait_until

ensure_backend()

import delta_crdt_ex_tpu as dc
from delta_crdt_ex_tpu.runtime.storage import FileStorage
from delta_crdt_ex_tpu.runtime.tcp_transport import TcpTransport

node_a, node_b = TcpTransport(), TcpTransport()
state_dir = tempfile.mkdtemp(prefix="crdt-demo-")

changes = []
a = dc.start_link(
    dc.AWLWWMap,
    transport=node_a,
    name="users",
    sync_interval=0.02,
    storage_module=FileStorage(state_dir),
)
b = dc.start_link(
    dc.AWLWWMap,
    transport=node_b,
    name="users",
    sync_interval=0.02,
    on_diffs=changes.append,
)
# one-way edges, set symmetrically — {name, (host, port)} addressing
a.set_neighbours([node_b.remote_addr("users")])
b.set_neighbours([node_a.remote_addr("users")])

dc.mutate(a, "add", ["alice", {"role": "admin"}])
dc.mutate(a, "add", ["bob", {"role": "dev"}])

# a remove only kills OBSERVED entries (observed-remove semantics, same
# as the reference): wait until node B has seen bob before removing him
wait_until(lambda: dc.read(b).get("bob") is not None, "bob reaching node B")
dc.mutate(b, "remove", ["bob"])

want = {"alice": {"role": "admin"}}
wait_until(lambda: dc.read(a) == dc.read(b) == want, "remove propagating")
print("node A:", dc.read(a))
print("node B:", dc.read(b))
print("diff feed at B:", changes)

# crash node A (no clean stop) and rehydrate from disk: same node id,
# same state, sync continues
node_a.close()
node_a2 = TcpTransport()
a2 = dc.start_link(
    dc.AWLWWMap,
    transport=node_a2,
    name="users",
    sync_interval=0.02,
    storage_module=FileStorage(state_dir),
)
a2.set_neighbours([node_b.remote_addr("users")])
b.set_neighbours([node_a2.remote_addr("users")])
dc.mutate(a2, "add", ["carol", {"role": "ops"}])
wait_until(lambda: dc.read(b).get("carol") is not None, "post-rehydrate sync")
print("after crash+rehydrate, node B:", dc.read(b))
for r in (a2, b):
    r.stop()
node_a2.close()
node_b.close()
