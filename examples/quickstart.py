"""Two in-process replicas, the reference README flow.

Run: PYTHONPATH=. python examples/quickstart.py
(CPU works fine: PALLAS_AXON_POOL_IPS= JAX_PLATFORMS=cpu)
"""

import delta_crdt_ex_tpu as dc
from examples._util import wait_until

c1 = dc.start_link(dc.AWLWWMap, sync_interval=0.02)
c2 = dc.start_link(dc.AWLWWMap, sync_interval=0.02)
dc.set_neighbours(c1, [c2])
dc.set_neighbours(c2, [c1])

dc.mutate(c1, "add", ["CRDT", "is magic!"])
wait_until(lambda: dc.read(c2) == {"CRDT": "is magic!"}, "replica 2 convergence")
print("replica 2 sees:", dc.read(c2))
c1.stop()
c2.stop()
