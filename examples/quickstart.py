"""Two in-process replicas, the reference README flow.

Run: python examples/quickstart.py
(runs on the configured accelerator when its pool is reachable, else
falls back to a labelled CPU run; JAX_PLATFORMS=cpu forces CPU)
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from examples._util import ensure_backend, wait_until

ensure_backend()

import delta_crdt_ex_tpu as dc

c1 = dc.start_link(dc.AWLWWMap, sync_interval=0.02)
c2 = dc.start_link(dc.AWLWWMap, sync_interval=0.02)
dc.set_neighbours(c1, [c2])
dc.set_neighbours(c2, [c1])

dc.mutate(c1, "add", ["CRDT", "is magic!"])
wait_until(lambda: dc.read(c2) == {"CRDT": "is magic!"}, "replica 2 convergence")
print("replica 2 sees:", dc.read(c2))
c1.stop()
c2.stop()
