"""Multi-chip SPMD gossip: one replica per device, bounded-divergence
ring anti-entropy over the mesh (ICI bytes ∝ divergence).

Each device applies its own mutation batch inside the SPMD program,
then `gossip_delta_step` exchanges leaf digests with its ring
neighbour, requests only the differing buckets, and joins the returned
slice shard-locally. N-1 steps converge an N-device ring.

Run: python examples/spmd_gossip.py
(defaults to 8 virtual CPU devices; a pre-forced environment —
JAX_PLATFORMS/XLA_FLAGS already set — keeps its own devices, so the
same file runs unchanged on a real multi-chip mesh)
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from delta_crdt_ex_tpu.utils.devices import backend_initialised

if not backend_initialised(default=False):  # allow pre-forced environments
    from delta_crdt_ex_tpu.utils.devices import force_cpu_devices

    force_cpu_devices(8)

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.models.binned_map import BinnedAWLWWMap
from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_PAD
from delta_crdt_ex_tpu.parallel import (
    gossip_delta_drive,
    make_mesh,
    place_states,
    unstack_states,
)

n = len(jax.devices())
print(f"mesh of {n} devices: {jax.devices()}")
mesh = make_mesh()

import dataclasses

L, B, R = 64, 8, 8
states = []
for i in range(n):
    st = BinnedStore.new(L, B, R)
    states.append(
        dataclasses.replace(st, ctx_gid=st.ctx_gid.at[0].set(jnp.uint64(100 + i)))
    )
stacked = place_states(states, mesh)
self_slot = jnp.zeros(n, jnp.int32)

# each replica writes one distinct key inside the SPMD step
groups = [
    BinnedAWLWWMap.group_batch(
        L,
        np.array([OP_ADD], np.int32),
        np.array([1000 + i], np.uint64),
        np.array([7 * i], np.uint32),
        np.array([i + 1], np.int64),
    )
    for i in range(n)
]
u = max(g.rows.shape[0] for g in groups)
m = max(g.op.shape[1] for g in groups)
rows = np.full((n, u), -1, np.int32)
op = np.full((n, u, m), OP_PAD, np.int32)
key = np.zeros((n, u, m), np.uint64)
valh = np.zeros((n, u, m), np.uint32)
ts = np.zeros((n, u, m), np.int64)
for i, g in enumerate(groups):
    gu, gm = g.op.shape
    rows[i, :gu] = g.rows
    op[i, :gu, :gm] = g.op
    key[i, :gu, :gm] = g.key
    valh[i, :gu, :gm] = g.valh
    ts[i, :gu, :gm] = g.ts

batch = tuple(map(jnp.asarray, (rows, op, key, valh, ts)))
empty = tuple(
    jnp.asarray(x)
    for x in (np.full((n, 1), -1, np.int32), np.full((n, 1, 1), OP_PAD, np.int32),
              np.zeros((n, 1, 1), np.uint64), np.zeros((n, 1, 1), np.uint32),
              np.zeros((n, 1, 1), np.int64))
)

stacked, roots, n_diff, _ = gossip_delta_drive(mesh, stacked, self_slot, *batch)
print(f"step 1: differing buckets per hop = {np.asarray(n_diff).tolist()}")
for step in range(2, n + 1):
    stacked, roots, n_diff, _ = gossip_delta_drive(mesh, stacked, self_slot, *empty)
    print(f"step {step}: differing buckets per hop = {np.asarray(n_diff).tolist()}")

roots = np.asarray(roots)
assert (roots == roots[0]).all(), "roots must agree after a full ring pass"
want = {1000 + i: 7 * i for i in range(n)}
for i, st in enumerate(unstack_states(stacked)):
    rws = BinnedAWLWWMap.winner_rows(st, jnp.arange(st.num_buckets, dtype=jnp.int32))
    win = np.asarray(rws.win)
    got = {
        int(k): int(v)
        for k, v in zip(np.asarray(rws.key)[win], np.asarray(rws.valh)[win])
    }
    assert got == want, (i, got)
print(f"converged: all {n} replicas share digest root {roots[0]} and hold {len(want)} keys")
