"""Bulk fan-in on the promoted packed layout: one device call merges a
delta slice into a whole stack of neighbour replica states.

This is the north-star bench shape (`bench.py`) exposed as a library
path: stack the neighbour states, `pack_states` them into the packed
entry layout (chip A/B 2026-07-31: 2.10× over the column layout), and
`fanout_merge_into` joins the slice into every neighbour in one vmapped
call — with the shared tier-escalation ladder handling capacity growth.
The reference loops neighbours one message at a time
(``causal_crdt.ex:264-283``); here the neighbour axis is a batch axis.

This demo speaks the kernel vocabulary (uint64 key hashes / uint32
value hashes, like `bench.py`); the replica runtime (`start_link`)
wraps the same kernels for arbitrary Python keys and values.

Run: python examples/bulk_fanout.py
(runs on the configured accelerator when its pool is reachable, else
falls back to a labelled CPU run; JAX_PLATFORMS=cpu forces CPU)
"""

import dataclasses
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from examples._util import ensure_backend

ensure_backend()

import jax.numpy as jnp
import numpy as np

from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.models.binned_map import BinnedAWLWWMap
from delta_crdt_ex_tpu.ops.apply import OP_ADD
from delta_crdt_ex_tpu.ops.binned import extract_rows
from delta_crdt_ex_tpu.ops.packed import unpack
from delta_crdt_ex_tpu.parallel import (
    fanout_merge_into,
    pack_states,
    stack_states,
    unstack_states,
)

N_NEIGHBOURS = 16
L = 256  # digest-tree leaves / hash buckets


def fresh_state(gid: int) -> BinnedStore:
    """Empty lattice with this writer's gid in context slot 0."""
    st = BinnedStore.new(num_buckets=L, bin_capacity=16, replica_capacity=4)
    return dataclasses.replace(st, ctx_gid=st.ctx_gid.at[0].set(jnp.uint64(gid)))


def apply_adds(state: BinnedStore, keys: np.ndarray, vals: np.ndarray, t0: int):
    """Local mutation batch through the bucket-grouped row kernel."""
    n = len(keys)
    g = BinnedAWLWWMap.group_batch(
        state.num_buckets,
        np.full(n, OP_ADD, np.int32),
        keys.astype(np.uint64),
        vals.astype(np.uint32),
        np.arange(t0, t0 + n, dtype=np.int64),
    )
    res = BinnedAWLWWMap.row_apply(
        state, 0, g.rows, g.op, g.key, g.valh, g.ts
    )
    if not bool(res.ok):  # no retry path at this level; fail loudly
        raise SystemExit("row_apply overflowed its bin tier")
    return res.state


def main():
    rng = np.random.default_rng(0)
    # a writer replica produces a delta; 16 neighbours each hold their
    # own prior state (different gids — the per-neighbour remap is real)
    writer = apply_adds(
        fresh_state(999),
        rng.integers(1, 1 << 63, size=64, dtype=np.uint64),
        np.arange(64), t0=100,
    )
    neighbours = [
        apply_adds(
            fresh_state(100 + i),
            rng.integers(1, 1 << 63, size=4, dtype=np.uint64),
            np.arange(4), t0=1,
        )
        for i in range(N_NEIGHBOURS)
    ]

    # ship the writer's rows as one slice, fan it into all neighbours
    sl = extract_rows(writer, jnp.arange(L, dtype=jnp.int32))
    stacked = pack_states(stack_states(neighbours))
    t0 = time.perf_counter()
    stacked, res, retries = fanout_merge_into(stacked, sl, kill_budget=16)
    dt = time.perf_counter() - t0
    # fanout_merge_into only returns on all-ok (the tier ladder retries
    # or raises otherwise) — no post-check needed here

    outs = unstack_states(unpack(stacked))
    dots = sorted({int(st.alive.sum()) for st in outs})
    print(f"fanned 1 slice into {N_NEIGHBOURS} neighbours in one call: "
          f"{dt*1e3:.1f} ms (compile included), {retries} tier retries, "
          f"every neighbour now holds {dots} live dots (64 merged + 4 local)")


if __name__ == "__main__":
    main()
