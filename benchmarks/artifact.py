"""Artifact freshness classification — dependency-free on purpose.

The resume matrix's skip gate shells into this module per row; keeping
it stdlib-only (no jax, no package imports) makes the gate instant and
immune to backend-claim wedges. ``benchmarks.common`` re-uses the same
predicate for ``load_partial`` so the two can't drift.
"""

from __future__ import annotations

import datetime
import json


def artifact_status(path: str, max_age_s: float = 43200, with_data: bool = False):
    """Classify a results artifact: ``missing`` (absent/unreadable),
    ``stale`` (emitted outside the freshness window), ``partial``
    (fresh, mid-run checkpoint), or ``fresh`` (fresh and complete).
    With ``with_data=True`` returns ``(status, dict | None)`` from ONE
    read of the file, so callers never re-open it (the artifact can be
    atomically replaced between reads by a concurrent run)."""
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, ValueError):
        return ("missing", None) if with_data else "missing"

    def done(status):
        return (status, d) if with_data else status

    try:
        t = datetime.datetime.fromisoformat(d["utc"])
        if t.tzinfo is None:
            t = t.replace(tzinfo=datetime.timezone.utc)
        age = (datetime.datetime.now(datetime.timezone.utc) - t).total_seconds()
    except (KeyError, TypeError, ValueError):
        return done("stale")
    if not (0 <= age < max_age_s):
        return done("stale")
    return done("partial" if d.get("partial") else "fresh")


if __name__ == "__main__":
    import sys

    print(artifact_status(sys.argv[1]))
