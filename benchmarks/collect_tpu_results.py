"""Collect one chip window's evidence into a BASELINE.md-ready digest.

Reads /tmp/northstar.json, benchmarks/results/*.tpu.json, and the
matrix log, then prints (a) a markdown fragment for BASELINE.md's TPU
column and (b) the north-star verdict vs the >=10x target — so a short
chip window spends its minutes measuring, not collating.

Run after ``run_tpu_matrix.sh``: ``python -m benchmarks.collect_tpu_results``
"""

from __future__ import annotations

import glob
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(path):
    """Whole-file JSON (results files may be indented), else the last
    line (the north-star file is captured stdout: stderr noise above,
    artifact line last)."""
    try:
        with open(path) as f:
            text = f.read().strip()
    except OSError:
        return None
    for candidate in (text, text.splitlines()[-1] if text else ""):
        try:
            return json.loads(candidate)
        except ValueError:
            continue
    return None


def main():
    log = sys.argv[1] if len(sys.argv) > 1 else "/tmp/tpu_matrix.log"
    out = []
    # /tmp does not survive container restarts — fall back to the
    # committed copy of the session's north-star line, but NEVER present
    # it as this window's result: label it stale and keep the exit code
    # reporting that THIS window produced no fresh north-star artifact
    ns = _load("/tmp/northstar.json")
    ns_stale = False
    if ns is None:
        ns = _load(os.path.join(REPO, "benchmarks", "results", "northstar.tpu.json"))
        ns_stale = ns is not None
    chip_success = False
    if ns is None:
        out.append("north-star: NO ARTIFACT at /tmp/northstar.json "
                   "or benchmarks/results/northstar.tpu.json")
    elif "error" in ns:
        # bench.py's failure artifacts (claim failure, interrupt, crash)
        # carry an "error" field and exit 0 by contract — never present
        # them as measurements
        out.append(f"north-star: RUN FAILED — {ns.get('metric')}: {ns.get('error')}")
    else:
        ratio = ns.get("vs_baseline", 0)
        fallback = "cpu_fallback" in ns.get("metric", "")
        tag = "  (CPU FALLBACK — not a chip number)" if fallback else ""
        if ns_stale:
            tag += ("  (committed artifact from an EARLIER session — this "
                    "window wrote no fresh north-star)")
        verdict = "MEETS" if ratio >= 10 else "below"
        out.append(f"north-star: {ns.get('value')} merges/sec, vs_baseline {ratio} — {verdict} the >=10x target{tag}")
        if ns.get("secondary_assert_failed"):
            out.append("  WARNING: GROUP=1 secondary tripped its overflow assertion")
        chip_success = not fallback and not ns_stale

    # initialised before the guarded block: the scomp section below
    # reads these even when the north-star artifact is absent/errored
    # (the resume-matrix scenario that only runs the scomp A/B)
    cols = pkd = fus = unf = scp_ns = tk_ns = None
    if ns is not None and "error" not in ns:
        run_tag = "EARLIER session" if ns_stale else "same run"
        cols = ns.get("columns_merges_per_sec")
        pkd = ns.get("packed_merges_per_sec")
        # the resume matrix copies the scomp run's artifact in as the
        # window's north-star — its A/B pair is scomp-vs-top_k
        scp_ns = ns.get("packed_scomp_merges_per_sec")
        tk_ns = ns.get("packed_topk_merges_per_sec")
        if scp_ns and tk_ns:
            out.append(
                f"scomp A/B ({run_tag}): packed_topk {tk_ns} vs packed_scomp "
                f"{scp_ns} merges/sec ({scp_ns / tk_ns:.2f}x) — winner "
                f"'{ns.get('layout')}' is the headline value"
            )
        if cols and pkd:
            out.append(
                f"layout A/B ({run_tag}): columns {cols} vs packed {pkd} "
                f"merges/sec ({pkd / cols:.2f}x) — winner '{ns.get('layout')}' "
                "is the headline value; promote ops/packed.py as the default "
                "layout if packed wins on chip"
            )
        fus = ns.get("packed_fused_merges_per_sec")
        unf = ns.get("packed_unfused_merges_per_sec")
        if fus and unf:
            out.append(
                f"fusion A/B ({run_tag}): packed_unfused {unf} vs "
                f"packed_fused {fus} merges/sec ({fus / unf:.2f}x) — promote "
                "merge_slice_packed_fused to the bench default if the fused "
                "kernel wins on chip"
            )

    # the scomp A/B writes its own artifact (resume_tpu_matrix.sh):
    # top_k-free compaction vs the top_k packed kernel. Same freshness
    # discipline as the group32 probe below: a prior window's copy must
    # not masquerade as this one's verdict.
    from benchmarks.artifact import artifact_status

    sc_status, sc = artifact_status(
        os.path.join(REPO, "benchmarks", "results", "scomp_ab.json"),
        with_data=True,
    )
    sc_tag = "" if sc_status == "fresh" else "  (artifact from an EARLIER session)"
    if sc is not None and "error" not in sc:
        scp = sc.get("packed_scomp_merges_per_sec")
        tk = sc.get("packed_topk_merges_per_sec")
        if scp and tk:
            out.append(
                f"scomp A/B: packed_topk {tk} vs packed_scomp {scp} "
                f"merges/sec ({scp / tk:.2f}x) — promote "
                "merge_slice_packed_scomp to the bench default if the "
                f"top_k-free compaction wins on chip{sc_tag}"
            )
        elif sc.get("value"):
            out.append(
                f"scomp run: {sc.get('value')} merges/sec "
                f"(layout {sc.get('layout')}, no in-run A/B fields){sc_tag}"
            )
    if (
        ns is not None
        and "error" not in ns
        and not (cols and pkd)
        and not (fus and unf)
        and not (scp_ns and tk_ns)
    ):
        out.append("layout A/B: fields absent (BENCH_AB=0 or pre-A/B artifact)")

    # the GROUP=32 dispatch-amortization probe (resume_tpu_matrix.sh):
    # compare against the window's GROUP=16 north-star — but only a
    # comparable one (same-window chip number): a ratio against a
    # CPU-fallback or earlier-session artifact would read as promotion
    # advice computed across different hardware or different windows
    g32_status, g32 = artifact_status(
        os.path.join(REPO, "benchmarks", "results", "group32_v2.json"),
        with_data=True,
    )
    if g32 is not None and "error" not in g32 and g32.get("value"):
        line = (
            f"group32 probe: {g32['value']} merges/sec "
            f"(layout {g32.get('layout')}, group {g32.get('group', 32)})"
        )
        if g32_status != "fresh":
            line += "  (artifact from an EARLIER session)"
        ns_comparable = (
            g32_status == "fresh"
            and ns is not None
            and "error" not in ns
            and ns.get("value")
            and "cpu_fallback" not in ns.get("metric", "")
            and not ns_stale
        )
        if ns_comparable:
            line += (
                f" vs north-star {ns['value']} "
                f"({g32['value'] / ns['value']:.2f}x) — promote BENCH_GROUP=32 "
                "as the bench default if it wins on chip"
            )
        else:
            line += "  (no comparable same-window chip north-star for a ratio)"
        out.append(line)

    rows = []
    for path in sorted(glob.glob(os.path.join(REPO, "benchmarks", "results", "*.tpu.json"))):
        data = _load(path)
        if not data:
            continue
        bench = data.get("bench", os.path.basename(path))
        cells = {
            k: v for k, v in data.items()
            if k not in ("bench", "backend", "devices", "utc", "partial")
        }
        tag = " (PARTIAL — killed mid-run)" if data.get("partial") else ""
        rows.append(f"| {bench}{tag} ({data.get('utc', '?')}) | " +
                    ", ".join(f"{k}={v}" for k, v in cells.items()) + " |")
    if rows:
        out.append("\nTPU harness rows (paste into BASELINE.md):")
        out.extend(rows)
    else:
        out.append("no *.tpu.json results found — did the matrix run on the chip?")

    if os.path.exists(log):
        with open(log, errors="replace") as f:
            lines = [l for l in f if "digest tree:" in l or "group=1 secondary" in l]
        if lines:
            out.append(f"\nkernel evidence from {log}:")
            out.extend("  " + l.strip() for l in lines[-6:])
    else:
        out.append(f"\n(no matrix log at {log} — pass the logfile used by run_tpu_matrix.sh)")

    print("\n".join(out))
    return 0 if chip_success else 1


if __name__ == "__main__":
    sys.exit(main())
