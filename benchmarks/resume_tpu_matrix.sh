#!/bin/bash
# Resume a TPU matrix session that died partway (container restart wiped
# /tmp mid-run on 2026-07-31: smoke + north-star landed, the harness
# rows did not). Runs ONLY the steps whose artifacts are missing,
# most-valuable-first, so another mid-session death still accretes
# evidence. Safe to re-run: each step is skipped once its
# benchmarks/results/*.tpu.json exists.
#
# Usage: bash benchmarks/resume_tpu_matrix.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG="${1:-benchmarks/results/tpu_resume.log}"
say() { echo "[tpu-resume $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }
# failure-shaped bench.py artifact lines carry an "error" field; plain
# success lines never do (same contract as run_tpu_matrix.sh)
ok_line() { case "$1" in ""|*'"error"'*) return 1;; *) return 0;; esac; }

# skip only artifacts FRESH within this round's window (12h), judged by
# the emit() timestamp INSIDE the artifact (file mtimes reset on git
# checkout): a committed artifact from an earlier session must not make
# a future session silently re-present old rows as newly measured, and
# a mid-run partial checkpoint must be re-run (it seeds the re-run via
# load_partial). One shared predicate for harness rows AND bench.py
# probes: benchmarks/artifact.py's artifact_status (common.py imports
# it too; dependency-free — no jax import, so the gate can't block on
# a wedged claim). A fresh FAILURE artifact (error field) re-runs.
probe_fresh() { # artifact -> 0 iff fresh AND not a failure artifact
  [ -f "$1" ] || return 1
  [ "$(timeout 60 python -m benchmarks.artifact "$1" 2>/dev/null)" = "fresh" ] \
    || return 1
  ! grep -q '"error"' "$1"
}

run_row() { # name timeout module [env...]
  local name="$1" tmo="$2" mod="$3"; shift 3
  local art="benchmarks/results/${name}.tpu.json"
  if probe_fresh "$art"; then
    say "$name: fresh artifact exists, skipping"
    return 0
  fi
  say "$name: running (timeout ${tmo}s)"
  if env "$@" timeout "$tmo" python -m "$mod" >>"$LOG" 2>&1; then
    say "$name done"
  else
    say "$name FAILED (rc=$?)"
  fi
}

say "resume session start; devices probe:"
timeout 120 python -c "import jax; print(jax.devices())" >>"$LOG" 2>&1 \
  || { say "chip unreachable, aborting"; exit 1; }

# Pallas verdict first — cheapest high-information probe in the window.
# batched_roots_fn now logs the Mosaic failure reason instead of
# swallowing it (r4 weak #3): either this prints "digest tree: pallas"
# or the epitaph text BASELINE.md needs.
if grep -q "pallas-verdict done" "$LOG" 2>/dev/null; then
  say "pallas verdict: already captured, skipping"
else
  say "pallas verdict probe (batched_roots_fn on the live chip)"
  if timeout 600 python -c "
from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache
enable_compilation_cache()
from delta_crdt_ex_tpu.ops.pallas_tree import batched_roots_fn
fn, tag = batched_roots_fn(16384)
print('digest tree:', tag)
" >>"$LOG" 2>&1; then
    say "pallas verdict done"; echo "pallas-verdict done" >>"$LOG"
  else
    say "pallas verdict probe FAILED (rc=$?)"
  fi
fi

run_row basic_operations 1800 benchmarks.basic_operations

# the attribution probes come BEFORE the slow runtime-driven rows: they
# decide the next kernel move, and the tunnel-dispatch-bound harness
# rows can eat a whole fragile claim window. Reduced width — the
# 64-wide gather probes' ~6 GiB of allocs wedged the first session.
if grep -q "merge-parts done" "$LOG" 2>/dev/null; then
  say "profile_merge_parts: already done, skipping"
else
  say "profile_merge_parts: running at N=16 (timeout 900s)"
  if MERGE_PARTS_NEIGHBOURS=16 timeout 900 python -m benchmarks.profile_merge_parts >>"$LOG" 2>&1; then
    say "profile_merge_parts done"; echo "merge-parts done" >>"$LOG"
  else
    say "profile_merge_parts FAILED (rc=$?)"
  fi
fi

# shared bench.py probe runner (same freshness gate as run_row).
# Returns 0 only when the probe RAN and succeeded; 2 on fresh-skip —
# callers with post-run actions (the scomp → north-star copy) must not
# treat a skipped old artifact as this window's measurement.
run_bench_probe() { # name timeout outfile [env...]
  local name="$1" tmo="$2" out="$3"; shift 3
  if probe_fresh "$out"; then
    say "$name: fresh artifact exists, skipping"
    return 2
  fi
  say "$name: running (timeout ${tmo}s)"
  # write aside and promote only on success: a failed run must not
  # truncate an earlier session's good artifact (the digest labels
  # those "EARLIER session" rather than losing them)
  env "$@" timeout "$tmo" python bench.py > "$out.new" 2>>"$LOG"
  local line
  line=$(tail -1 "$out.new" 2>/dev/null)
  if ok_line "$line"; then
    mv "$out.new" "$out"
    say "$name: $line"
    return 0
  fi
  say "$name FAILED: $line (failure line kept at $out.new)"
  return 1
}

# north-star with the PROMOTED scomp primary and top_k as the in-run
# alternate (BENCH_SCOMP defaults on since round 5): one run decides
# whether the promotion holds on chip AND refreshes the north-star —
# a success is copied to northstar.tpu.json so the digest and BASELINE
# see it as this window's headline.
if run_bench_probe "scomp A/B" 2400 benchmarks/results/scomp_ab.json \
    BENCH_SCOMP=1 BENCH_TOTAL_BUDGET=2200 BENCH_CLAIM_TIMEOUT=120 \
    BENCH_CLAIM_ATTEMPTS=2 BENCH_TPU_TIMEOUT=2000 BENCH_NO_CPU_FALLBACK=1; then
  cp benchmarks/results/scomp_ab.json benchmarks/results/northstar.tpu.json
  cp benchmarks/results/scomp_ab.json /tmp/northstar.json 2>/dev/null || true
  say "north-star artifact refreshed from the scomp run"
fi

# attribution of the promoted kernel's remaining per-call cost (the
# pair-compaction scatter + coverage preamble are the CPU-side terms;
# chip numbers decide the next lever — benchmarks/profile_scomp_parts.py)
if grep -q "scomp-parts done" "$LOG" 2>/dev/null; then
  say "profile_scomp_parts: already done, skipping"
else
  say "profile_scomp_parts: running at N=16 (timeout 900s)"
  if SCOMP_PARTS_NEIGHBOURS=16 timeout 900 python -m benchmarks.profile_scomp_parts >>"$LOG" 2>&1; then
    say "profile_scomp_parts done"; echo "scomp-parts done" >>"$LOG"
  else
    say "profile_scomp_parts FAILED (rc=$?)"
  fi
fi

# GROUP=32 re-probe under scomp v2: r4 rejected 32 for the TOP_K kernel
# (its sort is superlinear in slice size) — that term is gone, v2's
# G-sized work is linear, and doubling GROUP doubles dispatch
# amortization; CPU measures a wash (3,070 vs 3,099 median), so the
# chip decides. Lane width left to the Poisson formula (9 at GROUP=32):
# pinning 8 gives a ~12%/run chance the stream generator's honest
# overflow raise aborts the probe (P(Poisson(1) >= 9) x 16k buckets x
# 7 slices), and r4 measured the width-9 penalty at only ~3%.
run_bench_probe "group32 v2" 1600 benchmarks/results/group32_v2.json \
  BENCH_GROUP=32 BENCH_AB=0 BENCH_TOTAL_BUDGET=1500 \
  BENCH_CLAIM_TIMEOUT=120 BENCH_CLAIM_ATTEMPTS=2 BENCH_TPU_TIMEOUT=1300 \
  BENCH_NO_CPU_FALLBACK=1 || true

say "graft entry compile check (single chip)"
timeout 900 python -c "
import __graft_entry__ as g, jax
fn, args = g.entry()
out = jax.jit(fn)(*args)
jax.block_until_ready(out)
print('entry ok:', jax.devices())
" >>"$LOG" 2>&1 && say "entry compile OK" || say "entry compile FAILED"

run_row ring_device 900 benchmarks.ring_device
run_row ring_bench 1800 benchmarks.ring_bench
run_row full_bench 2400 benchmarks.full_bench
run_row mesh_gossip 1200 benchmarks.mesh_gossip
# the propagation pairs converge 20k/30k keys through the tunnel before
# every timed cell and only emit after all four cells — give them the
# big timeout and the last slot so a mid-row kill costs nothing else
run_row propagation 2700 benchmarks.propagation
run_row propagation_devplane 2700 benchmarks.propagation PROP_DEVICE_PLANE=1

say "collecting digest"
# the digest's exit code answers "did THIS window write a fresh
# north-star" — the resume path never runs bench.py, so exit 1 is the
# expected answer here, not a failure; only a missing output file is
timeout 300 python -m benchmarks.collect_tpu_results "$LOG" \
  >> benchmarks/results/tpu_digest.txt 2>&1
if [ -s benchmarks/results/tpu_digest.txt ]; then
  say "digest written (tpu_digest.txt)"
else
  say "digest FAILED (no output)"
fi
say "resume session complete"
