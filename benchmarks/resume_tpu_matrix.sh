#!/bin/bash
# Resume a TPU matrix session that died partway (container restart wiped
# /tmp mid-run on 2026-07-31: smoke + north-star landed, the harness
# rows did not). Runs ONLY the steps whose artifacts are missing,
# most-valuable-first, so another mid-session death still accretes
# evidence. Safe to re-run: each step is skipped once its
# benchmarks/results/*.tpu.json exists.
#
# Usage: bash benchmarks/resume_tpu_matrix.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG="${1:-benchmarks/results/tpu_resume.log}"
say() { echo "[tpu-resume $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

run_row() { # name timeout module [env...]
  local name="$1" tmo="$2" mod="$3"; shift 3
  if [ -f "benchmarks/results/${name}.tpu.json" ]; then
    say "$name: artifact exists, skipping"
    return 0
  fi
  say "$name: running (timeout ${tmo}s)"
  if env "$@" timeout "$tmo" python -m "$mod" >>"$LOG" 2>&1; then
    say "$name done"
  else
    say "$name FAILED (rc=$?)"
  fi
}

say "resume session start; devices probe:"
timeout 120 python -c "import jax; print(jax.devices())" >>"$LOG" 2>&1 \
  || { say "chip unreachable, aborting"; exit 1; }

run_row basic_operations 1800 benchmarks.basic_operations
run_row propagation 1800 benchmarks.propagation
run_row propagation_devplane 1800 benchmarks.propagation PROP_DEVICE_PLANE=1
run_row ring_bench 1800 benchmarks.ring_bench
run_row full_bench 2400 benchmarks.full_bench
run_row mesh_gossip 1200 benchmarks.mesh_gossip

say "graft entry compile check (single chip)"
timeout 900 python -c "
import __graft_entry__ as g, jax
fn, args = g.entry()
out = jax.jit(fn)(*args)
jax.block_until_ready(out)
print('entry ok:', jax.devices())
" >>"$LOG" 2>&1 && say "entry compile OK" || say "entry compile FAILED"

# last because it timed out at 1800s in the first session (the 64-wide
# gather probes alloc ~6 GiB on-device); run at reduced width so a hang
# costs 900s not 30min and the arrays fit comfortably
if grep -q "merge-parts done" "$LOG" 2>/dev/null; then
  say "profile_merge_parts: already done, skipping"
else
  say "profile_merge_parts: running at N=16 (timeout 900s)"
  if MERGE_PARTS_NEIGHBOURS=16 timeout 900 python -m benchmarks.profile_merge_parts >>"$LOG" 2>&1; then
    say "profile_merge_parts done"; echo "merge-parts done" >>"$LOG"
  else
    say "profile_merge_parts FAILED (rc=$?)"
  fi
fi

say "collecting digest"
timeout 300 python -m benchmarks.collect_tpu_results "$LOG" \
  >> benchmarks/results/tpu_digest.txt 2>&1 \
  && say "digest written" || say "digest FAILED"
say "resume session complete"
