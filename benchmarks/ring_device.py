"""Chip-resident 8-replica ring convergence — the device-side twin of
``ring_bench.py``.

The runtime ring bench drives 8 threaded replicas through the host
control plane, so on a tunnelled TPU it measures per-op dispatch, not
the engine. This bench keeps the SAME workload shape (8 replicas in a
one-way ring, N keys written at replica 0, clock stops when every
replica's digest root agrees) but entirely device-resident: the ring is
a stacked state batch, one writes-included ``ring_gossip_round`` call
gossips every hop simultaneously, and convergence takes exactly N-1
rounds — the ``shard_map`` multi-chip path's cost model measured on one
chip (``parallel/batched_sync.py::ring_gossip_round``; reference analog
``bench/propagation.exs`` 8-replica ring).

Emits: rounds/sec, total convergence wall-clock, and per-round ms at
the BASELINE ring config (10k keys).

Run: ``python -m benchmarks.ring_device [N ...]``  (default 10000)
"""

from __future__ import annotations

import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache

enable_compilation_cache()

from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.ops.binned import tree_from_leaves
from delta_crdt_ex_tpu.parallel import ring_gossip_round, stack_states
from delta_crdt_ex_tpu.utils.synth import build_state
from benchmarks.common import emit, log

RING = 8
TREE_DEPTH = 12  # matches ring_bench's runtime geometry


def run(number: int) -> dict:
    L = 1 << TREE_DEPTH
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 1 << 63, size=number, dtype=np.uint64)

    # replica 0 holds the N written keys; 1..7 start empty (same gids
    # per slot as the runtime ring would negotiate)
    bin_cap = 16
    while bin_cap * L < 4 * number:
        bin_cap *= 2
    writer, _ = build_state(11, keys, num_buckets=L, bin_capacity=bin_cap)
    empties = [
        BinnedStore.new(num_buckets=L, bin_capacity=bin_cap)
        for _ in range(RING - 1)
    ]
    stacked = stack_states([writer, *empties])
    jax.block_until_ready(stacked)

    roots_of = jax.jit(jax.vmap(lambda lf: tree_from_leaves(lf)[0][0]))

    # compile BOTH jitted programs outside the clock (the runtime
    # bench's warm phase analog) — a first-call trace inside the timed
    # loop would dominate a 7-round convergence
    res = ring_gossip_round(stacked)
    jax.block_until_ready(roots_of(res.state.leaf))

    stacked = stack_states([writer, *empties])  # fresh start for timing
    jax.block_until_ready(stacked)
    t0 = time.perf_counter()
    rounds = 0
    all_ok = True
    while rounds < 4 * RING:
        res = ring_gossip_round(stacked)
        stacked = res.state
        rounds += 1
        all_ok &= bool(np.asarray(res.ok).all())
        roots = np.asarray(roots_of(stacked.leaf))
        if bool((roots == roots[0]).all()):
            break
    jax.block_until_ready(stacked)
    conv_s = time.perf_counter() - t0
    if not all_ok:
        raise SystemExit("ring merge overflowed a tier")
    if rounds >= 4 * RING:
        raise SystemExit("ring did not converge within 4*RING rounds")

    log(
        f"device ring({RING}) {number} keys: {rounds} rounds, "
        f"{conv_s:.3f}s total, {conv_s / rounds * 1e3:.1f} ms/round "
        f"({rounds / conv_s:.1f} rounds/sec, incl. per-round root check)"
    )
    return {
        f"converge_s@{number}": round(conv_s, 3),
        f"rounds@{number}": rounds,
        f"ms_per_round@{number}": round(conv_s / rounds * 1e3, 2),
    }


def main(sizes=(10_000,)):
    results = {}
    for n in sizes:
        results.update(run(n))
    emit("ring_device", results)
    return results


if __name__ == "__main__":
    sizes = tuple(int(a) for a in sys.argv[1:]) or (10_000,)
    main(sizes)
