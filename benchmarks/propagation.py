"""Port of ``bench/propagation.exs``: propagation latency into a
pre-synced 2-replica pair.

Prepare: fill c1 with N keys, wait until c2 converges (BenchRecorder
sentinel on c2's ``on_diffs``), then ``hibernate`` + ``ping`` both
replicas (reference ``propagation.exs:61-64``). Measure: wall-clock for
10 adds / 10 removes at c1 to be observed at c2, with real background
sync threads at ``sync_interval`` 5 ms (reference ``:38-44``).

Run: ``python -m benchmarks.propagation [N ...]``  (default 20000 30000)

``PROP_DEVICE_PLANE=1`` pins both replicas to the first jax device, so
sync slices ride the device data plane (on one real chip: same-device
puts — slice columns never take the host round trip). The emitted
result rows gain a ``@dev`` suffix so the two planes never mix in the
results file.
"""

from __future__ import annotations

import os
import sys
import time

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from benchmarks.common import BenchRecorder, emit, emit_partial, load_partial, log

DEVICE_PLANE = os.environ.get("PROP_DEVICE_PLANE") == "1"


def _pin_device():
    if not DEVICE_PLANE:
        return None
    import jax

    return jax.devices()[0]


def prepare(number):
    transport = LocalTransport()
    rec = BenchRecorder()
    dev = _pin_device()
    c1 = start_link(AWLWWMap, transport=transport, sync_interval=0.005,
                    capacity=max(4096, 4 * number), tree_depth=12, max_sync_size=500,
                    device=dev)
    c2 = start_link(AWLWWMap, transport=transport, sync_interval=0.005,
                    on_diffs=rec.on_diffs, device=dev,
                    capacity=max(4096, 4 * number), tree_depth=12, max_sync_size=500)
    c1.set_neighbours([c2])
    c2.set_neighbours([c1])
    for x in range(1, number + 1):
        c1.mutate_async("add", [x, x])
    assert rec.wait(number, "add", timeout=120), "initial convergence timed out"
    # the sentinel key can arrive while truncated sync rounds are still
    # draining the backlog (max_sync_size bounds each round) — wait for
    # REAL convergence so the timed phase measures only the 10-op
    # propagation, not leftover backlog
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline and len(c2.read()) != number:
        time.sleep(0.02)
    assert len(c2.read()) == number, "full convergence timed out"
    # warm the small-tier sync kernels with round trips matching the
    # timed phase's shapes (1-op and 10-op rounds → the 8/16-row slice
    # tiers): first-time jit compiles must not land inside the timing
    c1.mutate("add", [0, 0])
    assert rec.wait(0, "add"), "warm add timed out"
    for x in range(-10, 0):
        c1.mutate("add", [x, x])
    for x in range(-10, 0):
        assert rec.wait(x, "add"), "warm adds timed out"
    for x in list(range(-10, 0)) + [0]:
        c1.mutate("remove", [x])
    for x in list(range(-10, 0)) + [0]:
        assert rec.wait(x, "remove"), "warm removes timed out"
    c1.hibernate(), c2.hibernate()
    c1.ping(), c2.ping()
    return transport, rec, c1, c2


def perform(pair, op):
    transport, rec, c1, c2 = pair
    t0 = time.perf_counter()
    if op == "add":
        for x in range(100_000, 100_011):
            c1.mutate("add", [x, x])
        assert rec.wait(100_010, "add"), "add propagation timed out"
    else:
        for x in range(1, 11):
            c1.mutate("remove", [x])
        assert rec.wait(10, "remove"), "remove propagation timed out"
    dt = time.perf_counter() - t0
    c1.stop()
    c2.stop()
    return dt


def main(sizes=(20_000, 30_000)):
    results = {}
    tag = "@dev" if DEVICE_PLANE else ""
    # separate results file per plane — emit() rewrites whole files, and
    # a device-plane run must not clobber the host-plane rows
    name = "propagation_devplane" if DEVICE_PLANE else "propagation"
    # each cell converges tens of thousands of keys through the
    # (possibly tunnel-slow) backend before its timed 10 ops — resume a
    # killed run's finished cells and checkpoint after every cell
    results.update(load_partial(name))
    todo = [
        (n, op)
        for n in sizes
        for op in ("add", "remove")
        if f"{op}10@{n}{tag}" not in results
    ]
    for i, (n, op) in enumerate(todo):
        log(f"preparing {n}-key pair for {op}{tag}…")
        dt = perform(prepare(n), op)
        results[f"{op}10@{n}{tag}"] = round(dt * 1000, 2)
        log(f"{op} 10 into {n}-key pair{tag}: {dt*1000:.1f} ms")
        if i + 1 < len(todo):
            emit_partial(name, results)
    emit(name, results)
    return results


if __name__ == "__main__":
    sizes = tuple(int(a) for a in sys.argv[1:]) or (20_000, 30_000)
    main(sizes)
