"""Port of ``bench/full_bench.exs``: 2-replica convergence wall-clock.

Add N keys at c1, wait until c2 observes key N via ``on_diffs``; then
remove all N, wait until c2 observes the removal of N — with
``sync_interval`` 20 ms and ``max_sync_size`` 500, background sync
threads (reference ``full_bench.exs:1-63``).

Run: ``python -m benchmarks.full_bench [N ...]``
(default 10 100 1000 10000 20000 30000)
"""

from __future__ import annotations

import sys
import time

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from benchmarks.common import emit, emit_partial, load_partial, log


def do_test(number):
    transport = LocalTransport()
    seen = {"add": False, "remove": False}

    def on_diffs(diffs):
        for d in diffs:
            if d[0] == "add" and d[1] == number:
                seen["add"] = True
            if d[0] == "remove" and d[1] == number:
                seen["remove"] = True

    kw = dict(transport=transport, sync_interval=0.02, max_sync_size=500,
              capacity=max(4096, 4 * number), tree_depth=12)
    c1 = start_link(AWLWWMap, **kw)
    c2 = start_link(AWLWWMap, on_diffs=on_diffs, **kw)
    c1.set_neighbours([c2])
    c2.set_neighbours([c1])

    t0 = time.perf_counter()
    for x in range(1, number + 1):
        c1.mutate_async("add", [x, x])
    deadline = time.monotonic() + 120
    while not seen["add"] and time.monotonic() < deadline:
        time.sleep(0.005)
    assert seen["add"], f"add convergence timed out at N={number}"
    t_add = time.perf_counter() - t0

    t0 = time.perf_counter()
    for x in range(1, number + 1):
        c1.mutate_async("remove", [x])
    while not seen["remove"] and time.monotonic() < deadline:
        time.sleep(0.005)
    assert seen["remove"], f"remove convergence timed out at N={number}"
    t_remove = time.perf_counter() - t0

    c1.stop()
    c2.stop()
    return t_add, t_remove


def main(sizes=(10, 100, 1000, 10_000, 20_000, 30_000)):
    # resume a killed run's cells, and checkpoint after every size: a
    # watchdog kill on a tunnel-slow backend keeps the finished cells
    results = load_partial("full_bench")
    todo = [
        n for n in sizes
        if not (f"add@{n}" in results and f"remove@{n}" in results)
    ]
    for i, n in enumerate(todo):
        t_add, t_remove = do_test(n)
        results[f"add@{n}"] = round(t_add, 3)
        results[f"remove@{n}"] = round(t_remove, 3)
        log(f"N={n}: add+converge {t_add:.3f}s, remove+converge {t_remove:.3f}s")
        if i + 1 < len(todo):
            emit_partial("full_bench", results)
    emit("full_bench", results)
    return results


if __name__ == "__main__":
    sizes = tuple(int(a) for a in sys.argv[1:]) or (10, 100, 1000, 10_000, 20_000, 30_000)
    main(sizes)
