#!/bin/bash
# Follow-on claim waiter with an end-of-round deadline: probes until
# DEADLINE_UTC (HH:MM, default 15:00) and fires the resume matrix on
# recovery. The deadline keeps a late recovery from starting a ~1-2h
# matrix that would still be holding the claim when the round driver
# runs its own bench.
set -u
cd "$(dirname "$0")/.."
LOG="${1:-benchmarks/results/claim_wait.log}"
DEADLINE="${DEADLINE_UTC:-15:00}"
say() { echo "[claim-wait2 $(date -u +%H:%M:%S)] $*" | tee -a "$LOG"; }

while true; do
  now=$(date -u +%H:%M)
  if [ "$(printf '%s\n' "$now" "$DEADLINE" | sort | tail -1)" = "$now" ] \
     && [ "$now" != "$DEADLINE" ]; then
    say "deadline $DEADLINE UTC reached with the claim still wedged — stopping"
    exit 1
  fi
  if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    say "claim recovered — firing resume matrix"
    bash benchmarks/resume_tpu_matrix.sh benchmarks/results/tpu_resume.log
    say "resume matrix finished"
    exit 0
  fi
  say "claim still wedged — sleeping 120s"
  sleep 120
done
