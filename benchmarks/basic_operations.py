"""Port of ``bench/basic_operations.exs``: single-replica op latency.

Times ``read`` / ``add`` (new key) / ``update`` (existing key) /
``remove`` on pre-filled 1k- and 10k-key maps, with the reference's
``before_each`` churn (re-add key 10, remove "key4"). Also reports the
TPU-native batched write path (``mutate_async`` + one flush), which is
how this framework is meant to be driven.

Run: ``python -m benchmarks.basic_operations``
"""

from __future__ import annotations

import os
import time

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from benchmarks.common import emit, log


def setup_crdt(n):
    crdt = start_link(AWLWWMap, threaded=False, capacity=max(2048, 4 * n), tree_depth=10)
    for x in range(n):
        crdt.mutate_async("add", [x + 1, x + 1])
    crdt.flush()
    return crdt


def time_op(crdt, fn, iters=200):
    # before_each churn, mirroring the reference
    for _ in range(3):  # burn-in
        crdt.mutate("add", [10, 10])
        crdt.mutate("remove", ["key4"])
        fn(crdt)
    t0 = time.perf_counter()
    for _ in range(iters):
        crdt.mutate("add", [10, 10])
        crdt.mutate("remove", ["key4"])
        fn(crdt)
    per_iter = (time.perf_counter() - t0) / iters
    return per_iter


def main():
    results = {}
    for n in (1000, 10_000):
        crdt = setup_crdt(n)
        ops = {
            "read": lambda c: c.read(),
            "add": lambda c: c.mutate("add", ["key4", "value"]),
            "update": lambda c: c.mutate("add", [10, 12]),
            "remove": lambda c: c.mutate("remove", [10]),
        }
        for name, fn in ops.items():
            per = time_op(crdt, fn)
            results[f"{name}@{n}"] = round(1.0 / per, 1)
            log(f"{name} @ {n} keys: {1.0/per:.1f} composite-iters/sec")

        # TPU-native batched writes: 1000 adds in one flush
        t0 = time.perf_counter()
        for x in range(1_000_000, 1_001_000):
            crdt.mutate_async("add", [x, x])
        crdt.flush()
        dt = time.perf_counter() - t0
        results[f"batched_add@{n}"] = round(1000 / dt, 1)
        log(f"batched add @ {n} keys: {1000/dt:.1f} ops/sec")
        crdt.stop()

    if not os.environ.get("BENCH_SKIP_1M"):
        # read-at-scale: full LWW read of a 1M-key map (VERDICT r1 #6 —
        # the reference's read is a full-map Enum.max_by pass,
        # aw_lww_map.ex:211-216; target: single-digit seconds)
        crdt = start_link(AWLWWMap, threaded=False, capacity=2_000_000, tree_depth=14)
        t0 = time.perf_counter()
        for x in range(1_000_000):
            crdt.mutate_async("add", [x, x])
        crdt.flush()
        dt = time.perf_counter() - t0
        results["bulk_load_1m_ops_per_sec"] = round(1_000_000 / dt, 1)
        log(f"bulk load 1M keys: {1_000_000/dt:.0f} ops/sec ({dt:.1f}s)")
        t0 = time.perf_counter()
        m = crdt.read()
        dt = time.perf_counter() - t0
        assert len(m) == 1_000_000 and m[123456] == 123456
        results["read_1m_s"] = round(dt, 3)
        log(f"full read of 1M-key map (maintained cache): {dt:.3f}s")
        # the post-merge path: cache invalidated, full winner pass rebuild
        crdt._read_cache = None
        t0 = time.perf_counter()
        m = crdt.read()
        dt = time.perf_counter() - t0
        assert len(m) == 1_000_000
        results["read_1m_cold_rebuild_s"] = round(dt, 2)
        log(f"full read of 1M-key map (cold winner-pass rebuild): {dt:.2f}s")
        crdt.read_keys(list(range(100, 1100)))  # warm the partial-read compile
        t0 = time.perf_counter()
        part = crdt.read_keys(list(range(5000, 6000)))
        dt = time.perf_counter() - t0
        assert len(part) == 1000
        results["read_keys_1k_of_1m_ms"] = round(dt * 1e3, 2)
        log(f"partial read (1k of 1M): {dt*1e3:.1f} ms")
        crdt.stop()

    emit("basic_operations", results)
    return results


if __name__ == "__main__":
    main()
