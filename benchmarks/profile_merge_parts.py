"""Phase-level timing of the north-star merge on the live device.

Compares ``merge_slice`` vs ``merge_rows`` on the bench workload and
times isolated pieces (slice-view preamble, insert sort, element
scatters, kill pass) to attribute the per-call cost.
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache

enable_compilation_cache()

from delta_crdt_ex_tpu.ops.binned import (
    _slice_view,
    entry_hash,
    merge_rows,
    merge_slice,
)
from delta_crdt_ex_tpu.utils.synth import build_state, interval_delta_stream

N_KEYS = 1_000_000
TREE_DEPTH = 14
BIN_CAP = 128
# 64 is the bench fan-in, but at 64 the standalone gather probes alloc
# ~6 GiB of device arrays on top of the broadcast state stack and the
# first chip session wedged for its full 30-min timeout; per-neighbour
# numbers are width-independent, so default to a width that fits easily.
NEIGHBOURS = int(os.environ.get("MERGE_PARTS_NEIGHBOURS", "16"))
DELTA = 512
GROUP = 16
RCAP = 8

from benchmarks.common import log  # shared stderr logger


def timed(fn, n=6):
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    L = 1 << TREE_DEPTH
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 1 << 63, size=N_KEYS, dtype=np.uint64)
    log(f"devices: {jax.devices()}")

    one, _ = build_state(11, keys, num_buckets=L, bin_capacity=BIN_CAP,
                         replica_capacity=RCAP)
    jax.block_until_ready(one)
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.copy(jnp.broadcast_to(x, (NEIGHBOURS,) + x.shape)), one
    )
    jax.block_until_ready(stacked)

    slices, _ = interval_delta_stream(22, rng, 1, GROUP * DELTA, L, bin_width=8)
    sl = slices[0]
    log(f"slice shape: rows={sl.rows.shape} entries={sl.key.shape}")

    @jax.jit
    def f_slice(states, s):
        res = jax.vmap(merge_slice, in_axes=(0, None, None, None))(
            states, s, 8, GROUP * DELTA
        )
        return res.state.leaf, res.ok

    log(f"merge_slice x{NEIGHBOURS}: {timed(lambda: f_slice(stacked, sl))*1e3:.1f} ms")

    @jax.jit
    def f_rows(states, s):
        res = jax.vmap(merge_rows, in_axes=(0, None))(states, s)
        return res.state.leaf, res.ok

    log(f"merge_rows  x{NEIGHBOURS}: {timed(lambda: f_rows(stacked, sl))*1e3:.1f} ms")

    @jax.jit
    def f_view(states, s):
        v = jax.vmap(lambda st: _slice_view(st, s))(states)
        return v.ins, v.rdense

    log(f"_slice_view x{NEIGHBOURS}: {timed(lambda: f_view(stacked, sl))*1e3:.1f} ms")

    # element scatters alone: one column, full 8192-entry compacted scatter
    u, s_w = sl.key.shape
    B = BIN_CAP

    @jax.jit
    def f_scatter(states, s):
        rows_clip = jnp.clip(s.rows, 0, L - 1)
        pos = states.fill[:, rows_clip][:, :, None] + jnp.broadcast_to(
            jnp.arange(s_w, dtype=jnp.int32), (u, s_w)
        )
        flat = rows_clip[:, None] * B + jnp.clip(pos, 0, B - 1)  # [N, U, S]
        def one_col(col, fl):
            return col.reshape(-1).at[fl.reshape(-1)].set(
                s.ctr.reshape(-1), mode="drop"
            )
        return jax.vmap(one_col)(states.ctr, flat)

    log(f"1-col scatter x{NEIGHBOURS}: {timed(lambda: f_scatter(stacked, sl))*1e3:.1f} ms")

    # does one vector-valued scatter amortise the per-index cost that 7
    # scalar-column scatters pay separately? (informs a packed-layout
    # refactor: entries as [L, B, W] words, one scatter per merge)
    E = 8192
    idx = jnp.asarray(
        rng.choice(L * B, size=E, replace=False).astype(np.int64)
    )
    vals1 = jnp.arange(E, dtype=jnp.uint32)
    vals8 = jnp.broadcast_to(vals1[:, None], (E, 8))

    @jax.jit
    def f_scatter_scalar(cols, v):
        # 7 separate scalar scatters at the same indices (current design)
        outs = []
        for c in range(7):
            outs.append(cols[c].at[idx].set(v + c, mode="drop"))
        return outs

    cols7 = [jnp.zeros(L * B, jnp.uint32) for _ in range(7)]
    log(
        f"7 scalar scatters @ {E} idx: "
        f"{timed(lambda: f_scatter_scalar(cols7, vals1))*1e3:.1f} ms"
    )

    @jax.jit
    def f_scatter_vec(tbl, v):
        return tbl.at[idx].set(v, mode="drop")

    tbl8 = jnp.zeros((L * B, 8), jnp.uint32)
    log(
        f"1 vector scatter [E,8] @ {E} idx: "
        f"{timed(lambda: f_scatter_vec(tbl8, vals8))*1e3:.1f} ms"
    )

    @jax.jit
    def f_sort(s):
        return jnp.argsort(
            jnp.broadcast_to(s.key.reshape(-1), (NEIGHBOURS, u * s_w)), axis=1
        )

    log(f"argsort 8192 x{NEIGHBOURS}: {timed(lambda: f_sort(sl))*1e3:.1f} ms")

    # gather-packing probe (mirror of the scatter probe): merge_slice's
    # compacted branch pays 6 per-column take() gathers at the same
    # indices. If TPU gather cost is per index entry (payload-width
    # free), one stacked [E, 7]-plane gather should win ~6x; on CPU the
    # plane concatenate makes it LOSE (measured 12.5 vs 21.3 ms) — chip
    # numbers decide whether the kernel change is worth it.
    # mirror _gather_rows/_ROW_COLS: 6 per-column gathers (key u64,
    # ts i64, valh, node, ctr, ehash) = 8 u32 planes
    g_idx = jnp.asarray(np.sort(rng.choice(L * B, size=E, replace=False)).astype(np.int32))
    ck = jnp.asarray(rng.integers(0, 1 << 63, (NEIGHBOURS, L * B), np.uint64))
    cts = jnp.asarray(rng.integers(0, 1 << 62, (NEIGHBOURS, L * B), np.int64))
    c32 = [jnp.asarray(rng.integers(0, 1 << 32, (NEIGHBOURS, L * B), np.uint32)) for _ in range(4)]

    @jax.jit
    def f_gather_scalar(ck, cts, c32):
        f = lambda a: a[:, g_idx]
        return (f(ck), f(cts)) + tuple(f(c) for c in c32)

    log(
        f"6 scalar gathers @ {E} idx x{NEIGHBOURS}: "
        f"{timed(lambda: f_gather_scalar(ck, cts, c32))*1e3:.1f} ms"
    )

    @jax.jit
    def f_gather_stacked(ck, cts, c32):
        planes = jnp.concatenate(
            [jax.lax.bitcast_convert_type(ck, jnp.uint32),
             jax.lax.bitcast_convert_type(cts, jnp.uint32)]
            + [c[..., None] for c in c32],
            axis=2,
        )  # [N, L*B, 8]
        g = planes[:, g_idx, :]
        return (
            jax.lax.bitcast_convert_type(g[..., 0:2], jnp.uint64),
            jax.lax.bitcast_convert_type(g[..., 2:4], jnp.int64),
            g[..., 4], g[..., 5], g[..., 6], g[..., 7],
        )

    log(
        f"1 stacked [E,8] gather @ {E} idx x{NEIGHBOURS}: "
        f"{timed(lambda: f_gather_stacked(ck, cts, c32))*1e3:.1f} ms"
    )

    # gather whole rows x64 (merge_rows' main memory traffic)
    @jax.jit
    def f_gather(states, s):
        rows_clip = jnp.clip(s.rows, 0, L - 1)
        return (
            states.key[:, rows_clip],
            states.ts[:, rows_clip],
            states.alive[:, rows_clip],
        )

    log(f"row gather x{NEIGHBOURS}: {timed(lambda: f_gather(stacked, sl))*1e3:.1f} ms")

    # --- probes that decide the NEXT packed-kernel lever ----------------
    # (added after the 2026-07-31 chip session: packed measures ~116 ms/
    # call vs a ~62 ms roofline+dispatch estimate — who eats the rest?)

    # (1) insert compaction: merge_slice's per-neighbour top_k over the
    # [u*s]=65,536-slot grid. If this costs more than the ~0.57 ms/
    # neighbour a full-grid scatter would add, the compaction (and the
    # whole need_ins_tier ladder) is a net loss on chip.
    grid = jnp.asarray(
        rng.integers(0, L * B, (NEIGHBOURS, u * s_w), np.int64)
    )

    @jax.jit
    def f_topk(g):
        nv, sel = jax.lax.top_k(-g, 8192)
        return nv, sel

    log(
        f"top_k 8192 of {u * s_w} x{NEIGHBOURS}: "
        f"{timed(lambda: f_topk(grid))*1e3:.1f} ms"
    )

    # (2) full-grid [65k, 8] record scatter (the compaction-free
    # alternative: every grid slot scatters, padding slots drop)
    vals_grid8 = jnp.broadcast_to(
        jnp.arange(u * s_w, dtype=jnp.uint32)[None, :, None],
        (NEIGHBOURS, u * s_w, 8),
    )
    tblN8 = jnp.zeros((NEIGHBOURS, L * B, 8), jnp.uint32)

    @jax.jit
    def f_scatter_fullgrid(tbl, g, v):
        def one(t, gi, vi):
            return t.at[gi].set(vi, mode="drop")
        return jax.vmap(one)(tbl, g, v)

    log(
        f"full-grid [{u * s_w},8] record scatter x{NEIGHBOURS}: "
        f"{timed(lambda: f_scatter_fullgrid(tblN8, grid, vals_grid8))*1e3:.1f} ms"
    )

    # (3) aux-scatter fusion: amin min-scatter + amax max-scatter at the
    # same (row, slot) indices, separate vs fused via the unsigned
    # complement trick (max(x) == ~min(~x)) into one [L*R, 2] min-scatter
    RR = RCAP
    aux_idx = jnp.asarray(rng.integers(0, L * RR, (NEIGHBOURS, E), np.int64))
    aux_vals = jnp.asarray(rng.integers(0, 1 << 32, (NEIGHBOURS, E), np.uint32))
    amin_t = jnp.full((NEIGHBOURS, L * RR), 0xFFFFFFFF, jnp.uint32)
    amax_t = jnp.zeros((NEIGHBOURS, L * RR), jnp.uint32)

    @jax.jit
    def f_aux_separate(mn, mx, ai, av):
        def one(m, x, i, v):
            return m.at[i].min(v, mode="drop"), x.at[i].max(v, mode="drop")
        return jax.vmap(one)(mn, mx, ai, av)

    log(
        f"amin+amax separate scatters @ {E} x{NEIGHBOURS}: "
        f"{timed(lambda: f_aux_separate(amin_t, amax_t, aux_idx, aux_vals))*1e3:.1f} ms"
    )

    # the fused timing must include the per-call stack/unstack the real
    # fused kernel pays (merge_slice_packed_fused re-stacks the summary
    # tables inside every merge), not just the scatter
    @jax.jit
    def f_aux_fused(mn, mx, ai, av):
        def one(m, x, i, v):
            t = jnp.stack([m, ~x], axis=-1)  # [L*R, 2]
            t = t.at[i].min(jnp.stack([v, ~v], axis=-1), mode="drop")
            return t[..., 0], ~t[..., 1]
        return jax.vmap(one)(mn, mx, ai, av)

    log(
        f"amin+~amax fused stack+[E,2] min-scatter+unstack @ {E} x{NEIGHBOURS}: "
        f"{timed(lambda: f_aux_fused(amin_t, amax_t, aux_idx, aux_vals))*1e3:.1f} ms"
    )


if __name__ == "__main__":
    main()
