"""SPMD mesh-gossip benchmark: per-step latency of the bounded-divergence
ring (`gossip_delta_step`) on an N-device mesh.

No reference analog (the reference has no multi-device data plane); this
extends the measured matrix to the parallel layer. Runs on the virtual
CPU mesh (`JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8`)
or a real multi-chip mesh unchanged.

Run: ``python -m benchmarks.mesh_gossip``
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np

if os.environ.get("JAX_PLATFORMS", "") in ("", "cpu"):
    # a bare-CPU invocation would otherwise measure a 1-device "ring"
    # (trivial steps, heal in 2) and quietly record nonsense. Must run
    # before importing benchmarks.common, whose compilation-cache setup
    # initialises the backend (the host device count parses only once),
    # and must go through force_cpu_devices even when the count is
    # explicit — the env var alone doesn't pin the platform on images
    # whose boot hook pre-imports jax. An explicit XLA_FLAGS count is
    # honoured; never force when an accelerator platform is pinned — the
    # TPU matrix must measure the chip mesh or fail the n>1 assert loudly.
    from delta_crdt_ex_tpu.utils.devices import forced_device_count, force_cpu_devices

    _n = forced_device_count()
    force_cpu_devices(_n if _n is not None else 8)

from benchmarks.common import emit, log


def main():
    import jax
    import jax.numpy as jnp

    from delta_crdt_ex_tpu.models.binned import BinnedStore
    from delta_crdt_ex_tpu.models.binned_map import group_batch
    from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_PAD
    from delta_crdt_ex_tpu.parallel import (
        gossip_delta_step,
        make_mesh,
        place_states,
    )

    n = len(jax.devices())
    assert n > 1, (
        "mesh_gossip needs a multi-device mesh; got 1 device — set "
        "XLA_FLAGS=--xla_force_host_platform_device_count=N for CPU runs"
    )
    mesh = make_mesh()
    log(f"mesh: {n} devices ({jax.default_backend()})")

    # writer-table slots must cover every mesh writer (post-gossip each
    # state knows all n gids; this bench asserts tier flags, no auto-grow)
    L, B, R = 1 << 10, 32, max(8, n)
    states = []
    for i in range(n):
        st = BinnedStore.new(L, B, R)
        states.append(
            dataclasses.replace(st, ctx_gid=st.ctx_gid.at[0].set(jnp.uint64(100 + i)))
        )
    stacked = place_states(states, mesh)
    self_slot = jnp.zeros(n, jnp.int32)

    def batch_for(n_ops, seed, u, m):
        """Fixed (u, m) shape across seeds so the timing loop never
        recompiles (group shapes vary with bucket collisions)."""
        r2 = np.random.default_rng(seed)
        groups = []
        for i in range(n):
            keys = r2.integers(1, 1 << 63, size=n_ops, dtype=np.uint64)
            groups.append(
                group_batch(
                    L,
                    np.full(n_ops, OP_ADD, np.int32),
                    keys,
                    (keys & np.uint64(0xFFFF)).astype(np.uint32),
                    (seed * 100_000 + np.arange(n_ops) + 1).astype(np.int64),
                )
            )
        assert all(
            g.rows.shape[0] <= u and g.op.shape[1] <= m for g in groups
        ), "fixed batch shape too small for this seed"
        rows = np.full((n, u), -1, np.int32)
        op = np.full((n, u, m), OP_PAD, np.int32)
        key = np.zeros((n, u, m), np.uint64)
        valh = np.zeros((n, u, m), np.uint32)
        ts = np.zeros((n, u, m), np.int64)
        for i, g in enumerate(groups):
            gu, gm = g.op.shape
            rows[i, :gu] = g.rows
            op[i, :gu, :gm] = g.op
            key[i, :gu, :gm] = g.key
            valh[i, :gu, :gm] = g.valh
            ts[i, :gu, :gm] = g.ts
        return tuple(map(jnp.asarray, (rows, op, key, valh, ts)))

    results = {}
    for n_ops in (16, 128):
        frontier = 256
        u, m = max(16, 2 * n_ops), 4
        # warm + compile
        stacked2, roots, oks, n_diff, _ = gossip_delta_step(
            mesh, stacked, self_slot, *batch_for(n_ops, 1, u, m), frontier=frontier
        )
        jax.block_until_ready(roots)
        assert bool(np.asarray(oks).all())
        iters = 8
        batches = [batch_for(n_ops, 2 + it, u, m) for it in range(iters)]
        t0 = time.perf_counter()
        st = stacked2
        all_oks = []
        for b in batches:
            st, roots, oks, n_diff, _ = gossip_delta_step(
                mesh, st, self_slot, *b, frontier=frontier
            )
            all_oks.append(oks)
        jax.block_until_ready(roots)
        dt = (time.perf_counter() - t0) / iters
        assert all(bool(np.asarray(o).all()) for o in all_oks), "tier overflow mid-timing"
        results[f"step_ms@{n_ops}ops"] = round(dt * 1e3, 2)
        log(f"{n_ops} ops/replica/step: {dt*1e3:.1f} ms/step")

    # ring-heal latency: steps until full convergence after one write wave
    st, roots, oks, n_diff, _ = gossip_delta_step(
        mesh, stacked, self_slot, *batch_for(64, 99, 128, 4), frontier=256
    )
    assert bool(np.asarray(oks).all()), "tier overflow on the write wave"
    empty = (
        jnp.full((n, 1), -1, jnp.int32),
        jnp.full((n, 1, 1), OP_PAD, jnp.int32),
        jnp.zeros((n, 1, 1), jnp.uint64),
        jnp.zeros((n, 1, 1), jnp.uint32),
        jnp.zeros((n, 1, 1), jnp.int64),
    )
    steps = 1
    while True:
        st, roots, oks, n_diff, _ = gossip_delta_step(
            mesh, st, self_slot, *empty, frontier=256
        )
        steps += 1
        assert bool(np.asarray(oks).all()), "tier overflow during heal"
        if int(np.asarray(n_diff).max()) == 0:
            break
        assert steps < 4 * n, "ring did not converge"
    rr = np.asarray(roots)
    assert (rr == rr[0]).all()
    results["heal_steps_64ops"] = steps
    log(f"ring heal after one 64-op wave: {steps} steps (n={n})")

    emit("mesh_gossip", results)


if __name__ == "__main__":
    main()
