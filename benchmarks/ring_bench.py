"""8-replica ring convergence latency — the BASELINE.json config
"bench/propagation.exs — 8-replica ring, 10k keys, convergence latency".

Eight threaded runtime replicas wired in a ONE-WAY ring (directional
edges, like the reference's ``set_neighbours``); replica 0 writes N
keys; the clock stops when every replica reads the full map. Data
reaches the far side of the ring transitively: eager pushes cover each
hop's own dots, the digest walk relays the rest — 7 hops of real
anti-entropy machinery, timers and all.

Run: ``python -m benchmarks.ring_bench [N ...]``  (default 10000)
"""

from __future__ import annotations

import sys
import time

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from benchmarks.common import emit, log

RING = 8


def run(number: int) -> dict:
    transport = LocalTransport()
    reps = [
        start_link(
            AWLWWMap,
            transport=transport,
            sync_interval=0.02,
            capacity=max(4096, 4 * number),
            tree_depth=12,
            max_sync_size=500,
            name=f"ring-{i}",
        )
        for i in range(RING)
    ]
    for i, r in enumerate(reps):
        r.set_neighbours([reps[(i + 1) % RING]])  # one-way ring

    t_write0 = time.perf_counter()
    for x in range(number):
        reps[0].mutate_async("add", [x, x])
    reps[0].flush()
    write_s = time.perf_counter() - t_write0

    t0 = time.perf_counter()
    deadline = t0 + 600
    while time.perf_counter() < deadline:
        if all(len(r.read()) == number for r in reps):
            break
        time.sleep(0.05)
    conv_s = time.perf_counter() - t0
    ok = all(r.read() == {x: x for x in range(number)} for r in reps)
    for r in reps:
        r.stop()
    assert ok, "ring did not converge to the full map"
    log(f"ring({RING}) {number} keys: write {write_s:.2f}s, converge {conv_s:.2f}s")
    return {f"write_s@{number}": round(write_s, 2), f"converge_s@{number}": round(conv_s, 2)}


def main(sizes=(10_000,)):
    results = {}
    for n in sizes:
        results.update(run(n))
    emit("ring_bench", results)
    return results


if __name__ == "__main__":
    sizes = tuple(int(a) for a in sys.argv[1:]) or (10_000,)
    main(sizes)
