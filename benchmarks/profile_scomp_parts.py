"""Phase-level attribution of the promoted scomp merge at the bench
config — what eats the remaining per-call time on CPU (and the ~113
ms/call left on chip) now that both top_k sorts are gone.

Times (a) the full bench merge_chunk (merge + roots), (b) the merge
alone, (c) the digest-tree roots alone, then isolated synthetic probes
for the scomp-v2-specific terms: the per-neighbour [G,2] pair
compaction scatter, the [k,7] payload gather from the hoisted
slice-only planes, the grid cumsum, and the main [k,8] record scatter.
G = u·s is ~8x the real entry count at the bench shape (8,192 keys
spread over ~6.4k buckets padded to 8,192 rows x 8 lanes), so the
G-sized terms pay that padding tax per neighbour per call.

Run: JAX_PLATFORMS=cpu python -m benchmarks.profile_scomp_parts
(SCOMP_PARTS_NEIGHBOURS=16 shrinks the fan-in; numbers scale roughly
linearly, with a superlinear tail at 64 from the 4.3 GB working set.)
"""

from __future__ import annotations

import os
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache

enable_compilation_cache()

from delta_crdt_ex_tpu.ops.binned import tree_from_leaves
from delta_crdt_ex_tpu.ops.packed import merge_slice_packed_scomp, pack
from delta_crdt_ex_tpu.utils.synth import build_state, interval_delta_stream

from benchmarks.common import log

N_KEYS = 1_000_000
TREE_DEPTH = 14
BIN_CAP = 128
NEIGHBOURS = int(os.environ.get("SCOMP_PARTS_NEIGHBOURS", "64"))
DELTA = 512
GROUP = 16
RCAP = 8


def timed(fn, n=6):
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def timed_chain(step, carry, args_list):
    """Donated-carry timing: ``step(carry, args) -> carry`` jitted with
    ``donate_argnums=(0,)``, warmed on ``args_list[0]`` and timed over
    the rest — the probe measures the in-place update the bench
    actually runs (without donation a scatter pays a full operand copy
    per call, where the first version of this script lost 0.6 s/call
    and attributed the copy, not the op). The probe outputs must be
    RETURNED by ``step`` or XLA dead-code-eliminates the work being
    timed."""
    carry = step(carry, args_list[0])
    jax.block_until_ready(carry)
    t0 = time.perf_counter()
    for a in args_list[1:]:
        carry = step(carry, a)
    jax.block_until_ready(carry)
    return (time.perf_counter() - t0) / (len(args_list) - 1)


def main():
    L = 1 << TREE_DEPTH
    B = BIN_CAP
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 1 << 63, size=N_KEYS, dtype=np.uint64)
    log(f"devices: {jax.devices()}")

    one, _ = build_state(11, keys, num_buckets=L, bin_capacity=BIN_CAP,
                         replica_capacity=RCAP)
    one = jax.jit(pack)(one)
    jax.block_until_ready(one)

    def fresh_stack():
        # each donated-carry probe consumes its stack — rebuild per probe
        st = jax.tree_util.tree_map(
            lambda x: jnp.copy(jnp.broadcast_to(x, (NEIGHBOURS,) + x.shape)), one
        )
        jax.block_until_ready(st)
        return st

    # fresh dots per timed call (like the bench): re-merging one slice
    # into an already-covering state does no insert work and would
    # time the wrong kernel
    n_timed = 6
    slices, _ = interval_delta_stream(
        22, rng, n_timed + 2, GROUP * DELTA, L, bin_width=8
    )
    sl = slices[0]
    u, s_w = sl.key.shape
    G = u * s_w
    k = GROUP * DELTA
    log(f"slice: rows={u} lanes={s_w} grid={G} inserts<={k}")

    mfn = lambda st, s: merge_slice_packed_scomp(st, s, 8, k, rows_sorted=True)

    @partial(jax.jit, donate_argnums=(0,))
    def f_full(states, s):
        res = jax.vmap(mfn, in_axes=(0, None))(states, s)
        roots = jax.vmap(lambda lf: tree_from_leaves(lf)[0][0])(res.state.leaf)
        # roots must flow into the carry or XLA prunes the whole tree
        # fold and this probe times the same program as f_merge
        return res.state, roots

    def full_step(carry, s):
        return f_full(carry[0], s)

    log(
        f"merge+roots x{NEIGHBOURS} (donated): "
        f"{timed_chain(full_step, (fresh_stack(), None), slices[: n_timed + 1])*1e3:.1f} ms"
    )

    @partial(jax.jit, donate_argnums=(0,))
    def f_merge(states, s):
        res = jax.vmap(mfn, in_axes=(0, None))(states, s)
        return res.state

    log(
        f"merge only  x{NEIGHBOURS} (donated): "
        f"{timed_chain(f_merge, fresh_stack(), slices[: n_timed + 1])*1e3:.1f} ms"
    )

    @jax.jit
    def f_roots(states):
        return jax.vmap(lambda lf: tree_from_leaves(lf)[0][0])(states.leaf)

    roots_stack = fresh_stack()
    log(f"roots only  x{NEIGHBOURS}: {timed(lambda: f_roots(roots_stack))*1e3:.1f} ms")

    # --- isolated synthetic probes (shapes match the v2 kernel) ---------
    flatN = jnp.asarray(
        rng.integers(0, L * B, (NEIGHBOURS, G), np.int64)
    )

    @jax.jit
    def f_pair_compact(fl):
        # per-neighbour [G,2] (flat, grid-index) pair compaction — the
        # only G-sized scatter v2 keeps per neighbour
        def one(f):
            ins_flat = f < (L * B) // 2
            rank = jnp.cumsum(ins_flat.astype(jnp.int32)) - 1
            dest = jnp.where(ins_flat, rank, k)
            pair = jnp.stack(
                [f.astype(jnp.uint32), jnp.arange(G, dtype=jnp.uint32)], -1
            )
            return (
                jnp.zeros((k + 1, 2), jnp.uint32).at[dest].set(pair, mode="drop")
            )[:k]
        return jax.vmap(one)(fl)

    log(
        f"[G={G},2] pair compaction scatter x{NEIGHBOURS}: "
        f"{timed(lambda: f_pair_compact(flatN))*1e3:.1f} ms"
    )

    # the [k,7] payload gather from the SHARED (slice-only, hoisted)
    # [G,7] plane pack — per-neighbour indices, one source table
    planes7 = jnp.asarray(rng.integers(0, 1 << 32, (G, 7), np.uint32))
    srcN = jnp.asarray(rng.integers(0, G, (NEIGHBOURS, k), np.int64))

    @jax.jit
    def f_payload_gather(src):
        return jax.vmap(lambda s: planes7[s])(src)

    log(
        f"payload [k={k},7] gather x{NEIGHBOURS}: "
        f"{timed(lambda: f_payload_gather(srcN))*1e3:.1f} ms"
    )

    @jax.jit
    def f_cumsum(fl):
        return jax.vmap(lambda f: jnp.cumsum((f < (L * B) // 2).astype(jnp.int32)))(fl)

    log(f"[G] cumsum x{NEIGHBOURS}: {timed(lambda: f_cumsum(flatN))*1e3:.1f} ms")

    # the hoisted plane pack itself (once per CALL, not per neighbour)
    key_col = jnp.asarray(rng.integers(0, 1 << 63, G, np.uint64))
    ts_col = jnp.asarray(rng.integers(0, 1 << 62, G, np.int64))
    u32_cols = [jnp.asarray(rng.integers(0, 1 << 32, G, np.uint32)) for _ in range(3)]

    @jax.jit
    def f_planes7(kc, tc, cs):
        return jnp.concatenate(
            [jax.lax.bitcast_convert_type(kc[:, None], jnp.uint32).reshape(G, 2),
             jax.lax.bitcast_convert_type(tc[:, None], jnp.uint32).reshape(G, 2)]
            + [c[:, None] for c in cs],
            axis=-1,
        )

    log(
        f"[G,7] plane pack (once/call): "
        f"{timed(lambda: f_planes7(key_col, ts_col, u32_cols))*1e3:.1f} ms"
    )

    # sorted unique per-neighbour indices: the real kernel's hint-path
    # precondition (ascending rows, unique slots)
    idxk = jnp.asarray(
        np.stack(
            [np.sort(rng.choice(L * B, size=k, replace=False)) for _ in range(NEIGHBOURS)]
        ).astype(np.int64)
    )
    vals8 = jnp.asarray(rng.integers(0, 1 << 32, (NEIGHBOURS, k, 8), np.uint32))

    def scatter_probe(name, hints):
        @partial(jax.jit, donate_argnums=(0,))
        def f(tb, _):
            def one(t, i, vv):
                return t.at[i].set(
                    vv, mode="drop",
                    indices_are_sorted=hints, unique_indices=hints,
                )
            return jax.vmap(one)(tb, idxk, vals8)

        tb = jnp.zeros((NEIGHBOURS, L * B, 8), jnp.uint32)
        ms = timed_chain(f, tb, [None] * (n_timed + 1)) * 1e3
        log(f"{name}: {ms:.1f} ms")

    scatter_probe(
        f"main [k={k},8] record scatter x{NEIGHBOURS} (donated, hints)", True
    )
    scatter_probe(
        f"main [k={k},8] record scatter x{NEIGHBOURS} (donated, no hints)", False
    )


if __name__ == "__main__":
    main()
