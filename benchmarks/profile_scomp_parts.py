"""Phase-level attribution of the promoted scomp merge at the bench
config — what eats the ~0.5 s/call left on CPU (and the ~113 ms/call
left on chip) now that top_k is gone.

Times (a) the full bench merge_chunk (merge + flags + roots), (b) the
merge alone, (c) the digest-tree roots alone, then isolated synthetic
probes for the scomp-specific terms: the per-neighbour [G,9] compaction
scatter over the padded grid, the grid cumsum, and the main [k,8]
record scatter. G = u·s is ~8x the real entry count at the bench shape
(8,192 keys spread over ~6.4k buckets padded to 8,192 rows x 8 lanes),
so the compaction term pays that padding tax per neighbour per call.

Run: JAX_PLATFORMS=cpu python -m benchmarks.profile_scomp_parts
(SCOMP_PARTS_NEIGHBOURS=16 shrinks the fan-in; numbers scale linearly.)
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache

enable_compilation_cache()

from delta_crdt_ex_tpu.ops.binned import tree_from_leaves
from delta_crdt_ex_tpu.ops.packed import merge_slice_packed_scomp, pack
from delta_crdt_ex_tpu.utils.synth import build_state, interval_delta_stream

from benchmarks.common import log

N_KEYS = 1_000_000
TREE_DEPTH = 14
BIN_CAP = 128
NEIGHBOURS = int(os.environ.get("SCOMP_PARTS_NEIGHBOURS", "64"))
DELTA = 512
GROUP = 16
RCAP = 8


def timed(fn, n=6):
    out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    L = 1 << TREE_DEPTH
    B = BIN_CAP
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 1 << 63, size=N_KEYS, dtype=np.uint64)
    log(f"devices: {jax.devices()}")

    one, _ = build_state(11, keys, num_buckets=L, bin_capacity=BIN_CAP,
                         replica_capacity=RCAP)
    one = jax.jit(pack)(one)
    jax.block_until_ready(one)
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.copy(jnp.broadcast_to(x, (NEIGHBOURS,) + x.shape)), one
    )
    jax.block_until_ready(stacked)

    slices, _ = interval_delta_stream(22, rng, 1, GROUP * DELTA, L, bin_width=8)
    sl = slices[0]
    u, s_w = sl.key.shape
    G = u * s_w
    k = GROUP * DELTA
    log(f"slice: rows={u} lanes={s_w} grid={G} inserts<={k}")

    mfn = lambda st, s: merge_slice_packed_scomp(st, s, 8, k, rows_sorted=True)

    @jax.jit
    def f_full(states, s):
        res = jax.vmap(mfn, in_axes=(0, None))(states, s)
        roots = jax.vmap(lambda lf: tree_from_leaves(lf)[0][0])(res.state.leaf)
        return res.ok, roots

    log(f"merge+roots x{NEIGHBOURS}: {timed(lambda: f_full(stacked, sl))*1e3:.1f} ms")

    @jax.jit
    def f_merge(states, s):
        res = jax.vmap(mfn, in_axes=(0, None))(states, s)
        return res.ok, res.state.leaf

    log(f"merge only  x{NEIGHBOURS}: {timed(lambda: f_merge(stacked, sl))*1e3:.1f} ms")

    @jax.jit
    def f_roots(states):
        return jax.vmap(lambda lf: tree_from_leaves(lf)[0][0])(states.leaf)

    log(f"roots only  x{NEIGHBOURS}: {timed(lambda: f_roots(stacked))*1e3:.1f} ms")

    # --- isolated synthetic probes (shapes match the real kernel) -------
    flatN = jnp.asarray(
        rng.integers(0, L * B, (NEIGHBOURS, G), np.int64)
    )
    planesN = jnp.asarray(rng.integers(0, 1 << 32, (NEIGHBOURS, G, 9), np.uint32))

    @jax.jit
    def f_compact_scatter(fl, pl):
        def one(f, p):
            ins_flat = f < (L * B) // 2
            rank = jnp.cumsum(ins_flat.astype(jnp.int32)) - 1
            dest = jnp.where(ins_flat, rank, k)
            return (
                jnp.zeros((k + 1, 9), jnp.uint32).at[dest].set(p, mode="drop")
            )[:k]
        return jax.vmap(one)(fl, pl)

    log(
        f"[G={G},9] cumsum+compaction scatter x{NEIGHBOURS}: "
        f"{timed(lambda: f_compact_scatter(flatN, planesN))*1e3:.1f} ms"
    )

    @jax.jit
    def f_cumsum(fl):
        return jax.vmap(lambda f: jnp.cumsum((f < (L * B) // 2).astype(jnp.int32)))(fl)

    log(f"[G] cumsum x{NEIGHBOURS}: {timed(lambda: f_cumsum(flatN))*1e3:.1f} ms")

    # the planes concatenate alone (9 [G]-plane writes per neighbour)
    @jax.jit
    def f_planes(pl):
        return jax.vmap(lambda p: jnp.concatenate([p[:, i:i+1] for i in range(9)], axis=-1))(pl)

    log(f"[G,9] plane concat x{NEIGHBOURS}: {timed(lambda: f_planes(planesN))*1e3:.1f} ms")

    idxk = jnp.asarray(
        np.sort(rng.choice(L * B, size=(NEIGHBOURS, k), replace=True), axis=1).astype(np.int64)
    )
    vals8 = jnp.asarray(rng.integers(0, 1 << 32, (NEIGHBOURS, k, 8), np.uint32))
    tblN = jnp.zeros((NEIGHBOURS, L * B, 8), jnp.uint32)

    @jax.jit
    def f_main_scatter(tb, ix, v):
        def one(t, i, vv):
            return t.at[i].set(vv, mode="drop", indices_are_sorted=True)
        return jax.vmap(one)(tb, ix, v)

    log(
        f"main [k={k},8] record scatter x{NEIGHBOURS}: "
        f"{timed(lambda: f_main_scatter(tblN, idxk, vals8))*1e3:.1f} ms"
    )


if __name__ == "__main__":
    main()
