"""Shared helpers for the ported reference benchmarks (``bench/*.exs``)."""

from __future__ import annotations

import json
import sys
import time

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.transport import LocalTransport

log = lambda *a: print(*a, file=sys.stderr, flush=True)

from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache

enable_compilation_cache()


def make_pair(transport=None, **opts):
    """Two deterministic replicas wired bidirectionally."""
    transport = transport or LocalTransport()
    opts.setdefault("threaded", False)
    c1 = start_link(AWLWWMap, transport=transport, **opts)
    c2 = start_link(AWLWWMap, transport=transport, **opts)
    c1.set_neighbours([c2])
    c2.set_neighbours([c1])
    transport.pump()
    return transport, c1, c2


def converge(transport, replicas, predicate, max_rounds=10_000):
    """Drive sync rounds until ``predicate()`` holds; returns rounds used."""
    for r in range(max_rounds):
        if predicate():
            return r
        for rep in replicas:
            rep.sync_to_all()
        transport.pump()
    raise RuntimeError("did not converge")


class BenchRecorder:
    """Convergence detector (reference ``BenchRecorder``,
    ``bench/propagation.exs:1-34``): watches an ``on_diffs`` feed for
    sentinel add/remove diffs."""

    def __init__(self):
        self.adds: set = set()
        self.removes: set = set()

    def on_diffs(self, diffs):
        for d in diffs:
            if d[0] == "add":
                self.adds.add(d[1])
            else:
                self.removes.add(d[1])

    def wait(self, key, kind="add", timeout=60.0) -> bool:
        import time as _t

        seen = self.adds if kind == "add" else self.removes
        deadline = _t.monotonic() + timeout
        while _t.monotonic() < deadline:
            if key in seen:
                return True
            _t.sleep(0.001)
        return False


def emit(name: str, results: dict):
    """Log results AND persist them to ``benchmarks/results/<name>.<backend>.json``
    so measured numbers are committed alongside the harness (BASELINE.md's
    measurement matrix). The write is atomic (temp file + ``os.replace``):
    per-cell partial emits exist to survive watchdog kills, so a kill
    landing mid-write must not truncate the evidence it protects."""
    import datetime
    import os

    import jax

    backend = jax.default_backend()
    payload = {
        "bench": name,
        "backend": backend,
        "devices": [str(d) for d in jax.devices()],
        "utc": datetime.datetime.now(datetime.timezone.utc).isoformat(timespec="seconds"),
        **results,
    }
    log(json.dumps(payload, default=float))
    out_dir = os.path.join(os.path.dirname(os.path.abspath(__file__)), "results")
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(out_dir, f"{name}.{backend}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, default=float)
        f.write("\n")
    os.replace(tmp, path)


def emit_partial(name: str, results: dict):
    """Per-cell checkpoint of a multi-cell bench: same artifact, flagged
    ``partial`` so the resume gate re-runs the row and the digest labels
    it — a watchdog kill keeps the finished cells."""
    emit(name, {**results, "partial": True})


def load_partial(name: str, max_age_s: float = 43200) -> dict:
    """Cells from a FRESH partial artifact of this bench on this
    backend, so a re-run after a watchdog kill resumes where it died
    instead of overwriting the richer evidence with its first cell.
    Complete artifacts return {} (the caller is a deliberate fresh run),
    as do stale ones (another session's cells must not mix in)."""
    import os

    import jax

    from benchmarks.artifact import artifact_status

    path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)),
        "results",
        f"{name}.{jax.default_backend()}.json",
    )
    # one read: the artifact can be atomically replaced under us
    status, d = artifact_status(path, max_age_s, with_data=True)
    if status != "partial":
        return {}
    cells = {
        k: v
        for k, v in d.items()
        if k not in ("bench", "backend", "devices", "utc", "partial")
    }
    if cells:
        log(f"{name}: resuming from partial artifact with {len(cells)} cells")
    return cells
