#!/bin/bash
# One-shot TPU measurement session: fire everything the moment a claim
# window opens, cheapest-first so a mid-session wedge still leaves
# artifacts. The north-star numbers go to stdout and $LOG (bench.py
# prints its JSON line to stdout only); the harness modules write
# benchmarks/results/*.tpu.json. CPU fallbacks are disabled for all
# bench.py runs (BENCH_NO_CPU_FALLBACK); the harness modules cannot fall
# back silently either — the ambient JAX_PLATFORMS pin makes a dead
# claim raise (step logs FAILED), and emit() stamps the backend into
# every results filename, so a cpu artifact can never masquerade as tpu.
#
# Usage: bash benchmarks/run_tpu_matrix.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/tpu_matrix.log}"
say() { echo "[tpu-matrix $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

# NOTE: bench.py now guarantees a JSON artifact line and exits 0 even on
# failure (the line carries a _failed/_interrupted metric label instead),
# so gates below inspect the LINE, not the exit code.
# every failure-shaped artifact (claim failure, interrupt, child crash)
# carries an "error" field; plain success lines never do. Matching the
# metric label with *_failed* would also match the secondary_assert_failed
# FIELD NAME on an otherwise-successful line.
ok_line() { case "$1" in ""|*'"error"'*) return 1;; *) return 0;; esac; }

say "session start; devices probe:"
timeout 120 python -c "import jax; print(jax.devices())" >>"$LOG" 2>&1 \
  || { say "chip unreachable, aborting (don't burn the step timeouts)"; exit 1; }

# Pallas verdict first — cheapest high-information probe in the window
# (batched_roots_fn logs the Mosaic failure reason since round 5)
say "pallas verdict probe (batched_roots_fn on the live chip)"
timeout 600 python -c "
from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache
enable_compilation_cache()
from delta_crdt_ex_tpu.ops.pallas_tree import batched_roots_fn
fn, tag = batched_roots_fn(16384)
print('digest tree:', tag)
" >>"$LOG" 2>&1 && say "pallas verdict done" || say "pallas verdict FAILED"

say "smoke bench (validates kernels on chip, ~1 min when healthy)"
SMOKE_LINE=$(BENCH_SMOKE=1 BENCH_TOTAL_BUDGET=800 BENCH_CLAIM_TIMEOUT=120 \
  BENCH_CLAIM_ATTEMPTS=2 BENCH_TPU_TIMEOUT=600 BENCH_NO_CPU_FALLBACK=1 \
  timeout 1000 python bench.py 2>>"$LOG")
echo "$SMOKE_LINE" >>"$LOG"
ok_line "$SMOKE_LINE" || { say "smoke FAILED: $SMOKE_LINE"; exit 1; }
say "smoke OK: $SMOKE_LINE"

say "full north-star bench (scomp primary + in-run top_k A/B since r5)"
BENCH_TOTAL_BUDGET=2200 BENCH_CLAIM_TIMEOUT=120 BENCH_CLAIM_ATTEMPTS=2 \
BENCH_TPU_TIMEOUT=2000 BENCH_NO_CPU_FALLBACK=1 \
  timeout 2400 python bench.py > /tmp/northstar.json 2>>"$LOG"
NORTH_LINE=$(tail -1 /tmp/northstar.json 2>/dev/null)
if ok_line "$NORTH_LINE"; then
  say "north-star: $NORTH_LINE"
  # persist outside /tmp (container restarts wipe it) — this is also
  # the scomp-vs-top_k decision artifact, so keep both names
  cp /tmp/northstar.json benchmarks/results/northstar.tpu.json
  cp /tmp/northstar.json benchmarks/results/scomp_ab.json
else
  say "north-star FAILED: $NORTH_LINE (see $LOG)"
fi

# the north-star run above already A/Bs scomp vs the top_k packed
# kernel in-process (BENCH_AB and BENCH_SCOMP default on; the artifact
# carries packed_scomp_/packed_topk_merges_per_sec and headlines the
# winner) — no second full run needed
case "$NORTH_LINE" in
  *packed_topk_merges_per_sec*|*packed_scomp_merges_per_sec*)
    say "kernel A/B captured in the north-star line";;
  *) say "WARNING: north-star line has no in-run A/B fields";;
esac

say "merge-part probes (scatter/gather packing attribution)"
timeout 1800 python -m benchmarks.profile_merge_parts >>"$LOG" 2>&1 \
  && say "profile_merge_parts done" || say "profile_merge_parts FAILED"

say "scomp v2 phase attribution (donated-carry probes)"
SCOMP_PARTS_NEIGHBOURS=16 timeout 900 python -m benchmarks.profile_scomp_parts >>"$LOG" 2>&1 \
  && say "profile_scomp_parts done" || say "profile_scomp_parts FAILED"

# GROUP=32 re-probe for scomp v2 (r4 rejected 32 for top_k — the
# superlinear sort is gone; CPU is a wash, the chip decides). Lane
# width left to the Poisson formula: a pinned 8 risks the stream
# generator's honest overflow raise (~12%/run at lambda=1). Written
# aside and promoted only on success so a failure can't truncate an
# earlier session's artifact.
say "group32 v2 probe (BENCH_GROUP=32)"
BENCH_GROUP=32 BENCH_AB=0 BENCH_TOTAL_BUDGET=1500 \
BENCH_CLAIM_TIMEOUT=120 BENCH_CLAIM_ATTEMPTS=2 BENCH_TPU_TIMEOUT=1300 \
BENCH_NO_CPU_FALLBACK=1 \
  timeout 1600 python bench.py > benchmarks/results/group32_v2.json.new 2>>"$LOG"
G32_LINE=$(tail -1 benchmarks/results/group32_v2.json.new 2>/dev/null)
if ok_line "$G32_LINE"; then
  mv benchmarks/results/group32_v2.json.new benchmarks/results/group32_v2.json
  say "group32 v2: $G32_LINE"
else
  say "group32 v2 FAILED: $G32_LINE (failure line kept at group32_v2.json.new)"
fi

say "harness matrix on TPU (runtime-driven; dispatch-bound, numbers are honest)"
timeout 900 python -m benchmarks.ring_device >>"$LOG" 2>&1 \
  && say "ring_device done" || say "ring_device FAILED"
timeout 1800 python -m benchmarks.basic_operations >>"$LOG" 2>&1 \
  && say "basic_operations done" || say "basic_operations FAILED"
timeout 1800 python -m benchmarks.propagation >>"$LOG" 2>&1 \
  && say "propagation done" || say "propagation FAILED"
PROP_DEVICE_PLANE=1 timeout 1800 python -m benchmarks.propagation >>"$LOG" 2>&1 \
  && say "propagation (device plane) done" || say "propagation (device plane) FAILED"
timeout 2400 python -m benchmarks.full_bench >>"$LOG" 2>&1 \
  && say "full_bench done" || say "full_bench FAILED"
timeout 1800 python -m benchmarks.ring_bench >>"$LOG" 2>&1 \
  && say "ring_bench done" || say "ring_bench FAILED"
timeout 1200 python -m benchmarks.mesh_gossip >>"$LOG" 2>&1 \
  && say "mesh_gossip done" || say "mesh_gossip FAILED"

# round-evidence refresh: the same chip window also re-validates the
# driver's own artifacts, so every evidence file carries one session's
# date (VERDICT r2 next #9)
say "graft entry compile check (single chip)"
timeout 900 python -c "
import __graft_entry__ as g, jax
fn, args = g.entry()
out = jax.jit(fn)(*args)
jax.block_until_ready(out)
print('entry ok:', jax.devices())
" >>"$LOG" 2>&1 && say "entry compile OK" || say "entry compile FAILED"

say "dryrun_multichip(8) on a virtual CPU mesh"
XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
PALLAS_AXON_POOL_IPS= \
  timeout 900 python -c "
import __graft_entry__ as g
g.dryrun_multichip(8)
print('dryrun_multichip ok')
" >>"$LOG" 2>&1 && say "dryrun_multichip OK" || say "dryrun_multichip FAILED"
say "session complete; harness results in benchmarks/results/, north-star in /tmp/northstar.json"
