#!/bin/bash
# One-shot TPU measurement session: fire everything the moment a claim
# window opens, cheapest-first so a mid-session wedge still leaves
# artifacts. Results land in benchmarks/results/*.tpu.json and stdout.
#
# Usage: bash benchmarks/run_tpu_matrix.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/tpu_matrix.log}"
say() { echo "[tpu-matrix $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

say "smoke bench (validates kernels on chip, ~1 min when healthy)"
BENCH_SMOKE=1 BENCH_CLAIM_TIMEOUT=120 BENCH_CLAIM_ATTEMPTS=2 \
  timeout 900 python bench.py >>"$LOG" 2>&1 || { say "smoke FAILED"; exit 1; }

say "full north-star bench"
BENCH_CLAIM_TIMEOUT=120 BENCH_CLAIM_ATTEMPTS=2 BENCH_TPU_TIMEOUT=2400 \
  timeout 2700 python bench.py 2>>"$LOG" | tee -a "$LOG"

say "harness matrix on TPU (runtime-driven; dispatch-bound, numbers are honest)"
timeout 1800 python -m benchmarks.basic_operations >>"$LOG" 2>&1 \
  && say "basic_operations done" || say "basic_operations FAILED"
timeout 1800 python -m benchmarks.propagation >>"$LOG" 2>&1 \
  && say "propagation done" || say "propagation FAILED"
timeout 2400 python -m benchmarks.full_bench >>"$LOG" 2>&1 \
  && say "full_bench done" || say "full_bench FAILED"
say "session complete; results in benchmarks/results/"
