#!/bin/bash
# One-shot TPU measurement session: fire everything the moment a claim
# window opens, cheapest-first so a mid-session wedge still leaves
# artifacts. The north-star numbers go to stdout and $LOG (bench.py
# prints its JSON line to stdout only); the harness modules write
# benchmarks/results/*.tpu.json. CPU fallbacks are disabled for the two
# bench.py runs (BENCH_NO_CPU_FALLBACK); the harness modules cannot fall
# back silently either — the ambient JAX_PLATFORMS pin makes a dead
# claim raise (step logs FAILED), and emit() stamps the backend into
# every results filename, so a cpu artifact can never masquerade as tpu.
#
# Usage: bash benchmarks/run_tpu_matrix.sh [logfile]
set -u
cd "$(dirname "$0")/.."
LOG="${1:-/tmp/tpu_matrix.log}"
say() { echo "[tpu-matrix $(date +%H:%M:%S)] $*" | tee -a "$LOG"; }

say "smoke bench (validates kernels on chip, ~1 min when healthy)"
BENCH_SMOKE=1 BENCH_CLAIM_TIMEOUT=120 BENCH_CLAIM_ATTEMPTS=2 \
BENCH_TPU_TIMEOUT=600 BENCH_NO_CPU_FALLBACK=1 \
  timeout 1000 python bench.py >>"$LOG" 2>&1 || { say "smoke FAILED"; exit 1; }
say "smoke OK: $(tail -1 "$LOG")"

say "full north-star bench"
BENCH_CLAIM_TIMEOUT=120 BENCH_CLAIM_ATTEMPTS=2 BENCH_TPU_TIMEOUT=2000 \
BENCH_NO_CPU_FALLBACK=1 \
  timeout 2400 python bench.py > /tmp/northstar.json 2>>"$LOG"
if [ $? -eq 0 ]; then
  say "north-star: $(cat /tmp/northstar.json)"
else
  say "north-star FAILED (see $LOG)"
fi

say "harness matrix on TPU (runtime-driven; dispatch-bound, numbers are honest)"
timeout 1800 python -m benchmarks.basic_operations >>"$LOG" 2>&1 \
  && say "basic_operations done" || say "basic_operations FAILED"
timeout 1800 python -m benchmarks.propagation >>"$LOG" 2>&1 \
  && say "propagation done" || say "propagation FAILED"
timeout 2400 python -m benchmarks.full_bench >>"$LOG" 2>&1 \
  && say "full_bench done" || say "full_bench FAILED"
timeout 1200 python -m benchmarks.mesh_gossip >>"$LOG" 2>&1 \
  && say "mesh_gossip done" || say "mesh_gossip FAILED"
say "session complete; harness results in benchmarks/results/, north-star in /tmp/northstar.json"
