#!/bin/bash
# Patient claim-waiter: a killed claim holder's grant can take many
# minutes to expire (observed after killing a mid-claim bench child).
# Probe the claim on a loop and fire resume_tpu_matrix.sh the moment it
# recovers. Log everything to the repo (a /tmp log dies with the
# container).
set -u
cd "$(dirname "$0")/.."
LOG="${1:-benchmarks/results/claim_wait.log}"
say() { echo "[claim-wait $(date -u +%H:%M:%S)] $*" | tee -a "$LOG"; }

for attempt in $(seq 1 120); do
  if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    say "claim recovered on attempt $attempt — firing resume matrix"
    bash benchmarks/resume_tpu_matrix.sh benchmarks/results/tpu_resume.log
    say "resume matrix finished"
    exit 0
  fi
  say "claim still wedged (attempt $attempt) — sleeping 60s"
  sleep 60
done
say "claim never recovered after 120 attempts"
exit 1
