"""Breakdown profile of the north-star merge call on the live device.

Times the two halves of ``bench.py``'s ``merge_chunk`` separately —
the vmapped ``merge_slice`` join and the digest-tree root fold — so
optimization effort goes where the time is. Run on TPU (no env knobs)
or CPU (``JAX_PLATFORMS=cpu``).
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache

enable_compilation_cache()

from delta_crdt_ex_tpu.ops.binned import merge_slice, tree_from_leaves
from delta_crdt_ex_tpu.utils.synth import build_state, interval_delta_stream

N_KEYS = 1_000_000
TREE_DEPTH = 14
BIN_CAP = 128
NEIGHBOURS = 64
DELTA = 512
GROUP = 16
RCAP = 8

from benchmarks.common import log  # shared stderr logger


def timed(fn, *args, n=6, **kw):
    out = fn(*args, **kw)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args, **kw)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / n


def main():
    L = 1 << TREE_DEPTH
    rng = np.random.default_rng(0)
    keys = rng.integers(1, 1 << 63, size=N_KEYS, dtype=np.uint64)
    log(f"devices: {jax.devices()}")

    one, _ = build_state(11, keys, num_buckets=L, bin_capacity=BIN_CAP,
                         replica_capacity=RCAP)
    jax.block_until_ready(one)
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.copy(jnp.broadcast_to(x, (NEIGHBOURS,) + x.shape)), one
    )
    jax.block_until_ready(stacked)

    slices, _ = interval_delta_stream(22, rng, 1, GROUP * DELTA, L, bin_width=8)
    sl = slices[0]

    # --- merge only (donated, like the bench) ---
    @jax.jit
    def merge_only(states, s):
        res = jax.vmap(merge_slice, in_axes=(0, None, None, None))(
            states, s, 8, GROUP * DELTA
        )
        return res.state, res.ok

    # non-donated so we can re-run on identical input
    t_merge = timed(lambda: merge_only(stacked, sl))
    log(f"merge_slice x{NEIGHBOURS} (no donation): {t_merge*1e3:.1f} ms/call")

    # --- roots only ---
    leaf = stacked.leaf

    @jax.jit
    def roots_xla(lf):
        return jax.vmap(lambda x: tree_from_leaves(x)[0][0])(lf)

    t_roots = timed(lambda: roots_xla(leaf))
    log(f"tree roots XLA x{NEIGHBOURS}: {t_roots*1e3:.1f} ms/call")

    # --- single-neighbour merge (dispatch floor) ---
    one_state = jax.tree_util.tree_map(lambda x: x[0], stacked)

    @jax.jit
    def merge_one(state, s):
        res = merge_slice(state, s, 8, GROUP * DELTA)
        return res.state, res.ok

    t_one = timed(lambda: merge_one(one_state, sl))
    log(f"merge_slice x1: {t_one*1e3:.1f} ms/call")

    # --- GROUP=1-sized slice, 64 neighbours (per-merge dispatch cost) ---
    slices1, _ = interval_delta_stream(22, rng, 1, DELTA, L, bin_width=8)

    @jax.jit
    def merge_small(states, s):
        res = jax.vmap(merge_slice, in_axes=(0, None, None, None))(
            states, s, 8, DELTA
        )
        return res.state, res.ok

    t_small = timed(lambda: merge_small(stacked, slices1[0]))
    log(f"merge_slice x{NEIGHBOURS}, {DELTA}-entry slice: {t_small*1e3:.1f} ms/call")

    log(
        f"summary: merge {t_merge*1e3:.1f} + roots {t_roots*1e3:.1f} ms; "
        f"bench-call estimate {(t_merge + t_roots)*1e3:.1f} ms"
    )


if __name__ == "__main__":
    main()
