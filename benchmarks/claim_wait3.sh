#!/bin/bash
# Round-5 claim waiter with an EPOCH deadline (claim_wait2.sh compared
# HH:MM strings, which breaks when the window crosses midnight UTC).
# Probes until DEADLINE_EPOCH (unix seconds) and fires the resume
# matrix on recovery. Leaves enough margin that a ~1-2h matrix is done
# before the round driver runs its own bench.
set -u
cd "$(dirname "$0")/.."
LOG="${1:-benchmarks/results/claim_wait.log}"
DEADLINE="${DEADLINE_EPOCH:?set DEADLINE_EPOCH (unix seconds)}"
say() { echo "[claim-wait3 $(date -u +%H:%M:%S)] $*" | tee -a "$LOG"; }

say "starting; deadline $(date -u -d "@$DEADLINE" +%Y-%m-%dT%H:%M:%SZ)"
while true; do
  if [ "$(date +%s)" -ge "$DEADLINE" ]; then
    say "deadline reached with the claim still down — stopping"
    exit 1
  fi
  if timeout 120 python -c "import jax; jax.devices()" >/dev/null 2>&1; then
    say "claim recovered — firing resume matrix"
    bash benchmarks/resume_tpu_matrix.sh benchmarks/results/tpu_resume.log
    say "resume matrix finished"
    exit 0
  fi
  say "claim still down — sleeping 120s"
  sleep 120
done
