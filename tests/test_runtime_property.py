"""Runtime-level oracle property (the reference's lattice-vs-model
pattern, ``aw_lww_map_property_test.exs:18-76``, lifted to the FULL
replica runtime: mutation queue, eager pushes, digest walk, diff feed).

With full convergence after every op, a plain dict is an exact oracle:
a remove observes every dot (nothing concurrent survives), so add-wins
semantics coincide with sequential map semantics. Divergence-mode
properties (partial sync, drops) live in ``test_simnet.py``.
"""

import pytest

pytest.importorskip("hypothesis")  # collection must degrade gracefully without it
from hypothesis import given, settings, strategies as st

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from tests.conftest import converge


@settings(max_examples=10, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(min_value=0, max_value=2),  # writer
            st.sampled_from(["add", "add", "add", "remove", "clear"]),
            st.integers(min_value=1, max_value=8),  # key
            st.integers(min_value=0, max_value=99),  # value
        ),
        max_size=12,
    ),
)
def test_fully_synced_scripts_match_dict_oracle(script):
    transport = LocalTransport()
    clock = LogicalClock()
    reps = [
        start_link(
            AWLWWMap,
            threaded=False,
            transport=transport,
            clock=clock,
            capacity=64,
            tree_depth=5,
        )
        for _ in range(3)
    ]
    for r in reps:
        r.set_neighbours([x for x in reps if x is not r])
    converge(transport, reps)

    model: dict = {}
    for who, op, key, val in script:
        if op == "add":
            reps[who].mutate("add", [key, val])
            model[key] = val
        elif op == "remove":
            reps[who].mutate("remove", [key])
            model.pop(key, None)
        else:
            reps[who].mutate("clear", [])
            model.clear()
        converge(transport, reps)
        for r in reps:
            assert r.read() == model, (op, key, val)
