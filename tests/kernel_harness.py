"""Thin host harness driving the lattice kernels directly (no runtime).

Keys and values are small ints carried verbatim in the device columns
(key = uint64 id, value = the ``valh`` column), so lattice tests compare
kernel output against the pure-Python spec without any payload plumbing.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from delta_crdt_ex_tpu.models.aw_lww_map import AWLWWMap
from delta_crdt_ex_tpu.models.state import DotStore
from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_CLEAR, OP_REMOVE


class KernelMap:
    def __init__(self, gid: int, capacity: int = 64, rcap: int = 8, num_buckets: int = 64):
        self.gid = gid
        state = DotStore.new(capacity, rcap, num_buckets)
        self.state = dataclasses.replace(
            state, ctx_gid=state.ctx_gid.at[0].set(jnp.uint64(gid))
        )
        self.slot = 0

    def _apply(self, op_rows):
        k = 8
        while k < len(op_rows):
            k *= 2
        op = np.zeros(k, np.int32)
        key = np.zeros(k, np.uint64)
        valh = np.zeros(k, np.uint32)
        ts = np.zeros(k, np.int64)
        for i, (o, kk, v, t) in enumerate(op_rows):
            op[i], key[i], valh[i], ts[i] = o, kk, v, t
        while True:
            res = AWLWWMap.apply_batch(
                self.state, jnp.int32(self.slot), *map(jnp.asarray, (op, key, valh, ts))
            )
            if bool(res.ok):
                self.state = res.state
                return res
            self.state = self.state.grow(self.state.capacity * 2)

    def add(self, key: int, val: int, ts: int):
        return self._apply([(OP_ADD, key, val, ts)])

    def remove(self, key: int, ts: int = 0):
        return self._apply([(OP_REMOVE, key, 0, ts)])

    def clear(self, ts: int = 0):
        return self._apply([(OP_CLEAR, 0, 0, ts)])

    def batch(self, rows):
        return self._apply(rows)

    def join_from(self, other: "KernelMap"):
        while True:
            res = AWLWWMap.join(self.state, other.state)
            if bool(res.ok):
                self.state = res.state
                return res
            self.state = self.state.grow(
                self.state.capacity * 2, self.state.replica_capacity * 2
            )

    def read(self) -> dict[int, int]:
        w = AWLWWMap.winner_slice(self.state, None, out_size=self.state.capacity)
        count = int(w.count)
        keys = np.asarray(w.key)[:count]
        vals = np.asarray(w.valh)[:count]
        return {int(keys[i]): int(vals[i]) for i in range(count)}

    def ctx(self) -> dict[int, int]:
        """Global compressed-context view (reference ``Dots.compress``)."""
        gids = np.asarray(self.state.ctx_gid)
        maxs = np.asarray(self.state.global_ctx())
        return {int(g): int(m) for g, m in zip(gids, maxs) if g != 0 and m != 0}

    def alive_count(self) -> int:
        return int(self.state.num_alive())
