"""Thin host harness driving the lattice kernels directly (no runtime).

Keys and values are small ints carried verbatim in the device columns
(key = uint64 id, value = the ``valh`` column), so lattice tests compare
kernel output against the pure-Python spec without any payload plumbing.
"""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp
import numpy as np

from delta_crdt_ex_tpu.models.aw_lww_map import AWLWWMap
from delta_crdt_ex_tpu.models.state import DotStore
from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_CLEAR, OP_REMOVE


class KernelMap:
    def __init__(self, gid: int, capacity: int = 64, rcap: int = 8, num_buckets: int = 64):
        self.gid = gid
        state = DotStore.new(capacity, rcap, num_buckets)
        self.state = dataclasses.replace(
            state, ctx_gid=state.ctx_gid.at[0].set(jnp.uint64(gid))
        )
        self.slot = 0

    def _apply(self, op_rows):
        k = 8
        while k < len(op_rows):
            k *= 2
        op = np.zeros(k, np.int32)
        key = np.zeros(k, np.uint64)
        valh = np.zeros(k, np.uint32)
        ts = np.zeros(k, np.int64)
        for i, (o, kk, v, t) in enumerate(op_rows):
            op[i], key[i], valh[i], ts[i] = o, kk, v, t
        while True:
            res = AWLWWMap.apply_batch(
                self.state, jnp.int32(self.slot), *map(jnp.asarray, (op, key, valh, ts))
            )
            if bool(res.ok):
                self.state = res.state
                return res
            self.state = self.state.grow(self.state.capacity * 2)

    def add(self, key: int, val: int, ts: int):
        return self._apply([(OP_ADD, key, val, ts)])

    def remove(self, key: int, ts: int = 0):
        return self._apply([(OP_REMOVE, key, 0, ts)])

    def clear(self, ts: int = 0):
        return self._apply([(OP_CLEAR, 0, 0, ts)])

    def batch(self, rows):
        return self._apply(rows)

    def join_from(self, other: "KernelMap"):
        while True:
            res = AWLWWMap.join(self.state, other.state)
            if bool(res.ok):
                self.state = res.state
                return res
            self.state = self.state.grow(
                self.state.capacity * 2, self.state.replica_capacity * 2
            )

    def read(self) -> dict[int, int]:
        w = AWLWWMap.winner_slice(self.state, None, out_size=self.state.capacity)
        count = int(w.count)
        keys = np.asarray(w.key)[:count]
        vals = np.asarray(w.valh)[:count]
        return {int(keys[i]): int(vals[i]) for i in range(count)}

    def ctx(self) -> dict[int, int]:
        """Global compressed-context view (reference ``Dots.compress``)."""
        gids = np.asarray(self.state.ctx_gid)
        maxs = np.asarray(self.state.global_ctx())
        return {int(g): int(m) for g, m in zip(gids, maxs) if g != 0 and m != 0}

    def alive_count(self) -> int:
        return int(self.state.num_alive())


class BinnedKernelMap:
    """Same harness over the bucket-binned engine (models/binned.py).
    All backend differences ride the model seam (``grow_for_apply`` /
    ``post_apply`` / the shared wire-slice shape), so
    :class:`HashKernelMap` below is this class with a different model
    resolved — one drive implementation serves both parity sides."""

    @staticmethod
    def _resolve():
        from delta_crdt_ex_tpu.models.binned import BinnedStore
        from delta_crdt_ex_tpu.models.binned_map import BinnedAWLWWMap

        return BinnedAWLWWMap, BinnedStore

    def __init__(self, gid: int, capacity: int = 64, rcap: int = 8, num_buckets: int = 64):
        self.M, store_cls = self._resolve()
        self.gid = gid
        bin_cap = 4
        while bin_cap * num_buckets < capacity:  # power-of-two tier
            bin_cap *= 2
        state = store_cls.new(num_buckets, bin_cap, rcap)
        self.state = dataclasses.replace(
            state, ctx_gid=state.ctx_gid.at[0].set(jnp.uint64(gid))
        )
        self.slot = 0

    def _apply(self, op_rows):
        # split at clears (clear is a full-state kernel, not a row op)
        seg: list = []
        results = []
        for row in op_rows:
            if row[0] == OP_CLEAR:
                results.append(self._apply_segment(seg))
                seg = []
                self.state = self.M.clear_all(self.state)
            else:
                seg.append(row)
        results.append(self._apply_segment(seg))
        return results[-1]

    def _apply_segment(self, op_rows):
        if not op_rows:
            return None
        op = np.array([r[0] for r in op_rows], np.int32)
        key = np.array([r[1] for r in op_rows], np.uint64)
        valh = np.array([r[2] for r in op_rows], np.uint32)
        ts = np.array([r[3] for r in op_rows], np.int64)
        g = self.M.group_batch(self.state.num_buckets, op, key, valh, ts)
        while True:
            res = self.M.row_apply(
                self.state,
                jnp.int32(self.slot),
                *map(jnp.asarray, (g.rows, g.op, g.key, g.valh, g.ts)),
            )
            if bool(res.ok):
                self.state = self.M.post_apply(res.state, res)
                return res
            self.state = self.M.grow_for_apply(self.state)

    def add(self, key: int, val: int, ts: int):
        return self._apply([(OP_ADD, key, val, ts)])

    def remove(self, key: int, ts: int = 0):
        return self._apply([(OP_REMOVE, key, 0, ts)])

    def clear(self, ts: int = 0):
        return self._apply([(OP_CLEAR, 0, 0, ts)])

    def batch(self, rows):
        return self._apply(rows)

    def join_from(self, other):
        # extraction runs on the SOURCE's model: either backend's slice
        # merges here (the wire slice shape is shared, ISSUE 8)
        rows = np.arange(other.state.num_buckets, dtype=np.int32)
        sl = other.M.extract_rows(other.state, jnp.asarray(rows))
        return self.merge_slice(sl)

    def merge_slice(self, sl):
        # the harness drives the runtime's merge path (row-granular);
        # the element-scatter bulk kernel keeps its own parity suite
        # (tests/test_merge_parity.py)
        self.state, res = self.M.merge_rows_into(self.state, sl)
        return res

    def read(self) -> dict[int, int]:
        return read_binned_state(self.state)

    def ctx(self) -> dict[int, int]:
        gids = np.asarray(self.state.ctx_gid)
        maxs = np.asarray(self.state.global_ctx())
        return {int(g): int(m) for g, m in zip(gids, maxs) if g != 0 and m != 0}

    def alive_count(self) -> int:
        return int(self.state.num_alive())


class HashKernelMap(BinnedKernelMap):
    """The open-addressing hash engine (ISSUE 8, models/hash_store.py)
    through the same drive: only the resolved model and the read differ
    — everything else rides the backend seam the base class uses."""

    @staticmethod
    def _resolve():
        from delta_crdt_ex_tpu.models.hash_store import HashAWLWWMap, HashStore

        return HashAWLWWMap, HashStore

    def read(self) -> dict[int, int]:
        return read_hash_state(self.state)


def read_hash_state(state) -> dict[int, int]:
    """{key: valh} LWW read of a HashStore (shared by harnesses/tests)."""
    from delta_crdt_ex_tpu.models.hash_store import HashAWLWWMap

    w = HashAWLWWMap.winner_all(state)
    win = np.asarray(w.win)
    keys = np.asarray(w.key)[win]
    vals = np.asarray(w.valh)[win]
    return {int(k): int(v) for k, v in zip(keys, vals)}


def read_binned_state(state) -> dict[int, int]:
    """{key: valh} LWW read of a BinnedStore (shared by harnesses/tests)."""
    from delta_crdt_ex_tpu.models.binned_map import BinnedAWLWWMap

    rows = jnp.arange(state.num_buckets, dtype=jnp.int32)
    w = BinnedAWLWWMap.winner_rows(state, rows)
    win = np.asarray(w.win)
    keys = np.asarray(w.key)[win]
    vals = np.asarray(w.valh)[win]
    return {int(k): int(v) for k, v in zip(keys, vals)}
