"""Lattice kernel tests vs the pure-Python spec and a plain-dict model.

Ports the reference's lattice suite (``test/aw_lww_map_test.exs``,
``test/aw_lww_map_property_test.exs``): unit cases plus the oracle
pattern — arbitrary add/remove sequences must read back like a plain
dict (SURVEY §4).
"""

import random

import pytest
pytest.importorskip("hypothesis")  # collection must degrade gracefully without it
from hypothesis import given, settings, strategies as st

from delta_crdt_ex_tpu.utils.pyref import PyAWLWWMap
from tests.kernel_harness import BinnedKernelMap, KernelMap


@pytest.fixture(params=["flat", "binned"], scope="module")
def M(request):
    """Both lattice engines must pass the whole suite: the flat heap
    (models/state.py) and the bucket-binned layout (models/binned.py)."""
    return KernelMap if request.param == "flat" else BinnedKernelMap

A_GID, B_GID = 11, 22


def test_can_add_and_read_a_value(M):
    m = M(A_GID)
    m.add(1, 2, ts=1)
    assert m.read() == {1: 2}


def test_can_join_two_adds(M):
    a = M(A_GID)
    a.add(1, 2, ts=1)
    b = M(B_GID)
    b.add(2, 2, ts=2)
    a.join_from(b)
    assert a.read() == {1: 2, 2: 2}


def test_can_remove_elements(M):
    m = M(A_GID)
    m.add(1, 2, ts=1)
    m.remove(1)
    assert m.read() == {}


def test_remove_only_kills_observed_dots_add_wins(M):
    # concurrent add at B vs remove at A: the unobserved add survives
    a = M(A_GID)
    a.add(1, 2, ts=1)
    b = M(B_GID)
    b.join_from(a)
    b.add(1, 99, ts=2)  # B's new dot, unseen by A
    a.remove(1)  # kills only A-observed dots
    b.join_from(a)
    assert b.read() == {1: 99}


def test_can_resolve_conflicts_lww(M):
    m = M(A_GID)
    m.add(1, 2, ts=1)
    m.add(1, 3, ts=2)
    assert m.read() == {1: 3}
    # the losing value's entry is gone, not just shadowed
    assert m.alive_count() == 1


def test_context_stays_compressed(M):
    # reference "can compute actual dots present": state context is the
    # compressed per-node max, not a growing dot list
    m = M(A_GID)
    m.add(1, 2, ts=1)
    m.add(1, 3, ts=2)
    assert m.ctx() == {A_GID: 2}
    assert m.alive_count() == 1


def test_clear_removes_everything(M):
    m = M(A_GID)
    m.add(1, 2, ts=1)
    m.add(2, 3, ts=2)
    m.clear()
    assert m.read() == {}
    # cleared dots stay observed: rejoining an old copy must not resurrect
    old = M(B_GID)
    old.add(3, 4, ts=3)
    m.join_from(old)
    assert m.read() == {3: 4}


def test_batch_sequential_semantics(M):
    from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_CLEAR, OP_REMOVE

    m = M(A_GID)
    m.batch(
        [
            (OP_ADD, 1, 10, 1),
            (OP_ADD, 2, 20, 2),
            (OP_ADD, 1, 11, 3),  # shadows the first add
            (OP_REMOVE, 2, 0, 4),
            (OP_ADD, 3, 30, 5),
        ]
    )
    assert m.read() == {1: 11, 3: 30}
    m.batch([(OP_ADD, 4, 40, 6), (OP_CLEAR, 0, 0, 7), (OP_ADD, 5, 50, 8)])
    assert m.read() == {5: 50}


def test_join_is_idempotent_and_commutative(M):
    a = M(A_GID)
    a.add(1, 1, ts=1)
    a.add(2, 2, ts=2)
    b = M(B_GID)
    b.add(2, 22, ts=3)
    b.add(3, 3, ts=4)

    ab = M(A_GID)
    ab.add(1, 1, ts=1)
    ab.add(2, 2, ts=2)
    ab.join_from(b)
    ab.join_from(b)  # idempotent
    ba = M(B_GID)
    ba.add(2, 22, ts=3)
    ba.add(3, 3, ts=4)
    ba.join_from(a)
    assert ab.read() == ba.read() == {1: 1, 2: 22, 3: 3}


ops_strategy = st.lists(
    st.tuples(
        st.sampled_from(["add", "remove"]),
        st.integers(min_value=1, max_value=8),  # key
        st.integers(min_value=0, max_value=100),  # value
    ),
    max_size=40,
)


@settings(max_examples=60, deadline=None)
@given(ops_strategy)
def test_property_single_replica_matches_dict_model(M, ops):
    """Reference property: arbitrary add/remove sequence == plain Map
    (``aw_lww_map_test.exs:51-86``)."""
    m = M(A_GID, capacity=128)
    model = {}
    spec = PyAWLWWMap()
    for i, (op, key, val) in enumerate(ops):
        ts = i + 1
        if op == "add":
            m.add(key, val, ts=ts)
            delta = spec.add(key, val, A_GID, ts)
            model[key] = val
        else:
            m.remove(key, ts=ts)
            delta = spec.remove(key)
            model.pop(key, None)
        spec = spec.join(delta, [key])
    assert m.read() == model
    assert spec.read() == model


@settings(max_examples=40, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(min_value=0, max_value=2),  # replica
            st.sampled_from(["add", "remove", "sync"]),
            st.integers(min_value=1, max_value=6),  # key / sync target
            st.integers(min_value=0, max_value=50),  # value
        ),
        max_size=30,
    ),
    st.randoms(use_true_random=False),
)
def test_property_multi_replica_convergence_vs_spec(M, script, rnd):
    """Random concurrent ops + random pairwise joins on 3 replicas: the
    kernel lattice and the Python spec stay in lockstep, and full pairwise
    sync converges everyone to the same read."""
    gids = [101, 202, 303]
    ks = [M(g, capacity=128) for g in gids]
    specs = [PyAWLWWMap() for _ in gids]
    ts = 0
    for who, op, key, val in script:
        ts += 1
        if op == "add":
            ks[who].add(key, val, ts=ts)
            specs[who] = specs[who].join(specs[who].add(key, val, gids[who], ts), [key])
        elif op == "remove":
            ks[who].remove(key, ts=ts)
            specs[who] = specs[who].join(specs[who].remove(key), [key])
        else:
            other = key % 3
            if other != who:
                ks[who].join_from(ks[other])
                all_keys = set(specs[who].value) | set(specs[other].value)
                specs[who] = specs[who].join(
                    PyAWLWWMap(dots=specs[other].dots, value=specs[other].value),
                    list(all_keys),
                )
        assert ks[who].read() == specs[who].read()

    # full mesh sync until converged
    for _ in range(3):
        for i in range(3):
            for j in range(3):
                if i != j:
                    ks[i].join_from(ks[j])
                    all_keys = set(specs[i].value) | set(specs[j].value)
                    specs[i] = specs[i].join(
                        PyAWLWWMap(dots=specs[j].dots, value=specs[j].value),
                        list(all_keys),
                    )
    reads = [k.read() for k in ks]
    assert reads[0] == reads[1] == reads[2] == specs[0].read()
