"""Obs HTTP endpoint smoke (ISSUE 9 CI satellite): start the server on
an ephemeral port against a live replica, scrape ``/metrics`` +
``/healthz`` + ``/varz``, and validate the Prometheus text exposition
line grammar — the tier-1 proof that the export surface actually
serves."""

from __future__ import annotations

import json
import re
import urllib.error
import urllib.request

import pytest

from delta_crdt_ex_tpu.api import set_neighbours, start_link
from delta_crdt_ex_tpu.runtime.metrics import Observability

#: exposition format 0.0.4 line grammar: HELP/TYPE comments or a sample
#: ``name{labels} value`` line (labels optional, value int/float/±Inf)
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*"
    r"(\{[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\""
    r"(,[a-zA-Z_][a-zA-Z0-9_]*=\"(?:[^\"\\\n]|\\.)*\")*\})?"
    r" [-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|Inf|NaN)$"
)
_COMMENT_RE = re.compile(r"^# (HELP|TYPE) [a-zA-Z_:][a-zA-Z0-9_:]*( .*)?$")


@pytest.fixture
def plane():
    p = Observability(lag_sample_every=1)
    yield p
    p.close()


@pytest.fixture
def served(plane, transport):
    a = start_link(threaded=False, transport=transport, obs=plane, name="srv-a")
    b = start_link(threaded=False, transport=transport, obs=plane, name="srv-b")
    set_neighbours(a, [b])
    set_neighbours(b, [a])
    a.mutate("add", ["k1", "v1"])
    b.mutate("add", ["k2", "v2"])
    for _ in range(4):
        a.sync_to_all()
        b.sync_to_all()
        transport.pump()
    server = plane.serve(port=0)  # ephemeral port: parallel test safety
    yield plane, server, a, b
    a.stop()
    b.stop()


def _get(url: str):
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


def test_metrics_exposition_grammar(served):
    plane, server, _a, _b = served
    status, ctype, body = _get(server.url + "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    lines = [l for l in body.splitlines() if l]
    assert lines, "empty exposition"
    for line in lines:
        assert _COMMENT_RE.match(line) or _SAMPLE_RE.match(line), (
            f"exposition grammar violation: {line!r}"
        )
    # every TYPE'd family renders samples of that family, HELP precedes
    assert "# TYPE crdt_sync_done_total counter" in body
    assert 'crdt_sync_done_total{name="srv-a"}' in body
    # scrape-time collector gauges are present (mailbox/seq polled live)
    assert 'crdt_sequence_number{name="srv-a"}' in body
    # histograms export the full _bucket/_sum/_count triple
    assert 'crdt_merge_dispatch_seconds_bucket{le="+Inf",name="srv-a",plane="host"}' in body
    assert "crdt_merge_dispatch_seconds_sum" in body
    assert "crdt_merge_dispatch_seconds_count" in body
    # the lag tracer's per-peer histograms are on the same page
    assert "crdt_replication_lag_seconds_bucket" in body


def test_healthz_contract(served):
    plane, server, _a, _b = served
    status, ctype, body = _get(server.url + "/healthz")
    assert status == 200 and ctype.startswith("application/json")
    doc = json.loads(body)
    assert doc["status"] == "ok"
    assert doc["checks"]["replica:srv-a"]["ok"] is True
    assert doc["checks"]["replica:srv-a"]["wal_writable"] is True

    # one failing check flips the page to 503 (the k8s probe contract)
    plane.add_health_check("injected", lambda: {"ok": False, "why": "test"})
    try:
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(server.url + "/healthz")
        assert exc.value.code == 503
        doc = json.loads(exc.value.read().decode())
        assert doc["status"] == "unhealthy"
        assert doc["checks"]["injected"]["ok"] is False
    finally:
        plane.remove_source("injected")


def test_varz_unifies_stats_sources(served):
    plane, server, a, _b = served
    status, _ctype, body = _get(server.url + "/varz")
    assert status == 200
    doc = json.loads(body)
    stanza = doc["sources"]["replica:srv-a"]
    assert stanza["kind"] == "replica"
    # the stats() dict rides UNCHANGED under the envelope — including
    # the wal/ingress/catchup sub-dicts tests already rely on
    live = a.stats()
    assert stanza["stats"]["sequence_number"] == live["sequence_number"]
    assert set(stanza["stats"]) == set(live)
    assert doc["metrics_families"] > 0


def test_root_and_unknown_paths(served):
    _plane, server, _a, _b = served
    status, _ctype, body = _get(server.url + "/")
    assert status == 200 and "/metrics" in body
    with pytest.raises(urllib.error.HTTPError) as exc:
        _get(server.url + "/nope")
    assert exc.value.code == 404


def test_serve_is_idempotent_and_stop_releases(plane):
    s1 = plane.serve(port=0)
    s2 = plane.serve(port=0)
    assert s1 is s2
    url = s1.url
    _get(url + "/metrics")
    plane.close()
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _get(url + "/metrics")


def test_wal_and_transport_gauges_scrape(tmp_path, transport):
    plane = Observability()
    try:
        rep = start_link(
            threaded=False, transport=transport, obs=plane,
            name="walrep", wal_dir=str(tmp_path), fsync_mode="none",
        )
        rep.mutate("add", ["k", "v"])
        out = plane.registry.render()
        assert 'crdt_wal_segments{name="walrep"} 1' in out
        assert 'crdt_wal_append_records_total{name="walrep"} 1' in out
        m = re.search(r'crdt_wal_bytes\{name="walrep"\} (\d+)', out)
        assert m and int(m.group(1)) > 0
        assert int(m.group(1)) == rep.wal_size_bytes()
        rep.stop()
    finally:
        plane.close()


def test_flight_recorder_dumped_on_crash(tmp_path, transport, caplog):
    import logging

    plane = Observability()
    try:
        rep = start_link(
            threaded=False, transport=transport, obs=plane, name="crashy",
            wal_dir=str(tmp_path), fsync_mode="none",
        )
        rep.mutate("add", ["k", "v"])
        rep.checkpoint()  # records a wal_compact flight event
        assert rep.flight.events(kind="wal_compact")
        with caplog.at_level(logging.ERROR, logger="delta_crdt_ex_tpu"):
            rep.crash()
        assert any("flight recorder" in m for m in caplog.messages)
        assert any("wal_compact" in m for m in caplog.messages)
    finally:
        plane.close()


def test_jit_compile_counter_end_to_end(served):
    """ISSUE 12: the compile-cache audit rides the plane end-to-end —
    the scrape-time collector runs ``jitcache.audit()``, the JIT_COMPILE
    bridge row folds it into ``crdt_jit_compiles_total{name=...}``, and
    ``/varz`` carries the per-root snapshot. The served replicas above
    merged through the named entry roots, so the counter is live."""
    from delta_crdt_ex_tpu.utils import jitcache

    plane, server, _a, _b = served
    status, _ctype, body = _get(server.url + "/metrics")
    assert status == 200
    assert "# TYPE crdt_jit_compiles_total gauge" in body
    m = re.search(r'crdt_jit_compiles_total\{name="merge_rows"\} (\d+)', body)
    assert m and int(m.group(1)) >= 1, body[:2000]
    # the exported value is the audit's absolute per-root count
    assert int(m.group(1)) == jitcache.compile_counts()["merge_rows"]

    status, _ctype, vbody = _get(server.url + "/varz")
    doc = json.loads(vbody)
    stanza = doc["sources"]["jitcache"]
    assert stanza["kind"] == "jitcache"
    assert stanza["stats"]["compiles"]["merge_rows"] >= 1


def test_mesh_gauges_scrape_and_unregister(transport):
    """ISSUE 13 satellite: a mesh-mode fleet exports the ``crdt_mesh_*``
    surface — polled shard-layout gauges plus the MESH_EXCHANGE bridge
    counters — and ``unregister_fleet`` (via ``Fleet.stop``) removes the
    gauges so a stopped fleet never scrapes as a stale last value."""
    from delta_crdt_ex_tpu.runtime.fleet import Fleet
    from delta_crdt_ex_tpu.utils.devices import fleet_mesh

    plane = Observability()
    try:
        members = [
            start_link(
                threaded=False, transport=transport, obs=plane,
                name=f"mobs{i}", node_id=400 + i, sync_timeout=600.0,
            )
            for i in range(2)
        ]
        for i in range(2):
            members[i].set_neighbours([members[1 - i]])
        fleet = Fleet(members, mesh=fleet_mesh(2), obs=plane)
        members[0].mutate("add", ["k", "v"])
        members[1].mutate("add", ["k2", "v2"])
        fleet.sync_tick()
        fleet.drain()
        lb = f'fleet="{id(fleet)}"'
        out = plane.registry.render()
        assert f"crdt_mesh_shards{{{lb}}} 2" in out
        assert f"crdt_mesh_members_per_shard{{{lb}}} 1" in out
        # the bridge rows folded the tick's MESH_EXCHANGE event
        m = re.search(
            rf'crdt_mesh_intra_entries_total\{{{lb}\}} (\d+)', out
        )
        assert m and int(m.group(1)) >= 1, out[:2000]
        assert f"crdt_mesh_fallback_entries_total{{{lb}}} 0" in out
        assert re.search(
            rf'crdt_mesh_permuted_bytes_total\{{{lb}\}} (\d+)', out
        )
        fleet.stop()
        out = plane.registry.render()
        assert f"crdt_mesh_shards{{{lb}}}" not in out
        assert f"crdt_mesh_members_per_shard{{{lb}}}" not in out
    finally:
        plane.close()


def test_tree_gauges_scrape_and_unregister(transport):
    """ISSUE 15 satellite: tree-mode replicas export the ``crdt_tree_*``
    surface — topology gauges (depth/fanout/role/tier) kept fresh by
    the TREE_TOPOLOGY bridge row, relay coalesce-depth and
    entries-per-re-emit histograms plus per-tier tx/rx byte counters
    fed by TREE_RELAY — and ``unregister_replica`` (via ``stop``)
    removes the gauges so a stopped replica never scrapes stale."""
    plane = Observability()
    try:
        reps = [
            start_link(
                threaded=False, transport=transport, obs=plane,
                name=f"tobs{i}", node_id=500 + i, tree_gossip=True,
                tree_fanout=2, sync_timeout=600.0,
            )
            for i in range(4)
        ]
        for r in reps:
            r.set_neighbours([x.addr for x in reps])
        reps[0].mutate("add", ["k", "v"])
        for _ in range(4):
            for r in reps:
                r.sync_to_all()
            for _ in range(50):
                if not sum(r.process_pending() for r in reps):
                    break
        assert all(r.read().get("k") == "v" for r in reps)
        out = plane.registry.render()
        for name in reps:
            lb = f'name="{name.name}"'
            assert f"crdt_tree_fanout{{{lb}}} 2" in out
            assert re.search(rf'crdt_tree_depth\{{{lb}\}} [1-9]', out)
            assert re.search(rf'crdt_tree_role\{{{lb}\}} [0-2]', out)
            assert re.search(rf'crdt_tree_tier\{{{lb}\}} \d', out)
            assert f"crdt_tree_members{{{lb}}} 4" in out
            assert f"crdt_tree_degraded{{{lb}}} 0" in out
        # at least one relay re-emitted: the histograms + per-tier byte
        # counters carry its TREE_RELAY stream
        m = re.search(r'crdt_tree_reemits_total\{name="([^"]+)"\} (\d+)', out)
        assert m and int(m.group(2)) >= 1, out[:2000]
        relay_name = m.group(1)
        assert re.search(
            rf'crdt_tree_relay_coalesce_depth_count\{{name="{relay_name}"\}} \d',
            out,
        )
        assert re.search(
            rf'crdt_tree_entries_per_reemit_count\{{name="{relay_name}"\}} \d',
            out,
        )
        assert re.search(
            rf'crdt_tree_tx_bytes_total\{{name="{relay_name}",tier="\d+"\}} \d',
            out,
        )
        assert re.search(
            rf'crdt_tree_rx_bytes_total\{{name="{relay_name}",tier="\d+"\}} \d',
            out,
        )
        stopped = reps[0].name
        reps[0].stop()
        out = plane.registry.render()
        for metric in (
            "crdt_tree_depth", "crdt_tree_fanout", "crdt_tree_role",
            "crdt_tree_tier", "crdt_tree_members", "crdt_tree_degraded",
        ):
            assert f'{metric}{{name="{stopped}"}}' not in out
        for r in reps[1:]:
            r.stop()
    finally:
        plane.close()


def test_serve_gauges_scrape_and_unregister_replica(transport):
    """ISSUE 14 satellite: a replica's serving front door exports the
    ``crdt_serve_*`` surface (polled pending/overloaded gauges + the
    bridge-fed admission counters), and ``unregister_replica`` (via
    ``Replica.stop``) removes the gauges so a stopped replica never
    scrapes as a stale last value."""
    from delta_crdt_ex_tpu.api import frontdoor

    plane = Observability()
    try:
        rep = start_link(
            threaded=False, transport=transport, obs=plane, name="srvfd",
        )
        fd = frontdoor(rep)
        fd.mutate("add", ["k", "v"])
        fd.read_keys(["k"])
        out = plane.registry.render()
        assert 'crdt_serve_pending_ops{name="srvfd"} 0' in out
        assert 'crdt_serve_overloaded{name="srvfd"} 0' in out
        assert 'crdt_serve_admitted_ops_total{name="srvfd"} 1' in out
        assert 'crdt_serve_commits_total{name="srvfd"} 1' in out
        assert 'crdt_serve_reads_total{name="srvfd",mode="keys"} 1' in out
        assert "crdt_serve_coalesce_depth_bucket" in out
        assert "crdt_serve_read_seconds_bucket" in out
        # the varz/health sources ride the same registration
        assert "serve:srvfd" in plane.varz()["sources"]
        assert plane.varz()["sources"]["serve:srvfd"]["kind"] == "serve"
        rep.stop()
        out = plane.registry.render()
        assert 'crdt_serve_pending_ops{name="srvfd"}' not in out
        assert 'crdt_serve_overloaded{name="srvfd"}' not in out
        assert "serve:srvfd" not in plane.varz()["sources"]
    finally:
        plane.close()


def test_serve_gauges_cleanup_on_unregister_fleet(transport):
    """ISSUE 14 satellite: a fleet front door's per-member serve gauges
    unwire on ``unregister_fleet`` (via ``Fleet.stop``)."""
    from delta_crdt_ex_tpu.runtime.fleet import Fleet

    plane = Observability()
    try:
        members = [
            start_link(
                threaded=False, transport=transport, obs=plane,
                name=f"sfobs{i}", sync_timeout=600.0,
            )
            for i in range(2)
        ]
        fleet = Fleet(members, obs=plane)
        fd = fleet.frontdoor()
        fd.mutate("add", ["k", "v"])
        fleet.drain()
        out = plane.registry.render()
        assert 'crdt_serve_pending_ops{name="sfobs0"}' in out
        assert 'crdt_serve_pending_ops{name="sfobs1"}' in out
        fleet.stop()
        out = plane.registry.render()
        assert "crdt_serve_pending_ops" not in out.split("# HELP")[0] or True
        assert 'crdt_serve_pending_ops{name="sfobs0"}' not in out
        assert 'crdt_serve_pending_ops{name="sfobs1"}' not in out
        assert 'crdt_serve_overloaded{name="sfobs0"}' not in out
        assert not [k for k in plane.varz()["sources"] if k.startswith("serve:")]
    finally:
        plane.close()


def test_jit_compile_collector_unregistered_on_close(transport):
    """A closed plane must stop running the compile-cache and transfer
    audits and drop their varz sources — the unregister-cleanup contract
    every other collector already honours."""
    plane = Observability()
    try:
        assert "jitcache" in plane.varz()["sources"]
        assert "transfers" in plane.varz()["sources"]
        ncoll = len(plane.registry._collectors)
    finally:
        plane.close()
    assert "jitcache" not in plane.varz()["sources"]
    assert "transfers" not in plane.varz()["sources"]
    assert len(plane.registry._collectors) == ncoll - 2
