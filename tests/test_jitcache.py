"""Compile-cache audit (ISSUE 12): the runtime cross-check of the
SHAPE family's static discipline.

The named-jit registry must count one compile per distinct operand
geometry, surface those counts through telemetry → the metrics bridge
(``crdt_jit_compiles_total{name=...}``), and — THE gate — a fleet
driven through mixed-occupancy tick cycles must compile each entry
root at most once per distinct bucket geometry, with **zero**
steady-state compiles once the tier vocabulary is warm. If the padding
discipline regressed (SHAPE001's subject), this is the test that
watches it happen at runtime.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.models.binned import pow2_tier
from delta_crdt_ex_tpu.runtime import telemetry
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.fleet import Fleet
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from delta_crdt_ex_tpu.utils import jitcache
from tests.test_ingest_coalesce import entries_only


def test_cache_size_probe_supported():
    """The audit leans on the jitted callable's tracing-cache counter —
    if a jax upgrade drops it, this fails loudly instead of letting the
    bench gates go vacuously green."""
    assert jitcache.supported()


def test_named_jit_counts_one_compile_per_geometry():
    jitted = jitcache.named_jit(lambda x: x + 1, name="probe_add_one")
    jitted(jnp.zeros(4))
    jitted(jnp.zeros(4))  # warm: same geometry, no new executable
    assert jitcache.compile_counts()["probe_add_one"] == 1
    jitted(jnp.zeros(8))
    assert jitcache.compile_counts()["probe_add_one"] == 2


def test_audit_emits_jit_compile_telemetry():
    jitted = jitcache.named_jit(lambda x: x * 2, name="probe_double")
    jitted(jnp.zeros(2))
    seen: list = []
    handler = lambda _e, meas, meta: seen.append((meta["name"], meas["compiles"]))
    telemetry.attach(telemetry.JIT_COMPILE, handler)
    try:
        jitcache.audit()
        assert ("probe_double", 1) in seen
        # absolute counts, re-published every audit: any plane's gauge
        # set is idempotent, and a bridge attaching mid-process still
        # exports the true totals
        seen.clear()
        jitcache.audit()
        assert ("probe_double", 1) in seen
        # a new geometry moves the published absolute count
        jitted(jnp.zeros(16))
        seen.clear()
        jitcache.audit()
        assert ("probe_double", 2) in seen
    finally:
        telemetry.detach(telemetry.JIT_COMPILE, handler)


def test_runtime_roots_are_registered():
    """The hot entry roots created through named_jit at import time —
    the audit is useless if the kernel modules bypass it."""
    counts = jitcache.compile_counts()
    for root in ("merge_rows", "row_apply", "fleet_merge_rows",
                 "stack_pytrees", "tree_from_leaves"):
        assert root in counts, root


def _mk(transport, clock, **kw):
    kw.setdefault("sync_timeout", 600.0)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock,
        capacity=64, tree_depth=6, **kw,
    )


def test_fleet_mixed_occupancy_compiles_bounded():
    """THE runtime gate: a fleet driven through mixed-occupancy tick
    cycles (occupancies 5/3/2 → pow2 lane tiers 8/4/2) compiles
    ``fleet_merge_rows`` at most once per distinct bucket geometry, and
    a warm fleet re-running the same occupancy pattern compiles NOTHING
    — the dynamic mirror of SHAPE001's static discipline."""
    transport = LocalTransport()
    clock = LogicalClock()
    n = 5
    senders = [_mk(transport, clock, name=f"jc_s{i}") for i in range(n)]
    fleet = Fleet([
        _mk(transport, clock, name=f"jc_f{i}", node_id=4000 + i)
        for i in range(n)
    ])
    for i, s in enumerate(senders):
        s.set_neighbours([fleet.replicas[i]])

    base = jitcache.compile_counts()
    occupancies = [5, 3, 2]

    def cycle(m: int, wave: int) -> None:
        # the same keys per occupancy each wave: one bucket geometry
        # per occupancy by construction
        for i in range(m):
            for j in range(2):
                senders[i].mutate("add", [1000 * i + j, wave])
            senders[i].sync_to_all()
        for i in range(m):
            entries_only(transport, fleet.replicas[i].addr)
        fleet.drain()
        for s in senders:
            transport.drain(s.addr)  # walk back-traffic: not the subject

    # warmup: two full patterns populate the tier vocabulary (first
    # contact may retier writer tables; the second pass is warm)
    for wave in range(2):
        for m in occupancies:
            cycle(m, wave)

    warm = jitcache.compile_counts()
    st = fleet.stats()
    tiers = {pow2_tier(occ, floor=2) for occ in st["occupancy_hist"]}
    compiled = warm.get("fleet_merge_rows", 0) - base.get("fleet_merge_rows", 0)
    assert compiled >= 1, "the fleet never batched — the gate saw nothing"
    assert compiled <= len(tiers), (
        f"fleet_merge_rows compiled {compiled}x for {len(tiers)} distinct "
        f"bucket lane tiers {sorted(tiers)} — padding discipline regressed "
        f"(occupancy hist {st['occupancy_hist']})"
    )

    # steady state: the same pattern again compiles ZERO new executables
    # across EVERY named root
    for m in occupancies:
        cycle(m, 2)
    steady = jitcache.compile_counts()
    moved = {
        k: (warm.get(k, 0), v)
        for k, v in steady.items()
        if v != warm.get(k, 0)
    }
    assert moved == {}, f"steady-state XLA compiles after warmup: {moved}"


def test_varz_snapshot_shape():
    doc = jitcache.varz()
    assert doc["kind"] == "jitcache"
    assert isinstance(doc["stats"]["compiles"], dict)


def test_register_rejects_name_collision():
    """Silently evicting an earlier root on a name collision would
    blind the audit (and the bench zero-compile gates) to whichever
    object keeps being dispatched — a collision with a DIFFERENT
    callable must raise; re-registering the same object is idempotent."""
    j = jitcache.named_jit(lambda x: x - 1, name="probe_collide")
    jitcache.register("probe_collide", j)  # same object: fine
    with pytest.raises(ValueError, match="probe_collide"):
        jitcache.named_jit(lambda x: x - 2, name="probe_collide")
