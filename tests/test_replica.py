"""Replica runtime tests — ports of ``test/causal_crdt_test.exs``.

Multi-replica topology lives in one process wired through a
LocalTransport (the reference's single-BEAM-node pattern, SURVEY §4),
driven deterministically via ``sync_to_all`` + ``pump`` instead of
``Process.sleep``.
"""

import pytest

from delta_crdt_ex_tpu import AWLWWMap, MemoryStorage
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from tests.conftest import converge


def mk(transport, clock, **opts):
    opts.setdefault("capacity", 64)
    opts.setdefault("tree_depth", 6)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock, **opts
    )


@pytest.fixture
def trio(transport, shared_clock):
    cs = [mk(transport, shared_clock) for _ in range(3)]
    for c in cs:
        c.set_neighbours(cs)  # includes self, like the reference fixture
    transport.pump()
    return cs


def test_basic_case(trio):
    c1, c2, c3 = trio
    c1.mutate_async("add", ["Derek", "Kraan"])
    c1.mutate_async("add", ["Tonci", "Galic"])
    assert c1.read() == {"Derek": "Kraan", "Tonci": "Galic"}


def test_conflicting_updates_resolve(trio, transport):
    c1, c2, c3 = trio
    c1.mutate_async("add", ["Derek", "one_wins"])
    c1.mutate_async("add", ["Derek", "two_wins"])
    c1.mutate_async("add", ["Derek", "three_wins"])
    converge(transport, trio)
    for c in trio:
        assert c.read() == {"Derek": "three_wins"}


def test_add_wins(trio, transport):
    c1, c2, c3 = trio
    c1.mutate("add", ["Derek", "add_wins"])
    c2.mutate("remove", ["Derek"])  # concurrent: c2 hasn't observed c1's dot
    converge(transport, trio)
    assert c1.read() == {"Derek": "add_wins"}
    assert c2.read() == {"Derek": "add_wins"}


def test_can_remove(trio, transport):
    c1, c2, _ = trio
    c1.mutate("add", ["Derek", "add_wins"])
    converge(transport, trio)
    assert c2.read() == {"Derek": "add_wins"}
    c1.mutate("remove", ["Derek"])
    converge(transport, trio)
    assert c1.read() == {}
    assert c2.read() == {}


def test_sync_is_directional(transport, shared_clock):
    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.set_neighbours([c2])
    c1.mutate("add", ["Derek", "Kraan"])
    c2.mutate("add", ["Tonci", "Galic"])
    converge(transport, [c1, c2])
    assert c1.read() == {"Derek": "Kraan"}
    assert c2.read() == {"Derek": "Kraan", "Tonci": "Galic"}


def test_sync_to_neighbours_by_name(transport, shared_clock):
    c1 = mk(transport, shared_clock, name="neighbour_name_1")
    c2 = mk(transport, shared_clock, name="neighbour_name_2")
    c1.set_neighbours(["neighbour_name_2"])
    c2.set_neighbours(["neighbour_name_1"])
    c1.mutate("add", ["Derek", "Kraan"])
    c2.mutate("add", ["Tonci", "Galic"])
    converge(transport, [c1, c2])
    assert c1.read() == {"Derek": "Kraan", "Tonci": "Galic"}
    assert c2.read() == {"Derek": "Kraan", "Tonci": "Galic"}


def test_storage_backend_stores_and_retrieves(transport, shared_clock):
    c = mk(transport, shared_clock, storage_module=MemoryStorage(), name="storage_test")
    c.mutate("add", ["Derek", "Kraan"])
    assert c.read() == {"Derek": "Kraan"}


def test_storage_rehydrates_after_crash(transport, shared_clock):
    c = mk(transport, shared_clock, storage_module=MemoryStorage(), name="storage_test")
    c.mutate("add", ["Derek", "Kraan"])
    node_id = c.node_id
    c.transport.unregister(c.addr)  # simulated crash: no terminate sync

    c2 = mk(transport, shared_clock, storage_module=MemoryStorage(), name="storage_test")
    assert c2.read() == {"Derek": "Kraan"}
    assert c2.node_id == node_id  # dot-namespace continuity (causal_crdt.ex:225-230)


def test_syncs_after_adding_neighbour(transport, shared_clock):
    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.mutate("add", ["CRDT1", "represent"])
    c2.mutate("add", ["CRDT2", "also here"])
    c1.set_neighbours([c2])  # triggers an immediate sync round
    transport.pump()
    assert c2.read() == {"CRDT1": "represent", "CRDT2": "also here"}
    assert c1.read() == {"CRDT1": "represent"}  # directional


def test_sync_after_network_partition(transport, shared_clock):
    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.set_neighbours([c2])
    c2.set_neighbours([c1])
    c1.mutate("add", ["CRDT1", "represent"])
    c2.mutate("add", ["CRDT2", "also here"])
    converge(transport, [c1, c2])
    assert c1.read() == {"CRDT1": "represent", "CRDT2": "also here"}

    # partition
    c1.set_neighbours([])
    c2.set_neighbours([])
    transport.pump()
    c1.mutate("add", ["CRDTa", "only present in 1"])
    c1.mutate("add", ["CRDTb", "only present in 1"])
    c1.mutate("remove", ["CRDT1"])
    converge(transport, [c1, c2])
    assert "CRDTa" in c1.read()
    assert "CRDTa" not in c2.read()
    assert "CRDT1" in c2.read()  # removal can't propagate yet

    # heal
    c1.set_neighbours([c2])
    c2.set_neighbours([c1])
    converge(transport, [c1, c2])
    for c in (c1, c2):
        r = c.read()
        assert "CRDTa" in r and "CRDTb" in r
        assert "CRDT1" not in r
        assert r["CRDT2"] == "also here"


def test_syncing_when_values_happen_to_be_the_same(transport, shared_clock):
    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.set_neighbours([c2])
    c2.set_neighbours([c1])
    c1.mutate("add", ["key", "value"])
    c2.mutate("add", ["key", "value"])  # same value, different dots
    converge(transport, [c1, c2])
    c1.mutate("remove", ["key"])  # must kill BOTH dots everywhere
    converge(transport, [c1, c2])
    assert "key" not in c1.read()
    assert "key" not in c2.read()


def test_down_cleans_monitor_and_outstanding(transport, shared_clock):
    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.set_neighbours([c2])
    converge(transport, [c1, c2])
    assert c2.addr in c1._monitors
    c2.transport.unregister(c2.addr)  # dies
    transport.pump()
    assert c2.addr not in c1._monitors
    assert c2.addr not in c1._outstanding
    c1.sync_to_all()  # must not blow up on the dead neighbour
    transport.pump()


def test_max_sync_size_validation(transport):
    with pytest.raises(ValueError):
        mk(transport, LogicalClock(), max_sync_size=0)
    with pytest.raises(ValueError):
        mk(transport, LogicalClock(), max_sync_size="bogus")
    c = mk(transport, LogicalClock(), max_sync_size="infinite")
    assert c.max_sync_size == float("inf")


def test_max_sync_size_bounds_but_converges(transport, shared_clock):
    c1 = mk(transport, shared_clock, max_sync_size=4, capacity=256, tree_depth=6)
    c2 = mk(transport, shared_clock, max_sync_size=4, capacity=256, tree_depth=6)
    c1.set_neighbours([c2])
    for i in range(40):
        c1.mutate_async("add", [f"k{i}", i])
    converge(transport, [c1, c2], rounds=40)
    assert c2.read() == {f"k{i}": i for i in range(40)}


def test_arbitrary_term_keys_and_values(transport, shared_clock):
    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.set_neighbours([c2])
    key = (1, "tuple", frozenset({3, 4}))
    c1.mutate("add", [key, {"nested": [1, 2, {"deep": None}]}])
    c1.mutate("add", [b"bytes-key", 3.14159])
    converge(transport, [c1, c2])
    got = c2.read()
    assert got[key] == {"nested": [1, 2, {"deep": None}]}
    assert got[b"bytes-key"] == 3.14159


def test_threaded_mode_doctest_flow(transport, shared_clock):
    """The README/doctest happy path with real background sync threads
    (reference doctest, delta_crdt.ex:17-28)."""
    import time

    c1 = start_link(AWLWWMap, transport=transport, clock=shared_clock,
                    sync_interval=0.003, capacity=64, tree_depth=6)
    c2 = start_link(AWLWWMap, transport=transport, clock=shared_clock,
                    sync_interval=0.003, capacity=64, tree_depth=6)
    try:
        # threaded mode: each replica's own loop drains its mailbox
        c1.set_neighbours([c2])
        c2.set_neighbours([c1])
        assert c1.read() == {}
        c1.mutate("add", ["CRDT", "is magic!"])
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if c2.read() == {"CRDT": "is magic!"}:
                break
            time.sleep(0.01)
        assert c2.read() == {"CRDT": "is magic!"}
    finally:
        c1.stop()
        c2.stop()


def test_subscriberless_sync_skips_winner_passes(transport, shared_clock, monkeypatch):
    """Without an on_diffs subscriber, a sync round must not run the
    O(U*B^2) winner passes (VERDICT r1 weak #3): telemetry is fed from the
    merge kernel's own insert/kill counts instead."""
    from delta_crdt_ex_tpu.runtime import telemetry
    from delta_crdt_ex_tpu.runtime.replica import Replica

    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.set_neighbours([c2])
    c1.mutate("add", ["Derek", "Kraan"])

    calls = []
    orig = Replica._winner_records_rows
    monkeypatch.setattr(
        Replica,
        "_winner_records_rows",
        lambda self, rows: calls.append(rows) or orig(self, rows),
    )
    events = []
    handler = lambda e, m, md: events.append((m, md))  # noqa: E731
    telemetry.attach(telemetry.SYNC_DONE, handler)
    try:
        converge(transport, [c1, c2])
    finally:
        telemetry.detach(telemetry.SYNC_DONE, handler)
    assert calls == []  # no winner pass anywhere in the sync rounds
    # telemetry still reports the merged keys on the *receiving* side
    # (fed from the merge kernel's insert/kill counts, not a winner pass)
    assert any(
        m["keys_updated_count"] > 0 for m, md in events if md["name"] == c2.name
    )
    monkeypatch.undo()
    assert c2.read() == {"Derek": "Kraan"}


def test_mutate_and_read_honor_call_timeouts(transport, shared_clock):
    """GenServer.call timeout parity (delta_crdt.ex:117-137): a busy
    replica raises TimeoutError instead of blocking forever."""
    import threading
    import time as _time

    c = mk(transport, shared_clock)
    c.mutate("add", ["k", 1])  # warm the compile so timings are honest

    hold = threading.Event()
    release = threading.Event()

    def holder():
        with c._lock:
            hold.set()
            release.wait(5)

    t = threading.Thread(target=holder, daemon=True)
    t.start()
    assert hold.wait(5)
    t0 = _time.monotonic()
    with pytest.raises(TimeoutError, match="mutate"):
        c.mutate("add", ["k2", 2], timeout=0.2)
    with pytest.raises(TimeoutError, match="read"):
        c.read(timeout=0.2)
    assert _time.monotonic() - t0 < 2.0
    release.set()
    t.join()
    # after the lock frees, the same calls succeed
    c.mutate("add", ["k2", 2], timeout=5)
    assert c.read(timeout=5)["k2"] == 2


def test_concurrent_mutators_race_sync_thread(transport, shared_clock):
    """VERDICT r1 weak #6: multiple user threads mutate both replicas
    while the threaded sync loops run — the lock serialisation must keep
    states consistent and the pair must converge on every written key."""
    import threading

    c1 = mk(transport, shared_clock, name="s1", sync_interval=0.01)
    c2 = mk(transport, shared_clock, name="s2", sync_interval=0.01)
    c1.set_neighbours([c2])
    c2.set_neighbours([c1])
    c1.start()
    c2.start()
    try:
        errs = []

        def writer(rep, base):
            try:
                for i in range(50):
                    if i % 7 == 3:
                        rep.mutate_async("add", [base + i, i])
                    else:
                        rep.mutate("add", [base + i, i], timeout=30)
                    if i % 11 == 5:
                        rep.read(timeout=30)
            except Exception as e:  # surfaced after join
                errs.append(e)

        threads = [
            threading.Thread(target=writer, args=(rep, base))
            for rep, base in ((c1, 0), (c2, 1000), (c1, 2000), (c2, 3000))
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(120)
        assert not errs, errs

        want_keys = {b + i for b in (0, 1000, 2000, 3000) for i in range(50)}
        import time as _t

        deadline = _t.monotonic() + 60
        while _t.monotonic() < deadline:
            r1, r2 = c1.read(timeout=30), c2.read(timeout=30)
            if r1 == r2 and set(r1) == want_keys:
                break
            _t.sleep(0.05)
        assert set(c1.read()) == want_keys
        assert c1.read() == c2.read()
    finally:
        c1.stop()
        c2.stop()


def test_eager_delta_push_converges_in_one_message(transport, shared_clock):
    """Almeida's delta mode: a replica's own fresh dots arrive at a
    neighbour as ONE direct delta-interval EntriesMsg — no digest-walk
    ping-pong rounds needed for own writes."""
    from delta_crdt_ex_tpu.runtime import sync as sync_proto

    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.mutate("add", ["x", 1])
    c1.mutate("add", ["y", 2])
    c1.set_neighbours([c2])  # immediate sync: push + walk open

    msgs = transport.drain(c2.addr)
    pushes = [m for m in msgs if isinstance(m, sync_proto.EntriesMsg)]
    assert pushes, f"no delta push among {[type(m).__name__ for m in msgs]}"
    c2.handle(pushes[0])
    assert c2.read() == {"x": 1, "y": 2}


def test_lost_push_heals_via_get_diff_repair(transport, shared_clock):
    """A lost delta push leaves the next one non-contiguous: the receiver
    detects the gap (need_ctx_gap) and requests the full rows — the
    get_diff repair path."""
    from delta_crdt_ex_tpu.runtime import sync as sync_proto

    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.set_neighbours([c2])
    converge(transport, [c1, c2])

    c1.mutate("add", ["k", 1])
    c1.sync_to_all()
    transport.drain(c2.addr)  # the push (and walk open) are LOST

    c1.mutate("add", ["k", 2])  # same bucket: counter advances past the gap
    c1.sync_to_all()
    msgs = transport.drain(c2.addr)
    pushes = [m for m in msgs if isinstance(m, sync_proto.EntriesMsg)]
    assert pushes and int(pushes[0].arrays["ctx_lo"].max()) > 0  # a true delta interval
    c2.handle(pushes[0])  # gap detected -> repair request
    assert c2.read().get("k") is None  # gapped push was not applied
    gets = [m for m in transport.drain(c1.addr) if isinstance(m, sync_proto.GetDiffMsg)]
    assert gets, "receiver must request full rows on a gapped push"
    c1.handle(gets[0])
    ents = [m for m in transport.drain(c2.addr) if isinstance(m, sync_proto.EntriesMsg)]
    assert ents
    c2.handle(ents[0])
    assert c2.read()["k"] == 2


def test_eager_remove_push_converges_in_one_message(transport, shared_clock):
    """Removes mint no dots, so they ride the full-row push leg: after a
    local remove, one EntriesMsg (state-form, lo=0) carries it to the
    neighbour without walk rounds."""
    from delta_crdt_ex_tpu.runtime import sync as sync_proto

    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.set_neighbours([c2])
    c1.mutate("add", ["k", 1])
    converge(transport, [c1, c2])
    assert c2.read() == {"k": 1}

    c1.mutate("remove", ["k"])
    c1.sync_to_all()
    msgs = transport.drain(c2.addr)
    ents = [m for m in msgs if isinstance(m, sync_proto.EntriesMsg)]
    assert ents, f"no push among {[type(m).__name__ for m in msgs]}"
    for m in ents:
        c2.handle(m)
    assert c2.read() == {}


def test_clear_push_cursor_advances_without_livelock(transport, shared_clock):
    """A clear stamps every bucket; with max_sync_size truncation the
    remove-push cursor must still advance each tick (unique stamps) and
    go quiet once everything is pushed — no perpetual resends."""
    from delta_crdt_ex_tpu.runtime import sync as sync_proto

    c1 = mk(transport, shared_clock, max_sync_size=8)
    c2 = mk(transport, shared_clock, max_sync_size=8)
    c1.set_neighbours([c2])
    for i in range(20):
        c1.mutate("add", [i, i])
    converge(transport, [c1, c2])
    assert len(c2.read()) == 20

    c1.mutate("clear", [])
    # 64 buckets / 8 per tick = 8 ticks to drain the stamps
    for _ in range(12):
        c1.sync_to_all()
        transport.pump()
    assert c2.read() == {}
    assert c1._rm_cursor[c2.addr] == c1._touch_seq
    # quiet: a further tick sends no entries
    c1.sync_to_all()
    msgs = transport.drain(c2.addr)
    assert not any(isinstance(m, sync_proto.EntriesMsg) for m in msgs), (
        "push leg must go quiet once cursors catch up"
    )


def test_64_neighbour_star_fanout(transport, shared_clock):
    """North-star topology at the runtime level: one writer with 64
    neighbours. The grouped delta push extracts once and fans out to all
    64 (equal cursors); everyone converges in a couple of ticks."""
    hub = mk(transport, shared_clock, name="hub")
    leaves = [mk(transport, shared_clock, name=f"leaf{i}") for i in range(64)]
    hub.set_neighbours(leaves)
    for k in range(8):
        hub.mutate("add", [k, k * 10])
    for _ in range(3):
        hub.sync_to_all()
        transport.pump()
    want = {k: k * 10 for k in range(8)}
    for leaf in leaves:
        assert leaf.read() == want
    # steady state: all cursors equal -> one extraction per tick, and
    # an idle tick sends nothing
    hub.sync_to_all()
    n_entries = sum(
        1
        for leaf in leaves
        for m in transport.drain(leaf.addr)
        if type(m).__name__ == "EntriesMsg"
    )
    assert n_entries == 0, "idle tick must not push"


def test_host_dicts_bounded_under_churn(transport, shared_clock):
    """Long-running remove/overwrite churn must not leak the host
    payload/key dictionaries: gc runs automatically every
    ``gc_interval_ops`` payload inserts (round-2 verdict weak #3), so
    their size stays proportional to live entries, not op history."""
    a = mk(transport, shared_clock, gc_interval_ops=64)
    b = mk(transport, shared_clock, gc_interval_ops=64)
    a.set_neighbours([b])
    live_keys = 16
    for rnd in range(30):
        for i in range(live_keys):
            a.mutate("add", [f"k{i}", rnd])  # overwrite churn
        for i in range(live_keys // 2):
            a.mutate("remove", [f"k{i}"])  # remove churn
        a.sync_to_all()
        transport.pump()
    bound = live_keys + a.gc_interval_ops
    assert len(a._payloads) <= bound, len(a._payloads)
    assert len(a._key_terms) <= bound, len(a._key_terms)
    # the receiver accumulates the same churn through EntriesMsg merges
    assert len(b._payloads) <= bound, len(b._payloads)
    assert len(b._key_terms) <= bound, len(b._key_terms)
    # and gc never ate a live entry: both replicas still read correctly
    want = {f"k{i}": 29 for i in range(live_keys // 2, live_keys)}
    assert a.read() == want
    assert b.read() == want


def test_mass_remove_wave_prunes_receiver_dicts(transport, shared_clock):
    """A remove wave reaches the receiver as kills with near-zero
    payloads; kills must count as gc pressure, or the receiver's host
    dicts sit at peak size until unrelated inserts arrive."""
    a = mk(transport, shared_clock, gc_interval_ops=64, capacity=1024, tree_depth=8)
    b = mk(transport, shared_clock, gc_interval_ops=64, capacity=1024, tree_depth=8)
    a.set_neighbours([b])
    for i in range(300):
        a.mutate("add", [f"k{i}", i])
    converge(transport, [a, b])
    assert len(b.read()) == 300
    peak = len(b._payloads)
    assert peak >= 300

    for i in range(280):
        a.mutate("remove", [f"k{i}"])
    converge(transport, [a, b])
    want = {f"k{i}": i for i in range(280, 300)}
    assert b.read() == want
    # kills pressured gc on the receiver: dict well below peak, bounded
    # by live + the pre-gc threshold (max(interval, floor/2))
    assert len(b._payloads) < peak // 2 + 64, (len(b._payloads), peak)


def test_crash_skips_goodbye_sync(transport, shared_clock):
    """crash() must NOT flush or sync pending work (stop() does both):
    peers keep only what already propagated, and monitors get Down."""
    a = mk(transport, shared_clock)
    b = mk(transport, shared_clock)
    a.set_neighbours([b])
    b.set_neighbours([a])
    a.mutate("add", ["seen", 1])
    converge(transport, [a, b])
    assert b.read() == {"seen": 1}

    a.mutate_async("add", ["unflushed", 2])  # queued, never flushed
    a.crash()
    transport.pump()
    assert b.read() == {"seen": 1}, "crash leaked a goodbye sync"
    assert not transport.alive(a.addr)
