"""Deterministic fault-point injection (crdtlint v6, FAULT family runtime).

Three layers under test:

1. the registry/plan mechanics (``utils/faults.py``): seeded schedules
   replay identically, rules fire exactly once at the Nth hit of their
   site, ``suspended()`` pauses without consuming hits, and the
   disarmed path is behaviourally inert;
2. the runtime wiring: an injected failure at a commit boundary rolls
   the replica's seq back and stages nothing durable (retry-safe), the
   WAL scrubs a failed group commit (no duplicate-seq logs), and a
   ``partial_write`` mints a torn tail that recovery truncates to the
   durable prefix;
3. the black box (ISSUE 20 satellite): flight-ring overflow keeps the
   NEWEST events, and ``Replica.crash()`` dumps the ring to
   ``flight_dump_path`` even when a log sink raises mid-dump.
"""

import json
import os

import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime import telemetry
from delta_crdt_ex_tpu.runtime.metrics import FlightRecorder
from delta_crdt_ex_tpu.utils import faults
from delta_crdt_ex_tpu.utils.faults import (
    ACTIONS,
    SITES,
    CrashInjected,
    FaultInjected,
    FaultPlan,
    FaultRule,
)


@pytest.fixture(autouse=True)
def _disarmed():
    """Every test starts and ends with no armed plan (module-global)."""
    faults.disarm()
    yield
    faults.disarm()


# ---------------------------------------------------------------------------
# plan mechanics


def test_rule_validation_rejects_unknown_site_action_and_bad_nth():
    with pytest.raises(ValueError):
        FaultRule("no.such.site", 1, "raise")
    with pytest.raises(ValueError):
        FaultRule("wal.append", 1, "explode")
    with pytest.raises(ValueError):
        FaultRule("wal.append", 0, "raise")


def test_seeded_plans_replay_identically():
    a = FaultPlan.seeded(42, n_rules=5)
    b = FaultPlan.seeded(42, n_rules=5)
    assert [(r.site, r.nth, r.action) for r in a.rules] == [
        (r.site, r.nth, r.action) for r in b.rules
    ]
    c = FaultPlan.seeded(43, n_rules=5)
    assert [(r.site, r.nth, r.action) for r in a.rules] != [
        (r.site, r.nth, r.action) for r in c.rules
    ]
    for r in a.rules:
        assert r.site in SITES and r.action in ACTIONS


def test_disarmed_faultpoint_is_inert():
    assert faults.active() is None
    for _ in range(100):
        assert faults.faultpoint("wal.append") is None
    # nothing counted, nothing tripped
    plan = faults.arm(FaultPlan([("wal.append", 1, "raise")]))
    assert plan.hits == {}


def test_rule_fires_exactly_once_at_nth_hit():
    with faults.armed(FaultPlan([("wal.append", 3, "raise")])) as plan:
        assert faults.faultpoint("wal.append") is None
        assert faults.faultpoint("wal.append") is None
        with pytest.raises(FaultInjected):
            faults.faultpoint("wal.append")
        # fired rules stay down: hit 3 does not re-trip on later hits
        for _ in range(5):
            assert faults.faultpoint("wal.append") is None
        assert plan.exhausted()


def test_unrelated_site_hits_do_not_consume_the_rule():
    with faults.armed(FaultPlan([("wal.fsync", 1, "raise")])):
        for _ in range(10):
            assert faults.faultpoint("wal.append") is None
        with pytest.raises(FaultInjected):
            faults.faultpoint("wal.fsync")


def test_crash_before_raises_crash_injected():
    with faults.armed(FaultPlan([("wal.write", 1, "crash_before")])):
        with pytest.raises(CrashInjected):
            faults.faultpoint("wal.write")


def test_crash_after_trips_at_next_hit_of_any_site():
    with faults.armed(
        FaultPlan([("replica.durable", 1, "crash_after")])
    ) as plan:
        assert faults.faultpoint("replica.durable") is None  # arms only
        assert plan.pending_crash == "replica.durable"
        with pytest.raises(CrashInjected, match="replica.durable"):
            faults.faultpoint("transport.send")  # ANY next hit trips


def test_partial_write_returns_clamped_fraction():
    with faults.armed(FaultPlan([
        FaultRule("wal.write", 1, "partial_write", 0.25),
        FaultRule("wal.write", 2, "partial_write", 7.5),
    ])):
        assert faults.faultpoint("wal.write") == 0.25
        assert faults.faultpoint("wal.write") == 0.99  # clamped


def test_rearming_a_plan_resets_its_counters():
    plan = FaultPlan([("wal.append", 2, "raise")])
    faults.arm(plan)
    assert faults.faultpoint("wal.append") is None
    faults.arm(plan)  # reset: the earlier hit is forgotten
    assert faults.faultpoint("wal.append") is None
    with pytest.raises(FaultInjected):
        faults.faultpoint("wal.append")


def test_suspended_pauses_without_consuming_hits():
    with faults.armed(FaultPlan([("wal.append", 2, "raise")])) as plan:
        assert faults.faultpoint("wal.append") is None
        with faults.suspended():
            # recovery replay: same code paths, no schedule consumption
            for _ in range(10):
                assert faults.faultpoint("wal.append") is None
        assert plan.hits["wal.append"] == 1  # untouched by the replay
        with pytest.raises(FaultInjected):
            faults.faultpoint("wal.append")


def test_trips_ledger_and_telemetry_emission():
    before = faults.trips().get("wal.rotate", 0)
    seen = []
    handler = lambda ev, meas, meta: seen.append((meas, meta))
    telemetry.attach(telemetry.FAULT_TRIP, handler)
    try:
        with faults.armed(FaultPlan([("wal.rotate", 1, "raise")])):
            with pytest.raises(FaultInjected):
                faults.faultpoint("wal.rotate")
    finally:
        telemetry.detach(telemetry.FAULT_TRIP, handler)
    assert faults.trips()["wal.rotate"] == before + 1
    assert seen == [({"trips": 1}, {"site": "wal.rotate"})]
    v = faults.varz()
    assert v["kind"] == "faults" and v["armed"] is False


# ---------------------------------------------------------------------------
# runtime wiring: commit boundaries, WAL scrub, torn tails


def _spawn(name, wal_dir, **kw):
    return start_link(
        AWLWWMap, threaded=False, name=name, capacity=128, tree_depth=5,
        wal_dir=wal_dir, fsync_mode="batch", **kw,
    )


def test_injected_commit_failure_rolls_seq_back_and_stages_nothing(tmp_path):
    rep = _spawn("flt_roll", str(tmp_path))
    try:
        rep.mutate("add", ["a", 1])
        seq0 = rep._seq
        with faults.armed(FaultPlan([("replica.durable", 1, "raise")])):
            with pytest.raises(FaultInjected):
                rep.mutate("add", ["b", 2])
        assert rep._seq == seq0, "failed commit must roll the seq back"
        rep.mutate("add", ["b", 2])  # retry commits cleanly
        assert rep.read() == {"a": 1, "b": 2}
    finally:
        rep.crash()
    rec = _spawn("flt_roll", str(tmp_path))
    try:
        # recovery replays a contiguous log: the failed attempt left no
        # record, the retry's record replays at the rolled-back seq
        assert rec.read() == {"a": 1, "b": 2}
    finally:
        rec.crash()


def test_fsync_failure_scrubs_batch_so_retry_cannot_duplicate_seq(tmp_path):
    """Regression: a fault between WAL byte-write and fsync used to
    leave the record durable while the caller rolled its seq back — the
    retry then minted the same seq and recovery (correctly) rejected
    the duplicate-seq log as corrupt."""
    rep = _spawn("flt_scrub", str(tmp_path))
    try:
        rep.mutate("add", ["a", 1])
        with faults.armed(FaultPlan([("wal.fsync", 1, "raise")])):
            with pytest.raises(FaultInjected):
                rep.mutate("add", ["b", 2])
        rep.mutate("add", ["b", 2])  # same seq re-minted — must be unique
    finally:
        rep.crash()
    rec = _spawn("flt_scrub", str(tmp_path))
    try:
        assert rec.read() == {"a": 1, "b": 2}
    finally:
        rec.crash()


def test_aborted_commit_drops_staged_record_from_the_buffer(tmp_path):
    """Regression (found by ``bench.py --chaos`` seed 14): crash_after
    armed at ``wal.append`` trips at ``wal.write`` — after the record
    is staged but before it is written. If the stale staged bytes
    survive in the append buffer, the replica's next successful commit
    flushes them alongside the retry's re-minted seq."""
    rep = _spawn("flt_abort", str(tmp_path))
    try:
        rep.mutate("add", ["a", 1])
        with faults.armed(FaultPlan([("wal.append", 1, "crash_after")])):
            with pytest.raises(CrashInjected):
                rep.mutate("add", ["b", 2])
        # the "process" survived in-test: the very next commit must not
        # resurrect the aborted record
        rep.mutate("add", ["c", 3])
    finally:
        rep.crash()
    rec = _spawn("flt_abort", str(tmp_path))
    try:
        assert rec.read() == {"a": 1, "c": 3}
    finally:
        rec.crash()


def test_partial_write_tears_tail_and_recovery_truncates(tmp_path):
    rep = _spawn("flt_torn", str(tmp_path))
    try:
        with faults.armed(FaultPlan([
            FaultRule("wal.write", 3, "partial_write", 0.5),
        ])):
            rep.mutate("add", ["a", 1])
            rep.mutate("add", ["b", 2])
            with pytest.raises(CrashInjected, match="partial WAL write"):
                rep.mutate("add", ["c", 3])
    finally:
        rep.crash()
    rec = _spawn("flt_torn", str(tmp_path))
    try:
        # the torn record was never published (FAULT003 ordering), so
        # truncating it loses nothing acknowledged
        assert rec.read() == {"a": 1, "b": 2}
    finally:
        rec.crash()


# ---------------------------------------------------------------------------
# the black box: flight-ring overflow + crash dumps


def test_flight_ring_overflow_keeps_newest_events():
    fr = FlightRecorder("ringtest", capacity=8)
    for i in range(20):
        fr.record("tick", i=i)
    evs = fr.events()
    assert len(evs) == 8
    assert [e["i"] for e in evs] == list(range(12, 20))
    assert fr.dropped() == 12
    assert fr.events_recorded() == 20


def test_flight_dump_survives_a_raising_log_sink():
    fr = FlightRecorder("poisondump", capacity=8)
    for i in range(5):
        fr.record("tick", i=i)

    class FlakyLog:
        def __init__(self):
            self.lines = 0

        def error(self, *a, **kw):
            self.lines += 1
            if self.lines % 2 == 0:
                raise RuntimeError("sink died")

    flaky = FlakyLog()
    assert fr.dump(log=flaky) == 5  # every event attempted, none lost


def test_crash_dumps_flight_ring_to_file_under_injected_fault(tmp_path):
    dump = tmp_path / "blackbox.jsonl"
    rep = start_link(
        AWLWWMap, threaded=False, name="flt_dump", capacity=128,
        tree_depth=5, wal_dir=str(tmp_path / "w"), fsync_mode="batch",
        obs=True, flight_dump_path=str(dump),
    )
    rep.mutate("add", ["a", 1])
    with faults.armed(FaultPlan([("replica.durable", 1, "crash_before")])):
        with pytest.raises(CrashInjected):
            rep.mutate("add", ["b", 2])
    rep.crash()
    assert dump.exists(), "crash() must write the black box"
    lines = [json.loads(l) for l in dump.read_text().splitlines()]
    assert lines, "dump file must hold the ring events"
    assert all(e["replica"] == "flt_dump" for e in lines)
    # the injected failure itself is in the black box: the failed
    # commit recorded a commit_abort trace before re-raising
    aborts = [e for e in lines if e["kind"] == "commit_abort"]
    assert aborts and "CrashInjected" in aborts[0]["error"]
    # a second crash of a fresh incarnation APPENDS (history preserved)
    n0 = len(lines)
    rec = start_link(
        AWLWWMap, threaded=False, name="flt_dump", capacity=128,
        tree_depth=5, wal_dir=str(tmp_path / "w"), fsync_mode="batch",
        obs=True, flight_dump_path=str(dump),
    )
    rec.crash()
    assert len(dump.read_text().splitlines()) >= n0
