"""Serving plane (ISSUE 14): lock-free snapshot reads, coalesced write
admission, backpressure/shedding.

The load-bearing contracts pinned here:

- snapshot reads are consistent (every read equals SOME committed
  generation — no torn reads), versions are observed monotonically per
  front door, and the read path never takes the replica lock — proven
  by reading WHILE the replica lock is held by another thread;
- the admission path and ``mutate_batch`` share one grouped-commit
  implementation (``Replica.apply_ops``): identical op sequences
  produce bit-for-bit identical state AND WAL bytes through either
  entrance;
- overload sheds explicitly (``Overloaded``), flips the plane's health
  check, and recovers when pressure drains;
- the property tests run on both store backends, solo and fleet-member.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import threading
import time

import numpy as np
import pytest

from delta_crdt_ex_tpu.api import frontdoor, start_fleet, start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.serve import (
    Frontdoor,
    Overloaded,
    StaleSnapshot,
)

STORES = ("binned", "hash")


def _mk(transport, store="binned", **kw):
    kw.setdefault("capacity", 4096)
    kw.setdefault("tree_depth", 8)
    return start_link(
        threaded=False, transport=transport, store=store, **kw
    )


def _state_equal(a, b) -> None:
    for f in dataclasses.fields(a.model.Store):
        va, vb = getattr(a.state, f.name), getattr(b.state, f.name)
        if isinstance(va, int):
            assert va == vb, f.name
        else:
            assert np.array_equal(np.asarray(va), np.asarray(vb)), f.name


def _wal_bytes(rep) -> bytes:
    segs = sorted(glob.glob(os.path.join(rep._wal.directory, "*")))
    return b"".join(open(s, "rb").read() for s in segs)


# ----------------------------------------------------------------------
# snapshot reads


@pytest.mark.parametrize("store", STORES)
def test_snapshot_reads_basics(transport, store):
    rep = _mk(transport, store, name=f"sv-basic-{store}")
    fd = frontdoor(rep)
    try:
        fd.mutate("add", ["a", 1])
        fd.mutate("add", ["b/x", 2])
        fd.mutate("add", ["b/y", 3])
        assert fd.read_keys(["a", "missing"]) == {"a": 1}
        assert fd.read() == {"a": 1, "b/x": 2, "b/y": 3}
        assert fd.scan("b/") == {"b/x": 2, "b/y": 3}
        fd.mutate("remove", ["a"])
        assert fd.read_keys(["a"]) == {}
        # snapshot versions are monotone per front door
        v1 = fd.snapshot().version
        fd.mutate("add", ["c", 4])
        v2 = fd.snapshot().version
        assert v2 > v1
    finally:
        rep.stop()


def test_snapshot_read_does_not_flush_pending(transport):
    """The lock-free read serves the last COMMITTED generation;
    ``Replica.read`` keeps its flush-then-read strong-read semantics."""
    rep = _mk(transport, name="sv-strong")
    fd = frontdoor(rep)
    try:
        fd.mutate("add", ["k", 1])
        rep.mutate_async("add", ["pending", 9])  # queued, not flushed
        assert "pending" not in fd.read()
        assert rep.read() == {"k": 1, "pending": 9}  # strong read flushes
        # ... and the flush published a fresh generation for readers
        assert fd.read()["pending"] == 9
    finally:
        rep.stop()


def test_snapshot_reads_lock_free(transport):
    """THE structural claim: snapshot reads complete while the replica
    lock is HELD by another thread (a strong read would block)."""
    rep = _mk(transport, name="sv-lockfree")
    fd = frontdoor(rep)
    try:
        fd.mutate("add", ["k", "v"])
        rep._lock.acquire()
        try:
            got: list = []

            def reader():
                got.append(fd.read_keys(["k"]))
                got.append(fd.read())
                got.append(fd.scan("k"))
                # the strong read DOES block (it is the locked mode; the
                # RLock is reentrant, so this must run off-thread)
                try:
                    rep.read(timeout=0.05)
                    got.append("strong-read-did-not-block")
                except TimeoutError:
                    got.append("strong-read-blocked")

            t = threading.Thread(target=reader)
            t.start()
            t.join(timeout=10)
            assert not t.is_alive(), "snapshot read blocked on the replica lock"
            assert got == [
                {"k": "v"}, {"k": "v"}, {"k": "v"}, "strong-read-blocked",
            ]
        finally:
            rep._lock.release()
    finally:
        rep.stop()


def test_snapshot_pins_generation_across_gc(transport):
    """A pinned snapshot keeps resolving after later commits and a
    ``gc()`` (the payload dict is replaced, never pruned in place)."""
    rep = _mk(transport, name="sv-gc")
    fd = frontdoor(rep)
    try:
        fd.mutate("add", ["old", 1])
        snap = fd.snapshot()
        fd.mutate("remove", ["old"])
        fd.mutate("add", ["new", 2])
        rep.gc()
        # the pinned generation still reads its own world
        assert snap.read_keys(["old"]) == {"old": 1}
        assert "new" not in snap.read()
        # the live view moved on
        assert fd.read() == {"new": 2}
    finally:
        rep.stop()


def test_awset_snapshot_views(transport):
    from delta_crdt_ex_tpu.models.binned_map import AWSet

    rep = start_link(
        AWSet, threaded=False, transport=transport, name="sv-set",
        capacity=4096, tree_depth=8,
    )
    fd = frontdoor(rep)
    try:
        fd.mutate("add", ["x"])
        fd.mutate("add", ["y2"])
        assert fd.read() == {"x", "y2"}
        assert fd.read_keys(["x", "z"]) == {"x"}
        assert fd.scan("y") == {"y2"}
    finally:
        rep.stop()


# ----------------------------------------------------------------------
# no-torn-reads property: seeded concurrent readers vs mutators


def _torn_read_property(rep, fd, *, generations=30, keys=5, readers=2):
    """Writer commits generation i as ONE batch setting ``gk0..gk{keys}``
    all to i; concurrent snapshot readers assert every read is a
    whole committed generation and versions/values are monotone."""
    gkeys = [f"g{j}" for j in range(keys)]
    stop = threading.Event()
    errors: list = []
    seen_max: list = []

    def reader():
        last_version = -1
        last_gen = -1
        try:
            while not stop.is_set():
                snap = fd.snapshot()
                if snap.version < last_version:
                    raise AssertionError(
                        f"version regressed {last_version} -> {snap.version}"
                    )
                last_version = snap.version
                view = snap.read_keys(gkeys)
                if not view:
                    continue
                vals = set(view.values())
                if len(view) == keys and len(vals) != 1:
                    raise AssertionError(f"torn read: {view}")
                gen = max(vals)
                if gen < last_gen:
                    raise AssertionError(
                        f"generation regressed {last_gen} -> {gen}"
                    )
                last_gen = gen
            seen_max.append(last_gen)
        except BaseException as e:  # noqa: BLE001 — surfaced below
            errors.append(e)

    threads = [threading.Thread(target=reader) for _ in range(readers)]
    for t in threads:
        t.start()
    try:
        for i in range(generations):
            rep.mutate_batch("add", [[k, i] for k in gkeys])
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=30)
    assert not errors, errors
    assert fd.read_keys(gkeys) == {k: generations - 1 for k in gkeys}


@pytest.mark.parametrize("store", STORES)
def test_no_torn_reads_solo(transport, store):
    rep = _mk(transport, store, name=f"sv-torn-{store}", node_id=101)
    fd = frontdoor(rep)
    try:
        _torn_read_property(rep, fd)
    finally:
        rep.stop()


@pytest.mark.parametrize("store", STORES)
def test_no_torn_reads_fleet_member(store):
    """The same property on a FLEET MEMBER while the fleet event loop
    gossips remote entries into it (ingest concurrent with reads)."""
    fleet = start_fleet(
        2, threaded=True, store=store,
        names=[f"svf-{store}-0", f"svf-{store}-1"],
        capacity=4096, tree_depth=8, sync_interval=0.01, sync_timeout=600.0,
    )
    a, b = fleet.replicas
    a.set_neighbours([b])
    b.set_neighbours([a])
    fd = frontdoor(a)
    try:
        # remote traffic: b writes disjoint keys that gossip into a
        stop = threading.Event()

        def remote_writer():
            i = 0
            while not stop.is_set():
                b.mutate_batch("add", [[f"r{i}_{j}", j] for j in range(4)])
                i += 1
                time.sleep(0.005)

        t = threading.Thread(target=remote_writer)
        t.start()
        try:
            _torn_read_property(a, fd, generations=20)
        finally:
            stop.set()
            t.join(timeout=30)
    finally:
        fleet.stop()


# ----------------------------------------------------------------------
# write admission


def test_admission_coalesces_and_resolves_tickets(transport):
    rep = _mk(transport, name="sv-adm", capacity=65536)
    fd = frontdoor(rep)
    try:
        n_clients, per = 8, 40

        def client(i):
            for j in range(per):
                fd.mutate("add", [f"c{i}/{j}", j])

        threads = [
            threading.Thread(target=client, args=(i,)) for i in range(n_clients)
        ]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        st = fd.stats()
        assert st["admitted_ops"] == n_clients * per
        assert st["pending_ops"] == 0
        # folding happened: strictly fewer commits than ops
        assert st["commits"] < n_clients * per
        assert st["ops_per_commit"] > 1.0
        assert rep.read_keys([f"c{i}/0" for i in range(n_clients)]) == {
            f"c{i}/0": 0 for i in range(n_clients)
        }
        tk = fd.mutate_async("add", ["async", 1])
        tk.result(30)
        assert tk.done() and tk.error is None
        assert fd.read_keys(["async"]) == {"async": 1}
    finally:
        rep.stop()


def test_admission_validation_is_per_client(transport):
    rep = _mk(transport, name="sv-val")
    fd = frontdoor(rep)
    try:
        with pytest.raises(ValueError, match="unknown operation"):
            fd.mutate("bogus", ["k"])
        with pytest.raises(ValueError, match="argument"):
            fd.mutate("add", ["k"])  # AWLWWMap add is arity 2
        # a rejected op never poisons admitted neighbours
        fd.mutate("add", ["fine", 1])
        assert fd.read_keys(["fine"]) == {"fine": 1}
        assert fd.stats()["admitted_ops"] == 1
    finally:
        rep.stop()


@pytest.mark.parametrize("store", STORES)
def test_admission_parity_with_mutate_batch(tmp_path, transport, store):
    """ISSUE 14 small fix: the admission path and ``mutate_batch``
    share ONE grouped-commit implementation — identical op sequences
    produce bit-for-bit identical state and WAL bytes."""
    a = _mk(
        transport, store, name=f"sv-par-a-{store}", node_id=55,
        clock=LogicalClock(), wal_dir=str(tmp_path / "a"), fsync_mode="none",
    )
    fd = frontdoor(a, journal=True)
    n_clients, per = 6, 25

    def client(i):
        for j in range(per):
            fd.mutate("add", [f"c{i}/{j}", (i, j)])
            if j % 7 == 3:
                fd.mutate("remove", [f"c{i}/{j - 1}"])

    threads = [threading.Thread(target=client, args=(i,)) for i in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=60)
    fd.close()
    journal = fd.journal()
    assert journal and sum(len(g) for g in journal) > 0

    # the unloaded twin replays the committed groups through the SAME
    # grouped-commit entrance mutate_batch uses
    b = _mk(
        transport, store, name=f"sv-par-b-{store}", node_id=55,
        clock=LogicalClock(), wal_dir=str(tmp_path / "b"), fsync_mode="none",
    )
    for group in journal:
        b.apply_ops(group)
    _state_equal(a, b)
    assert a._seq == b._seq
    assert _wal_bytes(a) == _wal_bytes(b)
    a.stop()
    b.stop()


def test_mutate_batch_routes_through_apply_ops(tmp_path, transport):
    """``mutate_batch`` and a hand-built ``apply_ops`` sequence are the
    same entrance: bit-for-bit state + WAL bytes."""
    mk = lambda tag: _mk(
        transport, name=f"sv-mb-{tag}", node_id=9, clock=LogicalClock(),
        wal_dir=str(tmp_path / tag), fsync_mode="none",
    )
    a, b = mk("a"), mk("b")
    items = [[f"k{i}", i] for i in range(50)]
    a.mutate_batch("add", items)
    b.apply_ops([("add", it) for it in items])
    _state_equal(a, b)
    assert _wal_bytes(a) == _wal_bytes(b)
    a.stop()
    b.stop()


def test_apply_ops_mixed_kinds_in_order(transport):
    rep = _mk(transport, name="sv-mixed")
    rep.apply_ops([
        ("add", ["a", 1]),
        ("add", ["b", 2]),
        ("remove", ["a"]),
        ("add", ["c", 3]),
    ])
    assert rep.read() == {"b": 2, "c": 3}
    rep.apply_ops([("clear", []), ("add", ["d", 4])])
    assert rep.read() == {"d": 4}
    rep.stop()


# ----------------------------------------------------------------------
# backpressure / shedding


def test_overload_sheds_and_recovers(transport):
    rep = _mk(transport, name="sv-shed", capacity=65536)
    fd = frontdoor(rep, max_pending_ops=8, max_commit_ops=8,
                   shed_health_hold=0.2)
    try:
        # deterministic pressure: the admission worker blocks on the
        # replica lock, so the queue cannot drain while we hold it
        rep._lock.acquire()
        held = True
        try:
            shed = 0
            tickets = []
            for i in range(50):
                try:
                    tickets.append(fd.mutate_async("add", [f"x{i}", i]))
                except Overloaded as e:
                    assert e.reason == "admission_queue"
                    shed += 1
            assert shed > 0
            st = fd.stats()
            assert st["overloaded"] and st["overload_reason"] == "admission_queue"
            assert st["shed_by_reason"]["admission_queue"] == shed
            assert fd.health()["ok"] is False
            # reads still serve while writes shed (the decoupling claim)
            assert isinstance(fd.read(), dict)
            rep._lock.release()
            held = False
            for tk in tickets:
                tk.result(30)
        finally:
            if held:
                rep._lock.release()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not fd.health()["ok"]:
            time.sleep(0.02)
        assert fd.health()["ok"], fd.stats()
        # shed ops were genuinely NOT applied
        assert len(fd.read()) == 50 - shed
    finally:
        rep.stop()


def test_healthz_flips_on_overload(transport):
    from delta_crdt_ex_tpu.runtime.metrics import Observability

    plane = Observability()
    rep = _mk(transport, name="sv-hz", obs=plane)
    fd = frontdoor(rep, max_pending_ops=4, max_commit_ops=4,
                   shed_health_hold=0.2)
    try:
        ok, checks = plane.health()
        assert ok and checks["serve:sv-hz"]["ok"]
        rep._lock.acquire()
        try:
            for i in range(20):
                try:
                    fd.mutate_async("add", [f"x{i}", i])
                except Overloaded:
                    pass
            ok, checks = plane.health()
            assert not ok
            assert checks["serve:sv-hz"]["ok"] is False
            assert checks["serve:sv-hz"]["overloaded"] is True
        finally:
            rep._lock.release()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            ok, checks = plane.health()
            if ok:
                break
            time.sleep(0.02)
        assert ok, checks
    finally:
        rep.stop()
        plane.close()


# ----------------------------------------------------------------------
# lifecycle / fleet front door


def test_frontdoor_cached_and_closed_on_stop(transport):
    rep = _mk(transport, name="sv-life")
    fd = frontdoor(rep)
    assert frontdoor(rep) is fd
    with pytest.raises(ValueError, match="already exists"):
        frontdoor(rep, max_pending_ops=1)
    rep.stop()
    assert not fd._worker.is_alive()
    with pytest.raises(RuntimeError, match="closed"):
        fd.mutate("add", ["k", 1])


def test_fleet_frontdoor_routing_and_reads():
    fleet = start_fleet(
        3, threaded=True, names=["ffd0", "ffd1", "ffd2"],
        capacity=4096, tree_depth=8, sync_interval=0.02, sync_timeout=600.0,
    )
    for i, rep in enumerate(fleet.replicas):
        rep.set_neighbours(
            [r for j, r in enumerate(fleet.replicas) if j != i]
        )
    fd = fleet.frontdoor()
    try:
        assert fleet.frontdoor() is fd
        # member doors register through the replica accessor, so an
        # individually stopped member closes its own door too
        assert all(
            rep._frontdoor is m for rep, m in zip(fleet.replicas, fd.members)
        )
        with pytest.raises(ValueError, match="unknown operation"):
            fd.mutate("bogus", [])
        with pytest.raises(ValueError, match="argument"):
            fd.mutate("add", [])
        keys = [f"k{i}" for i in range(30)]
        for i, k in enumerate(keys):
            fd.mutate("add", [k, i])
        # read-your-writes per key (owner-routed, no gossip wait)
        assert fd.read_keys(keys) == {k: i for i, k in enumerate(keys)}
        # writes actually spread over members
        owners = {id(fd.member_for(k)) for k in keys}
        assert len(owners) > 1
        st = fd.stats()
        assert st["admitted_ops"] == len(keys)
        assert fd.health()["ok"]
        # clear broadcasts (observed-remove union across members)
        fd.mutate("clear", [])
        assert fd.read_keys(keys) == {}
    finally:
        fleet.stop()
    assert all(not m._worker.is_alive() for m in fd.members)


def test_serve_bench_harness_tiny():
    """ISSUE 14 CI satellite: the ``bench.py --serve`` harness at tiny
    scale (seconds) gating the loaded-vs-twin parity assert and the
    ``/healthz`` overload flip/recovery in tier-1 — the harness's
    asserts ARE the gates; this pins that they run and hold."""
    import sys
    from pathlib import Path

    repo_root = str(Path(__file__).resolve().parent.parent)
    if repo_root not in sys.path:
        sys.path.insert(0, repo_root)
    import bench

    res = bench._serve_harness(tiny=True)
    assert res["tiny"] is True
    # parity gate ran and held (bit-for-bit state + WAL vs the twin)
    assert res["parity"]["result"] == "bit_for_bit_state_and_wal"
    assert res["parity"]["groups"] > 0
    # the overload gate ran: sheds happened, /healthz flipped, recovered
    assert res["overload"]["shed_ops"] > 0
    assert res["overload"]["healthz_under_overload"] == 503
    assert res["overload"]["healthz_recovered"] == 200
    # the structural lock-free read proof ran
    assert res["lock_free_reads"]["reads_while_lock_held"] == 20
    # latency/throughput are reported (gated only in full mode)
    rates = res["open_loop"]["rates"]
    assert rates and all(
        e["read"]["n"] > 0 and e["write"]["n"] > 0 for e in rates.values()
    )
    assert res["admission"]["speedup"] > 0


def test_stale_snapshot_defensive_retry(transport):
    """A snapshot whose payload view cannot resolve raises
    StaleSnapshot; the front door retries on a fresher generation and
    serves (defensive path — unreachable via public commits)."""
    rep = _mk(transport, name="sv-stale")
    fd = frontdoor(rep)
    try:
        fd.mutate("add", ["k", "v"])
        snap = fd.snapshot()
        broken = type(snap)(
            snap.version, snap.store, snap.model, snap.num_buckets, {}
        )
        with pytest.raises(StaleSnapshot):
            broken.read_keys(["k"])
        with pytest.raises(StaleSnapshot):
            broken.read()
        # a fresher publication heals the race: the retry shell serves
        # from the next generation
        with fd._lock:
            fd._snap = broken
        rep.mutate("add", ["k2", "v2"])  # publishes a fresh generation
        assert fd.read_keys(["k"]) == {"k": "v"}
        # a poisoned CACHED snapshot (version pinned above the live
        # publication, empty payload view): the retry shell drops it
        # from the cache and the rebuild serves the live generation
        poisoned = type(snap)(
            snap.version + 1_000_000, snap.store, snap.model,
            snap.num_buckets, {},
        )
        with fd._lock:
            fd._snap = poisoned
        # "k" IS in the poisoned store but its payload view is empty →
        # StaleSnapshot on attempt 1 → cache dropped → attempt 2 serves
        assert fd.read_keys(["k"]) == {"k": "v"}
        st = fd.stats()
        assert st["read_retries"] >= 1
        assert st["strong_read_fallbacks"] == 0
    finally:
        rep.stop()


def test_snapshot_cache_tracks_gc_republication(transport):
    """``gc()`` republishes the pruned payload dict at the unchanged
    version; the front door's cache must rebuild on the new
    publication instead of pinning the pre-gc dict forever."""
    rep = _mk(transport, name="sv-gcpub")
    fd = frontdoor(rep)
    try:
        fd.mutate("add", ["k", "v"])
        before = fd.snapshot()
        rep.gc()
        after = fd.snapshot()
        assert after.version == before.version
        assert after._payloads is rep._serve_pub[3]
        assert after._payloads is not before._payloads
        assert after.read_keys(["k"]) == {"k": "v"}
    finally:
        rep.stop()
