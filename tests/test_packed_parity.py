"""Packed-layout merge parity: ``merge_slice_packed`` (the roofline's
single-vector-scatter A/B candidate, ``ops/packed.py``) must produce
bit-identical lattice state to the column-layout ``merge_slice`` on
every workload — inserts, interval kills, unknown writers, tier
overflow flags. Also pins ``pack``/``unpack`` as bitwise inverses.
"""

import numpy as np
import jax.numpy as jnp
import pytest

pytest.importorskip("hypothesis")  # collection must degrade gracefully without it
from hypothesis import given, settings
from hypothesis import strategies as hyp_st

from delta_crdt_ex_tpu.ops.binned import extract_rows, merge_slice
from delta_crdt_ex_tpu.ops.packed import merge_slice_packed, pack, unpack
from delta_crdt_ex_tpu.utils.synth import build_state, interval_delta_stream
from tests.kernel_harness import BinnedKernelMap
from tests.test_merge_parity import assert_states_equal


def roundtrip_columns(st):
    return unpack(pack(st))


def build_pair_from_ops(ops, pre_join, L=16, rcap=4):
    """Two kernel maps built from an explicit interleaved history
    (``ops`` = [(who, op, key, value), …]; ``pre_join`` makes the first
    observe the second, giving kills remote targets) — ONE constructor
    for both the seeded and the hypothesis parity suites."""
    a = BinnedKernelMap(gid=100, capacity=128, rcap=rcap, num_buckets=L)
    b = BinnedKernelMap(gid=200, capacity=128, rcap=rcap, num_buckets=L)
    for ts, (who, op, k, v) in enumerate(ops, start=1):
        m = a if who == "a" else b
        if op == "add":
            m.add(k, v, ts=ts)
        elif op == "remove":
            m.remove(k, ts=ts)
        else:
            m.clear(ts=ts)
    if pre_join:
        a.join_from(b)
    return a, b


def random_divergent_pair(rng, L=16, rcap=4):
    """Randomized history for the seeded trials (same rng consumption
    order as the original inline loops, so seeds reproduce)."""
    ops = []
    for _ in range(1, int(rng.integers(2, 25))):
        who = "a" if rng.random() < 0.5 else "b"
        k = int(rng.integers(0, 24))
        op = rng.random()
        if op < 0.7:
            ops.append((who, "add", k, int(rng.integers(0, 100))))
        elif op < 0.95:
            ops.append((who, "remove", k, 0))
        else:
            ops.append((who, "clear", 0, 0))
    return build_pair_from_ops(ops, rng.random() < 0.6, L=L, rcap=rcap)


def assert_variant_parity(r_ref, r, ctx):
    """Flags must always agree; state/counters must be bit-identical
    whenever the reference merge is valid (overflowed merges are
    discarded by the tier ladder, so their dead fields may differ)."""
    for fl in ("ok", "need_gid_grow", "need_kill_tier",
               "need_fill_compact", "need_ctx_gap", "need_ins_tier"):
        assert bool(getattr(r_ref, fl)) == bool(getattr(r, fl)), (ctx, fl)
    if bool(r_ref.ok):
        from delta_crdt_ex_tpu.ops.packed import PackedStore

        as_cols = lambda s: unpack(s) if isinstance(s, PackedStore) else s
        assert_bitwise_equal(as_cols(r.state), as_cols(r_ref.state), ctx)
        assert int(r.n_inserted) == int(r_ref.n_inserted), ctx
        assert int(r.n_killed) == int(r_ref.n_killed), ctx


def assert_bitwise_equal(s1, s2, ctx):
    import dataclasses

    for f in dataclasses.fields(type(s1)):
        a, b = np.asarray(getattr(s1, f.name)), np.asarray(getattr(s2, f.name))
        assert np.array_equal(a, b), (ctx, f.name)


def test_pack_unpack_roundtrip():
    rng = np.random.default_rng(3)
    keys = rng.integers(1, 1 << 63, size=500, dtype=np.uint64)
    st, _ = build_state(11, keys, num_buckets=32, bin_capacity=32)
    assert_bitwise_equal(roundtrip_columns(st), st, "roundtrip")


def test_packed_merge_parity_randomized():
    rng = np.random.default_rng(4)
    for trial in range(10):
        L = 16
        a, b = random_divergent_pair(rng, L=L)
        sl = extract_rows(b.state, jnp.arange(L, dtype=jnp.int32))
        for max_inserts in (None, 256):
            r1 = merge_slice(a.state, sl, kill_budget=L, max_inserts=max_inserts)
            r2 = merge_slice_packed(
                pack(a.state), sl, kill_budget=L, max_inserts=max_inserts
            )
            ctx = (trial, max_inserts)
            assert_variant_parity(r1, r2, ctx)
            # packed-vs-columns is bit-identical even on overflowed
            # merges (both use the same top_k fill handling) — pin the
            # stronger contract for this pair, plus semantic reads
            assert_bitwise_equal(unpack(r2.state), r1.state, ctx)
            assert_states_equal(unpack(r2.state), r1.state, ctx)


def test_packed_interval_stream_parity():
    rng = np.random.default_rng(5)
    L = 64
    keys = rng.integers(1, 1 << 63, size=2000, dtype=np.uint64)
    st_col, _ = build_state(11, keys, num_buckets=L, bin_capacity=64)
    st_pk = pack(st_col)
    slices, _ = interval_delta_stream(22, rng, 6, 64, L, bin_width=8)
    for i, sl in enumerate(slices):
        r1 = merge_slice(st_col, sl, kill_budget=L, max_inserts=256)
        r2 = merge_slice_packed(st_pk, sl, kill_budget=L, max_inserts=256)
        assert bool(r1.ok), i
        assert_variant_parity(r1, r2, i)
        st_col, st_pk = r1.state, r2.state


def test_packed_fanout_parity_with_growth():
    """The promoted fan-out path (``fanout_merge_into`` over a
    ``PackedStore`` stack) must walk the SAME tier-escalation ladder as
    the column stack — same retry count, same final tiers — and land
    bit-identical lattice state, on a workload that overflows the kill
    budget, the bin tier, and the gid table at once (the
    ``test_fanout_tier_overflow_converges_and_bounds_retries``
    scenario)."""
    import jax.numpy as jnp

    from delta_crdt_ex_tpu.ops.binned import extract_rows as _extract
    from delta_crdt_ex_tpu.parallel import (
        fanout_merge_into,
        pack_states,
        stack_states,
        unstack_states,
    )
    from tests.test_parallel import fresh_states

    n, L = 8, 16
    origin = BinnedKernelMap(gid=500, capacity=64, rcap=2, num_buckets=L)
    for k in range(32):
        origin.add(k, k, ts=k + 1)
    neighbours = fresh_states(n, capacity=64, rcap=2, num_buckets=L)
    for m in neighbours:
        m.join_from(origin)
    stacked = stack_states([m.state for m in neighbours])

    updater = BinnedKernelMap(gid=999, capacity=64, rcap=4, num_buckets=L)
    updater.join_from(origin)
    for k in range(32):
        updater.remove(k, ts=100 + k)
    for j in range(48):
        updater.add(32 + j, 7000 + j, ts=200 + j)
    sl = _extract(updater.state, jnp.arange(L, dtype=jnp.int32))

    col2, col_res, col_retries = fanout_merge_into(stacked, sl, kill_budget=2)
    assert bool(col_res.ok.all()) and col_retries >= 1
    for scomp in (False, True):  # both packed compaction modes walk the ladder
        pk2, pk_res, pk_retries = fanout_merge_into(
            pack_states(stacked), sl, kill_budget=2, scatter_compact=scomp
        )
        assert bool(pk_res.ok.all()), scomp
        assert col_retries == pk_retries, scomp
        assert pk2.bin_capacity == col2.bin_capacity >= 8
        assert pk2.replica_capacity == col2.replica_capacity >= 4
        assert_bitwise_equal(unpack(pk2), col2, ("fanout growth", scomp))
        for col_st, pk_st in zip(unstack_states(col2), unstack_states(unpack(pk2))):
            assert_states_equal(pk_st, col_st, ("per-neighbour", scomp))


def test_fused_aux_parity_randomized():
    """``merge_slice_packed_fused`` (one [L,R,3] min-scatter for
    amin/amax/ctx via the unsigned-complement identity + one [k,2]
    add-scatter for fill/leaf) must be bit-identical to the plain packed
    kernel on every VALID merge; on overflowed merges only the flags
    must agree (the state is discarded by the tier-retry ladder)."""
    from delta_crdt_ex_tpu.ops.packed import merge_slice_packed_fused

    rng = np.random.default_rng(8)
    for trial in range(10):
        L = 16
        a, b = random_divergent_pair(rng, L=L)
        sl = extract_rows(b.state, jnp.arange(L, dtype=jnp.int32))
        st_pk = pack(a.state)
        for max_inserts in (None, 256):
            r1 = merge_slice_packed(st_pk, sl, kill_budget=L, max_inserts=max_inserts)
            r2 = merge_slice_packed_fused(
                st_pk, sl, kill_budget=L, max_inserts=max_inserts
            )
            assert_variant_parity(r1, r2, (trial, max_inserts))


def test_fused_aux_interval_stream_parity():
    from delta_crdt_ex_tpu.ops.packed import merge_slice_packed_fused

    rng = np.random.default_rng(9)
    L = 64
    keys = rng.integers(1, 1 << 63, size=2000, dtype=np.uint64)
    st_col, _ = build_state(11, keys, num_buckets=L, bin_capacity=64)
    st_a = pack(st_col)
    st_b = st_a
    slices, _ = interval_delta_stream(23, rng, 6, 64, L, bin_width=8)
    for i, sl in enumerate(slices):
        r1 = merge_slice_packed(st_a, sl, kill_budget=L, max_inserts=256)
        r2 = merge_slice_packed_fused(st_b, sl, kill_budget=L, max_inserts=256)
        assert bool(r1.ok) and bool(r2.ok), i
        st_a, st_b = r1.state, r2.state
        assert_bitwise_equal(unpack(st_b), unpack(st_a), i)


def test_scomp_parity_randomized():
    """``merge_slice_packed_scomp`` (cumsum-rank + one packed compaction
    scatter instead of the per-neighbour top_k) must be bit-identical to
    the top_k packed kernel on every VALID merge; flags always agree."""
    from delta_crdt_ex_tpu.ops.packed import merge_slice_packed_scomp

    rng = np.random.default_rng(10)
    for trial in range(10):
        L = 16
        a, b = random_divergent_pair(rng, L=L)
        sl = extract_rows(b.state, jnp.arange(L, dtype=jnp.int32))
        st_pk = pack(a.state)
        for max_inserts in (8, 256):  # 8 exercises the overflow flag
            r1 = merge_slice_packed(st_pk, sl, kill_budget=L, max_inserts=max_inserts)
            r2 = merge_slice_packed_scomp(
                st_pk, sl, kill_budget=L, max_inserts=max_inserts
            )
            assert_variant_parity(r1, r2, (trial, max_inserts))


def test_scomp_interval_stream_parity():
    from delta_crdt_ex_tpu.ops.packed import merge_slice_packed_scomp

    rng = np.random.default_rng(11)
    L = 64
    keys = rng.integers(1, 1 << 63, size=2000, dtype=np.uint64)
    st_col, _ = build_state(11, keys, num_buckets=L, bin_capacity=64)
    st_a = pack(st_col)
    st_b = st_a
    slices, _ = interval_delta_stream(24, rng, 6, 64, L, bin_width=8)
    for i, sl in enumerate(slices):
        r1 = merge_slice_packed(st_a, sl, kill_budget=L, max_inserts=256)
        r2 = merge_slice_packed_scomp(st_b, sl, kill_budget=L, max_inserts=256)
        assert bool(r1.ok) and bool(r2.ok), i
        st_a, st_b = r1.state, r2.state
        assert_bitwise_equal(unpack(st_b), unpack(st_a), i)


pair_ops = hyp_st.lists(
    hyp_st.tuples(
        hyp_st.sampled_from(["a", "b"]),  # who mutates
        hyp_st.sampled_from(["add", "remove", "clear"]),
        hyp_st.integers(min_value=0, max_value=23),  # key
        hyp_st.integers(min_value=0, max_value=100),  # value
    ),
    max_size=30,
)


@settings(max_examples=25, deadline=None)
@given(pair_ops, hyp_st.booleans(), hyp_st.sampled_from([8, 64]))
def test_property_all_kernel_variants_agree(ops, pre_join, max_inserts):
    """Hypothesis twin of the seeded parity trials: for ANY interleaved
    history, the column kernel and every packed variant (plain, fused,
    scomp) agree on flags, and bit-identically on state whenever the
    merge is valid."""
    from delta_crdt_ex_tpu.ops.packed import (
        merge_slice_packed_fused,
        merge_slice_packed_scomp,
    )

    L = 16
    a, b = build_pair_from_ops(ops, pre_join, L=L)
    sl = extract_rows(b.state, jnp.arange(L, dtype=jnp.int32))
    r_col = merge_slice(a.state, sl, kill_budget=L, max_inserts=max_inserts)
    st_pk = pack(a.state)
    for name, fn in (
        ("packed", merge_slice_packed),
        ("fused", merge_slice_packed_fused),
        ("scomp", merge_slice_packed_scomp),
    ):
        r = fn(st_pk, sl, kill_budget=L, max_inserts=max_inserts)
        assert_variant_parity(r_col, r, name)


def test_packed_grow_and_compact_roundtrip():
    rng = np.random.default_rng(7)
    keys = rng.integers(1, 1 << 63, size=500, dtype=np.uint64)
    st, _ = build_state(11, keys, num_buckets=32, bin_capacity=32)
    grown = pack(st).grow(bin_capacity=64, replica_capacity=8)
    assert grown.bin_capacity == 64 and grown.replica_capacity == 8
    assert_bitwise_equal(
        unpack(grown), st.grow(bin_capacity=64, replica_capacity=8), "grow"
    )
    from delta_crdt_ex_tpu.ops.binned import compact_rows
    from delta_crdt_ex_tpu.ops.packed import compact_rows_packed

    assert_bitwise_equal(
        unpack(compact_rows_packed(pack(st))), compact_rows(st), "compact"
    )


def test_packed_flags_parity_on_overflow():
    # an insert tier too small must flag identically on both layouts
    rng = np.random.default_rng(6)
    L = 64
    keys = rng.integers(1, 1 << 63, size=100, dtype=np.uint64)
    st_col, _ = build_state(11, keys, num_buckets=L, bin_capacity=32)
    slices, _ = interval_delta_stream(22, rng, 1, 64, L, bin_width=8)
    sl = slices[0]
    r1 = merge_slice(st_col, sl, kill_budget=L, max_inserts=8)
    r2 = merge_slice_packed(pack(st_col), sl, kill_budget=L, max_inserts=8)
    assert bool(r1.need_ins_tier) and bool(r2.need_ins_tier)
    assert bool(r1.ok) == bool(r2.ok) == False  # noqa: E712


def test_scomp_parity_shuffled_rows():
    """Unsorted slice rows through the scomp path, ``rows_sorted`` left
    at its safe False default: the cumsum compaction preserves grid
    order, so with shuffled rows the compacted flat indices are NOT
    ascending — the hint gate (ADVICE r4: a false sorted/unique hint is
    XLA UB) must keep the scatter correct. Result must stay
    bit-identical to the top_k packed kernel AND the column kernel on
    the same shuffled slice."""
    from delta_crdt_ex_tpu.ops.packed import merge_slice_packed_scomp

    rng = np.random.default_rng(12)
    for trial in range(8):
        L = 16
        a, b = random_divergent_pair(rng, L=L)
        rows = jnp.asarray(rng.permutation(L).astype(np.int32))
        sl = extract_rows(b.state, rows)
        st_pk = pack(a.state)
        for max_inserts in (8, 256):  # 8 exercises the overflow flag
            r1 = merge_slice_packed(
                st_pk, sl, kill_budget=L, max_inserts=max_inserts
            )
            r2 = merge_slice_packed_scomp(
                st_pk, sl, kill_budget=L, max_inserts=max_inserts
            )
            assert_variant_parity(r1, r2, (trial, max_inserts))
        # the loop's last r2 is the 256-case result — compare it against
        # the column kernel too (same slice, third implementation)
        r_col = merge_slice(a.state, sl, kill_budget=L, max_inserts=256)
        assert_variant_parity(r_col, r2, ("col", trial))


def test_scomp_parity_sorted_rows_vouched():
    """``rows_sorted=True`` — the hint fast path ``entry()`` and the
    bench run in production — must stay bit-identical to both the
    unvouched scomp call and the top_k kernel on ascending-row slices.
    This is the only test exercising the vouched hints: if a future
    change breaks the ascending/unique compacted-index invariant (e.g.
    reordering the scomp branch's pos computation), THIS fails before
    entry() scatters with false XLA hints on hardware."""
    from delta_crdt_ex_tpu.ops.packed import merge_slice_packed_scomp

    rng = np.random.default_rng(13)
    for trial in range(8):
        L = 16
        a, b = random_divergent_pair(rng, L=L)
        sl = extract_rows(b.state, jnp.arange(L, dtype=jnp.int32))
        st_pk = pack(a.state)
        for max_inserts in (8, 256):
            r_ref = merge_slice_packed(
                st_pk, sl, kill_budget=L, max_inserts=max_inserts
            )
            r_v = merge_slice_packed_scomp(
                st_pk, sl, kill_budget=L, max_inserts=max_inserts,
                rows_sorted=True,
            )
            assert_variant_parity(r_ref, r_v, (trial, max_inserts, "vouched"))
            r_unv = merge_slice_packed_scomp(
                st_pk, sl, kill_budget=L, max_inserts=max_inserts
            )
            assert_variant_parity(r_unv, r_v, (trial, max_inserts, "unvouched"))
