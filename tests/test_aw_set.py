"""AWSet: the presence-only δ-CRDT over the shared kernel table
(restores the set type earlier versions of the reference family shipped;
plugs into the ``crdt_module`` seam, ``delta_crdt.ex:56``)."""

from delta_crdt_ex_tpu import AWSet
from delta_crdt_ex_tpu.api import mutate, read, set_neighbours, start_link
from tests.conftest import converge


def mk(transport, clock, **opts):
    opts.setdefault("capacity", 64)
    opts.setdefault("tree_depth", 5)
    return start_link(AWSet, threaded=False, transport=transport, clock=clock, **opts)


def test_two_replica_set_convergence(transport, shared_clock):
    a = mk(transport, shared_clock)
    b = mk(transport, shared_clock)
    set_neighbours(a, [b])
    set_neighbours(b, [a])
    mutate(a, "add", ["x"])
    mutate(b, "add", [("tuple", 1)])
    converge(transport, [a, b])
    assert read(a) == read(b) == {"x", ("tuple", 1)}
    mutate(a, "remove", ["x"])
    converge(transport, [a, b])
    assert read(b) == {("tuple", 1)}


def test_add_wins_on_concurrent_add_remove(transport, shared_clock):
    a = mk(transport, shared_clock)
    b = mk(transport, shared_clock)
    set_neighbours(a, [b])
    set_neighbours(b, [a])
    mutate(a, "add", ["e"])
    converge(transport, [a, b])
    # concurrent: b removes (observing a's dot), a re-adds with a fresh dot
    mutate(b, "remove", ["e"])
    mutate(a, "add", ["e"])
    converge(transport, [a, b])
    assert read(a) == read(b) == {"e"}  # the unobserved add survives


def test_clear_and_diffs(transport, shared_clock):
    seen = []
    a = mk(transport, shared_clock, on_diffs=seen.append)
    mutate(a, "add", ["p"])
    assert seen == [[("add", "p", True)]]
    mutate(a, "clear", [])
    assert read(a) == set()
    assert seen[-1] == [("remove", "p")]


def test_partial_read_keys(transport, shared_clock):
    a = mk(transport, shared_clock)
    for e in range(10):
        a.mutate_async("add", [e])
    a.flush()
    assert a.read_keys([3, 7, 99]) == {3, 7}


def test_arity_validation(transport, shared_clock):
    a = mk(transport, shared_clock)
    try:
        mutate(a, "add", ["k", "v"])
        raise AssertionError("2-arg add must be rejected for AWSet")
    except ValueError:
        pass


def test_set_scripts_match_set_oracle(transport, shared_clock):
    """Random fully-synced scripts vs a python set (the oracle pattern of
    ``aw_lww_map_property_test.exs`` at the set's semantics)."""
    import numpy as np

    rng = np.random.default_rng(5)
    reps = [mk(transport, shared_clock) for _ in range(3)]
    for r in reps:
        r.set_neighbours([x for x in reps if x is not r])
    model: set = set()
    for step in range(60):
        who = reps[int(rng.integers(0, 3))]
        elem = int(rng.integers(0, 12))
        roll = rng.random()
        if roll < 0.6:
            who.mutate("add", [elem])
            model.add(elem)
        elif roll < 0.9:
            who.mutate("remove", [elem])
            model.discard(elem)
        else:
            who.mutate("clear", [])
            model.clear()
        converge(transport, reps)
        for i, r in enumerate(reps):
            assert r.read() == model, (step, i)


def test_set_crash_rehydrate(transport, shared_clock):
    """Crash (no terminate sync) + rehydrate keeps membership AND node-id
    continuity for the set model (``causal_crdt_test.exs:87-102``)."""
    from delta_crdt_ex_tpu.runtime.storage import MemoryStorage

    storage = MemoryStorage()
    a = mk(transport, shared_clock, name="awset-st", storage_module=storage)
    for e in ("x", "y", "z"):
        mutate(a, "add", [e])
    mutate(a, "remove", ["y"])
    node_id = a.node_id
    transport.unregister(a.addr)  # crash
    b = mk(transport, shared_clock, name="awset-st", storage_module=storage)
    assert read(b) == {"x", "z"}
    assert b.node_id == node_id  # dot-counter continuity
