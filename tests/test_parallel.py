"""Parallel-layer tests on the 8-virtual-device CPU mesh.

Covers the two TPU-native fan-out paths (SURVEY §2.2): the vmapped
neighbour batch (one call merges a slice into all neighbours) and the
shard_map ring gossip over a Mesh (one replica per device, state moved
by ppermute).
"""

import jax
import jax.numpy as jnp
import numpy as np

from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.models.binned_map import BinnedAWLWWMap, group_batch
from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_PAD
from delta_crdt_ex_tpu.parallel import (
    fanout_merge,
    gossip_delta_step,
    gossip_train_step,
    make_mesh,
    place_states,
    ring_gossip_round,
    stack_states,
    unstack_states,
)
from tests.kernel_harness import BinnedKernelMap, read_binned_state as _read


def fresh_states(n, capacity=64, rcap=8, num_buckets=64):
    return [
        BinnedKernelMap(gid=100 + i, capacity=capacity, rcap=rcap, num_buckets=num_buckets)
        for i in range(n)
    ]


def test_fanout_merge_matches_sequential():
    """One vmapped call == N sequential merges."""
    maps = fresh_states(4)
    for i, m in enumerate(maps):
        m.add(10 + i, i, ts=i + 1)
    delta_map = BinnedKernelMap(gid=999)
    delta_map.add(7, 77, ts=100)
    all_rows = jnp.arange(delta_map.state.num_buckets, dtype=jnp.int32)
    sl = BinnedAWLWWMap.extract_rows(delta_map.state, all_rows)

    stacked = stack_states([m.state for m in maps])
    res = fanout_merge(stacked, sl)
    assert bool(jnp.all(res.ok))
    outs = unstack_states(res.state)

    for i, m in enumerate(maps):
        m.join_from(delta_map)
        got = _read(outs[i])
        assert got == m.read()
        assert got[7] == 77


def test_ring_gossip_converges_all_replicas():
    n = 4
    maps = fresh_states(n)
    for i, m in enumerate(maps):
        m.add(10 + i, i, ts=i + 1)
    stacked = stack_states([m.state for m in maps])
    for _ in range(n - 1):
        res = ring_gossip_round(stacked)
        assert bool(jnp.all(res.ok))
        stacked = res.state
    want = {10 + i: i for i in range(n)}
    for st in unstack_states(stacked):
        assert _read(st) == want


def grouped_mutations(n, num_buckets, ops_per_replica):
    """Stack bucket-grouped mutation batches for gossip_train_step:
    ``ops_per_replica[i]`` is a list of (op, key, valh, ts)."""
    groups = []
    u = m = 1
    for ops in ops_per_replica:
        op = np.array([o[0] for o in ops], np.int32)
        key = np.array([o[1] for o in ops], np.uint64)
        valh = np.array([o[2] for o in ops], np.uint32)
        ts = np.array([o[3] for o in ops], np.int64)
        g = group_batch(num_buckets, op, key, valh, ts)
        groups.append(g)
        u = max(u, g.rows.shape[0])
        m = max(m, g.op.shape[1])
    rows = np.full((n, u), -1, np.int32)
    op = np.full((n, u, m), OP_PAD, np.int32)
    key = np.zeros((n, u, m), np.uint64)
    valh = np.zeros((n, u, m), np.uint32)
    ts = np.zeros((n, u, m), np.int64)
    for i, g in enumerate(groups):
        gu, gm = g.op.shape
        rows[i, :gu] = g.rows
        op[i, :gu, :gm] = g.op
        key[i, :gu, :gm] = g.key
        valh[i, :gu, :gm] = g.valh
        ts[i, :gu, :gm] = g.ts
    return tuple(map(jnp.asarray, (rows, op, key, valh, ts)))


def test_mesh_gossip_train_step_converges():
    """shard_map SPMD step over the 8-device CPU mesh: per-device mutation
    batch + ppermute ring merge; N-1 steps converge all replicas."""
    n = len(jax.devices())
    assert n == 8, "conftest must provide 8 virtual cpu devices"
    mesh = make_mesh()
    maps = fresh_states(n, capacity=128)
    stacked = place_states([m.state for m in maps], mesh)
    self_slot = jnp.zeros(n, jnp.int32)
    num_buckets = maps[0].state.num_buckets

    batches = grouped_mutations(
        n, num_buckets, [[(OP_ADD, 1000 + i, i, i + 1)] for i in range(n)]
    )
    stacked, roots, oks = gossip_train_step(mesh, stacked, self_slot, *batches)
    assert bool(oks.all())
    # after step 1, keep gossiping with empty batches
    empty = grouped_mutations(n, num_buckets, [[] for _ in range(n)])
    for _ in range(n - 1):
        stacked, roots, oks = gossip_train_step(mesh, stacked, self_slot, *empty)
        assert bool(oks.all())

    roots = np.asarray(roots)
    assert (roots == roots[0]).all(), "digest roots must agree after full ring"
    want = {1000 + i: i for i in range(n)}
    for st in unstack_states(stacked):
        assert _read(st) == want


def test_mesh_gossip_delta_step_converges():
    """Bounded-divergence SPMD step: digest exchange -> frontier request ->
    slice ship. Converges the ring and reports the true divergence count."""
    n = len(jax.devices())
    mesh = make_mesh()
    maps = fresh_states(n, capacity=128)
    stacked = place_states([m.state for m in maps], mesh)
    self_slot = jnp.zeros(n, jnp.int32)
    num_buckets = maps[0].state.num_buckets

    batches = grouped_mutations(
        n, num_buckets, [[(OP_ADD, 1000 + i, i, i + 1)] for i in range(n)]
    )
    stacked, roots, oks, n_diff, _fl = gossip_delta_step(
        mesh, stacked, self_slot, *batches
    )
    assert bool(oks.all())
    empty = grouped_mutations(n, num_buckets, [[] for _ in range(n)])
    for _ in range(2 * n):
        stacked, roots, oks, n_diff, _fl = gossip_delta_step(
            mesh, stacked, self_slot, *empty
        )
        assert bool(oks.all())

    roots = np.asarray(roots)
    assert (roots == roots[0]).all(), "digest roots must agree after ring heals"
    assert int(np.asarray(n_diff).max()) == 0, "no divergence left"
    want = {1000 + i: i for i in range(n)}
    for st in unstack_states(stacked):
        assert _read(st) == want


def test_mesh_gossip_delta_step_frontier_truncation_heals():
    """Divergence wider than the frontier heals over multiple steps: with
    frontier=2 a replica holding 5 distinct-bucket keys still propagates
    them all around the ring, 2 buckets per edge per step (the
    max_sync_size analog, causal_crdt.ex:206-214)."""
    n = len(jax.devices())
    mesh = make_mesh()
    maps = fresh_states(n, capacity=128)
    # distinct buckets: keys 0..4 land in buckets 0..4 (key & (L-1))
    seed_keys = [3, 7, 11, 19, 23]
    for j, k in enumerate(seed_keys):
        maps[0].add(k, 100 + j, ts=j + 1)
    stacked = place_states([m.state for m in maps], mesh)
    self_slot = jnp.zeros(n, jnp.int32)
    num_buckets = maps[0].state.num_buckets
    empty = grouped_mutations(n, num_buckets, [[] for _ in range(n)])

    diffs_seen = []
    for _ in range(3 * (n + len(seed_keys))):
        stacked, roots, oks, n_diff, _fl = gossip_delta_step(
            mesh, stacked, self_slot, *empty, frontier=2
        )
        assert bool(oks.all())
        diffs_seen.append(int(np.asarray(n_diff).max()))
    assert max(diffs_seen[:1]) >= 3, "initial divergence exceeds the frontier"
    assert diffs_seen[-1] == 0
    want = {k: 100 + j for j, k in enumerate(seed_keys)}
    for st in unstack_states(stacked):
        assert _read(st) == want


def test_fanout_tier_overflow_converges_and_bounds_retries():
    """VERDICT r1 #10: a 64-neighbour fanout merge that overflows the
    kill budget AND the bin tier AND the gid table; the host retry loop
    (fanout_merge_into) must converge every neighbour, paying a bounded
    number of re-tiering recompiles (worst case: one compact +
    log4(U/kb0) kill-tier raises + log2 bin growths + log2 gid growths)."""
    import time as _time

    from delta_crdt_ex_tpu.ops.binned import extract_rows as _extract
    from delta_crdt_ex_tpu.parallel import fanout_merge_into

    n = 64
    L = 16
    origin = BinnedKernelMap(gid=500, capacity=64, rcap=2, num_buckets=L)
    for k in range(32):  # 2 entries per bucket -> fill = 2 of bin_cap 4
        origin.add(k, k, ts=k + 1)

    neighbours = fresh_states(n, capacity=64, rcap=2, num_buckets=L)
    for m in neighbours:
        m.join_from(origin)
    stacked = stack_states([m.state for m in neighbours])
    assert stacked.bin_capacity == 4 and stacked.replica_capacity == 2

    # the updater (an unseen writer gid) observes origin's dots, removes
    # every key (kills in all 16 buckets > kill_budget) and adds 3 fresh
    # keys per bucket (fill 2 + 3 > bin_cap 4)
    updater = BinnedKernelMap(gid=999, capacity=64, rcap=4, num_buckets=L)
    updater.join_from(origin)
    for k in range(32):
        updater.remove(k, ts=100 + k)
    for j in range(48):
        updater.add(32 + j, 7000 + j, ts=200 + j)

    sl = _extract(updater.state, jnp.arange(L, dtype=jnp.int32))
    t0 = _time.perf_counter()
    stacked2, res, retries = fanout_merge_into(stacked, sl, kill_budget=2)
    dt = _time.perf_counter() - t0
    assert bool(res.ok.all())
    assert 1 <= retries <= 4, f"retry bound violated: {retries}"
    # tiers actually grew: bin 4 -> >=8, gid table 2 -> >=3 slots
    assert stacked2.bin_capacity >= 8
    assert stacked2.replica_capacity >= 4

    want = updater.read()
    assert len(want) == 48 and want[32] == 7000
    for st in unstack_states(stacked2):
        assert _read(st) == want
    print(f"fanout overflow: {retries} retiering recompiles in {dt:.1f}s")


def test_gossip_delta_drive_recovers_from_tier_overflow():
    """VERDICT r1 weak #2: growth cannot happen inside the SPMD program —
    the host drive must detect a failed step, grow the offending tier on
    the PRE-step states, and replay without losing the step's mutations."""
    from delta_crdt_ex_tpu.parallel import gossip_delta_drive

    n = len(jax.devices())
    mesh = make_mesh()
    # bin_cap 4, 16 buckets: replica 0's batch adds 6 same-bucket keys ->
    # row_apply overflows inside the step
    maps = fresh_states(n, capacity=64, num_buckets=16)
    stacked = place_states([m.state for m in maps], mesh)
    self_slot = jnp.zeros(n, jnp.int32)
    grows = []

    same_bucket = [(OP_ADD, 16 * j + 5, 50 + j, j + 1) for j in range(6)]
    batches = grouped_mutations(n, 16, [same_bucket] + [[] for _ in range(n - 1)])
    stacked, roots, n_diff, retiers = gossip_delta_drive(
        mesh, stacked, self_slot, *batches, on_grow=lambda s: grows.append(s.bin_capacity)
    )
    assert retiers >= 1 and grows, "overflow must force at least one retier"
    assert stacked.bin_capacity >= 8

    empty = grouped_mutations(n, 16, [[] for _ in range(n)])
    for _ in range(n):
        stacked, roots, n_diff, r2 = gossip_delta_drive(
            mesh, stacked, self_slot, *empty
        )
    want = {16 * j + 5: 50 + j for j in range(6)}
    for st in unstack_states(stacked):
        assert _read(st) == want


def test_two_pod_bridge_converges():
    """Two-tier topology (SURVEY §5.8): two 4-device meshes model two
    ICI pods; the inter-pod (DCN) leg is a host-mediated row-slice
    exchange — the same extract_rows payload the TCP transport pickles
    across processes (tests/test_multiprocess.py). Intra-pod divergence
    heals by ring gossip; one bridged slice per direction converges the
    pods; a final ring spreads nothing new (n_diff == 0)."""
    from delta_crdt_ex_tpu.parallel import fanout_merge_into, gossip_delta_drive

    devs = jax.devices()
    assert len(devs) == 8
    L = 16
    pods = []
    for pod_idx, dev_half in enumerate((devs[:4], devs[4:])):
        mesh = make_mesh(dev_half)
        n = len(dev_half)
        # disjoint writer gids per pod: the pods model distinct processes,
        # and a shared (gid, ctr) dot identity across pods would let one
        # pod's context cover (and kill) the other's unrelated entries
        maps = [
            BinnedKernelMap(gid=500 * (pod_idx + 1) + i, capacity=64, num_buckets=L)
            for i in range(n)
        ]
        for i, m in enumerate(maps):
            m.add(100 * pod_idx + i, 1000 + 10 * pod_idx + i, ts=1 + 8 * pod_idx + i)
        stacked = place_states([m.state for m in maps], mesh)
        pods.append((mesh, stacked, jnp.zeros(n, jnp.int32)))

    empty = grouped_mutations(4, L, [[] for _ in range(4)])

    def heal(pod):
        mesh, stacked, slots = pod
        for _ in range(4):
            stacked, roots, n_diff, _r = gossip_delta_drive(
                mesh, stacked, slots, *empty
            )
        return (mesh, stacked, slots), int(np.asarray(n_diff).max())

    pods[0], d0 = heal(pods[0])
    pods[1], d1 = heal(pods[1])
    assert d0 == 0 and d1 == 0

    # DCN leg: full-row slice of one replica per pod, merged into every
    # replica of the other pod in one vmapped call
    all_rows = jnp.arange(L, dtype=jnp.int32)
    from delta_crdt_ex_tpu.ops.binned import extract_rows as _extract

    # device_get = the host hop: a real deployment pickles these numpy
    # arrays over TCP (DCN); device arrays cannot cross mesh boundaries
    to_host = lambda sl: jax.tree_util.tree_map(lambda x: np.asarray(x), sl)
    sl_a = to_host(_extract(unstack_states(pods[0][1])[0], all_rows))
    sl_b = to_host(_extract(unstack_states(pods[1][1])[0], all_rows))
    mesh_a, stacked_a, slots_a = pods[0]
    mesh_b, stacked_b, slots_b = pods[1]
    stacked_a, _res, _r = fanout_merge_into(stacked_a, sl_b)
    stacked_b, _res, _r = fanout_merge_into(stacked_b, sl_a)
    pods = [(mesh_a, stacked_a, slots_a), (mesh_b, stacked_b, slots_b)]

    pods[0], d0 = heal(pods[0])
    pods[1], d1 = heal(pods[1])
    assert d0 == 0 and d1 == 0

    want = {100 * p + i: 1000 + 10 * p + i for p in (0, 1) for i in range(4)}
    for _mesh, stacked, _slots in pods:
        for st in unstack_states(stacked):
            assert _read(st) == want


def test_gossip_delta_step_randomized_oracle():
    """Randomized multi-step convergence of the bounded-divergence SPMD
    path against a per-replica sequential oracle: random per-replica
    writes each step (distinct key spaces so LWW ties never depend on
    replica order), interleaved with delta-gossip; after healing, every
    replica must read the union of all writes. Tier overflow mid-run is
    expected (bins fill up as keys spread) — the drive grows and replays."""
    from delta_crdt_ex_tpu.parallel import gossip_delta_drive

    n = len(jax.devices())
    mesh = make_mesh()
    rng = np.random.default_rng(7)
    L = 64
    maps = fresh_states(n, capacity=256, num_buckets=L)
    stacked = place_states([m.state for m in maps], mesh)
    self_slot = jnp.zeros(n, jnp.int32)

    expected = {}
    ts = 1
    for step in range(5):
        ops_per_replica = []
        for i in range(n):
            ops = []
            for _ in range(int(rng.integers(0, 4))):
                key = int(i * 100_000 + rng.integers(0, 40))
                val = int(rng.integers(0, 1 << 30))
                ops.append((OP_ADD, key, val, ts))
                expected[key] = (ts, val)
                ts += 1
            ops_per_replica.append(ops)
        batches = grouped_mutations(n, L, ops_per_replica)
        stacked, roots, n_diff, _r = gossip_delta_drive(
            mesh, stacked, self_slot, *batches, frontier=16
        )

    empty = grouped_mutations(n, L, [[] for _ in range(n)])
    for _ in range(3 * n):
        stacked, roots, n_diff, _r = gossip_delta_drive(
            mesh, stacked, self_slot, *empty, frontier=16
        )
        if int(np.asarray(n_diff).max()) == 0:
            break
    assert int(np.asarray(n_diff).max()) == 0

    want = {k: v for k, (_ts, v) in expected.items()}
    roots = np.asarray(roots)
    assert (roots == roots[0]).all()
    for st in unstack_states(stacked):
        assert _read(st) == want


def test_mesh_snapshot_restore_roundtrip():
    """SPMD checkpoint/resume (SURVEY §5.4): snapshot a converged mesh,
    restore onto a fresh mesh, and gossip continues from where it left."""
    import pickle

    from delta_crdt_ex_tpu.parallel import gossip_delta_drive
    from delta_crdt_ex_tpu.parallel.mesh_gossip import restore_mesh, snapshot_mesh

    n = len(jax.devices())
    mesh = make_mesh()
    maps = fresh_states(n, capacity=128)
    for i, m in enumerate(maps):
        m.add(10 + i, i, ts=i + 1)
    stacked = place_states([m.state for m in maps], mesh)
    self_slot = jnp.zeros(n, jnp.int32)
    empty = grouped_mutations(n, maps[0].state.num_buckets, [[] for _ in range(n)])
    for _ in range(n):
        stacked, roots, n_diff, _r = gossip_delta_drive(mesh, stacked, self_slot, *empty)

    blob = pickle.dumps(snapshot_mesh(stacked))  # survives process loss
    restored = restore_mesh(pickle.loads(blob), make_mesh())
    want = {10 + i: i for i in range(n)}
    for st in unstack_states(restored):
        assert _read(st) == want

    # gossip continues post-restore: new write propagates
    batches = grouped_mutations(
        n, maps[0].state.num_buckets, [[(OP_ADD, 999, 7, 100)]] + [[] for _ in range(n - 1)]
    )
    stacked2, roots, n_diff, _r = gossip_delta_drive(mesh, restored, self_slot, *batches)
    for _ in range(n):
        stacked2, roots, n_diff, _r = gossip_delta_drive(mesh, stacked2, self_slot, *empty)
    want[999] = 7
    for st in unstack_states(stacked2):
        assert _read(st) == want

    # layout guard: a foreign-layout snapshot is rejected loudly
    import pytest

    bad = snapshot_mesh(stacked)
    bad["layout"] = "flat-v0"
    with pytest.raises(ValueError, match="engine layout"):
        restore_mesh(bad, make_mesh())
