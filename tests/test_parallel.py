"""Parallel-layer tests on the 8-virtual-device CPU mesh.

Covers the two TPU-native fan-out paths (SURVEY §2.2): the vmapped
neighbour batch (one call joins all neighbours) and the shard_map ring
gossip over a Mesh (one replica per device, state moved by ppermute).
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from delta_crdt_ex_tpu.models.state import DotStore
from delta_crdt_ex_tpu.ops.apply import OP_ADD, OP_PAD
from delta_crdt_ex_tpu.parallel import (
    fanout_join,
    gossip_train_step,
    make_mesh,
    place_states,
    ring_gossip_round,
    stack_states,
    unstack_states,
)
from tests.kernel_harness import KernelMap


def fresh_states(n, capacity=64, rcap=8, num_buckets=64):
    maps = []
    for i in range(n):
        m = KernelMap(gid=100 + i, capacity=capacity, rcap=rcap, num_buckets=num_buckets)
        maps.append(m)
    return maps


def test_fanout_join_matches_sequential():
    """One vmapped call == N sequential joins."""
    maps = fresh_states(4)
    for i, m in enumerate(maps):
        m.add(10 + i, i, ts=i + 1)
    delta_map = KernelMap(gid=999)
    delta_map.add(7, 77, ts=100)

    stacked = stack_states([m.state for m in maps])
    res = fanout_join(stacked, delta_map.state, None)
    assert bool(jnp.all(res.ok))
    outs = unstack_states(res.state)

    for i, m in enumerate(maps):
        m.join_from(delta_map)
        got = _read(outs[i])
        assert got == m.read()
        assert got[7] == 77


def _read(state: DotStore):
    from delta_crdt_ex_tpu.models.aw_lww_map import AWLWWMap

    w = AWLWWMap.winner_slice(state, None, out_size=state.capacity)
    count = int(w.count)
    keys = np.asarray(w.key)[:count]
    vals = np.asarray(w.valh)[:count]
    return {int(keys[i]): int(vals[i]) for i in range(count)}


def test_ring_gossip_converges_all_replicas():
    n = 4
    maps = fresh_states(n)
    for i, m in enumerate(maps):
        m.add(10 + i, i, ts=i + 1)
    stacked = stack_states([m.state for m in maps])
    for _ in range(n - 1):
        res = ring_gossip_round(stacked)
        assert bool(jnp.all(res.ok))
        stacked = res.state
    want = {10 + i: i for i in range(n)}
    for st in unstack_states(stacked):
        assert _read(st) == want


def test_mesh_gossip_train_step_converges():
    """shard_map SPMD step over the 8-device CPU mesh: per-device mutation
    batch + ppermute ring join; N-1 steps converge all replicas."""
    n = len(jax.devices())
    assert n == 8, "conftest must provide 8 virtual cpu devices"
    mesh = make_mesh()
    maps = fresh_states(n, capacity=128)
    stacked = place_states([m.state for m in maps], mesh)
    self_slot = jnp.zeros(n, jnp.int32)

    k = 8
    op = np.full((n, k), OP_PAD, np.int32)
    key = np.zeros((n, k), np.uint64)
    valh = np.zeros((n, k), np.uint32)
    ts = np.zeros((n, k), np.int64)
    for i in range(n):
        op[i, 0] = OP_ADD
        key[i, 0] = 1000 + i
        valh[i, 0] = i
        ts[i, 0] = i + 1

    args = tuple(map(jnp.asarray, (op, key, valh, ts)))
    stacked, roots = gossip_train_step(mesh, stacked, self_slot, *args, depth=6)
    # after step 1, keep gossiping with empty batches
    empty = tuple(
        map(jnp.asarray, (np.full((n, k), OP_PAD, np.int32), np.zeros((n, k), np.uint64),
                          np.zeros((n, k), np.uint32), np.zeros((n, k), np.int64)))
    )
    for _ in range(n - 1):
        stacked, roots = gossip_train_step(mesh, stacked, self_slot, *empty, depth=6)

    roots = np.asarray(roots)
    assert (roots == roots[0]).all(), "digest roots must agree after full ring"
    want = {1000 + i: i for i in range(n)}
    for st in unstack_states(stacked):
        assert _read(st) == want
