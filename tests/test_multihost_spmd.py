"""Multi-controller SPMD gossip: two PROCESSES, four virtual devices
each, one global 8-device mesh — ``gossip_delta_step``'s ppermutes cross
the process boundary through jax.distributed's backend (the DCN analog;
on real hardware the same program rides ICI within a pod and DCN across
hosts). This is the multi-host validation of SURVEY §5.8: the SPMD data
plane is not limited to one process's devices.
"""

import os
import socket
import subprocess
import sys
import time

import pytest

#: minimal reproduction of the capability the real test needs: two
#: jax.distributed processes running ONE global (cross-process) jitted
#: computation on the forced-CPU backend. Some jaxlib builds reject this
#: outright ("Multiprocess computations aren't implemented on the CPU
#: backend") — an environment property, not a code regression, so the
#: real test must skip (not fail) there.
PROBE = r"""
import sys
pid, nproc, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
import jax
jax.distributed.initialize(coordinator_address=coord, num_processes=nproc, process_id=pid)
import numpy as np
from jax.experimental import multihost_utils
# report the detected device/mesh shape FIRST: when the probe fails,
# the skip reason can then say what the environment actually offered
# (chip-window logs otherwise show a bare skip with no why)
shape = (
    f"platform={jax.default_backend()}"
    f" global_devices={len(jax.devices())}"
    f" local_devices={len(jax.local_devices())}"
    f" processes={jax.process_count()}"
)
print("PROBE_SHAPE", shape, flush=True)
assert len(jax.devices()) == 8 and len(jax.local_devices()) == 4, shape
# a global computation spanning both processes' devices — the exact
# operation class the gossip drive's ppermutes need
out = multihost_utils.process_allgather(np.int32(pid), tiled=False)
assert sorted(np.asarray(out).ravel().tolist()) == list(range(nproc))
print("PROBE_OK", flush=True)
"""

WORKER = r"""
import dataclasses, os, sys
pid, nproc, coord = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
import jax
jax.distributed.initialize(coordinator_address=coord, num_processes=nproc, process_id=pid)
import numpy as np
import jax.numpy as jnp
import jax.tree_util as tu
from jax.experimental import multihost_utils

import delta_crdt_ex_tpu  # enables x64
from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.ops.apply import OP_ADD
from delta_crdt_ex_tpu.parallel.mesh_gossip import (
    gossip_delta_drive, make_mesh, replica_sharding,
)

n = len(jax.devices())
assert n == 8, f"expected 8 global devices, got {n}"
assert len(jax.local_devices()) == 4, "each process contributes 4"
mesh = make_mesh()
sharding = replica_sharding(mesh)
L = 64

# identical host-side construction in every process; each process then
# contributes only its addressable shards
states = []
for i in range(n):
    st = BinnedStore.new(L, 8, 4)  # writer table undersized on purpose
    st = dataclasses.replace(st, ctx_gid=st.ctx_gid.at[0].set(jnp.uint64(100 + i)))
    states.append(st)
host = tu.tree_map(lambda *xs: np.stack([np.asarray(x) for x in xs]), *states)

def gput(x):
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])

stacked = tu.tree_map(gput, host)
self_slot = gput(np.zeros(n, np.int32))

from functools import partial
from tests.test_parallel import grouped_mutations

gather = partial(multihost_utils.process_allgather, tiled=True)

def batches(ops_per_replica):
    # same wire shapes as the in-process mesh tests; re-place each array
    # as a global (process-spanning) sharded array
    return tuple(
        gput(np.asarray(a)) for a in grouped_mutations(n, L, ops_per_replica)
    )

# a multi-op wave per replica; the writer table starts at 4 slots (< n
# writers), so full gossip MUST grow it through gossip_delta_drive's
# grow-and-replay path — across the process boundary
grown = []
seed = batches(
    [[(OP_ADD, 1000 + 97 * i + j, i, 1 + i * 10 + j) for j in range(4)] for i in range(n)]
)
stacked, roots, n_diff, retiers = gossip_delta_drive(
    mesh, stacked, self_slot, *seed,
    gather=gather, on_grow=lambda st: grown.append(st.replica_capacity),
)

# heal with empty batches; the gathered per-step divergence must decay
# to zero (ring propagation: each step moves entries one hop)
empty = batches([[] for _ in range(n)])
decay = [int(np.asarray(gather(n_diff)).max())]
for _ in range(2 * n):
    stacked, roots, n_diff, retiers_h = gossip_delta_drive(
        mesh, stacked, self_slot, *empty,
        gather=gather, on_grow=lambda st: grown.append(st.replica_capacity),
    )
    retiers += retiers_h
    decay.append(int(np.asarray(gather(n_diff)).max()))
    if decay[-1] == 0:
        break

assert decay[0] > 0, f"seed wave produced no divergence: {decay}"
assert decay[-1] == 0, f"divergence left after ring heal: {decay}"
assert max(grown, default=0) >= n, (
    f"writer table never grew to mesh size across processes: {grown}"
)
roots_g = gather(roots)
assert (np.asarray(roots_g) == np.asarray(roots_g).ravel()[0]).all(), "roots diverged"
print(
    f"MULTIHOST_OK pid={pid} roots={np.asarray(roots_g).ravel()[0]} "
    f"decay={decay} grown={grown} retiers={retiers}",
    flush=True,
)
"""


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _worker_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["PALLAS_AXON_POOL_IPS"] = ""
    # substitute only the device-count flag; preserve ambient XLA flags
    import re

    flag = "xla_force_host_platform_device_count"
    flags = env.get("XLA_FLAGS", "")
    if flag in flags:
        flags = re.sub(rf"--{flag}=\d+", f"--{flag}=4", flags)
    else:
        flags = f"{flags} --{flag}=4".strip()
    env["XLA_FLAGS"] = flags
    env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
    return env


def _run_pair(script: str, timeout_s: float) -> list:
    """Spawn the two-process worker pair; returns [(rc, out, err), ...]."""
    coord = f"127.0.0.1:{_free_port()}"
    env = _worker_env()
    procs = [
        subprocess.Popen(
            [sys.executable, str(script), str(pid), "2", coord],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
        )
        for pid in range(2)
    ]
    try:
        deadline = time.monotonic() + timeout_s
        outs = []
        for p in procs:
            remaining = max(5.0, deadline - time.monotonic())
            out, err = p.communicate(timeout=remaining)
            outs.append((p.returncode, out, err))
        return outs
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


#: probe verdict cache: None = not yet probed, else (ok, reason)
_PROBE_RESULT: "tuple[bool, str] | None" = None


def _global_cpu_mesh_capability(tmp_path) -> "tuple[bool, str]":
    """Can this container run a cross-process global computation on the
    forced-CPU backend? Probed ONCE per session with a minimal two-
    process allgather; failures return the diagnostic line so the skip
    reason is honest about what the environment refused."""
    global _PROBE_RESULT
    if _PROBE_RESULT is None:
        script = tmp_path / "probe.py"
        script.write_text(PROBE)
        try:
            outs = _run_pair(script, timeout_s=120)
        except subprocess.TimeoutExpired:
            _PROBE_RESULT = (False, "capability probe timed out")
            return _PROBE_RESULT
        # detected device/mesh shape, whichever process reported one —
        # recorded into the skip reason so chip-window logs show WHAT
        # the environment offered, not just that the legs skipped
        shapes = {
            line.split("PROBE_SHAPE ", 1)[1]
            for _rc, out, _err in outs
            for line in out.splitlines()
            if line.startswith("PROBE_SHAPE ")
        }
        shape = "; ".join(sorted(shapes)) if shapes else "no device shape reported"
        bad = [(rc, err) for rc, out, err in outs if rc != 0 or "PROBE_OK" not in out]
        if bad:
            rc, err = bad[0]
            tail = err.strip().splitlines()[-1] if err.strip() else f"exit {rc}"
            _PROBE_RESULT = (False, f"{tail[-300:]} [detected: {shape}]")
        else:
            _PROBE_RESULT = (True, "")
    return _PROBE_RESULT


def test_two_process_global_mesh_gossip(tmp_path):
    ok, why = _global_cpu_mesh_capability(tmp_path)
    if not ok:
        pytest.skip(
            "container cannot form a two-process global CPU mesh "
            f"(probe: {why})"
        )
    script = tmp_path / "worker.py"
    script.write_text(WORKER)
    outs = _run_pair(script, timeout_s=240)
    for rc, out, err in outs:
        assert rc == 0 and "MULTIHOST_OK" in out, f"worker failed: {err[-3000:]}"
    # both controllers computed the same converged digest root
    roots = {o.split("roots=")[1].split()[0] for _, o, _ in outs}
    assert len(roots) == 1, roots
