"""Auxiliary subsystems: tracing, interval checkpointing, telemetry, gc."""

import time

from delta_crdt_ex_tpu import AWLWWMap, MemoryStorage
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime import telemetry
from delta_crdt_ex_tpu.runtime.tracing import profile_mutations


def mk(transport, clock, **opts):
    opts.setdefault("capacity", 64)
    opts.setdefault("tree_depth", 6)
    return start_link(AWLWWMap, threaded=False, transport=transport, clock=clock, **opts)


def test_profile_mutations(transport, shared_clock):
    c = mk(transport, shared_clock)
    out = profile_mutations(c, n=20)
    assert out["mutations"] == 20 and out["total_s"] > 0
    assert len(c.read()) == 20


def test_telemetry_sync_done_counts(transport, shared_clock):
    events = []
    telemetry.attach(telemetry.SYNC_DONE, lambda e, m, md: events.append((m, md)))
    try:
        c = mk(transport, shared_clock, name="telem")
        c.mutate("add", ["a", 1])
        c.mutate("add", ["a", 1])  # same value, NEW dot: internal change
        c.mutate("remove", ["missing"])  # no internal change
        counts = [m["keys_updated_count"] for m, md in events if md["name"] == "telem"]
        assert counts == [1, 1, 0]
    finally:
        telemetry.detach(telemetry.SYNC_DONE, events.append)


def test_interval_checkpointing_rehydrates(transport, shared_clock):
    store = MemoryStorage()
    c = start_link(
        AWLWWMap,
        transport=transport,
        clock=shared_clock,
        name="ickpt",
        storage_module=store,
        storage_mode="interval",
        checkpoint_interval=0.05,
        sync_interval=0.02,
        capacity=64,
        tree_depth=6,
    )
    c.mutate("add", ["k", "v"])
    deadline = time.monotonic() + 5
    while store.read("ickpt") is None and time.monotonic() < deadline:
        time.sleep(0.02)
    assert store.read("ickpt") is not None, "interval checkpoint never fired"
    c.stop()

    c2 = mk(transport, shared_clock, name="ickpt", storage_module=store)
    assert c2.read() == {"k": "v"}


def test_gc_prunes_dead_payloads(transport, shared_clock):
    c = mk(transport, shared_clock)
    for i in range(10):
        c.mutate("add", [f"k{i}", i])
    for i in range(5):
        c.mutate("remove", [f"k{i}"])
    assert len(c._payloads) >= 10  # dead dots still held
    c.gc()
    assert len(c._payloads) == 5
    assert len(c._key_terms) == 5
    assert c.read() == {f"k{i}": i for i in range(5, 10)}

def test_file_storage_rehydrates_across_processes(tmp_path, transport, shared_clock):
    """FileStorage survives a full process loss (unlike MemoryStorage):
    a fresh replica with the same name rehydrates node id and state from
    disk (reference crash-rehydrate contract, causal_crdt_test.exs:87-102)."""
    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.runtime.storage import FileStorage

    store = FileStorage(str(tmp_path))
    c = start_link(
        threaded=False, transport=transport, clock=shared_clock,
        storage_module=store, name="file_store", capacity=64, tree_depth=6,
    )
    c.mutate("add", [("tuple", "key"), {"v": 1}])
    c.mutate("add", ["k2", b"bytes"])
    node_id = c.node_id
    c.transport.unregister(c.addr)  # crash — no terminate sync

    store2 = FileStorage(str(tmp_path))  # fresh handle, same directory
    c2 = start_link(
        threaded=False, transport=transport, clock=shared_clock,
        storage_module=store2, name="file_store", capacity=64, tree_depth=6,
    )
    assert c2.node_id == node_id  # dot-namespace continuity
    assert c2.read() == {("tuple", "key"): {"v": 1}, "k2": b"bytes"}
    # dot continuity holds: new writes keep converging with a peer
    c3 = start_link(
        threaded=False, transport=transport, clock=shared_clock,
        capacity=64, tree_depth=6,
    )
    c2.set_neighbours([c3])
    c2.mutate("add", ["k3", 3])
    for _ in range(4):
        c2.sync_to_all()
        transport.pump()
    assert c3.read() == c2.read()


def test_rehydrate_rejects_foreign_layout(transport, shared_clock):
    """A snapshot written by a different engine layout must fail with a
    descriptive error, not an opaque KeyError (ADVICE r1)."""
    import dataclasses

    import pytest

    store = MemoryStorage()
    c = mk(transport, shared_clock, name="laytag", storage_module=store)
    c.mutate("add", ["k", "v"])
    snap = store.read("laytag")
    assert snap.layout == "binned-v2"
    c.stop()
    c.transport.unregister("laytag")
    store.write("laytag", dataclasses.replace(snap, layout="flat-v0"))
    with pytest.raises(ValueError, match="engine layout"):
        mk(transport, shared_clock, name="laytag", storage_module=store)

    # the real legacy case: a snapshot pickled BEFORE the tag existed has
    # no 'layout' in its instance dict, and unpickling falls back to the
    # dataclass default — the guard must read __dict__, not getattr
    untagged = dataclasses.replace(snap)
    del untagged.__dict__["layout"]
    store.write("laytag", untagged)
    with pytest.raises(ValueError, match="engine layout"):
        mk(transport, shared_clock, name="laytag", storage_module=store)
