"""Log-shipping catch-up (ISSUE 4): a rejoining/lagging peer replays the
originator's delta-log suffix instead of walking the digest tree.

Covers the WAL range-read cursor (segment boundaries, truncated tails,
reused ``start_seq``), the horizon fallback contract, the watermark
learning/persistence path, end-to-end catch-up parity against the
classic digest walk (bit-for-bit where the workload permits, canonical
content under unrestricted churn — see the note on ctx-only rows), and
a Down-mid-stream abort.

Parity note: the walk ships rows whose DIGESTS differ; log shipping
ships rows the WAL range TOUCHED. The sets coincide except for rows
whose leaf digest returned to its pre-lag value while the context still
advanced (an add+remove of a fresh dot in an otherwise untouched
bucket): log shipping propagates that context advance, the walk lazily
omits it. Re-merging an identical full row is bit-stable (the row pack
is a stable sort on aliveness), so scripts that avoid the corner give
bit-identical receiver states; unrestricted churn scripts assert read
and canonical alive-dot equality instead.
"""

import dataclasses
import os
import time

import numpy as np
import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.runtime import sync as sync_proto, telemetry
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from delta_crdt_ex_tpu.runtime.wal import WalLog

_COLS = tuple(f.name for f in dataclasses.fields(BinnedStore))


# ---------------------------------------------------------------------------
# WAL range-read cursor


def _mk_wal(tmp_path, **kw):
    w = WalLog(str(tmp_path / "log"), fsync_mode="none", **kw)
    w.bind(7)
    return w


def _append(w, seq, tag="x"):
    w.append({"kind": "batch", "seq": seq, "ops": [("add", f"{tag}{seq}", seq)], "ts": [seq]})
    w.commit()


def test_read_range_spans_segment_boundaries(tmp_path):
    w = _mk_wal(tmp_path, segment_bytes=128)  # rolls every couple records
    for seq in range(1, 13):
        _append(w, seq)
    assert len(w.segment_paths()) > 2  # the rolling actually happened
    records, next_seq, exhausted = w.read_range(0, 12)
    assert [r["seq"] for r in records] == list(range(1, 13))
    assert next_seq == 12 and exhausted
    # mid-log cursor: lo is exclusive, segments below it are skipped
    records, next_seq, exhausted = w.read_range(5, 9)
    assert [r["seq"] for r in records] == [6, 7, 8, 9]
    assert next_seq == 9 and exhausted
    # bounded read: the cursor resumes exactly after the last record
    records, next_seq, exhausted = w.read_range(0, 12, max_records=4)
    assert [r["seq"] for r in records] == [1, 2, 3, 4] and not exhausted
    records, next_seq, _ = w.read_range(next_seq, 12, max_records=4)
    assert [r["seq"] for r in records] == [5, 6, 7, 8]
    # byte budget bounds a read the same way
    records, next_seq, exhausted = w.read_range(0, 12, max_bytes=1)
    assert [r["seq"] for r in records] == [1] and not exhausted
    w.close()


def test_read_range_empty_and_out_of_range(tmp_path):
    w = _mk_wal(tmp_path)
    assert w.read_range(0, 0) == ([], 0, True)
    _append(w, 1)
    _append(w, 2)
    # lo beyond the log: nothing, exhausted (the requester is ahead)
    assert w.read_range(5, 9) == ([], 5, True)
    w.close()


def test_read_range_stops_at_truncated_tail(tmp_path):
    w = _mk_wal(tmp_path)
    for seq in (1, 2, 3):
        _append(w, seq)
    w.close()
    path = w.segment_paths()[-1]
    with open(path, "r+b") as f:
        f.truncate(os.path.getsize(path) - 3)  # tear the last record
    records, next_seq, exhausted = w.read_range(0, 3)
    assert [r["seq"] for r in records] == [1, 2]
    assert next_seq == 2  # the cursor never claims the torn record
    # recovery truncates the same tear away; the range then agrees
    w2 = WalLog(str(tmp_path / "log"), fsync_mode="none")
    _header, recs = w2.recover()
    assert [r["seq"] for r in recs] == [1, 2]
    assert w2.read_range(0, 9) == (recs, 2, True)
    w2.close()


def test_read_range_handles_records_larger_than_the_read_chunk(tmp_path):
    """A record bigger than the 256 KiB streaming chunk is read whole
    via one exact-size read (no per-chunk rebuffering) and round-trips
    intact."""
    w = _mk_wal(tmp_path)
    big = {"kind": "blob", "seq": 1, "data": os.urandom(700 << 10)}
    w.append(big)
    w.commit()
    _append(w, 2)
    w.close()
    records, next_seq, exhausted = w.read_range(0, 2)
    assert [r["seq"] for r in records] == [1, 2] and exhausted
    assert records[0]["data"] == big["data"]


def test_read_range_stops_at_mid_segment_corruption(tmp_path):
    """A CRC-corrupt record that is fully present (not a short tail)
    ends the stream immediately — no quadratic rebuffering hunting for
    bytes that cannot repair it."""
    w = _mk_wal(tmp_path)
    for seq in (1, 2, 3):
        _append(w, seq)
    w.close()
    path = w.segment_paths()[-1]
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        f.seek(size // 2)  # flip a byte inside a middle record's payload
        byte = f.read(1)
        f.seek(size // 2)
        f.write(bytes([byte[0] ^ 0xFF]))
    records, _next, _exhausted = w.read_range(0, 3)
    assert [r["seq"] for r in records] == [1]  # clean prefix only


def test_read_range_after_reused_start_seq(tmp_path):
    """Recovery that truncates a segment's FIRST record re-mints the
    same ``seg-<start_seq>`` filename on the next append; the range
    cursor must serve the re-minted records once, not twice."""
    w = _mk_wal(tmp_path)
    _append(w, 1)
    w.rotate()
    _append(w, 2)  # opens seg-...2.wal
    w.close()
    second = w.segment_paths()[-1]
    with open(second, "r+b") as f:
        # tear into the segment's first (only) record: recovery keeps
        # the header, truncates the record, and seq 2 re-mints into a
        # segment file with the SAME start_seq
        f.truncate(os.path.getsize(second) - 3)
    w2 = WalLog(str(tmp_path / "log"), fsync_mode="none")
    _header, recs = w2.recover()
    assert [r["seq"] for r in recs] == [1]
    _append(w2, 2)
    records, next_seq, exhausted = w2.read_range(0, 2)
    assert [r["seq"] for r in records] == [1, 2]
    assert exhausted
    w2.close()


def test_horizon_tracks_compaction(tmp_path):
    w = _mk_wal(tmp_path, segment_bytes=128)
    assert w.horizon() == 0  # empty log: nothing servable, nothing needed
    for seq in range(1, 13):
        _append(w, seq)
    assert w.horizon() == 0  # full history retained
    w.compact(8)
    h = w.horizon()
    assert 0 < h <= 8  # reclaimed segments raised the horizon
    records, _next, exhausted = w.read_range(h, 12)
    assert exhausted and [r["seq"] for r in records] == list(range(h + 1, 13))
    # everything at/above the horizon stays fully servable; below it the
    # caller must fall back to the walk (records are simply absent)
    below, _n, _e = w.read_range(0, 12)
    assert [r["seq"] for r in below] == list(range(h + 1, 13))
    w.close()


# ---------------------------------------------------------------------------
# end-to-end: replicas over LocalTransport


def _mk(transport, clock, name, tmp=None, **opts):
    kw = dict(
        threaded=False, transport=transport, clock=clock,
        capacity=256, tree_depth=6, sync_timeout=0.01,
    )
    if tmp is not None:
        kw.update(wal_dir=str(tmp), fsync_mode="none")
    kw.update(opts)
    return start_link(AWLWWMap, name=name, **kw)


def _drive(transport, replicas, rounds=8):
    """Deliver queued messages without opening new sync rounds (so tests
    can count/inspect the catch-up exchange itself)."""
    n = 0
    for _ in range(rounds):
        moved = 0
        for r in replicas:
            for m in transport.drain(r.addr):
                r.handle(m)
                moved += 1
        n += moved
        if not moved:
            break
    return n


def _lose_inflight(transport, rep):
    """Simulate in-flight loss toward ``rep``: its mailbox drains to the
    floor (the sender already advanced its push cursors)."""
    return transport.drain(rep.addr)


def assert_state_bit_equal(s1, s2, ctx=""):
    for c in _COLS:
        assert np.array_equal(
            np.asarray(getattr(s1, c)), np.asarray(getattr(s2, c))
        ), (ctx, c)


def _alive_dots(rep):
    """Canonical content fingerprint: every alive dot's full identity,
    position-independent (the parity form for workloads where log
    shipping propagates ctx-only rows the walk omits)."""
    alive = np.asarray(rep.state.alive)
    u, b = np.nonzero(alive)
    gid = np.asarray(rep.state.ctx_gid)[np.asarray(rep.state.node)[u, b]]
    return sorted(
        zip(
            np.asarray(rep.state.key)[u, b].tolist(),
            gid.tolist(),
            np.asarray(rep.state.ctr)[u, b].tolist(),
            np.asarray(rep.state.ts)[u, b].tolist(),
            np.asarray(rep.state.valh)[u, b].tolist(),
            u.tolist(),
        )
    )


def test_watermark_learned_from_walk_equality(tmp_path):
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "wm_a", tmp_path / "a")
    b = _mk(transport, clock, "wm_b")
    a.set_neighbours([b])
    transport.pump()
    for i in range(5):
        a.mutate("add", [i, i])
    a.sync_to_all()  # eager pushes deliver; the walk then finds equality
    transport.pump()
    assert b.read() == a.read()
    # the equality ack taught b how much of a's history it covers …
    assert b._applied_seq.get(a.addr) == a._seq == 5
    # … and taught a (via AckMsg) the floor its compaction may reclaim to
    assert a._ack_seq.get(b.addr) == 5


def test_midwalk_equality_does_not_advance_watermark(tmp_path):
    """Mid-walk frames re-verify only the FRONTIER subtrees; the rest
    was proven against the sender's state at round open. An equality on
    such a frame must not claim the frame's (possibly newer) seq, or a
    sender writing mid-round would make the peer's watermark over-claim
    and log shipping would permanently skip those records."""
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "mw_a", tmp_path / "a")
    b = _mk(transport, clock, "mw_b")
    a.set_neighbours([b])
    transport.pump()
    for i in range(4):
        a.mutate("add", [i, i])
    a.sync_to_all()
    transport.pump()
    assert b._applied_seq.get(a.addr) == 4

    # a mid-walk continuation frame (level > 0) whose frontier digests
    # match b's own tree, stamped with a far-future seq: equality fires,
    # the watermark must NOT jump to 999
    tree = b._ensure_tree()
    idx = np.zeros(1, np.int64)
    blocks = sync_proto.make_blocks(tree, 2, np.zeros(1, np.int64) + 0, 2)
    b.handle(
        sync_proto.DiffMsg(
            originator=a.addr, frm=a.addr, to=b.addr, level=2,
            idx=idx, blocks=blocks, seq=999, log_horizon=0,
        )
    )
    assert b._applied_seq.get(a.addr) == 4  # unchanged: not an opener


def test_superseded_chunk_does_not_fork_streams(tmp_path):
    """A chunk answering an older, timed-out request still APPLIES
    (idempotent) but must not pace follow-ups or complete the live
    stream — otherwise every timeout forks a duplicate full stream."""
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "fk_a", tmp_path / "a", catchup_chunk_rows=8)
    b = _mk(transport, clock, "fk_b")
    a.set_neighbours([b])
    transport.pump()
    a.mutate("add", ["prime", 0])
    a.sync_to_all()
    transport.pump()
    for i in range(40):
        a.mutate("add", [i, i])
    a.sync_to_all()
    _lose_inflight(transport, b)
    time.sleep(0.02)
    a.sync_to_all()
    for m in _lose_inflight(transport, b):
        b.handle(m)  # opener → request #1 queued at a
    for m in transport.drain(a.addr):
        a.handle(m)  # chunk #1 (more=True) queued at b
    chunk1 = next(
        m for m in transport.drain(b.addr) if isinstance(m, sync_proto.LogChunkMsg)
    )
    assert chunk1.more
    # the stream times out and restarts before chunk #1 is handled
    time.sleep(0.02)
    with b._lock:
        b._request_catchup(a.addr)  # request #2 (from the old watermark)
    # now the STALE chunk #1 arrives twice (delayed + duplicated)
    b.handle(chunk1)
    b.handle(chunk1)
    followups = [
        m for m in transport.drain(a.addr) if isinstance(m, sync_proto.GetLogMsg)
    ]
    # the restarted stream's request plus exactly ONE pace: the first
    # stale delivery matches the restarted cursor (same watermark — it
    # IS a valid answer) and legitimately paces the stream forward; the
    # duplicate is recognised as below the advanced cursor and paces
    # nothing. The buggy behaviour would pace BOTH (three requests,
    # forked streams re-shipping the suffix).
    assert len(followups) == 2
    assert followups[0].last_seq == chunk1.seq_lo  # request #2's cursor
    assert followups[1].last_seq > chunk1.seq_lo  # the single pace
    assert b.stats()["catchup"]["in_flight"] == 1
    # drive to completion: the live stream finishes and converges
    for m in followups:
        a.handle(m)
    for _ in range(12):
        for m in transport.drain(b.addr):
            b.handle(m)
        for m in transport.drain(a.addr):
            a.handle(m)
    assert b.read() == a.read()
    assert b.stats()["catchup"]["in_flight"] == 0


def test_catchup_after_inflight_loss_single_roundtrip(tmp_path):
    """The headline path: pushes lost in flight leave the peer lagging
    with advanced cursors; the next round opener resolves by ONE
    GetLog → LogChunk round trip plus the completion ack — no level
    walk, no GetDiff — and the states match a never-partitioned sync."""
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "lr_a", tmp_path / "a")
    b = _mk(transport, clock, "lr_b")
    a.set_neighbours([b])
    transport.pump()
    for i in range(8):
        a.mutate("add", [i, i])
    a.sync_to_all()
    transport.pump()
    assert b._applied_seq.get(a.addr) == 8

    for i in range(8, 24):
        a.mutate("add", [i, i])
    a.mutate("remove", [0])
    a.sync_to_all()
    lost = _lose_inflight(transport, b)
    assert any(isinstance(m, sync_proto.EntriesMsg) for m in lost)
    time.sleep(0.02)  # the opener's in-flight slot expires

    a.sync_to_all()
    kinds = []
    for _ in range(8):
        for m in transport.drain(b.addr):
            kinds.append(type(m).__name__)
            b.handle(m)
        for m in transport.drain(a.addr):
            kinds.append(type(m).__name__)
            a.handle(m)
    assert b.read() == a.read() == {i: i for i in range(1, 24)}
    # the catch-up exchange: opener, log request, one chunk, ack — and
    # whatever eager pushes rode along; never a GetDiffMsg leaf fetch
    assert "GetLogMsg" in kinds and "LogChunkMsg" in kinds
    assert "GetDiffMsg" not in kinds
    assert kinds.count("LogChunkMsg") == 1
    assert b.stats()["catchup"]["chunks_applied"] == 1
    assert a.stats()["catchup"]["chunks_served"] == 1
    assert a.stats()["catchup"]["bytes_shipped"] > 0
    # the stream's completion ack cleared the round's in-flight slot and
    # advanced the server's membership-compaction watermark
    assert not a._outstanding
    assert a._ack_seq.get(b.addr) == a._seq


def test_catchup_parity_bit_for_bit_vs_digest_walk(tmp_path):
    """Two identically-seeded receivers, one catching up via log
    shipping and one via the classic walk, end with BIT-IDENTICAL state
    arrays (workload avoids the ctx-only corner: fresh adds plus
    removes of pre-lag keys, so touched rows == differing rows)."""
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "pb_a", tmp_path / "a")
    bl = _mk(transport, clock, "pb_log", node_id=777)
    bw = _mk(transport, clock, "pb_walk", node_id=777, log_shipping=False)
    a.set_neighbours([bl, bw])
    transport.pump()
    for i in range(12):
        a.mutate("add", [i, i * 10])
    a.sync_to_all()
    transport.pump()
    assert bl.read() == bw.read() == a.read()
    assert_state_bit_equal(bl.state, bw.state, "pre-lag")

    # the lag: fresh adds + removes of pre-lag keys, all lost in flight
    for i in range(12, 40):
        a.mutate("add", [i, i * 10])
    for i in range(0, 6):
        a.mutate("remove", [i])
    a.sync_to_all()
    _lose_inflight(transport, bl)
    _lose_inflight(transport, bw)
    time.sleep(0.02)

    a.sync_to_all()
    _drive(transport, [a, bl, bw])
    assert bl.read() == bw.read() == a.read()
    assert len(a.read()) == 12 - 6 + 28
    assert_state_bit_equal(bl.state, bw.state, "post-catchup")
    assert bl.stats()["catchup"]["chunks_applied"] >= 1
    assert bw.stats()["catchup"]["chunks_applied"] == 0  # walked


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_catchup_parity_randomized_churn(tmp_path, seed):
    """Seeded random add/remove churn scripts with repeated partition /
    reconnect cycles: log-shipping and walk receivers both converge to
    the writer, with identical reads and identical canonical alive-dot
    content. (Raw array bytes may differ only on ctx-only rows — the
    add+remove corner — which log shipping propagates and the walk
    omits; see the module docstring.)"""
    rng = np.random.default_rng(seed)
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, f"rc_a{seed}", tmp_path / "a")
    bl = _mk(transport, clock, f"rc_log{seed}", node_id=777)
    bw = _mk(transport, clock, f"rc_walk{seed}", node_id=777, log_shipping=False)
    a.set_neighbours([bl, bw])
    transport.pump()
    for cycle in range(int(rng.integers(2, 5))):
        for _ in range(int(rng.integers(1, 16))):
            ki = int(rng.integers(0, 24))
            if rng.random() < 0.7:
                a.mutate("add", [ki, int(rng.integers(0, 100))])
            else:
                a.mutate("remove", [ki])
        a.sync_to_all()
        if rng.random() < 0.7:  # partition: this round is lost
            _lose_inflight(transport, bl)
            _lose_inflight(transport, bw)
            time.sleep(0.02)
        else:
            _drive(transport, [a, bl, bw])
    # reconnect and settle: repeated rounds (walk may need several)
    for _ in range(6):
        time.sleep(0.02)
        a.sync_to_all()
        _drive(transport, [a, bl, bw])
    assert bl.read() == bw.read() == a.read()
    assert _alive_dots(bl) == _alive_dots(bw) == _alive_dots(a)


def test_horizon_fallback_covers_prefix_by_walk(tmp_path):
    """A peer lagging past the compaction horizon gets the retained
    suffix as chunks PLUS an explicit horizon; the pre-horizon prefix
    heals through the classic walk — end state complete either way."""
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(
        transport, clock, "hz_a", tmp_path / "a",
        segment_bytes=256, compact_every=10**9, membership_compaction=False,
    )
    b = _mk(transport, clock, "hz_b")
    a.set_neighbours([b])
    transport.pump()
    for i in range(4):
        a.mutate("add", [i, i])
    a.sync_to_all()
    transport.pump()
    assert b._applied_seq.get(a.addr) == 4

    # the peer misses a long stretch; the writer compacts past its floor
    for i in range(4, 40):
        a.mutate("add", [i, i])
    a.sync_to_all()
    _lose_inflight(transport, b)
    a.checkpoint()  # membership gate off: reclaim to the snapshot
    horizon = a.stats()["wal"]["horizon"]
    assert horizon > 4  # the peer's floor was compacted past

    time.sleep(0.02)
    a.sync_to_all()
    # watermark (4) < advertised horizon → b starts the classic walk;
    # direct requests under the horizon get the suffix + explicit marker
    _drive(transport, [a, b])
    assert b.read() == a.read()

    # direct under-horizon request: explicit horizon + retained suffix
    b2 = _mk(transport, clock, "hz_b2")
    transport.send(a.addr, sync_proto.GetLogMsg(frm=b2.addr, to=a.addr, last_seq=0))
    for m in transport.drain(a.addr):
        a.handle(m)
    chunks = [
        m for m in transport.drain(b2.addr)
        if isinstance(m, sync_proto.LogChunkMsg)
    ]
    assert len(chunks) == 1 and chunks[0].horizon == horizon
    assert chunks[0].seq_lo == horizon  # served only the post-horizon suffix
    b2.handle(chunks[0])
    assert b2.stats()["catchup"]["horizon_fallbacks"] == 1
    # the clamped chunk did NOT connect to b2's watermark (0 < seq_lo):
    # claiming seq_hi would silently disable the walk that heals the
    # unshipped prefix — the watermark must stand until a walk equality
    assert b2._applied_seq.get(a.addr, 0) == 0


def test_clear_record_is_a_serving_barrier(tmp_path):
    """A ``clear`` touching more buckets than the hard row cap must not
    ship the whole keyspace in one frame: the serve answers an explicit
    horizon AT the clear, the walk covers through it, and log shipping
    resumes above it — the stream never false-acks and the receiver
    still converges."""
    transport = LocalTransport()
    clock = LogicalClock()
    # 64 buckets; hard cap = 4 × catchup_chunk_rows = 16 < 64 → barrier
    a = _mk(transport, clock, "cl_a", tmp_path / "a", catchup_chunk_rows=4)
    b = _mk(transport, clock, "cl_b")
    a.set_neighbours([b])
    transport.pump()
    for i in range(6):
        a.mutate("add", [i, i])
    a.sync_to_all()
    transport.pump()
    watermark = b._applied_seq.get(a.addr)
    assert watermark == a._seq

    a.mutate("clear", [])
    for i in range(10, 16):
        a.mutate("add", [i, i])
    a.sync_to_all()
    _lose_inflight(transport, b)
    time.sleep(0.02)
    a.sync_to_all()
    kinds = []
    for _ in range(16):
        time.sleep(0.02)  # walk rounds for the barrier span need expiry
        a.sync_to_all()
        for m in transport.drain(b.addr):
            kinds.append(type(m).__name__)
            b.handle(m)
        for m in transport.drain(a.addr):
            kinds.append(type(m).__name__)
            a.handle(m)
    assert b.read() == a.read() == {i: i for i in range(10, 16)}
    barrier_chunks = [1 for k in kinds if k == "LogChunkMsg"]
    assert barrier_chunks  # the log path answered (with the barrier)
    # the watermark never claimed the unshipped clear span by log alone:
    # it reached a's seq only through a genuine walk equality ack
    assert b._applied_seq.get(a.addr) == a._seq


def test_unknown_record_kind_is_a_serving_barrier(tmp_path):
    """A WAL record kind written by a newer build cannot be indexed by
    this one: serving must stop at it with an explicit horizon instead
    of silently skipping it (which would advance the peer's watermark
    past effects that were never shipped)."""
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "uk_a", tmp_path / "a")
    b = _mk(transport, clock, "uk_b")
    a.set_neighbours([b])
    transport.pump()
    a.mutate("add", ["prime", 0])
    a.sync_to_all()
    transport.pump()
    assert b._applied_seq.get(a.addr) == 1

    # a future build appends a record kind this build does not know
    a._wal.append({"kind": "from_the_future", "seq": a._seq + 1})
    a._wal.commit()
    a._seq += 1
    for i in range(6):
        a.mutate("add", [i, i])

    with b._lock:
        b._request_catchup(a.addr)  # stream from the watermark (1)
    for m in transport.drain(a.addr):
        a.handle(m)
    chunk = next(
        m for m in transport.drain(b.addr) if isinstance(m, sync_proto.LogChunkMsg)
    )
    # barrier at the unknown record: nothing served below it, horizon
    # names it, more invites the receiver to resume above it
    assert chunk.horizon == 2 and chunk.seq_hi == 1 and chunk.slices == []
    assert chunk.more
    b.handle(chunk)
    assert b._applied_seq.get(a.addr) == 1  # never advanced past the barrier
    # the resumed request (sent by the chunk handler) serves the suffix
    for m in transport.drain(a.addr):
        a.handle(m)
    chunk2 = next(
        m for m in transport.drain(b.addr) if isinstance(m, sync_proto.LogChunkMsg)
    )
    assert chunk2.seq_lo == 2 and chunk2.seq_hi == a._seq and chunk2.slices
    b.handle(chunk2)
    # still no coverage claim across the barrier — only a walk can ack it
    assert b._applied_seq.get(a.addr) == 1
    # …and the resume cursor (last_seq=2, past the barrier) must not
    # have moved the server's compaction floor: only applied_seq may
    assert a._ack_seq.get(b.addr, 0) <= 1


def test_get_log_without_wal_answers_walkable_horizon(tmp_path):
    """A server with no WAL (or log shipping disabled) answers an empty
    chunk whose horizon says "everything is pre-horizon" and opens a
    classic walk — requesters degrade gracefully, nothing stalls."""
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "nw_a")  # no wal_dir
    b = _mk(transport, clock, "nw_b")
    for i in range(5):
        a.mutate("add", [i, i])
    transport.send(a.addr, sync_proto.GetLogMsg(frm=b.addr, to=a.addr, last_seq=0))
    for m in transport.drain(a.addr):
        a.handle(m)
    msgs = transport.drain(b.addr)
    chunk = next(m for m in msgs if isinstance(m, sync_proto.LogChunkMsg))
    assert chunk.slices == [] and not chunk.more and chunk.horizon == a._seq
    assert any(isinstance(m, sync_proto.DiffMsg) for m in msgs)  # the walk
    for m in msgs:
        b.handle(m)
    _drive(transport, [a, b])
    assert b.read() == a.read()


def test_chunked_stream_is_requester_paced(tmp_path):
    """A lag wider than the chunk row budget streams as multiple
    bounded chunks, one in flight at a time (re-requested from each
    ``seq_hi``), and the final chunk acks the round."""
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "ch_a", tmp_path / "a", catchup_chunk_rows=8)
    b = _mk(transport, clock, "ch_b")
    a.set_neighbours([b])
    transport.pump()
    a.mutate("add", ["prime", 0])
    a.sync_to_all()
    transport.pump()
    assert b._applied_seq.get(a.addr) == 1

    for i in range(40):  # touches well over 8 distinct buckets
        a.mutate("add", [i, i])
    a.sync_to_all()
    _lose_inflight(transport, b)
    time.sleep(0.02)
    a.sync_to_all()
    kinds = []
    for _ in range(24):
        for m in transport.drain(b.addr):
            kinds.append(type(m).__name__)
            b.handle(m)
        for m in transport.drain(a.addr):
            kinds.append(type(m).__name__)
            a.handle(m)
    assert b.read() == a.read()
    n_chunks = kinds.count("LogChunkMsg")
    assert n_chunks > 1  # genuinely streamed
    assert b.stats()["catchup"]["chunks_applied"] == n_chunks
    assert not a._outstanding  # completion ack cleared the slot


def test_down_mid_stream_leaves_receiver_consistent(tmp_path):
    """The server dies between chunks: the receiver keeps every fully
    applied chunk (idempotent merges), clears the stream, and a later
    rejoin resumes from the advanced watermark without re-walking."""
    transport = LocalTransport()
    clock = LogicalClock()
    # eager_deltas off: the catch-up stream is the ONLY carrier, so the
    # resumption after the crash is observable (a restarted server's
    # reset push cursors would otherwise re-cover the lag by themselves)
    a = _mk(transport, clock, "dn_a", tmp_path / "a",
            catchup_chunk_rows=8, eager_deltas=False)
    b = _mk(transport, clock, "dn_b")
    a.set_neighbours([b])
    b.set_neighbours([a])  # b monitors a → Down(a) is delivered to b
    transport.pump()
    a.mutate("add", ["prime", 0])
    a.sync_to_all()
    transport.pump()

    for i in range(40):
        a.mutate("add", [i, i])
    a.sync_to_all()
    _lose_inflight(transport, b)
    time.sleep(0.02)
    a.sync_to_all()
    # deliver the opener and exactly ONE chunk round trip
    for m in _lose_inflight(transport, b):
        b.handle(m)  # opener (+ any stray) → b requests
    for m in transport.drain(a.addr):
        a.handle(m)  # a serves chunk 1
    chunk1 = [m for m in transport.drain(b.addr) if isinstance(m, sync_proto.LogChunkMsg)]
    assert len(chunk1) == 1 and chunk1[0].more
    b.handle(chunk1[0])  # applied; next request now queued at a
    applied_before = b.stats()["catchup"]["chunks_applied"]
    watermark = b._applied_seq.get(a.addr)
    assert watermark == chunk1[0].seq_hi

    a.crash()  # the server dies mid-stream; Down(a) reaches b
    b.process_pending()
    assert b.stats()["catchup"]["in_flight"] == 0  # stream aborted
    # every applied chunk was an ordinary idempotent merge: the partial
    # read is a consistent subset of what the writer actually wrote
    written = {i: i for i in range(40)} | {"prime": 0}
    assert set(b.read().items()) <= set(written.items())
    assert b._applied_seq.get(a.addr) == watermark  # stands at last chunk

    # the server rehydrates (same wal_dir) and the stream resumes from
    # the watermark — no pre-watermark rows are re-requested
    a2 = _mk(transport, clock, "dn_a", tmp_path / "a",
             catchup_chunk_rows=8, eager_deltas=False)
    a2.set_neighbours([b])
    time.sleep(0.02)
    a2.sync_to_all()
    _drive(transport, [a2, b])
    assert b.read() == a2.read()
    assert b.stats()["catchup"]["chunks_applied"] > applied_before


def test_watermarks_survive_restart_via_snapshot(tmp_path):
    """peer_seqs ride compaction snapshots: a restarted replica resumes
    log-shipped catch-up from its persisted watermark instead of
    re-requesting history from zero."""
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "sn_a", tmp_path / "a")
    b = _mk(transport, clock, "sn_b", tmp_path / "b")
    a.set_neighbours([b])
    transport.pump()
    for i in range(6):
        a.mutate("add", [i, i])
    a.sync_to_all()
    transport.pump()
    assert b._applied_seq.get(a.addr) == 6
    b.checkpoint()  # snapshot carries the watermark
    b.crash()

    b2 = _mk(transport, clock, "sn_b", tmp_path / "b")
    assert b2._applied_seq.get(a.addr) == 6
    assert b2.read() == a.read()


def test_catchup_telemetry_and_stats(tmp_path):
    transport = LocalTransport()
    clock = LogicalClock()
    a = _mk(transport, clock, "tl_a", tmp_path / "a")
    b = _mk(transport, clock, "tl_b")
    a.set_neighbours([b])
    transport.pump()
    a.mutate("add", ["prime", 0])
    a.sync_to_all()
    transport.pump()

    events = []
    handler = lambda e, meas, meta: events.append((e, dict(meas), dict(meta)))
    telemetry.attach(telemetry.CATCHUP_CHUNK, handler)
    telemetry.attach(telemetry.CATCHUP_DONE, handler)
    try:
        for i in range(12):
            a.mutate("add", [i, i])
        a.sync_to_all()
        _lose_inflight(transport, b)
        time.sleep(0.02)
        a.sync_to_all()
        _drive(transport, [a, b])
    finally:
        telemetry.detach(telemetry.CATCHUP_CHUNK, handler)
        telemetry.detach(telemetry.CATCHUP_DONE, handler)
    assert b.read() == a.read()
    roles = {m.get("role") for e, _meas, m in events if e == telemetry.CATCHUP_CHUNK}
    assert roles == {"server", "client"}
    done = [meas for e, meas, _m in events if e == telemetry.CATCHUP_DONE]
    assert len(done) == 1 and done[0]["chunks"] == 1
    assert done[0]["duration_s"] >= 0 and done[0]["horizon_fallback"] == 0
    st = b.stats()["catchup"]
    assert st["chunks_applied"] == 1 and st["rows_applied"] > 0
    assert st["last_duration_s"] >= 0 and st["in_flight"] == 0


def test_log_chunk_roundtrips_over_tcp():
    """Catch-up frames are ordinary transport messages: a LogChunkMsg
    with numpy slice bodies survives the TCP frame path (including the
    big-array side channel) byte-for-byte."""
    tcp = pytest.importorskip("delta_crdt_ex_tpu.runtime.tcp_transport")
    t1 = tcp.TcpTransport()
    t2 = tcp.TcpTransport()
    try:
        t2.register("sink", None)
        arrays = {
            "rows": np.arange(8, dtype=np.int32),
            "key": np.arange(64, dtype=np.uint64).reshape(8, 8),
        }
        chunk = sync_proto.LogChunkMsg(
            frm="src", to="sink", seq_lo=3, seq_hi=9, more=True, horizon=None,
            slices=[{"buckets": np.arange(8, dtype=np.int64),
                     "arrays": arrays, "payloads": {(1, 2, 3): ("k", "v")}}],
        )
        get = sync_proto.GetLogMsg(frm="src", to="sink", last_seq=3)
        assert t1.send(("sink", t2.endpoint), get)
        assert t1.send(("sink", t2.endpoint), chunk)
        got = []
        deadline = time.monotonic() + 5
        while len(got) < 2 and time.monotonic() < deadline:
            got += t2.drain("sink")
            time.sleep(0.01)
        assert [type(m).__name__ for m in got] == ["GetLogMsg", "LogChunkMsg"]
        assert got[0].last_seq == 3
        rt = got[1]
        assert (rt.seq_lo, rt.seq_hi, rt.more, rt.horizon) == (3, 9, True, None)
        assert np.array_equal(rt.slices[0]["arrays"]["key"], arrays["key"])
        assert rt.slices[0]["payloads"] == {(1, 2, 3): ("k", "v")}
    finally:
        t1.close()
        t2.close()


# ---------------------------------------------------------------------------
# past-horizon mode decision (ROADMAP follow-up (a) / ISSUE 5 satellite)


def _lagged_pair(tmp_path, transport, clock, **writer_opts):
    """Prime a (writer, receiver) pair to watermark 4, then lag the
    writer by ops 4..40 with the receiver partitioned."""
    a = _mk(
        transport, clock, "sx_a", tmp_path / "a",
        segment_bytes=256, compact_every=10**9, **writer_opts,
    )
    b = _mk(transport, clock, "sx_b")
    a.set_neighbours([b])
    transport.pump()
    for i in range(4):
        a.mutate("add", [i, i])
    a.sync_to_all()
    transport.pump()
    assert b._applied_seq.get(a.addr) == 4
    for i in range(4, 40):
        a.mutate("add", [i, i])
    a.sync_to_all()
    _lose_inflight(transport, b)
    return a, b


def test_past_horizon_dominant_suffix_streams_clamped_chunks(tmp_path):
    """Past the horizon with a DOMINANT retained suffix (the
    membership-retain shape), the peer answers the opener with a clamped
    catch-up stream: the suffix ships as chunks, only the short prefix
    heals by walk — and the walk floor prevents a re-request loop."""
    transport = LocalTransport()
    clock = LogicalClock()
    a, b = _lagged_pair(
        tmp_path, transport, clock,
        membership_compaction=True, membership_retain=32,
    )
    a.checkpoint()  # reclaim to the retain bound: horizon lands mid-lag
    horizon = a.stats()["wal"]["horizon"]
    w = b._applied_seq.get(a.addr)
    assert w < horizon < a._seq
    assert a._seq - horizon >= b.catchup_suffix_ratio * (horizon - w)

    time.sleep(0.02)
    before = b.stats()["catchup"]["chunks_applied"]
    a.sync_to_all()
    _drive(transport, [a, b], rounds=30)
    st = b.stats()["catchup"]
    assert st["chunks_applied"] > before, "dominant suffix must stream"
    assert st["horizon_fallbacks"] >= 1  # the stream was clamped
    assert st["in_flight"] == 0

    # the prefix healed by walk in the same exchange: full convergence,
    # and the walk equality retired the per-peer walk floor
    time.sleep(0.02)
    a.sync_to_all()
    _drive(transport, [a, b], rounds=30)
    assert b.read() == a.read()
    assert b._applied_seq.get(a.addr) == a._seq
    assert b._catchup_walk_floor.get(a.addr) is None


def test_past_horizon_small_suffix_skips_chunks_entirely(tmp_path):
    """When compaction swallowed (nearly) the whole lag, the walk must
    carry everything anyway — the peer skips the suffix chunks instead
    of paying stream round trips on top of the walk (the measured ~0.8x
    regression shape)."""
    transport = LocalTransport()
    clock = LogicalClock()
    a, b = _lagged_pair(
        tmp_path, transport, clock, membership_compaction=False,
    )
    a.checkpoint()  # membership gate off: reclaim to the snapshot seq
    horizon = a.stats()["wal"]["horizon"]
    w = b._applied_seq.get(a.addr)
    assert w < horizon and a._seq - horizon == 0  # empty servable suffix

    time.sleep(0.02)
    before = b.stats()["catchup"]["chunks_applied"]
    while b._applied_seq.get(a.addr) != a._seq:
        a.sync_to_all()
        moved = _drive(transport, [a, b], rounds=30)
        assert moved, "no progress toward convergence"
        time.sleep(0.02)
    assert b.read() == a.read()
    assert b.stats()["catchup"]["chunks_applied"] == before, (
        "an empty suffix must not open a catch-up stream"
    )


def test_catchup_suffix_ratio_knob_gates_the_stream(tmp_path):
    """The same dominant-suffix lag with an extreme ratio knob goes
    straight to the walk — the mode decision is the knob, not a
    hardcode."""
    transport = LocalTransport()
    clock = LogicalClock()
    a, b = _lagged_pair(
        tmp_path, transport, clock,
        membership_compaction=True, membership_retain=32,
    )
    b.catchup_suffix_ratio = 10_000.0
    a.checkpoint()
    assert b._applied_seq.get(a.addr) < a.stats()["wal"]["horizon"]

    time.sleep(0.02)
    before = b.stats()["catchup"]["chunks_applied"]
    while b._applied_seq.get(a.addr) != a._seq:
        a.sync_to_all()
        moved = _drive(transport, [a, b], rounds=30)
        assert moved, "no progress toward convergence"
        time.sleep(0.02)
    assert b.read() == a.read()
    assert b.stats()["catchup"]["chunks_applied"] == before
