"""Native fasthash must agree bit-for-bit with the Python hashlib path —
mixed native/non-native clusters depend on it."""

import os
import random

import numpy as np
import pytest

from delta_crdt_ex_tpu import native
from delta_crdt_ex_tpu.utils.hashing import (
    canonical_bytes,
    key_hash64,
    value_hash32,
)


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
def test_hash64_matches_hashlib():
    rng = random.Random(7)
    terms = [
        "",
        "x",
        b"\x00" * 128,  # exactly one block
        b"\x01" * 129,  # block boundary + 1
        ("tuple", 1, 2.5, None),
        list(range(50)),
        {"k": {"nested": [1, 2, 3]}},
    ] + [rng.randbytes(rng.randint(0, 1000)) for _ in range(200)]
    blobs = [canonical_bytes(t) for t in terms]
    got = native.hash64_batch(blobs)
    want = np.array([key_hash64(t) for t in terms], np.uint64)
    assert (got == want).all()


@pytest.mark.skipif(not native.available(), reason="no C++ toolchain")
def test_hash32_matches_hashlib():
    terms = ["a", 1, None, b"bytes", (1, 2), {"x": 1}] + [f"v{i}" for i in range(100)]
    blobs = [canonical_bytes(t) for t in terms]
    got = native.hash32_batch(blobs)
    want = np.array([value_hash32(t) for t in terms], np.uint32)
    assert (got == want).all()


def test_batch_helpers_accept_empty():
    assert native.hash64_batch([]) is None
