"""Batched fleet egress (ISSUE 10): vmapped sync ticks and multi-member
wire frames must be OBSERVABLY IDENTICAL to the per-member loop —
bit-for-bit wire bytes, opener streams, and cursor state — while
launching one extraction/tree dispatch per shape bucket instead of one
per member, and (over TCP) shipping many members' slices in one
``FleetFrameMsg`` frame.

Covers the pure-kernel lane parity (vmapped tree build + extraction ==
solo, BOTH backends), seeded randomized fleet-vs-solo parity on full
bidirectional gossip (state bits, wire streams, ack bookkeeping), the
``FleetFrameMsg`` TCP codec roundtrip + mixed-version per-message
fallback, the ragged-bucket fallback-to-solo legs, and the
``_own_ctr_cache`` fleet-commit invalidation regression.
"""

import pickle
import time

import numpy as np
import jax.numpy as jnp
import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime import sync as sync_proto, transition
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.fleet import Fleet, _lane_slice
from delta_crdt_ex_tpu.runtime.replica import (
    _LaneLevels,
    _LazyLevels,
    _StackedLevels,
)
from delta_crdt_ex_tpu.runtime.transport import LocalTransport


def _assert_state_bit_equal(r1, r2, ctx=""):
    import jax

    l1, _ = jax.tree.flatten(r1.state)
    l2, _ = jax.tree.flatten(r2.state)
    assert len(l1) == len(l2), ctx
    for a, b in zip(l1, l2):
        assert np.array_equal(np.asarray(a), np.asarray(b)), ctx


def _mk(transport, store="binned", **kw):
    kw.setdefault("capacity", 256)
    kw.setdefault("tree_depth", 4)
    # in-flight sync slots must not expire mid-test: the parity drives
    # clear them explicitly, and a wall-clock expiry landing between a
    # fleet tick and its solo twin's loop (slow CI) would open a walk
    # on one side only
    kw.setdefault("sync_timeout", 600.0)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=LogicalClock(),
        store=store, **kw,
    )


def _norm(msg):
    """Address-free canonical form of one outbound sync message (the
    twins differ only in names/addresses)."""
    if isinstance(msg, sync_proto.EntriesMsg):
        return (
            "entries",
            np.asarray(msg.buckets).tolist(),
            {c: np.asarray(v).tolist() for c, v in msg.arrays.items()},
            sorted(map(repr, msg.payloads.items())),
        )
    if isinstance(msg, sync_proto.DiffMsg):
        return (
            "diff", msg.level, np.asarray(msg.idx).tolist(),
            [np.asarray(b).tolist() for b in msg.blocks], msg.seq,
            msg.log_horizon,
        )
    if isinstance(msg, sync_proto.AckMsg):
        return ("ack",)
    if isinstance(msg, sync_proto.GetDiffMsg):
        return ("get_diff", np.asarray(msg.buckets).tolist())
    return (type(msg).__name__,)


def _wire_bytes(msg):
    """Pickled size of the address-free message body — the wire-byte
    parity quantity (names/addresses differ between the twins)."""
    if isinstance(msg, sync_proto.EntriesMsg):
        return len(pickle.dumps(
            (np.asarray(msg.buckets),
             {c: np.asarray(v) for c, v in msg.arrays.items()},
             msg.payloads),
            protocol=4,
        ))
    if isinstance(msg, sync_proto.DiffMsg):
        return len(pickle.dumps(
            (msg.level, msg.idx, msg.blocks, msg.seq, msg.log_horizon),
            protocol=4,
        ))
    return 0


# ---------------------------------------------------------------------------
# vmapped egress kernels: lane k == solo dispatch, bit-for-bit


def test_fleet_tree_from_leaves_lane_parity():
    rng = np.random.default_rng(7)
    leaves = rng.integers(0, 2**32, size=(5, 16), dtype=np.uint32)
    stacked = transition.jit_fleet_tree_from_leaves(jnp.asarray(leaves))
    for lane in range(5):
        solo = transition.binned_ops.tree_from_leaves(jnp.asarray(leaves[lane]))
        assert len(stacked) == len(solo)
        for j, lvl in enumerate(solo):
            assert np.array_equal(np.asarray(stacked[j][lane]), np.asarray(lvl))


def test_stacked_levels_lane_view_matches_lazy_levels():
    rng = np.random.default_rng(8)
    leaves = rng.integers(0, 2**32, size=(3, 16), dtype=np.uint32)
    stacked = _StackedLevels(
        transition.jit_fleet_tree_from_leaves(jnp.asarray(leaves))
    )
    stacked.prefetch(2)
    for lane in range(3):
        solo = _LazyLevels(
            transition.binned_ops.tree_from_leaves(jnp.asarray(leaves[lane]))
        )
        view = _LaneLevels(stacked, lane)
        assert len(view) == len(solo)
        for j in range(len(solo)):
            assert np.array_equal(view[j], solo[j])


@pytest.mark.parametrize("store", ["binned", "hash"])
def test_fleet_extraction_lane_parity(store):
    """Batched interval/full-row extraction == the member's own solo
    extraction bit-for-bit, both backends, including the hash store's
    per-member dense-tier trim (``_lane_slice``)."""
    transport = LocalTransport()
    n = 4
    reps = [
        _mk(transport, store=store, name=f"x{store}{i}", node_id=50 + i)
        for i in range(n)
    ]
    for i, r in enumerate(reps):
        for j in range(1 + 3 * i):  # ragged content: distinct dense tiers
            r.mutate("add", [i * 100 + j, j])
    model = reps[0].model
    states = [r.state for r in reps]
    stacked = transition.stack_states(states)

    u = 16
    rows = np.full((n, u), -1, np.int32)
    lo = np.zeros((n, u), np.uint32)
    for i, r in enumerate(reps):
        own = np.asarray(r.state.ctx_max[:, r.self_slot])
        pend = np.nonzero(own)[0][:u]
        rows[i, : len(pend)] = pend
    slots = np.asarray([r.self_slot for r in reps], np.int32)
    gids = np.asarray([r.node_id for r in reps], np.uint64)

    sl, tiers = model.fleet_extract_own_delta(
        stacked, jnp.asarray(rows), jnp.asarray(slots), jnp.asarray(gids),
        jnp.asarray(lo),
    )
    import jax

    host = jax.device_get(sl)
    for i, r in enumerate(reps):
        solo = r.model.extract_own_delta(
            r.state, jnp.asarray(rows[i]), jnp.int32(r.self_slot),
            jnp.uint64(r.node_id), jnp.asarray(lo[i]),
        )
        lane = _lane_slice(
            host, i, rows[i], None if tiers is None else tiers[i]
        )
        for c in type(solo)._fields:
            sv = np.asarray(getattr(solo, c))
            lv = np.asarray(getattr(lane, c))
            assert sv.shape == lv.shape, (store, i, c)
            assert np.array_equal(sv, lv), (store, i, c)

    sl2, tiers2 = model.fleet_extract_rows(stacked, jnp.asarray(rows))
    host2 = jax.device_get(sl2)
    for i, r in enumerate(reps):
        solo = r.model.extract_rows(r.state, jnp.asarray(rows[i]))
        lane = _lane_slice(
            host2, i, rows[i], None if tiers2 is None else tiers2[i]
        )
        for c in type(solo)._fields:
            assert np.array_equal(
                np.asarray(getattr(solo, c)), np.asarray(getattr(lane, c))
            ), (store, i, c)


def test_fleet_own_ctr_columns():
    rng = np.random.default_rng(9)
    cm = rng.integers(0, 1000, size=(3, 16, 8)).astype(np.uint32)
    slots = np.asarray([0, 3, 7], np.int32)
    cols = np.asarray(
        transition.jit_fleet_own_ctr_columns(jnp.asarray(cm), jnp.asarray(slots))
    )
    for k in range(3):
        assert np.array_equal(cols[k], cm[k, :, slots[k]])


# ---------------------------------------------------------------------------
# runtime egress parity: batched sync ticks == per-member loop


def _twin_universes(store, n, tree_depth=4):
    transport = LocalTransport()
    fleet_members = [
        _mk(transport, store=store, name=f"ef{store}{n}_{i}", node_id=100 + i,
            tree_depth=tree_depth)
        for i in range(n)
    ]
    solos = [
        _mk(transport, store=store, name=f"eo{store}{n}_{i}", node_id=100 + i,
            tree_depth=tree_depth)
        for i in range(n)
    ]
    frecv = [
        _mk(transport, store=store, name=f"efr{store}{n}_{i}", node_id=900 + i,
            tree_depth=tree_depth)
        for i in range(n)
    ]
    orecv = [
        _mk(transport, store=store, name=f"eor{store}{n}_{i}", node_id=900 + i,
            tree_depth=tree_depth)
        for i in range(n)
    ]
    for i in range(n):
        fleet_members[i].set_neighbours([frecv[i]])
        solos[i].set_neighbours([orecv[i]])
    return transport, fleet_members, solos, frecv, orecv


@pytest.mark.parametrize("store", ["binned", "hash"])
def test_egress_streams_bit_parity(store):
    """One-directional egress: the receivers' drained message streams —
    eager-delta pushes, full-row (remove) pushes, walk openers — are
    canonically identical and byte-for-byte equal in wire size."""
    transport, fm, sm, frecv, orecv = _twin_universes(store, 4)
    fleet = Fleet(fm)
    fleet_bytes = solo_bytes = 0
    for rnd in range(3):
        for i in range(4):
            for j in range(2 + i):
                k = rnd * 1000 + i * 10 + j
                fm[i].mutate("add", [k, k])
                sm[i].mutate("add", [k, k])
            if rnd == 1 and i % 2 == 0:
                fm[i].mutate("remove", [rnd * 1000 + i * 10])
                sm[i].mutate("remove", [rnd * 1000 + i * 10])
        fleet.sync_tick()
        for r in sm:
            r.sync_to_all()
        for i in range(4):
            a_msgs = transport.drain(frecv[i].addr)
            b_msgs = transport.drain(orecv[i].addr)
            assert len(a_msgs) == len(b_msgs) > 0, (rnd, i)
            for a, b in zip(a_msgs, b_msgs):
                assert _norm(a) == _norm(b), (rnd, i, type(a).__name__)
                fleet_bytes += _wire_bytes(a)
                solo_bytes += _wire_bytes(b)
            # clear the in-flight slots identically so every round opens
            fm[i]._outstanding.clear()
            fm[i]._sync_open_seq.clear()
            sm[i]._outstanding.clear()
            sm[i]._sync_open_seq.clear()
        for i in range(4):
            for va, vb in zip(
                fm[i]._push_cursor.values(), sm[i]._push_cursor.values()
            ):
                assert np.array_equal(va, vb), (rnd, i)
            assert list(fm[i]._rm_cursor.values()) == list(
                sm[i]._rm_cursor.values()
            ), (rnd, i)
    assert fleet_bytes == solo_bytes > 0
    eg = fleet.stats()["egress"]
    assert eg["ticks"] == 3
    assert eg["dispatches"] >= 1
    assert eg["batched_jobs"] >= 1
    assert eg["trees_batched"] >= 4


@pytest.mark.parametrize("store", ["binned", "hash"])
def test_egress_randomized_gossip_parity(store):
    """Seeded randomized bidirectional gossip: fleet members sync via
    batched ticks, solos via sync_to_all; receivers handle everything
    (walk replies, repairs, acks). End state must be bit-identical,
    receivers' inbound wire streams canonically equal, and the ack
    bookkeeping (outstanding slots cleared by AckMsg) must match."""
    rng = np.random.default_rng(1234 if store == "binned" else 4321)
    transport, fm, sm, frecv, orecv = _twin_universes(store, 3)
    fleet = Fleet(fm)
    f_streams = [[] for _ in range(3)]
    o_streams = [[] for _ in range(3)]
    for rnd in range(6):
        for i in range(3):
            for _ in range(int(rng.integers(0, 4))):
                k = int(rng.integers(0, 40))
                v = int(rng.integers(0, 1000))
                fm[i].mutate("add", [k, v])
                sm[i].mutate("add", [k, v])
            if rng.random() < 0.3:
                k = int(rng.integers(0, 40))
                fm[i].mutate("remove", [k])
                sm[i].mutate("remove", [k])
        fleet.sync_tick()
        for r in sm:
            r.sync_to_all()
        # receivers process their mailboxes (generating acks/repairs),
        # members process the back-traffic
        for _ in range(4):
            moved = 0
            for i in range(3):
                for m in transport.drain(frecv[i].addr):
                    f_streams[i].append(_norm(m))
                    frecv[i].handle(m)
                    moved += 1
                for m in transport.drain(orecv[i].addr):
                    o_streams[i].append(_norm(m))
                    orecv[i].handle(m)
                    moved += 1
            moved += fleet.tick()
            for r in sm:
                moved += r.process_pending()
            if not moved:
                break
    assert f_streams == o_streams
    for i in range(3):
        assert fm[i]._seq == sm[i]._seq
        _assert_state_bit_equal(fm[i], sm[i])
        _assert_state_bit_equal(frecv[i], orecv[i])
        assert fm[i].read() == sm[i].read()
        assert len(fm[i]._outstanding) == len(sm[i]._outstanding)


def test_ragged_bucket_falls_back_to_solo():
    """Members with incompatible shapes (different tree depths) cannot
    share a bucket: singleton buckets extract solo, still bit-identical
    to the per-member loop."""
    transport = LocalTransport()
    fa = _mk(transport, name="rg_f0", node_id=100, tree_depth=4)
    fb = _mk(transport, name="rg_f1", node_id=101, tree_depth=5)
    oa = _mk(transport, name="rg_o0", node_id=100, tree_depth=4)
    ob = _mk(transport, name="rg_o1", node_id=101, tree_depth=5)
    ra = _mk(transport, name="rg_ra", node_id=900, tree_depth=4)
    rb = _mk(transport, name="rg_rb", node_id=901, tree_depth=5)
    sa = _mk(transport, name="rg_sa", node_id=900, tree_depth=4)
    sb = _mk(transport, name="rg_sb", node_id=901, tree_depth=5)
    fa.set_neighbours([ra])
    fb.set_neighbours([rb])
    oa.set_neighbours([sa])
    ob.set_neighbours([sb])
    fleet = Fleet([fa, fb])
    for rep in (fa, fb, oa, ob):
        rep.mutate("add", [1, 1])
        rep.mutate("add", [2, 2])
    fleet.sync_tick()
    oa.sync_to_all()
    ob.sync_to_all()
    for recv, srecv in ((ra, sa), (rb, sb)):
        am = transport.drain(recv.addr)
        bm = transport.drain(srecv.addr)
        assert len(am) == len(bm) > 0
        for a, b in zip(am, bm):
            assert _norm(a) == _norm(b)
    eg = fleet.stats()["egress"]
    assert eg["solo_jobs"] >= 2  # both members' jobs were singleton buckets
    assert eg["dispatches"] == 0


def test_single_member_tick_uses_solo_path():
    transport = LocalTransport()
    f = _mk(transport, name="solo_f", node_id=100)
    r = _mk(transport, name="solo_r", node_id=900)
    f.set_neighbours([r])
    fleet = Fleet([f])
    f.mutate("add", [1, 1])
    fleet.sync_tick()
    eg = fleet.stats()["egress"]
    assert eg["solo_members"] == 1
    assert eg["dispatches"] == 0
    kinds = [type(m).__name__ for m in transport.drain(r.addr)]
    assert "EntriesMsg" in kinds and "DiffMsg" in kinds


def test_own_ctr_cache_invalidated_on_fleet_commit():
    """Regression (ISSUE 10 satellite): a batched fleet commit must
    drop the member's ``_own_ctr_cache`` — the adopted lane's ctx_max
    can carry own-gid counters the cache predates, and a stale cache
    would plan a stale cursor slice on the next batched egress."""
    transport = LocalTransport()
    senders = [_mk(transport, name=f"occ_s{i}", node_id=10 + i) for i in range(2)]
    members = [_mk(transport, name=f"occ_f{i}", node_id=100 + i) for i in range(2)]
    for i in range(2):
        senders[i].set_neighbours([members[i]])
    fleet = Fleet(members)
    fleet.sync_tick()  # builds every member's cursor-source cache
    for m in members:
        assert m._own_ctr_cache is not None
    for i, s in enumerate(senders):
        s.mutate("add", [i, i])
        s.sync_to_all()
    # keep only the delta pushes so the tick is one batched dispatch
    for m in members:
        kept = [
            x
            for x in transport.drain(m.addr)
            if isinstance(x, sync_proto.EntriesMsg)
        ]
        assert kept
        for x in kept:
            transport.send(m.addr, x)
    fleet.tick()
    st = fleet.stats()
    assert st["dispatches"] >= 1 and st["fallbacks"]["singleton"] == 0
    for m in members:
        assert m._fleet_dispatches >= 1
        assert m._own_ctr_cache is None  # the regression pin


# ---------------------------------------------------------------------------
# FleetFrameMsg: TCP codec roundtrip + fallbacks


def _tcp_pair():
    from delta_crdt_ex_tpu.runtime.tcp_transport import TcpTransport

    return TcpTransport(), TcpTransport()


def _await_hello(transport, endpoint, timeout=5.0):
    """Wait until the pooled connection's HELLO negotiation lands."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        with transport._lock:
            conn = transport._conns.get(endpoint)
        if conn is not None and conn.accepts_f:
            return True
        time.sleep(0.02)
    return False


def test_fleet_frame_tcp_roundtrip():
    """Batched egress over TCP: one FleetFrameMsg per endpoint per tick
    carries every member's slices + openers; the peer decodes it back
    to per-member deliveries and converges."""
    ta, tb = _tcp_pair()
    try:
        members = [
            _mk(ta, name=f"tf_m{i}", node_id=100 + i) for i in range(3)
        ]
        peers = [_mk(tb, name=f"tf_p{i}", node_id=900 + i) for i in range(3)]
        for i in range(3):
            members[i].set_neighbours([(f"tf_p{i}", tb.endpoint)])
        fleet = Fleet(members)
        for i in range(3):
            members[i].mutate("add", [i, i])
        fleet.sync_tick()  # primes the connection (HELLO in flight)
        assert _await_hello(ta, tb.endpoint)
        for i in range(3):
            members[i].mutate("add", [100 + i, 100 + i])
        fleet.sync_tick()
        deadline = time.monotonic() + 5.0
        done = False
        while time.monotonic() < deadline and not done:
            for i in range(3):
                for m in tb.drain(f"tf_p{i}"):
                    peers[i].handle(m)
            done = all(
                peers[i].read().get(i) == i
                and peers[i].read().get(100 + i) == 100 + i
                for i in range(3)
            )
            time.sleep(0.02)
        assert done, "peers did not converge over fleet frames"
        eg = fleet.stats()["egress"]
        assert eg["frames"] >= 1
        assert eg["members_per_frame"] > 1.0  # many members, one frame
    finally:
        ta.close()
        tb.close()


def test_fleet_frame_mixed_version_fallback(monkeypatch):
    """A peer that never advertised _FEAT_FLEET gets plain per-member
    frames — mixed-version clusters converge message-for-message."""
    from delta_crdt_ex_tpu.runtime import tcp_transport as tt

    monkeypatch.setattr(
        tt, "_OUR_FEATURES", tt._FEAT_MSGZ | tt._FEAT_MSGB
    )  # the HELLO reply no longer claims fleet frames (a legacy build)
    ta, tb = _tcp_pair()
    try:
        members = [
            _mk(ta, name=f"mv_m{i}", node_id=100 + i) for i in range(2)
        ]
        peers = [_mk(tb, name=f"mv_p{i}", node_id=900 + i) for i in range(2)]
        for i in range(2):
            members[i].set_neighbours([(f"mv_p{i}", tb.endpoint)])
        fleet = Fleet(members)
        for rnd in range(2):
            for i in range(2):
                members[i].mutate("add", [rnd * 10 + i, i])
            fleet.sync_tick()
            time.sleep(0.3)
        deadline = time.monotonic() + 5.0
        done = False
        while time.monotonic() < deadline and not done:
            for i in range(2):
                for m in tb.drain(f"mv_p{i}"):
                    peers[i].handle(m)
            done = all(
                peers[i].read().get(i) == i
                and peers[i].read().get(10 + i) == i
                for i in range(2)
            )
            time.sleep(0.02)
        assert done, "legacy peers did not converge per-message"
        assert fleet.stats()["egress"]["frames"] == 0
    finally:
        ta.close()
        tb.close()


def test_send_fleet_frame_downgrades_per_member():
    """``send_fleet_frame`` against a connection that renegotiated down
    (accepts_f False) unbundles into per-member sends."""
    ta, tb = _tcp_pair()
    try:
        sink = _mk(tb, name="dg_p", node_id=900)
        ta.send(("dg_p", tb.endpoint), sync_proto.AckMsg(clear_addr="x"))
        with ta._lock:
            conn = ta._conns[tb.endpoint]
        conn.accepts_f = False  # simulate a renegotiated-down peer
        ok = ta.send_fleet_frame(
            tb.endpoint,
            [(("dg_p", tb.endpoint), sync_proto.AckMsg(clear_addr="y"))],
        )
        # the messages flow per-member, but no envelope rode the wire —
        # the False return keeps frame-aggregation counters honest
        assert ok is False
        deadline = time.monotonic() + 5.0
        got = []
        while time.monotonic() < deadline and len(got) < 2:
            got += tb.drain("dg_p")
            time.sleep(0.02)
        assert sorted(m.clear_addr for m in got) == ["x", "y"]
        assert sink is not None
    finally:
        ta.close()
        tb.close()


def test_fleet_frame_replica_ladder_fallback():
    """A FleetFrameMsg delivered whole to a replica mailbox (a
    transport without frame-level decode) fans out through the
    dispatch-ladder arm: own entries dispatch, others forward."""
    transport = LocalTransport()
    a = _mk(transport, name="lf_a", node_id=100)
    b = _mk(transport, name="lf_b", node_id=101)
    w = _mk(transport, name="lf_w", node_id=102)
    for i in range(2):
        w.mutate("add", [i, i])
    own = np.asarray(w.state.ctx_max[:, w.self_slot])
    rows = np.nonzero(own)[0]
    entries = []
    for to in (a.addr, b.addr):
        arrays, payloads = w._extract_rows_wire(rows, None)
        entries.append((
            to,
            sync_proto.EntriesMsg(
                originator=w.addr, frm=w.addr, to=to,
                buckets=rows.astype(np.int64), arrays=arrays,
                payloads=payloads,
            ),
        ))
    frame = sync_proto.FleetFrameMsg(frm=w.addr, entries=entries)
    a.handle(frame)  # a's entry dispatches locally, b's forwards
    for m in transport.drain(b.addr):
        b.handle(m)
    assert a.read() == {0: 0, 1: 1}
    assert b.read() == {0: 0, 1: 1}


def test_egress_observability_surface():
    """FLEET_EGRESS rides the PR 9 plane: the bridge row folds the
    event into ``crdt_fleet_egress_*`` counters/histograms, the polled
    gauges (members per frame, frames per tick, bucket occupancy)
    render at scrape time, and a stopped fleet's gauges disappear."""
    from delta_crdt_ex_tpu.runtime.metrics import Observability

    transport = LocalTransport()
    plane = Observability()
    members = [
        _mk(transport, name=f"obsf{i}", node_id=100 + i) for i in range(2)
    ]
    recv = [_mk(transport, name=f"obsr{i}", node_id=900 + i) for i in range(2)]
    for i in range(2):
        members[i].set_neighbours([recv[i]])
    fleet = Fleet(members, obs=plane)
    try:
        for i in range(2):
            members[i].mutate("add", [i, i])
        fleet.sync_tick()
        out = plane.registry.render()
        assert "crdt_fleet_egress_ticks_total" in out
        assert "crdt_fleet_egress_members" in out
        assert "crdt_fleet_egress_members_per_frame" in out
        assert "crdt_fleet_egress_frames_per_tick" in out
        assert "crdt_fleet_egress_bucket_occupancy" in out
        eg = fleet.stats()["egress"]
        assert eg["ticks"] >= 1
    finally:
        fleet.stop()
        assert "crdt_fleet_egress_members_per_frame{" not in plane.registry.render()
        plane.close()


def test_fleet_frame_wire_manifest_locked():
    """FleetFrameMsg is in the checked-in protocol manifest (the
    reviewed WIRE005 bump this PR shipped)."""
    import json
    from pathlib import Path

    manifest = json.loads(
        (Path(__file__).resolve().parent.parent / "tools" / "crdtlint"
         / "protocol_manifest.json").read_text()
    )
    msgs = manifest["packages"]["delta_crdt_ex_tpu"]["messages"]
    assert "FleetFrameMsg" in msgs
    assert [f for f, _t in msgs["FleetFrameMsg"]["fields"]] == [
        "frm", "entries",
    ]
