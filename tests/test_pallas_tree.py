"""Pallas digest-tree roots kernel vs the XLA reference implementation.

Runs the kernel in interpreter mode on CPU (Pallas TPU lowering needs
real hardware); bit-for-bit equality with ``ops.binned.tree_from_leaves``
is the contract — either implementation may serve the sync walk.
"""

import numpy as np
import jax.numpy as jnp

from delta_crdt_ex_tpu.ops.binned import tree_from_leaves
from delta_crdt_ex_tpu.ops.pallas_tree import batched_roots_pallas


def test_pallas_roots_matches_xla():
    """The roll-fold roots kernel (the one that lowers on real TPUs —
    8-row blocks, no reshapes) agrees with the XLA fold, including the
    batch-padding path (N not a multiple of 8)."""
    rng = np.random.default_rng(1)
    for n, L in [(3, 256), (8, 512), (11, 128)]:
        leaves = jnp.asarray(rng.integers(0, 1 << 32, size=(n, L), dtype=np.uint32))
        got = batched_roots_pallas(leaves, interpret=True)
        want = [int(tree_from_leaves(leaves[i])[0][0]) for i in range(n)]
        assert [int(x) for x in got] == want


def test_pallas_roots_distinguish_sibling_order():
    """The combine is position-dependent: swapping two sibling leaves
    must change the root (a symmetric combine would miss reorderings)."""
    a = jnp.zeros((1, 128), jnp.uint32).at[0, 0].set(7)
    b = jnp.zeros((1, 128), jnp.uint32).at[0, 1].set(7)
    ra = batched_roots_pallas(a, interpret=True)
    rb = batched_roots_pallas(b, interpret=True)
    assert int(ra[0]) != int(rb[0])
