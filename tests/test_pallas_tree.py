"""Pallas digest-tree kernel vs the XLA reference implementation.

Runs the kernel in interpreter mode on CPU (Pallas TPU lowering needs
real hardware); bit-for-bit equality with ``ops.binned.tree_from_leaves``
is the contract — either implementation may serve the sync walk.
"""

import numpy as np
import jax.numpy as jnp

from delta_crdt_ex_tpu.ops.binned import tree_from_leaves
from delta_crdt_ex_tpu.ops.pallas_tree import (
    batched_roots_pallas,
    tree_from_leaves_pallas,
    unpack_levels,
)


def test_pallas_tree_matches_xla_levels():
    rng = np.random.default_rng(0)
    L = 256
    leaves = jnp.asarray(rng.integers(0, 1 << 32, size=(3, L), dtype=np.uint32))
    packed = tree_from_leaves_pallas(leaves, interpret=True)
    depth = L.bit_length() - 1
    for i in range(3):
        want = tree_from_leaves(leaves[i])  # root first, leaf last
        got = unpack_levels(packed[i], depth) + [leaves[i]]
        assert len(got) == len(want)
        for lw, lg in zip(want, got):
            assert np.array_equal(np.asarray(lw), np.asarray(lg))


def test_pallas_roots_matches_xla():
    """The roll-fold roots kernel (the one that lowers on real TPUs —
    8-row blocks, no reshapes) agrees with the XLA fold, including the
    batch-padding path (N not a multiple of 8)."""
    rng = np.random.default_rng(1)
    for n, L in [(3, 256), (8, 512), (11, 128)]:
        leaves = jnp.asarray(rng.integers(0, 1 << 32, size=(n, L), dtype=np.uint32))
        got = batched_roots_pallas(leaves, interpret=True)
        want = [int(tree_from_leaves(leaves[i])[0][0]) for i in range(n)]
        assert [int(x) for x in got] == want


def test_pallas_tree_distinguishes_sibling_order():
    a = jnp.zeros((1, 64), jnp.uint32).at[0, 0].set(7)
    b = jnp.zeros((1, 64), jnp.uint32).at[0, 1].set(7)
    pa = tree_from_leaves_pallas(a, interpret=True)
    pb = tree_from_leaves_pallas(b, interpret=True)
    assert int(pa[0, 1]) != int(pb[0, 1])  # roots differ
