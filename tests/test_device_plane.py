"""Device data plane for the anti-entropy runtime (SURVEY §5.8 hybrid).

When peer replicas pin their states to devices of one mesh, sync slices
travel device↔device (``jax.device_put`` onto the receiver's device —
ICI on real hardware) while the control plane (messages, payload dicts)
stays on host. Unpinned or cross-host peers keep the host plane. Runs
on the 8-virtual-CPU-device mesh from conftest.
"""

import jax
import numpy as np

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime import sync as sync_proto
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from tests.conftest import converge


def _mk(transport, clock, **opts):
    opts.setdefault("capacity", 64)
    opts.setdefault("tree_depth", 6)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock, **opts
    )


def _capture_entries(transport):
    captured = []
    orig = transport.send

    def send(addr, msg):
        if isinstance(msg, sync_proto.EntriesMsg):
            captured.append(msg)
        return orig(addr, msg)

    transport.send = send
    return captured


def test_pinned_peers_sync_device_to_device(transport, shared_clock):
    d0, d1 = jax.devices()[:2]
    a = _mk(transport, shared_clock, device=d0)
    b = _mk(transport, shared_clock, device=d1)
    a.set_neighbours([b])
    captured = _capture_entries(transport)

    a.mutate("add", ["k", "v"])
    converge(transport, [a, b])
    assert b.read() == {"k": "v"}

    assert captured, "no entries message crossed the transport"
    for msg in captured:
        key_col = msg.arrays["key"]
        assert isinstance(key_col, jax.Array), type(key_col)
        assert key_col.devices() == {d1}, "slice not placed on receiver device"
        # row indices are control metadata and stay host-side
        assert isinstance(msg.arrays["rows"], np.ndarray)
    # the receiver's merged state lives where it was pinned
    assert b.state.leaf.devices() == {d1}


def test_unpinned_receiver_uses_host_plane(transport, shared_clock):
    d0 = jax.devices()[0]
    a = _mk(transport, shared_clock, device=d0)
    b = _mk(transport, shared_clock)  # unpinned
    a.set_neighbours([b])
    captured = _capture_entries(transport)

    a.mutate("add", ["k", "v"])
    converge(transport, [a, b])
    assert b.read() == {"k": "v"}
    assert captured
    for msg in captured:
        assert isinstance(msg.arrays["key"], np.ndarray), type(msg.arrays["key"])


def test_mixed_device_fanout_keeps_per_device_plane(transport, shared_clock):
    """A fanned-out push builds one message body PER DISTINCT DEVICE
    among equal-cursor neighbours (VERDICT r3 weak #4): differently
    pinned peers each receive a slice on their own device, unpinned
    peers get host numpy — in the same fan-out."""
    devs = jax.devices()
    a = _mk(transport, shared_clock, device=devs[0])
    b = _mk(transport, shared_clock, device=devs[1])
    c = _mk(transport, shared_clock, device=devs[2])
    d = _mk(transport, shared_clock)  # unpinned
    a.set_neighbours([b, c, d])
    captured = _capture_entries(transport)

    a.mutate("add", ["k", "v"])
    converge(transport, [a, b, c, d])
    assert b.read() == c.read() == d.read() == {"k": "v"}
    assert captured
    want_dev = {b.addr: devs[1], c.addr: devs[2]}
    seen_planes = set()
    for msg in captured:
        key_col = msg.arrays["key"]
        if msg.to in want_dev:
            assert isinstance(key_col, jax.Array), (msg.to, type(key_col))
            assert key_col.devices() == {want_dev[msg.to]}
            seen_planes.add("device")
        elif msg.to == d.addr:
            assert isinstance(key_col, np.ndarray), type(key_col)
            seen_planes.add("host")
    assert seen_planes == {"device", "host"}


def test_two_devices_four_replicas_all_device_plane(transport, shared_clock):
    """4 replicas across 2 devices: every peer in the fan-out receives a
    device-plane slice, grouped by its own pinned device."""
    d0, d1 = jax.devices()[:2]
    a = _mk(transport, shared_clock, device=d0)
    peers = [
        _mk(transport, shared_clock, device=dev) for dev in (d0, d1, d1)
    ]
    a.set_neighbours(peers)
    captured = _capture_entries(transport)

    a.mutate("add", ["k", "v"])
    converge(transport, [a] + peers)
    for p in peers:
        assert p.read() == {"k": "v"}
    assert captured
    want_dev = {p.addr: p.device for p in peers}
    covered = set()
    for msg in captured:
        if msg.to in want_dev:
            key_col = msg.arrays["key"]
            assert isinstance(key_col, jax.Array), (msg.to, type(key_col))
            assert key_col.devices() == {want_dev[msg.to]}
            covered.add(msg.to)
    assert covered == set(want_dev), "every pinned peer saw a device-plane slice"


def test_walk_repair_path_rides_device_plane(transport, shared_clock):
    """The digest-walk repair transfer (_send_entries via GetDiff) is
    single-target, so it uses the receiver's device even when eager
    pushes are off — the device plane is not an eager-push special."""
    d0, d1 = jax.devices()[:2]
    a = _mk(transport, shared_clock, device=d0, eager_deltas=False)
    b = _mk(transport, shared_clock, device=d1, eager_deltas=False)
    a.set_neighbours([b])
    captured = _capture_entries(transport)

    for i in range(8):
        a.mutate("add", [f"k{i}", i])
    converge(transport, [a, b])
    assert b.read() == {f"k{i}": i for i in range(8)}
    assert captured
    for msg in captured:
        assert isinstance(msg.arrays["key"], jax.Array)
        assert msg.arrays["key"].devices() == {d1}


def test_device_pinned_pair_full_protocol_soak(shared_clock):
    """Partition/heal + removes over pinned replicas: the device plane
    must not change any protocol outcome (same assertions as the host-
    plane replica tests)."""
    transport = LocalTransport()
    d0, d1 = jax.devices()[:2]
    a = _mk(transport, shared_clock, device=d0)
    b = _mk(transport, shared_clock, device=d1)
    a.set_neighbours([b])
    b.set_neighbours([a])

    for i in range(20):
        a.mutate("add", [f"k{i}", i])
    converge(transport, [a, b])
    assert b.read() == {f"k{i}": i for i in range(20)}

    # partition: b writes alone, then heal
    a.set_neighbours([])
    b.mutate("remove", ["k0"])
    b.mutate("add", ["k1", "overwritten"])
    a.set_neighbours([b])
    converge(transport, [a, b])
    want = {f"k{i}": i for i in range(2, 20)} | {"k1": "overwritten"}
    assert a.read() == want
    assert b.read() == want


def test_gap_repair_rides_device_plane(transport, shared_clock):
    """A lost eager push gaps the next interval; the get_diff repair's
    full-row transfer must also use the receiver's device — the repair
    path shares _send_entries with the walk."""
    from delta_crdt_ex_tpu.runtime import sync as sync_proto

    d0, d1 = jax.devices()[:2]
    c1 = _mk(transport, shared_clock, device=d0)
    c2 = _mk(transport, shared_clock, device=d1)
    c1.set_neighbours([c2])
    converge(transport, [c1, c2])

    c1.mutate("add", ["k", 1])
    c1.sync_to_all()
    transport.drain(c2.addr)  # push lost

    c1.mutate("add", ["k", 2])
    c1.sync_to_all()
    pushes = [m for m in transport.drain(c2.addr)
              if isinstance(m, sync_proto.EntriesMsg)]
    assert pushes
    c2.handle(pushes[0])  # gap -> repair request
    gets = [m for m in transport.drain(c1.addr)
            if isinstance(m, sync_proto.GetDiffMsg)]
    assert gets
    c1.handle(gets[0])
    ents = [m for m in transport.drain(c2.addr)
            if isinstance(m, sync_proto.EntriesMsg)]
    assert ents
    assert isinstance(ents[0].arrays["key"], jax.Array)
    assert ents[0].arrays["key"].devices() == {d1}
    c2.handle(ents[0])
    assert c2.read()["k"] == 2


def test_adversarial_schedule_device_pinned(shared_clock):
    """Seeded drop/dup/reorder over pinned replicas: the device plane
    must preserve convergence under every delivery schedule the host
    plane survives (idempotence/commutativity are plane-independent)."""
    from delta_crdt_ex_tpu.runtime.simnet import SimNetwork

    net = SimNetwork(seed=7, drop_rate=0.2, dup_rate=0.2)
    devs = jax.devices()
    rs = [_mk(net, shared_clock, device=devs[i]) for i in range(3)]
    for r in rs:
        r.set_neighbours([p for p in rs if p is not r])
    for i, r in enumerate(rs):
        for k in range(8):
            r.mutate("add", [f"k{i}-{k}", (i, k)])
    rs[0].mutate("remove", ["k0-0"])

    want = {f"k{i}-{k}": (i, k) for i in range(3) for k in range(8)}
    del want["k0-0"]
    for _ in range(60):
        for r in rs:
            r.sync_to_all()
        net.step()
        for r in rs:
            r.process_pending()
        if all(r.read() == want for r in rs):
            break
    assert all(r.read() == want for r in rs)


def test_rehydrate_repins_state_to_device(transport, shared_clock):
    """Crash-rehydrate must land the restored state back on the pinned
    device (the device_put runs after either init branch), preserving
    node-id continuity as usual."""
    from delta_crdt_ex_tpu.runtime.storage import MemoryStorage

    d1 = jax.devices()[1]
    st = MemoryStorage()
    a = _mk(transport, shared_clock, name="pinned", storage_module=st, device=d1)
    a.mutate("add", ["k", "v"])
    nid = a.node_id
    transport.unregister(a.name)  # crash without stop()

    b = _mk(transport, shared_clock, name="pinned", storage_module=st, device=d1)
    assert b.node_id == nid
    assert b.read() == {"k": "v"}
    assert b.state.leaf.devices() == {d1}


def test_sync_round_telemetry_reports_plane(transport, shared_clock):
    """SYNC_ROUND telemetry names the data plane that carried each
    merged slice — device for pinned peers, host otherwise."""
    from delta_crdt_ex_tpu.runtime import telemetry

    d0, d1 = jax.devices()[:2]
    planes = []
    rec = lambda event, meas, meta: planes.append(meta["plane"])
    telemetry.attach(telemetry.SYNC_ROUND, rec)
    try:
        a = _mk(transport, shared_clock, device=d0)
        b = _mk(transport, shared_clock, device=d1)
        c = _mk(transport, shared_clock)  # unpinned
        a.set_neighbours([b])
        a.mutate("add", ["k", 1])
        converge(transport, [a, b])
        assert "device" in planes and "host" not in planes, planes
        a.set_neighbours([c])
        a.mutate("add", ["k2", 2])
        converge(transport, [a, c])
        assert "host" in planes, planes
    finally:
        telemetry.detach(telemetry.SYNC_ROUND, rec)
