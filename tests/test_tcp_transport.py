"""Cross-"node" sync over the TCP control plane.

Two TcpTransports in one process model two hosts (the reference's
``{name, node}`` addressing, ``causal_crdt_test.exs:68-78``): replicas on
different transports sync through real sockets.
"""

import time

import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.tcp_transport import TcpTransport


@pytest.fixture
def two_nodes():
    ta = TcpTransport()
    tb = TcpTransport()
    yield ta, tb
    ta.close()
    tb.close()


def pump_both(ta, tb, rounds=10):
    for _ in range(rounds):
        ta.pump()
        tb.pump()
        time.sleep(0.01)  # socket delivery threads need a beat


def test_cross_node_bidirectional_sync(two_nodes, shared_clock):
    ta, tb = two_nodes
    a = start_link(AWLWWMap, threaded=False, transport=ta, clock=shared_clock,
                   name="a", capacity=64, tree_depth=6)
    b = start_link(AWLWWMap, threaded=False, transport=tb, clock=shared_clock,
                   name="b", capacity=64, tree_depth=6)
    # {name, node}-style remote addresses
    a.set_neighbours([tb.remote_addr("b")])
    b.set_neighbours([ta.remote_addr("a")])
    a.mutate("add", ["from_a", 1])
    b.mutate("add", ["from_b", 2])

    deadline = time.monotonic() + 20
    want = {"from_a": 1, "from_b": 2}
    while time.monotonic() < deadline:
        a.sync_to_all()
        b.sync_to_all()
        pump_both(ta, tb, rounds=5)
        if a.read() == want and b.read() == want:
            break
    assert a.read() == want
    assert b.read() == want


def test_remote_liveness_ping(two_nodes):
    ta, tb = two_nodes
    assert ta.alive(("anything", tb.endpoint))
    tb.close()
    time.sleep(0.05)
    assert not ta.alive(("anything", tb.endpoint))


def test_stalled_peer_does_not_block_other_edges(two_nodes):
    """One peer that accepts but never reads must not stall sends to
    anyone else: sendall runs on a per-connection sender thread, so the
    caller returns immediately and the healthy edge keeps flowing
    (failure isolation of the reference's per-process mailboxes)."""
    import socket as socketlib

    import numpy as np

    ta, tb = two_nodes

    srv = socketlib.socket(socketlib.AF_INET, socketlib.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    stalled_ep = srv.getsockname()
    try:
        # flood the stalled edge with frames far beyond any socket buffer
        big = np.zeros(4_000_000, np.uint8)
        t0 = time.monotonic()
        for _ in range(8):
            assert ta.send(("x", stalled_ep), big)
        assert time.monotonic() - t0 < 2.0, "send() blocked on a stalled socket"

        class Sink:
            pass

        tb.register("sink", Sink())
        assert ta.send(("sink", tb.endpoint), {"hello": 1})
        deadline = time.monotonic() + 5
        got = []
        while time.monotonic() < deadline and not got:
            got = tb.drain("sink")
            time.sleep(0.01)
        assert got == [{"hello": 1}], "healthy edge stalled behind the wedged peer"
    finally:
        srv.close()


def test_down_delivered_for_dead_remote_node(two_nodes, shared_clock):
    ta, tb = two_nodes
    ta.heartbeat_interval = 0.05
    a = start_link(AWLWWMap, threaded=False, transport=ta, clock=shared_clock,
                   name="a", capacity=64, tree_depth=6)
    b = start_link(AWLWWMap, threaded=False, transport=tb, clock=shared_clock,
                   name="b", capacity=64, tree_depth=6)
    a.set_neighbours([tb.remote_addr("b")])
    a.sync_to_all()
    assert tb.remote_addr("b") in a._monitors
    tb.close()  # node death
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and a._monitors:
        time.sleep(0.05)
        ta.pump()
    assert tb.remote_addr("b") not in a._monitors


def test_large_frames_compress_transparently():
    """Frames over _COMPRESS_MIN travel as zlib-compressed _MSGZ (padded
    sync arrays are mostly zeros — 10-50x on the wire) and arrive
    bit-identical; small frames stay raw."""
    import numpy as np

    from delta_crdt_ex_tpu.runtime import tcp_transport as T

    a = T.TcpTransport()
    b = T.TcpTransport()
    try:
        b.register("sink", None)
        big = {"arr": np.zeros((512, 64), np.uint64), "tag": "padded-slice"}
        assert a.send(("sink", b.endpoint), big)
        small = {"tag": "tiny"}
        assert a.send(("sink", b.endpoint), small)
        got = []
        deadline = time.time() + 10
        while len(got) < 2 and time.time() < deadline:
            got.extend(b.drain("sink"))
            time.sleep(0.02)
        assert len(got) == 2
        payloads = {m["tag"]: m for m in got}
        assert np.array_equal(payloads["padded-slice"]["arr"], big["arr"])
        # the compressed path was really taken for the big frame
        import pickle, zlib

        raw = pickle.dumps(("sink", big), protocol=4)
        assert len(raw) >= T._COMPRESS_MIN
        assert len(zlib.compress(raw, 1)) < 0.9 * len(raw)
    finally:
        a.close()
        b.close()


def test_hello_negotiates_array_side_channel():
    """The HELLO capability exchange flips the outbound connection to
    the arrays side-channel; a big array message sent after negotiation
    really travels as _MSGB (capability-gated — see MIGRATING.md
    rolling-upgrade note), and a peer negotiated down to MSGZ-only still
    gets compressed pickle4 frames."""
    import numpy as np

    from delta_crdt_ex_tpu.runtime import tcp_transport as T

    a = T.TcpTransport()
    b = T.TcpTransport()
    sent_kinds = []
    orig = T._send_frame

    def spy(sock, kind, payload):
        sent_kinds.append(kind)
        return orig(sock, kind, payload)

    def pump(tag, n):
        got = []
        deadline = time.time() + 10
        while len(got) < n and time.time() < deadline:
            got.extend(b.drain("sink"))
            time.sleep(0.02)
        assert any(m["tag"] == tag for m in got), got
        return got

    try:
        b.register("sink", None)
        # first send opens the connection and fires HELLO
        assert a.send(("sink", b.endpoint), {"tag": "opener"})
        conn = a._conns[b.endpoint]
        deadline = time.time() + 5
        while not (conn.accepts_z and conn.accepts_b) and time.time() < deadline:
            time.sleep(0.01)
        assert conn.accepts_z and conn.accepts_b, "HELLO never negotiated"

        T._send_frame = spy
        big = {"arr": np.zeros((1024, 128), np.uint64), "tag": "padded"}
        assert a.send(("sink", b.endpoint), big)
        got = pump("padded", 2)
        m = [g for g in got if g["tag"] == "padded"][0]
        assert np.array_equal(m["arr"], big["arr"])
        assert T._MSGB in sent_kinds, "negotiated peer should get _MSGB"

        # peer downgraded to MSGZ-only (e.g. older build): big frames
        # fall back to whole-frame compressed pickle4
        conn.accepts_b = False
        sent_kinds.clear()
        assert a.send(("sink", b.endpoint), dict(big, tag="padded2"))
        pump("padded2", 1)
        assert T._MSGZ in sent_kinds and T._MSGB not in sent_kinds
    finally:
        T._send_frame = orig
        a.close()
        b.close()


def test_msgb_encode_decode_roundtrip():
    """Wire-format unit: dense buffers ship raw (probe says
    incompressible), padded buffers ship zlib'd; both reconstruct
    bit-identically, as do in-band small objects."""
    import numpy as np

    from delta_crdt_ex_tpu.runtime import tcp_transport as T

    rng = np.random.default_rng(0)
    dense = rng.integers(0, 2**63, (512, 128), dtype=np.uint64)
    sparse = np.zeros((512, 128), np.uint64)
    sparse[:, 0] = 7
    obj = ("sink", {"dense": dense, "sparse": sparse, "meta": [1, "two", None]})
    payload = T._encode_msgb(obj)
    name, msg = T._decode_msgb(payload)
    assert name == "sink"
    assert np.array_equal(msg["dense"], dense)
    assert np.array_equal(msg["sparse"], sparse)
    assert msg["meta"] == [1, "two", None]
    # handler behaviour must not depend on the wire path: _MSGB arrays
    # are writable like the legacy pickle4 ones
    assert msg["dense"].flags.writeable and msg["sparse"].flags.writeable
    msg["dense"][0, 0] = 1  # must not raise
    # a dense-head/padded-tail buffer (wire tiers pad at the END) must
    # still be caught by the probe
    padded = np.zeros(1 << 16, np.uint64)
    padded[:2048] = rng.integers(0, 2**63, 2048, dtype=np.uint64)
    assert T._maybe_z_buffer(memoryview(padded))[0] == 1
    # the probe's two decisions really happened: the padded column
    # compressed (wire < raw), the dense one did not (wire ~ raw + head)
    raw_total = dense.nbytes + sparse.nbytes
    assert len(payload) < raw_total * 0.6, "sparse buffer did not compress"
    assert len(payload) > dense.nbytes, "dense buffer cannot compress below raw"
    # per-buffer decision unit
    assert T._maybe_z_buffer(memoryview(sparse.reshape(-1)))[0] == 1
    assert T._maybe_z_buffer(memoryview(dense.reshape(-1)))[0] == 0


def test_legacy_peer_never_receives_compressed_frames():
    """A peer that does not speak HELLO (an older build) must receive
    only plain _MSG frames — compression silently downgrading to frame
    drops on old peers was the round-2 advisor finding."""
    import socket as socketlib
    import struct
    import threading

    from delta_crdt_ex_tpu.runtime import tcp_transport as T

    srv = socketlib.socket(socketlib.AF_INET, socketlib.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    seen_kinds = []
    done = threading.Event()

    def legacy_server():
        conn, _ = srv.accept()
        with conn:
            # read frames like an old build: parse, never answer HELLO
            while len(seen_kinds) < 2:
                hdr = b""
                while len(hdr) < 4:
                    chunk = conn.recv(4 - len(hdr))
                    if not chunk:
                        return
                    hdr += chunk
                n = struct.unpack(">I", hdr)[0]
                body = b""
                while len(body) < n:
                    chunk = conn.recv(n - len(body))
                    if not chunk:
                        return
                    body += chunk
                seen_kinds.append(body[0])
            done.set()

    threading.Thread(target=legacy_server, daemon=True).start()
    a = T.TcpTransport()
    try:
        import numpy as np

        big = {"arr": np.zeros((512, 64), np.uint64)}
        assert a.send(("sink", srv.getsockname()), big)
        assert done.wait(5), f"legacy server saw only {seen_kinds}"
        assert seen_kinds[0] == T._HELLO
        assert seen_kinds[1] == T._MSG, "legacy peer must get plain _MSG"
    finally:
        a.close()
        srv.close()


def test_msgb_roundtrip_property():
    """Property: ANY picklable message structure (nested containers,
    mixed-dtype/shape/contiguity numpy arrays, scalars) survives the
    arrays side-channel bit-identically."""
    import numpy as np

    pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    from delta_crdt_ex_tpu.runtime import tcp_transport as T

    dtypes = st.sampled_from(["u8", "u4", "i8", "i4", "b1", "f8"])

    @st.composite
    def arrays(draw):
        dt = np.dtype(draw(dtypes))
        shape = draw(st.lists(st.integers(0, 64), min_size=1, max_size=3))
        seed = draw(st.integers(0, 2**31))
        rng = np.random.default_rng(seed)
        a = (rng.integers(0, 100, size=shape) % 2 if dt.kind == "b"
             else rng.integers(0, 1 << 30, size=shape)).astype(dt)
        if draw(st.booleans()) and a.ndim >= 2 and a.shape[0] > 1:
            a = a[::2]  # non-contiguous view: must fall back in-band
        return a

    leaves = st.one_of(
        arrays(),
        st.integers(-(2**40), 2**40),
        st.text(max_size=8),
        st.none(),
    )
    messages = st.recursive(
        leaves,
        lambda c: st.one_of(
            st.lists(c, max_size=4),
            st.dictionaries(st.text(max_size=4), c, max_size=4),
            st.tuples(c, c),
        ),
        max_leaves=12,
    )

    def eq(a, b):
        if isinstance(a, np.ndarray):
            return (
                isinstance(b, np.ndarray)
                and a.dtype == b.dtype
                and a.shape == b.shape
                and np.array_equal(a, b)
            )
        if isinstance(a, (list, tuple)):
            return (
                type(a) is type(b)
                and len(a) == len(b)
                and all(eq(x, y) for x, y in zip(a, b))
            )
        if isinstance(a, dict):
            return set(a) == set(b) and all(eq(a[k], b[k]) for k in a)
        return a == b and type(a) is type(b)

    @settings(max_examples=60, deadline=None)
    @given(messages)
    def check(msg):
        name, out = T._decode_msgb(T._encode_msgb(("sink", msg)))
        assert name == "sink"
        assert eq(out, msg)

    check()


def test_device_of_local_vs_remote():
    """device_of: same-process names report their replica's pinned
    device (device plane applies); remote addresses always report None
    (cross-host slices must serialise — host plane)."""
    import jax

    from delta_crdt_ex_tpu import AWLWWMap
    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.runtime import tcp_transport as T
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock

    ta, tb = T.TcpTransport(), T.TcpTransport()
    d0 = jax.devices()[0]
    try:
        a = start_link(AWLWWMap, threaded=False, transport=ta, name="a",
                       clock=LogicalClock(), capacity=64, tree_depth=6, device=d0)
        assert ta.device_of("a") is d0
        assert ta.device_of(("a", ta.endpoint)) is d0  # self-remote resolves local
        assert ta.device_of(("a", tb.endpoint)) is None  # genuinely remote
        assert tb.device_of(("a", ta.endpoint)) is None
        a.transport.unregister(a.name)
    finally:
        ta.close()
        tb.close()
