"""Cross-"node" sync over the TCP control plane.

Two TcpTransports in one process model two hosts (the reference's
``{name, node}`` addressing, ``causal_crdt_test.exs:68-78``): replicas on
different transports sync through real sockets.
"""

import time

import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.tcp_transport import TcpTransport


@pytest.fixture
def two_nodes():
    ta = TcpTransport()
    tb = TcpTransport()
    yield ta, tb
    ta.close()
    tb.close()


def pump_both(ta, tb, rounds=10):
    for _ in range(rounds):
        ta.pump()
        tb.pump()
        time.sleep(0.01)  # socket delivery threads need a beat


def test_cross_node_bidirectional_sync(two_nodes, shared_clock):
    ta, tb = two_nodes
    a = start_link(AWLWWMap, threaded=False, transport=ta, clock=shared_clock,
                   name="a", capacity=64, tree_depth=6)
    b = start_link(AWLWWMap, threaded=False, transport=tb, clock=shared_clock,
                   name="b", capacity=64, tree_depth=6)
    # {name, node}-style remote addresses
    a.set_neighbours([tb.remote_addr("b")])
    b.set_neighbours([ta.remote_addr("a")])
    a.mutate("add", ["from_a", 1])
    b.mutate("add", ["from_b", 2])

    deadline = time.monotonic() + 20
    want = {"from_a": 1, "from_b": 2}
    while time.monotonic() < deadline:
        a.sync_to_all()
        b.sync_to_all()
        pump_both(ta, tb, rounds=5)
        if a.read() == want and b.read() == want:
            break
    assert a.read() == want
    assert b.read() == want


def test_remote_liveness_ping(two_nodes):
    ta, tb = two_nodes
    assert ta.alive(("anything", tb.endpoint))
    tb.close()
    time.sleep(0.05)
    assert not ta.alive(("anything", tb.endpoint))


def test_stalled_peer_does_not_block_other_edges(two_nodes):
    """One peer that accepts but never reads must not stall sends to
    anyone else: sendall runs on a per-connection sender thread, so the
    caller returns immediately and the healthy edge keeps flowing
    (failure isolation of the reference's per-process mailboxes)."""
    import socket as socketlib

    import numpy as np

    ta, tb = two_nodes

    srv = socketlib.socket(socketlib.AF_INET, socketlib.SOCK_STREAM)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    stalled_ep = srv.getsockname()
    try:
        # flood the stalled edge with frames far beyond any socket buffer
        big = np.zeros(4_000_000, np.uint8)
        t0 = time.monotonic()
        for _ in range(8):
            assert ta.send(("x", stalled_ep), big)
        assert time.monotonic() - t0 < 2.0, "send() blocked on a stalled socket"

        class Sink:
            pass

        tb.register("sink", Sink())
        assert ta.send(("sink", tb.endpoint), {"hello": 1})
        deadline = time.monotonic() + 5
        got = []
        while time.monotonic() < deadline and not got:
            got = tb.drain("sink")
            time.sleep(0.01)
        assert got == [{"hello": 1}], "healthy edge stalled behind the wedged peer"
    finally:
        srv.close()


def test_down_delivered_for_dead_remote_node(two_nodes, shared_clock):
    ta, tb = two_nodes
    ta.heartbeat_interval = 0.05
    a = start_link(AWLWWMap, threaded=False, transport=ta, clock=shared_clock,
                   name="a", capacity=64, tree_depth=6)
    b = start_link(AWLWWMap, threaded=False, transport=tb, clock=shared_clock,
                   name="b", capacity=64, tree_depth=6)
    a.set_neighbours([tb.remote_addr("b")])
    a.sync_to_all()
    assert tb.remote_addr("b") in a._monitors
    tb.close()  # node death
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and a._monitors:
        time.sleep(0.05)
        ta.pump()
    assert tb.remote_addr("b") not in a._monitors


def test_large_frames_compress_transparently():
    """Frames over _COMPRESS_MIN travel as zlib-compressed _MSGZ (padded
    sync arrays are mostly zeros — 10-50x on the wire) and arrive
    bit-identical; small frames stay raw."""
    import numpy as np

    from delta_crdt_ex_tpu.runtime import tcp_transport as T

    a = T.TcpTransport()
    b = T.TcpTransport()
    try:
        b.register("sink", None)
        big = {"arr": np.zeros((512, 64), np.uint64), "tag": "padded-slice"}
        assert a.send(("sink", b.endpoint), big)
        small = {"tag": "tiny"}
        assert a.send(("sink", b.endpoint), small)
        got = []
        deadline = time.time() + 10
        while len(got) < 2 and time.time() < deadline:
            got.extend(b.drain("sink"))
            time.sleep(0.02)
        assert len(got) == 2
        payloads = {m["tag"]: m for m in got}
        assert np.array_equal(payloads["padded-slice"]["arr"], big["arr"])
        # the compressed path was really taken for the big frame
        import pickle, zlib

        raw = pickle.dumps(("sink", big), protocol=4)
        assert len(raw) >= T._COMPRESS_MIN
        assert len(zlib.compress(raw, 1)) < 0.9 * len(raw)
    finally:
        a.close()
        b.close()
