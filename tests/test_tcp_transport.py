"""Cross-"node" sync over the TCP control plane.

Two TcpTransports in one process model two hosts (the reference's
``{name, node}`` addressing, ``causal_crdt_test.exs:68-78``): replicas on
different transports sync through real sockets.
"""

import time

import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.tcp_transport import TcpTransport


@pytest.fixture
def two_nodes():
    ta = TcpTransport()
    tb = TcpTransport()
    yield ta, tb
    ta.close()
    tb.close()


def pump_both(ta, tb, rounds=10):
    for _ in range(rounds):
        ta.pump()
        tb.pump()
        time.sleep(0.01)  # socket delivery threads need a beat


def test_cross_node_bidirectional_sync(two_nodes, shared_clock):
    ta, tb = two_nodes
    a = start_link(AWLWWMap, threaded=False, transport=ta, clock=shared_clock,
                   name="a", capacity=64, tree_depth=6)
    b = start_link(AWLWWMap, threaded=False, transport=tb, clock=shared_clock,
                   name="b", capacity=64, tree_depth=6)
    # {name, node}-style remote addresses
    a.set_neighbours([tb.remote_addr("b")])
    b.set_neighbours([ta.remote_addr("a")])
    a.mutate("add", ["from_a", 1])
    b.mutate("add", ["from_b", 2])

    deadline = time.monotonic() + 20
    want = {"from_a": 1, "from_b": 2}
    while time.monotonic() < deadline:
        a.sync_to_all()
        b.sync_to_all()
        pump_both(ta, tb, rounds=5)
        if a.read() == want and b.read() == want:
            break
    assert a.read() == want
    assert b.read() == want


def test_remote_liveness_ping(two_nodes):
    ta, tb = two_nodes
    assert ta.alive(("anything", tb.endpoint))
    tb.close()
    time.sleep(0.05)
    assert not ta.alive(("anything", tb.endpoint))


def test_down_delivered_for_dead_remote_node(two_nodes, shared_clock):
    ta, tb = two_nodes
    ta.heartbeat_interval = 0.05
    a = start_link(AWLWWMap, threaded=False, transport=ta, clock=shared_clock,
                   name="a", capacity=64, tree_depth=6)
    b = start_link(AWLWWMap, threaded=False, transport=tb, clock=shared_clock,
                   name="b", capacity=64, tree_depth=6)
    a.set_neighbours([tb.remote_addr("b")])
    a.sync_to_all()
    assert tb.remote_addr("b") in a._monitors
    tb.close()  # node death
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline and a._monitors:
        time.sleep(0.05)
        ta.pump()
    assert tb.remote_addr("b") not in a._monitors
