"""telemetry attach/detach racing execute (ISSUE 9 satellite).

The handler table is a module global shared by every replica loop and
fleet tick thread in the process (the RACE gate pins its lock with a
real-tree injection in test_crdtlint.py); these tests drive the REAL
races: handlers attached/detached mid-stream while threaded replicas
and fleet loops execute events concurrently — no exceptions, no torn
handler lists, and a detached handler stops receiving."""

from __future__ import annotations

import threading
import time

import pytest

from delta_crdt_ex_tpu.api import set_neighbours, start_fleet, start_link
from delta_crdt_ex_tpu.runtime import telemetry


@pytest.fixture(autouse=True)
def _isolated_telemetry_handlers():
    """Earlier suites attach throwaway handlers without detaching; the
    emptiness assertions here are about THIS module's churn, so start
    and end with a clean process-global table."""
    with telemetry._lock:
        telemetry._handlers.clear()
    yield
    with telemetry._lock:
        telemetry._handlers.clear()


def test_attach_detach_race_execute_threaded():
    """Raw module-level race: executors hammer every declared event
    while the main thread attaches/detaches handlers. The lock-copied
    handler snapshot means a handler sees a consistent call or none —
    never a torn list."""
    stop = threading.Event()
    errors: list[BaseException] = []

    def executor():
        try:
            while not stop.is_set():
                for ev in telemetry.declared_events():
                    telemetry.execute(ev, {"n": 1}, {"name": "race"})
        except BaseException as e:  # noqa: BLE001 - the assertion surface
            errors.append(e)

    threads = [threading.Thread(target=executor, daemon=True) for _ in range(3)]
    for t in threads:
        t.start()
    calls = [0]

    def handler(_ev, _meas, _meta):
        calls[0] += 1

    try:
        for _ in range(200):
            for ev in telemetry.declared_events():
                telemetry.attach(ev, handler)
            for ev in telemetry.declared_events():
                telemetry.detach(ev, handler)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not errors, errors
    # fully detached: the table is clean and execute reaches no one
    for ev in telemetry.declared_events():
        assert not telemetry.has_handlers(ev)
    before = calls[0]
    telemetry.execute(telemetry.SYNC_DONE, {"n": 1}, {"name": "race"})
    assert calls[0] == before


def test_attach_detach_race_replica_loop(transport):
    """Handlers attached/detached while a THREADED replica's event loop
    emits from merges and mutations — the live replica-loop half of the
    race."""
    a = start_link(
        threaded=True, transport=transport, name="tel-a", sync_interval=0.005
    )
    b = start_link(
        threaded=True, transport=transport, name="tel-b", sync_interval=0.005
    )
    set_neighbours(a, [b])
    set_neighbours(b, [a])
    seen = []

    def handler(_ev, meas, meta):
        seen.append((dict(meas), dict(meta)))

    try:
        deadline = time.monotonic() + 2.0
        i = 0
        while time.monotonic() < deadline:
            telemetry.attach(telemetry.SYNC_DONE, handler)
            a.mutate("add", [f"k{i}", i])
            telemetry.detach(telemetry.SYNC_DONE, handler)
            b.mutate("add", [f"p{i}", i])
            i += 1
        assert seen, "attached windows never observed an event"
        for meas, meta in seen:
            assert "keys_updated_count" in meas and "name" in meta
    finally:
        telemetry.detach(telemetry.SYNC_DONE, handler)
        a.stop()
        b.stop()
    assert not telemetry.has_handlers(telemetry.SYNC_DONE)


def test_attach_detach_race_fleet_tick_thread(transport):
    """Same race against a threaded FLEET's tick thread (the other
    execute source the thread graph names): members merge under the
    fleet loop while handlers churn."""
    fleet = start_fleet(
        4, threaded=True, transport=transport, sync_interval=0.005,
        names=[f"telf{i}" for i in range(4)],
    )
    reps = fleet.replicas
    for r in reps:
        set_neighbours(r, [p for p in reps if p is not r])
    counts = [0]

    def handler(_ev, _meas, _meta):
        counts[0] += 1

    events = (telemetry.SYNC_DONE, telemetry.SYNC_ROUND, telemetry.FLEET_DISPATCH)
    try:
        deadline = time.monotonic() + 2.0
        i = 0
        while time.monotonic() < deadline:
            for ev in events:
                telemetry.attach(ev, handler)
            reps[i % len(reps)].mutate_async("add", [f"k{i}", i])
            time.sleep(0.002)
            for ev in events:
                telemetry.detach(ev, handler)
            i += 1
        assert counts[0] > 0, "attached windows never observed an event"
    finally:
        for ev in events:
            telemetry.detach(ev, handler)
        fleet.stop()
    for ev in events:
        assert not telemetry.has_handlers(ev)


# ---------------------------------------------------------------------------
# execute_many — the batch emission form the grouped ingest path uses


def test_execute_many_plain_handler_sees_per_message_stream():
    """A handler WITHOUT a batch attribute observes the exact stream a
    loop of execute() calls would deliver — order and payloads — so the
    per-message SYNC_DONE/SYNC_ROUND parity contracts hold verbatim."""
    seen: list = []

    def handler(ev, meas, meta):
        seen.append((ev, meas, meta))

    meas_list = [{"keys_updated_count": n} for n in (3, 0, 7)]
    meta = {"name": "x"}
    telemetry.attach(telemetry.SYNC_DONE, handler)
    try:
        telemetry.execute_many(telemetry.SYNC_DONE, meas_list, meta)
    finally:
        telemetry.detach(telemetry.SYNC_DONE, handler)
    assert seen == [(telemetry.SYNC_DONE, m, meta) for m in meas_list]


def test_execute_many_batch_handler_gets_one_call():
    """A handler carrying a ``batch`` attribute consumes the whole list
    in ONE call (the metrics bridge's amortisation path)."""
    per_message: list = []
    batches: list = []

    def handler(ev, meas, meta):
        per_message.append(meas)

    handler.batch = lambda ev, meas_list, meta: batches.append(
        (ev, list(meas_list), meta)
    )

    meas_list = [{"keys_updated_count": n} for n in range(5)]
    telemetry.attach(telemetry.SYNC_DONE, handler)
    try:
        telemetry.execute_many(telemetry.SYNC_DONE, meas_list, {"name": "x"})
        # plain execute still takes the per-message path
        telemetry.execute(telemetry.SYNC_DONE, {"keys_updated_count": 9}, {})
    finally:
        telemetry.detach(telemetry.SYNC_DONE, handler)
    assert batches == [(telemetry.SYNC_DONE, meas_list, {"name": "x"})]
    assert per_message == [{"keys_updated_count": 9}]


def test_execute_many_mixed_handlers():
    """Batch and plain handlers coexist on one event: each consumes the
    same batch through its own form."""
    plain: list = []
    batched: list = []

    def plain_h(ev, meas, meta):
        plain.append(meas["keys_updated_count"])

    def batch_capable(ev, meas, meta):  # pragma: no cover - batch wins
        raise AssertionError("execute_many must prefer .batch")

    batch_capable.batch = lambda ev, ml, meta: batched.extend(
        m["keys_updated_count"] for m in ml
    )

    meas_list = [{"keys_updated_count": n} for n in (1, 2, 3)]
    telemetry.attach(telemetry.SYNC_DONE, plain_h)
    telemetry.attach(telemetry.SYNC_DONE, batch_capable)
    try:
        telemetry.execute_many(telemetry.SYNC_DONE, meas_list, {})
    finally:
        telemetry.detach(telemetry.SYNC_DONE, plain_h)
        telemetry.detach(telemetry.SYNC_DONE, batch_capable)
    assert plain == [1, 2, 3]
    assert batched == [1, 2, 3]
