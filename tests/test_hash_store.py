"""Hash-table dot store (ISSUE 8): the open-addressing backend must be
OBSERVABLY IDENTICAL to the binned store — same reads, same canonical
state (dots, contexts, leaf digests bit-for-bit), same protocol traffic
(acks, walk blocks), and byte-identical WAL contents when fed identical
streams — while paying no tier-promotion repacking (the only growth
event is the ×2 rehash) and shipping dense, content-sized wire slices.

Covers: kernel-level upsert/lookup/rehash units (probe-placement
invariant, collision resolution, dead-lane reuse), seeded randomized
hash-vs-binned parity at the kernel AND runtime level (state, WAL
bytes, ack streams, read views), ``CtxGapError`` gap semantics,
fleet-lane parity (vmap lane == solo hash kernel; hash fleets vs hash
solos), snapshot backend tagging, the ``store="hash"`` tier-1 smoke
(convergence + WAL crash recovery), the dense-extraction byte win, and
a hypothesis upsert/extract round-trip property (importorskip-guarded).
"""

from __future__ import annotations

import dataclasses

import numpy as np
import jax.numpy as jnp
import pytest

from delta_crdt_ex_tpu import AWLWWMap, HashAWLWWMap as HashModel
from delta_crdt_ex_tpu.api import _resolve_store, start_link
from delta_crdt_ex_tpu.models.binned_map import AWSet, BinnedAWLWWMap, CtxGapError
from delta_crdt_ex_tpu.models.hash_store import (
    GROUP,
    HashAWSet,
    HashStore,
    grow_table,
)
from delta_crdt_ex_tpu.ops import hash_map as hash_ops
from delta_crdt_ex_tpu.runtime import sync as sync_proto, telemetry, transition
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.fleet import Fleet
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from tests.kernel_harness import (
    BinnedKernelMap,
    HashKernelMap,
    read_binned_state,
    read_hash_state,
)
from tests.test_ingest_coalesce import (
    _wal_segment_bytes,
    entries_only,
    keys_for_buckets,
)


def canonical_dots(state) -> set:
    """{(gid, bucket, ctr, key, valh, ts)} — the store-layout-independent
    dot set both backends must agree on exactly."""
    alive = np.asarray(state.alive)
    idx = np.nonzero(alive)
    node = np.asarray(state.node)[idx]
    gid = np.asarray(state.ctx_gid)[node]
    key = np.asarray(state.key)[idx]
    bucket = key & np.uint64(state.num_buckets - 1)
    return {
        (int(g), int(b), int(c), int(k), int(v), int(t))
        for g, b, c, k, v, t in zip(
            gid.tolist(),
            bucket.tolist(),
            np.asarray(state.ctr)[idx].tolist(),
            key.tolist(),
            np.asarray(state.valh)[idx].tolist(),
            np.asarray(state.ts)[idx].tolist(),
        )
    }


def assert_canonical_equal(hs, bs, ctx=""):
    """Hash-vs-binned state parity: identical dot sets and bit-identical
    shared arrays (contexts + leaf digests ⇒ identical digest trees ⇒
    identical walk traffic)."""
    assert canonical_dots(hs) == canonical_dots(bs), ctx
    for col in ("ctx_gid", "ctx_max", "leaf"):
        assert np.array_equal(
            np.asarray(getattr(hs, col)), np.asarray(getattr(bs, col))
        ), (ctx, col)


def assert_hash_bit_equal(s1: HashStore, s2: HashStore, ctx=""):
    for f in dataclasses.fields(HashStore):
        if f.name == "probe_window":
            assert s1.probe_window == s2.probe_window, ctx
            continue
        assert np.array_equal(
            np.asarray(getattr(s1, f.name)), np.asarray(getattr(s2, f.name))
        ), (ctx, f.name)


def assert_placement_invariant(state: HashStore, ctx=""):
    """Every alive entry sits inside its key's probe window — the
    invariant lookups (kills, reads, presence tests) rely on."""
    alive = np.asarray(state.alive)
    (idx,) = np.nonzero(alive)
    if not len(idx):
        return
    base = np.asarray(hash_ops.probe_base(jnp.asarray(np.asarray(state.key)[idx]), state.table_size))
    disp = idx - base
    assert (disp >= 0).all() and (disp < state.probe_window).all(), (ctx, disp)


# ---------------------------------------------------------------------------
# kernel-level units: upsert / lookup / rehash


def test_upsert_lookup_roundtrip():
    m = HashKernelMap(gid=7, capacity=128, num_buckets=16)
    m.add(5, 50, ts=1)
    m.add(21, 60, ts=2)
    m.add(5, 70, ts=3)  # overwrite kills the old dot
    assert m.read() == {5: 70, 21: 60}
    w = m.M.winners_for_keys(m.state, jnp.asarray(np.array([5, 21, 99], np.uint64)))
    found = np.asarray(w.found)
    assert found.tolist() == [True, True, False]
    assert int(np.asarray(w.valh)[0]) == 70
    m.remove(21, ts=4)
    assert m.read() == {5: 70}
    assert_placement_invariant(m.state)


def test_same_window_collisions_place_distinct_lanes():
    """Many concurrent dots of one key (distinct writers) share one
    probe window; batch placement must give each its own lane."""
    a = HashKernelMap(gid=1, capacity=128, num_buckets=4)
    writers = [HashKernelMap(gid=100 + i, capacity=128, num_buckets=4) for i in range(6)]
    for ts, w in enumerate(writers, start=1):
        w.add(9, 10 + ts, ts=ts)
        a.join_from(w)
    assert a.alive_count() == 6  # six concurrent dots of key 9
    assert a.read() == {9: 16}  # LWW: last ts wins
    assert_placement_invariant(a.state)


def test_window_overflow_grows_and_retries():
    """A probe window fuller than its lanes must escape to the host
    growth path (rehash), never silently drop an insert."""
    st = HashStore.new(num_buckets=4, bin_capacity=16, replica_capacity=8)
    assert st.table_size == 64
    m = HashKernelMap(gid=1, capacity=64, num_buckets=4)
    for i in range(120):  # >> table size: must rehash, possibly twice
        m.add(i * 4, i, ts=i + 1)  # same bucket row, different windows
    assert m.alive_count() == 120
    assert m.state.table_size >= 256
    assert_placement_invariant(m.state, "after growth")


def test_update_churn_reuses_dead_lanes():
    """THE steady-state property this backend exists for: an overwrite
    kills the old dot and its insert reuses the freed lane (there are
    no tombstones), so updating existing keys forever never fills a
    probe window and never grows the table."""
    m = HashKernelMap(gid=3, capacity=128, num_buckets=16)
    for i in range(8):
        m.add(i, i, ts=i + 1)
    h0 = m.state.table_size
    alive0 = m.alive_count()
    for rnd in range(3 * m.state.probe_window):  # >> window lanes
        for i in range(8):
            m.add(i, 100 + rnd, ts=1000 + rnd * 8 + i)
    assert m.state.table_size == h0, "steady-state churn grew the table"
    assert m.alive_count() == alive0
    assert m.read() == {i: 100 + 3 * m.state.probe_window - 1 for i in range(8)}
    assert_placement_invariant(m.state, "after churn")


def test_rehash_preserves_content():
    m = HashKernelMap(gid=3, capacity=128, num_buckets=16)
    for i in range(40):
        m.add(i, i, ts=i + 1)
    for i in range(0, 40, 2):
        m.remove(i, ts=100 + i)
    pre_read = m.read()
    pre_dots = canonical_dots(m.state)
    pre_leaf = np.asarray(m.state.leaf).copy()
    grown = grow_table(m.state)
    assert grown.table_size == 2 * m.state.table_size
    assert read_hash_state(grown) == pre_read
    assert canonical_dots(grown) == pre_dots
    assert np.array_equal(np.asarray(grown.leaf), pre_leaf)
    assert_placement_invariant(grown, "post-rehash")


def test_rehash_is_pure_and_deterministic():
    m = HashKernelMap(gid=3, capacity=128, num_buckets=16)
    for i in range(30):
        m.add(i, i, ts=i + 1)
    before = jnp.asarray(np.asarray(m.state.key)).copy()
    s1, ok1 = hash_ops.rehash(m.state, table_size=m.state.table_size * 2,
                              probe_window=m.state.probe_window)
    s2, ok2 = hash_ops.rehash(m.state, table_size=m.state.table_size * 2,
                              probe_window=m.state.probe_window)
    assert bool(ok1) and bool(ok2)
    assert np.array_equal(np.asarray(m.state.key), np.asarray(before))  # input untouched
    assert_hash_bit_equal(s1, s2, "rehash determinism")


def test_clear_kills_everything_but_keeps_context():
    m = HashKernelMap(gid=3, capacity=128, num_buckets=16)
    for i in range(10):
        m.add(i, i, ts=i + 1)
    ctx_before = m.ctx()
    m.clear(ts=99)
    assert m.read() == {}
    assert m.alive_count() == 0
    assert m.ctx() == ctx_before  # observed dots stay covered
    assert not np.asarray(m.state.leaf).any()


# ---------------------------------------------------------------------------
# kernel-level hash-vs-binned parity (the test_merge_parity pattern)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_randomized_script_parity_vs_binned(seed):
    """One seeded op/merge script through both backends: reads, dot
    sets, contexts and leaf digests must agree bit-for-bit at every
    checkpoint (leaf equality ⇒ identical digest trees ⇒ the sync walk
    cannot tell the stores apart)."""
    rng = np.random.default_rng(seed)
    L = 16
    hs = {g: HashKernelMap(gid=g, capacity=128, rcap=4, num_buckets=L) for g in (100, 200)}
    bs = {g: BinnedKernelMap(gid=g, capacity=128, rcap=4, num_buckets=L) for g in (100, 200)}
    for ts in range(1, 40):
        g = 100 if rng.random() < 0.5 else 200
        k = int(rng.integers(0, 24))
        r = rng.random()
        if r < 0.62:
            v = int(rng.integers(0, 100))
            hs[g].add(k, v, ts=ts)
            bs[g].add(k, v, ts=ts)
        elif r < 0.9:
            hs[g].remove(k, ts=ts)
            bs[g].remove(k, ts=ts)
        elif r < 0.96:
            hs[g].clear(ts=ts)
            bs[g].clear(ts=ts)
        else:
            src = 300 - g  # merge the other replica's full state
            hs[g].join_from(hs[src])
            bs[g].join_from(bs[src])
        if ts % 7 == 0:
            for g2 in (100, 200):
                assert hs[g2].read() == bs[g2].read(), (seed, ts, g2)
                assert_canonical_equal(hs[g2].state, bs[g2].state, (seed, ts, g2))
    hs[100].join_from(hs[200])
    bs[100].join_from(bs[200])
    assert hs[100].read() == bs[100].read(), seed
    assert_canonical_equal(hs[100].state, bs[100].state, (seed, "final"))
    assert_placement_invariant(hs[100].state, seed)


def test_cross_backend_slices_merge_identically():
    """The wire slice shape is shared: a binned replica merges a dense
    hash extraction and a hash replica merges a padded binned row slice,
    and both land on the same canonical state."""
    src_h = HashKernelMap(gid=9, capacity=128, num_buckets=16)
    src_b = BinnedKernelMap(gid=9, capacity=128, num_buckets=16)
    for i in range(20):
        src_h.add(i, i + 1, ts=i + 1)
        src_b.add(i, i + 1, ts=i + 1)
    # hash → binned
    tgt_b = BinnedKernelMap(gid=5, capacity=128, num_buckets=16)
    tgt_b.join_from(src_h)
    # binned → hash
    tgt_h = HashKernelMap(gid=5, capacity=128, num_buckets=16)
    tgt_h.join_from(src_b)
    assert tgt_b.read() == tgt_h.read() == src_h.read()
    assert_canonical_equal(tgt_h.state, tgt_b.state, "cross-backend")
    # and the dense hash slice really is smaller than the binned one
    rows = jnp.arange(16, dtype=jnp.int32)
    sl_h = src_h.M.extract_rows(src_h.state, rows)
    sl_b = src_b.M.extract_rows(src_b.state, rows)
    assert sl_h.key.shape[1] <= sl_b.key.shape[1]


def test_ctx_gap_semantics_match_binned():
    """A delta-interval slice that skips an interval must gap on the
    hash kernel exactly like the binned one (same ``_slice_view``):
    ``need_ctx_gap`` set, ``gap_row`` flags the offending row, state
    unusable, and the model wrapper raises ``CtxGapError``."""
    src = HashKernelMap(gid=11, capacity=128, num_buckets=8)
    bsrc = BinnedKernelMap(gid=11, capacity=128, num_buckets=8)
    k = 3  # one bucket row
    for ts in range(1, 7):
        src.add(k, ts, ts=ts)
        bsrc.add(k, ts, ts=ts)
    rows = jnp.asarray(np.array([k & 7], np.int32))
    # interval (3, 6] while the receiver has seen nothing: gapped
    mk_delta = lambda m, slot_gid: m.M.extract_own_delta(
        m.state, rows, jnp.int32(0), jnp.uint64(slot_gid), jnp.asarray(np.array([3], np.uint32))
    )
    sl_h = mk_delta(src, 11)
    sl_b = mk_delta(bsrc, 11)
    fresh_h = HashKernelMap(gid=12, capacity=128, num_buckets=8)
    fresh_b = BinnedKernelMap(gid=12, capacity=128, num_buckets=8)
    res_h = fresh_h.M.merge_rows(fresh_h.state, sl_h)
    res_b = fresh_b.M.merge_rows(fresh_b.state, sl_b)
    assert bool(res_h.need_ctx_gap) and bool(res_b.need_ctx_gap)
    assert not bool(res_h.ok) and not bool(res_b.ok)
    assert np.array_equal(np.asarray(res_h.gap_row), np.asarray(res_b.gap_row))
    with pytest.raises(CtxGapError):
        fresh_h.merge_slice(sl_h)
    # contiguous interval (0, 6] merges clean and reads identically
    sl_h0 = mk_delta(src, 11)._replace(
        ctx_lo=jnp.zeros_like(sl_h.ctx_lo)
    )
    # rebuild with lo=0 through the proper extraction (alive mask differs)
    sl_h0 = src.M.extract_own_delta(
        src.state, rows, jnp.int32(0), jnp.uint64(11), jnp.asarray(np.array([0], np.uint32))
    )
    fresh_h.merge_slice(sl_h0)
    assert fresh_h.read() == {k: 6}


def test_merge_counts_match_binned():
    """Per-row insert/kill counts feed SYNC_DONE telemetry and the
    fleet's per-message accounting — they must match binned exactly."""
    for seed in range(3):
        rng = np.random.default_rng(40 + seed)
        src_h = HashKernelMap(gid=1, capacity=128, num_buckets=8)
        src_b = BinnedKernelMap(gid=1, capacity=128, num_buckets=8)
        tgt_h = HashKernelMap(gid=2, capacity=128, num_buckets=8)
        tgt_b = BinnedKernelMap(gid=2, capacity=128, num_buckets=8)
        for ts in range(1, 25):
            k = int(rng.integers(0, 16))
            v = int(rng.integers(0, 50))
            src_h.add(k, v, ts=ts)
            src_b.add(k, v, ts=ts)
            if rng.random() < 0.3:
                tgt_h.add(k, v + 1, ts=ts + 100)
                tgt_b.add(k, v + 1, ts=ts + 100)
        # seed kills: the target observes then the source removes
        src_h.join_from(tgt_h)
        src_b.join_from(tgt_b)
        rows = jnp.arange(8, dtype=jnp.int32)
        res_h = tgt_h.merge_slice(src_h.M.extract_rows(src_h.state, rows))
        res_b = tgt_b.merge_slice(src_b.M.extract_rows(src_b.state, rows))
        assert int(res_h.n_inserted) == int(res_b.n_inserted), seed
        assert int(res_h.n_killed) == int(res_b.n_killed), seed
        assert np.array_equal(np.asarray(res_h.n_ins_row), np.asarray(res_b.n_ins_row)), seed
        assert np.array_equal(np.asarray(res_h.n_kill_row), np.asarray(res_b.n_kill_row)), seed
        assert tgt_h.read() == tgt_b.read(), seed


# ---------------------------------------------------------------------------
# runtime parity: identical streams into paired hash/binned receivers


def _mk_sender(transport, clock, i, **opts):
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock,
        capacity=64, tree_depth=6, name=f"hs_snd{i}", **opts,
    )


def _mk_pairs(transport, clock, n, tmp=None, **opts):
    """n hash receivers + n binned receivers, pairwise-equal node ids,
    fed identical streams — the fleet-vs-solo parity shape with the
    store backend as the varying axis."""
    wal = lambda tag, i: (
        {"wal_dir": str(tmp / f"{tag}{i}"), "fsync_mode": "none"} if tmp else {}
    )
    hashes = [
        start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=64, tree_depth=6, node_id=5000 + i, name=f"hr{i}",
            store="hash", **wal("h", i), **opts,
        )
        for i in range(n)
    ]
    binned = [
        start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=64, tree_depth=6, node_id=5000 + i, name=f"br{i}",
            store="binned", **wal("b", i), **opts,
        )
        for i in range(n)
    ]
    return hashes, binned


def _norm_msg(m, addr_map):
    sub = lambda v: addr_map.get(v, v)
    t = type(m).__name__
    if isinstance(m, sync_proto.AckMsg):
        return (t, sub(m.clear_addr))
    if isinstance(m, sync_proto.DiffMsg):
        return (
            t, sub(m.originator), sub(m.frm), m.level, m.idx.tolist(),
            [b.tolist() for b in m.blocks], m.seq, m.log_horizon,
        )
    if isinstance(m, sync_proto.GetDiffMsg):
        return (t, sub(m.originator), sub(m.frm), np.asarray(m.buckets).tolist())
    if isinstance(m, sync_proto.GetLogMsg):
        return (t, sub(m.frm), m.last_seq, m.applied_seq)
    return (t, repr(m))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_hash_vs_binned_bit_for_bit_parity_randomized(seed, tmp_path):
    """THE acceptance property (ISSUE 8): seeded randomized gossip
    scripts fed identically to hash-store and binned-store receivers end
    with identical reads, identical canonical state (dots + bit-equal
    contexts/leaf digests), byte-identical WAL segment contents,
    identical sequence numbers, identical outbound protocol streams
    (walk replies + acks — the digest trees are bit-equal so the walk
    cannot diverge), and pairwise-identical SYNC_DONE streams."""
    rng = np.random.default_rng(seed)
    transport = LocalTransport()
    clock = LogicalClock()
    n = 2
    senders = [_mk_sender(transport, clock, i) for i in range(n)]
    hashes, binned = _mk_pairs(transport, clock, n, tmp=tmp_path)
    for i, s in enumerate(senders):
        s.set_neighbours([hashes[i], binned[i]])
    addr_map = {}
    for i in range(n):
        addr_map[hashes[i].addr] = f"recv{i}"
        addr_map[binned[i].addr] = f"recv{i}"

    done: list = []
    handler = lambda _e, meas, meta: done.append(
        (meta["name"], meas["keys_updated_count"])
    )
    telemetry.attach(telemetry.SYNC_DONE, handler)
    try:
        for _round in range(int(rng.integers(2, 4))):
            for _ in range(int(rng.integers(1, 9))):
                i = int(rng.integers(0, n))
                ki = int(rng.integers(0, 12))
                if rng.random() < 0.7:
                    senders[i].mutate("add", [ki, int(rng.integers(0, 100))])
                else:
                    senders[i].mutate("remove", [ki])
            for s in senders:
                s.sync_to_all()
            for r in hashes + binned:
                r.process_pending()
            for i, s in enumerate(senders):
                back = transport.drain(s.addr)
                frm = lambda m: getattr(m, "frm", None) or getattr(m, "clear_addr", None)
                from_h = [_norm_msg(m, addr_map) for m in back if frm(m) == hashes[i].addr]
                from_b = [_norm_msg(m, addr_map) for m in back if frm(m) == binned[i].addr]
                assert from_h == from_b, (seed, i)
                for m in back:  # walk continues: feed replies back
                    s.handle(m)
            for r in hashes + binned:
                r.process_pending()
    finally:
        telemetry.detach(telemetry.SYNC_DONE, handler)

    for i in range(n):
        rh, rb = hashes[i], binned[i]
        assert rh.read() == rb.read()
        assert rh._seq == rb._seq
        assert_canonical_equal(rh.state, rb.state, (seed, i))
        assert _wal_segment_bytes(rh) == _wal_segment_bytes(rb) != b""
        assert [c for nme, c in done if nme == rh.name] == [
            c for nme, c in done if nme == rb.name
        ], (seed, i)


@pytest.mark.parametrize("seed", [0, 1])
def test_symmetric_universes_converge_identically(seed):
    """Hash↔hash and binned↔binned universes driven by one script:
    reads, canonical state, and sequence numbers agree — the hash store
    also WRITES protocol-compatible slices, not just reads them."""
    rng = np.random.default_rng(100 + seed)
    mk_pair = lambda store: (LocalTransport(), LogicalClock(), store)
    universes = {}
    for store in ("hash", "binned"):
        t, c, _ = mk_pair(store)
        a = start_link(AWLWWMap, threaded=False, transport=t, clock=c, capacity=64,
                       tree_depth=6, node_id=71, name=f"{store}_a", store=store)
        b = start_link(AWLWWMap, threaded=False, transport=t, clock=c, capacity=64,
                       tree_depth=6, node_id=72, name=f"{store}_b", store=store)
        a.set_neighbours([b])
        b.set_neighbours([a])
        universes[store] = (a, b)
    script = []
    for _ in range(30):
        script.append(
            (
                int(rng.integers(0, 2)),
                "add" if rng.random() < 0.7 else "remove",
                int(rng.integers(0, 10)),
                int(rng.integers(0, 100)),
            )
        )
    for who, op, k, v in script:
        for store in ("hash", "binned"):
            rep = universes[store][who]
            rep.mutate(op, [k, v] if op == "add" else [k])
    for _ in range(6):
        for store in ("hash", "binned"):
            a, b = universes[store]
            a.sync_to_all(); b.sync_to_all()
            a.process_pending(); b.process_pending()
    ha, hb = universes["hash"]
    ba, bb = universes["binned"]
    assert ha.read() == hb.read() == ba.read() == bb.read(), seed
    assert ha._seq == ba._seq and hb._seq == bb._seq
    assert_canonical_equal(ha.state, ba.state, (seed, "a"))
    assert_canonical_equal(hb.state, bb.state, (seed, "b"))


def test_gap_repair_roundtrip_runtime():
    """A lost eager push gaps the next interval; the hash receiver must
    answer with the same GetDiffMsg repair and converge."""
    t = LocalTransport()
    c = LogicalClock()
    s = _mk_sender(t, c, 0)
    r = start_link(AWLWWMap, threaded=False, transport=t, clock=c, capacity=64,
                   tree_depth=6, name="gap_h", store="hash")
    s.set_neighbours([r])
    k1, k2 = keys_for_buckets(3, 4, 2)
    s.mutate("add", [k1, "one"])
    s.sync_to_all()
    t.drain(r.addr)  # the push is LOST
    s.mutate("add", [k2, "two"])  # same bucket: next interval gaps
    s.sync_to_all()
    entries_only(t, r.addr)
    r.process_pending()
    gets = [m for m in t.drain(s.addr) if isinstance(m, sync_proto.GetDiffMsg)]
    assert len(gets) == 1
    s.handle(gets[0])
    entries_only(t, r.addr)
    r.process_pending()
    assert r.read() == {k1: "one", k2: "two"}


# ---------------------------------------------------------------------------
# fleet: vmapped hash transitions + capacity bucketing


def test_fleet_hash_merge_vmap_lane_equals_solo_kernel():
    """Lane k of one batched ``fleet_hash_merge_rows`` dispatch is
    bit-for-bit the solo hash ``merge_rows`` on lane k's inputs."""
    from delta_crdt_ex_tpu.models.binned_map import stack_entry_slices
    from delta_crdt_ex_tpu.ops.binned import RowSlice

    n = 3
    states, slices = [], []
    for i in range(n):
        tgt = HashKernelMap(gid=100 + i, capacity=128, num_buckets=16)
        src = HashKernelMap(gid=500 + i, capacity=128, num_buckets=16)
        for ts, k in enumerate(keys_for_buckets(0, 16, 5, mask=15, start=1000 * i), start=1):
            src.add(k, k % 97, ts=ts)
        for ts, k in enumerate(keys_for_buckets(0, 16, 2, mask=15, start=1000 * i), start=10):
            tgt.add(k, 7, ts=ts)  # kill-pass prey
        states.append(tgt.state)
        slices.append(src.M.extract_rows(src.state, jnp.arange(16, dtype=jnp.int32)))
    solo = [hash_ops.merge_rows(st, sl) for st, sl in zip(states, slices)]
    assert all(bool(r.ok) for r in solo)
    np_slices = [
        RowSlice(**{c: np.asarray(getattr(s, c)) for c in RowSlice._fields})
        for s in slices
    ]
    stacked_sl, _ = stack_entry_slices(np_slices)
    res = transition.jit_fleet_hash_merge_rows(
        transition.stack_states(states), stacked_sl
    )
    assert np.asarray(res.ok).all()
    for k in range(n):
        lane = transition.index_state(res.state, k)
        assert_hash_bit_equal(solo[k].state, lane, f"lane {k}")
        assert np.array_equal(np.asarray(res.n_ins_row)[k], np.asarray(solo[k].n_ins_row))
        assert np.array_equal(np.asarray(res.n_kill_row)[k], np.asarray(solo[k].n_kill_row))


def test_fleet_hash_members_batch_and_match_solo(tmp_path):
    """A fleet of hash-store members batches across replicas (the
    backend-tagged bucket key routes to the hash vmap dispatch) and
    stays bit-identical to solo hash replicas on the same streams."""
    transport = LocalTransport()
    clock = LogicalClock()
    n = 3
    senders = [_mk_sender(transport, clock, i) for i in range(n)]
    mk = lambda pre, i: start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock,
        capacity=64, tree_depth=6, node_id=8000 + i, name=f"{pre}{i}",
        store="hash", wal_dir=str(tmp_path / f"{pre}{i}"), fsync_mode="none",
    )
    fleet = Fleet([mk("fh", i) for i in range(n)])
    solos = [mk("sh", i) for i in range(n)]
    for i, s in enumerate(senders):
        s.set_neighbours([fleet.replicas[i], solos[i]])
    for i, s in enumerate(senders):
        for k in keys_for_buckets(0, 64, 3, start=777 * i):
            s.mutate("add", [k, k])
        s.sync_to_all()
    for r in list(fleet.replicas) + solos:
        entries_only(transport, r.addr)
    fleet.drain()
    for r in solos:
        r.process_pending()
    st = fleet.stats()
    assert st["dispatches"] >= 1  # the hash batch WAS vmapped
    for i in range(n):
        rf, rs = fleet.replicas[i], solos[i]
        assert rf.read() == rs.read()
        assert rf._seq == rs._seq
        assert_hash_bit_equal(rf.state, rs.state, i)
        assert _wal_segment_bytes(rf) == _wal_segment_bytes(rs)


def _keys_for_probe_base(table_size: int, n: int, start: int = 1) -> list:
    """``n`` int key terms whose probe windows share one hot base —
    drives window pressure directly (the growth advisory's signal),
    independent of table size."""
    from delta_crdt_ex_tpu.utils.hashing import key_hash64

    base_of = lambda k: int(
        np.asarray(
            hash_ops.probe_base(
                jnp.asarray(np.uint64(key_hash64(k))), table_size
            )
        )
    )
    k = start
    target = base_of(k)
    out = [k]
    while len(out) < n:
        k += 1
        if base_of(k) == target:
            out.append(k)
    return out


def test_fleet_window_advisory_grows_off_batch_path(tmp_path):
    """A fleet-held member whose hot probe window nears overflow in a
    batched merge grows via the post-commit advisory
    (``grow_store_advised`` — no mid-batch escape), and stays
    bit-identical to a solo replica fed the same stream (whose
    ``merge_rows_into`` runs the same policy)."""
    transport = LocalTransport()
    clock = LogicalClock()
    senders = [_mk_sender(transport, clock, i) for i in range(2)]
    mk = lambda pre, i: start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock,
        capacity=256, tree_depth=6, node_id=8800 + i, name=f"{pre}{i}",
        store="hash", wal_dir=str(tmp_path / f"{pre}{i}"), fsync_mode="none",
    )
    fleet = Fleet([mk("adv_f", i) for i in range(2)])
    solos = [mk("adv_s", i) for i in range(2)]
    for i, s in enumerate(senders):
        s.set_neighbours([fleet.replicas[i], solos[i]])
    h0 = fleet.replicas[0].state.table_size
    w = fleet.replicas[0].state.probe_window
    # one hot window: wave 1 fills it just below the ¾ advisory line,
    # wave 2 crosses the line but stays far from overflow (the advisory
    # grows the members at commit, no escape), wave 3 lands in the
    # grown table where the hot base has split in two
    hot = _keys_for_probe_base(h0, 3 * w // 4 + 2)
    waves = (hot[: 3 * w // 4 - 1], hot[3 * w // 4 - 1 :], [900_001, 900_002])
    for wave in waves:
        for s in senders:
            s.mutate_batch("add", [[k, k % 91] for k in wave])
            s.sync_to_all()
        for r in list(fleet.replicas) + solos:
            entries_only(transport, r.addr)
        fleet.drain()
        for r in solos:
            r.process_pending()
    assert fleet.stats()["fallbacks"]["escape"] == 0, "advisory must preempt escapes"
    for i in range(2):
        rf, rs = fleet.replicas[i], solos[i]
        assert rf.state.table_size > h0, "window advisory never grew the member"
        assert rf.read() == rs.read()
        assert_hash_bit_equal(rf.state, rs.state, i)
        assert _wal_segment_bytes(rf) == _wal_segment_bytes(rs)


def test_batch_key_declares_backend():
    """Backends declare their own batch-compatibility key (the fleet
    must never stack a hash member with a binned one)."""
    t = LocalTransport()
    c = LogicalClock()
    h = start_link(AWLWWMap, threaded=False, transport=t, clock=c, capacity=64,
                   tree_depth=6, name="geo_h", store="hash")
    b = start_link(AWLWWMap, threaded=False, transport=t, clock=c, capacity=64,
                   tree_depth=6, name="geo_b", store="binned")
    gh, gb = h._geometry(), b._geometry()
    assert gh[0] == "hash" and gb[0] == "binned"
    assert gh != gb
    # hash key moves only on rehash (capacity), not on content growth
    assert gh[2] == h.state.table_size


# ---------------------------------------------------------------------------
# runtime smoke: store="hash" end-to-end (the tier-1 anti-bit-rot gate)


def test_store_hash_e2e_convergence_and_wal_recovery(tmp_path):
    t = LocalTransport()
    c = LogicalClock()
    mk = lambda name, **kw: start_link(
        AWLWWMap, threaded=False, transport=t, clock=c, capacity=64,
        tree_depth=6, name=name, store="hash", **kw,
    )
    a = mk("e2e_a", wal_dir=str(tmp_path / "a"), fsync_mode="none")
    b = mk("e2e_b")
    a.set_neighbours([b])
    b.set_neighbours([a])
    for i in range(40):
        a.mutate("add", [f"k{i}", i])
    b.mutate("add", ["k1", "theirs"])
    for _ in range(6):
        a.sync_to_all(); b.sync_to_all()
        a.process_pending(); b.process_pending()
    assert a.read() == b.read() and len(a.read()) == 40
    want = a.read()
    node_id = a.node_id
    a.crash()
    reborn = mk("e2e_a", wal_dir=str(tmp_path / "a"))
    assert reborn.node_id == node_id
    assert reborn.read() == want
    # fresh dots post-recovery land cleanly
    reborn.mutate("add", ["post", 1])
    assert reborn.read() == {**want, "post": 1}
    reborn.crash()


def test_snapshot_records_store_backend(tmp_path):
    """A hash-store WAL/snapshot must refuse to rehydrate a binned
    replica (and vice versa) with the extraction-migration pointer."""
    t = LocalTransport()
    c = LogicalClock()
    a = start_link(AWLWWMap, threaded=False, transport=t, clock=c, capacity=64,
                   tree_depth=6, name="tagged", store="hash",
                   wal_dir=str(tmp_path), fsync_mode="none", compact_every=1)
    a.mutate("add", ["k", 1])  # compact_every=1: snapshot written
    a.crash()
    with pytest.raises(ValueError, match="extraction"):
        start_link(AWLWWMap, threaded=False, transport=t, clock=c, capacity=64,
                   tree_depth=6, name="tagged", store="binned",
                   wal_dir=str(tmp_path), fsync_mode="none")


def test_resolve_store_mapping():
    assert _resolve_store(BinnedAWLWWMap, None) is BinnedAWLWWMap
    assert _resolve_store(BinnedAWLWWMap, "hash") is HashModel
    assert _resolve_store(HashModel, "binned") is BinnedAWLWWMap
    assert _resolve_store(AWSet, "hash") is HashAWSet
    assert _resolve_store(HashAWSet, "hash") is HashAWSet
    with pytest.raises(ValueError, match="unknown store"):
        _resolve_store(BinnedAWLWWMap, "flat")


def test_hash_awset_reads_as_set():
    t = LocalTransport()
    c = LogicalClock()
    a = start_link(AWSet, threaded=False, transport=t, clock=c, capacity=64,
                   tree_depth=6, name="hset", store="hash")
    a.mutate("add", ["x"])
    a.mutate("add", ["y"])
    a.mutate("remove", ["x"])
    assert a.read() == {"y"}


# ---------------------------------------------------------------------------
# dense extraction: the byte win + determinism


def test_dense_extraction_is_smaller_and_deterministic():
    """The hash store ships content-sized slices: at low bucket fill the
    lane tier undercuts the binned bin tier, dead lanes are zeroed, and
    repeated extraction is byte-identical (deterministic arrival
    order)."""
    h = HashKernelMap(gid=4, capacity=1024, num_buckets=16)
    b = BinnedKernelMap(gid=4, capacity=1024, num_buckets=16)
    for i in range(24):  # ~1.5 entries/bucket vs bin tier 64
        h.add(i, i, ts=i + 1)
        b.add(i, i, ts=i + 1)
    rows = jnp.arange(16, dtype=jnp.int32)
    sl_h = h.M.extract_rows(h.state, rows)
    sl_b = b.M.extract_rows(b.state, rows)
    assert sl_h.key.shape[1] < sl_b.key.shape[1]
    lane_bytes = lambda sl: sum(
        np.asarray(getattr(sl, c)).nbytes
        for c in ("key", "valh", "ts", "node", "ctr", "alive")
    )
    assert lane_bytes(sl_h) < lane_bytes(sl_b)
    # dead lanes zeroed + deterministic bytes
    sl_h2 = h.M.extract_rows(h.state, rows)
    for c in ("key", "valh", "ts", "node", "ctr", "alive"):
        a1, a2 = np.asarray(getattr(sl_h, c)), np.asarray(getattr(sl_h2, c))
        assert np.array_equal(a1, a2), c
        if c != "alive":
            assert not a1[~np.asarray(sl_h.alive)].any(), c


def test_catchup_stats_record_chunk_fill(tmp_path):
    """The log-ship server surfaces shipped lanes vs entries per store
    (the PR 4 padding-overhead finding, now observable): a hash server's
    chunk fill ratio must beat the binned server's on the same data."""
    ratios = {}
    for store in ("hash", "binned"):
        t = LocalTransport()
        c = LogicalClock()
        w = start_link(AWLWWMap, threaded=False, transport=t, clock=c, capacity=1024,
                       tree_depth=6, name=f"cw_{store}", store=store,
                       wal_dir=str(tmp_path / store), fsync_mode="none")
        r = start_link(AWLWWMap, threaded=False, transport=t, clock=c, capacity=1024,
                       tree_depth=6, name=f"cr_{store}", store=store)
        for i in range(48):
            w.mutate("add", [f"k{i}", i])
        w.set_neighbours([r])
        w.sync_to_all()
        # force the log-ship path: the receiver requests the WAL suffix
        r._request_catchup(w.addr)
        w.process_pending()
        r.process_pending()
        w.process_pending()
        r.process_pending()
        assert r.read() == w.read()
        cu = w.stats()["catchup"]
        assert cu["store"] == store
        assert cu["chunks_served"] >= 1
        assert cu["lanes_shipped"] > 0 and cu["entries_shipped"] > 0
        assert cu["entries_shipped"] == 48  # same content either way
        ratios[store] = (cu["chunk_fill_ratio"], cu["lanes_shipped"])
    # dense hash chunks ship far fewer lanes for the same entries (the
    # pow4 dense tier still pads a little — but never to the bin tier)
    assert ratios["hash"][1] < ratios["binned"][1]
    assert ratios["hash"][0] >= 2 * ratios["binned"][0]


# ---------------------------------------------------------------------------
# Pallas point-lookup kernel (interpret mode = CPU-checkable)


def test_pallas_probe_lookup_interpret_matches_reference():
    m = HashKernelMap(gid=21, capacity=256, num_buckets=16)
    for i in range(30):
        m.add(i, i * 3 + 1, ts=i + 1)
    for i in range(0, 30, 3):
        m.remove(i, ts=100 + i)
    keys = np.arange(0, 34, dtype=np.uint64)
    try:
        out = np.asarray(
            hash_ops.probe_lookup_pallas(jnp.asarray(keys), m.state, interpret=True)
        )
    except Exception as e:  # pallas interpret API churn: only the
        pytest.skip(f"pallas interpret unavailable: {e!r}")  # TPU path may skip
    ref = m.M.winners_for_keys(m.state, jnp.asarray(keys))
    found_ref = np.asarray(ref.found)
    assert np.array_equal(out[:, 0].astype(bool), found_ref)
    # winner columns agree wherever found (ctr + valh identify the dot)
    sel = found_ref
    assert np.array_equal(out[sel, 3].astype(np.uint32), np.asarray(ref.ctr)[sel])
    assert np.array_equal(out[sel, 4].astype(np.uint32), np.asarray(ref.valh)[sel])
    # free-slot probe: a returned lane really is free (dead) and in-window
    free = out[:, 7]
    in_table = free < m.state.table_size
    assert (~np.asarray(m.state.alive)[free[in_table]]).all()


def test_probed_lookup_fn_reports_selection():
    fn, tag = hash_ops.probed_lookup_fn()
    # CPU tier-1: the probe must fall back (and say why) or succeed
    assert (fn is None and tag.startswith("xla")) or tag == "pallas"


# ---------------------------------------------------------------------------
# hypothesis property: upsert/extract round-trip


def test_property_upsert_extract_roundtrip():
    hypothesis = pytest.importorskip("hypothesis")
    from hypothesis import HealthCheck, given, settings
    from hypothesis import strategies as st

    ops = st.lists(
        st.tuples(
            st.sampled_from(["add", "remove"]),
            st.integers(min_value=0, max_value=15),
            st.integers(min_value=0, max_value=99),
        ),
        min_size=1,
        max_size=25,
    )

    @settings(
        max_examples=20,
        deadline=None,
        suppress_health_check=[HealthCheck.too_slow],
    )
    @given(script=ops)
    def run(script):
        m = HashKernelMap(gid=77, capacity=128, num_buckets=8)
        spec: dict[int, int] = {}
        for ts, (op, k, v) in enumerate(script, start=1):
            if op == "add":
                m.add(k, v, ts=ts)
                spec[k] = v
            else:
                m.remove(k, ts=ts)
                spec.pop(k, None)
        assert m.read() == spec
        # extract everything dense and replay into a fresh table: the
        # round-trip must reproduce the read AND the canonical dot set
        sl = m.M.extract_rows(m.state, jnp.arange(8, dtype=jnp.int32))
        fresh = HashKernelMap(gid=88, capacity=128, num_buckets=8)
        fresh.merge_slice(sl)
        assert fresh.read() == spec
        assert canonical_dots(fresh.state) == canonical_dots(m.state)
        assert_placement_invariant(fresh.state)

    run()
