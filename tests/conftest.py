"""Test harness config.

Tests run on CPU with 8 virtual devices so multi-chip sharding paths
(`parallel/`) are exercised without TPU hardware. Because this image
pre-imports jax at interpreter startup, the platform must be forced via
``jax.config.update`` (see below) — env vars alone are too late.
"""

# The ambient image pre-imports jax via an axon sitecustomize, so JAX_PLATFORMS
# env-var writes alone are too late; force_cpu_devices handles the dance
# (jax.config update + env var for subprocesses).
from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache, force_cpu_devices

force_cpu_devices(8)
enable_compilation_cache()

import pytest  # noqa: E402

from delta_crdt_ex_tpu.runtime.clock import LogicalClock  # noqa: E402
from delta_crdt_ex_tpu.runtime.storage import MemoryStorage  # noqa: E402
from delta_crdt_ex_tpu.runtime.transport import LocalTransport  # noqa: E402


@pytest.fixture
def transport():
    return LocalTransport()


@pytest.fixture(autouse=True)
def _clean_memory_storage():
    yield
    MemoryStorage.clear()


@pytest.fixture
def shared_clock():
    """One logical clock shared by all replicas in a test: global LWW order
    is then deterministic (ts strictly increases across the whole test)."""
    return LogicalClock()


def converge(transport, replicas, rounds: int = 6):
    """Deterministic convergence driver: repeated full sync rounds with
    message pumping — the "sync now / quiesce" hook SURVEY §4 calls for
    instead of the reference's flaky ``Process.sleep`` waits."""
    for _ in range(rounds):
        for r in replicas:
            r.sync_to_all()
        transport.pump()
