"""Test harness config.

Tests run on CPU with 8 virtual devices so multi-chip sharding paths
(`parallel/`) are exercised without TPU hardware. Because this image
pre-imports jax at interpreter startup, the platform must be forced via
``jax.config.update`` (see below) — env vars alone are too late.
"""

import os

# The ambient image pre-imports jax via an axon sitecustomize, so JAX_PLATFORMS
# has already been snapshotted into jax.config before this conftest runs —
# env-var writes alone are too late. XLA_FLAGS is still read lazily at first
# backend init, so set it here, then override the platform via jax.config.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402

from delta_crdt_ex_tpu.runtime.clock import LogicalClock  # noqa: E402
from delta_crdt_ex_tpu.runtime.storage import MemoryStorage  # noqa: E402
from delta_crdt_ex_tpu.runtime.transport import LocalTransport  # noqa: E402


@pytest.fixture
def transport():
    return LocalTransport()


@pytest.fixture(autouse=True)
def _clean_memory_storage():
    yield
    MemoryStorage.clear()


@pytest.fixture
def shared_clock():
    """One logical clock shared by all replicas in a test: global LWW order
    is then deterministic (ts strictly increases across the whole test)."""
    return LogicalClock()


def converge(transport, replicas, rounds: int = 6):
    """Deterministic convergence driver: repeated full sync rounds with
    message pumping — the "sync now / quiesce" hook SURVEY §4 calls for
    instead of the reference's flaky ``Process.sleep`` waits."""
    for _ in range(rounds):
        for r in replicas:
            r.sync_to_all()
        transport.pump()
