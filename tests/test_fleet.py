"""Batched replica fleets (ISSUE 6): one vmapped dispatch serving N
replicas must be OBSERVABLY IDENTICAL to N solo replicas — bit-for-bit
state arrays, byte-identical WAL contents, and the same outbound
protocol traffic (acks included) — while launching far fewer kernels.

Covers the pure-transition kernel parity (vmap lane == solo kernel,
ragged masking included), the runtime fleet-vs-solo parity on seeded
randomized gossip scripts (state + WAL bytes + ack streams), the
fallback paths (growth escape, ctx-gap repair, device-plane slices,
stale-version optimistic-concurrency replay), the observability
surface, and the threaded ``start_fleet`` end-to-end loop.
"""

import dataclasses
import time

import numpy as np
import jax.numpy as jnp
import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_fleet, start_link
from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.models.binned_map import (
    combine_entry_arrays,
    stack_entry_slices,
)
from delta_crdt_ex_tpu.ops.binned import RowSlice, extract_rows, merge_rows
from delta_crdt_ex_tpu.runtime import sync as sync_proto, telemetry, transition
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.fleet import Fleet
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from tests.test_ingest_coalesce import (
    _wal_segment_bytes,
    assert_state_bit_equal,
    entries_only,
    keys_for_buckets,
)

_COLS = tuple(f.name for f in dataclasses.fields(BinnedStore))


# ---------------------------------------------------------------------------
# pure-transition kernel parity: vmap lane == solo kernel, bit-for-bit


def _np_slice(sl: RowSlice) -> RowSlice:
    return RowSlice(**{c: np.asarray(getattr(sl, c)) for c in RowSlice._fields})


def _mk_states_and_slices(n, seed=0, rows_per=None):
    """n (target state, incoming slice) pairs with per-pair writers and
    overlapping keys so merges exercise inserts AND kills."""
    from tests.kernel_harness import BinnedKernelMap

    L = 16
    rng = np.random.default_rng(seed)
    states, slices = [], []
    for i in range(n):
        tgt = BinnedKernelMap(gid=100 + i, capacity=128, rcap=8, num_buckets=L)
        src = BinnedKernelMap(gid=500 + i, capacity=128, rcap=8, num_buckets=L)
        ks = keys_for_buckets(0, L, 5, mask=L - 1, start=1000 * i)
        for ts, k in enumerate(ks, start=1):
            src.add(k, int(rng.integers(0, 100)), ts=ts)
        for ts, k in enumerate(ks[:2], start=10):  # kill-pass prey
            tgt.add(k, 7, ts=ts)
        nrows = rows_per[i] if rows_per else L
        rows = jnp.asarray(np.arange(nrows, dtype=np.int32))
        states.append(tgt.state)
        slices.append(extract_rows(src.state, rows))
    return states, slices


def test_fleet_merge_rows_vmap_lane_equals_solo_kernel():
    """The tentpole property: lane k of one batched ``fleet_merge_rows``
    dispatch is bit-for-bit the solo ``merge_rows`` on lane k's inputs —
    every state column, dead slots included, plus the per-row counts."""
    n = 3
    states, slices = _mk_states_and_slices(n, seed=1)
    solo = [merge_rows(st, sl) for st, sl in zip(states, slices)]
    assert all(bool(r.ok) for r in solo)

    stacked_sl, _ = stack_entry_slices([_np_slice(s) for s in slices])
    res = transition.jit_fleet_merge_rows(
        transition.stack_states(states), stacked_sl
    )
    assert np.asarray(res.ok).all()
    for k in range(n):
        lane = transition.index_state(res.state, k)
        assert_state_bit_equal(solo[k].state, lane, f"lane {k}")
        assert np.array_equal(
            np.asarray(res.n_ins_row)[k], np.asarray(solo[k].n_ins_row)
        )
        assert np.array_equal(
            np.asarray(res.n_kill_row)[k], np.asarray(solo[k].n_kill_row)
        )


def test_fleet_merge_rows_ragged_masking_and_padding_lanes():
    """Ragged fan-in: lanes with fewer rows pad with -1 rows and lanes
    past the real member count are all-padding — both must merge as
    exact no-ops (bit parity for the real lanes, input state returned
    for padding lanes)."""
    n = 2
    states, slices = _mk_states_and_slices(n, seed=2, rows_per=[16, 4])
    solo = [merge_rows(st, sl) for st, sl in zip(states, slices)]

    np_slices = [_np_slice(s) for s in slices]
    stacked_sl, real_rows = stack_entry_slices(np_slices, lanes=4)
    assert real_rows == 16 + 4
    assert stacked_sl.rows.shape == (4, 16)  # ragged rows padded to max
    stacked_states = transition.stack_states(
        states + [states[0], states[0]]  # padding lanes replicate lane 0
    )
    res = transition.jit_fleet_merge_rows(stacked_states, stacked_sl)
    assert np.asarray(res.ok).all()
    for k in range(n):
        assert_state_bit_equal(
            solo[k].state, transition.index_state(res.state, k), f"lane {k}"
        )
    for k in (2, 3):  # all-padding lanes: exact no-op on the input state
        assert_state_bit_equal(
            states[0], transition.index_state(res.state, k), f"pad lane {k}"
        )
        assert int(np.asarray(res.n_inserted)[k]) == 0
        assert int(np.asarray(res.n_killed)[k]) == 0


def test_stack_entry_slices_rejects_unequal_lane_tiers():
    states, slices = _mk_states_and_slices(2, seed=3)
    a = _np_slice(slices[0])
    widened = RowSlice(
        **{
            **{c: np.asarray(getattr(a, c)) for c in RowSlice._fields},
            **{
                c: np.concatenate(
                    [np.asarray(getattr(a, c))] * 2, axis=1
                )
                for c in ("key", "valh", "ts", "node", "ctr", "alive")
            },
        }
    )
    with pytest.raises(ValueError, match="lane tiers"):
        stack_entry_slices([a, widened])


def test_stack_entry_slices_pads_ragged_writer_tables():
    """Unequal ctx widths pad with zero gids — empty slots that claim
    nothing (the per-replica masking half of ragged fan-in)."""
    states, slices = _mk_states_and_slices(2, seed=4)
    a, b = (_np_slice(s) for s in slices)
    # narrow b's writer table to its 1 nonzero gid + 1 pad column
    nz = np.asarray(b.ctx_gid) != 0
    keep = max(int(nz.sum()), 1) + 1
    b = RowSlice(
        **{
            **{c: np.asarray(getattr(b, c)) for c in RowSlice._fields},
            "ctx_gid": np.asarray(b.ctx_gid)[:keep],
            "ctx_rows": np.asarray(b.ctx_rows)[:, :keep],
            "ctx_lo": np.asarray(b.ctx_lo)[:, :keep],
        }
    )
    stacked, _ = stack_entry_slices([a, b])
    assert stacked.ctx_gid.shape == (2, np.asarray(a.ctx_gid).shape[0])
    # the padded columns are all-zero gids claiming nothing
    gids_b = np.asarray(stacked.ctx_gid)[1]
    assert (gids_b[keep:] == 0).all()
    res = transition.jit_fleet_merge_rows(
        transition.stack_states(states), stacked
    )
    assert np.asarray(res.ok).all()
    solo = [merge_rows(st, sl) for st, sl in zip(states, slices)]
    for k in range(2):
        assert_state_bit_equal(
            solo[k].state, transition.index_state(res.state, k), f"lane {k}"
        )


# ---------------------------------------------------------------------------
# runtime parity: fleet vs N solo replicas, identical streams


def _mk_sender(transport, clock, i, **opts):
    # in-flight sync slots must not expire mid-test: a wall-clock expiry
    # landing between the fleet drain and the solo twins' loop on a
    # loaded host would re-open a walk toward one twin only and fail the
    # stream-parity asserts spuriously
    opts.setdefault("sync_timeout", 600.0)
    return start_link(
        AWLWWMap,
        threaded=False,
        transport=transport,
        clock=clock,
        capacity=64,
        tree_depth=6,
        name=f"fs{i}",
        **opts,
    )


def _mk_pairs(transport, clock, n, tmp=None, **opts):
    """n fleet receivers + n solo receivers, pairwise-equal node ids so
    their states are bit-comparable; optional per-member WALs."""
    wal = lambda tag, i: (
        {"wal_dir": str(tmp / f"{tag}{i}"), "fsync_mode": "none"} if tmp else {}
    )
    fleet = Fleet(
        [
            start_link(
                AWLWWMap, threaded=False, transport=transport, clock=clock,
                capacity=64, tree_depth=6, node_id=1000 + i, name=f"ff{i}",
                **wal("f", i), **opts,
            )
            for i in range(n)
        ]
    )
    solos = [
        start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=64, tree_depth=6, node_id=1000 + i, name=f"fo{i}",
            **wal("o", i), **opts,
        )
        for i in range(n)
    ]
    return fleet, solos


def _norm_msg(m, addr_map):
    """Wire-normal form of an outbound protocol message for stream
    comparison: type name + payload fields, receiver addresses replaced
    by pair-invariant tokens."""
    sub = lambda v: addr_map.get(v, v)
    t = type(m).__name__
    if isinstance(m, sync_proto.AckMsg):
        return (t, sub(m.clear_addr))
    if isinstance(m, sync_proto.DiffMsg):
        return (
            t, sub(m.originator), sub(m.frm), m.level, m.idx.tolist(),
            [b.tolist() for b in m.blocks], m.seq, m.log_horizon,
        )
    if isinstance(m, sync_proto.GetDiffMsg):
        return (t, sub(m.originator), sub(m.frm), np.asarray(m.buckets).tolist())
    if isinstance(m, sync_proto.GetLogMsg):
        return (t, sub(m.frm), m.last_seq, m.applied_seq)
    return (t, repr(m))


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_fleet_vs_solo_bit_for_bit_parity_randomized(seed, tmp_path):
    """THE acceptance property (ISSUE 6): seeded randomized gossip
    scripts fed identically to a fleet and to N solo replicas end with
    bit-identical states, sequence numbers, byte-identical WAL segment
    contents, and identical outbound protocol streams (acks included) —
    while the fleet actually batched across replicas."""
    rng = np.random.default_rng(seed)
    transport = LocalTransport()
    clock = LogicalClock()
    n = 3
    senders = [_mk_sender(transport, clock, i) for i in range(n)]
    fleet, solos = _mk_pairs(transport, clock, n, tmp=tmp_path)
    for i, s in enumerate(senders):
        s.set_neighbours([fleet.replicas[i], solos[i]])
    addr_map = {}
    for i in range(n):
        addr_map[fleet.replicas[i].addr] = f"recv{i}"
        addr_map[solos[i].addr] = f"recv{i}"

    done: list = []
    handler = lambda _e, meas, meta: done.append(
        (meta["name"], meas["keys_updated_count"])
    )
    telemetry.attach(telemetry.SYNC_DONE, handler)
    try:
        for _round in range(int(rng.integers(2, 5))):
            for _ in range(int(rng.integers(1, 10))):
                i = int(rng.integers(0, n))
                ki = int(rng.integers(0, 12))
                if rng.random() < 0.7:
                    senders[i].mutate("add", [ki, int(rng.integers(0, 100))])
                else:
                    senders[i].mutate("remove", [ki])
            for s in senders:
                s.sync_to_all()
            fleet.drain()
            for r in solos:
                r.process_pending()
            # walk replies / acks flow back: compare each sender's
            # per-receiver stream, fleet vs solo — byte-normal equal
            for i, s in enumerate(senders):
                back = transport.drain(s.addr)
                from_f = [
                    _norm_msg(m, addr_map)
                    for m in back
                    if getattr(m, "frm", getattr(m, "clear_addr", None))
                    in (fleet.replicas[i].addr,)
                    or getattr(m, "clear_addr", None) == fleet.replicas[i].addr
                ]
                from_s = [
                    _norm_msg(m, addr_map)
                    for m in back
                    if getattr(m, "frm", getattr(m, "clear_addr", None))
                    in (solos[i].addr,)
                    or getattr(m, "clear_addr", None) == solos[i].addr
                ]
                assert from_f == from_s, (seed, i)
    finally:
        telemetry.detach(telemetry.SYNC_DONE, handler)

    for i in range(n):
        rf, rs = fleet.replicas[i], solos[i]
        assert rf.read() == rs.read()
        assert rf._seq == rs._seq
        assert_state_bit_equal(rf.state, rs.state, (seed, i))
        assert _wal_segment_bytes(rf) == _wal_segment_bytes(rs)
        # per-message SYNC_DONE parity, pairwise
        assert [c for nme, c in done if nme == rf.name] == [
            c for nme, c in done if nme == rs.name
        ], (seed, i)


def test_fleet_batches_across_replicas_and_counts(tmp_path):
    """The fleet must actually batch: one wave of N singleton groups
    rides ONE vmapped dispatch (occupancy N), and the observability
    surfaces (fleet stats, member stats, FLEET_DISPATCH telemetry)
    agree."""
    transport = LocalTransport()
    clock = LogicalClock()
    n = 4
    senders = [_mk_sender(transport, clock, i) for i in range(n)]
    fleet, solos = _mk_pairs(transport, clock, n)
    for i, s in enumerate(senders):
        s.set_neighbours([fleet.replicas[i]])

    events = []
    handler = lambda _e, meas, _m: events.append(meas)
    telemetry.attach(telemetry.FLEET_DISPATCH, handler)
    try:
        for i, s in enumerate(senders):
            for k in keys_for_buckets(0, 64, 3, start=777 * i):
                s.mutate("add", [k, k])
            s.sync_to_all()
        for r in fleet.replicas:
            entries_only(transport, r.addr)
        fleet.drain()
    finally:
        telemetry.detach(telemetry.FLEET_DISPATCH, handler)

    st = fleet.stats()
    assert st["dispatches"] == 1
    assert st["occupancy_hist"] == {n: 1}
    assert st["avg_occupancy"] == n
    assert st["batched_messages"] == n
    assert 0 < st["ragged_fill_ratio"] <= 1
    assert st["ticks"] >= 1 and st["ticks_per_sec"] > 0
    for r in fleet.replicas:
        assert r.stats()["fleet"] == {
            "dispatches": 1,
            "batched_messages": 1,
            "fallbacks": 0,
        }
        assert len(r.read()) == 3
    assert len(events) == 1 and events[0]["replicas"] == n
    assert events[0]["rows"] <= events[0]["padded_rows"]


def test_fleet_growth_escape_falls_back_solo(tmp_path):
    """A member whose bin tier overflows mid-batch (need_fill_grow)
    must fall back to the solo growth path while clean members keep the
    batched result — end states still match the solo universe."""
    transport = LocalTransport()
    clock = LogicalClock()
    n = 2
    senders = [_mk_sender(transport, clock, i) for i in range(n)]
    # tiny bins: 64 capacity / 64 buckets → 4-slot bins (the floor).
    # Each sender writes >4 same-bucket keys: its own bin grows to 8
    # (equal S=8 slices, so the two groups share one batch bucket) and
    # the receivers' 4-slot bins overflow mid-batch → need_fill_grow
    fleet, solos = _mk_pairs(transport, clock, n)
    for i, s in enumerate(senders):
        s.set_neighbours([fleet.replicas[i], solos[i]])
    for k in keys_for_buckets(3, 4, 6, start=0):
        senders[0].mutate("add", [k, "x"])
    for k in keys_for_buckets(40, 41, 5, start=50_000):
        senders[1].mutate("add", [k, "y"])
    for s in senders:
        s.sync_to_all()
    for r in list(fleet.replicas) + solos:
        entries_only(transport, r.addr)
    fleet.drain()
    for r in solos:
        r.process_pending()
    st = fleet.stats()
    assert st["dispatches"] == 1  # the batch WAS launched...
    assert st["fallbacks"]["escape"] == 2  # ...and both lanes escaped
    for i in range(n):
        assert fleet.replicas[i].read() == solos[i].read()
        assert_state_bit_equal(fleet.replicas[i].state, solos[i].state, i)


def test_fleet_gap_partitions_and_repairs_like_solo(tmp_path):
    """A lost earlier push gaps one member's group mid-batch: the
    escape fallback must route through the solo gap machinery — the
    gapped source gets its GetDiffMsg repair, clean members commit the
    batch, and post-repair states match solo bit-for-bit."""
    transport = LocalTransport()
    clock = LogicalClock()
    n = 2
    senders = [_mk_sender(transport, clock, i) for i in range(n)]
    fleet, solos = _mk_pairs(transport, clock, n)
    for i, s in enumerate(senders):
        s.set_neighbours([fleet.replicas[i], solos[i]])

    k1a, k1b = keys_for_buckets(3, 4, 2)
    senders[0].mutate("add", [k1a, "one"])
    senders[0].sync_to_all()
    for r in list(fleet.replicas) + solos:
        transport.drain(r.addr)  # the push is LOST everywhere

    senders[0].mutate("add", [k1b, "two"])  # same bucket: interval gaps
    (k2,) = keys_for_buckets(40, 48, 1)
    senders[1].mutate("add", [k2, "other"])
    for s in senders:
        s.sync_to_all()
    for r in list(fleet.replicas) + solos:
        entries_only(transport, r.addr)
    fleet.drain()
    for r in solos:
        r.process_pending()

    assert fleet.stats()["fallbacks"]["escape"] >= 1
    gets = [
        m
        for m in transport.drain(senders[0].addr)
        if isinstance(m, sync_proto.GetDiffMsg)
    ]
    assert sorted(m.frm for m in gets) == sorted(
        [fleet.replicas[0].addr, solos[0].addr]
    )
    for m in gets:
        senders[0].handle(m)  # repair
    for r in list(fleet.replicas) + solos:
        entries_only(transport, r.addr)
    fleet.drain()
    for r in solos:
        r.process_pending()
    for i in range(n):
        assert fleet.replicas[i].read() == solos[i].read()
        assert_state_bit_equal(fleet.replicas[i].state, solos[i].state, i)


def test_fleet_device_plane_slices_keep_solo_path():
    """Device-plane slices (non-numpy columns) must never enter the
    host-side batch — they reroute through the per-replica path."""
    transport = LocalTransport()
    clock = LogicalClock()
    senders = [_mk_sender(transport, clock, i) for i in range(2)]
    fleet, _ = _mk_pairs(transport, clock, 2)
    for i, s in enumerate(senders):
        s.set_neighbours([fleet.replicas[i]])
    for i, s in enumerate(senders):
        s.mutate("add", [keys_for_buckets(0, 64, 1, start=i * 999)[0], i])
        s.sync_to_all()
    # re-plane every queued EntriesMsg onto the device data plane
    for r in fleet.replicas:
        msgs = transport.drain(r.addr)
        for m in msgs:
            if isinstance(m, sync_proto.EntriesMsg):
                m.arrays = {
                    c: (jnp.asarray(v) if c != "rows" else v)
                    for c, v in m.arrays.items()
                }
            transport.send(r.addr, m)
    fleet.drain()
    assert fleet.stats()["fallbacks"]["shape"] >= 1 or (
        fleet.stats()["fallbacks"]["singleton"] >= 1
    )
    for i, r in enumerate(fleet.replicas):
        assert len(r.read()) == 1


def test_fleet_stale_version_refuses_commit():
    """Optimistic concurrency: a member whose state moved between
    staging and commit must refuse the batched result (the merge read a
    stale state) and leave the replica untouched."""
    transport = LocalTransport()
    clock = LogicalClock()
    s = _mk_sender(transport, clock, 0)
    fleet, _ = _mk_pairs(transport, clock, 2)
    rep = fleet.replicas[0]
    s.set_neighbours([rep])
    s.mutate("add", [keys_for_buckets(0, 64, 1)[0], "v"])
    s.sync_to_all()
    msgs = [
        m
        for m in transport.drain(rep.addr)
        if isinstance(m, sync_proto.EntriesMsg)
    ]
    assert msgs
    prep = rep.fleet_prepare(msgs)
    assert prep is not None
    _sl, offsets, version, _geom = prep
    rep.mutate("add", [keys_for_buckets(0, 64, 1, start=12345)[0], "w"])
    seq_before = rep._seq
    assert not rep.fleet_commit(
        msgs, offsets, None, 0, lambda: (None, None), 0, 0.0, version
    )
    assert rep._seq == seq_before  # untouched: the fleet replays solo


def test_fleet_rejects_threaded_members():
    transport = LocalTransport()
    clock = LogicalClock()
    r = _mk_sender(transport, clock, 0)
    r.start()
    try:
        with pytest.raises(ValueError, match="threaded=False"):
            Fleet([r])
    finally:
        r.stop()
    with pytest.raises(ValueError, match="at least one"):
        Fleet([])
    # and the inverse: a fleet member must not start its own loop
    r2 = _mk_sender(transport, clock, 99)
    Fleet([r2, _mk_sender(transport, clock, 98)])
    with pytest.raises(ValueError, match="fleet member"):
        r2.start()
    # nor join a second fleet (two drains of one mailbox race)
    with pytest.raises(ValueError, match="already belongs"):
        Fleet([r2, _mk_sender(transport, clock, 97)])


def test_start_fleet_threaded_end_to_end():
    """The api entry point: a threaded fleet of mutually-syncing
    members converges through its single shared event loop."""
    transport = LocalTransport()
    clock = LogicalClock()
    fleet = start_fleet(
        3,
        transport=transport,
        clock=clock,
        capacity=64,
        tree_depth=6,
        sync_interval=0.02,
        names=["fa", "fb", "fc"],
    )
    try:
        a, b, c = fleet.replicas
        for r in fleet.replicas:
            r.set_neighbours([x for x in fleet.replicas if x is not r])

        def converged(want):
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                if all(r.read() == want for r in fleet.replicas):
                    return True
                time.sleep(0.02)
            return False

        a.mutate("add", ["k1", 1])
        b.mutate("add", ["k2", 2])
        assert converged({"k1": 1, "k2": 2})
        # c has OBSERVED k1 now, so its remove wins everywhere
        c.mutate("remove", ["k1"])
        assert converged({"k2": 2})
        assert fleet.stats()["ticks"] >= 1
    finally:
        fleet.stop()


def test_fleet_member_wal_recovery_round_trip(tmp_path):
    """A fleet member's WAL is the ordinary per-replica WAL: crash and
    restart with the same name + wal_dir rehydrates the merged state."""
    transport = LocalTransport()
    clock = LogicalClock()
    s = _mk_sender(transport, clock, 0)
    fleet, _ = _mk_pairs(transport, clock, 2, tmp=tmp_path)
    rep = fleet.replicas[0]
    s.set_neighbours([rep])
    keys = keys_for_buckets(0, 64, 4)
    for k in keys:
        s.mutate("add", [k, f"v{k}"])
    s.sync_to_all()
    entries_only(transport, rep.addr)
    fleet.drain()
    want = rep.read()
    assert len(want) == 4
    node_id = rep.node_id
    rep.crash()
    reborn = start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock,
        capacity=64, tree_depth=6, name=rep.name,
        wal_dir=str(tmp_path / "f0"), fsync_mode="none",
    )
    assert reborn.node_id == node_id
    assert reborn.read() == want
    reborn.crash()
