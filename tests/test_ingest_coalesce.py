"""Ingress coalescing (ISSUE 3): grouped fan-in merges on the replica
hot path must be OBSERVABLY IDENTICAL to sequential per-slice handling —
bit-for-bit state arrays, the same outbound protocol messages, and
byte-identical WAL contents — while cutting kernel dispatches.

Also covers the batch-receive transport API (``drain_nowait``), the
mid-group ``CtxGapError`` repair fallback, the coalescing stats surface,
and membership-driven WAL compaction (ack-watermark-gated reclaim).
"""

import dataclasses
import time

import numpy as np
import jax.numpy as jnp
import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.models.binned_map import combine_entry_arrays, merge_group_into
from delta_crdt_ex_tpu.ops.binned import RowSlice, extract_rows, merge_rows
from delta_crdt_ex_tpu.runtime import sync as sync_proto, telemetry
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.transport import Down, LocalTransport
from delta_crdt_ex_tpu.utils.hashing import key_hash64
from tests.conftest import converge

_COLS = tuple(f.name for f in dataclasses.fields(BinnedStore))


def keys_for_buckets(lo, hi, n, mask=63, start=0):
    """``n`` distinct int key terms whose hash buckets land in
    ``[lo, hi)`` — lets tests give each sender a disjoint bucket range,
    the workload shape coalescing groups maximally."""
    out, k = [], start
    while len(out) < n:
        if lo <= (key_hash64(k) & mask) < hi:
            out.append(k)
        k += 1
    return out


def assert_state_bit_equal(s1, s2, ctx=""):
    for c in _COLS:
        assert np.array_equal(
            np.asarray(getattr(s1, c)), np.asarray(getattr(s2, c))
        ), (ctx, c)


def entries_only(transport, addr):
    """Drain an address and re-queue only its EntriesMsgs, preserving
    order — engineers a consecutive entries run for the coalescer."""
    msgs = [
        m
        for m in transport.drain(addr)
        if isinstance(m, sync_proto.EntriesMsg)
    ]
    for m in msgs:
        transport.send(addr, m)
    return len(msgs)


# ---------------------------------------------------------------------------
# transport batch receive


def test_drain_nowait_bounded_and_ordered():
    t = LocalTransport()
    t.register("a", None)
    for i in range(10):
        t.send("a", i)
    assert t.drain_nowait("a", 4) == [0, 1, 2, 3]
    assert t.drain_nowait("a", 4) == [4, 5, 6, 7]
    assert t.drain_nowait("a", 4) == [8, 9]
    assert t.drain_nowait("a", 4) == []
    assert t.drain_nowait("missing", 4) == []


def test_drain_nowait_down_not_reordered_past_entries():
    t = LocalTransport()
    t.register("a", None)
    t.register("b", None)
    assert t.monitor("a", "b")
    t.send("a", "e1")
    t.send("a", "e2")
    t.unregister("b")  # queues Down("b") AFTER the entries
    assert t.drain_nowait("a", 10) == ["e1", "e2", Down("b")]


def test_drain_nowait_tcp_parity():
    tcp = pytest.importorskip("delta_crdt_ex_tpu.runtime.tcp_transport")
    t = tcp.TcpTransport()
    try:
        t.register("a", None)
        for i in range(5):
            t.send("a", i)
        assert t.drain_nowait("a", 3) == [0, 1, 2]
        assert t.drain_nowait("a", None) == [3, 4]
        assert t.drain("a") == []
    finally:
        t.close()


# ---------------------------------------------------------------------------
# kernel-level parity: one grouped dispatch == sequential merges


def _slice_wire(sl):
    return {c: np.asarray(getattr(sl, c)) for c in RowSlice._fields}


def test_merge_group_kernel_parity_bit_for_bit():
    """Merging k disjoint-row slices with ONE ``merge_group_into``
    dispatch equals the k sequential ``merge_rows`` merges on EVERY
    state column — including dead-slot bytes and the gid table's slot
    assignment order."""
    from tests.kernel_harness import BinnedKernelMap

    L = 16
    rng = np.random.default_rng(5)
    for trial in range(4):
        tgt = BinnedKernelMap(gid=100, capacity=128, rcap=8, num_buckets=L)
        b = BinnedKernelMap(gid=200 + trial, capacity=128, rcap=8, num_buckets=L)
        c = BinnedKernelMap(gid=300 + trial, capacity=128, rcap=8, num_buckets=L)
        kb = keys_for_buckets(0, 8, 6, mask=L - 1, start=1000 * trial)
        kc = keys_for_buckets(8, 16, 6, mask=L - 1, start=1000 * trial)
        for ts, k in enumerate(kb, start=1):
            b.add(k, int(rng.integers(0, 100)), ts=ts)
        for ts, k in enumerate(kc, start=1):
            c.add(k, int(rng.integers(0, 100)), ts=ts)
        # target pre-observes some of b, so the kill pass has local prey
        for ts, k in enumerate(kb[:3], start=10):
            tgt.add(k, 7, ts=ts)

        rows_b = jnp.asarray(np.arange(0, 8, dtype=np.int32))
        rows_c = jnp.asarray(np.arange(8, 16, dtype=np.int32))
        sl_b = extract_rows(b.state, rows_b)
        sl_c = extract_rows(c.state, rows_c)

        r1 = merge_rows(tgt.state, sl_b)
        assert bool(r1.ok), trial
        r2 = merge_rows(r1.state, sl_c)
        assert bool(r2.ok), trial

        g_state, g_res, offsets = merge_group_into(
            tgt.state, [_slice_wire(sl_b), _slice_wire(sl_c)]
        )
        assert offsets == [(0, 8), (8, 16)]
        assert_state_bit_equal(r2.state, g_state, trial)
        # per-row counts decompose the totals over each message's range
        ins_row = np.asarray(g_res.n_ins_row)
        kill_row = np.asarray(g_res.n_kill_row)
        assert int(ins_row[0:8].sum() + kill_row[0:8].sum()) == int(
            r1.n_inserted
        ) + int(r1.n_killed)
        assert int(ins_row[8:16].sum() + kill_row[8:16].sum()) == int(
            r2.n_inserted
        ) + int(r2.n_killed)


def test_combine_entry_arrays_unions_writer_tables():
    """Messages with different writer tables (an interval push's
    one-writer table next to a full-row slice's R-wide table) combine
    into one first-appearance-ordered union; empty slots claim nothing."""
    from tests.kernel_harness import BinnedKernelMap

    L = 16
    b = BinnedKernelMap(gid=11, capacity=128, rcap=8, num_buckets=L)
    c = BinnedKernelMap(gid=22, capacity=128, rcap=8, num_buckets=L)
    for ts, k in enumerate(keys_for_buckets(0, 8, 3, mask=L - 1), start=1):
        b.add(k, 1, ts=ts)
    for ts, k in enumerate(keys_for_buckets(8, 16, 3, mask=L - 1), start=1):
        c.add(k, 2, ts=ts)
    sl_b = extract_rows(b.state, jnp.asarray(np.arange(0, 8, dtype=np.int32)))
    sl_c = extract_rows(c.state, jnp.asarray(np.arange(8, 16, dtype=np.int32)))
    combined, offsets = combine_entry_arrays([_slice_wire(sl_b), _slice_wire(sl_c)])
    gids = np.asarray(combined.ctx_gid)
    nz = gids[gids != 0].tolist()
    assert nz == [11, 22]  # first-appearance order, deduped, zero-padded
    assert offsets == [(0, 8), (8, 16)]
    # claims stay per-message: c's rows claim nothing for writer 11
    crows = np.asarray(combined.ctx_rows)
    clo = np.asarray(combined.ctx_lo)
    col11 = int(np.nonzero(gids == 11)[0][0])
    assert not (crows[8:16, col11] > clo[8:16, col11]).any()


# ---------------------------------------------------------------------------
# runtime-level parity: coalesced vs sequential ingest


def _mk_sender(transport, clock, i):
    return start_link(
        AWLWWMap,
        threaded=False,
        transport=transport,
        clock=clock,
        capacity=64,
        tree_depth=6,
        name=f"sender{i}",
    )


def _mk_receiver(transport, clock, tmp, coalesce, **opts):
    return start_link(
        AWLWWMap,
        threaded=False,
        transport=transport,
        clock=clock,
        capacity=64,
        tree_depth=6,
        node_id=777,  # equal ids: receiver states must be bit-comparable
        name=f"recv_{'c' if coalesce else 's'}",
        wal_dir=str(tmp),
        fsync_mode="none",
        ingress_coalesce=coalesce,
        **opts,
    )


def _wal_segment_bytes(rep):
    rep._wal.close(flush=True)
    out = b""
    for p in sorted(rep._wal.segment_paths()):
        with open(p, "rb") as f:
            out += f.read()
    return out


def test_coalesced_ingest_bit_for_bit_parity(tmp_path):
    """The acceptance property: a coalescing receiver and a sequential
    receiver fed the IDENTICAL message stream end with bit-identical
    state arrays, sequence numbers, reads, per-message SYNC_DONE counts,
    and byte-identical WAL segment contents — while the coalescing side
    used fewer kernel dispatches than messages."""
    transport = LocalTransport()
    clock = LogicalClock()
    senders = [_mk_sender(transport, clock, i) for i in range(4)]
    rc = _mk_receiver(transport, clock, tmp_path / "c", True)
    rs = _mk_receiver(transport, clock, tmp_path / "s", False)
    for s in senders:
        s.set_neighbours([rc, rs])

    done: list = []
    handler = lambda _e, meas, meta: done.append(
        (meta["name"], meas["keys_updated_count"])
    )
    telemetry.attach(telemetry.SYNC_DONE, handler)
    try:
        key_sets = [
            keys_for_buckets(i * 16, (i + 1) * 16, 6, start=10_000 * i)
            for i in range(4)
        ]
        # round 1: adds (interval delta pushes)
        for i, s in enumerate(senders):
            for k in key_sets[i]:
                s.mutate("add", [k, f"v{k}"])
        for s in senders:
            s.sync_to_all()
        for r in (rc, rs):
            entries_only(transport, r.addr)
            r.process_pending()
        # round 2: removes + fresh adds (full-row pushes ride along)
        for i, s in enumerate(senders):
            s.mutate("remove", [key_sets[i][0]])
            for k in keys_for_buckets(
                i * 16, (i + 1) * 16, 2, start=10_000 * i + 5000
            ):
                s.mutate("add", [k, f"w{k}"])
        for s in senders:
            s.sync_to_all()
        for r in (rc, rs):
            entries_only(transport, r.addr)
            r.process_pending()
        for s in senders:  # drop walk back-traffic: pushes carry all data
            transport.drain(s.addr)
    finally:
        telemetry.detach(telemetry.SYNC_DONE, handler)

    assert rc.read() == rs.read() and len(rc.read()) == 24 - 4 + 8
    assert rc._seq == rs._seq > 0
    assert_state_bit_equal(rc.state, rs.state, "runtime parity")
    # per-message telemetry parity: same SYNC_DONE count sequence
    assert [c for n, c in done if n == rc.name] == [
        c for n, c in done if n == rs.name
    ]
    # the coalescing side actually batched (disjoint sender buckets)
    st = rc.stats()["ingress"]
    assert st["messages"] > st["dispatches"] >= 1
    assert st["merges_per_dispatch"] > 1
    assert max(st["coalesce_depth_hist"]) >= 2
    assert rs.stats()["ingress"]["dispatches"] == 0  # off: plain handle()
    # WAL: same records, byte-for-byte
    assert _wal_segment_bytes(rc) == _wal_segment_bytes(rs) != b""


def test_gap_mid_group_falls_back_and_repairs(tmp_path):
    """A lost earlier push makes one group member non-contiguous: the
    grouped join raises CtxGapError with the gapped member identified
    (per-row gap mask), handling PARTITIONS — the clean member still
    merges grouped, only the gapped source replays solo and gets the
    GetDiffMsg repair — and after the repair both receivers converge
    identically."""
    transport = LocalTransport()
    clock = LogicalClock()
    s1 = _mk_sender(transport, clock, 1)
    s2 = _mk_sender(transport, clock, 2)
    rc = _mk_receiver(transport, clock, tmp_path / "c", True)
    rs = _mk_receiver(transport, clock, tmp_path / "s", False)
    for s in (s1, s2):
        s.set_neighbours([rc, rs])

    k1a, k1b = keys_for_buckets(3, 4, 2)  # same bucket: counters chain
    (k2,) = keys_for_buckets(40, 48, 1)
    s1.mutate("add", [k1a, "one"])
    s1.sync_to_all()
    transport.drain(rc.addr)  # the push is LOST at both receivers
    transport.drain(rs.addr)

    s1.mutate("add", [k1b, "two"])  # same bucket: interval now gaps
    s2.mutate("add", [k2, "other"])
    for s in (s1, s2):
        s.sync_to_all()
    for r in (rc, rs):
        n = entries_only(transport, r.addr)
        assert n == 2  # one gapped push + one good push, consecutive
        r.process_pending()

    # the coalescer PARTITIONED: the gapped member was identified from
    # the kernel's per-row gap mask, so the clean member stayed grouped
    # and no whole-group fallback was needed
    assert rc.stats()["ingress"]["gap_partitions"] == 1
    assert rc.stats()["ingress"]["gap_fallbacks"] == 0
    for r in (rc, rs):
        assert r.read() == {k2: "other"}  # gapped slice not applied
    # both receivers asked the gapped source (and only it) for full rows
    gets = [
        m
        for m in transport.drain(s1.addr)
        if isinstance(m, sync_proto.GetDiffMsg)
    ]
    assert sorted(m.frm for m in gets) == sorted([rc.addr, rs.addr])
    assert not any(
        isinstance(m, sync_proto.GetDiffMsg) for m in transport.drain(s2.addr)
    )
    for m in gets:
        s1.handle(m)  # repair: full-row slices back to each receiver
    for r in (rc, rs):
        entries_only(transport, r.addr)
        r.process_pending()
        assert r.read() == {k1a: "one", k1b: "two", k2: "other"}
    assert_state_bit_equal(rc.state, rs.state, "post-repair")


def test_non_entries_messages_break_runs_in_order(tmp_path):
    """A Down between two entries runs is handled in place — the second
    run's merges happen after the monitor pruning, never before."""
    transport = LocalTransport()
    clock = LogicalClock()
    s1 = _mk_sender(transport, clock, 1)
    rc = _mk_receiver(transport, clock, tmp_path / "c", True)
    s1.set_neighbours([rc])
    s1.mutate("add", [1, "x"])
    s1.sync_to_all()
    entries_only(transport, rc.addr)
    rc._monitors.add(s1.addr)
    transport.send(rc.addr, Down(s1.addr))
    rc.process_pending()
    assert rc.read() == {1: "x"}
    assert s1.addr not in rc._monitors


def test_coalesce_disabled_matches_old_drain_path(tmp_path):
    """ingress_coalesce=False routes through plain handle() — stats
    stay zero and behaviour matches the pre-coalescing event loop."""
    transport = LocalTransport()
    clock = LogicalClock()
    s = _mk_sender(transport, clock, 0)
    r = _mk_receiver(transport, clock, tmp_path / "r", False)
    s.set_neighbours([r])
    s.mutate("add", ["k", 1])
    s.sync_to_all()
    r.process_pending()
    assert r.read() == {"k": 1}
    ing = r.stats()["ingress"]
    assert ing == {
        "messages": 0,
        "dispatches": 0,
        "merges_per_dispatch": 0.0,
        "coalesce_depth_hist": {},
        "gap_fallbacks": 0,
        "gap_partitions": 0,
    }


# ---------------------------------------------------------------------------
# membership-driven WAL compaction


def _mk_wal_writer(transport, clock, tmp, **opts):
    return start_link(
        AWLWWMap,
        threaded=False,
        transport=transport,
        clock=clock,
        capacity=64,
        tree_depth=6,
        name=opts.pop("name", "w"),
        wal_dir=str(tmp),
        fsync_mode="none",
        segment_bytes=256,  # roll every few records
        compact_every=10**9,  # compaction driven manually via checkpoint()
        sync_timeout=0.05,  # in-flight slots from dropped rounds expire fast
        **opts,
    )


def test_membership_compaction_gates_reclaim_on_lagging_peer(tmp_path):
    transport = LocalTransport()
    clock = LogicalClock()
    w = _mk_wal_writer(transport, clock, tmp_path / "w")
    p = _mk_sender(transport, clock, 9)
    w.set_neighbours([p])
    for i in range(12):
        w.mutate("add", [i, i])
    transport.drain(p.addr)  # the peer lags: it saw nothing
    n_before = len(w._wal.segment_paths())
    assert n_before > 1  # small segment_bytes rolled several segments

    w.checkpoint()  # snapshot written, but reclaim is gated at floor 0
    assert w.stats()["wal"]["ack_floor"] == 0
    assert len(w._wal.segment_paths()) >= n_before - 1  # nothing reclaimed
    # (the active segment may have rotated; covered ones must survive)

    time.sleep(0.06)  # let the dropped opening round's in-flight slot expire
    converge(transport, [w, p])  # peer catches up; equality round acks
    assert p.read() == w.read()
    assert w._ack_seq.get(p.addr, 0) > 0
    w.checkpoint()  # all monitored peers past the records: reclaim all
    assert len(w._wal.segment_paths()) <= 1


def test_membership_compaction_ignores_departed_peers(tmp_path):
    transport = LocalTransport()
    clock = LogicalClock()
    w = _mk_wal_writer(transport, clock, tmp_path / "w")
    p = _mk_sender(transport, clock, 9)
    w.set_neighbours([p])
    for i in range(12):
        w.mutate("add", [i, i])
    transport.drain(p.addr)
    p.transport.unregister(p.addr)  # peer dies: Down fires at w
    w.process_pending()
    w.checkpoint()
    assert len(w._wal.segment_paths()) <= 1  # dead peers don't gate


def test_membership_compaction_retention_is_bounded(tmp_path):
    """A monitored peer that NEVER acks (a pure fan-in aggregator's
    tree always differs from one writer's, so equality acks never fire)
    must not pin reclaim at zero forever: at most ``membership_retain``
    records stay past the ack floor, the rest reclaim."""
    transport = LocalTransport()
    clock = LogicalClock()
    w = _mk_wal_writer(transport, clock, tmp_path / "w", membership_retain=4)
    p = _mk_sender(transport, clock, 9)
    w.set_neighbours([p])
    for i in range(12):
        w.mutate("add", [i, i])
    transport.drain(p.addr)  # peer lags and will never ack
    n_before = len(w._wal.segment_paths())
    assert n_before > 1
    w.checkpoint()  # floor = max(ack 0, seq 12 - retain 4) = 8
    n_after = len(w._wal.segment_paths())
    assert 1 <= n_after < n_before  # old history reclaimed, recent kept
    # the retained segments still cover the last `membership_retain` seqs
    kept = []
    for path in w._wal.segment_paths():
        start = int(path.rsplit("seg-", 1)[1][:-4])
        kept.append(start)
    assert min(kept) <= 12 - 4 + 1 <= 12  # horizon segment survives


def test_membership_compaction_opt_out(tmp_path):
    transport = LocalTransport()
    clock = LogicalClock()
    w = _mk_wal_writer(
        transport, clock, tmp_path / "w", membership_compaction=False
    )
    p = _mk_sender(transport, clock, 9)
    w.set_neighbours([p])
    for i in range(12):
        w.mutate("add", [i, i])
    transport.drain(p.addr)  # lagging peer, but the gate is off
    w.checkpoint()
    assert len(w._wal.segment_paths()) <= 1


# ---------------------------------------------------------------------------
# property: random scripts, coalesced == sequential


@pytest.mark.parametrize("seed", [0, 1, 2, 3])
def test_randomized_scripts_coalesced_equals_sequential(seed):
    """Seeded stand-in for the hypothesis property below (the container
    may lack hypothesis): random add/remove scripts across 3 senders,
    synced in random-size rounds, must leave a coalescing receiver and a
    sequential receiver bit-identical."""
    rng = np.random.default_rng(seed)
    transport = LocalTransport()
    clock = LogicalClock()
    senders = [_mk_sender(transport, clock, i) for i in range(3)]
    rc = start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock,
        capacity=64, tree_depth=6, node_id=777, name="rand_c",
        ingress_coalesce=True, max_coalesce=4,
    )
    rs = start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock,
        capacity=64, tree_depth=6, node_id=777, name="rand_s",
        ingress_coalesce=False,
    )
    for s in senders:
        s.set_neighbours([rc, rs])
    for _round in range(int(rng.integers(1, 4))):
        for _ in range(int(rng.integers(1, 8))):
            who = senders[int(rng.integers(0, 3))]
            ki = int(rng.integers(0, 12))
            if rng.random() < 0.75:
                who.mutate("add", [ki, int(rng.integers(0, 100))])
            else:
                who.mutate("remove", [ki])
        for s in senders:
            s.sync_to_all()
        for r in (rc, rs):
            entries_only(transport, r.addr)
            r.process_pending()
        for s in senders:
            transport.drain(s.addr)
    assert rc.read() == rs.read()
    assert rc._seq == rs._seq
    assert_state_bit_equal(rc.state, rs.state, seed)


try:
    from hypothesis import given, settings, strategies as st

    HAVE_HYPOTHESIS = True
except ImportError:  # pragma: no cover - optional dep
    HAVE_HYPOTHESIS = False


if HAVE_HYPOTHESIS:

    @settings(max_examples=8, deadline=None)
    @given(
        st.lists(
            st.tuples(
                st.integers(min_value=0, max_value=2),  # sender
                st.sampled_from(["add", "add", "add", "remove"]),
                st.integers(min_value=0, max_value=11),  # key index
                st.integers(min_value=0, max_value=99),  # value
            ),
            min_size=1,
            max_size=16,
        ),
        st.integers(min_value=1, max_value=3),  # sync rounds interleaved
    )
    def test_property_coalesced_equals_sequential(script, rounds):
        transport = LocalTransport()
        clock = LogicalClock()
        senders = [_mk_sender(transport, clock, i) for i in range(3)]
        rc = start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=64, tree_depth=6, node_id=777, name="prop_c",
            ingress_coalesce=True, max_coalesce=4,
        )
        rs = start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=64, tree_depth=6, node_id=777, name="prop_s",
            ingress_coalesce=False,
        )
        for s in senders:
            s.set_neighbours([rc, rs])
        chunks = max(1, len(script) // rounds)
        for start in range(0, len(script), chunks):
            for who, op, ki, val in script[start : start + chunks]:
                if op == "add":
                    senders[who].mutate("add", [ki, val])
                else:
                    senders[who].mutate("remove", [ki])
            for s in senders:
                s.sync_to_all()
            for r in (rc, rs):
                entries_only(transport, r.addr)
                r.process_pending()
            for s in senders:
                transport.drain(s.addr)
        assert rc.read() == rs.read()
        assert rc._seq == rs._seq
        assert_state_bit_equal(rc.state, rs.state, "property")
