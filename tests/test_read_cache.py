"""Incremental full-read cache (VERDICT r3 weak #5).

Local flushes maintain the read dict in place whenever it is complete: a
local add kills every observed same-key dot and inserts the sole winner
(remove-delta ⊔ add-delta, ``aw_lww_map.ex:99-112``), so replaying a
batch onto the dict equals the device result — even with remote entries
present. Remote merges invalidate the cache; the next full read rebuilds
it through the vectorized winner pass and maintenance resumes. These
tests pin the equivalence of the two paths (reference read semantics:
``aw_lww_map.ex:211-216``).
"""

import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock


def mk(transport, clock, **opts):
    opts.setdefault("capacity", 64)
    opts.setdefault("tree_depth", 6)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock, **opts
    )


def full_pass_read(replica) -> dict:
    """Read through the slow path regardless of cache state."""
    replica.flush()
    return replica._read_all()


def test_maintained_cache_matches_full_pass(transport, shared_clock):
    c = mk(transport, shared_clock)
    for i in range(50):
        c.mutate_async("add", [f"k{i}", i])
    for i in range(0, 50, 3):
        c.mutate_async("remove", [f"k{i}"])
    c.mutate_async("add", ["k1", "overwritten"])
    assert c.read() == full_pass_read(c)
    # clear shadows everything before it in the same batch
    c.mutate_async("add", ["pre", 1])
    c.mutate_async("clear", [])
    c.mutate_async("add", ["post", 2])
    assert c.read() == {"post": 2} == full_pass_read(c)
    c.stop()


def test_local_add_after_merge_observes_remote_dot(transport):
    # b's clock is far ahead, but a LATER local add still wins: add kills
    # every OBSERVED dot of the key (observed-remove) and inserts the
    # sole survivor — the maintained cache and the device agree
    a = mk(transport, LogicalClock())
    b = mk(transport, LogicalClock(start=1_000_000))
    b.mutate("add", ["k", "remote"])
    b.set_neighbours([a])
    for _ in range(6):
        b.sync_to_all()
        transport.pump()
    assert a.read() == {"k": "remote"}  # merge invalidated + rebuilt cache
    a.mutate("add", ["k", "local-observed-remove"])
    assert a.read() == {"k": "local-observed-remove"}
    assert a.read() == full_pass_read(a)
    a.stop()
    b.stop()


def test_cache_resumes_after_merge_rebuild(transport, shared_clock):
    a = mk(transport, shared_clock)
    b = mk(transport, shared_clock)
    b.mutate("add", ["remote-key", "rv"])
    b.set_neighbours([a])
    for _ in range(6):
        b.sync_to_all()
        transport.pump()
    assert a._read_cache is None  # merge invalidated
    assert a.read() == {"remote-key": "rv"}  # rebuild primes the cache
    assert a._read_cache is not None
    a.mutate("add", ["local-key", 1])  # maintained incrementally again
    assert a._read_cache is not None
    assert a.read() == {"remote-key": "rv", "local-key": 1} == full_pass_read(a)
    a.stop()
    b.stop()


def test_cache_rebuilt_after_rehydrate(transport, shared_clock):
    from delta_crdt_ex_tpu import MemoryStorage

    storage = MemoryStorage()
    c = mk(transport, shared_clock, storage_module=storage, name="rc-rehydrate")
    c.mutate("add", ["k", 1])
    transport.unregister("rc-rehydrate")  # simulated crash: no stop()
    c2 = mk(transport, shared_clock, storage_module=storage, name="rc-rehydrate")
    assert c2._read_cache is None
    c2.mutate("add", ["j", 2])
    assert c2.read() == {"k": 1, "j": 2} == full_pass_read(c2)
    c2.stop()


def test_python_equal_distinct_terms(transport, shared_clock):
    # 1 and True are ==-equal in Python but canonically distinct CRDT
    # keys: the dict view collapses them, and both the maintained cache
    # and the winner-pass rebuild must agree the LATEST write's value
    # wins the collapse (the alias guard invalidates maintenance)
    c = mk(transport, shared_clock)
    c.mutate("add", [1, "int-first"])
    c.mutate("add", [True, "bool-second"])
    assert c._read_cache is None  # alias detected: maintenance dropped
    assert c.read() == {1: "bool-second"}  # rebuild: latest write wins
    assert sorted(
        c.read_items(), key=lambda kv: kv[1]
    ) == [(True, "bool-second"), (1, "int-first")]  # exact terms via items
    # while aliased, every read goes through the full pass; still exact
    c.mutate("add", ["other", 3])
    assert c.read() == {1: "bool-second", "other": 3}
    # removing one alias un-collapses the map; maintenance resumes
    c.mutate("remove", [True])
    assert c.read() == {1: "int-first", "other": 3}
    assert c._read_cache_kh is not None
    c.stop()


def test_unhashable_key_disables_cache(transport, shared_clock):
    c = mk(transport, shared_clock)
    c.mutate("add", [["unhashable", "list"], 1])
    with pytest.raises(TypeError, match="unhashable"):
        c.read()
    assert c.read_items() == [(["unhashable", "list"], 1)]
    c.stop()
