"""Public API parity tests — the ``DeltaCrdt`` facade surface
(``lib/delta_crdt.ex``) plus runtime extensions.
"""

import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import child_spec, start_link
from delta_crdt_ex_tpu.runtime import telemetry
from tests.conftest import converge


def mk(transport, clock, **opts):
    opts.setdefault("capacity", 64)
    opts.setdefault("tree_depth", 6)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock, **opts
    )


def test_child_spec_requires_crdt():
    """Reference raises without a :crdt option (``delta_crdt.ex:73-79``)."""
    with pytest.raises(ValueError, match="must specify 'crdt'"):
        child_spec({})
    spec = child_spec({"crdt": AWLWWMap, "name": "sup_child", "shutdown": 1.0})
    assert spec["id"] == "sup_child"
    fn, args, opts = spec["start"]
    assert fn is start_link and args == (AWLWWMap,)
    assert "shutdown" not in opts  # consumed by the spec, not forwarded


def test_unknown_op_and_wrong_arity_raise(transport, shared_clock):
    c = mk(transport, shared_clock)
    with pytest.raises(ValueError, match="unknown operation"):
        c.mutate("bogus", [1])
    with pytest.raises(ValueError, match="expects 2 argument"):
        c.mutate("add", ["only-key"])


def test_read_keys_partial_read(transport, shared_clock):
    """``AWLWWMap.read/2`` partial read (``aw_lww_map.ex:218-224``)."""
    c = mk(transport, shared_clock)
    for i in range(10):
        c.mutate_async("add", [f"k{i}", i])
    got = c.read_keys(["k3", "k7", "missing"])
    assert got == {"k3": 3, "k7": 7}


def test_read_items_supports_unhashable_keys(transport, shared_clock):
    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock)
    c1.set_neighbours([c2])
    c1.mutate("add", [["list", "key"], "v1"])  # lists are unhashable in python
    c1.mutate("add", [{"dict": "key"}, "v2"])
    converge(transport, [c1, c2])
    items = sorted(c2.read_items(), key=repr)
    assert items == sorted(
        [(["list", "key"], "v1"), ({"dict": "key"}, "v2")], key=repr
    )
    with pytest.raises(TypeError, match="unhashable"):
        c2.read()


def test_capacity_grown_telemetry_fires(transport, shared_clock):
    events = []

    def rec(event, meas, meta):
        events.append((meas["capacity"], meas["replica_capacity"]))

    telemetry.attach(telemetry.CAPACITY_GROWN, rec)
    try:
        c = mk(transport, shared_clock, capacity=64, tree_depth=3)  # 8 buckets x 8 bins
        for i in range(200):
            c.mutate_async("add", [i, i])
        c.flush()
        assert len(c.read()) == 200
        assert events, "growth must fire telemetry"
        assert events[-1][0] >= 256
    finally:
        telemetry.detach(telemetry.CAPACITY_GROWN, rec)


def test_sync_round_telemetry_reports_merge(transport, shared_clock):
    rounds = []

    def rec(event, meas, meta):
        rounds.append(meas)

    telemetry.attach(telemetry.SYNC_ROUND, rec)
    try:
        c1 = mk(transport, shared_clock)
        c2 = mk(transport, shared_clock)
        c1.set_neighbours([c2])
        c1.mutate("add", ["x", 1])
        converge(transport, [c1, c2])
        assert any(r["entries"] >= 1 for r in rounds)
    finally:
        telemetry.detach(telemetry.SYNC_ROUND, rec)


def test_mutate_batch_matches_per_op(transport, shared_clock):
    from delta_crdt_ex_tpu.api import mutate_batch

    a = mk(transport, shared_clock, capacity=256)
    b = mk(transport, shared_clock, capacity=256)
    items = [[f"k{i}", i] for i in range(100)]
    mutate_batch(a, "add", items)
    for args in items:
        b.mutate("add", args)
    assert a.read() == b.read() == {f"k{i}": i for i in range(100)}
    mutate_batch(a, "remove", [[f"k{i}"] for i in range(0, 100, 2)])
    assert a.read() == {f"k{i}": i for i in range(1, 100, 2)}
    # a rejected batch must not partially commit (not even later)
    before = a.read()
    with pytest.raises(ValueError, match="expects"):
        mutate_batch(a, "add", [["ok", 1], ["bad-arity"]])
    assert a.read() == before
    a.stop()
    b.stop()
