"""Cross-kernel merge parity: the row-granular merge (``merge_rows``,
the runtime path) and the element-scatter merge (``merge_slice``, the
bulk fan-in path) implement the same join (``aw_lww_map.ex:153-209``)
under different cost models — every merge must produce bit-identical
lattice state (dots, context, digests, summaries) on both.
"""

import numpy as np
import jax.numpy as jnp

from delta_crdt_ex_tpu.ops.binned import extract_rows, merge_rows, merge_slice
from delta_crdt_ex_tpu.utils.synth import build_state, interval_delta_stream
from tests.kernel_harness import BinnedKernelMap, read_binned_state


def dots_of(st):
    node = np.asarray(st.node)
    ctr = np.asarray(st.ctr)
    alive = np.asarray(st.alive)
    gid = np.asarray(st.ctx_gid)[node]
    u, b = np.nonzero(alive)
    return {(int(gid[x, y]), int(x), int(ctr[x, y])) for x, y in zip(u, b)}


def assert_states_equal(s1, s2, ctx):
    assert read_binned_state(s1) == read_binned_state(s2), ctx
    assert dots_of(s1) == dots_of(s2), ctx
    for col in ("ctx_max", "leaf", "amin", "amax"):
        assert np.array_equal(
            np.asarray(getattr(s1, col)), np.asarray(getattr(s2, col))
        ), (ctx, col)


def test_state_form_slices_identical_across_kernels():
    rng = np.random.default_rng(0)
    for trial in range(12):
        L = 16
        a = BinnedKernelMap(gid=100, capacity=128, rcap=4, num_buckets=L)
        b = BinnedKernelMap(gid=200, capacity=128, rcap=4, num_buckets=L)
        for ts in range(1, int(rng.integers(2, 25))):
            who = a if rng.random() < 0.5 else b
            k = int(rng.integers(0, 24))
            op = rng.random()
            if op < 0.7:
                who.add(k, int(rng.integers(0, 100)), ts=ts)
            elif op < 0.95:
                who.remove(k, ts=ts)
            else:
                who.clear(ts=ts)
        if rng.random() < 0.6:  # give kills remote targets
            a.join_from(b)
        sl = extract_rows(b.state, jnp.arange(L, dtype=jnp.int32))
        r1 = merge_slice(a.state, sl, kill_budget=L, max_inserts=None)
        r2 = merge_rows(a.state, sl)
        assert bool(r1.ok) and bool(r2.ok), trial
        assert_states_equal(r1.state, r2.state, trial)
        assert int(r1.n_inserted) == int(r2.n_inserted), trial
        assert int(r1.n_killed) == int(r2.n_killed), trial


def test_interval_stream_and_gap_parity():
    rng = np.random.default_rng(1)
    L = 64
    keys = rng.integers(1, 1 << 63, size=2000, dtype=np.uint64)
    st1, _ = build_state(11, keys, num_buckets=L, bin_capacity=64)
    st2 = st1
    slices, _ = interval_delta_stream(22, rng, 6, 64, L, bin_width=8)
    for i, sl in enumerate(slices):
        r1 = merge_slice(st1, sl, kill_budget=L, max_inserts=None)
        r2 = merge_rows(st2, sl)
        assert bool(r1.ok) and bool(r2.ok), i
        st1, st2 = r1.state, r2.state
    assert_states_equal(st1, st2, "interval stream")

    # a skipped interval must gap on BOTH kernels, leaving state unused
    fresh, _ = build_state(11, keys, num_buckets=L, bin_capacity=64)
    r1 = merge_slice(fresh, slices[1], kill_budget=L, max_inserts=None)
    r2 = merge_rows(fresh, slices[1])
    assert bool(r1.need_ctx_gap) and bool(r2.need_ctx_gap)
    assert not bool(r1.ok) and not bool(r2.ok)


def test_large_writer_table_fallback_parity():
    """States whose writer tables exceed the select-unroll threshold
    compile the gather/scatter fallback branches of ``_slice_view`` and
    ``_table_lookup``; the merge result must be identical to the small-R
    one-hot path. Leaf digests and dot sets are slot-independent (entry
    hashes use global writer ids), so an rcap=8 and an rcap=64 replica
    fed the same script must agree bit-for-bit on both."""
    L = 16
    for trial in range(4):
        pairs = {}
        for rcap in (8, 64):
            a = BinnedKernelMap(gid=100, capacity=128, rcap=rcap, num_buckets=L)
            b = BinnedKernelMap(gid=200, capacity=128, rcap=rcap, num_buckets=L)
            script = np.random.default_rng(1000 + trial)
            for ts in range(1, 20):
                who = a if script.random() < 0.5 else b
                k = int(script.integers(0, 24))
                if script.random() < 0.75:
                    who.add(k, int(script.integers(0, 100)), ts=ts)
                else:
                    who.remove(k, ts=ts)
            a.join_from(b)  # give kills remote targets
            sl = extract_rows(b.state, jnp.arange(L, dtype=jnp.int32))
            r1 = merge_slice(a.state, sl, kill_budget=L, max_inserts=None)
            r2 = merge_rows(a.state, sl)
            assert bool(r1.ok) and bool(r2.ok), (trial, rcap)
            assert_states_equal(r1.state, r2.state, (trial, rcap))
            pairs[rcap] = r1.state
        # cross-rcap agreement on every slot-independent view
        s8, s64 = pairs[8], pairs[64]
        assert read_binned_state(s8) == read_binned_state(s64), trial
        assert dots_of(s8) == dots_of(s64), trial
        assert np.array_equal(np.asarray(s8.leaf), np.asarray(s64.leaf)), trial


def test_insert_compaction_tier_is_transparent():
    """``max_inserts`` (top_k sort-compaction of the insert scatter) is a
    pure cost-model knob: for any tier large enough to hold the inserts,
    the merged state must be bit-identical to the uncompacted
    (``max_inserts=None``) merge — including digests and summaries."""
    rng = np.random.default_rng(3)
    for trial in range(6):
        L = 16
        a = BinnedKernelMap(gid=100, capacity=256, rcap=4, num_buckets=L)
        b = BinnedKernelMap(gid=200, capacity=256, rcap=4, num_buckets=L)
        for ts in range(1, int(rng.integers(5, 30))):
            who = a if rng.random() < 0.5 else b
            k = int(rng.integers(0, 40))
            if rng.random() < 0.8:
                who.add(k, int(rng.integers(0, 100)), ts=ts)
            else:
                who.remove(k, ts=ts)
        if trial % 2:
            a.join_from(b)  # give the kill pass remote targets
        sl = extract_rows(b.state, jnp.arange(L, dtype=jnp.int32))
        r_none = merge_slice(a.state, sl, kill_budget=L, max_inserts=None)
        for tier in (sl.key.size, 64, 256):
            r_tier = merge_slice(a.state, sl, kill_budget=L, max_inserts=tier)
            assert bool(r_tier.ok) == bool(r_none.ok), (trial, tier)
            assert_states_equal(r_none.state, r_tier.state, (trial, tier))
            assert int(r_none.n_inserted) == int(r_tier.n_inserted)
        # an undersized tier must flag, not corrupt
        if int(r_none.n_inserted) > 1:
            r_small = merge_slice(a.state, sl, kill_budget=L, max_inserts=1)
            assert not bool(r_small.ok) and bool(r_small.need_ins_tier), trial


def test_flagged_first_order_filler_never_flagged():
    """The cumsum-rank rewrite of ``flagged_first_order`` fills unused
    budget slots with ``argmin(flags)`` — this pins the invariant the
    kill pass depends on: a filler slot must NEVER alias a flagged row,
    or the row would be processed twice and ``leaf.at[rows].add`` would
    double-subtract its digest (the top_k version filled with unflagged
    rows; the replacement must keep that property in every shape)."""
    from delta_crdt_ex_tpu.ops.binned import flagged_first_order

    rng = np.random.default_rng(7)
    cases = [
        np.array([True] + [False] * 15),          # the alias hazard: row 0 flagged
        np.array([False] * 16),                   # none flagged
        np.array([True] * 16),                    # all flagged
        np.array([False, True] * 8),              # alternating
        np.array([False] * 15 + [True]),          # last-only
    ] + [rng.random(16) < p for p in (0.1, 0.5, 0.9)]
    for budget in (1, 4, 16, 32):
        for ci, flags in enumerate(cases):
            order = np.asarray(flagged_first_order(jnp.asarray(flags), budget))
            kb = min(budget, flags.size)
            assert order.shape == (kb,), (ci, budget)
            assert ((order >= 0) & (order < flags.size)).all(), (ci, budget)
            n_flagged = int(flags.sum())
            expect = np.flatnonzero(flags)[: min(kb, n_flagged)]
            got = order[: min(kb, n_flagged)]
            # flagged prefix: the first `budget` flagged rows, ascending
            assert np.array_equal(got, expect), (ci, budget, order, flags)
            # THE invariant: no slot past the flagged prefix may hold a
            # flagged row (masking via flags[order] must hide fillers)
            assert not flags[order[min(kb, n_flagged):]].any(), (
                ci, budget, order, flags,
            )
