"""Write-ahead delta log: crash recovery, torn tails, compaction.

The recovery invariant under test (ISSUE 1): *snapshot + WAL replay
reproduces the pre-crash ``read()`` exactly*, with node-id, dot-counter,
and LWW-clock continuity — the reference's crash-rehydrate semantics
(``causal_crdt_test.exs:87-102``) at O(delta) durability cost instead of
O(state) write-through. Crashes land at random points between WAL
appends and compaction snapshots; a torn final record is truncated, not
crashed on; and counter continuity is proven the way it matters: a peer
that saw the pre-crash dots must accept (not skip as covered) the dots
minted after recovery.
"""

import glob
import os
import random

import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime import telemetry
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.wal import WalLog
from tests.conftest import converge


def mk(transport, clock, **opts):
    opts.setdefault("capacity", 64)
    opts.setdefault("tree_depth", 6)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock, **opts
    )


def seg_files(wal_dir) -> list:
    return sorted(glob.glob(os.path.join(str(wal_dir), "replica_*", "*.wal")))


def test_wal_rehydrates_after_crash(tmp_path, transport, shared_clock):
    c = mk(transport, shared_clock, name="walbasic", wal_dir=str(tmp_path))
    c.mutate("add", ["Derek", "Kraan"])
    c.mutate("add", ["Tonci", "Galic"])
    c.mutate("remove", ["Derek"])
    pre = c.read()
    node_id = c.node_id
    c.crash()

    c2 = mk(transport, shared_clock, name="walbasic", wal_dir=str(tmp_path))
    assert c2.read() == pre == {"Tonci": "Galic"}
    assert c2.node_id == node_id  # dot-namespace continuity, no snapshot needed
    c2.mutate("add", ["After", "crash"])
    assert c2.read() == {"Tonci": "Galic", "After": "crash"}


@pytest.mark.parametrize("seed", [1, 2, 3])
def test_crash_recovery_at_random_point(tmp_path, transport, shared_clock, seed):
    """Random add/remove/clear history, crash at a random point between
    WAL appends and compaction snapshots (compact_every small, segments
    tiny so the log rolls), restart from disk: read() must equal the
    pre-crash read, and fresh mutations must mint fresh dots."""
    rng = random.Random(seed)
    wal = str(tmp_path / f"w{seed}")
    c = mk(
        transport, shared_clock, name=f"walrand{seed}", wal_dir=wal,
        compact_every=rng.choice([3, 7]), segment_bytes=rng.choice([256, 1024]),
    )
    keys = [f"k{i}" for i in range(12)]
    n_ops = rng.randrange(10, 40)
    for op_i in range(n_ops):
        r = rng.random()
        if r < 0.65:
            c.mutate("add", [rng.choice(keys), op_i])
        elif r < 0.95:
            c.mutate("remove", [rng.choice(keys)])
        else:
            c.mutate("clear", [])
    pre = c.read()
    node_id = c.node_id
    c.crash()

    c2 = mk(transport, shared_clock, name=f"walrand{seed}", wal_dir=wal)
    assert c2.read() == pre
    assert c2.node_id == node_id
    # fresh dots after recovery: a new add must land (and win) cleanly
    c2.mutate("add", ["post", seed])
    assert c2.read() == {**pre, "post": seed}


def test_torn_tail_record_is_truncated(tmp_path, transport, shared_clock):
    c = mk(transport, shared_clock, name="waltorn", wal_dir=str(tmp_path))
    c.mutate("add", ["kept", 1])
    c.mutate("add", ["torn", 2])
    c.crash()

    seg = seg_files(tmp_path)[-1]
    size = os.path.getsize(seg)
    with open(seg, "r+b") as f:
        f.truncate(size - 5)  # tear the final record mid-bytes

    c2 = mk(transport, shared_clock, name="waltorn", wal_dir=str(tmp_path))
    # clean recovery to the record boundary: the torn append is gone, the
    # prefix survives, and the log accepts new appends
    assert c2.read() == {"kept": 1}
    c2.mutate("add", ["after", 3])
    pre = c2.read()
    c2.crash()
    c3 = mk(transport, shared_clock, name="waltorn", wal_dir=str(tmp_path))
    assert c3.read() == pre == {"kept": 1, "after": 3}


def test_empty_final_segment_discarded(tmp_path, transport, shared_clock):
    """Power loss between the dirent fsync and the first content fsync
    leaves a durable zero-length segment: recovery must discard it and
    start, not brick on bad magic."""
    c = mk(transport, shared_clock, name="walempty", wal_dir=str(tmp_path))
    c.mutate("add", ["a", 1])
    pre = c.read()
    c.crash()
    seg_dir = os.path.dirname(seg_files(tmp_path)[-1])
    with open(os.path.join(seg_dir, "seg-" + "9" * 20 + ".wal"), "wb"):
        pass  # durable-but-empty newest segment
    c2 = mk(transport, shared_clock, name="walempty", wal_dir=str(tmp_path))
    assert c2.read() == pre
    c2.mutate("add", ["b", 2])
    assert c2.read() == {"a": 1, "b": 2}


def test_conflicting_explicit_node_id_rejected(tmp_path, transport, shared_clock):
    """Same misconfiguration guard as the snapshot branch: an explicit
    node_id conflicting with the WAL header must raise, not silently
    adopt the log's namespace."""
    c = mk(transport, shared_clock, name="walnid", wal_dir=str(tmp_path))
    c.mutate("add", ["a", 1])
    nid = c.node_id
    c.crash()
    with pytest.raises(ValueError, match="mixed histories"):
        mk(transport, shared_clock, name="walnid", wal_dir=str(tmp_path),
           node_id=nid ^ 0xBEEF)
    # the matching id is of course fine
    c2 = mk(transport, shared_clock, name="walnid", wal_dir=str(tmp_path),
            node_id=nid)
    assert c2.read() == {"a": 1}


def test_no_counter_reuse_after_recovery(tmp_path, transport, shared_clock):
    """THE reason node/counter continuity matters: a peer that observed
    pre-crash dots records them in its causal context. If the recovered
    replica re-minted used counters, the peer would treat the new writes
    as already-covered and silently drop them."""
    hub = mk(transport, shared_clock, name="walhub", wal_dir=str(tmp_path))
    peer = mk(transport, shared_clock, name="walpeer")
    hub.set_neighbours([peer])
    peer.set_neighbours([hub])
    for i in range(8):
        hub.mutate("add", [f"pre{i}", i])
    converge(transport, [hub, peer])
    assert len(peer.read()) == 8

    hub.crash()
    hub2 = mk(transport, shared_clock, name="walhub", wal_dir=str(tmp_path))
    assert hub2.read() == peer.read()
    hub2.set_neighbours([peer])
    peer.set_neighbours([hub2])
    hub2.mutate("add", ["pre0", "overwritten"])  # same key: new dot, same bucket
    for i in range(4):
        hub2.mutate("add", [f"post{i}", i])
    converge(transport, [hub2, peer])
    want = {f"pre{i}": i for i in range(1, 8)}
    want.update({"pre0": "overwritten", **{f"post{i}": i for i in range(4)}})
    assert hub2.read() == want
    assert peer.read() == want, "peer dropped post-recovery dots (counter reuse)"


def test_receiver_logs_remote_slices(tmp_path, transport, shared_clock):
    """Accepted remote delta slices are WAL records too: a receiver that
    never wrote locally still recovers everything it merged."""
    writer = mk(transport, shared_clock, name="walwriter")
    rx = mk(transport, shared_clock, name="walrx", wal_dir=str(tmp_path))
    writer.set_neighbours([rx])
    rx.set_neighbours([writer])
    for i in range(10):
        writer.mutate("add", [f"k{i}", i])
    writer.mutate("remove", ["k0"])
    converge(transport, [writer, rx])
    pre = rx.read()
    assert len(pre) == 9
    rx.crash()

    rx2 = mk(transport, shared_clock, name="walrx", wal_dir=str(tmp_path))
    assert rx2.read() == pre
    # and the recovered context still accepts the writer's next delta
    writer.set_neighbours([rx2])
    rx2.set_neighbours([writer])
    writer.mutate("add", ["k10", 10])
    converge(transport, [writer, rx2])
    assert rx2.read() == {**pre, "k10": 10}


def test_compaction_reclaims_segments(tmp_path, transport, shared_clock):
    c = mk(
        transport, shared_clock, name="walcomp", wal_dir=str(tmp_path),
        compact_every=5, segment_bytes=256,
    )
    for i in range(23):
        c.mutate("add", [f"x{i}", i])
    # 4 compactions have run: covered segments deleted, snapshot present
    assert len(seg_files(tmp_path)) <= 2, seg_files(tmp_path)
    assert glob.glob(os.path.join(str(tmp_path), "snapshots", "*.pkl"))
    pre = c.read()
    c.crash()
    c2 = mk(transport, shared_clock, name="walcomp", wal_dir=str(tmp_path))
    assert c2.read() == pre


def test_volatile_snapshot_store_keeps_segments(tmp_path, transport, shared_clock):
    """Compaction through a volatile checkpoint store (MemoryStorage —
    no ``fsync`` attribute) must NOT delete segments: the snapshot dies
    with the process, so the log is the only durable copy."""
    from delta_crdt_ex_tpu import MemoryStorage

    c = mk(transport, shared_clock, name="walvol", wal_dir=str(tmp_path),
           storage_module=MemoryStorage(), compact_every=5)
    for i in range(12):
        c.mutate("add", [f"k{i}", i])
    pre = c.read()
    c.crash()
    MemoryStorage.clear()  # the process died: RAM snapshots are gone
    c2 = mk(transport, shared_clock, name="walvol", wal_dir=str(tmp_path),
            storage_module=MemoryStorage(), compact_every=5)
    assert c2.read() == pre, "compaction deleted the only durable copy"


@pytest.mark.parametrize("fsync_mode", ["record", "batch", "interval", "none"])
def test_fsync_modes_all_recover(tmp_path, transport, shared_clock, fsync_mode):
    """Every cadence recovers a process-crash cleanly (the cadences
    differ only in the machine-crash window, which a test can't model);
    ``"record"``/``"batch"`` must also survive the in-process buffer
    drop that ``crash()`` performs."""
    wal = str(tmp_path / fsync_mode)
    c = mk(
        transport, shared_clock, name=f"walf_{fsync_mode}", wal_dir=wal,
        fsync_mode=fsync_mode,
    )
    c.mutate("add", ["a", 1])
    c.mutate("add", ["b", 2])
    pre = c.read()
    c.crash()
    c2 = mk(transport, shared_clock, name=f"walf_{fsync_mode}", wal_dir=wal,
            fsync_mode=fsync_mode)
    assert c2.read() == pre


def test_bad_fsync_mode_rejected(tmp_path):
    with pytest.raises(ValueError, match="fsync_mode"):
        WalLog(str(tmp_path), fsync_mode="bogus")


def test_wal_telemetry_events(tmp_path, transport, shared_clock):
    events = {}
    handlers = []
    for ev in (telemetry.WAL_APPEND, telemetry.WAL_COMPACT, telemetry.WAL_RECOVER):
        h = (lambda e, m, md, _ev=ev: events.setdefault(_ev, []).append(m))
        telemetry.attach(ev, h)
        handlers.append((ev, h))
    try:
        c = mk(transport, shared_clock, name="waltel", wal_dir=str(tmp_path),
               compact_every=3)
        for i in range(7):
            c.mutate("add", [f"k{i}", i])
        pre = c.read()
        c.crash()
        c2 = mk(transport, shared_clock, name="waltel", wal_dir=str(tmp_path))
        assert c2.read() == pre
    finally:
        for ev, h in handlers:
            telemetry.detach(ev, h)
    appends = events[telemetry.WAL_APPEND]
    assert len(appends) == 7 and all(m["bytes"] > 0 for m in appends)
    assert events[telemetry.WAL_COMPACT], "compact_every=3 must have compacted"
    (rec,) = events[telemetry.WAL_RECOVER]
    assert rec["records"] > 0 and rec["bytes"] > 0


def test_mixed_history_rejected(tmp_path, transport, shared_clock):
    """A snapshot from one node and a WAL from another in the same dir
    is corruption, not a recovery case."""
    c = mk(transport, shared_clock, name="walmix", wal_dir=str(tmp_path))
    c.mutate("add", ["a", 1])
    c.crash()
    # forge a self-consistent snapshot under a DIFFERENT node id (as if
    # another replica's snapshot landed in this wal_dir)
    import numpy as np

    snap_store = c.storage_module
    snap = c._snapshot()
    snap.node_id ^= 0xDEAD
    snap.arrays["ctx_gid"] = snap.arrays["ctx_gid"].copy()
    snap.arrays["ctx_gid"][c.self_slot] = np.uint64(snap.node_id)
    snap_store.write("walmix", snap)
    with pytest.raises(ValueError, match="mixed histories"):
        mk(transport, shared_clock, name="walmix", wal_dir=str(tmp_path))


@pytest.mark.slow
def test_wal_soak_crash_restart_cycles(tmp_path, transport, shared_clock):
    """Stress: hundreds of mixed ops across repeated crash/restart
    cycles with tiny rolling segments and aggressive compaction — the
    recovered read must match a dict oracle at every cycle boundary.
    (Sequential sync ops with full observation make the oracle exact,
    as in test_runtime_property.py.)"""
    rng = random.Random(7)
    wal = str(tmp_path)
    oracle: dict = {}
    name = "walsoak"
    keys = [f"k{i}" for i in range(40)]
    c = mk(transport, shared_clock, name=name, wal_dir=wal,
           compact_every=11, segment_bytes=512, capacity=256, tree_depth=6)
    for cycle in range(6):
        for _ in range(rng.randrange(30, 80)):
            r = rng.random()
            if r < 0.6:
                k, v = rng.choice(keys), rng.randrange(1000)
                c.mutate("add", [k, v])
                oracle[k] = v
            elif r < 0.97:
                k = rng.choice(keys)
                c.mutate("remove", [k])
                oracle.pop(k, None)
            else:
                c.mutate("clear", [])
                oracle.clear()
        assert c.read() == oracle, f"divergence before crash in cycle {cycle}"
        c.crash()
        c = mk(transport, shared_clock, name=name, wal_dir=wal,
               compact_every=11, segment_bytes=512, capacity=256, tree_depth=6)
        assert c.read() == oracle, f"recovery diverged in cycle {cycle}"
