"""True cross-process sync: a replica in a child interpreter converges
with one in this process over the TCP transport — the closest analog of
the reference's multi-node distribution (SURVEY §4: the reference tests
distribution logically in one BEAM; we additionally cross a real process
boundary here).

Sync edges are one-way (the setter's data flows to the neighbour,
``delta_crdt.ex:84-95``), and the parent does not know the child's
ephemeral endpoint — so the child bootstraps membership *through the
CRDT*: it publishes its endpoint under a well-known key, and the parent
adds the reverse edge when it sees it (exactly how Horde builds cluster
membership on top of this library).
"""

import os
import subprocess
import sys
import time

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.tcp_transport import TcpTransport

CHILD = r"""
import sys, time
import delta_crdt_ex_tpu  # enables x64
from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.tcp_transport import TcpTransport

parent_host, parent_port = sys.argv[1], int(sys.argv[2])
t = TcpTransport()
c = start_link(AWLWWMap, threaded=False, transport=t, name="child",
               capacity=64, tree_depth=6)
c.set_neighbours([("parent", (parent_host, parent_port))])
c.mutate("add", ["from_child", "hello"])
c.mutate("add", ["child_endpoint", list(t.endpoint)])  # membership via the CRDT
deadline = time.monotonic() + 60
while time.monotonic() < deadline:
    c.sync_to_all()
    t.pump()
    time.sleep(0.02)
    if c.read().get("from_parent") == "hi":
        print("CHILD_CONVERGED", flush=True)
        sys.exit(0)
sys.exit(3)
"""


def test_cross_process_convergence(tmp_path):
    t = TcpTransport()
    try:
        parent = start_link(
            AWLWWMap, threaded=False, transport=t, name="parent",
            capacity=64, tree_depth=6,
        )
        parent.mutate("add", ["from_parent", "hi"])
        host, port = t.endpoint

        script = tmp_path / "child.py"
        script.write_text(CHILD)
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["PYTHONPATH"] = os.getcwd() + os.pathsep + env.get("PYTHONPATH", "")
        child = subprocess.Popen(
            [sys.executable, str(script), str(host), str(port)],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True,
        )
        try:
            linked = False
            deadline = time.monotonic() + 90
            # keep serving sync rounds until the child process reports
            # ITS convergence and exits (stopping as soon as the parent
            # converges would starve the child of the reverse direction)
            while time.monotonic() < deadline and child.poll() is None:
                parent.sync_to_all()
                t.pump()
                time.sleep(0.02)
                if not linked:
                    got = parent.read()
                    if "child_endpoint" in got:
                        # reverse edge learned through the CRDT itself
                        ch_host, ch_port = got["child_endpoint"]
                        parent.set_neighbours([("child", (ch_host, ch_port))])
                        linked = True
            out, err = child.communicate(timeout=60)
            assert "CHILD_CONVERGED" in out, f"child failed: {err[-2000:]}"
            got = parent.read()
            assert got["from_child"] == "hello" and got["from_parent"] == "hi"
        finally:
            if child.poll() is None:
                child.kill()
    finally:
        t.close()
