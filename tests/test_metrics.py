"""Observability plane unit tests (ISSUE 9): registry semantics +
Prometheus exposition, the telemetry→metrics bridge's full-coverage
contract, the flight-recorder ring, and the dot-provenance lag tracer's
sampling/matching math (deterministic `now` injection throughout)."""

from __future__ import annotations

import re

import pytest

from delta_crdt_ex_tpu.runtime import telemetry
from delta_crdt_ex_tpu.runtime.metrics import (
    COUNT_BUCKETS,
    FlightRecorder,
    LagTracer,
    MetricsBridge,
    Observability,
    Registry,
    default_observability,
    resolve_obs,
)

@pytest.fixture(autouse=True)
def _isolated_telemetry_handlers():
    """Earlier suites attach throwaway telemetry handlers and never
    detach (harmless for them, fatal for assertions about the
    process-global table here): run every test in this module against
    a clean table, and leave it clean."""
    with telemetry._lock:
        telemetry._handlers.clear()
    yield
    with telemetry._lock:
        telemetry._handlers.clear()


# ----------------------------------------------------------------------
# registry + metric families


def test_counter_semantics():
    reg = Registry()
    c = reg.counter("crdt_test_total", "help", ("name",))
    c.inc(1, ("a",))
    c.inc(2.5, ("a",))
    c.inc(7, ("b",))
    assert c.value(("a",)) == 3.5
    assert c.value(("b",)) == 7
    assert c.value(("missing",)) == 0.0
    with pytest.raises(ValueError):
        c.inc(-1, ("a",))


def test_gauge_set_inc_remove():
    reg = Registry()
    g = reg.gauge("crdt_g", "help", ("name",))
    g.set(5, ("x",))
    g.inc(2, ("x",))
    assert g.value(("x",)) == 7
    g.remove(("x",))
    assert g.value(("x",)) == 0.0
    assert "crdt_g" not in reg.render()  # no samples -> family omitted


def test_histogram_buckets_cumulative():
    reg = Registry()
    h = reg.histogram("crdt_h", "help", (), buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 1.0, 3.0, 100.0):
        h.observe(v)
    assert h.count() == 4
    assert h.sum() == 104.5
    out = reg.render()
    # le="1" holds 0.5 AND the exactly-1.0 observation (Prometheus le is
    # inclusive); +Inf is the total count
    assert 'crdt_h_bucket{le="1"} 2' in out
    assert 'crdt_h_bucket{le="2"} 2' in out
    assert 'crdt_h_bucket{le="4"} 3' in out
    assert 'crdt_h_bucket{le="+Inf"} 4' in out
    assert "crdt_h_count 4" in out


def test_registry_get_or_create_idempotent_and_conflicts():
    reg = Registry()
    a = reg.counter("crdt_x_total", "help", ("name",))
    assert reg.counter("crdt_x_total", "help", ("name",)) is a
    with pytest.raises(ValueError):
        reg.gauge("crdt_x_total", "help", ("name",))  # kind conflict
    with pytest.raises(ValueError):
        reg.counter("crdt_x_total", "help", ("other",))  # label conflict
    with pytest.raises(ValueError):
        reg.counter("bad name", "help")  # invalid metric name


def test_label_arity_enforced():
    reg = Registry()
    c = reg.counter("crdt_y_total", "help", ("a", "b"))
    with pytest.raises(ValueError):
        c.inc(1, ("only-one",))


def test_render_escapes_label_values():
    reg = Registry()
    c = reg.counter("crdt_esc_total", "help", ("name",))
    c.inc(1, ('we"ird\\v\nal',))
    line = [l for l in reg.render().splitlines() if l.startswith("crdt_esc")][0]
    assert '\\"' in line and "\\\\" in line and "\\n" in line


def test_collector_runs_at_render_and_errors_are_contained():
    reg = Registry()
    g = reg.gauge("crdt_polled", "help")
    calls = []

    def ok_collector():
        calls.append(1)
        g.set(42)

    def bad_collector():
        raise RuntimeError("dead source")

    reg.register_collector(ok_collector)
    reg.register_collector(bad_collector)
    out = reg.render()
    assert "crdt_polled 42" in out and calls
    reg.unregister_collector(ok_collector)
    reg.render()
    assert len(calls) == 1


def test_snapshot_shape():
    reg = Registry()
    reg.counter("crdt_s_total", "h", ("name",)).inc(2, ("a",))
    snap = reg.snapshot()
    assert snap["crdt_s_total"] == {"type": "counter", "values": {"a": 2.0}}


# ----------------------------------------------------------------------
# the telemetry -> metrics bridge


def test_bridge_table_covers_every_declared_event():
    """The runtime mirror of crdtlint OBS001: every declared event
    tuple has a subscription row."""
    reg = Registry()
    bridge = MetricsBridge(reg)
    subscribed = {ev for ev, _h in bridge._table()}
    assert subscribed == set(telemetry.declared_events())


def test_bridge_folds_events_into_metrics():
    reg = Registry()
    bridge = MetricsBridge(reg).attach()
    try:
        telemetry.execute(
            telemetry.SYNC_DONE, {"keys_updated_count": 3}, {"name": "r1"}
        )
        telemetry.execute(
            telemetry.SYNC_ROUND,
            {"duration_s": 0.01, "buckets": 4, "entries": 9},
            {"name": "r1", "plane": "host"},
        )
        telemetry.execute(
            telemetry.FLEET_DISPATCH,
            {"replicas": 3, "messages": 7, "rows": 10, "padded_rows": 12,
             "duration_s": 0.002},
            {"fleet": 123},
        )
        assert bridge.sync_done.value(("r1",)) == 1
        assert bridge.keys_updated.value(("r1",)) == 3
        assert bridge.sync_entries.value(("r1", "host")) == 9
        assert bridge.sync_seconds.count(("r1", "host")) == 1
        assert bridge.fleet_messages.value(("123",)) == 7
    finally:
        bridge.detach()
    # detached: further events no longer fold
    telemetry.execute(
        telemetry.SYNC_DONE, {"keys_updated_count": 1}, {"name": "r1"}
    )
    assert bridge.sync_done.value(("r1",)) == 1


def test_bridge_batch_handlers_match_per_message_folds():
    """execute_many through the bridge's batch handlers produces the
    EXACT registry values a loop of per-message execute calls does —
    the amortisation must never change a metric."""
    meas_done = [{"keys_updated_count": n} for n in (3, 0, 7, 2)]
    meas_round = [
        {"duration_s": 0.001 * (i + 1), "buckets": i, "entries": 2 * i}
        for i in range(4)
    ]

    reg_a, reg_b = Registry(), Registry()
    for reg, batched in ((reg_a, True), (reg_b, False)):
        bridge = MetricsBridge(reg).attach()
        try:
            if batched:
                telemetry.execute_many(
                    telemetry.SYNC_DONE, meas_done, {"name": "r1"}
                )
                telemetry.execute_many(
                    telemetry.SYNC_ROUND, meas_round,
                    {"name": "r1", "plane": "host"},
                )
            else:
                for m in meas_done:
                    telemetry.execute(telemetry.SYNC_DONE, m, {"name": "r1"})
                for m in meas_round:
                    telemetry.execute(
                        telemetry.SYNC_ROUND, m, {"name": "r1", "plane": "host"}
                    )
        finally:
            bridge.detach()
    assert reg_a.snapshot() == reg_b.snapshot()
    assert reg_a.get("crdt_sync_done_total").value(("r1",)) == 4
    assert reg_a.get("crdt_sync_keys_updated_total").value(("r1",)) == 12
    assert reg_a.get("crdt_merge_dispatch_seconds").count(("r1", "host")) == 4


def test_bridge_attach_is_idempotent():
    reg = Registry()
    bridge = MetricsBridge(reg).attach()
    bridge.attach()  # second attach must not double-subscribe
    try:
        telemetry.execute(
            telemetry.SYNC_DONE, {"keys_updated_count": 1}, {"name": "x"}
        )
        assert bridge.sync_done.value(("x",)) == 1
    finally:
        bridge.detach()
    assert not telemetry.has_handlers(telemetry.SYNC_DONE)


# ----------------------------------------------------------------------
# flight recorder


def test_flight_recorder_ring_and_drop_accounting():
    fr = FlightRecorder("r1", capacity=4)
    for i in range(10):
        fr.record("sync_open", seq=i)
    events = fr.events()
    assert len(events) == 4
    assert [e["seq"] for e in events] == [6, 7, 8, 9]  # oldest dropped
    assert fr.dropped() == 6
    assert fr.events_recorded() == 10
    assert events[0]["kind"] == "sync_open"
    assert fr.events(kind="nope") == []


def test_flight_recorder_dump_goes_through_logger():
    import logging

    fr = FlightRecorder("r2", capacity=8)
    fr.record("growth", capacity=128)
    records = []

    class Sink(logging.Handler):
        def emit(self, rec):
            records.append(rec.getMessage())

    log = logging.getLogger("test-flight-sink")
    log.addHandler(Sink())
    assert fr.dump(log) == 1
    assert any("growth" in m for m in records)


def test_flight_recorder_validates_capacity():
    with pytest.raises(ValueError):
        FlightRecorder("x", capacity=0)


# ----------------------------------------------------------------------
# lag tracer


def test_lag_tracer_matches_every_peer_once():
    reg = Registry()
    tr = LagTracer(reg, sample_every=1)
    tr.note_commit("origin", 1, now=10.0)
    tr.note_visible("p1", "origin", 1, now=10.5)
    tr.note_visible("p2", "origin", 1, now=11.0)
    # repeated advance by the same peer must not double-count
    tr.note_visible("p1", "origin", 5, now=12.0)
    assert tr.lag.count(("origin", "p1")) == 1
    assert tr.lag.count(("origin", "p2")) == 1
    assert tr.lag.sum(("origin", "p1")) == pytest.approx(0.5)
    assert tr.lag.sum(("origin", "p2")) == pytest.approx(1.0)
    assert tr.peers_seen() == {"p1", "p2"}


def test_lag_tracer_self_visibility_ignored():
    tr = LagTracer(Registry(), sample_every=1)
    tr.note_commit("o", 1, now=0.0)
    tr.note_visible("o", "o", 1, now=1.0)
    assert tr.peers_seen() == set()


def test_lag_tracer_sampling_rate():
    tr = LagTracer(Registry(), sample_every=4)
    for seq in range(1, 9):
        tr.note_commit("o", seq, now=0.0)
    tr.note_visible("p", "o", 8, now=1.0)
    assert tr.lag.count(("o", "p")) == 2  # seqs 4 and 8


def test_lag_tracer_propagation_rounds():
    reg = Registry()
    tr = LagTracer(reg, sample_every=1)
    tr.note_commit("o", 1, now=0.0)
    tr.note_round("o")
    tr.note_round("o")
    tr.note_visible("p", "o", 1, now=1.0)
    assert tr.rounds.count(("o", "p")) == 1
    assert tr.rounds.sum(("o", "p")) == 2  # waited through 2 rounds


def test_lag_tracer_watermark_below_sample_matches_nothing():
    tr = LagTracer(Registry(), sample_every=1)
    tr.note_commit("o", 10, now=0.0)
    tr.note_visible("p", "o", 9, now=1.0)
    assert tr.lag.count(("o", "p")) == 0


def test_lag_tracer_pending_bounds():
    tr = LagTracer(Registry(), sample_every=1)
    for seq in range(1, tr.MAX_PENDING + 10):
        tr.note_commit("o", seq, now=0.0)
    tr.note_visible("p", "o", tr.MAX_PENDING + 9, now=1.0)
    assert tr.lag.count(("o", "p")) == tr.MAX_PENDING  # oldest evicted


def test_lag_tracer_backward_seq_resets_origin():
    """A backward seq means the origin restarted (recovery resumes
    from a snapshot): the dead incarnation's samples and floors are
    dropped so the new incarnation's lag is measured fresh."""
    tr = LagTracer(Registry(), sample_every=1)
    tr.note_commit("o", 10, now=0.0)
    tr.note_commit("o", 20, now=0.0)
    tr.note_visible("p", "o", 20, now=1.0)
    assert tr.lag.count(("o", "p")) == 2
    tr.note_commit("o", 5, now=2.0)  # restart: seq went backwards
    tr.note_visible("p", "o", 5, now=3.0)
    assert tr.lag.count(("o", "p")) == 3  # old floor (20) dropped too
    assert tr.lag.sum(("o", "p")) == 1.0 + 1.0 + 1.0


def test_lag_tracer_validates_sample_every():
    with pytest.raises(ValueError):
        LagTracer(Registry(), sample_every=0)


# ----------------------------------------------------------------------
# the Observability facade + the obs= knob


def test_resolve_obs_semantics():
    import delta_crdt_ex_tpu.runtime.metrics as metrics_mod

    assert resolve_obs(None) is None
    assert resolve_obs(False) is None
    plane = Observability()
    try:
        assert resolve_obs(plane) is plane
    finally:
        plane.close()
    default = resolve_obs(True)
    try:
        assert default is default_observability()
    finally:
        # in production the process default stays attached for the
        # process lifetime; in THIS process it must not leak its
        # always-attached bridge into every later test
        default.close()
        metrics_mod._default_obs = None
    with pytest.raises(TypeError):
        resolve_obs("yes")


def test_observability_varz_and_health_aggregation():
    plane = Observability()
    try:
        plane.add_varz_source("a", lambda: {"kind": "x", "stats": {"n": 1}})
        plane.add_varz_source("dying", lambda: 1 / 0)
        plane.add_health_check("ok", lambda: {"ok": True})
        varz = plane.varz()
        assert varz["sources"]["a"]["stats"]["n"] == 1
        assert "error" in varz["sources"]["dying"]
        ok, detail = plane.health()
        assert ok and detail["ok"]["ok"]
        plane.add_health_check("bad", lambda: {"ok": False, "why": "down"})
        ok, detail = plane.health()
        assert not ok and not detail["bad"]["ok"]
        plane.add_health_check("crash", lambda: 1 / 0)
        ok, detail = plane.health()
        assert not ok and "error" in detail["crash"]
    finally:
        plane.close()


def test_observability_registers_replica_sources(transport):
    from delta_crdt_ex_tpu.api import start_link

    plane = Observability()
    try:
        rep = start_link(
            threaded=False, transport=transport, obs=plane, name="obs-reg"
        )
        rep.mutate("add", ["k", "v"])
        out = plane.registry.render()
        assert 'crdt_sync_done_total{name="obs-reg"} 1' in out
        assert 'crdt_sequence_number{name="obs-reg"} 1' in out
        assert 'crdt_payloads{name="obs-reg"} 1' in out
        varz = plane.varz()
        assert varz["sources"]["replica:obs-reg"]["kind"] == "replica"
        # stats() schema is UNCHANGED under the envelope (MIGRATING.md)
        assert varz["sources"]["replica:obs-reg"]["stats"]["payloads"] == 1
        ok, detail = plane.health()
        assert ok and detail["replica:obs-reg"]["ok"]
        rep.stop()
        # a stopped replica's GAUGES and sources are gone from scrapes
        # (counters stay — cumulative series are never retracted)
        out = plane.registry.render()
        assert 'crdt_sequence_number{name="obs-reg"}' not in out
        assert 'crdt_payloads{name="obs-reg"}' not in out
        assert "replica:obs-reg" not in plane.varz()["sources"]
    finally:
        plane.close()


def test_observability_fleet_registration(transport):
    from delta_crdt_ex_tpu.api import start_fleet

    plane = Observability()
    fleet = start_fleet(
        3, threaded=False, transport=transport, obs=plane,
        names=[f"fm{i}" for i in range(3)],
    )
    try:
        fleet.replicas[0].mutate("add", ["k", 1])
        fleet.drain()
        out = plane.registry.render()
        assert "crdt_fleet_ticks" in out
        varz = plane.varz()
        fleet_sources = [
            k for k, v in varz["sources"].items() if v.get("kind") == "fleet"
        ]
        assert len(fleet_sources) == 1
        assert all(f"replica:fm{i}" in varz["sources"] for i in range(3))
        ok, _detail = plane.health()
        assert ok
    finally:
        fleet.stop()
        # a stopped fleet's gauges are gone from scrapes (same contract
        # as a stopped replica — no stale last values forever)
        assert "crdt_fleet_ticks{" not in plane.registry.render()
        plane.close()


def test_replica_flight_recorder_records_sync_opens(transport):
    from delta_crdt_ex_tpu.api import set_neighbours, start_link

    plane = Observability()
    try:
        a = start_link(threaded=False, transport=transport, obs=plane, name="fa")
        b = start_link(threaded=False, transport=transport, obs=plane, name="fb")
        set_neighbours(a, [b])
        a.mutate("add", ["k", "v"])
        a.sync_to_all()
        transport.pump()
        kinds = {e["kind"] for e in a.flight.events()}
        assert "sync_open" in kinds
        a.stop()
        b.stop()
    finally:
        plane.close()


def test_disabled_obs_pays_nothing(transport):
    """Without a plane there is no recorder, no tracer, no handlers —
    the has_handlers guards keep disabled telemetry at a lock check."""
    from delta_crdt_ex_tpu.api import start_link

    rep = start_link(threaded=False, transport=transport, name="noobs")
    assert rep.flight is None and rep._lag is None and rep._obs is None
    for ev in telemetry.declared_events():
        assert not telemetry.has_handlers(ev)
    rep.stop()
