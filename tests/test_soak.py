"""Long-horizon randomized soak: many replicas, churning topology,
partitions/heals, crash-rehydrate mid-run, hundreds of ops — the
scaled-up version of the reference's integration scenarios
(``causal_crdt_test.exs:114-152`` partition/heal, ``:87-102`` storage
rehydrate) run as one continuous seeded history against a dict oracle.

The full soak takes minutes, so it is gated behind ``RUN_SOAK=1``
(``pytest tests/test_soak.py`` after setting it); a miniature seeded
version always runs to keep the path exercised in every suite run.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.storage import MemoryStorage
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from tests.conftest import converge


def _mk(transport, clock, name, storage, device=None):
    return start_link(
        AWLWWMap,
        threaded=False,
        transport=transport,
        clock=clock,
        capacity=256,
        tree_depth=6,
        name=name,
        storage_module=storage,
        device=device,
    )


def _soak_device(i: int, pin: bool):
    """Device assignment for replica i: pinned soaks alternate pinned/
    unpinned replicas over just TWO devices, so at >=5 replicas every
    plane pairing sees churn — pinned->pinned on the SAME device (the
    free-put fast path), pinned->pinned cross-device, and
    pinned<->unpinned (host-plane fallback)."""
    if not pin or i % 2:
        return None
    import jax

    devs = jax.devices()
    return devs[(i // 2) % min(2, len(devs))]


def _run_soak(n_replicas: int, n_ops: int, seed: int, pin_devices: bool = False):
    rng = np.random.default_rng(seed)
    transport = LocalTransport()
    clock = LogicalClock()
    storage = MemoryStorage()
    reps = [
        _mk(transport, clock, f"soak{seed}-{i}", storage, _soak_device(i, pin_devices))
        for i in range(n_replicas)
    ]

    def rewire(partition: set[int]):
        """Full mesh within each side of the partition (empty set = healed)."""
        for i, r in enumerate(reps):
            side = i in partition
            r.set_neighbours(
                [x for j, x in enumerate(reps) if x is not r and (j in partition) == side]
            )

    rewire(set())
    model: dict = {}
    partitioned: set[int] = set()

    try:
        for step in range(n_ops):
            who = int(rng.integers(0, n_replicas))
            op = rng.random()
            key = int(rng.integers(1, 40))
            # During a partition only ADDS keep the dict an exact oracle
            # (the shared clock makes LWW == program order); a remove or
            # clear issued on one side cannot observe the other side's
            # concurrent adds, so add-wins would legitimately disagree
            # with the dict (that divergence is covered by test_simnet).
            if partitioned and op >= 0.62:
                op = op * 0.62 if op < 0.86 else op  # remap mutations to add
            if op < 0.62:
                # adds never need convergence for dict-exactness: the
                # shared clock makes global LWW order == program order
                val = int(rng.integers(0, 1000))
                reps[who].mutate("add", [key, val])
                model[key] = val
            elif op < 0.82:
                # a remove is dict-exact only if the remover has OBSERVED
                # every prior dot (observed-remove): converge first
                converge(transport, reps, rounds=8)
                reps[who].mutate("remove", [key])
                model.pop(key, None)
            elif op < 0.86:
                converge(transport, reps, rounds=8)
                reps[who].mutate("clear", [])
                model.clear()
            elif op < 0.92 and not partitioned:
                # partition a random nonempty proper subset
                k = int(rng.integers(1, n_replicas))
                partitioned = set(
                    int(x) for x in rng.choice(n_replicas, k, replace=False)
                )
                rewire(partitioned)
            elif op < 0.96 and partitioned:
                partitioned = set()
                rewire(partitioned)  # heal
            else:
                # crash a replica (no terminate sync), rehydrate from storage
                victim = int(rng.integers(0, n_replicas))
                name = reps[victim].name
                transport.unregister(reps[victim].addr)
                reps[victim] = _mk(
                    transport, clock, name, storage,
                    _soak_device(victim, pin_devices),
                )
                rewire(partitioned)

            # under partition the sides diverge; only assert on full heals.
            # Ops during a partition only reach the writer's side, so the
            # oracle is maintained but checked when everyone can see it.
            if not partitioned and (step % 7 == 0 or step == n_ops - 1):
                converge(transport, reps, rounds=8)
                for i, r in enumerate(reps):
                    assert r.read() == model, (seed, step, i)

        if partitioned:
            rewire(set())
        converge(transport, reps, rounds=10)
        for i, r in enumerate(reps):
            assert r.read() == model, (seed, "final", i)
    finally:
        # clean up even on assertion failure: lingering MemoryStorage
        # snapshots would rehydrate into unrelated later tests
        for r in reps:
            try:
                r.stop()
            except Exception:
                pass
        MemoryStorage.clear()


def test_soak_miniature():
    """Always-on seeded miniature (3 replicas, 40 ops)."""
    _run_soak(3, 40, seed=11)


def test_soak_miniature_device_pinned():
    """Same hazards with half the replicas pinned to mesh devices: the
    device data plane must survive partitions, crash-rehydrate (which
    re-pins), and mixed-plane fan-out."""
    _run_soak(3, 40, seed=12, pin_devices=True)


def test_soak_medium_always_on():
    """Always-on ~30s medium soak (VERDICT r3 #9): 6 replicas, 2×300
    ops over two seeds, every hazard enabled, half the replicas
    device-pinned — the adversarial path (partitions + crash-rehydrate +
    mixed data planes) cannot rot between rounds behind the RUN_SOAK
    gate. The gated full soak stays the heavier run (more seeds, longer
    histories)."""
    _run_soak(6, 300, seed=31, pin_devices=True)
    _run_soak(6, 300, seed=32, pin_devices=True)


@pytest.mark.skipif(os.environ.get("RUN_SOAK") != "1", reason="set RUN_SOAK=1")
@pytest.mark.parametrize("seed,pin", [(1, False), (2, False), (3, False), (4, True)])
def test_soak_full(seed, pin):
    """Full soak: 6 replicas, 600 ops per seed, every hazard enabled
    (seed 4 runs with half the replicas device-pinned)."""
    _run_soak(6, 600, seed=seed, pin_devices=pin)
