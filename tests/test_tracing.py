"""runtime/tracing.py coverage (ISSUE 9 satellite — the module had
none): ``annotate`` spans, ``trace`` device captures, and the
``profile_mutations`` fprof-analog, all against a live replica. The
``jax.profiler`` capture calls are capability-probed — some CPU builds
ship without a profiler backend, and that must skip, not fail."""

from __future__ import annotations

import jax
import jax.numpy as jnp
import pytest

from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime import tracing


def test_annotate_is_a_reusable_span():
    with tracing.annotate("test.span"):
        x = jnp.arange(8).sum()
    assert int(x) == 28
    # nesting and re-entry both work (TraceAnnotation is per-use)
    with tracing.annotate("outer"), tracing.annotate("inner"):
        pass


def test_annotate_survives_exceptions():
    with pytest.raises(RuntimeError):
        with tracing.annotate("test.boom"):
            raise RuntimeError("boom")


def _probe_profiler(tmp_path) -> bool:
    """Capability probe: a CPU build without a profiler backend raises
    on start_trace — then the capture tests skip with an honest reason."""
    try:
        jax.profiler.start_trace(str(tmp_path / "probe"))
        jax.profiler.stop_trace()
        return True
    except Exception:
        return False


def test_trace_captures_device_trace(tmp_path):
    if not _probe_profiler(tmp_path):
        pytest.skip("jax.profiler trace capture unavailable in this build")
    logdir = tmp_path / "trace"
    with tracing.trace(str(logdir)):
        jnp.arange(64).sum().block_until_ready()
    captured = list(logdir.rglob("*"))
    assert captured, "trace() produced no profile artifacts"


def test_trace_stops_on_exception(tmp_path):
    if not _probe_profiler(tmp_path):
        pytest.skip("jax.profiler trace capture unavailable in this build")
    with pytest.raises(RuntimeError):
        with tracing.trace(str(tmp_path / "t2")):
            raise RuntimeError("mid-trace")
    # the finally-stop ran: a fresh trace can start (an unstopped trace
    # would raise "already started" here)
    with tracing.trace(str(tmp_path / "t3")):
        pass


def test_profile_mutations_against_live_replica(transport):
    crdt = start_link(threaded=False, transport=transport, name="prof")
    out = tracing.profile_mutations(crdt, n=32)
    assert out["mutations"] == 32
    assert out["total_s"] > 0
    assert out["per_op_us"] == pytest.approx(out["total_s"] / 32 * 1e6)
    assert out["trace_dir"] is None
    # the mutations really applied (hibernate flushed them)
    assert len(crdt.read()) == 32
    crdt.stop()


def test_profile_mutations_with_trace_dir(tmp_path, transport):
    if not _probe_profiler(tmp_path):
        pytest.skip("jax.profiler trace capture unavailable in this build")
    crdt = start_link(threaded=False, transport=transport, name="prof2")
    logdir = tmp_path / "prof"
    out = tracing.profile_mutations(crdt, n=8, logdir=str(logdir))
    assert out["trace_dir"] == str(logdir)
    assert list(logdir.rglob("*")), "profiled run produced no artifacts"
    crdt.stop()
