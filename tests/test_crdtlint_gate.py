"""The crdtlint tier-1 gate.

One test runs the FULL rule suite (all families: LOCK, RACE, SYNC,
PURE, DONATE, WIRE, WAL, OBS, SHAPE, LEAK, SPMD, TRANSFER, FAULT + the
SUPPRESS hygiene pass) over the real package
through the engine and fails on any non-baselined finding — this is the
regression gate CI leans on, so it renders findings verbatim on
failure. The rest pin the gate's own wiring: the checked-in protocol
manifest must cover the real package (or WIRE005 silently guards
nothing), the CLI must agree with the engine, and ``--format github``
must emit workflow-command annotations CI logs can surface on the diff.

Stdlib-only under test (the linter never imports jax), cheap enough for
tier-1.
"""

from __future__ import annotations

import subprocess
import sys
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.crdtlint.cli import DEFAULT_BASELINE, RULE_CATALOG  # noqa: E402
from tools.crdtlint.engine import load_baseline, run_lint  # noqa: E402
from tools.crdtlint.rules.wire import DEFAULT_MANIFEST, load_manifest  # noqa: E402

PKG = "delta_crdt_ex_tpu"


def test_full_suite_gate_is_green():
    """THE gate: every rule family over the real tree, baseline applied,
    hygiene on — zero unsuppressed findings."""
    baseline = load_baseline(DEFAULT_BASELINE) if DEFAULT_BASELINE.exists() else None
    new, _baselined, _allowed = run_lint([REPO_ROOT / PKG], baseline=baseline)
    assert new == [], "crdtlint gate is red:\n" + "\n".join(
        f.render() for f in new
    )


def test_gate_covers_every_catalogued_family():
    """The gate runs ALL families — a rule added to the catalog without
    being registered in ALL_RULES would silently not gate."""
    from tools.crdtlint.rules import ALL_RULES

    catalogued = {rule for rule, _ in RULE_CATALOG}
    for family in ("LOCK001", "LOCK002", "LOCK003", "RACE001", "RACE002",
                   "RACE003", "RACE004", "RACE005", "SYNC001", "PURE001",
                   "DONATE001", "WIRE001", "WIRE005", "WAL001", "WAL002",
                   "OBS001", "OBS002", "SHAPE001", "SHAPE002", "LEAK001",
                   "SPMD001", "TRANSFER001", "TRANSFER002",
                   "FAULT001", "FAULT002", "FAULT003", "FAULT004",
                   "FAULT005",
                   "SUPPRESS001", "SUPPRESS002", "SUPPRESS003"):
        assert family in catalogued
    # every registered checker's module exports at least one catalogued
    # rule id (wiring smoke, not a bijection)
    assert len(ALL_RULES) >= 14


def test_full_suite_wall_clock_budget():
    """The twelve-family suite must stay comfortably inside the tier-1
    timeout: one full engine run over the real tree in under 60 s (it
    takes ~9 s serial today — ``--jobs`` exists for CI that wants it
    faster; the budget is headroom, not a target)."""
    import time

    t0 = time.perf_counter()
    run_lint([REPO_ROOT / PKG])
    assert time.perf_counter() - t0 < 60.0


def test_jobs_parallel_matches_serial():
    """--jobs N must be a pure wall-clock lever: findings, their order,
    and the allow/baseline partition are byte-identical to a serial
    run (per-rule sharding, merged in registration order). Covers the
    ISSUE 12 families too: SHAPE/LEAK/SPMD are whole-project analyses
    (storing-parameter fix point, project-wide static-wrapper
    discovery), so a per-file shard would lose their cross-file edges
    — the per-rule sharding must keep them byte-identical."""
    serial = run_lint([REPO_ROOT / PKG])
    parallel = run_lint([REPO_ROOT / PKG], jobs=2)
    assert serial == parallel


def test_jobs_parallel_matches_serial_on_red_tree():
    """Same parity on a tree where the new families actually FIRE (the
    green real tree can't distinguish ordering): a SHAPE001 mutation
    overlay must produce identical findings serial and parallel."""
    rel = f"{PKG}/runtime/fleet.py"
    src = (REPO_ROOT / rel).read_text()
    overlay = {rel: src.replace(
        "        lanes = self._lane_tier(n)\n        sl, real_rows",
        "        lanes = n\n        sl, real_rows",
    )}
    serial = run_lint([REPO_ROOT / PKG], overlay=overlay)
    parallel = run_lint([REPO_ROOT / PKG], overlay=overlay, jobs=3)
    assert serial == parallel
    assert any(f.rule == "SHAPE001" for f in serial[0])


def test_jobs_parallel_matches_serial_on_transfer_red_tree():
    """TRANSFER parity leg (ISSUE 17): the transfer checker is part
    whole-project ledger scan (TRANSFER002 dedupes labels package-wide)
    and part per-module boundary walk — a per-file shard would lose the
    cross-module duplicate-label edge, so the per-rule sharding must
    keep a firing TRANSFER tree byte-identical serial vs parallel."""
    rel = f"{PKG}/runtime/replica.py"
    src = (REPO_ROOT / rel).read_text()
    anchor = "        got = _TR_WAL_ENTRIES.get(a)"
    assert anchor in src
    overlay = {rel: src.replace(anchor, "        got = jax.device_get(a)", 1)}
    serial = run_lint([REPO_ROOT / PKG], overlay=overlay)
    parallel = run_lint([REPO_ROOT / PKG], overlay=overlay, jobs=3)
    assert serial == parallel
    assert any(f.rule == "TRANSFER001" for f in serial[0])


def test_jobs_parallel_matches_serial_on_fault_red_tree():
    """FAULT parity leg (ISSUE 20): the fault checker mixes a
    whole-project pass (FAULT005 dedupes faultpoint labels and checks
    the SITES vocabulary package-wide) with per-module walks — the
    per-rule sharding must keep a firing FAULT tree byte-identical
    serial vs parallel."""
    rel = f"{PKG}/utils/faults.py"
    src = (REPO_ROOT / rel).read_text()
    anchor = '    "fleet.loop",'
    assert anchor in src
    overlay = {rel: src.replace(anchor, anchor + '\n    "ghost.site",', 1)}
    serial = run_lint([REPO_ROOT / PKG], overlay=overlay)
    parallel = run_lint([REPO_ROOT / PKG], overlay=overlay, jobs=3)
    assert serial == parallel
    assert any(f.rule == "FAULT005" for f in serial[0])


def test_fault_family_pinned_at_zero_findings_empty_baseline():
    """The FAULT family gates the real tree at ZERO findings with an
    EMPTY baseline — the failure-atomicity instrument starts clean, so
    any future torn window / swallowed exception / ordering slip is a
    red gate, not a new baseline entry."""
    baseline = load_baseline(DEFAULT_BASELINE)
    assert not [e for e in baseline if e[1].startswith("FAULT")]
    new, baselined, _allowed = run_lint(
        [REPO_ROOT / PKG],
        select={"FAULT001", "FAULT002", "FAULT003", "FAULT004", "FAULT005"},
    )
    assert [f for f in new if f.rule.startswith("FAULT")] == []
    assert baselined == []


def test_transfer_family_pinned_at_zero_findings_empty_baseline():
    """The TRANSFER family gates the real tree at ZERO findings with an
    EMPTY baseline — the device-resident campaign's instrument starts
    clean, so any future un-audited crossing is a red gate, not a new
    baseline entry."""
    baseline = load_baseline(DEFAULT_BASELINE)
    assert not [e for e in baseline if e[1].startswith("TRANSFER")]
    new, baselined, _allowed = run_lint(
        [REPO_ROOT / PKG], select={"TRANSFER001", "TRANSFER002"},
    )
    assert [f for f in new if f.rule.startswith("TRANSFER")] == []
    assert baselined == []


def test_stats_reports_per_rule_timing():
    stats: dict[str, float] = {}
    run_lint([REPO_ROOT / PKG], stats_out=stats)
    assert "check_races" in stats and stats["check_races"] > 0
    assert len(stats) >= 8


def test_protocol_manifest_covers_real_package():
    """WIRE005 only locks packages recorded in the manifest — the real
    package must be there, with the full current message vocabulary."""
    manifest = load_manifest(DEFAULT_MANIFEST)
    stanza = manifest["packages"][PKG]
    assert stanza["module"].endswith("runtime/sync.py")
    msgs = set(stanza["messages"])
    assert {
        "DiffMsg", "GetDiffMsg", "EntriesMsg",
        "GetLogMsg", "LogChunkMsg", "AckMsg",
    } <= msgs
    for name, entry in stanza["messages"].items():
        assert entry["fields"], f"{name}: manifest entry without fields"
        assert len(entry["sha256"]) == 64


def _cli(*argv: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", *argv],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=180,
    )


def test_cli_gate_green_and_github_format(tmp_path):
    proc = _cli(PKG)
    assert proc.returncode == 0, f"crdtlint CLI gate red:\n{proc.stdout}{proc.stderr}"

    # --format github on a red fixture tree emits ::error annotations
    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "box.py").write_text(
        "import threading\n\n\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = []\n\n"
        "    def put(self, x):\n"
        "        with self._lock:\n"
        "            self._items.append(x)\n\n"
        "    def size(self):\n"
        "        return len(self._items)\n"
    )
    proc = _cli(str(pkg), "--format", "github", "--no-baseline")
    assert proc.returncode == 1
    line = next(l for l in proc.stdout.splitlines() if l.startswith("::error"))
    assert "file=" in line and "line=" in line and "title=crdtlint LOCK001" in line


def test_cli_list_rules_names_all_families():
    out = _cli("--list-rules").stdout
    for rule in ("LOCK002", "LOCK003", "RACE001", "RACE005", "WIRE001",
                 "WIRE004", "WIRE005", "WAL001", "WAL002", "SHAPE001",
                 "SHAPE002", "LEAK001", "SPMD001", "TRANSFER001",
                 "TRANSFER002", "FAULT001", "FAULT003", "FAULT005",
                 "SUPPRESS001", "SUPPRESS003"):
        assert rule in out


def test_cli_sarif_format(tmp_path):
    """--format sarif emits one valid SARIF 2.1.0 document on stdout:
    rule metadata from the catalog, one result per finding keyed by
    ruleIndex, 1-based regions — the code-scanning upload contract."""
    import json

    pkg = tmp_path / "fixpkg"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "box.py").write_text(
        "import threading\n\n\n"
        "class Box:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "        self._items = []\n\n"
        "    def put(self, x):\n"
        "        with self._lock:\n"
        "            self._items.append(x)\n\n"
        "    def size(self):\n"
        "        return len(self._items)\n"
    )
    proc = _cli(str(pkg), "--format", "sarif", "--no-baseline")
    assert proc.returncode == 1
    doc = json.loads(proc.stdout)  # stdout is ONLY the document
    assert doc["version"] == "2.1.0"
    driver = doc["runs"][0]["tool"]["driver"]
    assert driver["name"] == "crdtlint"
    rules = driver["rules"]
    assert {r["id"] for r in rules} == {rule for rule, _ in RULE_CATALOG}
    results = doc["runs"][0]["results"]
    assert results, "red fixture tree must produce results"
    for res in results:
        assert rules[res["ruleIndex"]]["id"] == res["ruleId"]
        assert res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"].endswith(".py")
        assert loc["region"]["startLine"] >= 1
    assert any(res["ruleId"] == "LOCK001" for res in results)

    # green tree → exit 0, still a parseable document with zero results
    proc = _cli(PKG, "--format", "sarif")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    doc = json.loads(proc.stdout)
    assert doc["runs"][0]["results"] == []


def test_cli_jobs_and_stats():
    proc = _cli(PKG, "--jobs", "2", "--stats")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "timing check_races" in proc.stdout
    assert "timing total" in proc.stdout
