"""Mesh-sharded fleets (ISSUE 13): the shard_map twins and the
intra-mesh delivery plane must be OBSERVABLY IDENTICAL to the vmap
fleet — bit-for-bit state, WAL bytes, ack/protocol streams, and wire
bytes — while the hot dispatches ride a replica-sharded device mesh
and co-mesh sync-tick entries move as ppermute rotations instead of
host sends.

The conftest forces 8 virtual CPU devices
(``--xla_force_host_platform_device_count``), so every shard count in
{1, 2, 4, 8} is exercised in-process without TPU hardware — the same
topology ``bench.py --fleet --mesh`` measures.

Covers: mesh-vs-vmap kernel lane parity (both store backends),
mesh-vs-vmap fleet parity on intra-mesh gossip (state, WAL bytes, seq,
ack bookkeeping) and on off-mesh egress (wire streams + bytes, the TCP
fallback path), mixed on/off-mesh destinations in one tick,
shard-padding lanes (members ≶ shards), resident sharded-state
placement + invalidation on fallback, and the mesh construction
validation."""

import pickle

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime import sync as sync_proto, transition
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.fleet import Fleet
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from delta_crdt_ex_tpu.utils.devices import (
    detected_topology,
    fleet_mesh,
    mesh_shard_count,
)
from tests.test_ingest_coalesce import (
    _wal_segment_bytes,
    keys_for_buckets,
)


def assert_state_bit_equal(s1, s2, ctx=""):
    """Backend-agnostic bit comparison (the binned-column helper in
    test_ingest_coalesce assumes BinnedStore fields)."""
    l1, t1 = jax.tree.flatten(s1)
    l2, t2 = jax.tree.flatten(s2)
    assert t1 == t2, ctx
    for a, b in zip(l1, l2):
        assert np.array_equal(np.asarray(a), np.asarray(b)), ctx


def _mk(transport, store="binned", **kw):
    kw.setdefault("capacity", 256)
    kw.setdefault("tree_depth", 4)
    # in-flight sync slots must not expire mid-test (see test_fleet.py)
    kw.setdefault("sync_timeout", 600.0)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=LogicalClock(),
        store=store, **kw,
    )


def _norm(msg):
    """Address-free canonical form of one outbound sync message."""
    if isinstance(msg, sync_proto.EntriesMsg):
        return (
            "entries",
            np.asarray(msg.buckets).tolist(),
            {c: np.asarray(v).tolist() for c, v in msg.arrays.items()},
            sorted(map(repr, msg.payloads.items())),
        )
    if isinstance(msg, sync_proto.DiffMsg):
        return (
            "diff", msg.level, np.asarray(msg.idx).tolist(),
            [np.asarray(b).tolist() for b in msg.blocks], msg.seq,
            msg.log_horizon,
        )
    if isinstance(msg, sync_proto.AckMsg):
        return ("ack",)
    return (type(msg).__name__,)


def _wire_bytes(msg):
    """Pickled size of the address-free body — the wire-byte quantity."""
    if isinstance(msg, sync_proto.EntriesMsg):
        return len(pickle.dumps(
            (np.asarray(msg.buckets),
             {c: np.asarray(v) for c, v in msg.arrays.items()},
             msg.payloads),
            protocol=4,
        ))
    if isinstance(msg, sync_proto.DiffMsg):
        return len(pickle.dumps(
            (msg.level, msg.idx, msg.blocks, msg.seq, msg.log_horizon),
            protocol=4,
        ))
    return 0


# ---------------------------------------------------------------------------
# mesh twin kernel parity: shard_map form == vmap form, bit-for-bit


@pytest.mark.parametrize("shards", [1, 2, 4, 8])
def test_mesh_merge_twin_matches_vmap(shards):
    from tests.test_fleet import _mk_states_and_slices, _np_slice
    from delta_crdt_ex_tpu.models.binned_map import stack_entry_slices

    mesh = fleet_mesh(shards)
    states, slices = _mk_states_and_slices(8, seed=shards)
    stacked_sl, _ = stack_entry_slices([_np_slice(s) for s in slices])
    stacked_st = transition.stack_states(states)
    ref = transition.jit_fleet_merge_rows(stacked_st, stacked_sl)
    got = transition.jit_mesh_fleet_merge_rows(mesh, stacked_st, stacked_sl)
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("store", ["binned", "hash"])
def test_mesh_extraction_twins_match_vmap(store):
    transport = LocalTransport()
    n = 8
    mesh = fleet_mesh(4)
    reps = [
        _mk(transport, store=store, name=f"mx{store}{i}", node_id=50 + i)
        for i in range(n)
    ]
    for i, r in enumerate(reps):
        for j in range(1 + 2 * i):  # ragged content: distinct dense tiers
            r.mutate("add", [i * 100 + j, j])
    model = reps[0].model
    stacked = transition.stack_states([r.state for r in reps])
    u = 16
    rows = np.full((n, u), -1, np.int32)
    lo = np.zeros((n, u), np.uint32)
    for i, r in enumerate(reps):
        own = np.asarray(r.state.ctx_max[:, r.self_slot])
        pend = np.nonzero(own)[0][:u]
        rows[i, : len(pend)] = pend
    slots = np.asarray([r.self_slot for r in reps], np.int32)
    gids = np.asarray([r.node_id for r in reps], np.uint64)

    ref, ref_tiers = model.fleet_extract_own_delta(
        stacked, jnp.asarray(rows), jnp.asarray(slots), jnp.asarray(gids),
        jnp.asarray(lo),
    )
    got, got_tiers = model.mesh_fleet_extract_own_delta(
        mesh, stacked, jnp.asarray(rows), jnp.asarray(slots),
        jnp.asarray(gids), jnp.asarray(lo),
    )
    assert ref_tiers == got_tiers
    for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(got)):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    ref2, t2 = model.fleet_extract_rows(stacked, jnp.asarray(rows))
    got2, g2 = model.mesh_fleet_extract_rows(mesh, stacked, jnp.asarray(rows))
    assert t2 == g2
    for a, b in zip(jax.tree.leaves(ref2), jax.tree.leaves(got2)):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_mesh_tree_and_ctr_twins_match_vmap():
    mesh = fleet_mesh(4)
    rng = np.random.default_rng(7)
    leaves = jnp.asarray(
        rng.integers(0, 2**32, size=(8, 16), dtype=np.uint32)
    )
    ref = transition.jit_fleet_tree_from_leaves(leaves)
    got = transition.jit_mesh_fleet_tree_from_leaves(mesh, leaves)
    assert len(ref) == len(got)
    for a, b in zip(ref, got):
        assert np.array_equal(np.asarray(a), np.asarray(b))

    cm = jnp.asarray(rng.integers(0, 1000, size=(8, 16, 8)).astype(np.uint32))
    slots = jnp.asarray(np.arange(8, dtype=np.int32) % 8)
    assert np.array_equal(
        np.asarray(transition.jit_fleet_own_ctr_columns(cm, slots)),
        np.asarray(transition.jit_mesh_fleet_own_ctr_columns(mesh, cm, slots)),
    )


def test_mesh_plane_rotate_moves_lanes_intact():
    mesh = fleet_mesh(4)
    rng = np.random.default_rng(11)
    bufs = {
        "a": rng.integers(0, 2**31, size=(4, 2, 3)).astype(np.int64),
        "b": rng.integers(0, 2**32, size=(4, 2), dtype=np.uint64),
    }
    for shift in (1, 2, 3):
        out = jax.device_get(
            transition.jit_mesh_plane_rotate(
                mesh, shift, jax.device_put(bufs, transition.replica_sharding(mesh))
            )
        )
        for c, buf in bufs.items():
            assert np.array_equal(out[c], np.roll(buf, shift, axis=0)), (c, shift)


# ---------------------------------------------------------------------------
# runtime parity: mesh fleet == vmap fleet, intra-mesh gossip


def _drive_converged(fleet_a, fleet_b, members_a, members_b, rounds=6):
    for _ in range(rounds):
        fleet_a.sync_tick()
        fleet_b.sync_tick()
        fleet_a.drain()
        fleet_b.drain()
        for r in members_a + members_b:
            r._outstanding.clear()
            r._sync_open_seq.clear()


@pytest.mark.parametrize("store", ["binned", "hash"])
@pytest.mark.parametrize("shards", [2, 8])
def test_mesh_vs_vmap_intra_gossip_bit_parity(store, shards, tmp_path):
    """THE acceptance property: members gossiping among themselves —
    every sync-tick entry crosses the mesh plane — end bit-identical to
    the vmap fleet on state, seq, WAL segment bytes, and ack
    bookkeeping, on both store backends and at shard counts below and
    at the device count."""
    transport = LocalTransport()
    n = 4
    mk = lambda tag, i: _mk(
        transport, store=store, name=f"mg{store}{shards}{tag}{i}",
        node_id=100 + i, wal_dir=str(tmp_path / f"{tag}{i}"),
        fsync_mode="none",
    )
    fm = [mk("m", i) for i in range(n)]
    vm = [mk("v", i) for i in range(n)]
    for i in range(n):
        fm[i].set_neighbours([fm[(i + 1) % n], fm[(i + 2) % n]])
        vm[i].set_neighbours([vm[(i + 1) % n], vm[(i + 2) % n]])
    f_mesh = Fleet(fm, mesh=fleet_mesh(shards))
    f_vmap = Fleet(vm)

    for rnd in range(3):
        for i in range(n):
            for j in range(2 + i):
                k = rnd * 100 + i * 10 + j
                fm[i].mutate("add", [k, k])
                vm[i].mutate("add", [k, k])
            if rnd == 1 and i % 2 == 0:
                fm[i].mutate("remove", [100 + i * 10])
                vm[i].mutate("remove", [100 + i * 10])
        _drive_converged(f_mesh, f_vmap, fm, vm, rounds=1)
    _drive_converged(f_mesh, f_vmap, fm, vm)

    for i in range(n):
        assert fm[i].read() == vm[i].read(), i
        assert fm[i]._seq == vm[i]._seq, i
        assert_state_bit_equal(fm[i].state, vm[i].state, (store, shards, i))
        assert _wal_segment_bytes(fm[i]) == _wal_segment_bytes(vm[i]), i
        assert len(fm[i]._outstanding) == len(vm[i]._outstanding), i
    ms = f_mesh.stats()["mesh"]
    assert ms["enabled"] and ms["shards"] == shards
    assert ms["intra_entries"] > 0
    assert ms["fallback_entries"] == 0  # every destination is co-mesh
    if shards > 1:
        assert ms["exchanges"] > 0 and ms["permuted_bytes"] > 0
    # topology provenance: the PROBE_SHAPE field vocabulary
    assert ms["topology"]["platform"] == "cpu"
    assert ms["topology"]["global_devices"] >= shards
    vs = f_vmap.stats()["mesh"]
    assert not vs["enabled"] and vs["shards"] == 0


@pytest.mark.parametrize("store", ["binned", "hash"])
def test_mesh_off_mesh_fallback_stream_parity(store):
    """Off-mesh destinations (receivers outside the fleet) take the
    PR 10 collector path unchanged: the receivers' drained streams are
    canonically identical and byte-for-byte equal in wire size to the
    vmap fleet's — and the plane counts them as fallback entries."""
    transport = LocalTransport()
    n = 4
    fm = [
        _mk(transport, store=store, name=f"of{store}m{i}", node_id=100 + i)
        for i in range(n)
    ]
    vm = [
        _mk(transport, store=store, name=f"of{store}v{i}", node_id=100 + i)
        for i in range(n)
    ]
    frecv = [
        _mk(transport, store=store, name=f"of{store}mr{i}", node_id=900 + i)
        for i in range(n)
    ]
    orecv = [
        _mk(transport, store=store, name=f"of{store}vr{i}", node_id=900 + i)
        for i in range(n)
    ]
    for i in range(n):
        fm[i].set_neighbours([frecv[i]])
        vm[i].set_neighbours([orecv[i]])
    f_mesh = Fleet(fm, mesh=fleet_mesh(4))
    f_vmap = Fleet(vm)
    mesh_bytes = vmap_bytes = 0
    for rnd in range(3):
        for i in range(n):
            for j in range(2 + i):
                k = rnd * 1000 + i * 10 + j
                fm[i].mutate("add", [k, k])
                vm[i].mutate("add", [k, k])
        f_mesh.sync_tick()
        f_vmap.sync_tick()
        for i in range(n):
            a_msgs = transport.drain(frecv[i].addr)
            b_msgs = transport.drain(orecv[i].addr)
            assert len(a_msgs) == len(b_msgs) > 0, (rnd, i)
            for a, b in zip(a_msgs, b_msgs):
                assert _norm(a) == _norm(b), (rnd, i, type(a).__name__)
                mesh_bytes += _wire_bytes(a)
                vmap_bytes += _wire_bytes(b)
            fm[i]._outstanding.clear()
            fm[i]._sync_open_seq.clear()
            vm[i]._outstanding.clear()
            vm[i]._sync_open_seq.clear()
    assert mesh_bytes == vmap_bytes > 0
    ms = f_mesh.stats()["mesh"]
    assert ms["fallback_entries"] > 0
    assert ms["intra_entries"] == 0 and ms["exchanges"] == 0


def test_mesh_mixed_destinations_one_tick():
    """Members whose neighbour sets span the mesh AND an off-mesh
    receiver in the SAME tick: co-mesh entries ride the exchange,
    off-mesh ones the collector — and both receiver classes see exactly
    the vmap twin's streams."""
    transport = LocalTransport()
    n = 4
    fm = [_mk(transport, name=f"mixm{i}", node_id=100 + i) for i in range(n)]
    vm = [_mk(transport, name=f"mixv{i}", node_id=100 + i) for i in range(n)]
    frecv = [_mk(transport, name=f"mixmr{i}", node_id=900 + i) for i in range(n)]
    orecv = [_mk(transport, name=f"mixvr{i}", node_id=900 + i) for i in range(n)]
    for i in range(n):
        # one co-fleet neighbour + one external receiver each
        fm[i].set_neighbours([fm[(i + 1) % n], frecv[i]])
        vm[i].set_neighbours([vm[(i + 1) % n], orecv[i]])
    f_mesh = Fleet(fm, mesh=fleet_mesh(4))
    f_vmap = Fleet(vm)
    for i in range(n):
        fm[i].mutate("add", [i, i * 11])
        vm[i].mutate("add", [i, i * 11])
    f_mesh.sync_tick()
    f_vmap.sync_tick()
    # external receivers: stream parity through the fallback path
    for i in range(n):
        a_msgs = transport.drain(frecv[i].addr)
        b_msgs = transport.drain(orecv[i].addr)
        assert len(a_msgs) == len(b_msgs) > 0, i
        for a, b in zip(a_msgs, b_msgs):
            assert _norm(a) == _norm(b), i
    ms = f_mesh.stats()["mesh"]
    assert ms["intra_entries"] > 0 and ms["fallback_entries"] > 0
    # co-mesh deliveries land in member mailboxes: both fleets drain
    # them into identical end states
    f_mesh.drain()
    f_vmap.drain()
    for i in range(n):
        assert fm[i].read() == vm[i].read(), i
        assert_state_bit_equal(fm[i].state, vm[i].state, i)


@pytest.mark.parametrize("n,shards", [(3, 8), (5, 4), (2, 2)])
def test_mesh_shard_padding_lanes(n, shards):
    """Member counts below/above/at the shard count: the lane tier pads
    to a shard multiple (padding lanes merge nothing), occupancy counts
    real members only, and parity holds."""
    transport = LocalTransport()
    fm = [_mk(transport, name=f"pad{n}{shards}m{i}", node_id=100 + i) for i in range(n)]
    vm = [_mk(transport, name=f"pad{n}{shards}v{i}", node_id=100 + i) for i in range(n)]
    for i in range(n):
        fm[i].set_neighbours([fm[(i + 1) % n]])
        vm[i].set_neighbours([vm[(i + 1) % n]])
    f_mesh = Fleet(fm, mesh=fleet_mesh(shards))
    f_vmap = Fleet(vm)
    assert f_mesh._lane_tier(n) % shards == 0
    assert f_mesh._lane_tier(n) >= max(n, shards)
    for rnd in range(2):
        for i in range(n):
            fm[i].mutate("add", [rnd * 10 + i, i])
            vm[i].mutate("add", [rnd * 10 + i, i])
        _drive_converged(f_mesh, f_vmap, fm, vm, rounds=1)
    _drive_converged(f_mesh, f_vmap, fm, vm)
    for i in range(n):
        assert fm[i].read() == vm[i].read(), (n, shards, i)
        assert_state_bit_equal(fm[i].state, vm[i].state, (n, shards, i))


def test_mesh_ingress_batches_and_resident_state_sharded():
    """The ingress half rides the mesh twins too: a batched wave lands
    in ONE sharded dispatch, and the resident stacked result stays
    replica-sharded over the mesh between ticks."""
    from tests.test_ingest_coalesce import entries_only

    transport = LocalTransport()
    clock = LogicalClock()
    n = 4
    mesh = fleet_mesh(4)
    senders = [
        start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=256, tree_depth=4, name=f"ribs{i}", sync_timeout=600.0,
        )
        for i in range(n)
    ]
    members = [
        _mk(transport, name=f"ribm{i}", node_id=100 + i) for i in range(n)
    ]
    for i, s in enumerate(senders):
        s.set_neighbours([members[i]])
    fleet = Fleet(members, mesh=mesh)
    for rnd in range(2):
        for i, s in enumerate(senders):
            for k in keys_for_buckets(0, 16, 2, start=rnd * 37 + 7 * i):
                s.mutate("add", [k, k])
            s.sync_to_all()
        for r in members:
            entries_only(transport, r.addr)
        fleet.drain()
    st = fleet.stats()
    assert st["dispatches"] >= 1
    assert st["occupancy_hist"].get(n, 0) >= 1
    # resident stacked state: cached and replica-sharded over the mesh
    assert fleet._stack_cache, "no resident stacked state cached"
    sharding = transition.replica_sharding(mesh)
    for _versions, stacked in fleet._stack_cache.values():
        leaf = jax.tree.leaves(stacked)[0]
        assert leaf.sharding.is_equivalent_to(sharding, leaf.ndim)


def test_mesh_resident_state_invalidated_on_fallback(tmp_path):
    """A member escaping a sharded batched dispatch (bin-tier overflow
    → solo growth path) must drop the bucket's resident sharded stack —
    its lane in the result is stale — and end states still match the
    vmap twin's."""
    transport = LocalTransport()
    n = 2
    # per-replica clocks: the twin universes' ts streams must be
    # identical, not interleaved through one shared counter
    mk_member = lambda tag, i: start_link(
        AWLWWMap, threaded=False, transport=transport, clock=LogicalClock(),
        capacity=64, tree_depth=6, node_id=1000 + i, name=f"{tag}{i}",
        sync_timeout=600.0,
    )
    mk_sender = lambda tag, i: start_link(
        AWLWWMap, threaded=False, transport=transport, clock=LogicalClock(),
        capacity=64, tree_depth=6, node_id=7000 + i, name=f"{tag}s{i}",
        sync_timeout=600.0,
    )
    fsend = [mk_sender("mf", i) for i in range(n)]
    vsend = [mk_sender("mv", i) for i in range(n)]
    fm = [mk_member("mff", i) for i in range(n)]
    vm = [mk_member("mvf", i) for i in range(n)]
    for i in range(n):
        fsend[i].set_neighbours([fm[i]])
        vsend[i].set_neighbours([vm[i]])
    f_mesh = Fleet(fm, mesh=fleet_mesh(2))
    f_vmap = Fleet(vm)
    # tiny bins (64 cap / 64 buckets → 4-slot bins): >4 same-bucket keys
    # overflow a member's bin tier mid-batch → per-lane escape (the
    # test_fleet growth-escape scenario, in mesh mode)
    for k in keys_for_buckets(3, 4, 6, start=0):
        fsend[0].mutate("add", [k, "x"])
        vsend[0].mutate("add", [k, "x"])
    for k in keys_for_buckets(40, 41, 5, start=50_000):
        fsend[1].mutate("add", [k, "y"])
        vsend[1].mutate("add", [k, "y"])
    for s in fsend + vsend:
        s.sync_to_all()
    from tests.test_ingest_coalesce import entries_only

    for r in fm + vm:
        entries_only(transport, r.addr)
    f_mesh.drain()
    f_vmap.drain()
    assert f_mesh.stats()["fallbacks"]["escape"] >= 1
    # the escape dropped the resident sharded stack for that bucket
    assert not f_mesh._stack_cache
    for i in range(n):
        assert fm[i].read() == vm[i].read(), i
        assert_state_bit_equal(fm[i].state, vm[i].state, i)


# ---------------------------------------------------------------------------
# construction + validation


def test_fleet_mesh_helpers():
    assert mesh_shard_count(8) == 8
    assert mesh_shard_count(6) == 4
    assert mesh_shard_count(1) == 1
    with pytest.raises(ValueError):
        fleet_mesh(3)
    with pytest.raises(ValueError):
        fleet_mesh(1024)  # more shards than devices
    mesh = fleet_mesh()
    assert mesh.axis_names == ("replicas",)
    assert mesh.devices.size == mesh_shard_count()
    topo = detected_topology()
    assert set(topo) == {
        "platform", "global_devices", "local_devices", "processes"
    }


def test_fleet_rejects_bad_mesh():
    import jax as _jax
    from jax.sharding import Mesh

    transport = LocalTransport()
    rep = _mk(transport, name="badmesh0")
    with pytest.raises(ValueError, match="replicas"):
        Fleet([rep], mesh=Mesh(np.array(_jax.devices()[:2]), ("clients",)))


def test_fleet_mesh_int_and_true_knobs():
    transport = LocalTransport()
    r1 = _mk(transport, name="knob0")
    f = Fleet([r1], mesh=2)
    assert f._mesh_shards == 2
    r2 = _mk(transport, name="knob1")
    f2 = Fleet([r2], mesh=True)
    assert f2._mesh_shards == mesh_shard_count()
