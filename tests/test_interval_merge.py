"""Delta-interval merge tests (Almeida et al.'s delta-intervals).

A partial slice whose context is the interval ``(lo, hi]`` claims only
the dots it ships: older alive dots of the same (bucket, writer) must
survive the merge, and a non-contiguous interval (a gap beneath ``lo``)
must be rejected rather than silently over-advancing the context.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from delta_crdt_ex_tpu.models.binned_map import BinnedAWLWWMap as M
from delta_crdt_ex_tpu.ops.binned import RowSlice
from tests.kernel_harness import BinnedKernelMap

L = 64  # num_buckets of the harness default
WRITER = 777


def interval_slice(rows, entries, lo, hi):
    """Build a single-writer RowSlice by hand: ``entries`` is a list of
    (row_index_into_rows, key, valh, ts, ctr); interval (lo, hi] per row."""
    u, s = len(rows), max(len(entries), 1)
    sl = dict(
        rows=np.asarray(rows, np.int32),
        key=np.zeros((u, s), np.uint64),
        valh=np.zeros((u, s), np.uint32),
        ts=np.zeros((u, s), np.int64),
        node=np.zeros((u, s), np.int32),
        ctr=np.zeros((u, s), np.uint32),
        alive=np.zeros((u, s), bool),
        ctx_rows=np.asarray(hi, np.uint32).reshape(u, 1),
        ctx_lo=np.asarray(lo, np.uint32).reshape(u, 1),
        ctx_gid=np.array([WRITER], np.uint64),
    )
    fill = [0] * u
    for r, key, valh, ts, ctr in entries:
        j = fill[r]
        fill[r] = j + 1
        sl["key"][r, j] = key
        sl["valh"][r, j] = valh
        sl["ts"][r, j] = ts
        sl["ctr"][r, j] = ctr
        sl["alive"][r, j] = True
    return RowSlice(**{k: jnp.asarray(v) for k, v in sl.items()})


def test_interval_delta_does_not_kill_older_unshipped_dots():
    b = BinnedKernelMap(11)
    bucket = 1
    k1, k2 = 1, 1 + L  # same bucket
    # delta 1: writer adds k1 (ctr 1); interval (0, 1]
    b.merge_slice(interval_slice([bucket], [(0, k1, 10, 1, 1)], [0], [1]))
    assert b.read() == {k1: 10}
    # delta 2: writer adds k2 (ctr 2); interval (1, 2] — k1 NOT shipped
    b.merge_slice(interval_slice([bucket], [(0, k2, 20, 2, 2)], [1], [2]))
    assert b.read() == {k1: 10, k2: 20}  # k1 survives: not claimed


def test_state_form_slice_with_same_content_would_kill():
    """Contrast case: the same partial content shipped as a state-form
    slice (lo=0) over-claims and kills the unshipped dot — exactly the
    unsoundness delta-intervals exist to prevent."""
    b = BinnedKernelMap(11)
    bucket = 1
    k1, k2 = 1, 1 + L
    b.merge_slice(interval_slice([bucket], [(0, k1, 10, 1, 1)], [0], [1]))
    b.merge_slice(interval_slice([bucket], [(0, k2, 20, 2, 2)], [0], [2]))
    assert b.read() == {k2: 20}  # state-form claim (0,2] killed ctr 1


def test_interval_gap_is_rejected():
    b = BinnedKernelMap(11)
    bucket = 1
    k1, k3 = 1, 1 + 2 * L
    b.merge_slice(interval_slice([bucket], [(0, k1, 10, 1, 1)], [0], [1]))
    # skip ctr 2: interval (2, 3] has a gap beneath it
    with pytest.raises(ValueError, match="not contiguous"):
        b.merge_slice(interval_slice([bucket], [(0, k3, 30, 3, 3)], [2], [3]))
    res = M.merge_slice(
        b.state, interval_slice([bucket], [(0, k3, 30, 3, 3)], [2], [3]), kill_budget=4
    )
    assert bool(res.need_ctx_gap) and not bool(res.ok)


def test_interval_removal_propagates():
    """A delta-interval can also carry a remove: the interval covers the
    removed dot but the slice does not contain it alive."""
    b = BinnedKernelMap(11)
    bucket = 1
    k1 = 1
    b.merge_slice(interval_slice([bucket], [(0, k1, 10, 1, 1)], [0], [1]))
    assert b.read() == {k1: 10}
    # writer removed k1: interval (0, 1] re-claims dot 1, ships nothing
    b.merge_slice(interval_slice([bucket], [], [0], [1]))
    assert b.read() == {}


def test_empty_interval_claims_nothing():
    """An idle writer's row ships lo == hi > 0 (an empty interval): it
    must not read as a (0, hi] state-form claim — older unshipped dots
    survive and the local context must not advance."""
    b = BinnedKernelMap(11)
    bucket = 1
    k1 = 1
    b.merge_slice(interval_slice([bucket], [(0, k1, 10, 1, 1)], [0], [1]))
    ctx_before = np.asarray(b.state.ctx_max).copy()
    # empty claim (1, 1]: nothing shipped, nothing claimed
    b.merge_slice(interval_slice([bucket], [], [1], [1]))
    assert b.read() == {k1: 10}
    assert np.array_equal(np.asarray(b.state.ctx_max), ctx_before)


def test_interval_merge_is_idempotent():
    b = BinnedKernelMap(11)
    bucket = 1
    sl = interval_slice([bucket], [(0, 1, 10, 1, 1)], [0], [1])
    b.merge_slice(sl)
    r1 = b.read()
    leaf1 = np.asarray(b.state.leaf).copy()
    b.merge_slice(sl)
    assert b.read() == r1
    assert np.array_equal(np.asarray(b.state.leaf), leaf1)
