"""Hierarchical topology-aware anti-entropy (ISSUE 15).

Covers the deterministic spanning-tree derivation (runtime/treesync.py),
tree-mode replicas (links-only monitors, relay coalesce-and-re-emit,
failure degrade), the parity contracts — seeded tree-vs-flat canonical
parity on BOTH store backends, raw bit-for-bit parity between coalesced
and per-message relay handling (state, WAL bytes, full wire streams,
ack streams) — the mid-group ``CtxGapError`` repair at a relay, parent
crash / WAL-recovery chaos with partitions, the FleetFrameMsg relay
rewrite + renegotiated-down unbundle paths, and the fleet tier-0
integration.
"""

import dataclasses
import pickle
from pathlib import Path

import numpy as np
import pytest

from delta_crdt_ex_tpu.api import start_fleet, start_link
from delta_crdt_ex_tpu.models.binned import BinnedStore
from delta_crdt_ex_tpu.runtime import sync as sync_proto, treesync
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from delta_crdt_ex_tpu.runtime.tcp_transport import TcpTransport
from tests.test_ingest_coalesce import keys_for_buckets

_COLS = tuple(f.name for f in dataclasses.fields(BinnedStore))


def assert_state_bit_equal(s1, s2, ctx=""):
    for c in _COLS:
        assert np.array_equal(
            np.asarray(getattr(s1, c)), np.asarray(getattr(s2, c))
        ), (ctx, c)


def mk_universe(n, *, tree, transport=None, clock=None, names=None, **opts):
    transport = transport or LocalTransport()
    clock = clock or LogicalClock()
    opts.setdefault("capacity", 256)
    opts.setdefault("tree_depth", 6)
    opts.setdefault("sync_timeout", 120.0)
    fanout = opts.pop("tree_fanout", 2)
    reps = []
    for i in range(n):
        reps.append(
            start_link(
                threaded=False,
                transport=transport,
                clock=clock,
                name=(names[i] if names else f"tr{i}"),
                node_id=i + 1,
                tree_gossip=tree,
                tree_fanout=fanout,
                **opts,
            )
        )
    for r in reps:
        r.set_neighbours([x.addr for x in reps])
    return transport, reps


def drive_round(reps):
    """One deterministic global round: every replica ticks its sync,
    then messages deliver to quiescence (relay cascades included —
    process_pending flushes pending re-emissions at the end of each
    drain pass)."""
    for r in reps:
        r.sync_to_all()
    for _ in range(500):
        if not sum(r.process_pending() for r in reps):
            return
    raise AssertionError("universe did not quiesce")


def drive_to_convergence(reps, rounds=12):
    for _ in range(rounds):
        drive_round(reps)


# ----------------------------------------------------------------------
# derivation


def test_derive_tree_deterministic_and_total():
    members = [f"m{i}" for i in range(37)]
    t1 = treesync.derive_tree(members, fanout=4, seed=7)
    t2 = treesync.derive_tree(list(reversed(members)), fanout=4, seed=7)
    assert t1 == t2  # member order is irrelevant
    assert t1.epoch == t2.epoch
    # every member appears exactly once, parent/children agree
    seen = set()
    for m in t1.members:
        seen.add(m)
        p = t1.parent.get(m)
        if p is None:
            assert m == t1.root
        else:
            assert m in t1.children[p]
    assert seen == set(members)
    # fanout bound holds for relay-tree (ungrouped) nodes
    for _p, kids in t1.children.items():
        assert len(kids) <= 4
    # depth ~ log_4(37)
    assert 2 <= t1.depth <= 4
    # a different seed reshuffles the root (overwhelmingly likely)
    t3 = treesync.derive_tree(members, fanout=4, seed=8)
    assert t3.epoch != t1.epoch


def test_derive_tree_down_members_excluded_deterministically():
    members = [f"m{i}" for i in range(16)]
    base = treesync.derive_tree(members, fanout=4, seed=0)
    down = {base.root}
    t1 = treesync.derive_tree(members, fanout=4, seed=0, down=down)
    t2 = treesync.derive_tree(members, fanout=4, seed=0, down=set(down))
    assert t1 == t2
    assert base.root not in t1.members
    assert t1.root != base.root


def test_derive_tree_groups_cluster_under_one_captain():
    members = [f"m{i}" for i in range(12)]
    group = {m: ("g", int(m[1:]) // 4) for m in members}  # 3 groups of 4
    t = treesync.derive_tree(
        members, fanout=2, seed=3, group_key=lambda m: group[m]
    )
    # each group's non-captain members hang directly off the captain
    for gk in {("g", 0), ("g", 1), ("g", 2)}:
        g_members = [m for m in members if group[m] == gk]
        caps = [m for m in g_members if t.parent.get(m) not in g_members]
        assert len(caps) == 1  # one captain per group
        cap = caps[0]
        for m in g_members:
            if m != cap:
                assert t.parent[m] == cap
                assert t.tier[m] == t.tier[cap] + 1


def test_too_damaged_thresholds():
    assert treesync.too_damaged(1, 0, 0.25)  # alone: flat is the tree
    assert not treesync.too_damaged(16, 4, 0.25)
    assert treesync.too_damaged(16, 5, 0.25)


def test_group_of_endpoint_and_owner():
    t = LocalTransport()

    class _Owner:
        tree_group = None
        device = None

    o = _Owner()
    t.register("a", o)
    assert treesync.group_of(t, "a") is None  # singleton
    o.tree_group = ("fleet", "xyz")
    assert treesync.group_of(t, "a") == ("group", ("fleet", "xyz"))
    # TCP canonical tuples group by endpoint without any owner in sight
    addr = ("peer", ("10.0.0.1", 4321))
    assert treesync.group_of(t, addr) == ("endpoint", ("10.0.0.1", 4321))


# ----------------------------------------------------------------------
# tree-mode sync behaviour


def test_tree_mode_monitors_only_links_and_converges():
    _t, reps = mk_universe(10, tree=True)
    drive_round(reps)
    topo = reps[0]._tree_refresh()
    for r in reps:
        mine = r._tree_refresh()
        assert mine.epoch == topo.epoch
        assert r._monitors <= set(mine.links(r.addr))
        assert len(r._monitors) <= 1 + max(2, r.tree_fanout)
    # a leaf write floods the whole tree through relay re-emissions
    leaf = next(r for r in reps if topo.role(r.addr) == "leaf")
    leaf.mutate("add", ["k", "v"])
    drive_round(reps)
    assert all(r.read().get("k") == "v" for r in reps)
    relays = [r for r in reps if topo.role(r.addr) in ("relay", "root")]
    assert any(r.stats()["tree"]["reemits"] > 0 for r in relays)
    # health reads the LINKS, not the whole membership
    h = leaf.health()
    assert h["ok"] and h["neighbours"] == len(topo.links(leaf.addr))


def test_relay_coalesces_children_fan_in():
    """A relay with several children merging one drain pass's inbound
    deltas re-emits ONE merged slice per link, not one per child."""
    t, reps = mk_universe(10, tree=True, tree_fanout=8)
    drive_round(reps)
    topo = reps[0]._tree_refresh()
    root = next(r for r in reps if r.addr == topo.root)
    kids = topo.children[root.addr]
    assert len(kids) >= 3
    by_addr = {r.addr: r for r in reps}
    # several children write, push to the root in one drain window
    for i, k in enumerate(kids[:3]):
        by_addr[k].mutate("add", [f"k{i}", i])
        by_addr[k].sync_to_all()
    root.process_pending()
    st = root.stats()["tree"]
    assert st["reemits"] >= 1
    assert st["msgs_folded"] >= 3
    # the merged re-emission folded >1 inbound message into one slice
    assert max(st["depth_hist"]) >= 2 or st["folds_per_reemit"] > 1.0


def test_stats_tree_absent_when_disabled():
    _t, reps = mk_universe(2, tree=False)
    assert "tree" not in reps[0].stats()


@pytest.mark.parametrize("store", ["binned", "hash"])
def test_seeded_tree_vs_flat_canonical_parity(store):
    """Seeded randomized scripts: a tree universe and a flat universe
    fed the identical op stream converge to the SAME canonical state
    (sorted winners + gid-keyed causal context) bit-for-bit, on both
    store backends."""
    rng = np.random.default_rng(1234)
    script = [
        [
            (
                int(rng.integers(0, 8)),
                "add" if rng.random() < 0.7 else "remove",
                int(rng.integers(0, 24)),
                int(rng.integers(0, 100)),
            )
            for _ in range(10)
        ]
        for _ in range(3)
    ]
    finals = {}
    for tag, tree in (("tree", True), ("flat", False)):
        _t, reps = mk_universe(
            8, tree=tree, names=[f"p{i}" for i in range(8)], store=store
        )
        for ops in script:
            for w, f, k, v in ops:
                reps[w].mutate(f, [k, v] if f == "add" else [k])
            drive_round(reps)
        drive_to_convergence(reps)
        finals[tag] = reps
    for i in range(8):
        a, b = finals["tree"][i], finals["flat"][i]
        assert a.read() == b.read(), i
        assert a.canonical_state_bytes() == b.canonical_state_bytes(), i


class RecordingTransport(LocalTransport):
    """LocalTransport recording every successful send's pickled bytes
    per destination — the full wire stream, plus the ack stream."""

    def __init__(self):
        super().__init__()
        self.wire: dict = {}
        self.acks: dict = {}

    def send(self, addr, msg):
        ok = super().send(addr, msg)
        if ok:
            self.wire.setdefault(addr, []).append(
                pickle.dumps(msg, protocol=4)
            )
            if isinstance(msg, sync_proto.AckMsg):
                self.acks.setdefault(addr, []).append(msg.clear_addr)
        return ok


def test_relay_coalescing_bit_parity_vs_per_message(tmp_path):
    """The relay's grouped ingest + coalesced re-emission must be
    OBSERVABLY IDENTICAL to per-message handling: same final state bits,
    same WAL bytes, same full wire streams (every pickled message to
    every destination), same ack streams."""
    rng = np.random.default_rng(7)
    script = [
        [
            (
                int(rng.integers(0, 6)),
                "add" if rng.random() < 0.75 else "remove",
                int(rng.integers(0, 16)),
                int(rng.integers(0, 50)),
            )
            for _ in range(8)
        ]
        for _ in range(3)
    ]
    runs = {}
    for tag, coalesce in (("coal", True), ("seq", False)):
        transport = RecordingTransport()
        clock = LogicalClock()
        wal = tmp_path / tag
        reps = []
        for i in range(6):
            reps.append(
                start_link(
                    threaded=False,
                    transport=transport,
                    clock=clock,
                    name=f"w{i}",
                    node_id=i + 1,
                    tree_gossip=True,
                    tree_fanout=2,
                    capacity=256,
                    tree_depth=6,
                    sync_timeout=120.0,
                    ingress_coalesce=coalesce,
                    wal_dir=str(wal),
                    fsync_mode="none",
                )
            )
        for r in reps:
            r.set_neighbours([x.addr for x in reps])
        for ops in script:
            for w, f, k, v in ops:
                reps[w].mutate(f, [k, v] if f == "add" else [k])
            drive_round(reps)
        drive_to_convergence(reps, rounds=4)
        runs[tag] = (transport, reps)

    tc, rc = runs["coal"]
    ts, rs = runs["seq"]
    for i in range(6):
        assert_state_bit_equal(rc[i].state, rs[i].state, i)
        assert rc[i]._seq == rs[i]._seq, i
        wal_c = b"".join(
            Path(p).read_bytes() for p in sorted(rc[i]._wal.segment_paths())
        )
        wal_s = b"".join(
            Path(p).read_bytes() for p in sorted(rs[i]._wal.segment_paths())
        )
        assert wal_c == wal_s, f"WAL bytes diverged for member {i}"
    assert tc.acks == ts.acks
    assert set(tc.wire) == set(ts.wire)
    for dst in tc.wire:
        assert tc.wire[dst] == ts.wire[dst], f"wire stream diverged to {dst}"


def test_gap_repair_at_relay_mid_group():
    """A lost eager push leaves the NEXT one non-contiguous at the
    relay: the grouped ingest partitions, the gapped sender replays solo
    and answers the ``GetDiffMsg`` repair, and the relay still re-emits
    the healed rows onward — convergence end-to-end."""
    t, reps = mk_universe(8, tree=True, tree_fanout=8)
    drive_round(reps)
    topo = reps[0]._tree_refresh()
    root = next(r for r in reps if r.addr == topo.root)
    by_addr = {r.addr: r for r in reps}
    kids = [by_addr[k] for k in topo.children[root.addr]]
    assert len(kids) >= 2
    victim, clean = kids[0], kids[1]
    # two distinct keys in ONE bucket: the second add mints the bucket's
    # next counter without killing anything (no full-row push rides
    # along to mask the gap)
    k_a, k_b = keys_for_buckets(0, 1, 2, mask=63)
    (k_c,) = keys_for_buckets(1, 2, 1, mask=63)
    # victim's first push is LOST (drained and dropped at the root)
    victim.mutate("add", [k_a, 1])
    victim.sync_to_all()
    dropped = [
        m
        for m in t.drain(root.addr)
        if not (isinstance(m, sync_proto.EntriesMsg) and m.frm == victim.addr)
    ]
    for m in dropped:
        t.send(root.addr, m)
    # second round: the same bucket's next interval push is now
    # non-contiguous at the root (the gap shape); a clean sibling's
    # push rides the same entries run (the mid-group shape)
    victim.mutate("add", [k_b, 2])
    clean.mutate("add", [k_c, 3])
    victim.sync_to_all()
    clean.sync_to_all()
    root.process_pending()
    ing = root.stats()["ingress"]
    assert ing["gap_fallbacks"] + ing["gap_partitions"] >= 1
    drive_to_convergence(reps)
    for r in reps:
        got = r.read()
        assert got.get(k_a) == 1 and got.get(k_b) == 2, r.name
        assert got.get(k_c) == 3, r.name


def test_parent_crash_reparents_deterministically():
    t, reps = mk_universe(10, tree=True)
    drive_round(reps)
    topo = reps[0]._tree_refresh()
    by_addr = {r.addr: r for r in reps}
    # crash a mid-tree relay (not the root): its children must re-parent
    relay_addr = next(
        a
        for a, kids in topo.children.items()
        if a != topo.root and kids
    )
    relay = by_addr[relay_addr]
    survivors = [r for r in reps if r is not relay]
    relay.crash()
    # deterministic re-derive: every survivor that observes the death
    # lands on the same reduced tree; a write still floods everyone
    survivors[0].mutate("add", ["after-crash", 9])
    drive_to_convergence(survivors)
    assert all(r.read().get("after-crash") == 9 for r in survivors)
    # every survivor that OBSERVED the death (the dead relay's links)
    # re-derived onto ONE shared reduced tree excluding it; members
    # whose links never touched the dead relay may keep the old view —
    # their edges stay valid, and the reverse-link machinery keeps
    # mixed-epoch data flow bidirectional (what the coverage assert
    # above just proved)
    observer_epochs = {
        r._tree_refresh().epoch for r in survivors if r._tree_down
    }
    assert len(observer_epochs) == 1
    for r in survivors:
        if r._tree_down:
            assert relay_addr not in r._tree_refresh().members
    # at least one stale-view member synced back via a reverse edge OR
    # every member observed the death (tiny trees) — either way the
    # union of view-edges stayed strongly connected
    assert any(r._tree_reverse for r in survivors) or all(
        r._tree_down for r in survivors
    )


def test_degrade_to_flat_past_threshold_and_recover():
    _t, reps = mk_universe(4, tree=True, tree_degrade_ratio=0.2)
    drive_round(reps)
    dead = reps[-1]
    dead.crash()
    survivors = reps[:-1]
    survivors[0].mutate("add", ["deg", 1])
    drive_to_convergence(survivors)
    # 1/4 down > 0.2: everyone who observed it degrades to flat gossip
    assert all(r.read().get("deg") == 1 for r in survivors)
    degraded = [r.stats()["tree"]["degraded"] for r in survivors]
    assert any(degraded)
    for r in survivors:
        if r.stats()["tree"]["degraded"]:
            assert r.stats()["tree"]["role"] == "flat"
    # membership shrinking to the survivors recovers the tree
    for r in survivors:
        r.set_neighbours([x.addr for x in survivors])
    drive_round(survivors)
    assert all(not r.stats()["tree"]["degraded"] for r in survivors)


class PartitionedTransport(LocalTransport):
    """Chaos transport: sends whose (frm → to) edge crosses the active
    partition are DROPPED (returns False, the unreachable-peer shape).
    Messages without a ``frm`` field (acks, Down) pass — partition
    chaos targets the data plane; convergence must hold regardless."""

    def __init__(self):
        super().__init__()
        self.groups: "list[set] | None" = None

    def _blocked(self, frm, to) -> bool:
        if self.groups is None or frm is None:
            return False
        gf = next((i for i, g in enumerate(self.groups) if frm in g), None)
        gt = next((i for i, g in enumerate(self.groups) if to in g), None)
        return gf is not None and gt is not None and gf != gt

    def send(self, addr, msg):
        if self._blocked(getattr(msg, "frm", None), addr):
            return False
        return super().send(addr, msg)


@pytest.mark.parametrize("store", ["binned", "hash"])
def test_chaos_partition_relay_crash_wal_recovery_parity(tmp_path, store):
    """The ISSUE 15 chaos gate: seeded ops under a network partition
    plus a relay crash + WAL recovery still converge, and the final
    state is canonically BIT-IDENTICAL to a flat-gossip universe fed
    the same ops with no faults at all."""
    rng = np.random.default_rng(99)
    script = [
        [
            (
                int(rng.integers(0, 6)),
                "add" if rng.random() < 0.7 else "remove",
                int(rng.integers(0, 20)),
                int(rng.integers(0, 90)),
            )
            for _ in range(8)
        ]
        for _ in range(4)
    ]

    # -- the chaos (tree) universe ------------------------------------
    transport = PartitionedTransport()
    clock = LogicalClock()
    reps = []
    for i in range(6):
        reps.append(
            start_link(
                threaded=False,
                transport=transport,
                clock=clock,
                name=f"c{i}",
                node_id=i + 1,
                store=store,
                tree_gossip=True,
                tree_fanout=2,
                capacity=256,
                tree_depth=6,
                sync_timeout=120.0,
                wal_dir=str(tmp_path / f"c{i}"),
                fsync_mode="none",
            )
        )
    for r in reps:
        r.set_neighbours([x.addr for x in reps])
    drive_round(reps)

    addrs = [r.addr for r in reps]
    for rnd, ops in enumerate(script):
        for w, f, k, v in ops:
            reps[w].mutate(f, [k, v] if f == "add" else [k])
        if rnd == 1:
            # partition the universe down the middle for a round
            transport.groups = [set(addrs[:3]), set(addrs[3:])]
        elif rnd == 2:
            transport.groups = None  # heal
        drive_round(reps)

    # crash a relay (or the root) and recover it from its WAL
    topo = next(
        t for t in (r._tree_refresh() for r in reps) if t is not None
    )
    relay_addr = next(a for a in topo.children if topo.children[a])
    idx = addrs.index(relay_addr)
    name = reps[idx].name
    reps[idx].crash()
    reps[idx] = start_link(
        threaded=False,
        transport=transport,
        clock=clock,
        name=name,
        store=store,
        tree_gossip=True,
        tree_fanout=2,
        capacity=256,
        tree_depth=6,
        sync_timeout=120.0,
        wal_dir=str(tmp_path / name),
        fsync_mode="none",
    )
    reps[idx].set_neighbours([x.addr for x in reps])
    for r in reps:
        r.set_neighbours([x.addr for x in reps])
    drive_to_convergence(reps)

    # -- the fault-free flat twin -------------------------------------
    _t2, flat = mk_universe(
        6, tree=False, names=[f"f{i}" for i in range(6)], store=store
    )
    for ops in script:
        for w, f, k, v in ops:
            flat[w].mutate(f, [k, v] if f == "add" else [k])
        drive_round(flat)
    drive_to_convergence(flat)

    want = flat[0].read()
    for r in reps:
        assert r.read() == want, r.name
    assert (
        reps[0].canonical_state_bytes() == flat[0].canonical_state_bytes()
    )
    for r in reps[1:]:
        assert r.canonical_state_bytes() == reps[0].canonical_state_bytes()


# ----------------------------------------------------------------------
# FleetFrameMsg relay rewrite


class _FramingStub:
    """Transport stub with the fleet-frame surface: fleet_sink maps
    remote names to endpoints, send_fleet_frame records envelopes (or
    refuses — the renegotiated-down path)."""

    def __init__(self, sinks, accept=True):
        self.sinks = sinks
        self.accept = accept
        self.frames: list = []
        self.sent: list = []

    def fleet_sink(self, addr):
        return self.sinks.get(addr)

    def send_fleet_frame(self, endpoint, entries):
        if not self.accept:
            for to, m in entries:
                self.send(to, m)
            return False
        self.frames.append((endpoint, list(entries)))
        return True

    def send(self, addr, msg):
        self.sent.append((addr, msg))
        return True

    # Replica surface the ctor touches
    def canonical_addr(self, name):
        return name

    def register(self, addr, owner):
        pass

    def unregister(self, addr):
        pass

    def monitor(self, w, t):
        return True

    def demonitor(self, w, t):
        pass

    def alive(self, a):
        return True


def test_fleet_frame_relay_rewrite_groups_per_next_hop():
    """A relayed envelope's forwarded entries regroup into ONE rewritten
    frame per next-hop endpoint — entries rewritten, inner messages
    untouched — instead of N per-member sends."""
    stub = _FramingStub(
        {"b1": ("hostB", 1), "b2": ("hostB", 1), "c1": ("hostC", 2)}
    )
    rep = start_link(
        threaded=False, transport=stub, name="relay0", capacity=64,
        tree_depth=6,
    )
    inner = [object(), object(), object()]
    fm = sync_proto.FleetFrameMsg(
        frm="origin",
        entries=[("b1", inner[0]), ("c1", inner[1]), ("b2", inner[2])],
    )
    rep._handle_fleet_frame(fm)
    assert len(stub.frames) == 2
    frames = dict(stub.frames)
    assert frames[("hostB", 1)] == [("b1", inner[0]), ("b2", inner[2])]
    assert frames[("hostC", 2)] == [("c1", inner[1])]
    assert stub.sent == []  # nothing fell back per-member


def test_fleet_frame_relay_unbundles_for_renegotiated_down_peer():
    stub = _FramingStub({"b1": ("hostB", 1), "b2": ("hostB", 1)}, accept=False)
    rep = start_link(
        threaded=False, transport=stub, name="relay1", capacity=64,
        tree_depth=6,
    )
    inner = [object(), object()]
    fm = sync_proto.FleetFrameMsg(
        frm="origin", entries=[("b1", inner[0]), ("b2", inner[1])]
    )
    rep._handle_fleet_frame(fm)
    assert stub.frames == []
    assert stub.sent == [("b1", inner[0]), ("b2", inner[1])]


def test_tcp_deliver_fleet_frame_rewrites_per_endpoint(monkeypatch):
    """The TCP receive path's envelope fan-out: local entries deliver
    to mailboxes, remote ones re-frame per next hop."""
    t = TcpTransport(port=0)
    try:
        class _Sink:
            pass

        local = _Sink()
        t.register("loc", local)
        sinks = {("x", ("h", 9)): ("h", 9)}
        sent_frames = []
        monkeypatch.setattr(
            t, "fleet_sink", lambda a: ("h", 9) if a == ("x", ("h", 9)) else None
        )
        monkeypatch.setattr(
            t,
            "send_fleet_frame",
            lambda ep, entries: sent_frames.append((ep, list(entries))) or True,
        )
        fm = sync_proto.FleetFrameMsg(
            frm=("o", ("o", 1)),
            entries=[("loc", "m1"), (("x", ("h", 9)), "m2"), ("loc", "m3")],
        )
        t._deliver_fleet_frame(fm)
        assert t.drain("loc") == ["m1", "m3"]
        assert sent_frames == [(("h", 9), [(("x", ("h", 9)), "m2")])]
        assert sinks  # silence lint
    finally:
        t.close()


# ----------------------------------------------------------------------
# fleet tier-0 integration


def test_fleet_members_share_tier0_group_and_converge_with_external():
    transport = LocalTransport()
    clock = LogicalClock()
    fleet = start_fleet(
        5,
        threaded=False,
        transport=transport,
        clock=clock,
        names=[f"fm{i}" for i in range(5)],
        tree_gossip=True,
        tree_fanout=2,
        capacity=256,
        tree_depth=6,
        sync_timeout=120.0,
    )
    try:
        groups = {r.tree_group for r in fleet.replicas}
        assert len(groups) == 1 and next(iter(groups)) is not None
        ext = start_link(
            threaded=False,
            transport=transport,
            clock=clock,
            name="external",
            node_id=99,
            tree_gossip=True,
            tree_fanout=2,
            capacity=256,
            tree_depth=6,
            sync_timeout=120.0,
        )
        members = [r.addr for r in fleet.replicas] + [ext.addr]
        for r in fleet.replicas:
            r.set_neighbours(members)
        ext.set_neighbours(members)
        topo = ext._tree_refresh()
        # the fleet is ONE bottom-tier cluster: exactly one fleet member
        # (the captain) has links outside the fleet
        fleet_addrs = {r.addr for r in fleet.replicas}
        outward = [
            a
            for a in fleet_addrs
            if any(l not in fleet_addrs for l in topo.links(a))
        ]
        assert len(outward) == 1
        # a write at the external replica reaches every fleet member
        ext.mutate("add", ["from-outside", 42])
        for _ in range(12):
            ext.sync_to_all()
            ext.process_pending()
            fleet.run_duties()
            fleet.drain()
            if all(
                r.read().get("from-outside") == 42 for r in fleet.replicas
            ):
                break
        assert all(
            r.read().get("from-outside") == 42 for r in fleet.replicas
        )
        # and a fleet write reaches the external replica through the
        # captain's relay re-emission
        fleet.replicas[3].mutate("add", ["from-inside", 7])
        for _ in range(12):
            fleet.run_duties()
            fleet.drain()
            ext.sync_to_all()
            ext.process_pending()
            if ext.read().get("from-inside") == 7:
                break
        assert ext.read().get("from-inside") == 7
        ext.stop()
    finally:
        fleet.stop()
