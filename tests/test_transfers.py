"""The device↔host transfer ledger (ISSUE 17, runtime half).

crdtlint TRANSFER001 forces every hot-module crossing through
``utils/transfers`` sites; these tests pin the ledger the bench gates
and ``stats()`` surfaces lean on: the name-collision guard (two sites
silently merging counts would corrupt every ledger delta), the
count/byte accounting and delta semantics, deterministic per-round
crossing counts over a real gossip round on BOTH store backends (the
``--ingest``/``--tree`` bench-gate property at test scale), and the
tentpole's retirement claim — the narrow mesh delivery plane performs
ZERO audited get-crossings per tick (device-resident delivery), where
the legacy padded plane pays a whole-buffer ``device_get`` every
exchange.
"""

import numpy as np
import pytest

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.fleet import Fleet
from delta_crdt_ex_tpu.runtime.transport import LocalTransport
from delta_crdt_ex_tpu.utils import transfers
from delta_crdt_ex_tpu.utils.devices import fleet_mesh


# ---------------------------------------------------------------------------
# ledger primitives


def test_register_same_origin_idempotent():
    """Re-evaluating one register statement (module reload) returns the
    same handle — registration is keyed on (label, call site)."""
    handles = []
    for _ in range(2):
        handles.append(transfers.register("testonly.reload_probe"))
    assert handles[0] is handles[1]


def test_register_collision_from_different_call_site_raises():
    """The name-collision guard: the SAME label from a DIFFERENT call
    site must raise — two sites silently merging their tallies would
    blind every bench gate that diffs ledger snapshots."""
    transfers.register("testonly.collision_probe")
    with pytest.raises(ValueError, match="already registered"):
        transfers.register("testonly.collision_probe")


def test_register_rejects_non_string_labels():
    with pytest.raises(ValueError, match="non-empty str"):
        transfers.register("")
    with pytest.raises(ValueError, match="non-empty str"):
        transfers.register(None)


def test_site_accounting_and_delta_semantics():
    """get/put/note all advance (count, bytes); delta() omits quiet
    sites and snapshot() is insertion-stable sorted by label."""
    site = transfers.register("testonly.accounting_probe")
    before = transfers.snapshot()
    a = np.arange(16, dtype=np.int64)  # 128 bytes
    dev = site.put(a)
    back = site.get(dev)
    assert np.array_equal(back, a)
    site.note(7, crossings=2)
    after = transfers.snapshot()
    d = transfers.delta(before, after)
    assert d["testonly.accounting_probe"] == {"count": 4, "bytes": 263}
    # every other site was quiet: delta omits it
    assert set(d) == {"testonly.accounting_probe"}
    assert list(after) == sorted(after)
    # pytree accounting: a dict counts one crossing, summed leaf bytes
    pre = transfers.snapshot()
    site.get({"x": np.zeros(4, np.int64), "y": np.zeros(2, np.int64)})
    d = transfers.delta(pre, transfers.snapshot())
    assert d["testonly.accounting_probe"] == {"count": 1, "bytes": 48}


def test_audited_helper_forms_count_through_the_site():
    site = transfers.register("testonly.helper_probe")
    pre = transfers.snapshot()
    dev = transfers.audited_put(np.ones(4, np.float32), site)
    transfers.audited_get(dev, site)
    d = transfers.delta(pre, transfers.snapshot())
    assert d["testonly.helper_probe"]["count"] == 2


def test_varz_envelope_shape():
    v = transfers.varz()
    assert v["kind"] == "transfers"
    assert "testonly.accounting_probe" in v["stats"]


# ---------------------------------------------------------------------------
# a known gossip round crosses deterministically, both store backends


def _mk(transport, store, name, **kw):
    kw.setdefault("capacity", 256)
    kw.setdefault("tree_depth", 4)
    kw.setdefault("sync_timeout", 600.0)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=LogicalClock(),
        store=store, name=name, **kw,
    )


def _pump(replicas, iters=4):
    for _ in range(iters):
        for r in replicas:
            r.process_pending()


@pytest.mark.parametrize("store", ["binned", "hash"])
def test_gossip_round_crossing_counts_steady(store):
    """The bench-gate property at test scale: identical gossip rounds
    cross the device boundary an identical number of times per site
    (counts pinned; bytes may drift with slice tiers, digest-ladder
    cache fills are demand-driven and excluded — the ``--tree`` gate's
    ``demand_ok`` convention)."""
    transport = LocalTransport()
    w = _mk(transport, store, f"trw_{store}", node_id=11)
    p = _mk(transport, store, f"trp_{store}", node_id=12)
    w.set_neighbours([p])

    def round_delta(rnd):
        pre = transfers.snapshot()
        for j in range(4):
            w.mutate("add", [1000 * rnd + j, rnd])
        w.sync_to_all()
        _pump([w, p])
        return transfers.delta(pre, transfers.snapshot())

    round_delta(0)  # warmup: capacity placement, first-touch tiers
    d1, d2 = round_delta(1), round_delta(2)
    pin = lambda d: {
        s: v["count"] for s, v in d.items() if s != "replica.digest_levels"
    }
    assert pin(d1) == pin(d2), (d1, d2)
    # the round really moved data through audited sites, and the local
    # mutation plus the receiver's ingest both show up
    assert "replica.apply_counts" in d1
    assert sum(v["bytes"] for v in d1.values()) > 0
    assert all(v["count"] > 0 for v in d1.values())
    w.stop()
    p.stop()


# ---------------------------------------------------------------------------
# the tentpole claim: narrow mesh delivery is device-resident


def _mesh_tick_delta(narrow, tag):
    """One steady intra-mesh gossip tick's ledger delta, meshplane
    sites only."""
    transport = LocalTransport()
    n = 4
    reps = [
        _mk(transport, "binned", f"trm{tag}{i}", node_id=100 + i)
        for i in range(n)
    ]
    for i in range(n):
        reps[i].set_neighbours([reps[(i + 1) % n]])
    fleet = Fleet(reps, mesh=fleet_mesh(2), mesh_narrow=narrow)

    def tick(rnd):
        for i in range(n):
            reps[i].mutate("add", [rnd * 100 + i, i])
        pre = transfers.snapshot()
        fleet.sync_tick()
        fleet.drain()
        for r in reps:
            r._outstanding.clear()
            r._sync_open_seq.clear()
        return transfers.delta(pre, transfers.snapshot())

    tick(0)  # warmup
    d = tick(1)
    for r in reps:
        r.stop()
    return {s: v for s, v in d.items() if s.startswith("meshplane.")}


def test_narrow_mesh_plane_has_zero_get_crossings():
    """Narrow (default) delivery: ONE dense put ships the whole tick
    and receivers read device-resident rows — no ``deliver`` site, no
    get-crossing at all. The legacy padded plane pays both a ship put
    AND a whole-buffer readback; that contrast is the retirement
    evidence the ``--mesh`` bench artifact records."""
    narrow = _mesh_tick_delta(True, "n")
    assert set(narrow) == {"meshplane.ship_dense"}, narrow
    assert narrow["meshplane.ship_dense"]["count"] >= 1
    legacy = _mesh_tick_delta(False, "l")
    assert set(legacy) == {
        "meshplane.ship_padded", "meshplane.deliver_padded",
    }, legacy
    # the readback the narrow plane retired
    assert legacy["meshplane.deliver_padded"]["count"] >= 1
