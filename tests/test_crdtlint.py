"""crdtlint: golden fixtures per rule family + end-to-end over the real
package.

The fixture tests build throwaway mini-packages on disk and assert each
rule family fires on its positive snippet and stays silent on the
negative one. The end-to-end tests run the real CLI over
``delta_crdt_ex_tpu`` (must be clean: zero unsuppressed findings) and —
via the engine's source overlay — re-lint mutated copies of real
modules to prove the pass actually *detects* the bug classes it claims
to (every ``with self._lock:`` deletion in replica.py, an unannotated
``.item()`` in ops/join.py), not just that the tree happens to be
quiet.

Pure-stdlib under test: no jax/numpy import happens in the linter, so
these tests are cheap enough for tier-1.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.crdtlint.engine import (  # noqa: E402
    Finding,
    load_baseline,
    run_lint,
    write_baseline,
)

PKG = "delta_crdt_ex_tpu"


def make_pkg(root: Path, modules: dict[str, str]) -> Path:
    """Write a mini-package; keys are slash paths under the package dir
    (e.g. "ops/kern.py"), values module source."""
    pkg = root / "fixpkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    for rel, src in modules.items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        init = path.parent / "__init__.py"
        if not init.exists():
            init.write_text("")
        path.write_text(textwrap.dedent(src))
    return pkg


def lint(pkg: Path, **kw) -> list[Finding]:
    new, _baselined, _allowed = run_lint([pkg], **kw)
    return new


def rules_of(findings: list[Finding]) -> set[str]:
    return {f.rule for f in findings}


# ----------------------------------------------------------------------
# LOCK001 — lock discipline


LOCKED_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self._stop = threading.Event()

        def put(self, x):
            with self._lock:
                self._items.append(x)

        def {body}
"""


def test_lock_unguarded_public_read_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {"box.py": LOCKED_CLASS.format(body="size(self):\n            return len(self._items)")},
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK001"}
    assert "_items" in found[0].message and "size" in found[0].message


def test_lock_guarded_access_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": LOCKED_CLASS.format(
                body=(
                    "size(self):\n"
                    "            with self._lock:\n"
                    "                return len(self._items)"
                )
            )
        },
    )
    assert lint(pkg) == []


_HELPER_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def put(self, x):
            with self._lock:
                self._put(x)

        def _put(self, x):
            self._items.append(x)
"""


def test_lock_private_helper_inherits_caller_lock(tmp_path):
    # a private helper called only under the lock is clean; the same
    # helper reached from a lock-free public path is flagged
    pkg = make_pkg(tmp_path, {"box.py": _HELPER_CLASS})
    assert lint(pkg) == []

    dirty = _HELPER_CLASS + (
        "\n"
        "        def put_fast(self, x):\n"
        "            self._put(x)\n"
    )
    pkg2 = make_pkg(tmp_path / "b", {"box.py": dirty})
    found = lint(pkg2)
    assert rules_of(found) == {"LOCK001"}


def test_lock_thread_entry_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                    self._thread = threading.Thread(target=self._loop)

                def bump(self):
                    with self._lock:
                        self._n += 1

                def _loop(self):
                    while True:
                        print(self._n)
            """
        },
    )
    found = lint(pkg)
    # the thread-entry read is both a discipline violation (LOCK001:
    # guarded attr, unguarded path) and an actual race (RACE001: caller
    # writes, thread reads, no common lock) — both families fire
    assert rules_of(found) == {"LOCK001", "RACE001"}
    assert any(f.rule == "LOCK001" and "_loop" in f.message for f in found)


def test_lock_acquire_wrapper_recognised(tmp_path):
    # Replica's _acquire idiom: helper acquires, caller releases
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._items = []

                def _acquire(self):
                    if not self._lock.acquire(timeout=1):
                        raise TimeoutError

                def put(self, x):
                    self._acquire()
                    try:
                        self._items.append(x)
                    finally:
                        self._lock.release()
            """
        },
    )
    assert lint(pkg) == []


def test_lock_threadsafe_attrs_exempt(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import queue
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()
                    self._wake = threading.Event()
                    self._data = {}

                def put(self, x):
                    with self._lock:
                        self._data[x] = x
                        self._q.put(x)

                def poke(self):
                    self._q.put_nowait(None)
                    self._wake.set()
            """
        },
    )
    assert lint(pkg) == []


def test_lock_init_does_not_mint_guards(tmp_path):
    # attributes only ever written in __init__ are pre-publication state
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._name = "box"
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def name(self):
                    return self._name
            """
        },
    )
    assert lint(pkg) == []


# ----------------------------------------------------------------------
# SYNC001 / SYNC002 — host-sync leaks


def test_sync_item_in_jit_reachable_cross_module(tmp_path):
    # entry registered in one module, offending body in another: the
    # rule must walk the import graph, not the file it found jit() in
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            def combine(x, y):
                return x + y

            def fold(x):
                bad = combine(x, x).item()
                return bad
            """,
            "models/model.py": """
            import jax

            from fixpkg.ops import kern

            jit_fold = jax.jit(kern.fold)
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SYNC001"}
    assert found[0].path.endswith("ops/kern.py")


def test_sync_unreachable_function_not_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            def helper(x):
                return x.tolist()
            """,
        },
    )
    assert lint(pkg) == []


def test_sync_int_coercion_flagged_static_shape_exempt(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            import jax

            @jax.jit
            def fold(x):
                n = int(x.shape[0])      # static: fine
                v = int(x.sum())         # traced: host sync
                return n + v
            """,
        },
    )
    found = lint(pkg)
    assert len(found) == 1 and found[0].rule == "SYNC001"
    assert "int()" in found[0].message


def test_sync_np_asarray_and_decorated_partial_jit(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "parallel/mesh.py": """
            from functools import partial

            import jax
            import numpy as np

            @partial(jax.jit, static_argnames=("k",))
            def step(x, k=1):
                return np.asarray(x) + k
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SYNC001"}
    assert "np.asarray" in found[0].message


def test_sync_shard_map_body_reached_via_nested_def(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "parallel/mesh.py": """
            import jax
            from jax import shard_map

            @jax.jit
            def gossip(x):
                def step(local):
                    return local.block_until_ready()
                return shard_map(step, mesh=None, in_specs=None, out_specs=None)(x)
            """,
        },
    )
    assert "SYNC001" in rules_of(lint(pkg))


def test_sync_block_until_ready_in_op_module_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            import jax

            def probe(f, x):
                jax.jit(f)(x).block_until_ready()
                return f
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SYNC002"}


def test_sync_allow_comment_suppresses(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            import jax

            def probe(f, x):
                # crdtlint: allow[host-sync] probe must synchronise by design
                jax.jit(f)(x).block_until_ready()
                return f
            """,
        },
    )
    new, _baselined, allowed = run_lint([pkg])
    assert new == [] and len(allowed) == 1


def test_sync_allow_comment_does_not_bleed_to_next_line(tmp_path):
    # a trailing allow on line N must not suppress a finding on N+1
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            import jax

            def probe(f, x):
                a = jax.jit(f)(x).block_until_ready()  # crdtlint: allow[host-sync] why
                b = jax.jit(f)(x).block_until_ready()
                return a, b
            """,
        },
    )
    new, _baselined, allowed = run_lint([pkg])
    assert len(allowed) == 1 and len(new) == 1
    assert new[0].rule == "SYNC002"


def test_lock_reentrant_with_does_not_release_outer_hold(tmp_path):
    # RLock reentrancy: an inner `with self._lock:` exiting must not make
    # the rest of the outer critical section look unguarded
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        with self._lock:
                            self._items.append(x)
                        self._items.append(x)
            """
        },
    )
    assert lint(pkg) == []


def test_sync_block_until_ready_outside_op_modules_ignored(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "runtime/driver.py": """
            import jax

            def hibernate(state):
                jax.block_until_ready(state)
            """,
        },
    )
    assert lint(pkg) == []


# ----------------------------------------------------------------------
# PURE001–PURE003 — lattice-op purity


def test_purity_arg_mutation_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/join2.py": """
            def join(local, remote):
                local.ctx = remote.ctx
                return local
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"PURE001"}


def test_purity_mutator_call_flagged_at_indexer_exempt(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "models/m.py": """
            def merge_contexts(a, b):
                out = a.at[0].set(b[0])   # functional jax update: fine
                a.update(b)               # in-place: flagged
                return out
            """,
        },
    )
    found = lint(pkg)
    assert len(found) == 1 and found[0].rule == "PURE001"
    assert "update" in found[0].message


def test_purity_impure_calls_and_global(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/j.py": """
            import random
            import time

            _CACHE = {}

            def delta_of(state):
                global _CACHE
                _CACHE = {}
                return state

            def merge(a, b):
                if random.random() < 0.5:
                    return a
                return b, time.time()
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"PURE002", "PURE003"}
    assert sum(f.rule == "PURE003" for f in found) == 2


def test_purity_scope_limited_to_ops_models(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "runtime/r.py": """
            import time

            def merge(a, b):
                a.x = time.time()
                return a
            """,
        },
    )
    assert lint(pkg) == []


def test_purity_nonmatching_names_ignored(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import time

            def stamp(a):
                return time.time()
            """,
        },
    )
    assert lint(pkg) == []


# ----------------------------------------------------------------------
# DONATE001 — donation hygiene


def test_donation_reuse_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))

            def driver(state):
                out = jit_grow(state)
                return out, state.shape
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"DONATE001"}
    assert "'state'" in found[0].message


def test_donation_rebind_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))

            def driver(state):
                state = jit_grow(state)
                return state
            """,
        },
    )
    assert lint(pkg) == []


def test_donation_cross_module_call_site(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))
            """,
            "runtime/r.py": """
            from fixpkg.ops.k import jit_grow

            def driver(state):
                out = jit_grow(state)
                return out, state
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"DONATE001"}
    assert found[0].path.endswith("runtime/r.py")


def test_lock_conditional_acquire_does_not_leak_held_state(tmp_path):
    # a lock acquired in only one branch is NOT held after the join
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def maybe(self, cond, x):
                    if cond:
                        self._lock.acquire()
                    self._items.append(x)
                    if cond:
                        self._lock.release()
            """
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK001"}


def test_sync_similar_name_helper_not_flagged(tmp_path):
    # SYNC002 must match the exact name, not a substring
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            def safe_block_until_ready(x):
                return x

            def driver(x):
                return safe_block_until_ready(x)
            """,
        },
    )
    assert lint(pkg) == []


def test_donation_early_return_branch_not_flagged(tmp_path):
    # `return state` only runs when the donating branch was NOT taken
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))

            def driver(state, flag):
                if flag:
                    out = jit_grow(state)
                    return out
                return state
            """,
        },
    )
    assert lint(pkg) == []


def test_cli_select_rejects_unknown_rule(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", PKG, "--select", "SYNC01"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2 and "unknown rule" in proc.stderr


def test_donation_multiline_call_not_flagged(tmp_path):
    # the donor's own Name node on a continuation line is the donation
    # itself, not a read after the call
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))

            def driver(state):
                out = jit_grow(
                    state,
                )
                return out
            """,
        },
    )
    assert lint(pkg) == []


def test_sync_same_name_host_function_not_flagged(tmp_path):
    # reachability is keyed by node identity: an untraced host-side
    # function sharing a jit entry's name must not be flagged
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            @jax.jit
            def kernel(x):
                return x + 1

            class HostProbe:
                def kernel(self, x):
                    return x.item()
            """,
        },
    )
    assert lint(pkg) == []


# ----------------------------------------------------------------------
# baseline workflow


def test_baseline_roundtrip_and_count_semantics(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {"box.py": LOCKED_CLASS.format(body="size(self):\n            return len(self._items)")},
    )
    found = lint(pkg)
    assert len(found) == 1
    bl_path = tmp_path / "baseline.json"
    write_baseline(bl_path, found)
    data = json.loads(bl_path.read_text())
    assert data["version"] == 1 and len(data["findings"]) == 1

    # baselined finding no longer reported as new
    new, baselined, _ = run_lint([pkg], baseline=load_baseline(bl_path))
    assert new == [] and len(baselined) == 1

    # a second finding site: the baseline absorbs only what it records
    # (the size() fingerprint); the new peek() site is reported as new
    extra = LOCKED_CLASS.format(
        body=(
            "size(self):\n"
            "            return len(self._items)\n\n"
            "        def peek(self):\n"
            "            return len(self._items)"
        )
    )
    pkg2 = make_pkg(tmp_path / "b", {"box.py": extra})
    new2, baselined2, _ = run_lint([pkg2], baseline=load_baseline(bl_path))
    assert len(new2) + len(baselined2) == 2 and len(baselined2) <= 1


def test_write_baseline_with_select_preserves_other_rules(tmp_path):
    # selective rewrite must carry over accepted debt of unselected rules
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def size(self):
                    return len(self._items)

            def merge(a, b):
                a.update(b)
                return a
            """,
        },
    )
    bl = tmp_path / "bl.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", str(pkg),
         "--baseline", str(bl), "--write-baseline"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    full = load_baseline(bl)
    assert {r for (_p, r, _m) in full} == {"LOCK001", "PURE001"}
    # selective rewrite of just PURE001 must not drop the LOCK001 entry
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", str(pkg),
         "--baseline", str(bl), "--select", "PURE001", "--write-baseline"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert {r for (_p, r, _m) in load_baseline(bl)} == {"LOCK001", "PURE001"}


# ----------------------------------------------------------------------
# end-to-end over the real package


def _cli(*argv: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", *argv],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_e2e_package_is_clean():
    """The tier-1 gate: zero unsuppressed findings on the real tree."""
    proc = _cli(PKG)
    assert proc.returncode == 0, f"crdtlint found:\n{proc.stdout}{proc.stderr}"
    assert "0 finding(s)" in proc.stdout


def test_e2e_list_rules_and_bad_package():
    assert "LOCK001" in _cli("--list-rules").stdout
    assert _cli("no_such_pkg").returncode == 2


def test_e2e_every_lock_deletion_in_replica_is_caught():
    """Acceptance: deleting any single ``with self._lock:`` from
    runtime/replica.py must produce a finding."""
    rel = f"{PKG}/runtime/replica.py"
    src = (REPO_ROOT / rel).read_text()
    lines = src.splitlines(keepends=True)
    sites = [i for i, l in enumerate(lines) if l.strip() == "with self._lock:"]
    assert len(sites) >= 10, "replica.py lost its lock regions?"
    for site in sites:
        mutated = lines[:]
        indent = len(lines[site]) - len(lines[site].lstrip())
        mutated[site] = " " * indent + "if True:\n"
        new, _, _ = run_lint(
            [REPO_ROOT / PKG], overlay={rel: "".join(mutated)}
        )
        assert any(f.rule == "LOCK001" for f in new), (
            f"deleting the lock at replica.py:{site + 1} went undetected"
        )


def test_e2e_unannotated_item_in_join_is_caught():
    """Acceptance: an unannotated .item() in ops/join.py must fail."""
    rel = f"{PKG}/ops/join.py"
    src = (REPO_ROOT / rel).read_text()
    anchor = "    n_killed = jnp.sum((local.alive & ~alive1).astype(jnp.int32))"
    assert anchor in src
    mutated = src.replace(anchor, anchor + "\n    _dbg = n_killed.item()")
    new, _, _ = run_lint([REPO_ROOT / PKG], overlay={rel: mutated})
    assert any(
        f.rule == "SYNC001" and f.path.endswith("ops/join.py") for f in new
    )


def test_e2e_real_tree_clean_via_engine():
    new, _baselined, allowed = run_lint([REPO_ROOT / PKG])
    assert new == []
    # the pallas probe carries exactly one justified allow
    assert any(f.path.endswith("ops/pallas_tree.py") for f in allowed)


# ----------------------------------------------------------------------
# WIRE001–WIRE005 — wire-protocol drift (fixtures)


WIRE_PKG = {
    "runtime/sync.py": """
    import dataclasses


    @dataclasses.dataclass
    class PingMsg:
        frm: str
        seq: int


    @dataclasses.dataclass
    class PongMsg:
        frm: str
        seq: int
    """,
    "runtime/node.py": """
    from fixpkg.runtime import sync


    class Node:
        def handle(self, msg):
            if isinstance(msg, sync.PingMsg):
                pass
            elif isinstance(msg, sync.PongMsg):
                pass
    """,
}


def test_wire_complete_protocol_clean(tmp_path):
    pkg = make_pkg(tmp_path, WIRE_PKG)
    assert lint(pkg) == []


def test_wire_unhandled_message_flagged(tmp_path):
    mods = dict(WIRE_PKG)
    mods["runtime/sync.py"] += (
        "\n\n    @dataclasses.dataclass\n    class LostMsg:\n        frm: str\n"
    )
    pkg = make_pkg(tmp_path, mods)
    found = lint(pkg)
    assert rules_of(found) == {"WIRE001"}
    assert "LostMsg" in found[0].message


def test_wire_duplicate_and_ghost_arms_flagged(tmp_path):
    mods = dict(WIRE_PKG)
    mods["runtime/node.py"] = """
    from fixpkg.runtime import sync


    class Node:
        def handle(self, msg):
            if isinstance(msg, sync.PingMsg):
                pass
            elif isinstance(msg, sync.PongMsg):
                pass
            elif isinstance(msg, sync.PingMsg):
                pass
            elif isinstance(msg, sync.GhostMsg):
                pass
    """
    pkg = make_pkg(tmp_path, mods)
    found = lint(pkg)
    assert rules_of(found) == {"WIRE002"}
    msgs = " | ".join(f.message for f in found)
    assert "already handled" in msgs and "missing" in msgs


def test_wire_unserializable_field_flagged(tmp_path):
    mods = dict(WIRE_PKG)
    mods["runtime/sync.py"] = """
    import dataclasses
    import threading
    from typing import Callable


    @dataclasses.dataclass
    class PingMsg:
        frm: str
        notify: Callable


    @dataclasses.dataclass
    class PongMsg:
        frm: str
        gate: threading.Lock
    """
    pkg = make_pkg(tmp_path, mods)
    found = [f for f in lint(pkg) if f.rule == "WIRE003"]
    assert len(found) == 2
    assert "Callable" in found[0].message and "Lock" in found[1].message


def test_wire_frame_kind_sent_but_not_decoded(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "runtime/codec.py": """
            _MSG = 0
            _PING = 1
            _LOST = 2


            def _send_frame(sock, kind, payload):
                sock.sendall(bytes([kind]) + payload)


            def client(sock):
                _send_frame(sock, _MSG, b"x")
                _send_frame(sock, _PING, b"")
                _send_frame(sock, _LOST, b"?")


            def serve(sock, kind, payload):
                if kind == _MSG:
                    return payload
                elif kind == _PING:
                    return b"pong"
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"WIRE004"}
    assert "_LOST" in found[0].message


def test_wire_manifest_drift_flagged(tmp_path):
    from tools.crdtlint.rules.wire import write_manifest

    pkg = make_pkg(tmp_path, WIRE_PKG)
    manifest = tmp_path / "manifest.json"
    # recorded manifest: PingMsg with the OLD field list, PongMsg absent
    write_manifest(manifest, {
        "fixpkg": {
            "module": "fixpkg/runtime/sync.py",
            "messages": {
                "PingMsg": {"fields": [["frm", "str"]], "sha256": "stale"},
                "GoneMsg": {"fields": [], "sha256": "x"},
            },
        },
    })
    found = [
        f for f in lint(pkg, manifest=manifest) if f.rule == "WIRE005"
    ]
    msgs = " | ".join(f.message for f in found)
    assert "PingMsg" in msgs and "drifted" in msgs        # hash mismatch
    assert "PongMsg" in msgs and "not in the protocol" in msgs
    assert "GoneMsg" in msgs and "no longer defined" in msgs


def test_wire_manifest_in_sync_clean(tmp_path):
    from tools.crdtlint.engine import Project
    from tools.crdtlint.rules.wire import compute_manifest, write_manifest

    pkg = make_pkg(tmp_path, WIRE_PKG)
    manifest = tmp_path / "manifest.json"
    write_manifest(manifest, {"fixpkg": compute_manifest(Project(pkg))})
    assert lint(pkg, manifest=manifest) == []


# ----------------------------------------------------------------------
# LOCK002 / LOCK003 — lock order + blocking under lock (fixtures)


def test_lockorder_inverted_pair_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK002"}
    assert "deadlock" in found[0].message


def test_lockorder_consistent_order_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
            """,
        },
    )
    assert lint(pkg) == []


def test_lockorder_three_lock_rotation_cycle_flagged(tmp_path):
    # no inverted PAIR anywhere — the deadlock is the 3-cycle a->b->c->a
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._c = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def bc(self):
                    with self._b:
                        with self._c:
                            pass

                def ca(self):
                    with self._c:
                        with self._a:
                            pass
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK002"}
    assert "_a" in found[0].message and "_c" in found[0].message


def test_lockorder_interprocedural_held_state_edge(tmp_path):
    # the second lock is taken in a helper that is only ever CALLED with
    # the first held — the edge must come from the propagated entry
    # state, not the helper's lexical context
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        self._grab_b()

                def _grab_b(self):
                    with self._b:
                        pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK002"}


def test_lockorder_reentrant_rlock_not_a_cycle(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._items = []

                def outer(self):
                    with self._lock:
                        self._inner()

                def _inner(self):
                    with self._lock:
                        self._items.append(1)
            """,
        },
    )
    assert lint(pkg) == []


def test_blocking_under_lock_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import os
            import time
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._fd = 3

                def slow(self):
                    with self._lock:
                        time.sleep(1.0)

                def sync(self):
                    with self._lock:
                        os.fsync(self._fd)

                def fine(self):
                    time.sleep(1.0)
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK003"}
    assert len(found) == 2  # slow() + sync(); fine() holds nothing


def test_blocking_via_constructed_member_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "wal.py": """
            import os


            class Wal:
                def __init__(self, fd):
                    self._fd = fd

                def commit(self):
                    self._write_out()

                def _write_out(self):
                    os.fsync(self._fd)
            """,
            "rep.py": """
            import threading

            from fixpkg.wal import Wal


            class Rep:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._wal = Wal(3)

                def mutate(self):
                    with self._lock:
                        self._wal.commit()
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK003"}
    assert "via Wal.commit" in found[0].message
    assert found[0].path.endswith("rep.py")


def test_blocking_via_module_import_constructed_member(tmp_path):
    # `self._wal = wal.Wal(...)` — constructor through a MODULE import
    # must resolve like the from-import form
    pkg = make_pkg(
        tmp_path,
        {
            "wal.py": """
            import os


            class Wal:
                def __init__(self, fd):
                    self._fd = fd

                def commit(self):
                    os.fsync(self._fd)
            """,
            "rep.py": """
            import threading

            from fixpkg import wal


            class Rep:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._wal = wal.Wal(3)

                def mutate(self):
                    with self._lock:
                        self._wal.commit()
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK003"}
    assert "via Wal.commit" in found[0].message


def test_wire_malformed_manifest_is_a_finding_not_a_crash(tmp_path):
    pkg = make_pkg(tmp_path, WIRE_PKG)
    manifest = tmp_path / "manifest.json"
    manifest.write_text('{"version": 1, "packages": null}\n')
    found = lint(pkg, manifest=manifest)
    assert rules_of(found) == {"WIRE005"}
    assert "malformed" in found[0].message


def test_blocking_thread_join_receiver_typed(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    pass

                def stop_bad(self):
                    with self._lock:
                        self._t.join()

                def stop_good(self):
                    self._t.join()

                def strings_fine(self):
                    with self._lock:
                        return ", ".join(["a", "b"])
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK003"}
    assert len(found) == 1 and "Thread.join" in found[0].message


# ----------------------------------------------------------------------
# WAL001 / WAL002 — record-kind exhaustiveness (fixtures)


WAL_PKG = {
    "wal.py": """
    class Log:
        def append_batch(self, seq, ops):
            self._stage({"kind": "batch", "seq": seq, "ops": ops})

        def append_slice(self, seq, arrays):
            self._stage({"kind": "entries", "seq": seq, "arrays": arrays})

        def _stage(self, rec):
            pass
    """,
    "rep.py": """
    class Rep:
        def _replay(self, records):
            for rec in records:
                if rec["kind"] == "batch":
                    pass
                elif rec["kind"] == "entries":
                    pass

        def _scan_log_rows(self, records):
            for rec in records:
                kind = rec.get("kind")
                if kind == "batch":
                    pass
                elif kind == "entries":
                    pass
    """,
}


def test_wal_kinds_covered_clean(tmp_path):
    pkg = make_pkg(tmp_path, WAL_PKG)
    assert lint(pkg) == []


def test_wal_new_kind_must_reach_both_dispatchers(tmp_path):
    mods = dict(WAL_PKG)
    mods["wal.py"] += (
        "\n"
        "        def append_clear(self, seq):\n"
        '            self._stage({"kind": "clear", "seq": seq})\n'
    )
    pkg = make_pkg(tmp_path, mods)
    found = lint(pkg)
    assert rules_of(found) == {"WAL001", "WAL002"}
    assert all("'clear'" in f.message for f in found)


def test_wal_membership_classification_counts(tmp_path):
    # `kind in ("a", "b")` is an explicit classification, same as ==
    mods = dict(WAL_PKG)
    mods["wal.py"] += (
        "\n"
        "        def append_clear(self, seq):\n"
        '            self._stage({"kind": "clear", "seq": seq})\n'
    )
    mods["rep.py"] = """
    class Rep:
        def _replay(self, records):
            for rec in records:
                if rec["kind"] in ("batch", "entries", "clear"):
                    pass

        def _scan_log_rows(self, records):
            for rec in records:
                kind = rec.get("kind")
                if kind in ("clear",):
                    pass  # explicit barrier
                elif kind == "batch":
                    pass
                elif kind == "entries":
                    pass
    """
    pkg = make_pkg(tmp_path, mods)
    assert lint(pkg) == []


def test_wal_missing_replay_dispatcher_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"wal.py": WAL_PKG["wal.py"]})
    found = lint(pkg)
    assert rules_of(found) == {"WAL001", "WAL002"}
    assert any("no recovery replay" in f.message for f in found)


# ----------------------------------------------------------------------
# SUPPRESS001 / SUPPRESS002 — stale-suppression hygiene


def test_stale_allow_comment_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def probe(f, x):
                # crdtlint: allow[host-sync] probe must synchronise
                jax.jit(f)(x).block_until_ready()
                y = x  # crdtlint: allow[donation] nothing donated here
                return f, y
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SUPPRESS001"}
    assert "allow[donation]" in found[0].message


def test_stale_baseline_entry_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {"box.py": LOCKED_CLASS.format(body="size(self):\n            return len(self._items)")},
    )
    baseline = {
        ("fixpkg/box.py", "LOCK001", "long-gone finding message"): 1,
    }
    found = [f for f in lint(pkg, baseline=baseline) if f.rule == "SUPPRESS002"]
    assert len(found) == 1
    assert "long-gone finding message" in found[0].message


def test_hygiene_skipped_under_select(tmp_path):
    # a --select run cannot distinguish stale from not-run: no SUPPRESS
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            def f(x):
                return x  # crdtlint: allow[purity] speculative
            """,
        },
    )
    assert lint(pkg, select={"LOCK001"}) == []
    assert rules_of(lint(pkg)) == {"SUPPRESS001"}


def test_multiline_justification_comment_projects_past_continuation(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def probe(f, x):
                # crdtlint: allow[host-sync] the justification of this
                # probe spans several comment lines before the call
                jax.jit(f)(x).block_until_ready()
                return f
            """,
        },
    )
    new, _baselined, allowed = run_lint([pkg])
    assert new == [] and len(allowed) == 1


# ----------------------------------------------------------------------
# mutation tests — every new rule family proves it turns the gate red
# on the REAL tree (engine overlay, working tree untouched)


def _overlay_lint(rel: str, mutate) -> list[Finding]:
    src = (REPO_ROOT / rel).read_text()
    new, _, _ = run_lint([REPO_ROOT / PKG], overlay={rel: mutate(src)})
    return new


def test_mutation_deleted_dispatch_arm_is_caught():
    """Acceptance: deleting a dispatch arm in replica.py turns the gate
    red (WIRE001: the message is no longer handled anywhere)."""
    rel = f"{PKG}/runtime/replica.py"
    arm = (
        "            elif isinstance(msg, sync_proto.GetLogMsg):\n"
        "                self._handle_get_log(msg)\n"
    )
    assert arm in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(rel, lambda s: s.replace(arm, ""))
    assert any(
        f.rule == "WIRE001" and "GetLogMsg" in f.message for f in new
    )


def test_mutation_unserializable_ackmsg_field_is_caught():
    """Acceptance: adding an unserializable field to AckMsg turns the
    gate red (WIRE003 type check + WIRE005 manifest drift)."""
    rel = f"{PKG}/runtime/sync.py"
    anchor = "    clear_addr: Hashable"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel, lambda s: s.replace(anchor, anchor + "\n    waiter: 'threading.Event'")
    )
    assert any(f.rule == "WIRE003" and "AckMsg" in f.message for f in new)
    assert any(f.rule == "WIRE005" and "AckMsg" in f.message for f in new)


def test_mutation_reordered_wire_fields_is_caught():
    """Acceptance: reordering DiffMsg fields without bumping the
    manifest turns the gate red (WIRE005 — order is wire contract)."""
    rel = f"{PKG}/runtime/sync.py"
    src = (REPO_ROOT / rel).read_text()
    a = "    originator: Hashable\n    frm: Hashable\n"
    assert a in src
    new = _overlay_lint(
        rel, lambda s: s.replace(a, "    frm: Hashable\n    originator: Hashable\n", 1)
    )
    assert any(f.rule == "WIRE005" and "DiffMsg" in f.message for f in new)


def test_mutation_undecoded_frame_kind_is_caught():
    """A frame kind sent by the TCP codec without a receive-path decode
    arm turns the gate red (WIRE004)."""
    rel = f"{PKG}/runtime/tcp_transport.py"
    new = _overlay_lint(
        rel,
        lambda s: s.replace("_MSGB = 5", "_MSGB = 5\n_TRACE = 7").replace(
            '_send_frame(sock, _PING, b"")',
            '_send_frame(sock, _TRACE, b"");  _send_frame(sock, _PING, b"")',
            1,
        ),
    )
    assert any(f.rule == "WIRE004" and "_TRACE" in f.message for f in new)


def test_mutation_inverted_lock_pair_is_caught():
    """Acceptance: an inverted lock-acquisition pair in replica.py turns
    the gate red (LOCK002)."""
    rel = f"{PKG}/runtime/replica.py"
    probe = (
        "\n"
        "    def probe_setup(self):\n"
        "        self._probe_lock = threading.Lock()\n"
        "\n"
        "    def probe_forward(self):\n"
        "        with self._lock:\n"
        "            with self._probe_lock:\n"
        "                pass\n"
        "\n"
        "    def probe_backward(self):\n"
        "        with self._probe_lock:\n"
        "            with self._lock:\n"
        "                pass\n"
    )

    def mutate(s: str) -> str:
        cls_end = s.rindex("\n    def stop(self)")
        tail_end = s.index("self.transport.unregister(self.name)", cls_end)
        tail_end = s.index("\n", tail_end) + 1
        return s[:tail_end] + probe + s[tail_end:]

    new = _overlay_lint(rel, mutate)
    assert any(f.rule == "LOCK002" for f in new)


def test_mutation_invented_wal_kind_is_caught():
    """Acceptance: a WAL record kind written by a producer without
    replay/serving arms turns the gate red (WAL001 + WAL002)."""
    rel = f"{PKG}/runtime/replica.py"
    anchor = '"kind": "entries",'
    src = (REPO_ROOT / rel).read_text()
    assert anchor in src
    new = _overlay_lint(
        rel, lambda s: s.replace(anchor, '"kind": "tombstone",', 1)
    )
    assert any(f.rule == "WAL001" and "'tombstone'" in f.message for f in new)
    assert any(f.rule == "WAL002" and "'tombstone'" in f.message for f in new)


def test_mutation_host_sync_in_fleet_transition_is_caught():
    """Acceptance (ISSUE 6): an injected host sync in the fleet's pure
    batched-transition path turns the gate red (SYNC001) — every
    function in ``runtime/transition.py`` is a jit entry root by
    contract, so the leak is caught even with no caller jit-wrapping
    the mutated function."""
    rel = f"{PKG}/runtime/transition.py"
    anchor = "    return jax.vmap(binned_ops.merge_rows)(states, slices)"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor, "    _n = states.fill.sum().item()\n" + anchor, 1
        ),
    )
    assert any(
        f.rule == "SYNC001" and f.path.endswith("runtime/transition.py")
        for f in new
    )
    # int() coercion of a traced value is the same leak class
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor, "    _n = int(states.fill.sum())\n" + anchor, 1
        ),
    )
    assert any(
        f.rule == "SYNC001" and f.path.endswith("runtime/transition.py")
        for f in new
    )


def test_mutation_host_sync_in_fleet_egress_extraction_is_caught():
    """Acceptance (ISSUE 10): an injected ``.item()`` in the batched
    egress extraction (``fleet_extract_rows``) turns the gate red
    (SYNC001) — the new egress functions are jit entry roots by the
    same module contract as the merge forms."""
    rel = f"{PKG}/runtime/transition.py"
    anchor = "    return jax.vmap(binned_ops.extract_rows)(states, rows)"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor, "    _n = rows.sum().item()\n" + anchor, 1
        ),
    )
    assert any(
        f.rule == "SYNC001" and f.path.endswith("runtime/transition.py")
        for f in new
    )


def test_mutation_fleet_frame_wire_drift_is_caught():
    """Acceptance (ISSUE 10): FleetFrameMsg is manifest-locked — adding
    a wire field without ``--write-protocol-manifest`` turns the gate
    red (WIRE005), exactly the reviewed-bump workflow this PR used to
    land the message."""
    rel = f"{PKG}/runtime/sync.py"
    anchor = "    entries: list  # [(to_addr, message), ...] in send order"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel, lambda s: s.replace(anchor, anchor + "\n    hops: int = 0", 1)
    )
    assert any(
        f.rule == "WIRE005" and "FleetFrameMsg" in f.message for f in new
    )


def test_mutation_impure_fleet_transition_is_caught():
    """An in-place argument mutation (PURE001) or a clock read
    (PURE003) injected into the fleet merge transition turns the gate
    red — the vmapped lattice ops are purity-scoped like ops/ and
    models/ joins."""
    rel = f"{PKG}/runtime/transition.py"
    anchor = "    return jax.vmap(binned_ops.merge_rows)(states, slices)"
    new = _overlay_lint(
        rel,
        lambda s: s.replace(anchor, "    states.key = slices\n" + anchor, 1),
    )
    assert any(f.rule == "PURE001" for f in new)
    new = _overlay_lint(
        rel,
        lambda s: s.replace(anchor, "    _t = time.time()\n" + anchor, 1),
    )
    assert any(f.rule == "PURE003" for f in new)


def test_mutation_host_sync_in_hash_kernel_is_caught():
    """Acceptance (ISSUE 8): an injected ``.item()`` in the hash-store
    kernel module turns the gate red (SYNC001) — ``ops/hash_map.py`` is
    a jit-entry-root module by contract like ``runtime/transition.py``,
    so the leak is caught with no caller jit-wrapping the function."""
    rel = f"{PKG}/ops/hash_map.py"
    anchor = "    v = _slice_view(state, sl)"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor, "    _n = state.alive.sum().item()\n" + anchor, 1
        ),
    )
    assert any(
        f.rule == "SYNC001" and f.path.endswith("ops/hash_map.py") for f in new
    )


def test_mutation_impure_rehash_is_caught():
    """Acceptance (ISSUE 8): an impure rehash turns the gate red —
    every function in ``ops/hash_map.py`` is purity-scoped whatever its
    name (rehash rebuilds anti-entropy state that must replicate
    bit-for-bit), so an in-place argument mutation (PURE001) and a
    clock read (PURE003) are both caught."""
    rel = f"{PKG}/ops/hash_map.py"
    anchor = "    H_old = state.table_size"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(anchor, "    state.arr = state.ctr\n" + anchor, 1),
    )
    assert any(
        f.rule == "PURE001" and f.path.endswith("ops/hash_map.py") for f in new
    )
    new = _overlay_lint(
        rel,
        lambda s: s.replace(anchor, "    _t = time.time()\n" + anchor, 1),
    )
    assert any(
        f.rule == "PURE003" and f.path.endswith("ops/hash_map.py") for f in new
    )


def test_mutation_impure_class_method_in_hash_kernel_is_caught():
    """A CLASS-based kernel helper gets no gate bypass: methods of a
    top-level class in a whole-module/transition-root module are their
    own purity and host-sync roots (they have no enclosing function
    whose ast.walk would cover them — the hole a nested-def skip keyed
    on ``parts[-2]`` alone would leave open)."""
    rel = f"{PKG}/ops/hash_map.py"
    helper = (
        "class _KernelHelper:\n"
        "    def merge_rows_extra(self, state):\n"
        "        return time.time()\n"
        "    def scan(self, state):\n"
        "        return state.alive.sum().item()\n"
    )
    new = _overlay_lint(rel, lambda s: s + "\n\n" + helper)
    assert any(
        f.rule == "PURE003" and f.path.endswith("ops/hash_map.py") for f in new
    ), "class-method clock read escaped the whole-module purity gate"
    assert any(
        f.rule == "SYNC001" and f.path.endswith("ops/hash_map.py") for f in new
    ), "class-method .item() escaped the transition-root host-sync gate"


def test_mutation_stale_allow_is_caught():
    """A freshly stale allow comment (rule fixed, comment left behind)
    turns the gate red (SUPPRESS001)."""
    rel = f"{PKG}/runtime/wal.py"
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            "import dataclasses",
            "import dataclasses  # crdtlint: allow[purity] speculative",
            1,
        ),
    )
    assert any(
        f.rule == "SUPPRESS001" and f.path.endswith("runtime/wal.py")
        for f in new
    )


# ----------------------------------------------------------------------
# RACE001–005 — happens-before race detection (fixtures)


RACY_COUNTER = """
    import threading

    class Box:
        def __init__(self):
            self._n = 0
            self._thread = threading.Thread(target=self._loop)
            self._thread.start()

        def _loop(self):
            while True:
                self._n += 1

        def size(self):
            return self._n
"""


def test_race_cross_thread_counter_flagged(tmp_path):
    """A completely lock-free cross-thread counter: LOCK001 is blind
    (no lock anywhere means no guard to infer) — RACE001 is the rule
    that sees it."""
    pkg = make_pkg(tmp_path, {"box.py": RACY_COUNTER})
    found = lint(pkg)
    assert rules_of(found) == {"RACE001"}
    assert any("_n" in f.message and "_loop" in f.message for f in found)


def test_race_counter_with_common_lock_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                    self._thread = threading.Thread(target=self._loop)
                    self._thread.start()

                def _loop(self):
                    while True:
                        with self._lock:
                            self._n += 1

                def size(self):
                    with self._lock:
                        return self._n
            """
        },
    )
    assert lint(pkg) == []


# -- happens-before edges, one fixture per edge kind -------------------


START_EDGE = """
    import threading

    class Box:
        def __init__(self):
            self._cfg = None

        def start(self):
            self._cfg = 42
            self._thread = threading.Thread(target=self._loop)
            self._thread.start()
            {post}

        def _loop(self):
            print(self._cfg)
"""


def test_hb_start_edge_orders_pre_start_writes(tmp_path):
    pkg = make_pkg(tmp_path, {"box.py": START_EDGE.format(post="return self")})
    assert lint(pkg) == []


def test_hb_write_after_start_is_published_race(tmp_path):
    pkg = make_pkg(
        tmp_path, {"box.py": START_EDGE.format(post="self._cfg = 43")}
    )
    found = lint(pkg)
    assert "RACE004" in rules_of(found)
    assert any(
        f.rule == "RACE004" and "_cfg" in f.message and "_loop" in f.message
        for f in found
    )


JOIN_EDGE = """
    import threading

    class Box:
        def __init__(self):
            self._out = []
            self._thread = threading.Thread(target=self._work)
            self._thread.start()

        def _work(self):
            self._out.append(1)

        def result(self):
            {pre}return list(self._out)
"""


def test_hb_join_edge_orders_thread_writes(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {"box.py": JOIN_EDGE.format(pre="self._thread.join()\n            ")},
    )
    assert lint(pkg) == []


def test_hb_missing_join_is_iteration_race(tmp_path):
    pkg = make_pkg(tmp_path, {"box.py": JOIN_EDGE.format(pre="")})
    found = lint(pkg)
    assert rules_of(found) == {"RACE005"}
    assert "_out" in found[0].message


EVENT_EDGE = """
    import threading

    class Box:
        def __init__(self):
            self._ready = threading.Event()
            self._result = None
            self._thread = threading.Thread(target=self._work)
            self._thread.start()

        def _work(self):
            self._result = 41
            self._ready.set()

        def read(self):
            self._ready.wait({timeout})
            return self._result
"""


def test_hb_event_set_wait_edge_orders_handoff(tmp_path):
    pkg = make_pkg(tmp_path, {"box.py": EVENT_EDGE.format(timeout="")})
    assert lint(pkg) == []


def test_hb_timed_wait_is_not_an_edge(tmp_path):
    """``Event.wait(timeout)`` can return with nothing set — pacing,
    not synchronisation. The same handoff with a timeout races."""
    pkg = make_pkg(tmp_path, {"box.py": EVENT_EDGE.format(timeout="0.5")})
    found = lint(pkg)
    assert rules_of(found) == {"RACE001"}
    assert any("_result" in f.message for f in found)


QUEUE_EDGE = """
    import queue
    import threading

    class Box:
        def __init__(self):
            self._q = queue.Queue()
            self._q2 = queue.Queue()
            self._payload = None
            self._thread = threading.Thread(target=self._work)
            self._thread.start()

        def _work(self):
            self._payload = 7
            self._q.put(None)

        def read(self):
            self._q{get_q}.get()
            return self._payload
"""


def test_hb_queue_put_get_edge_orders_handoff(tmp_path):
    pkg = make_pkg(tmp_path, {"box.py": QUEUE_EDGE.format(get_q="")})
    assert lint(pkg) == []


def test_hb_distinct_queues_do_not_synchronize(tmp_path):
    """put on one queue object and get on ANOTHER is no handoff — the
    HB channel is per-object, and blessing cross-queue pairs would hide
    real races behind unrelated queue traffic."""
    pkg = make_pkg(tmp_path, {"box.py": QUEUE_EDGE.format(get_q="2")})
    found = lint(pkg)
    assert rules_of(found) == {"RACE001"}
    assert any("_payload" in f.message for f in found)


# -- RACE002: closure escapes across the thread boundary ---------------


ESCAPE = """
    import threading

    def collect():
        acc = []

        def fill():
            acc.append(1)

        t = threading.Thread(target=fill)
        t.start()
        {mid}
        return list(acc)
"""


def test_race_closure_escape_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"box.py": ESCAPE.format(mid="pass")})
    found = lint(pkg)
    assert rules_of(found) == {"RACE002"}
    assert "'acc'" in found[0].message and "fill" in found[0].message


def test_race_closure_escape_joined_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"box.py": ESCAPE.format(mid="t.join()")})
    assert lint(pkg) == []


def test_race_threadsafe_capture_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import queue
            import threading

            def collect():
                acc = queue.Queue()

                def fill():
                    acc.put(1)

                threading.Thread(target=fill).start()
                return acc.get()
            """
        },
    )
    assert lint(pkg) == []


# -- RACE003: check-then-act on version fields -------------------------


VERSION_CHECK = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._ver = 0
            self._val = None

        def bump(self, v):
            with self._lock:
                self._val = v
                self._ver += 1

        def commit(self, expect, v):
            {body}
"""


def test_race_version_check_outside_lock_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": VERSION_CHECK.format(
                body=(
                    "if self._ver != expect:\n"
                    "                return False\n"
                    "            with self._lock:\n"
                    "                self._val = v\n"
                    "            return True"
                )
            )
        },
    )
    found = lint(pkg)
    assert "RACE003" in rules_of(found)
    assert any(
        f.rule == "RACE003" and "_ver" in f.message and "commit" in f.message
        for f in found
    )


def test_race_version_check_inside_lock_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": VERSION_CHECK.format(
                body=(
                    "with self._lock:\n"
                    "                if self._ver != expect:\n"
                    "                    return False\n"
                    "                self._val = v\n"
                    "            return True"
                )
            )
        },
    )
    assert lint(pkg) == []


# -- RACE005: lock-free iteration --------------------------------------


def test_race_unlocked_iteration_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._items = {}
                    self._thread = threading.Thread(target=self._feed)
                    self._thread.start()

                def _feed(self):
                    self._items[1] = 2

                def keys(self):
                    return [k for k in self._items]
            """
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"RACE005"}
    assert "_items" in found[0].message


def test_race_locked_iteration_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = {}
                    self._thread = threading.Thread(target=self._feed)
                    self._thread.start()

                def _feed(self):
                    with self._lock:
                        self._items[1] = 2

                def keys(self):
                    with self._lock:
                        return [k for k in self._items]
            """
        },
    )
    assert lint(pkg) == []


# -- module globals (the telemetry/native shape) -----------------------


MOD_GLOBAL = """
    import threading

    _cache = {{}}
    _lock = threading.Lock()

    def start_filler():
        def fill():
            {fill_body}

        threading.Thread(target=fill, daemon=True).start()

    def peek():
        {peek_body}
"""


def test_race_module_global_cross_thread_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": MOD_GLOBAL.format(
                fill_body="_cache[1] = 2",
                peek_body="return _cache.get(1)",
            )
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"RACE001"}
    assert any("_cache" in f.message for f in found)


def test_race_module_global_locked_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": MOD_GLOBAL.format(
                fill_body="with _lock:\n                _cache[1] = 2",
                peek_body="with _lock:\n            return _cache.get(1)",
            )
        },
    )
    assert lint(pkg) == []


# ----------------------------------------------------------------------
# RACE mutation tests — ≥5 distinct injected races in the REAL tree
# turn the gate red (engine overlay, working tree untouched)


def test_mutation_deleted_lock_around_cross_thread_write_is_caught():
    """Injected race 1: delete the ``with self._lock:`` around the
    fleet tick counters — the loop thread then writes what stats()
    reads with no common lock (RACE001; LOCK001 stays blind because the
    attr no longer has a guarded write to infer a guard from)."""
    rel = f"{PKG}/runtime/fleet.py"
    anchor = (
        "            with self._lock:\n"
        "                # tick/dispatch counters are read by stats() from any\n"
        "                # caller thread while the fleet loop writes them\n"
        "                # (crdtlint RACE001)\n"
    )
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel, lambda s: s.replace(anchor, "            if True:\n", 1)
    )
    assert any(
        f.rule == "RACE001" and "_ticks" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_event_wait_removed_before_dependent_read_is_caught():
    """Injected race 2: a correct Event handoff added to the real Fleet
    is green (the set→wait edge orders the publication); deleting the
    ``wait()`` turns the same read into a race (RACE001). Proves the HB
    edge is what suppresses — not an accident of the surrounding tree."""
    rel = f"{PKG}/runtime/fleet.py"
    probe = (
        "    def probe_publish(self):\n"
        "        self._probe_done = threading.Event()\n"
        "        self._probe_box = {}\n"
        "\n"
        "        def probe_fill():\n"
        "            self._probe_box[\"r\"] = 1\n"
        "            self._probe_done.set()\n"
        "\n"
        "        threading.Thread(target=probe_fill, daemon=True).start()\n"
        "        self._probe_done.wait()\n"
        "        return self._probe_box[\"r\"]\n"
        "\n"
    )
    anchor = "\ndef start_fleet(replicas"
    src = (REPO_ROOT / rel).read_text()
    assert anchor in src

    with_handoff = src.replace(anchor, "\n" + probe + anchor, 1)
    new, _, _ = run_lint([REPO_ROOT / PKG], overlay={rel: with_handoff})
    assert not any(f.rule.startswith("RACE") for f in new), "\n".join(
        f.render() for f in new
    )

    no_wait = with_handoff.replace("        self._probe_done.wait()\n", "", 1)
    new, _, _ = run_lint([REPO_ROOT / PKG], overlay={rel: no_wait})
    assert any(
        f.rule == "RACE001" and "_probe_box" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_version_check_moved_outside_lock_is_caught():
    """Injected race 3: hoist fleet_commit's ``_state_version`` check
    above the lock — the optimistic-commit recheck is then stale by
    commit time (RACE003)."""
    rel = f"{PKG}/runtime/replica.py"
    anchor = (
        "        with self._lock:\n"
        "            if self._state_version != version:\n"
        "                return None\n"
    )
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor,
            "        if self._state_version != version:\n"
            "            return None\n"
            "        with self._lock:\n",
            1,
        ),
    )
    assert any(
        f.rule == "RACE003" and "_state_version" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_attr_init_below_thread_start_is_caught():
    """Injected race 4: move ``heartbeat_interval``'s assignment below
    the heartbeat thread's start() in TcpTransport.__init__ — the
    started thread can read the attribute before it exists (RACE004)."""
    rel = f"{PKG}/runtime/tcp_transport.py"
    init_line = "        self.heartbeat_interval = heartbeat_interval\n"
    start_line = "        self._hb_thread.start()\n"
    src = (REPO_ROOT / rel).read_text()
    assert init_line in src and start_line in src
    new = _overlay_lint(
        rel,
        lambda s: s.replace(init_line, "", 1).replace(
            start_line, start_line + init_line, 1
        ),
    )
    assert any(
        f.rule == "RACE004" and "heartbeat_interval" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_unlocked_dict_iteration_is_caught():
    """Injected race 5: drop the lock around the heartbeat loop's
    ``_monitors`` snapshot — monitor()/unregister() mutate the dict
    from caller threads mid-iteration (RACE005)."""
    rel = f"{PKG}/runtime/tcp_transport.py"
    anchor = (
        "            with self._lock:\n"
        "                remote_targets = "
        "[t for t in self._monitors if self._is_remote(t)]\n"
    )
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor,
            "            remote_targets = "
            "[t for t in self._monitors if self._is_remote(t)]\n",
            1,
        ),
    )
    assert any(
        f.rule == "RACE005" and "_monitors" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_unlocked_telemetry_handler_table_is_caught():
    """Injected race 6 (module globals over the import graph): delete
    the lock around telemetry.attach's handler-table append — attach
    runs on caller threads while execute/has_handlers read the table
    from the replica/fleet event loops (RACE001 on a module global,
    with the thread root discovered cross-module)."""
    rel = f"{PKG}/runtime/telemetry.py"
    anchor = (
        "def attach(event: tuple, handler: Callable[[tuple, dict, dict], None]) -> None:\n"
        "    with _lock:\n"
        "        _handlers[event] = _handlers[event] + (handler,)\n"
    )
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor,
            "def attach(event: tuple, handler: Callable[[tuple, dict, dict], None]) -> None:\n"
            "    _handlers[event] = _handlers[event] + (handler,)\n",
            1,
        ),
    )
    assert any(
        f.rule == "RACE001" and "_handlers" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_race_allow_tag_and_hygiene(tmp_path):
    """The ``race`` family tag suppresses any RACE00x finding with a
    stated why; once the race is fixed the leftover allow turns the
    gate red itself (SUPPRESS001) — same hygiene contract as every
    other family."""
    annotated = RACY_COUNTER.replace(
        "        def size(self):\n",
        "        def size(self):\n"
        "            # crdtlint: allow[race] approximate counter: torn\n"
        "            # reads tolerated, single writer\n",
    )
    pkg = make_pkg(tmp_path, {"box.py": annotated})
    new, _baselined, allowed = run_lint([pkg])
    assert new == []
    assert {f.rule for f in allowed} == {"RACE001"}

    # fix the race (single-threaded now) but keep the allow: stale
    fixed = annotated.replace(
        "            self._thread = threading.Thread(target=self._loop)\n"
        "            self._thread.start()\n",
        "",
    )
    pkg2 = make_pkg(tmp_path / "b", {"box.py": fixed})
    found = lint(pkg2)
    assert rules_of(found) == {"SUPPRESS001"}


def test_race_snapshot_builtin_reports_race005_only(tmp_path):
    """``list(self._x.values())`` records both an iteration and a
    method-call access on one line — the defect must surface as ONE
    RACE005 finding, not a RACE001/RACE005 double report needing two
    allow comments."""
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._items = {}
                    self._thread = threading.Thread(target=self._feed)
                    self._thread.start()

                def _feed(self):
                    self._items[1] = 2

                def values(self):
                    return list(self._items.values())
            """
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"RACE005"}
    assert len([f for f in found if "_items" in f.message]) == 1


# ----------------------------------------------------------------------
# OBS001/OBS002 — observability-plane coverage + hot-path guards


OBS_PKG = {
    "runtime/telemetry.py": """
        SYNC_DONE = ("pkg", "sync", "done")
        WAL_FLUSH = ("pkg", "wal", "flush")

        def has_handlers(event):
            return False

        def execute(event, measurements, metadata):
            pass
    """,
    "runtime/replica.py": """
        from fixpkg.runtime import telemetry

        class Replica:
            def merge(self):
                if telemetry.has_handlers(telemetry.SYNC_DONE):
                    telemetry.execute(telemetry.SYNC_DONE, {"n": 1}, {})

            def flush(self):
                want = telemetry.has_handlers(telemetry.WAL_FLUSH)
                if want:
                    telemetry.execute(telemetry.WAL_FLUSH, {"b": 2}, {})
    """,
    "runtime/metrics.py": """
        from fixpkg.runtime import telemetry

        class Bridge:
            def _on_done(self, e, m, meta):
                pass

            def _on_flush(self, e, m, meta):
                pass

            def _table(self):
                return [
                    (telemetry.SYNC_DONE, self._on_done),
                    (telemetry.WAL_FLUSH, self._on_flush),
                ]
    """,
}


def test_obs_clean_fixture(tmp_path):
    pkg = make_pkg(tmp_path, OBS_PKG)
    assert [f for f in lint(pkg) if f.rule.startswith("OBS")] == []


def test_obs001_unemitted_event_flagged(tmp_path):
    mods = dict(OBS_PKG)
    mods["runtime/telemetry.py"] = (
        OBS_PKG["runtime/telemetry.py"]
        + '\n        GHOST = ("pkg", "ghost", "x")\n'
    )
    mods["runtime/metrics.py"] = OBS_PKG["runtime/metrics.py"].replace(
        "(telemetry.WAL_FLUSH, self._on_flush),",
        "(telemetry.WAL_FLUSH, self._on_flush),\n"
        "                    (telemetry.GHOST, self._on_flush),",
    )
    found = [f for f in lint(make_pkg(tmp_path, mods)) if f.rule == "OBS001"]
    assert len(found) == 1 and "never emitted" in found[0].message


def test_obs001_unbridged_event_flagged(tmp_path):
    mods = dict(OBS_PKG)
    mods["runtime/metrics.py"] = OBS_PKG["runtime/metrics.py"].replace(
        "                    (telemetry.WAL_FLUSH, self._on_flush),\n", ""
    )
    found = [f for f in lint(make_pkg(tmp_path, mods)) if f.rule == "OBS001"]
    assert len(found) == 1
    assert "WAL_FLUSH" in found[0].message and "bridge" in found[0].message


def test_obs001_missing_bridge_table_flagged(tmp_path):
    mods = dict(OBS_PKG)
    mods["runtime/metrics.py"] = "from fixpkg.runtime import telemetry\n"
    found = [f for f in lint(make_pkg(tmp_path, mods)) if f.rule == "OBS001"]
    assert any("no metrics-bridge subscription table" in f.message for f in found)


def test_obs002_unguarded_hot_execute_flagged(tmp_path):
    mods = dict(OBS_PKG)
    mods["runtime/replica.py"] = """
        from fixpkg.runtime import telemetry

        class Replica:
            def merge(self):
                telemetry.execute(telemetry.SYNC_DONE, {"n": 1}, {})
    """
    found = [f for f in lint(make_pkg(tmp_path, mods)) if f.rule == "OBS002"]
    assert len(found) == 1 and "SYNC_DONE" in found[0].message


def test_obs002_cold_module_execute_clean(tmp_path):
    """Unguarded execute OUTSIDE the hot module set (e.g. a storage
    module) is fine — the guard discipline is a hot-path contract."""
    mods = dict(OBS_PKG)
    mods["runtime/storage.py"] = """
        from fixpkg.runtime import telemetry

        def persist():
            telemetry.execute(telemetry.WAL_FLUSH, {"b": 1}, {})
    """
    assert [f for f in lint(make_pkg(tmp_path, mods)) if f.rule.startswith("OBS")] == []


def test_obs002_hoisted_guard_clean(tmp_path):
    """`want = telemetry.has_handlers(E)` ... `if want:` is a guard."""
    pkg = make_pkg(tmp_path, OBS_PKG)
    assert [f for f in lint(pkg) if f.rule == "OBS002"] == []


def test_obs002_guarded_closure_clean(tmp_path):
    """A nested def whose *definition* sits under a has_handlers guard
    inherits the guarded state — the deferred-emission idiom (the
    closure is parked and called later, but only ever created under
    the guard)."""
    mods = dict(OBS_PKG)
    mods["runtime/replica.py"] = """
        from fixpkg.runtime import telemetry

        class Replica:
            def merge(self):
                want = telemetry.has_handlers(telemetry.SYNC_DONE)
                if want:
                    def emit(n):
                        telemetry.execute(telemetry.SYNC_DONE, {"n": n}, {})
                    self._defer = emit

            def flush(self):
                if telemetry.has_handlers(telemetry.WAL_FLUSH):
                    telemetry.execute(telemetry.WAL_FLUSH, {"b": 2}, {})
    """
    assert [f for f in lint(make_pkg(tmp_path, mods)) if f.rule == "OBS002"] == []


def test_obs_execute_many_counts_as_emission_and_needs_guard(tmp_path):
    """``telemetry.execute_many`` is an emission site for OBS001 (an
    event emitted ONLY through the batch form is not a dead contract)
    and is held to the same OBS002 guard discipline."""
    mods = dict(OBS_PKG)
    mods["runtime/replica.py"] = """
        from fixpkg.runtime import telemetry

        class Replica:
            def merge(self):
                if telemetry.has_handlers(telemetry.SYNC_DONE):
                    telemetry.execute_many(
                        telemetry.SYNC_DONE, [{"n": 1}, {"n": 2}], {}
                    )

            def flush(self):
                want = telemetry.has_handlers(telemetry.WAL_FLUSH)
                if want:
                    telemetry.execute(telemetry.WAL_FLUSH, {"b": 2}, {})
    """
    assert [f for f in lint(make_pkg(tmp_path, mods)) if f.rule.startswith("OBS")] == []
    # strip the guard: the batch form is red exactly like execute
    mods["runtime/replica.py"] = mods["runtime/replica.py"].replace(
        "if telemetry.has_handlers(telemetry.SYNC_DONE):\n                    telemetry.execute_many(",
        "telemetry.execute_many(",
    )
    red = tmp_path / "red"
    red.mkdir()
    found = [f for f in lint(make_pkg(red, mods)) if f.rule == "OBS002"]
    assert len(found) == 1 and "SYNC_DONE" in found[0].message


def test_obs002_unguarded_closure_flagged(tmp_path):
    """A nested def defined OUTSIDE any guard is no excuse — its
    execute is still red, and the finding names the closure."""
    mods = dict(OBS_PKG)
    mods["runtime/replica.py"] = """
        from fixpkg.runtime import telemetry

        class Replica:
            def merge(self):
                def emit(n):
                    telemetry.execute(telemetry.SYNC_DONE, {"n": n}, {})
                emit(1)
                if telemetry.has_handlers(telemetry.WAL_FLUSH):
                    telemetry.execute(telemetry.WAL_FLUSH, {"b": 2}, {})
    """
    found = [f for f in lint(make_pkg(tmp_path, mods)) if f.rule == "OBS002"]
    assert len(found) == 1 and "SYNC_DONE" in found[0].message
    assert "Replica.merge.emit" in found[0].message


def test_mutation_dropped_bridge_row_is_caught():
    """ISSUE 9 acceptance: deleting one subscription row from the REAL
    metrics bridge turns the gate red (OBS001)."""
    rel = f"{PKG}/runtime/metrics.py"
    row = "            (telemetry.CATCHUP_DONE, self._on_catchup_done),\n"
    assert row in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(rel, lambda s: s.replace(row, ""))
    assert any(
        f.rule == "OBS001" and "CATCHUP_DONE" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_unguarded_hot_emission_is_caught():
    """ISSUE 9 acceptance: stripping a has_handlers guard off a
    hot-path emission in the REAL replica turns the gate red (OBS002)."""
    rel = f"{PKG}/runtime/replica.py"
    guard = "if telemetry.has_handlers(telemetry.SYNC_ROUND):"
    assert guard in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(rel, lambda s: s.replace(guard, "if True:", 1))
    assert any(
        f.rule == "OBS002" and "SYNC_ROUND" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_declared_unemitted_event_is_caught():
    """A declared-but-dead event tuple in the REAL telemetry module is
    a red OBS001 (both legs: unemitted and unbridged)."""
    rel = f"{PKG}/runtime/telemetry.py"
    new = _overlay_lint(
        rel, lambda s: s + '\nGHOST_EVENT = ("delta_crdt", "ghost", "x")\n'
    )
    msgs = [f.message for f in new if f.rule == "OBS001"]
    assert any("never emitted" in m for m in msgs)
    assert any("bridge" in m for m in msgs)


def test_mutation_unguarded_serve_emission_is_caught():
    """ISSUE 14 acceptance: ``runtime/serve.py`` is an OBS002 hot-path
    module — stripping the has_handlers guard off the REAL shed
    emission turns the gate red."""
    rel = f"{PKG}/runtime/serve.py"
    guard = "if telemetry.has_handlers(telemetry.SERVE_SHED):"
    assert guard in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(rel, lambda s: s.replace(guard, "if True:", 1))
    assert any(
        f.rule == "OBS002" and "SERVE_SHED" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_dropped_serve_bridge_row_is_caught():
    """ISSUE 14 acceptance: deleting the SERVE_SHED subscription row
    from the REAL metrics bridge turns the gate red (OBS001)."""
    rel = f"{PKG}/runtime/metrics.py"
    row = "            (telemetry.SERVE_SHED, self._on_serve_shed),\n"
    assert row in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(rel, lambda s: s.replace(row, ""))
    assert any(
        f.rule == "OBS001" and "SERVE_SHED" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_unlocked_serve_shed_counter_read_is_caught():
    """ISSUE 14 acceptance: the serving front door sits in the
    LOCK/RACE thread graph (its admission worker is a discovered
    thread entry, its one lock mints guards) — an injected UNLOCKED
    read of the shed counter in the REAL ``runtime/serve.py`` turns
    the gate red."""
    rel = f"{PKG}/runtime/serve.py"
    probe = (
        "\n"
        "    def shed_probe(self) -> int:\n"
        "        return self._shed_ops\n"
    )
    anchor = "    def close(self) -> None:"
    src = (REPO_ROOT / rel).read_text()
    assert anchor in src
    new = _overlay_lint(rel, lambda s: s.replace(anchor, probe + "\n" + anchor, 1))
    assert any(
        f.rule in ("LOCK001", "RACE001")
        and "_shed_ops" in f.message
        and "Frontdoor" in f.message
        for f in new
    ), "\n".join(f.render() for f in new)


# ----------------------------------------------------------------------
# SHAPE001/SHAPE002 — recompile discipline (ISSUE 12)


SHAPE_REPLICA_RAW = """
    import numpy as np

    def jit_merge(state, sl):
        return state

    class Replica:
        def drain(self, msgs, state):
            n = len(msgs)
            rows = np.full(n, -1, np.int32)
            return jit_merge(state, rows)
"""

SHAPE_REPLICA_TIERED = """
    import numpy as np

    def pow2_tier(n, floor=1):
        return max(n, floor)

    def jit_merge(state, sl):
        return state

    class Replica:
        def drain(self, msgs, state):
            n = pow2_tier(len(msgs))
            rows = np.full(n, -1, np.int32)
            return jit_merge(state, rows)
"""


def test_shape001_raw_len_operand_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"runtime/replica.py": SHAPE_REPLICA_RAW})
    found = [f for f in lint(pkg) if f.rule == "SHAPE001"]
    assert len(found) == 1 and "jit_merge" in found[0].message


def test_shape001_tiered_operand_clean(tmp_path):
    pkg = make_pkg(tmp_path, {"runtime/replica.py": SHAPE_REPLICA_TIERED})
    assert [f for f in lint(pkg) if f.rule.startswith("SHAPE")] == []


def test_shape001_pad_fn_lanes_discipline(tmp_path):
    """``stack_entry_slices`` lanes= must be tier-derived; a raw
    ``len()`` (or omitting lanes entirely) is red."""
    fleet = """
        def pow2_tier(n, floor=1):
            return max(n, floor)

        def stack_entry_slices(slices, lanes=None):
            return slices, 0

        class Fleet:
            def dispatch(self, members):
                sl, _ = stack_entry_slices(
                    [m.sl for m in members], lanes={lanes}
                )
                return sl
    """
    red_raw = make_pkg(
        tmp_path / "raw",
        {"runtime/fleet.py": fleet.format(lanes="len(members)")},
    )
    found = [f for f in lint(red_raw) if f.rule == "SHAPE001"]
    assert len(found) == 1 and "raw data-dependent size" in found[0].message

    green = make_pkg(
        tmp_path / "tiered",
        {"runtime/fleet.py": fleet.format(lanes="pow2_tier(len(members), floor=2)")},
    )
    assert [f for f in lint(green) if f.rule.startswith("SHAPE")] == []

    omitted = fleet.replace(", lanes={lanes}", "").replace("\n                )", ")")
    red_omit = make_pkg(tmp_path / "omit", {"runtime/fleet.py": omitted})
    found = [f for f in lint(red_omit) if f.rule == "SHAPE001"]
    assert len(found) == 1 and "without lanes=" in found[0].message


def test_shape001_unpadded_stack_flagged(tmp_path):
    """A list stacked by ``stack_pytrees`` must be tier-padded in
    scope; the ``lst += [lst[0]] * (lanes - len(lst))`` idiom (with a
    sanitised tier) is the green form."""
    fleet = """
        def pow2_tier(n, floor=1):
            return max(n, floor)

        def jit_stack_pytrees(*trees):
            return trees

        class Fleet:
            def tick(self, items):
                leaves = [e.leaf for e in items]
{pad}
                return jit_stack_pytrees(*leaves)
    """
    red = make_pkg(tmp_path / "red", {"runtime/fleet.py": fleet.format(pad="")})
    found = [f for f in lint(red) if f.rule == "SHAPE001"]
    assert len(found) == 1 and "never padded" in found[0].message

    pad = (
        "                lanes = pow2_tier(len(items), floor=2)\n"
        "                leaves += [leaves[0]] * (lanes - len(items))"
    )
    green = make_pkg(tmp_path / "green", {"runtime/fleet.py": fleet.format(pad=pad)})
    assert [f for f in lint(green) if f.rule.startswith("SHAPE")] == []


def test_shape002_static_arg_vocabulary(tmp_path):
    """Static args at jit call sites come from the closed geometry
    vocabulary: tier calls, constants, geometry attributes, forwarded
    params — a raw ``len()`` static is red."""
    mod = """
        import jax

        def pow2_tier(n, floor=1):
            return max(n, floor)

        def extract(state, rows, lanes):
            return state

        jit_extract = jax.jit(extract, static_argnames=("lanes",))

        def ship(state, rows, msgs):
            return jit_extract(state, rows, lanes={lanes})
    """
    red = make_pkg(
        tmp_path / "red", {"models/hash_store.py": mod.format(lanes="len(msgs)")}
    )
    found = [f for f in lint(red) if f.rule == "SHAPE002"]
    assert len(found) == 1 and "lanes=" in found[0].message

    for i, lanes in enumerate(
        ("pow2_tier(len(msgs))", "32", "state.table_size * 2")
    ):
        green = make_pkg(
            tmp_path / f"green{i}",
            {"models/hash_store.py": mod.format(lanes=lanes)},
        )
        assert [f for f in lint(green) if f.rule.startswith("SHAPE")] == [], lanes


def test_shape_allow_tag(tmp_path):
    """The ``shape`` family tag suppresses with a stated why."""
    annotated = SHAPE_REPLICA_RAW.replace(
        "            return jit_merge(state, rows)",
        "            # crdtlint: allow[shape] one-shot recovery path:\n"
        "            # runs once per boot, recompiles are irrelevant\n"
        "            return jit_merge(state, rows)",
    )
    pkg = make_pkg(tmp_path, {"runtime/replica.py": annotated})
    new, _baselined, allowed = run_lint([pkg])
    assert new == []
    assert {f.rule for f in allowed} == {"SHAPE001"}


# ----------------------------------------------------------------------
# LEAK001 — buffer-pinning closure captures (ISSUE 12)


#: ``{body}`` lines use ABSOLUTE indentation matching the template
#: (drain statements at 12, nested closure bodies at 16, sibling
#: methods at 8) — dedent strips the common 4-space prefix.
LEAK_REPLICA = """
    def jit_merge_rows(state, sl):
        return state

    class Replica:
        def __init__(self):
            self._defer = []
            self._state = None

        def drain(self, sl):
            res = jit_merge_rows(self._state, sl)
{body}
"""


def test_leak001_escaping_whole_result_flagged(tmp_path):
    body = (
        "            def emit():\n"
        "                return res.n_inserted\n"
        "            self._defer.append(emit)"
    )
    pkg = make_pkg(tmp_path, {"runtime/replica.py": LEAK_REPLICA.format(body=body)})
    found = [f for f in lint(pkg) if f.rule == "LEAK001"]
    assert len(found) == 1
    assert "res" in found[0].message and "default-arg capture" in found[0].message


def test_leak001_default_arg_narrowing_clean(tmp_path):
    """The PR 9 fix idiom: default-arg capture of just the count leaves
    is green — defaults evaluate at def time, res is never held."""
    body = (
        "            def emit(ins=res.n_inserted, kill=res.n_killed):\n"
        "                return ins + kill\n"
        "            self._defer.append(emit)"
    )
    pkg = make_pkg(tmp_path, {"runtime/replica.py": LEAK_REPLICA.format(body=body)})
    assert [f for f in lint(pkg) if f.rule == "LEAK001"] == []


def test_leak001_heavy_default_still_flagged(tmp_path):
    """``r=res`` / ``s=res.state`` as a default re-widens the capture —
    the default holds the whole pytree exactly like free capture."""
    body = (
        "            def emit(s=res.state):\n"
        "                return s\n"
        "            self._defer.append(emit)"
    )
    pkg = make_pkg(tmp_path, {"runtime/replica.py": LEAK_REPLICA.format(body=body)})
    found = [f for f in lint(pkg) if f.rule == "LEAK001"]
    assert len(found) == 1 and "res.state" in found[0].message


def test_leak001_interprocedural_deferrer(tmp_path):
    """A closure handed to a method that parks its parameter (the
    ``_note_state_changed`` shape) escapes one call down — the
    storing-parameter fix point must see through the indirection."""
    body = (
        "            def emit():\n"
        "                return res\n"
        "            self._note(emit)\n"
        "\n"
        "        def _note(self, count_fn):\n"
        "            self._defer.append(count_fn)"
    )
    pkg = make_pkg(tmp_path, {"runtime/replica.py": LEAK_REPLICA.format(body=body)})
    found = [f for f in lint(pkg) if f.rule == "LEAK001"]
    assert len(found) == 1 and "_note" in found[0].message


def test_leak001_self_state_capture_flagged(tmp_path):
    body = (
        "            def emit():\n"
        "                return self._state\n"
        "            self._callback = emit"
    )
    pkg = make_pkg(tmp_path, {"runtime/replica.py": LEAK_REPLICA.format(body=body)})
    found = [f for f in lint(pkg) if f.rule == "LEAK001"]
    assert len(found) == 1 and "self._state" in found[0].message


def test_leak001_local_closure_clean(tmp_path):
    """A closure that never escapes (called inline, handed to an
    immediately-applied combinator) may capture anything."""
    body = (
        "            def pick(lane):\n"
        "                return res\n"
        "            return pick(0)"
    )
    pkg = make_pkg(tmp_path, {"runtime/replica.py": LEAK_REPLICA.format(body=body)})
    assert [f for f in lint(pkg) if f.rule == "LEAK001"] == []


def test_leak001_factory_result_escape(tmp_path):
    """A closure factory's call result carries the inner closure's
    captures into the sink (the fleet ``counts_for`` shape)."""
    body = (
        "            def make(lane):\n"
        "                def fn():\n"
        "                    return res\n"
        "                return fn\n"
        "            self._note(make(0))\n"
        "\n"
        "        def _note(self, count_fn):\n"
        "            self._defer.append(count_fn)"
    )
    pkg = make_pkg(tmp_path, {"runtime/replica.py": LEAK_REPLICA.format(body=body)})
    found = [f for f in lint(pkg) if f.rule == "LEAK001"]
    assert len(found) == 1 and "make" in found[0].message


def test_leak001_cold_module_clean(tmp_path):
    """The rule is a hot-path (replica/fleet) contract — a storage
    module parking closures is not its business."""
    body = (
        "        def emit():\n"
        "            return res\n"
        "        self._defer.append(emit)"
    )
    pkg = make_pkg(
        tmp_path, {"runtime/storage.py": LEAK_REPLICA.format(body=body)}
    )
    assert [f for f in lint(pkg) if f.rule == "LEAK001"] == []


# ----------------------------------------------------------------------
# SPMD001 — shard_map readiness of transition-contract modules


def test_spmd001_host_callback_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"runtime/transition.py": """
        import jax

        def fleet_step(states):
            jax.debug.print("x {s}", s=states)
            return states
    """})
    found = [f for f in lint(pkg) if f.rule == "SPMD001"]
    assert len(found) == 1 and "host callback" in found[0].message


def test_spmd001_axis_branch_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"runtime/transition.py": """
        def fleet_step(states):
            if states.key.shape[0] > 4:
                return states
            return states
    """})
    found = [f for f in lint(pkg) if f.rule == "SPMD001"]
    assert len(found) == 1 and "shard" in found[0].message


def test_spmd001_axis_free_reduction_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"runtime/transition.py": """
        import jax.numpy as jnp

        def fleet_total(states):
            return jnp.sum(states)
    """})
    found = [f for f in lint(pkg) if f.rule == "SPMD001"]
    assert len(found) == 1 and "axis-free reduction" in found[0].message


def test_spmd001_vmapped_and_axised_forms_clean(tmp_path):
    """Reductions inside vmapped inner functions are per-lane; explicit
    axis= names the folded axes — both survive the mesh lift."""
    pkg = make_pkg(tmp_path, {"runtime/transition.py": """
        import jax
        import jax.numpy as jnp

        def fleet_total(states):
            per_lane = jax.vmap(lambda s: jnp.sum(s))(states)
            return jnp.sum(states, axis=1)
    """})
    assert [f for f in lint(pkg) if f.rule == "SPMD001"] == []


def test_spmd001_cold_module_clean(tmp_path):
    """Host callbacks in the I/O shell are the shell's business."""
    pkg = make_pkg(tmp_path, {"runtime/replica.py": """
        import jax

        def drive(states):
            jax.debug.print("x {s}", s=states)
            return states
    """})
    assert [f for f in lint(pkg) if f.rule == "SPMD001"] == []


def test_spmd001_mesh_twin_axis_free_reduction_flagged(tmp_path):
    """ISSUE 13: the ``mesh_`` prefix joins the axis-function contract —
    an axis-free reduction inside a mesh-lifted kernel folds only the
    local shard and must be red."""
    pkg = make_pkg(tmp_path, {"runtime/transition.py": """
        import jax.numpy as jnp

        def mesh_fleet_total(mesh, states):
            return jnp.sum(states)
    """})
    found = [f for f in lint(pkg) if f.rule == "SPMD001"]
    assert len(found) == 1 and "axis-free reduction" in found[0].message


def test_spmd001_mesh_twin_axis_branch_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"runtime/transition.py": """
        def mesh_fleet_step(mesh, states):
            if states.key.shape[0] > 4:
                return states
            return states
    """})
    found = [f for f in lint(pkg) if f.rule == "SPMD001"]
    assert len(found) == 1 and "shard" in found[0].message


def test_spmd001_mesh_rotate_shape_clean(tmp_path):
    """The delivery-plane rotate shape is green: the permutation is
    built from the mesh's static size (no branch), and the per-column
    permute lives in a nested def (traces with its parent)."""
    pkg = make_pkg(tmp_path, {"runtime/transition.py": """
        import jax

        def mesh_plane_rotate(mesh, shift, buffers):
            n = mesh.devices.size
            perm = [(i, (i + shift) % n) for i in range(n)]

            def rotate(tree):
                return jax.tree.map(
                    lambda a: jax.lax.ppermute(a, "replicas", perm), tree
                )

            return rotate(buffers)
    """})
    assert [f for f in lint(pkg) if f.rule == "SPMD001"] == []


def test_sync001_mesh_twin_is_jit_entry_root(tmp_path):
    """ISSUE 13 satellite: the shard_map wrappers live in the
    transition-contract module, so every mesh twin is a SYNC001 jit
    entry root by contract — a host sync snuck into one is red without
    any caller tracing it."""
    pkg = make_pkg(tmp_path, {"runtime/transition.py": """
        import numpy as np

        def mesh_fleet_probe(mesh, states):
            return np.asarray(states)
    """})
    found = [f for f in lint(pkg) if f.rule == "SYNC001"]
    assert len(found) == 1 and "numpy array" in found[0].message


# ----------------------------------------------------------------------
# ISSUE 12 acceptance: the new families catch real-tree regressions
# (engine overlay, working tree untouched)


def test_mutation_fleet_pad_deleted_is_caught():
    """Deleting the pow2 pad at the REAL fleet bucket stack site turns
    the gate red (SHAPE001): exact member counts mint one executable
    per occupancy."""
    rel = f"{PKG}/runtime/fleet.py"
    old = (
        "        lanes = self._lane_tier(n)\n"
        "        sl, real_rows = stack_entry_slices"
    )
    assert old in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            old, "        lanes = n\n        sl, real_rows = stack_entry_slices"
        ),
    )
    assert any(
        f.rule == "SHAPE001" and "stack_entry_slices" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_egress_tree_pad_deleted_is_caught():
    """Deleting the egress tree-group pad (PR 10's review fix) is also
    red — the batched periodic path would recompile per due-set size."""
    rel = f"{PKG}/runtime/fleet.py"
    old = (
        "            leaves = [e.state.leaf for e in items]\n"
        "            leaves += [leaves[0]] * (lanes - len(items))"
    )
    assert old in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(old, "            leaves = [e.state.leaf for e in items]"),
    )
    assert any(
        f.rule == "SHAPE001" and "jit_stack_pytrees" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_rewidened_deferred_closure_is_caught():
    """ISSUE 12 acceptance: re-widening the REAL grouped-commit count
    lambda to capture ``res`` (the PR 9 bug, verbatim) turns the gate
    red (LEAK001) — that bug can never return silently."""
    rel = f"{PKG}/runtime/replica.py"
    old = "            lambda ins=res.n_ins_row, kill=res.n_kill_row: (ins, kill),"
    assert old in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(old, "            lambda: (res.n_ins_row, res.n_kill_row),"),
    )
    assert any(
        f.rule == "LEAK001" and "res" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_rewidened_fleet_counts_factory_is_caught():
    """Same class at the fleet commit seam: the ``counts_for`` factory
    re-widened to read ``res`` inside the parked inner fn is red."""
    rel = f"{PKG}/runtime/fleet.py"
    old = (
        "        def counts_for(lane, ins_rows=res.n_ins_row, kill_rows=res.n_kill_row):\n"
        "            def fn():\n"
        "                if not counts_cell:\n"
        "                    counts_cell.append(\n"
        "                        _TR_DISPATCH_COUNTS.get((ins_rows, kill_rows))\n"
        "                    )"
    )
    assert old in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(old, (
            "        def counts_for(lane):\n"
            "            def fn():\n"
            "                if not counts_cell:\n"
            "                    counts_cell.append(\n"
            "                        _TR_DISPATCH_COUNTS.get((res.n_ins_row, res.n_kill_row))\n"
            "                    )"
        )),
    )
    assert any(
        f.rule == "LEAK001" and "counts_for" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_host_callback_in_transition_is_caught():
    """ISSUE 12 acceptance: a host callback injected into the REAL
    transition module turns the gate red (SPMD001) before the
    mesh-sharding PR would trip over it."""
    rel = f"{PKG}/runtime/transition.py"
    inject = (
        "\n\ndef fleet_debug_probe(states):\n"
        '    jax.debug.print("probe {x}", x=states)\n'
        "    return states\n"
    )
    new = _overlay_lint(rel, lambda s: s + inject)
    assert any(
        f.rule == "SPMD001" and "host callback" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_mesh_kernel_axis_free_reduction_is_caught():
    """ISSUE 13 acceptance: an axis-free reduction injected into the
    REAL mesh-lifted merge twin turns the gate red (SPMD001) — under
    shard_map it would fold only the local shard, a silent semantic
    change the static gate must catch before any parity test runs."""
    rel = f"{PKG}/runtime/transition.py"
    old = "    return _lift(mesh, fleet_merge_rows)(states, slices)"
    assert old in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            old,
            "    gate = states.key.sum()\n"
            "    return _lift(mesh, fleet_merge_rows)(states, slices)",
        ),
    )
    assert any(
        f.rule == "SPMD001" and "axis-free reduction" in f.message
        for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_adhoc_static_lanes_is_caught():
    """A novel ad-hoc static arg at the REAL hash dense-extraction site
    is red (SHAPE002) — static values outside the geometry vocabulary
    mint one executable per value."""
    rel = f"{PKG}/models/hash_store.py"
    old = "    return jit.extract_rows_packed(state, rows, lanes=_dense_lanes(counts))"
    assert old in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            old,
            "    return jit.extract_rows_packed("
            "state, rows, lanes=int(np.asarray(counts).max()) + 1)",
        ),
    )
    assert any(
        f.rule == "SHAPE002" and "lanes=" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_relay_flush_lock_deleted_is_caught():
    """ISSUE 15 acceptance: deleting the relay flush's lock acquisition
    in the REAL replica turns the gate red — the relay pending/counter
    state is replica-lock-guarded, and a lock-free flush is exactly the
    unlocked-counter class LOCK001/RACE hunt for (the relay module's
    state joins the existing thread graph)."""
    rel = f"{PKG}/runtime/replica.py"
    src = (REPO_ROOT / rel).read_text()
    i = src.index("def _relay_flush")
    j = src.index("with self._lock:", i)
    anchor = "with self._lock:"
    mutated = src[:j] + "if True:        " + src[j + len(anchor):]
    new, _, _ = run_lint([REPO_ROOT / PKG], overlay={rel: mutated})
    rules = {f.rule for f in new}
    assert "LOCK001" in rules or "RACE001" in rules, rules


def test_mutation_unguarded_tree_relay_emission_is_caught():
    """ISSUE 15 acceptance: removing the ``has_handlers`` guard on the
    relay flush's TREE_RELAY emission turns the gate red (OBS002) —
    disabled telemetry would rebuild the per-re-emission measurement
    dicts on every flush."""
    rel = f"{PKG}/runtime/replica.py"
    anchor = "if telemetry.has_handlers(telemetry.TREE_RELAY):"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(rel, lambda s: s.replace(anchor, "if True:", 1))
    assert any(
        f.rule == "OBS002" and "TREE_RELAY" in f.message for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_relay_closure_capturing_slice_is_caught():
    """ISSUE 15 acceptance: a relay-flush closure re-widened to capture
    the extracted slice pytree and parked in the drain's deferral list
    turns the gate red (LEAK001) — extraction results hold device
    buffers sliced off the live store generation, the same
    buffer-pinning class as parking a whole MergeRowsResult."""
    rel = f"{PKG}/runtime/replica.py"
    anchor = (
        "                        self._relay_depth_hist[folded] = (\n"
        "                            self._relay_depth_hist.get(folded, 0) + 1\n"
        "                        )\n"
    )
    assert anchor in (REPO_ROOT / rel).read_text()
    inject = anchor + (
        "                    if self._telemetry_defer is not None:\n"
        "                        self._telemetry_defer.append(\n"
        "                            (lambda: sl, lambda _x: None)\n"
        "                        )\n"
    )
    new = _overlay_lint(rel, lambda s: s.replace(anchor, inject, 1))
    assert any(
        f.rule == "LEAK001" and "_relay_flush" in f.message for f in new
    ), "\n".join(f.render() for f in new)


# ----------------------------------------------------------------------
# TRANSFER001/TRANSFER002 — device↔host transfer-boundary audit


TRANSFER_HOT = """
    import jax
    import jax.numpy as jnp
    import numpy as np
    from fixpkg.utils import transfers

    _TR_PROBE = transfers.register("replica.probe")


    def sink(y):
        # keeps the probe handle non-ghost regardless of the body under
        # test — the fixtures probe TRANSFER001 one crossing at a time
        return _TR_PROBE.get(y)


    def ship(x):
        dev = jnp.zeros((4,))
        {body}
"""


_TRANSFER_CASE = iter(range(1 << 20))


def _transfer_lint(tmp_path, body: str) -> list:
    pkg = make_pkg(
        tmp_path / f"case{next(_TRANSFER_CASE)}",
        {"runtime/replica.py": TRANSFER_HOT.format(body=body)},
    )
    return [f for f in lint(pkg) if f.rule.startswith("TRANSFER")]


def test_transfer_raw_device_get_flagged_audited_site_clean(tmp_path):
    new = _transfer_lint(tmp_path, "return jax.device_get(dev)")
    assert rules_of(new) == {"TRANSFER001"}
    assert "route the crossing through an audited transfer site" in new[0].message
    new = _transfer_lint(tmp_path, "return _TR_PROBE.get(dev)")
    assert new == []


def test_transfer_raw_device_put_flagged_audited_put_clean(tmp_path):
    new = _transfer_lint(tmp_path, "return jax.device_put(np.asarray(x))")
    assert rules_of(new) == {"TRANSFER001"}
    new = _transfer_lint(tmp_path, "return _TR_PROBE.put(np.asarray(x))")
    assert new == []


def test_transfer_np_asarray_on_device_value_flagged_host_clean(tmp_path):
    new = _transfer_lint(tmp_path, "return np.asarray(dev)")
    assert rules_of(new) == {"TRANSFER001"}
    assert "unaudited crossing" in new[0].message
    # np.asarray of a host value is host work, not a crossing
    new = _transfer_lint(tmp_path, "return np.asarray([1, 2, 3])")
    assert new == []
    # the audited helper form is the counted path
    new = _transfer_lint(
        tmp_path, "return transfers.audited_get(dev, _TR_PROBE)"
    )
    assert new == []


def test_transfer_item_int_and_iteration_flagged_static_shape_clean(tmp_path):
    new = _transfer_lint(tmp_path, "return dev.item()")
    assert rules_of(new) == {"TRANSFER001"}
    new = _transfer_lint(tmp_path, "return int(dev[0])")
    assert rules_of(new) == {"TRANSFER001"}
    new = _transfer_lint(
        tmp_path, "return [int(v) for v in dev]"
    )
    assert rules_of(new) == {"TRANSFER001"}
    # static shape arithmetic is host metadata, not a crossing
    new = _transfer_lint(tmp_path, "return int(dev.shape[0]) * 2")
    assert new == []


def test_transfer_taint_propagates_and_dies_at_audited_get(tmp_path):
    """Taint flows through assignment chains; an audited fetch kills it,
    so downstream host numpy on the fetched copy stays green."""
    new = _transfer_lint(
        tmp_path,
        "mid = dev * 2\n"
        "        other = mid\n"
        "        return other.tolist()",
    )
    assert rules_of(new) == {"TRANSFER001"}
    new = _transfer_lint(
        tmp_path,
        "host = _TR_PROBE.get(dev * 2)\n"
        "        return host.tolist()",
    )
    assert new == []


def test_transfer_non_hot_module_not_boundary_checked(tmp_path):
    """TRANSFER001 scopes to the hot data-plane leaves — a cold utility
    module may device_get freely (it is not on a ledger-gated path)."""
    pkg = make_pkg(
        tmp_path,
        {"util/helpers.py": """
            import jax

            def peek(x):
                return jax.device_get(x)
        """},
    )
    assert [f for f in lint(pkg) if f.rule == "TRANSFER001"] == []


def test_transfer_ledger_label_hygiene(tmp_path):
    """TRANSFER002 fires on a non-literal label, a duplicate label
    (package-wide), and a ghost handle that audits nothing."""
    pkg = make_pkg(
        tmp_path,
        {
            "runtime/replica.py": """
                from fixpkg.utils import transfers

                _LBL = "replica." + "dyn"
                _TR_DYN = transfers.register(_LBL)
                _TR_DUP = transfers.register("shared.site")
                _TR_GHOST = transfers.register("replica.ghost")


                def use(x):
                    return _TR_DYN.get(_TR_DUP.get(x))
            """,
            "runtime/fleet.py": """
                from fixpkg.utils import transfers

                _TR_ALSO = transfers.register("shared.site")


                def use(x):
                    return _TR_ALSO.get(x)
            """,
        },
    )
    new = [f for f in lint(pkg) if f.rule == "TRANSFER002"]
    msgs = "\n".join(f.message for f in new)
    assert "non-literal label" in msgs
    assert "'shared.site' already registered" in msgs
    assert "ghost label" in msgs
    assert len(new) == 3, msgs


def test_mutation_unshimmed_device_get_in_relay_flush_is_caught():
    """ISSUE 17 acceptance: a raw ``jax.device_get`` snuck into the
    relay flush path of the REAL replica turns the gate red
    (TRANSFER001) — the exact invisible-to-the-ledger crossing class
    the TRANSFER family exists to keep out of the hot modules."""
    rel = f"{PKG}/runtime/replica.py"
    anchor = "                sl = self.model.extract_rows(self.state, jnp.asarray(rows))"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor, anchor + "\n                _dbg = jax.device_get(sl)", 1
        ),
    )
    assert any(
        f.rule == "TRANSFER001" and "device_get" in f.message
        and "_relay_flush" in f.message
        for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_audited_wal_fetch_bypass_is_caught():
    """ISSUE 17 acceptance: routing the WAL-entry fetch around its
    audited site in the REAL replica is doubly red — the raw
    ``jax.device_get`` is an unaudited crossing (TRANSFER001) AND the
    orphaned ``_TR_WAL_ENTRIES`` handle becomes a ghost label
    (TRANSFER002): the ledger would still declare the site while
    counting nothing."""
    rel = f"{PKG}/runtime/replica.py"
    anchor = "        got = _TR_WAL_ENTRIES.get(a)"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel, lambda s: s.replace(anchor, "        got = jax.device_get(a)", 1)
    )
    assert any(f.rule == "TRANSFER001" for f in new), new
    assert any(
        f.rule == "TRANSFER002" and "_TR_WAL_ENTRIES" in f.message
        and "ghost" in f.message
        for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_meshplane_ledger_bypass_is_caught():
    """ISSUE 17 acceptance: shipping the narrow plane's dense bundle
    with a raw ``jax.device_put`` instead of the audited
    ``_TR_SHIP_DENSE`` site turns the gate red (TRANSFER001 +
    TRANSFER002 ghost) — the retirement evidence in the mesh bench
    diffs exactly this site, so an un-audited ship would silently
    zero the before/after story."""
    rel = f"{PKG}/runtime/meshplane.py"
    anchor = "        shipped = _TR_SHIP_DENSE.put(bundle)"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(anchor, "        shipped = jax.device_put(bundle)", 1),
    )
    assert any(
        f.rule == "TRANSFER001" and "device_put" in f.message for f in new
    ), "\n".join(f.render() for f in new)
    assert any(
        f.rule == "TRANSFER002" and "_TR_SHIP_DENSE" in f.message for f in new
    )


def test_mutation_unrestoring_commit_handler_is_caught():
    """ISSUE 20 acceptance: gutting the seq-rollback handler around the
    grouped-entries durability point in the REAL replica turns the gate
    red (FAULT001) — the loop keeps minting ``self._seq += 1`` while an
    injected raise at the fault point would leave the group
    half-advanced with nothing rolling it back."""
    rel = f"{PKG}/runtime/replica.py"
    old = (
        "            except BaseException as e:\n"
        "                self._commit_abort(e)\n"
        "                raise"
    )
    assert old in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            old, "            except BaseException:\n                raise", 1
        ),
    )
    assert any(
        f.rule == "FAULT001" and "_commit_entries_group" in f.message
        for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_publish_before_durable_is_caught():
    """ISSUE 20 acceptance: reordering publication ahead of the WAL
    append in the REAL batched-adds commit path turns the gate red
    (FAULT003) — a crash in the window loses work that lock-free
    readers already observed."""
    rel = f"{PKG}/runtime/replica.py"
    old = (
        "        try:\n"
        "            self._durable_batch(batch, ts)\n"
        "        except BaseException as e:\n"
        "            self._commit_abort(e)\n"
        "            raise\n"
        "        self._note_state_changed(lambda: n_changed, maintained)"
    )
    assert old in (REPO_ROOT / rel).read_text()
    swapped = (
        "        self._note_state_changed(lambda: n_changed, maintained)\n"
        "        try:\n"
        "            self._durable_batch(batch, ts)\n"
        "        except BaseException as e:\n"
        "            self._commit_abort(e)\n"
        "            raise"
    )
    new = _overlay_lint(rel, lambda s: s.replace(old, swapped, 1))
    assert any(
        f.rule == "FAULT003" and "_flush_batch_adds" in f.message
        for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_ghost_fault_site_is_caught():
    """ISSUE 20 acceptance: a SITES vocabulary entry with no faultpoint
    call site is red (FAULT005) — a chaos schedule naming it could
    never trip, so the label set must stay exactly the set of program
    points."""
    rel = f"{PKG}/utils/faults.py"
    old = '    "fleet.loop",'
    assert old in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(old, old + '\n    "ghost.site",', 1),
    )
    assert any(
        f.rule == "FAULT005" and "'ghost.site'" in f.message
        and "ghost" in f.message
        for f in new
    ), "\n".join(f.render() for f in new)


def test_mutation_nonliteral_faultpoint_label_is_caught():
    """Companion FAULT005 leg: a faultpoint whose label is a variable
    (not a string literal) is red — chaos schedules key on statically
    knowable site names."""
    rel = f"{PKG}/runtime/wal.py"
    old = 'faultpoint("wal.rotate")'
    assert old in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel, lambda s: s.replace(old, "faultpoint(_SITE_ROTATE)", 1)
    )
    assert any(
        f.rule == "FAULT005" and "not a string literal" in f.message
        for f in new
    ), "\n".join(f.render() for f in new)


# ----------------------------------------------------------------------
# SUPPRESS003 — allow-comment expiry (ISSUE 20)


def test_expired_allow_still_suppresses_through_suppress003(tmp_path):
    """An expired ``allow[tag expires=...]`` keeps suppressing the
    underlying finding — the gate goes red through ONE actionable
    SUPPRESS003 at the comment, not through the original finding
    popping back up at an unrelated line."""
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def probe(f, x):
                # crdtlint: allow[host-sync expires=2000-01-01] dated
                jax.jit(f)(x).block_until_ready()
                return f
            """,
        },
    )
    new, _baselined, allowed = run_lint([pkg])
    assert rules_of(new) == {"SUPPRESS003"}
    assert "expires=2000-01-01" in new[0].message
    # the original finding routed through the (expired) allow
    assert any(f.rule.startswith("SYNC") for f in allowed)


def test_future_dated_allow_is_quiet(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def probe(f, x):
                # crdtlint: allow[host-sync expires=2999-12-31] dated
                jax.jit(f)(x).block_until_ready()
                return f
            """,
        },
    )
    assert lint(pkg) == []


def test_expired_and_stale_allow_reports_only_suppress003(tmp_path):
    """An expired record's SUPPRESS003 subsumes the staleness complaint
    — one actionable finding per comment, not two."""
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            def f(x):
                return x  # crdtlint: allow[donation expires=2000-01-01] old
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SUPPRESS003"}
    assert len(found) == 1


def test_malformed_expiry_date_fails_closed(tmp_path):
    """A typo'd date (month 13) counts as expired — a guard that can
    never expire because of a typo must surface, not silently live
    forever."""
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def probe(f, x):
                # crdtlint: allow[host-sync expires=2026-13-01] typo
                jax.jit(f)(x).block_until_ready()
                return f
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SUPPRESS003"}
    assert "2026-13-01" in found[0].message
