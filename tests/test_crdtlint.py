"""crdtlint: golden fixtures per rule family + end-to-end over the real
package.

The fixture tests build throwaway mini-packages on disk and assert each
rule family fires on its positive snippet and stays silent on the
negative one. The end-to-end tests run the real CLI over
``delta_crdt_ex_tpu`` (must be clean: zero unsuppressed findings) and —
via the engine's source overlay — re-lint mutated copies of real
modules to prove the pass actually *detects* the bug classes it claims
to (every ``with self._lock:`` deletion in replica.py, an unannotated
``.item()`` in ops/join.py), not just that the tree happens to be
quiet.

Pure-stdlib under test: no jax/numpy import happens in the linter, so
these tests are cheap enough for tier-1.
"""

from __future__ import annotations

import json
import subprocess
import sys
import textwrap
from pathlib import Path

REPO_ROOT = Path(__file__).resolve().parent.parent
if str(REPO_ROOT) not in sys.path:
    sys.path.insert(0, str(REPO_ROOT))

from tools.crdtlint.engine import (  # noqa: E402
    Finding,
    load_baseline,
    run_lint,
    write_baseline,
)

PKG = "delta_crdt_ex_tpu"


def make_pkg(root: Path, modules: dict[str, str]) -> Path:
    """Write a mini-package; keys are slash paths under the package dir
    (e.g. "ops/kern.py"), values module source."""
    pkg = root / "fixpkg"
    pkg.mkdir(parents=True)
    (pkg / "__init__.py").write_text("")
    for rel, src in modules.items():
        path = pkg / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        init = path.parent / "__init__.py"
        if not init.exists():
            init.write_text("")
        path.write_text(textwrap.dedent(src))
    return pkg


def lint(pkg: Path, **kw) -> list[Finding]:
    new, _baselined, _allowed = run_lint([pkg], **kw)
    return new


def rules_of(findings: list[Finding]) -> set[str]:
    return {f.rule for f in findings}


# ----------------------------------------------------------------------
# LOCK001 — lock discipline


LOCKED_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self._stop = threading.Event()

        def put(self, x):
            with self._lock:
                self._items.append(x)

        def {body}
"""


def test_lock_unguarded_public_read_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {"box.py": LOCKED_CLASS.format(body="size(self):\n            return len(self._items)")},
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK001"}
    assert "_items" in found[0].message and "size" in found[0].message


def test_lock_guarded_access_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": LOCKED_CLASS.format(
                body=(
                    "size(self):\n"
                    "            with self._lock:\n"
                    "                return len(self._items)"
                )
            )
        },
    )
    assert lint(pkg) == []


_HELPER_CLASS = """
    import threading

    class Box:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def put(self, x):
            with self._lock:
                self._put(x)

        def _put(self, x):
            self._items.append(x)
"""


def test_lock_private_helper_inherits_caller_lock(tmp_path):
    # a private helper called only under the lock is clean; the same
    # helper reached from a lock-free public path is flagged
    pkg = make_pkg(tmp_path, {"box.py": _HELPER_CLASS})
    assert lint(pkg) == []

    dirty = _HELPER_CLASS + (
        "\n"
        "        def put_fast(self, x):\n"
        "            self._put(x)\n"
    )
    pkg2 = make_pkg(tmp_path / "b", {"box.py": dirty})
    found = lint(pkg2)
    assert rules_of(found) == {"LOCK001"}


def test_lock_thread_entry_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._n = 0
                    self._thread = threading.Thread(target=self._loop)

                def bump(self):
                    with self._lock:
                        self._n += 1

                def _loop(self):
                    while True:
                        print(self._n)
            """
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK001"}
    assert "_loop" in found[0].message


def test_lock_acquire_wrapper_recognised(tmp_path):
    # Replica's _acquire idiom: helper acquires, caller releases
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._items = []

                def _acquire(self):
                    if not self._lock.acquire(timeout=1):
                        raise TimeoutError

                def put(self, x):
                    self._acquire()
                    try:
                        self._items.append(x)
                    finally:
                        self._lock.release()
            """
        },
    )
    assert lint(pkg) == []


def test_lock_threadsafe_attrs_exempt(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import queue
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._q = queue.Queue()
                    self._wake = threading.Event()
                    self._data = {}

                def put(self, x):
                    with self._lock:
                        self._data[x] = x
                        self._q.put(x)

                def poke(self):
                    self._q.put_nowait(None)
                    self._wake.set()
            """
        },
    )
    assert lint(pkg) == []


def test_lock_init_does_not_mint_guards(tmp_path):
    # attributes only ever written in __init__ are pre-publication state
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._name = "box"
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def name(self):
                    return self._name
            """
        },
    )
    assert lint(pkg) == []


# ----------------------------------------------------------------------
# SYNC001 / SYNC002 — host-sync leaks


def test_sync_item_in_jit_reachable_cross_module(tmp_path):
    # entry registered in one module, offending body in another: the
    # rule must walk the import graph, not the file it found jit() in
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            def combine(x, y):
                return x + y

            def fold(x):
                bad = combine(x, x).item()
                return bad
            """,
            "models/model.py": """
            import jax

            from fixpkg.ops import kern

            jit_fold = jax.jit(kern.fold)
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SYNC001"}
    assert found[0].path.endswith("ops/kern.py")


def test_sync_unreachable_function_not_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            def helper(x):
                return x.tolist()
            """,
        },
    )
    assert lint(pkg) == []


def test_sync_int_coercion_flagged_static_shape_exempt(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            import jax

            @jax.jit
            def fold(x):
                n = int(x.shape[0])      # static: fine
                v = int(x.sum())         # traced: host sync
                return n + v
            """,
        },
    )
    found = lint(pkg)
    assert len(found) == 1 and found[0].rule == "SYNC001"
    assert "int()" in found[0].message


def test_sync_np_asarray_and_decorated_partial_jit(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "parallel/mesh.py": """
            from functools import partial

            import jax
            import numpy as np

            @partial(jax.jit, static_argnames=("k",))
            def step(x, k=1):
                return np.asarray(x) + k
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SYNC001"}
    assert "np.asarray" in found[0].message


def test_sync_shard_map_body_reached_via_nested_def(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "parallel/mesh.py": """
            import jax
            from jax import shard_map

            @jax.jit
            def gossip(x):
                def step(local):
                    return local.block_until_ready()
                return shard_map(step, mesh=None, in_specs=None, out_specs=None)(x)
            """,
        },
    )
    assert "SYNC001" in rules_of(lint(pkg))


def test_sync_block_until_ready_in_op_module_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            import jax

            def probe(f, x):
                jax.jit(f)(x).block_until_ready()
                return f
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SYNC002"}


def test_sync_allow_comment_suppresses(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            import jax

            def probe(f, x):
                # crdtlint: allow[host-sync] probe must synchronise by design
                jax.jit(f)(x).block_until_ready()
                return f
            """,
        },
    )
    new, _baselined, allowed = run_lint([pkg])
    assert new == [] and len(allowed) == 1


def test_sync_allow_comment_does_not_bleed_to_next_line(tmp_path):
    # a trailing allow on line N must not suppress a finding on N+1
    pkg = make_pkg(
        tmp_path,
        {
            "ops/kern.py": """
            import jax

            def probe(f, x):
                a = jax.jit(f)(x).block_until_ready()  # crdtlint: allow[host-sync] why
                b = jax.jit(f)(x).block_until_ready()
                return a, b
            """,
        },
    )
    new, _baselined, allowed = run_lint([pkg])
    assert len(allowed) == 1 and len(new) == 1
    assert new[0].rule == "SYNC002"


def test_lock_reentrant_with_does_not_release_outer_hold(tmp_path):
    # RLock reentrancy: an inner `with self._lock:` exiting must not make
    # the rest of the outer critical section look unguarded
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        with self._lock:
                            self._items.append(x)
                        self._items.append(x)
            """
        },
    )
    assert lint(pkg) == []


def test_sync_block_until_ready_outside_op_modules_ignored(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "runtime/driver.py": """
            import jax

            def hibernate(state):
                jax.block_until_ready(state)
            """,
        },
    )
    assert lint(pkg) == []


# ----------------------------------------------------------------------
# PURE001–PURE003 — lattice-op purity


def test_purity_arg_mutation_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/join2.py": """
            def join(local, remote):
                local.ctx = remote.ctx
                return local
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"PURE001"}


def test_purity_mutator_call_flagged_at_indexer_exempt(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "models/m.py": """
            def merge_contexts(a, b):
                out = a.at[0].set(b[0])   # functional jax update: fine
                a.update(b)               # in-place: flagged
                return out
            """,
        },
    )
    found = lint(pkg)
    assert len(found) == 1 and found[0].rule == "PURE001"
    assert "update" in found[0].message


def test_purity_impure_calls_and_global(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/j.py": """
            import random
            import time

            _CACHE = {}

            def delta_of(state):
                global _CACHE
                _CACHE = {}
                return state

            def merge(a, b):
                if random.random() < 0.5:
                    return a
                return b, time.time()
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"PURE002", "PURE003"}
    assert sum(f.rule == "PURE003" for f in found) == 2


def test_purity_scope_limited_to_ops_models(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "runtime/r.py": """
            import time

            def merge(a, b):
                a.x = time.time()
                return a
            """,
        },
    )
    assert lint(pkg) == []


def test_purity_nonmatching_names_ignored(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import time

            def stamp(a):
                return time.time()
            """,
        },
    )
    assert lint(pkg) == []


# ----------------------------------------------------------------------
# DONATE001 — donation hygiene


def test_donation_reuse_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))

            def driver(state):
                out = jit_grow(state)
                return out, state.shape
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"DONATE001"}
    assert "'state'" in found[0].message


def test_donation_rebind_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))

            def driver(state):
                state = jit_grow(state)
                return state
            """,
        },
    )
    assert lint(pkg) == []


def test_donation_cross_module_call_site(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))
            """,
            "runtime/r.py": """
            from fixpkg.ops.k import jit_grow

            def driver(state):
                out = jit_grow(state)
                return out, state
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"DONATE001"}
    assert found[0].path.endswith("runtime/r.py")


def test_lock_conditional_acquire_does_not_leak_held_state(tmp_path):
    # a lock acquired in only one branch is NOT held after the join
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def maybe(self, cond, x):
                    if cond:
                        self._lock.acquire()
                    self._items.append(x)
                    if cond:
                        self._lock.release()
            """
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK001"}


def test_sync_similar_name_helper_not_flagged(tmp_path):
    # SYNC002 must match the exact name, not a substring
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            def safe_block_until_ready(x):
                return x

            def driver(x):
                return safe_block_until_ready(x)
            """,
        },
    )
    assert lint(pkg) == []


def test_donation_early_return_branch_not_flagged(tmp_path):
    # `return state` only runs when the donating branch was NOT taken
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))

            def driver(state, flag):
                if flag:
                    out = jit_grow(state)
                    return out
                return state
            """,
        },
    )
    assert lint(pkg) == []


def test_cli_select_rejects_unknown_rule(tmp_path):
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", PKG, "--select", "SYNC01"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 2 and "unknown rule" in proc.stderr


def test_donation_multiline_call_not_flagged(tmp_path):
    # the donor's own Name node on a continuation line is the donation
    # itself, not a read after the call
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def grow(state):
                return state

            jit_grow = jax.jit(grow, donate_argnums=(0,))

            def driver(state):
                out = jit_grow(
                    state,
                )
                return out
            """,
        },
    )
    assert lint(pkg) == []


def test_sync_same_name_host_function_not_flagged(tmp_path):
    # reachability is keyed by node identity: an untraced host-side
    # function sharing a jit entry's name must not be flagged
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            @jax.jit
            def kernel(x):
                return x + 1

            class HostProbe:
                def kernel(self, x):
                    return x.item()
            """,
        },
    )
    assert lint(pkg) == []


# ----------------------------------------------------------------------
# baseline workflow


def test_baseline_roundtrip_and_count_semantics(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {"box.py": LOCKED_CLASS.format(body="size(self):\n            return len(self._items)")},
    )
    found = lint(pkg)
    assert len(found) == 1
    bl_path = tmp_path / "baseline.json"
    write_baseline(bl_path, found)
    data = json.loads(bl_path.read_text())
    assert data["version"] == 1 and len(data["findings"]) == 1

    # baselined finding no longer reported as new
    new, baselined, _ = run_lint([pkg], baseline=load_baseline(bl_path))
    assert new == [] and len(baselined) == 1

    # a second finding site: the baseline absorbs only what it records
    # (the size() fingerprint); the new peek() site is reported as new
    extra = LOCKED_CLASS.format(
        body=(
            "size(self):\n"
            "            return len(self._items)\n\n"
            "        def peek(self):\n"
            "            return len(self._items)"
        )
    )
    pkg2 = make_pkg(tmp_path / "b", {"box.py": extra})
    new2, baselined2, _ = run_lint([pkg2], baseline=load_baseline(bl_path))
    assert len(new2) + len(baselined2) == 2 and len(baselined2) <= 1


def test_write_baseline_with_select_preserves_other_rules(tmp_path):
    # selective rewrite must carry over accepted debt of unselected rules
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def size(self):
                    return len(self._items)

            def merge(a, b):
                a.update(b)
                return a
            """,
        },
    )
    bl = tmp_path / "bl.json"
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", str(pkg),
         "--baseline", str(bl), "--write-baseline"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    full = load_baseline(bl)
    assert {r for (_p, r, _m) in full} == {"LOCK001", "PURE001"}
    # selective rewrite of just PURE001 must not drop the LOCK001 entry
    proc = subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", str(pkg),
         "--baseline", str(bl), "--select", "PURE001", "--write-baseline"],
        cwd=REPO_ROOT, capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr
    assert {r for (_p, r, _m) in load_baseline(bl)} == {"LOCK001", "PURE001"}


# ----------------------------------------------------------------------
# end-to-end over the real package


def _cli(*argv: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        [sys.executable, "-m", "tools.crdtlint", *argv],
        cwd=REPO_ROOT,
        capture_output=True,
        text=True,
        timeout=120,
    )


def test_e2e_package_is_clean():
    """The tier-1 gate: zero unsuppressed findings on the real tree."""
    proc = _cli(PKG)
    assert proc.returncode == 0, f"crdtlint found:\n{proc.stdout}{proc.stderr}"
    assert "0 finding(s)" in proc.stdout


def test_e2e_list_rules_and_bad_package():
    assert "LOCK001" in _cli("--list-rules").stdout
    assert _cli("no_such_pkg").returncode == 2


def test_e2e_every_lock_deletion_in_replica_is_caught():
    """Acceptance: deleting any single ``with self._lock:`` from
    runtime/replica.py must produce a finding."""
    rel = f"{PKG}/runtime/replica.py"
    src = (REPO_ROOT / rel).read_text()
    lines = src.splitlines(keepends=True)
    sites = [i for i, l in enumerate(lines) if l.strip() == "with self._lock:"]
    assert len(sites) >= 10, "replica.py lost its lock regions?"
    for site in sites:
        mutated = lines[:]
        indent = len(lines[site]) - len(lines[site].lstrip())
        mutated[site] = " " * indent + "if True:\n"
        new, _, _ = run_lint(
            [REPO_ROOT / PKG], overlay={rel: "".join(mutated)}
        )
        assert any(f.rule == "LOCK001" for f in new), (
            f"deleting the lock at replica.py:{site + 1} went undetected"
        )


def test_e2e_unannotated_item_in_join_is_caught():
    """Acceptance: an unannotated .item() in ops/join.py must fail."""
    rel = f"{PKG}/ops/join.py"
    src = (REPO_ROOT / rel).read_text()
    anchor = "    n_killed = jnp.sum((local.alive & ~alive1).astype(jnp.int32))"
    assert anchor in src
    mutated = src.replace(anchor, anchor + "\n    _dbg = n_killed.item()")
    new, _, _ = run_lint([REPO_ROOT / PKG], overlay={rel: mutated})
    assert any(
        f.rule == "SYNC001" and f.path.endswith("ops/join.py") for f in new
    )


def test_e2e_real_tree_clean_via_engine():
    new, _baselined, allowed = run_lint([REPO_ROOT / PKG])
    assert new == []
    # the pallas probe carries exactly one justified allow
    assert any(f.path.endswith("ops/pallas_tree.py") for f in allowed)


# ----------------------------------------------------------------------
# WIRE001–WIRE005 — wire-protocol drift (fixtures)


WIRE_PKG = {
    "runtime/sync.py": """
    import dataclasses


    @dataclasses.dataclass
    class PingMsg:
        frm: str
        seq: int


    @dataclasses.dataclass
    class PongMsg:
        frm: str
        seq: int
    """,
    "runtime/node.py": """
    from fixpkg.runtime import sync


    class Node:
        def handle(self, msg):
            if isinstance(msg, sync.PingMsg):
                pass
            elif isinstance(msg, sync.PongMsg):
                pass
    """,
}


def test_wire_complete_protocol_clean(tmp_path):
    pkg = make_pkg(tmp_path, WIRE_PKG)
    assert lint(pkg) == []


def test_wire_unhandled_message_flagged(tmp_path):
    mods = dict(WIRE_PKG)
    mods["runtime/sync.py"] += (
        "\n\n    @dataclasses.dataclass\n    class LostMsg:\n        frm: str\n"
    )
    pkg = make_pkg(tmp_path, mods)
    found = lint(pkg)
    assert rules_of(found) == {"WIRE001"}
    assert "LostMsg" in found[0].message


def test_wire_duplicate_and_ghost_arms_flagged(tmp_path):
    mods = dict(WIRE_PKG)
    mods["runtime/node.py"] = """
    from fixpkg.runtime import sync


    class Node:
        def handle(self, msg):
            if isinstance(msg, sync.PingMsg):
                pass
            elif isinstance(msg, sync.PongMsg):
                pass
            elif isinstance(msg, sync.PingMsg):
                pass
            elif isinstance(msg, sync.GhostMsg):
                pass
    """
    pkg = make_pkg(tmp_path, mods)
    found = lint(pkg)
    assert rules_of(found) == {"WIRE002"}
    msgs = " | ".join(f.message for f in found)
    assert "already handled" in msgs and "missing" in msgs


def test_wire_unserializable_field_flagged(tmp_path):
    mods = dict(WIRE_PKG)
    mods["runtime/sync.py"] = """
    import dataclasses
    import threading
    from typing import Callable


    @dataclasses.dataclass
    class PingMsg:
        frm: str
        notify: Callable


    @dataclasses.dataclass
    class PongMsg:
        frm: str
        gate: threading.Lock
    """
    pkg = make_pkg(tmp_path, mods)
    found = [f for f in lint(pkg) if f.rule == "WIRE003"]
    assert len(found) == 2
    assert "Callable" in found[0].message and "Lock" in found[1].message


def test_wire_frame_kind_sent_but_not_decoded(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "runtime/codec.py": """
            _MSG = 0
            _PING = 1
            _LOST = 2


            def _send_frame(sock, kind, payload):
                sock.sendall(bytes([kind]) + payload)


            def client(sock):
                _send_frame(sock, _MSG, b"x")
                _send_frame(sock, _PING, b"")
                _send_frame(sock, _LOST, b"?")


            def serve(sock, kind, payload):
                if kind == _MSG:
                    return payload
                elif kind == _PING:
                    return b"pong"
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"WIRE004"}
    assert "_LOST" in found[0].message


def test_wire_manifest_drift_flagged(tmp_path):
    from tools.crdtlint.rules.wire import write_manifest

    pkg = make_pkg(tmp_path, WIRE_PKG)
    manifest = tmp_path / "manifest.json"
    # recorded manifest: PingMsg with the OLD field list, PongMsg absent
    write_manifest(manifest, {
        "fixpkg": {
            "module": "fixpkg/runtime/sync.py",
            "messages": {
                "PingMsg": {"fields": [["frm", "str"]], "sha256": "stale"},
                "GoneMsg": {"fields": [], "sha256": "x"},
            },
        },
    })
    found = [
        f for f in lint(pkg, manifest=manifest) if f.rule == "WIRE005"
    ]
    msgs = " | ".join(f.message for f in found)
    assert "PingMsg" in msgs and "drifted" in msgs        # hash mismatch
    assert "PongMsg" in msgs and "not in the protocol" in msgs
    assert "GoneMsg" in msgs and "no longer defined" in msgs


def test_wire_manifest_in_sync_clean(tmp_path):
    from tools.crdtlint.engine import Project
    from tools.crdtlint.rules.wire import compute_manifest, write_manifest

    pkg = make_pkg(tmp_path, WIRE_PKG)
    manifest = tmp_path / "manifest.json"
    write_manifest(manifest, {"fixpkg": compute_manifest(Project(pkg))})
    assert lint(pkg, manifest=manifest) == []


# ----------------------------------------------------------------------
# LOCK002 / LOCK003 — lock order + blocking under lock (fixtures)


def test_lockorder_inverted_pair_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        with self._b:
                            pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK002"}
    assert "deadlock" in found[0].message


def test_lockorder_consistent_order_clean(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def one(self):
                    with self._a:
                        with self._b:
                            pass

                def two(self):
                    with self._a:
                        with self._b:
                            pass
            """,
        },
    )
    assert lint(pkg) == []


def test_lockorder_three_lock_rotation_cycle_flagged(tmp_path):
    # no inverted PAIR anywhere — the deadlock is the 3-cycle a->b->c->a
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()
                    self._c = threading.Lock()

                def ab(self):
                    with self._a:
                        with self._b:
                            pass

                def bc(self):
                    with self._b:
                        with self._c:
                            pass

                def ca(self):
                    with self._c:
                        with self._a:
                            pass
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK002"}
    assert "_a" in found[0].message and "_c" in found[0].message


def test_lockorder_interprocedural_held_state_edge(tmp_path):
    # the second lock is taken in a helper that is only ever CALLED with
    # the first held — the edge must come from the propagated entry
    # state, not the helper's lexical context
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._a = threading.Lock()
                    self._b = threading.Lock()

                def forward(self):
                    with self._a:
                        self._grab_b()

                def _grab_b(self):
                    with self._b:
                        pass

                def backward(self):
                    with self._b:
                        with self._a:
                            pass
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK002"}


def test_lockorder_reentrant_rlock_not_a_cycle(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.RLock()
                    self._items = []

                def outer(self):
                    with self._lock:
                        self._inner()

                def _inner(self):
                    with self._lock:
                        self._items.append(1)
            """,
        },
    )
    assert lint(pkg) == []


def test_blocking_under_lock_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import os
            import time
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._fd = 3

                def slow(self):
                    with self._lock:
                        time.sleep(1.0)

                def sync(self):
                    with self._lock:
                        os.fsync(self._fd)

                def fine(self):
                    time.sleep(1.0)
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK003"}
    assert len(found) == 2  # slow() + sync(); fine() holds nothing


def test_blocking_via_constructed_member_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "wal.py": """
            import os


            class Wal:
                def __init__(self, fd):
                    self._fd = fd

                def commit(self):
                    self._write_out()

                def _write_out(self):
                    os.fsync(self._fd)
            """,
            "rep.py": """
            import threading

            from fixpkg.wal import Wal


            class Rep:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._wal = Wal(3)

                def mutate(self):
                    with self._lock:
                        self._wal.commit()
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK003"}
    assert "via Wal.commit" in found[0].message
    assert found[0].path.endswith("rep.py")


def test_blocking_via_module_import_constructed_member(tmp_path):
    # `self._wal = wal.Wal(...)` — constructor through a MODULE import
    # must resolve like the from-import form
    pkg = make_pkg(
        tmp_path,
        {
            "wal.py": """
            import os


            class Wal:
                def __init__(self, fd):
                    self._fd = fd

                def commit(self):
                    os.fsync(self._fd)
            """,
            "rep.py": """
            import threading

            from fixpkg import wal


            class Rep:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._wal = wal.Wal(3)

                def mutate(self):
                    with self._lock:
                        self._wal.commit()
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK003"}
    assert "via Wal.commit" in found[0].message


def test_wire_malformed_manifest_is_a_finding_not_a_crash(tmp_path):
    pkg = make_pkg(tmp_path, WIRE_PKG)
    manifest = tmp_path / "manifest.json"
    manifest.write_text('{"version": 1, "packages": null}\n')
    found = lint(pkg, manifest=manifest)
    assert rules_of(found) == {"WIRE005"}
    assert "malformed" in found[0].message


def test_blocking_thread_join_receiver_typed(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "box.py": """
            import threading


            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._t = threading.Thread(target=self._run)

                def _run(self):
                    pass

                def stop_bad(self):
                    with self._lock:
                        self._t.join()

                def stop_good(self):
                    self._t.join()

                def strings_fine(self):
                    with self._lock:
                        return ", ".join(["a", "b"])
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"LOCK003"}
    assert len(found) == 1 and "Thread.join" in found[0].message


# ----------------------------------------------------------------------
# WAL001 / WAL002 — record-kind exhaustiveness (fixtures)


WAL_PKG = {
    "wal.py": """
    class Log:
        def append_batch(self, seq, ops):
            self._stage({"kind": "batch", "seq": seq, "ops": ops})

        def append_slice(self, seq, arrays):
            self._stage({"kind": "entries", "seq": seq, "arrays": arrays})

        def _stage(self, rec):
            pass
    """,
    "rep.py": """
    class Rep:
        def _replay(self, records):
            for rec in records:
                if rec["kind"] == "batch":
                    pass
                elif rec["kind"] == "entries":
                    pass

        def _scan_log_rows(self, records):
            for rec in records:
                kind = rec.get("kind")
                if kind == "batch":
                    pass
                elif kind == "entries":
                    pass
    """,
}


def test_wal_kinds_covered_clean(tmp_path):
    pkg = make_pkg(tmp_path, WAL_PKG)
    assert lint(pkg) == []


def test_wal_new_kind_must_reach_both_dispatchers(tmp_path):
    mods = dict(WAL_PKG)
    mods["wal.py"] += (
        "\n"
        "        def append_clear(self, seq):\n"
        '            self._stage({"kind": "clear", "seq": seq})\n'
    )
    pkg = make_pkg(tmp_path, mods)
    found = lint(pkg)
    assert rules_of(found) == {"WAL001", "WAL002"}
    assert all("'clear'" in f.message for f in found)


def test_wal_membership_classification_counts(tmp_path):
    # `kind in ("a", "b")` is an explicit classification, same as ==
    mods = dict(WAL_PKG)
    mods["wal.py"] += (
        "\n"
        "        def append_clear(self, seq):\n"
        '            self._stage({"kind": "clear", "seq": seq})\n'
    )
    mods["rep.py"] = """
    class Rep:
        def _replay(self, records):
            for rec in records:
                if rec["kind"] in ("batch", "entries", "clear"):
                    pass

        def _scan_log_rows(self, records):
            for rec in records:
                kind = rec.get("kind")
                if kind in ("clear",):
                    pass  # explicit barrier
                elif kind == "batch":
                    pass
                elif kind == "entries":
                    pass
    """
    pkg = make_pkg(tmp_path, mods)
    assert lint(pkg) == []


def test_wal_missing_replay_dispatcher_flagged(tmp_path):
    pkg = make_pkg(tmp_path, {"wal.py": WAL_PKG["wal.py"]})
    found = lint(pkg)
    assert rules_of(found) == {"WAL001", "WAL002"}
    assert any("no recovery replay" in f.message for f in found)


# ----------------------------------------------------------------------
# SUPPRESS001 / SUPPRESS002 — stale-suppression hygiene


def test_stale_allow_comment_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def probe(f, x):
                # crdtlint: allow[host-sync] probe must synchronise
                jax.jit(f)(x).block_until_ready()
                y = x  # crdtlint: allow[donation] nothing donated here
                return f, y
            """,
        },
    )
    found = lint(pkg)
    assert rules_of(found) == {"SUPPRESS001"}
    assert "allow[donation]" in found[0].message


def test_stale_baseline_entry_flagged(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {"box.py": LOCKED_CLASS.format(body="size(self):\n            return len(self._items)")},
    )
    baseline = {
        ("fixpkg/box.py", "LOCK001", "long-gone finding message"): 1,
    }
    found = [f for f in lint(pkg, baseline=baseline) if f.rule == "SUPPRESS002"]
    assert len(found) == 1
    assert "long-gone finding message" in found[0].message


def test_hygiene_skipped_under_select(tmp_path):
    # a --select run cannot distinguish stale from not-run: no SUPPRESS
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            def f(x):
                return x  # crdtlint: allow[purity] speculative
            """,
        },
    )
    assert lint(pkg, select={"LOCK001"}) == []
    assert rules_of(lint(pkg)) == {"SUPPRESS001"}


def test_multiline_justification_comment_projects_past_continuation(tmp_path):
    pkg = make_pkg(
        tmp_path,
        {
            "ops/k.py": """
            import jax

            def probe(f, x):
                # crdtlint: allow[host-sync] the justification of this
                # probe spans several comment lines before the call
                jax.jit(f)(x).block_until_ready()
                return f
            """,
        },
    )
    new, _baselined, allowed = run_lint([pkg])
    assert new == [] and len(allowed) == 1


# ----------------------------------------------------------------------
# mutation tests — every new rule family proves it turns the gate red
# on the REAL tree (engine overlay, working tree untouched)


def _overlay_lint(rel: str, mutate) -> list[Finding]:
    src = (REPO_ROOT / rel).read_text()
    new, _, _ = run_lint([REPO_ROOT / PKG], overlay={rel: mutate(src)})
    return new


def test_mutation_deleted_dispatch_arm_is_caught():
    """Acceptance: deleting a dispatch arm in replica.py turns the gate
    red (WIRE001: the message is no longer handled anywhere)."""
    rel = f"{PKG}/runtime/replica.py"
    arm = (
        "            elif isinstance(msg, sync_proto.GetLogMsg):\n"
        "                self._handle_get_log(msg)\n"
    )
    assert arm in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(rel, lambda s: s.replace(arm, ""))
    assert any(
        f.rule == "WIRE001" and "GetLogMsg" in f.message for f in new
    )


def test_mutation_unserializable_ackmsg_field_is_caught():
    """Acceptance: adding an unserializable field to AckMsg turns the
    gate red (WIRE003 type check + WIRE005 manifest drift)."""
    rel = f"{PKG}/runtime/sync.py"
    anchor = "    clear_addr: Hashable"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel, lambda s: s.replace(anchor, anchor + "\n    waiter: 'threading.Event'")
    )
    assert any(f.rule == "WIRE003" and "AckMsg" in f.message for f in new)
    assert any(f.rule == "WIRE005" and "AckMsg" in f.message for f in new)


def test_mutation_reordered_wire_fields_is_caught():
    """Acceptance: reordering DiffMsg fields without bumping the
    manifest turns the gate red (WIRE005 — order is wire contract)."""
    rel = f"{PKG}/runtime/sync.py"
    src = (REPO_ROOT / rel).read_text()
    a = "    originator: Hashable\n    frm: Hashable\n"
    assert a in src
    new = _overlay_lint(
        rel, lambda s: s.replace(a, "    frm: Hashable\n    originator: Hashable\n", 1)
    )
    assert any(f.rule == "WIRE005" and "DiffMsg" in f.message for f in new)


def test_mutation_undecoded_frame_kind_is_caught():
    """A frame kind sent by the TCP codec without a receive-path decode
    arm turns the gate red (WIRE004)."""
    rel = f"{PKG}/runtime/tcp_transport.py"
    new = _overlay_lint(
        rel,
        lambda s: s.replace("_MSGB = 5", "_MSGB = 5\n_TRACE = 7").replace(
            '_send_frame(sock, _PING, b"")',
            '_send_frame(sock, _TRACE, b"");  _send_frame(sock, _PING, b"")',
            1,
        ),
    )
    assert any(f.rule == "WIRE004" and "_TRACE" in f.message for f in new)


def test_mutation_inverted_lock_pair_is_caught():
    """Acceptance: an inverted lock-acquisition pair in replica.py turns
    the gate red (LOCK002)."""
    rel = f"{PKG}/runtime/replica.py"
    probe = (
        "\n"
        "    def probe_setup(self):\n"
        "        self._probe_lock = threading.Lock()\n"
        "\n"
        "    def probe_forward(self):\n"
        "        with self._lock:\n"
        "            with self._probe_lock:\n"
        "                pass\n"
        "\n"
        "    def probe_backward(self):\n"
        "        with self._probe_lock:\n"
        "            with self._lock:\n"
        "                pass\n"
    )

    def mutate(s: str) -> str:
        cls_end = s.rindex("\n    def stop(self)")
        tail_end = s.index("self.transport.unregister(self.name)", cls_end)
        tail_end = s.index("\n", tail_end) + 1
        return s[:tail_end] + probe + s[tail_end:]

    new = _overlay_lint(rel, mutate)
    assert any(f.rule == "LOCK002" for f in new)


def test_mutation_invented_wal_kind_is_caught():
    """Acceptance: a WAL record kind written by a producer without
    replay/serving arms turns the gate red (WAL001 + WAL002)."""
    rel = f"{PKG}/runtime/replica.py"
    anchor = '"kind": "entries",'
    src = (REPO_ROOT / rel).read_text()
    assert anchor in src
    new = _overlay_lint(
        rel, lambda s: s.replace(anchor, '"kind": "tombstone",', 1)
    )
    assert any(f.rule == "WAL001" and "'tombstone'" in f.message for f in new)
    assert any(f.rule == "WAL002" and "'tombstone'" in f.message for f in new)


def test_mutation_host_sync_in_fleet_transition_is_caught():
    """Acceptance (ISSUE 6): an injected host sync in the fleet's pure
    batched-transition path turns the gate red (SYNC001) — every
    function in ``runtime/transition.py`` is a jit entry root by
    contract, so the leak is caught even with no caller jit-wrapping
    the mutated function."""
    rel = f"{PKG}/runtime/transition.py"
    anchor = "    return jax.vmap(binned_ops.merge_rows)(states, slices)"
    assert anchor in (REPO_ROOT / rel).read_text()
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor, "    _n = states.fill.sum().item()\n" + anchor, 1
        ),
    )
    assert any(
        f.rule == "SYNC001" and f.path.endswith("runtime/transition.py")
        for f in new
    )
    # int() coercion of a traced value is the same leak class
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            anchor, "    _n = int(states.fill.sum())\n" + anchor, 1
        ),
    )
    assert any(
        f.rule == "SYNC001" and f.path.endswith("runtime/transition.py")
        for f in new
    )


def test_mutation_impure_fleet_transition_is_caught():
    """An in-place argument mutation (PURE001) or a clock read
    (PURE003) injected into the fleet merge transition turns the gate
    red — the vmapped lattice ops are purity-scoped like ops/ and
    models/ joins."""
    rel = f"{PKG}/runtime/transition.py"
    anchor = "    return jax.vmap(binned_ops.merge_rows)(states, slices)"
    new = _overlay_lint(
        rel,
        lambda s: s.replace(anchor, "    states.key = slices\n" + anchor, 1),
    )
    assert any(f.rule == "PURE001" for f in new)
    new = _overlay_lint(
        rel,
        lambda s: s.replace(anchor, "    _t = time.time()\n" + anchor, 1),
    )
    assert any(f.rule == "PURE003" for f in new)


def test_mutation_stale_allow_is_caught():
    """A freshly stale allow comment (rule fixed, comment left behind)
    turns the gate red (SUPPRESS001)."""
    rel = f"{PKG}/runtime/wal.py"
    new = _overlay_lint(
        rel,
        lambda s: s.replace(
            "import dataclasses",
            "import dataclasses  # crdtlint: allow[purity] speculative",
            1,
        ),
    )
    assert any(
        f.rule == "SUPPRESS001" and f.path.endswith("runtime/wal.py")
        for f in new
    )
