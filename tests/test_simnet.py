"""Convergence under adversarial delivery schedules.

Property: for ANY seeded delivery order — reordered, duplicated, dropped
— replicas converge to the same map, equal to the per-key LWW resolution
of all surviving writes. This is the deterministic-scheduler analog of a
race detector (SURVEY §5.2): merge commutativity, idempotence, and
retry-on-drop are each exercised by a fault class.
"""

import pytest

pytest.importorskip("hypothesis")  # collection must degrade gracefully without it
from hypothesis import given, settings, strategies as st

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from delta_crdt_ex_tpu.runtime.clock import LogicalClock
from delta_crdt_ex_tpu.runtime.simnet import SimNetwork


def build(n_replicas, seed, drop, dup):
    net = SimNetwork(seed=seed, drop_rate=drop, dup_rate=dup)
    clock = LogicalClock()
    reps = [
        start_link(
            AWLWWMap,
            threaded=False,
            transport=net,
            clock=clock,
            capacity=128,
            tree_depth=5,
            max_sync_size=6,
            sync_timeout=0.0,  # lossy schedule: re-arm in-flight slots every tick
        )
        for _ in range(n_replicas)
    ]
    for r in reps:
        r.set_neighbours(reps)
    net.step()
    return net, reps


@settings(max_examples=15, deadline=None)
@given(
    st.integers(min_value=0, max_value=10_000),  # schedule seed
    st.lists(
        st.tuples(
            st.integers(min_value=0, max_value=2),  # writer
            st.sampled_from(["add", "add", "remove", "clear"]),
            st.integers(min_value=1, max_value=6),  # key
            st.integers(min_value=0, max_value=50),  # value
        ),
        max_size=25,
    ),
)
def test_convergence_under_reordered_and_duplicated_delivery(seed, script):
    """With interleaved partial sync a sequential dict is NOT the right
    oracle (a remove only kills *observed* dots — add-wins), so the
    asserted property is the CRDT one: all replicas converge to the same
    map, and every surviving value is some value actually written to that
    key."""
    net, reps = build(3, seed, drop=0.0, dup=0.3)
    writes: dict = {}
    for who, op, key, val in script:
        if op == "add":
            reps[who].mutate("add", [key, val])
            writes.setdefault(key, set()).add(val)
        elif op == "remove":
            reps[who].mutate("remove", [key])
        else:
            reps[who].mutate("clear", [])
        if net.rng.random() < 0.5:
            net.run(reps, rounds=1)
    net.run(reps, rounds=50)
    while net.pending:  # drain in-flight protocol tails without new ticks
        net.step()
    reads = [r.read() for r in reps]
    assert reads[0] == reads[1] == reads[2]
    for key, val in reads[0].items():
        assert val in writes[key]


@settings(max_examples=10, deadline=None)
@given(st.integers(min_value=0, max_value=10_000))
def test_convergence_despite_message_drops(seed):
    net, reps = build(3, seed, drop=0.25, dup=0.1)
    for i in range(12):
        reps[i % 3].mutate("add", [f"k{i}", i])
        net.run(reps, rounds=1)
    reps[0].mutate("remove", ["k0"])
    # drops only delay convergence; periodic re-sync heals every loss
    net.run(reps, rounds=120)
    net.drop_rate = 0.0  # final quiesce without loss
    net.run(reps, rounds=15)
    want = {f"k{i}": i for i in range(1, 12)}
    reads = [r.read() for r in reps]
    assert reads[0] == reads[1] == reads[2] == want
