"""Diff change-feed tests — ports of ``test/delta_subscriber_test.exs``.

Covers the reference's ``on_diffs`` emission rules:
- callback as a plain function and as the (fn, extra_args) tuple form
  (the reference's MFA shape, ``causal_crdt.ex:361-381``);
- no-op writes are silent (``delta_subscriber_test.exs:23-24``);
- ``add k, nil`` emits a ``("remove", k)`` diff (``:26-27``);
- diffs bundle per sync round (``:49-77``);
- replaying the diff stream reconstructs the map (property test,
  ``:79-133``).
"""

import random

from delta_crdt_ex_tpu import AWLWWMap
from delta_crdt_ex_tpu.api import start_link
from tests.conftest import converge


def mk(transport, clock, **opts):
    opts.setdefault("capacity", 64)
    opts.setdefault("tree_depth", 6)
    return start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock, **opts
    )


def test_on_diffs_as_function(transport, shared_clock):
    seen = []
    c = mk(transport, shared_clock, on_diffs=seen.append)
    c.mutate("add", ["Derek", "Kraan"])
    assert seen == [[("add", "Derek", "Kraan")]]
    c.mutate("remove", ["Derek"])
    assert seen == [[("add", "Derek", "Kraan")], [("remove", "Derek")]]


def test_on_diffs_as_mfa_tuple(transport, shared_clock):
    """The reference's {m, f, a} form: extra args are prepended
    (``causal_crdt.ex:363-366``)."""
    seen = []

    def recorder(tag, diffs):
        seen.append((tag, diffs))

    c = mk(transport, shared_clock, on_diffs=(recorder, ["tagged"]))
    c.mutate("add", ["Derek", "Kraan"])
    assert seen == [("tagged", [("add", "Derek", "Kraan")])]


def test_noop_write_emits_no_diff(transport, shared_clock):
    """Re-adding an existing key/value pair changes dots but not the read
    value — the user callback stays silent (``delta_subscriber_test.exs:23-24``)."""
    seen = []
    c = mk(transport, shared_clock, on_diffs=seen.append)
    c.mutate("add", ["Derek", "Kraan"])
    c.mutate("add", ["Derek", "Kraan"])
    assert seen == [[("add", "Derek", "Kraan")]]


def test_add_nil_value_emits_remove_diff(transport, shared_clock):
    """``add(k, nil)`` reads as absent, so the diff is a remove
    (``delta_subscriber_test.exs:26-27``)."""
    seen = []
    c = mk(transport, shared_clock, on_diffs=seen.append)
    c.mutate("add", ["Derek", "Kraan"])
    c.mutate("add", ["Derek", None])
    assert seen == [[("add", "Derek", "Kraan")], [("remove", "Derek")]]


def test_remove_of_absent_key_is_silent(transport, shared_clock):
    seen = []
    c = mk(transport, shared_clock, on_diffs=seen.append)
    c.mutate("remove", ["never-added"])
    assert seen == []


def test_diffs_bundle_per_sync_round(transport, shared_clock):
    """Remote deltas arriving in one sync round land in ONE callback
    invocation (``delta_subscriber_test.exs:49-77``)."""
    seen = []
    c1 = mk(transport, shared_clock)
    c2 = mk(transport, shared_clock, on_diffs=seen.append)
    for i in range(8):
        c1.mutate_async("add", [f"k{i}", i])
    c1.flush()
    c1.set_neighbours([c2])
    converge(transport, [c1, c2])
    assert len(seen) >= 1
    flat = [d for bundle in seen for d in bundle]
    assert sorted(flat) == sorted(("add", f"k{i}", i) for i in range(8))
    # bundling: far fewer callback invocations than diffs
    assert len(seen) < len(flat)


def test_replaying_diffs_reconstructs_map(transport, shared_clock):
    """Property (``delta_subscriber_test.exs:79-133``): a subscriber that
    folds the diff stream into a plain dict ends up with exactly the
    replica's read() after convergence."""
    rng = random.Random(7)
    replay: dict = {}

    def apply_diffs(diffs):
        for d in diffs:
            if d[0] == "add":
                replay[d[1]] = d[2]
            else:
                replay.pop(d[1], None)

    c1 = mk(transport, shared_clock, capacity=256)
    c2 = mk(transport, shared_clock, capacity=256, on_diffs=apply_diffs)
    c1.set_neighbours([c2])
    c2.set_neighbours([c1])

    keys = [f"key-{i}" for i in range(12)]
    for step in range(60):
        k = rng.choice(keys)
        writer = rng.choice([c1, c2])
        if rng.random() < 0.7:
            writer.mutate("add", [k, rng.randrange(1000)])
        else:
            writer.mutate("remove", [k])
        if step % 10 == 9:
            converge(transport, [c1, c2])
    converge(transport, [c1, c2])

    assert c1.read() == c2.read()
    assert replay == c2.read()
