"""Property suite: random multi-writer interval streams vs the pyref
oracle (VERDICT r1 missing #5 — the wire-format analog of the
reference's ``aw_lww_map_property_test.exs:18-76`` op-level property
suite). Lives in its own module so a missing ``hypothesis`` skips ONLY
the property tests — the seeded delta-interval suite in
``test_interval_merge.py`` still runs.
"""

import numpy as np
import jax.numpy as jnp
import pytest

pytest.importorskip("hypothesis")  # collection must degrade gracefully without it
from hypothesis import given, settings, strategies as st

from delta_crdt_ex_tpu.models.binned_map import BinnedAWLWWMap as M
from delta_crdt_ex_tpu.ops.binned import RowSlice
from tests.kernel_harness import BinnedKernelMap
from tests.test_interval_merge import L

from delta_crdt_ex_tpu.utils.pyref import PyAWLWWMap

BUCKET = 5
WRITER_GIDS = (101, 202, 303)


def _pow2(n, floor=1):
    k = floor
    while k < n:
        k *= 2
    return k


def multi_slice(entries, lo, hi, gid):
    """Single-writer single-bucket RowSlice, pow2-padded in S to bound
    jit recompiles. ``entries`` = [(key, valh, ts, ctr)]; interval (lo, hi]."""
    s = _pow2(max(len(entries), 1))
    sl = dict(
        rows=np.asarray([BUCKET], np.int32),
        key=np.zeros((1, s), np.uint64),
        valh=np.zeros((1, s), np.uint32),
        ts=np.zeros((1, s), np.int64),
        node=np.zeros((1, s), np.int32),
        ctr=np.zeros((1, s), np.uint32),
        alive=np.zeros((1, s), bool),
        ctx_rows=np.asarray([[hi]], np.uint32),
        ctx_lo=np.asarray([[lo]], np.uint32),
        ctx_gid=np.array([gid], np.uint64),
    )
    for j, (key, valh, ts, ctr) in enumerate(entries):
        sl["key"][0, j] = key
        sl["valh"][0, j] = valh
        sl["ts"][0, j] = ts
        sl["ctr"][0, j] = ctr
        sl["alive"][0, j] = True
    return RowSlice(**{k: jnp.asarray(v) for k, v in sl.items()})


@st.composite
def interval_scenario(draw):
    """Per-writer event timelines plus a randomly ordered message stream.

    Each writer's timeline is a sequence of add/remove ops over a 4-key
    space (all keys land in bucket ``BUCKET``). Messages are (writer,
    T, lo, hi) delta-intervals snapshotted at timeline position T —
    in-order, stale, duplicated, overlapping, empty (lo == hi), gapped
    (lo above the receiver's horizon) and state-form (lo == 0) all arise
    from the draw.
    """
    n_writers = draw(st.integers(1, 3))
    timelines = []
    for w in range(n_writers):
        n_ev = draw(st.integers(0, 6))
        evs = [
            (draw(st.sampled_from(["add", "remove"])), draw(st.integers(0, 3)))
            for _ in range(n_ev)
        ]
        timelines.append(evs)
    msgs = []
    for w, evs in enumerate(timelines):
        n_msgs = draw(st.integers(0, 5))
        for _ in range(n_msgs):
            t = draw(st.integers(0, len(evs)))
            minted = sum(1 for e in evs[:t] if e[0] == "add")
            hi = draw(st.integers(0, minted))
            lo = draw(st.integers(0, hi))
            msgs.append((w, t, lo, hi))
    msgs = draw(st.permutations(msgs)) if msgs else []
    return timelines, msgs


def _writer_history(w, evs):
    """alive[t] = {ctr: (key, valh, ts)} after the first t events; ctr is
    minted per add (1-based), ts unique across all writers."""
    alive = {}
    out = [dict(alive)]
    ctr = 0
    for i, (op, kidx) in enumerate(evs):
        key = BUCKET + kidx * L
        if op == "add":
            ctr += 1
            # remove-delta ⊔ add-delta: an add supersedes the key's old dots
            alive = {c: e for c, e in alive.items() if e[0] != key}
            alive[ctr] = (key, 1 + w * 100 + i, 1 + w * 1000 + i)
        else:
            alive = {c: e for c, e in alive.items() if e[0] != key}
        out.append(dict(alive))
    return out


@settings(max_examples=200, deadline=None)
@given(interval_scenario())
def test_interval_streams_match_oracle(scenario):
    timelines, msgs = scenario
    histories = [_writer_history(w, evs) for w, evs in enumerate(timelines)]
    b = BinnedKernelMap(11)
    oracle = PyAWLWWMap()  # compressed (state-form) receiver context

    def deliver(w, t, lo, hi):
        gid = WRITER_GIDS[w]
        snap = histories[w][t]
        entries = [
            (key, valh, ts, c) for c, (key, valh, ts) in sorted(snap.items()) if lo < c <= hi
        ]
        sl = multi_slice(entries, lo, hi, gid)
        gap = hi > lo and oracle.dots.get(gid, 0) < lo
        if gap:
            with pytest.raises(ValueError, match="not contiguous"):
                b.merge_slice(sl)
            res = M.merge_slice(b.state, sl, kill_budget=4)
            assert bool(res.need_ctx_gap) and not bool(res.ok)
            return oracle  # receiver state unchanged
        b.merge_slice(sl)
        delta = PyAWLWWMap(
            dots={(gid, c) for c in range(lo + 1, hi + 1)},
            value={},
            compressed=False,
        )
        for key, valh, ts, c in entries:
            delta.value.setdefault(key, {})[(valh, ts)] = {(gid, c)}
        keys = set(oracle.value) | set(delta.value)
        return oracle.join(delta, keys)

    for w, t, lo, hi in msgs:
        oracle = deliver(w, t, lo, hi)
        assert b.read() == oracle.read()
        assert b.ctx() == {g: c for g, c in oracle.dots.items() if c}

    # convergence: final full-state (lo=0) slice from every writer
    for w, evs in enumerate(timelines):
        minted = sum(1 for e in evs if e[0] == "add")
        oracle = deliver(w, len(evs), 0, minted)
    final = {}
    for w, evs in enumerate(timelines):
        for c, (key, valh, ts) in histories[w][len(evs)].items():
            if key not in final or final[key][1] < ts:
                final[key] = (valh, ts)
    assert b.read() == {k: v for k, (v, _ts) in final.items()}
    assert b.read() == oracle.read()
