"""North-star benchmark: 1M-key AWLWWMap, 64-neighbour batched anti-entropy.

Measures **merges/sec**: one merge = joining a 512-entry delta-interval
slice into a 1M-key replica state *and* updating its sync index (the
reference's ``update_state_with_delta``: lattice join + MerkleMap puts,
``causal_crdt.ex:383-404``; our merge kernel maintains the digest-tree
leaves incrementally, and the per-call root derivation is the
``update_hashes`` analog, ``causal_crdt.ex:254``).

The TPU path is the bucket-binned O(delta) engine
(``delta_crdt_ex_tpu/ops/binned.py``): each device call scans a chunk of
delta slices, each vmapped across all 64 neighbour states — dispatch
overhead amortises over NDELTA × NEIGHBOURS merges per call.

Baseline: the reference publishes no numbers and Elixir/BEAM is not in
this image (BASELINE.md), so ``vs_baseline`` is measured against a lean
pure-Python dict implementation of the same semantic steps (per-entry
coverage check + insert, per-bucket context union, per-bucket index
update) running the identical workload single-threaded. It does O(delta)
work per merge — a deliberately *favourable* cost model for the baseline
(BEAM's persistent maps pay O(log n) per touched key plus actor
overhead), so the reported ratio is conservative.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "merges/sec", "vs_baseline": N, ...}
where ``value`` is the MEDIAN per-call-window rate (min/max and the
aggregate ride alongside — see ``call_stats``), plus ``utc``/``group``
provenance and, when the in-run A/B ran, both kernels' numbers.

Env knobs: BENCH_SMOKE=1 shrinks sizes for CPU smoke runs;
BENCH_PACKED/BENCH_SCOMP/BENCH_FUSED pick the merge kernel (scomp is
the promoted default, the A/B tail times the top_k alternate);
BENCH_GROUP/BENCH_BIN_WIDTH shape the delta grouping; BENCH_AB=0
skips the alternate-kernel tail; BENCH_NO_CPU_FALLBACK=1 fails fast
instead of emitting a labelled CPU number (interactive chip windows);
BENCH_OBS_ROUNDS overrides the ``--obs`` A/B round count and
BENCH_OBS_DEBUG=1 prints its per-round timings.

Deadline contract: the whole run fits one wall-clock budget
(``BENCH_TOTAL_BUDGET`` seconds, default 1380 — comfortably under a
30-minute external timeout). The claim probe and the device child only
get the budget *minus* a reserve for the labelled CPU fallback, so the
fallback always has time to run; and a JSON line is guaranteed on every
exit path (deadline exhaustion, claim failure, child crash, SIGTERM)
— a bench that can exit with no artifact is a broken bench.
"""

from __future__ import annotations

import json
import math
import os
import signal
import sys
import time
from functools import partial

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SMOKE = os.environ.get("BENCH_SMOKE") == "1"
#: A/B switch for the packed-entry layout (ops/packed.py — the
#: roofline's single-vector-scatter lever). Parity-pinned to the column
#: kernel; PROMOTED to the default after the 2026-07-31 chip A/B
#: measured packed 8,852.8 vs columns 4,211.9 merges/s (2.1×, past the
#: ≥1.2× promotion bar; CPU full-config is a wash — BASELINE.md
#: "Merge-kernel roofline"). BENCH_PACKED=0 times columns as primary.
PACKED = os.environ.get("BENCH_PACKED", "1") == "1"
#: A/B switch for the fused-aux packed kernel (amin/amax/ctx as one
#: [L,R,3] min-scatter via the unsigned-complement identity, fill/leaf
#: as one [k,2] add-scatter — ~25% fewer random-access index entries).
#: Chip A/B 2026-07-31: LOST 1.9x (BASELINE.md) — kept as an opt-in
#: probe; the A/B alternate is the plain packed kernel.
FUSED = PACKED and os.environ.get("BENCH_FUSED", "0") == "1"
#: A/B switch for top_k-free insert compaction (cumsum rank + one
#: packed [G,9] compaction scatter instead of the per-neighbour top_k
#: over the 65,536-slot grid). PROMOTED to the default in round 5 on
#: the CPU full-config evidence (1,060 → 2,024 merges/s, vs_baseline
#: 3.03, benchmarks/results/scomp_cpu_full_20260731.log; parity +
#: growth-ladder suites green) — the chip A/B never got a window in
#: r4. BENCH_SCOMP=0 times the top_k packed kernel as primary; either
#: way the A/B tail measures the other, so one chip run decides
#: whether top_k is the roofline gap's missing term.
SCOMP = PACKED and not FUSED and os.environ.get("BENCH_SCOMP", "1") == "1"


def layout_name() -> str:
    """The primary merge layout's artifact label (one definition for the
    child log line, the A/B log line, and the parent artifact field)."""
    if FUSED:
        return "packed_fused"
    if SCOMP:
        return "packed_scomp"
    return "packed" if PACKED else "columns"

N_KEYS = 4096 if SMOKE else 1_000_000
# geometry: load ≈ N_KEYS/L per bucket; bin capacity must clear the
# Poisson tail (≈ load + 6·sqrt(load)) — larger loads waste less headroom,
# and total HBM ≈ NEIGHBOURS · L · B · 33 bytes must leave headroom
TREE_DEPTH = 8 if SMOKE else 14  # L = 2**depth leaf buckets
BIN_CAP = 64 if SMOKE else 128
NEIGHBOURS = 4 if SMOKE else 64
DELTA = 128 if SMOKE else 512  # the merge unit: one 512-entry delta slice
#: delta slices joined into one group before merging (lattice
#: associativity: merging the group == merging its slices in order; the
#: python baseline merges identical groups, so the ratio is unaffected).
#: This amortises fixed per-call dispatch. Buffer donation already keeps
#: the merge O(slice) — 16× the capacity costs 1.11× per call
#: (BASELINE.md "O(slice) merge evidence").
GROUP = int(os.environ.get("BENCH_GROUP", "0")) or (4 if SMOKE else 16)
CALLS = 2 if SMOKE else 6  # timed calls
WARMUP_CALLS = 1
RCAP = 8
BASE_ITERS = 2 if SMOKE else 12  # baseline group-merges (each = GROUP deltas)

log = lambda *a: print(*a, file=sys.stderr, flush=True)


def make_workload(seed=0):
    L = 1 << TREE_DEPTH
    rng = np.random.default_rng(seed)
    keys = rng.integers(1, 1 << 63, size=N_KEYS, dtype=np.uint64)
    return L, rng, keys


# ---------------------------------------------------------------------------
# TPU side

def _stage(name):
    log(f"[bench +{time.perf_counter() - _T_START:.1f}s] {name}")


_T_START = time.perf_counter()



def _topology() -> dict:
    """Detected device/mesh shape for artifact self-description
    (ISSUE 14 satellite: EVERY bench artifact carries it, so
    chip-window reruns are distinguishable from CPU evidence by field,
    not filename)."""
    from delta_crdt_ex_tpu.utils.devices import detected_topology

    return detected_topology()


def _jit_steady_gate(tag: str, roots: tuple, before: dict, after: dict) -> dict:
    """ISSUE 12 in-run gate: ZERO steady-state XLA compiles after warmup
    on the named dispatch roots — the measured rounds must ride a warm
    cache, or the speedup numbers are partly compile noise and the
    shape-tier discipline (crdtlint SHAPE001/002) has regressed at
    runtime. ``before`` is the compile-count snapshot taken entering
    the LAST measured round; every earlier round is warmup."""
    from delta_crdt_ex_tpu.utils import jitcache

    assert jitcache.supported(), (
        "jit tracing-cache counter unavailable: the steady-state "
        "compile gate cannot run (it must not pass vacuously)"
    )
    moved = {
        k: (before.get(k, 0), after.get(k, 0))
        for k in roots
        if after.get(k, 0) != before.get(k, 0)
    }
    assert not moved, f"{tag}: steady-state XLA compiles after warmup: {moved}"
    return {k: after.get(k, 0) for k in roots if k in after}


def _transfer_steady_gate(
    tag: str, pre1: dict, pre2: dict, post: dict, demand_ok: tuple = ()
) -> dict:
    """ISSUE 17 in-run gate: steady-state device↔host crossings per
    round must be CONSTANT — the per-site crossing-count delta over the
    last measured round must equal the round before it, or a hot path
    has grown an unpriced boundary trip the TRANSFER lint family cannot
    see (it proves sites are audited, not how often they fire).
    ``pre1``/``pre2``/``post`` are ledger snapshots entering the
    second-to-last measured round, entering the last, and after it.
    Byte deltas may wobble with payload content; counts may not.
    ``demand_ok`` names sites whose crossings are demand-driven cache
    fills (the lazy digest ladder: which levels a walk touches depends
    on WHERE the probe key landed, not how many rounds ran) — still
    measured and reported, just not pinned. Returns the last round's
    per-site delta — the artifact's ``transfers_per_round`` stamp."""
    from delta_crdt_ex_tpu.utils import transfers

    d_prev = transfers.delta(pre1, pre2)
    d_last = transfers.delta(pre2, post)
    c_prev = {s: d["count"] for s, d in d_prev.items() if s not in demand_ok}
    c_last = {s: d["count"] for s, d in d_last.items() if s not in demand_ok}
    assert c_prev == c_last, (
        f"{tag}: per-round device-host crossing counts drifted in "
        f"steady state: {c_prev} -> {c_last}"
    )
    return d_last


def _transfers_snapshot() -> dict:
    """Current ledger image for artifact stamping (next to
    ``_topology()`` in EVERY bench artifact: absolute totals at emit
    time, so retirement PRs carry before/after evidence by field)."""
    from delta_crdt_ex_tpu.utils import transfers

    return transfers.snapshot()


def _jit_metrics_probe(roots: tuple) -> None:
    """Scrape a throwaway obs plane's /metrics and assert the compile
    counter is visible for the given entry roots (the ISSUE 12
    acceptance: the counter rides the export surface, not just the
    in-process registry)."""
    import urllib.request

    from delta_crdt_ex_tpu.runtime.metrics import Observability

    plane = Observability()
    try:
        server = plane.serve(port=0)
        with urllib.request.urlopen(server.url + "/metrics", timeout=10) as r:
            body = r.read().decode()
        for root in roots:
            needle = f'crdt_jit_compiles_total{{name="{root}"}}'
            assert needle in body, f"{needle} missing from /metrics"
    finally:
        plane.close()


def bench_tpu(seed=0, on_primary=None):
    import jax
    import jax.numpy as jnp

    from delta_crdt_ex_tpu.utils.devices import enable_compilation_cache

    log(f"compilation cache: {enable_compilation_cache()}")

    from delta_crdt_ex_tpu.ops.binned import merge_slice
    from delta_crdt_ex_tpu.utils.synth import build_state, interval_delta_stream

    _stage("importing jax / claiming device…")
    log(f"jax devices: {jax.devices()}")
    L, rng, keys = make_workload(seed)

    _stage("build_state (host arrays + init_from_columns compile)…")
    one, _ = build_state(11, keys, num_buckets=L, bin_capacity=BIN_CAP,
                         replica_capacity=RCAP)
    jax.block_until_ready(one)
    _stage("broadcast to neighbour stack…")
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (NEIGHBOURS,) + x.shape), one
    )
    stacked = jax.tree_util.tree_map(jnp.copy, stacked)
    jax.block_until_ready(stacked)

    # delta streams from a second writer (gid 22): one GROUP-slice join
    # per device call (a group of GROUP in-order 512-entry interval
    # deltas concatenates into one exact interval slice), fresh dots.
    # bin_width bounds per-bucket slice occupancy; at the full config the
    # per-delta bucket load is λ = GROUP·DELTA/L (0.5 at GROUP=16), and
    # the width must clear the Poisson tail for the whole run (the stream
    # generator raises on overflow) — λ + 6√λ + 2 keeps the per-run
    # slice-overflow odds negligible at any BENCH_GROUP (the floor keeps
    # the default geometries at their measured widths: smoke 16, full 8).
    # BENCH_GROUP is a dispatch-amortization knob, not a free axis: the
    # run's TOTAL inserts still land in BIN_CAP-slot bins, so warn when
    # the end-of-run occupancy tail approaches capacity (the overflow
    # assertion in timed_group_run would fail the run honestly).
    _stage("delta stream generation…")
    lam = GROUP * DELTA / L
    bw = max(16 if SMOKE else 8, math.ceil(lam + 6 * math.sqrt(lam) + 2))
    # probe override: the Poisson formula can land on a non-power-of-2
    # slice lane width (e.g. 9 at BENCH_GROUP=32), which TPU tiling
    # penalises — BENCH_BIN_WIDTH pins it to isolate grouping effects
    # (the stream generator still raises honestly on slice overflow; a
    # malformed value must not crash a claimed chip window, so it falls
    # back to the formula)
    try:
        bw_env = int(os.environ.get("BENCH_BIN_WIDTH", "0").strip() or 0)
        if bw_env > 0:
            bw = bw_env
        elif bw_env < 0:
            log(f"ignoring non-positive BENCH_BIN_WIDTH={bw_env}")
    except ValueError:
        log(f"ignoring malformed BENCH_BIN_WIDTH={os.environ['BENCH_BIN_WIDTH']!r}")
    lam_end = N_KEYS / L + (WARMUP_CALLS + CALLS + 1) * GROUP * DELTA / L
    if lam_end + 6 * math.sqrt(lam_end) > BIN_CAP:
        log(
            f"WARNING: end-of-run bucket load {lam_end:.1f} + tail exceeds "
            f"bin capacity {BIN_CAP}; expect fill-overflow assertions at "
            f"this BENCH_GROUP"
        )
    next_ctr = None
    calls = []
    for _ in range(WARMUP_CALLS + CALLS):
        slices, next_ctr = interval_delta_stream(
            22, rng, 1, GROUP * DELTA, L, next_ctr=next_ctr, bin_width=bw
        )
        calls.append(slices[0])

    # the digest-tree fold: fused Pallas kernel (whole batch, all levels
    # in VMEM, one launch) when TPU lowering is available, else the
    # per-level XLA fold. The probe compile can wedge on experimental
    # backends (remote-compile relays), so it gets its own watchdog.
    _stage("digest-tree impl probe…")
    roots_of, tree_impl = _probed_roots_fn(1 << TREE_DEPTH)
    log(f"digest tree: {tree_impl}")

    merge_fn = merge_slice
    if PACKED:
        from delta_crdt_ex_tpu.ops.packed import (
            merge_slice_packed,
            merge_slice_packed_fused,
            merge_slice_packed_scomp,
            pack,
        )

        _stage("packing entry columns (BENCH_PACKED=1)…")
        stacked = jax.jit(pack)(stacked)
        jax.block_until_ready(stacked)
        if FUSED:
            merge_fn = merge_slice_packed_fused
            log("merge layout: packed, fused aux scatters")
        elif SCOMP:
            # interval_delta_stream rows come from np.unique → the valid
            # prefix is strictly ascending, so the scatter-hint fast
            # path's precondition holds for every bench slice
            merge_fn = partial(merge_slice_packed_scomp, rows_sorted=True)
            log("merge layout: packed, top_k-free scatter compaction")
        else:
            merge_fn = merge_slice_packed
            log("merge layout: packed (one vector scatter per insert)")

    merges = CALLS * GROUP * NEIGHBOURS

    def timed_group_run(fn, states0):
        """Warm + time the GROUP-merge call chain for one merge layout —
        ONE implementation so the primary run and the A/B's alternate
        layout measure identical work (incl. the overflow-flag stack)."""

        @partial_jit_donate
        def merge_chunk(states, sl):
            res = jax.vmap(fn, in_axes=(0, None, None, None))(
                states, sl, 8, GROUP * DELTA
            )
            flags = jnp.stack(
                [res.need_gid_grow, res.need_kill_tier, res.need_fill_compact,
                 res.need_ctx_gap, res.need_ins_tier]
            )
            # per-sync-round index refresh (update_hashes analog): roots
            roots = roots_of(res.state.leaf)
            return res.state, res.ok, flags, roots

        st = states0
        for i in range(WARMUP_CALLS):
            st, oks, flags, roots = merge_chunk(st, calls[i])
        roots.block_until_ready()
        assert bool(jnp.all(oks)), f"merge overflow in bench workload: {np.asarray(jnp.any(flags, axis=1)).tolist()} (gid/kill/fill/gap/ins)"
        t0 = time.perf_counter()
        all_ok = []
        all_flags = []
        pend = []
        for i in range(CALLS):
            st, oks, flags, roots = merge_chunk(st, calls[WARMUP_CALLS + i])
            all_ok.append(oks)
            all_flags.append(flags)
            pend.append(roots)
        # block in dispatch order, stamping each completion: calls run
        # sequentially on the device stream, so stamp deltas are honest
        # per-call intervals while dispatch stays fully pipelined (the
        # first interval absorbs any dispatch-ahead — the median is
        # robust to it, and to the scheduler hiccups that made r04's
        # single-pass 777-merges/s noise artifact)
        stamps = []
        for r in pend:
            r.block_until_ready()
            stamps.append(time.perf_counter())
        dt = stamps[-1] - t0
        call_dts = [stamps[0] - t0] + [
            stamps[i] - stamps[i - 1] for i in range(1, CALLS)
        ]
        oks = jnp.stack(all_ok)
        flags = jnp.stack(all_flags)
        assert bool(jnp.all(oks)), f"merge overflow: {np.asarray(jnp.any(flags, axis=(0, 2))).tolist()} (gid/kill/fill/gap/ins)"
        return st, dt, call_dts

    def call_stats(dts):
        """Per-call completion intervals → the measured side's
        Benchee-grade summary.

        Sub-floor intervals are coalesced first: when calls are observed
        completing in a batch (tiny workloads finish before the blocked
        observer reaches their stamp), the collapsed intervals stop
        meaning per-call cost — a 33 µs "call" is an observation
        artifact, not a rate. At the full config every window is one
        call (~0.1 s on chip). The headline is then the MEDIAN window
        rate: robust to one scheduler hiccup (the baseline gets
        best-of-3 passes, so the comparison stays conservative —
        measured median vs baseline best), with min/max carried so the
        artifact shows its spread."""
        import statistics

        per_call = GROUP * NEIGHBOURS
        floor = 0.005
        wins: list[tuple[int, float]] = []  # (n_calls, dt)
        acc_n, acc_dt = 0, 0.0
        for d in dts:
            acc_n += 1
            acc_dt += d
            if acc_dt >= floor:
                wins.append((acc_n, acc_dt))
                acc_n, acc_dt = 0, 0.0
        if acc_n:  # trailing sub-floor remainder folds into the last window
            if wins:
                n0, d0 = wins[-1]
                wins[-1] = (n0 + acc_n, d0 + acc_dt)
            else:
                wins.append((acc_n, acc_dt))
        rates = sorted(n * per_call / d for n, d in wins)
        return {
            "merges_per_sec": round(statistics.median(rates), 2),
            "stat": f"median_of_{len(wins)}_call_windows",
            "call_rate_min": round(rates[0], 2),
            "call_rate_max": round(rates[-1], 2),
        }

    _stage("merge_chunk compile + warmup + timing…")
    st, dt, call_dts = timed_group_run(merge_fn, stacked)
    stats = call_stats(call_dts)
    stats["aggregate_merges_per_sec"] = round(merges / dt, 2)
    log(
        f"tpu: {merges} merges in {dt:.3f}s (per-call rate "
        f"min/med/max {stats['call_rate_min']}/{stats['merges_per_sec']}/"
        f"{stats['call_rate_max']} merges/sec)"
    )

    # secondary evidence (stderr only): per-merge dispatch at GROUP=1 —
    # the O(slice) criterion is "GROUP=1 merges/sec within 2x of
    # GROUP=16" (one 512-entry slice per call, same 64-neighbour vmap)
    secondary_assert_failed = False
    try:
        n1 = 4
        slices1, _ = interval_delta_stream(
            22, rng, n1 + 1, DELTA, L, next_ctr=next_ctr, bin_width=bw
        )

        @partial_jit_donate
        def merge_one(states, s):
            res = jax.vmap(merge_fn, in_axes=(0, None, None, None))(
                states, s, 8, DELTA
            )
            return res.state, res.ok

        st1, ok1 = merge_one(st, slices1[0])  # compile + warm
        jax.block_until_ready(st1.leaf)
        all_ok1 = [ok1]
        t0 = time.perf_counter()
        for i in range(n1):
            st1, ok1 = merge_one(st1, slices1[1 + i])  # fresh dots per call
            all_ok1.append(ok1)
        jax.block_until_ready(st1.leaf)
        g1 = n1 * NEIGHBOURS / (time.perf_counter() - t0)
        assert bool(jnp.all(jnp.stack(all_ok1))), "group=1 merge overflow"
        log(
            f"group=1 secondary: {g1:.1f} merges/sec "
            f"(group={GROUP}: {merges / dt:.1f}; ratio {(merges / dt) / g1:.2f}x)"
        )
    except AssertionError as e:
        # a tier overflow is a correctness signal, not a perf hiccup —
        # it must be distinguishable in the artifact, not just a log line
        secondary_assert_failed = True
        log(f"group=1 secondary OVERFLOW ASSERTION: {e!r}")
    except Exception as e:  # never let the secondary kill the artifact
        log(f"group=1 secondary failed: {e!r}")

    # ---- alternate-layout A/B (full config only) ---------------------
    # One chip window may be exactly one bench run, so the run itself
    # measures BOTH merge layouts (the roofline's packed-entry lever,
    # ops/packed.py — bit-parity-pinned) and the artifact reports both;
    # the parent headlines the better one, labelled. BENCH_AB=0 skips.
    # the primary measurement is complete: hand it to the caller BEFORE
    # the (long) A/B tail, so an external watchdog killing the child
    # mid-A/B cannot lose it (the artifact contract)
    if on_primary is not None:
        try:
            on_primary(stats, secondary_assert_failed)
        except Exception as e:
            log(f"on_primary callback failed: {e!r}")

    alt = None
    if not SMOKE and os.environ.get("BENCH_AB", "1") == "1":
        try:
            _stage("alternate-layout A/B…")
            from delta_crdt_ex_tpu.ops.packed import (  # noqa: F811
                merge_slice_packed,
                pack,
            )

            if FUSED:
                # fused primary → the A/B isolates the fusion itself
                alt_name, alt_fn = "packed_unfused", merge_slice_packed
            elif SCOMP:
                # scomp primary → the A/B isolates the compaction change
                alt_name, alt_fn = "packed_topk", merge_slice_packed
            elif PACKED:
                # top_k primary (BENCH_SCOMP=0) → the A/B still answers
                # the live question, scomp-vs-top_k (columns-vs-packed
                # was settled by the r4 chip session, BASELINE.md)
                alt_name, alt_fn = "packed_scomp", partial(
                    merge_slice_packed_scomp, rows_sorted=True
                )
            else:
                alt_name, alt_fn = "packed", merge_slice_packed
            # free the primary run's states before building the second
            # stack: two full neighbour stacks would not fit HBM together
            st = st1 = None
            base = jax.tree_util.tree_map(
                lambda x: jnp.copy(jnp.broadcast_to(x, (NEIGHBOURS,) + x.shape)),
                one,
            )
            if alt_fn is not merge_slice:
                base = jax.jit(pack, donate_argnums=(0,))(base)
            jax.block_until_ready(base)
            _st2, dt2, dts2 = timed_group_run(alt_fn, base)
            alt_stats = call_stats(dts2)
            # full Benchee-grade summary for the alternate too: if it
            # wins the headline, the artifact must keep ITS spread and
            # aggregate, not the losing primary's (ADVICE r5 low #2)
            alt_stats["aggregate_merges_per_sec"] = round(merges / dt2, 2)
            alt = (alt_name, alt_stats)
            log(
                f"A/B: {alt_name} {alt_stats['merges_per_sec']:.1f} vs "
                f"{layout_name()} {stats['merges_per_sec']:.1f} "
                f"merges/sec (median-of-calls both sides)"
            )
        except AssertionError as e:
            log(f"alternate-layout A/B overflowed a tier — ignored: {e!r}")
        except Exception as e:  # never let the A/B kill the artifact
            log(f"alternate-layout A/B failed: {e!r}")
    return stats, secondary_assert_failed, alt


def partial_jit_donate(fn):
    import jax

    return jax.jit(fn, donate_argnums=(0,))


def _probed_roots_fn(num_leaves: int):
    """Pick the digest-tree impl with a compile watchdog.

    ``batched_roots_fn`` probes Pallas by compiling the kernel; on an
    experimental remote-compile backend that probe can hang rather than
    raise. Run it in a daemon thread and fall back to the per-level XLA
    fold if it doesn't finish within BENCH_PALLAS_TIMEOUT seconds (the
    hung thread is abandoned — it holds no locks the XLA path needs)."""
    import threading

    import jax

    from delta_crdt_ex_tpu.ops.binned import tree_from_leaves as xla_tree
    from delta_crdt_ex_tpu.ops.pallas_tree import batched_roots_fn

    timeout = float(os.environ.get("BENCH_PALLAS_TIMEOUT", "300"))
    result = {}

    def probe():
        result["fn"] = batched_roots_fn(num_leaves)

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout)
    if "fn" in result:
        return result["fn"]
    log(f"pallas probe did not finish in {timeout:.0f}s — using XLA fold")
    return jax.vmap(lambda lf: xla_tree(lf)[0][0]), "xla (probe timeout)"


# ---------------------------------------------------------------------------
# durability cost (ISSUE 1: WAL vs full-snapshot every_op)

def bench_durability():
    """``--durability``: mutation throughput under ``every_op``
    durability, full-image snapshot writes vs WAL record appends.

    The reference's write-through persists O(state) per mutation batch
    (``causal_crdt.ex:402-403``); the WAL persists O(delta) — and the
    WAL side is measured at a STRICTER contract (fsync per group commit;
    ``FileStorage`` snapshot writes never fsync). Prints exactly one
    JSON line with both rates; the acceptance bar is wal_vs_snapshot
    ≥ 5 on this workload. Host-I/O bound by design, so it runs wherever
    invoked (no device claim dance)."""
    import shutil
    import tempfile

    from delta_crdt_ex_tpu import AWLWWMap, FileStorage
    from delta_crdt_ex_tpu.api import start_link

    import statistics

    waves = 12 if SMOKE else 48
    batch = 16 if SMOKE else 32
    depth = 6 if SMOKE else 10
    # bin capacity must clear the preload Poisson tail with margin, or a
    # mid-loop grow-tier recompile pollutes one wave of one run
    cap = 8192 if SMOKE else 131072
    # the north-star workload is a 1M-key map; 50k is a conservative
    # stand-in that keeps the bench fast while the O(state) snapshot
    # cost is already unmistakable
    preload = 2000 if SMOKE else 50000

    def run(tag, **durability_opts):
        root = tempfile.mkdtemp(prefix=f"walbench_{tag}_")
        try:
            rep = start_link(
                AWLWWMap, threaded=False, name=f"dur_{tag}",
                capacity=cap, tree_depth=depth, **{
                    k: (v(root) if callable(v) else v)
                    for k, v in durability_opts.items()
                },
            )
            # preload to a realistic map size: per-op durability cost is
            # what's measured, and it only tells the O(state)-vs-O(delta)
            # story at a state visibly larger than a delta (bulk batches
            # take the vectorized path, so this is also the jit warmup)
            PRE = 2000
            for s in range(0, preload, PRE):
                rep.mutate_batch(
                    "add", [[f"p{j}", j] for j in range(s, min(s + PRE, preload))]
                )
            if rep._wal is not None:
                rep.checkpoint()  # compact: waves measure steady-state appends
            rep.mutate_batch("add", [[f"warm{i}", i] for i in range(batch)])
            dts = []
            for w in range(waves):
                items = [[f"k{w}_{i}", i] for i in range(batch)]
                t0 = time.perf_counter()
                rep.mutate_batch("add", items)
                dts.append(time.perf_counter() - t0)
            rep.transport.unregister(rep.addr)
            # median per-wave rate: robust to one-off compile/IO spikes
            # (same honesty stance as the merge bench's call windows)
            med = batch / statistics.median(dts)
            agg = waves * batch / sum(dts)
            log(
                f"durability[{tag}]: {waves * batch} ops in {sum(dts):.3f}s "
                f"(median {med:.1f} aggregate {agg:.1f} ops/sec)"
            )
            return med, agg
        finally:
            shutil.rmtree(root, ignore_errors=True)

    run("jitwarm")  # discarded: pays every process-wide jit compile, so
    # no timed run is polluted by whichever happened to go first
    snap, snap_agg = run(
        "snapshot",
        storage_module=lambda root: FileStorage(root),
        storage_mode="every_op",
    )
    # the WAL side runs at a STRICTER durability contract than the
    # snapshot side (group-commit fsync per batch vs no fsync at all)
    wal, wal_agg = run("wal", wal_dir=lambda root: root, fsync_mode="batch")
    base, base_agg = run("none")  # no persistence: the shared ceiling
    _emit({
        "metric": "durability_every_op_mutate_ops_per_sec"
                  + ("_smoke" if SMOKE else ""),
        "unit": "ops/sec",
        "stat": f"median_of_{waves}_waves",
        "value": round(wal, 2),
        "no_persistence_ops_per_sec": round(base, 2),
        "snapshot_ops_per_sec": round(snap, 2),
        "wal_ops_per_sec": round(wal, 2),
        "wal_vs_snapshot": round(wal / snap, 3),
        "wal_overhead_vs_none": round(base / wal, 3),
        "aggregate_ops_per_sec": {
            "none": round(base_agg, 2),
            "snapshot": round(snap_agg, 2),
            "wal": round(wal_agg, 2),
        },
        "preload_keys": preload,
        "waves": waves,
        "batch": batch,
        "topology": _topology(),
        "transfers": _transfers_snapshot(),
    })


# ---------------------------------------------------------------------------
# chaos: seeded fault injection + crash/WAL-recovery parity (ISSUE 20)

def bench_chaos():
    """``--chaos``: the fault-injection gate (crdtlint v6's runtime
    cross-check of the FAULT family). Two leg families, each on BOTH
    dot-store backends (``binned`` and ``hash``):

    1. **Cluster chaos** — three WAL-backed replicas on a seeded
       adversarial ``SimNetwork`` (drops, dups, reorder) while a seeded
       ``FaultPlan`` trips raise / crash-before / crash-after / delay at
       the labelled commit+WAL fault points. A ``CrashInjected`` kills
       the victim mid-schedule (``Replica.crash()``) and recovery
       replays its WAL under ``faults.suspended()`` (replay walks the
       same commit paths, so it must not consume schedule hits). After
       the schedule drains, the net heals, a fault-free twin joins, and
       EVERY replica must reach ``canonical_state_bytes()`` bit-parity
       with the twin — the convergence contract survives deterministic
       failure at every labelled boundary.

    2. **Torn tail** — one replica, one group commit per mutation; a
       ``partial_write`` trip at ``wal.write`` tears the Nth record
       mid-write and crashes. Recovery must truncate the torn tail and
       land EXACTLY on the durable prefix (commit ordering, FAULT003:
       the torn op was never published, so nothing acknowledged is
       lost), then re-applied ops + a twin close with bit-parity.

    Host-I/O + protocol bound: runs anywhere (no device claim dance).
    Zero-overhead-when-disabled is gated separately: ``--ingest`` runs
    with faults disarmed and must hold its existing numbers."""
    import random
    import shutil
    import tempfile

    from delta_crdt_ex_tpu import AWLWWMap
    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock
    from delta_crdt_ex_tpu.runtime.simnet import SimNetwork
    from delta_crdt_ex_tpu.utils import faults
    from delta_crdt_ex_tpu.utils.faults import (
        CrashInjected,
        FaultInjected,
        FaultPlan,
        FaultRule,
    )

    #: sites this single-process, threaded=False topology actually
    #: drives (thread-loop / tcp / fleet sites are exercised by their
    #: own suites — seeding rules on never-hit sites just pads the plan)
    CLUSTER_SITES = (
        "replica.commit.batch",
        "replica.commit.entries",
        "replica.durable",
        "wal.append",
        "wal.fsync",
    )
    seeds = (11, 12) if SMOKE else (11, 12, 13, 14, 15)
    ops = 18 if SMOKE else 60

    class ChaosNet(SimNetwork):
        """SimNetwork mapping injected faults during delivery onto the
        two legal outcomes: frame loss (transient — anti-entropy
        re-covers) or a recorded crash the driver must service."""

        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self.crashed: list = []

        def _deliver(self, addr, msg):
            try:
                super()._deliver(addr, msg)
            except FaultInjected:
                pass  # frame lost mid-commit; the next sync tick retries
            except CrashInjected:
                if addr not in self.crashed:
                    self.crashed.append(addr)

    def cluster_leg(store, seed):
        root = tempfile.mkdtemp(prefix=f"chaosbench_{store}_{seed}_")
        recoveries = 0
        try:
            net = ChaosNet(seed=seed, drop_rate=0.05, dup_rate=0.1)
            clock = LogicalClock()

            def spawn(i):
                return start_link(
                    AWLWWMap, threaded=False, store=store, transport=net,
                    clock=clock, name=f"cb_{store}_{seed}_r{i}",
                    capacity=256, tree_depth=6, max_sync_size=8,
                    sync_timeout=0.0,
                    wal_dir=os.path.join(root, f"r{i}"), fsync_mode="batch",
                )

            reps = [spawn(i) for i in range(3)]
            for r in reps:
                r.set_neighbours(reps)
            net.step()

            def recover(i):
                nonlocal recoveries
                recoveries += 1
                # replaying the WAL walks the commit/append paths —
                # suspend (not reset) so recovery consumes no hits
                with faults.suspended():
                    reps[i].crash()
                    reps[i] = spawn(i)
                    for r in reps:
                        r.set_neighbours(reps)

            def service_crashes():
                while net.crashed:
                    addr = net.crashed.pop()
                    for i, r in enumerate(reps):
                        if r.addr == addr:
                            recover(i)
                            break

            plan = FaultPlan.seeded(
                seed, sites=CLUSTER_SITES, n_rules=4, window=(1, 10),
                actions=("raise", "crash_before", "crash_after", "delay"),
            )
            rng = random.Random(seed ^ 0xC0FFEE)
            with faults.armed(plan):
                for n in range(ops):
                    i = n % 3
                    for _attempt in range(64):
                        try:
                            reps[i].mutate("add", [f"k{n}", n])
                            break
                        except FaultInjected:
                            continue  # transient: op rolled back, retry
                        except CrashInjected:
                            recover(i)
                    else:
                        raise AssertionError(
                            f"k{n} never committed in 64 attempts"
                        )
                    if rng.random() < 0.5:
                        for j in range(len(reps)):
                            try:
                                reps[j].sync_to_all()
                            except FaultInjected:
                                pass
                            except CrashInjected:
                                recover(j)
                        net.step()
                        service_crashes()
            fired = sum(1 for ru in plan.rules if ru.fired)
            assert fired >= 1, f"schedule never fired: {plan.rules}"
            # heal the net, join a fault-free twin, converge, assert
            # bit-parity — the whole cohort must agree canonically
            net.drop_rate = net.dup_rate = 0.0
            twin = start_link(
                AWLWWMap, threaded=False, store=store, transport=net,
                clock=clock, name=f"cb_{store}_{seed}_twin",
                capacity=256, tree_depth=6, max_sync_size=8,
                sync_timeout=0.0,
            )
            cohort = reps + [twin]
            for r in cohort:
                r.set_neighbours(cohort)
            net.run(cohort, rounds=160)
            while net.pending:
                net.step()
            want = {f"k{n}": n for n in range(ops)}
            for i, r in enumerate(cohort):
                got = r.read()
                assert got == want, (
                    f"[{store} seed={seed}] replica {i} diverged: "
                    f"{len(got)}/{len(want)} keys"
                )
            canon = twin.canonical_state_bytes()
            for i, r in enumerate(reps):
                assert r.canonical_state_bytes() == canon, (
                    f"[{store} seed={seed}] replica {i} lost canonical "
                    f"bit-parity with the fault-free twin"
                )
            for r in cohort:
                r.stop()
            log(
                f"chaos[{store} seed={seed}]: {ops} ops, {fired}/"
                f"{len(plan.rules)} rules fired, {recoveries} crash-"
                f"recoveries, cohort of {len(cohort)} at bit-parity"
            )
            return {
                "kind": "cluster", "store": store, "seed": seed,
                "ops": ops, "rules_fired": fired,
                "rules": len(plan.rules), "recoveries": recoveries,
            }
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def torn_leg(store, seed):
        root = tempfile.mkdtemp(prefix=f"chaostorn_{store}_{seed}_")
        try:
            net = SimNetwork(seed=seed)  # loss-free: pure delivery pump
            clock = LogicalClock()
            wal_dir = os.path.join(root, "w")

            def spawn():
                return start_link(
                    AWLWWMap, threaded=False, store=store, transport=net,
                    clock=clock, name=f"cbt_{store}_{seed}",
                    capacity=256, tree_depth=6,
                    wal_dir=wal_dir, fsync_mode="batch",
                )

            rep = spawn()
            total = 8 if SMOKE else 16
            tear_at = 3 + (seed % 4)  # Nth group-commit write tears
            plan = FaultPlan(
                [FaultRule("wal.write", tear_at, "partial_write", 0.5)],
                seed=seed,
            )
            committed = {}
            torn_op = None
            with faults.armed(plan):
                for n in range(total):
                    try:
                        rep.mutate("add", [f"t{n}", n])
                        committed[f"t{n}"] = n
                    except CrashInjected:
                        torn_op = n
                        break
            assert torn_op is not None, "partial_write never tripped"
            rep.crash()
            rep = spawn()  # recovery: the torn tail must truncate
            got = rep.read()
            assert got == committed, (
                f"[{store} seed={seed}] torn-tail recovery mismatch: "
                f"{len(got)} keys vs durable prefix of {len(committed)}"
            )
            # the torn op was never published (FAULT003 ordering), so
            # re-applying it and the rest heals with no duplicates lost
            for n in range(torn_op, total):
                rep.mutate("add", [f"t{n}", n])
            want = {f"t{n}": n for n in range(total)}
            assert rep.read() == want
            rep.crash()
            rep = spawn()  # healed WAL replays the full map
            assert rep.read() == want, "post-heal recovery mismatch"
            # a fault-free twin merges to bit-parity
            twin = start_link(
                AWLWWMap, threaded=False, store=store, transport=net,
                clock=clock, name=f"cbt_{store}_{seed}_twin",
                capacity=256, tree_depth=6,
            )
            pair = [rep, twin]
            for r in pair:
                r.set_neighbours(pair)
            net.run(pair, rounds=40)
            while net.pending:
                net.step()
            assert twin.read() == want
            assert rep.canonical_state_bytes() == \
                twin.canonical_state_bytes(), (
                    f"[{store} seed={seed}] torn-tail survivor lost "
                    f"canonical bit-parity with the fault-free twin"
                )
            rep.stop()
            twin.stop()
            log(
                f"chaos-torn[{store} seed={seed}]: tore commit "
                f"{tear_at}, durable prefix {len(committed)}, "
                f"recovered + healed to bit-parity"
            )
            return {
                "kind": "torn_tail", "store": store, "seed": seed,
                "torn_at_commit": tear_at,
                "durable_prefix": len(committed), "ops": total,
            }
        finally:
            shutil.rmtree(root, ignore_errors=True)

    legs = []
    torn_seeds = (7,) if SMOKE else (7, 9)
    for store in ("binned", "hash"):
        for seed in seeds:
            legs.append(cluster_leg(store, seed))
        for seed in torn_seeds:
            legs.append(torn_leg(store, seed))
    trips = faults.trips()
    assert sum(trips.values()) > 0, "no fault ever tripped"
    assert faults.active() is None, "plan leaked past its armed() scope"
    _emit({
        "metric": "chaos_parity_legs" + ("_smoke" if SMOKE else ""),
        "unit": "legs_at_bit_parity",
        "stat": "all_or_assert",
        "value": len(legs),
        "stores": ["binned", "hash"],
        "cluster_seeds": list(seeds),
        "torn_seeds": list(torn_seeds),
        "recoveries": sum(l.get("recoveries", 0) for l in legs),
        "fault_trips": trips,
        "legs": legs,
        "topology": _topology(),
        "transfers": _transfers_snapshot(),
    })


# ---------------------------------------------------------------------------
# ingress coalescing (ISSUE 3: grouped fan-in merges on the replica hot path)

def bench_ingest():
    """``--ingest``: runtime-level ingress throughput, coalescing on vs
    off — the live-runtime counterpart of the grouped-merge kernel bench.

    Topology: 64 sender replicas fanning into one receiver over a
    LocalTransport (the 64-neighbour CPU fallback shape), each sender's
    keys engineered into a disjoint bucket range (the sharded-writer
    workload where ingress batching groups maximally). Per round every
    sender mutates fresh keys and eagerly pushes one delta-interval
    ``EntriesMsg``; the measured quantity is the receiver's
    ``process_pending`` drain — one ``merge_rows_into`` dispatch per
    message (sequential) vs grouped fan-in dispatches (coalesced). Both
    receivers consume the IDENTICAL message stream and the bench asserts
    their final states are bit-identical (the parity property, live)
    before reporting. Host-bound dispatch amortisation is the measured
    effect, so this runs wherever invoked (no device claim dance)."""
    import dataclasses as _dc
    import statistics

    from delta_crdt_ex_tpu import AWLWWMap
    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.models.binned import BinnedStore
    from delta_crdt_ex_tpu.runtime import sync as sync_proto
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock
    from delta_crdt_ex_tpu.runtime.transport import LocalTransport
    from delta_crdt_ex_tpu.utils.hashing import key_hash64_batch

    n_senders = 8 if SMOKE else 64
    rounds = 3 if SMOKE else 10
    keys_per_round = 2 if SMOKE else 4
    depth = 7 if SMOKE else 10  # buckets = senders × disjoint range
    buckets = 1 << depth
    span = buckets // n_senders
    max_coalesce = 16

    # per-sender key pools: scan a hash batch once, bin ints by bucket
    need = keys_per_round * (rounds + 1)
    pools: list[list[int]] = [[] for _ in range(n_senders)]
    base = 0
    while min(len(p) for p in pools) < need:
        cand = list(range(base, base + (1 << 16)))
        hs = np.asarray(key_hash64_batch(cand), np.uint64)
        owner = (hs & np.uint64(buckets - 1)).astype(np.int64) // span
        for k, o in zip(cand, owner.tolist()):
            if o < n_senders and len(pools[o]) < need:
                pools[o].append(k)
        base += 1 << 16

    transport = LocalTransport()
    clock = LogicalClock()
    # bin capacity sized for the WHOLE run's per-bucket Poisson tail: a
    # sender outgrowing its bin tier mid-run changes its slice lane
    # width, which (correctly) splits coalesce groups at the tier
    # boundary and burns fresh compiles — real systems hit that once per
    # growth, a 10-round bench would hit it mid-measurement
    mk = lambda **kw: start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock,
        capacity=buckets * 16, tree_depth=depth, **kw,
    )
    senders = [mk(name=f"ing_s{i}") for i in range(n_senders)]
    rc = mk(name="ing_coal", node_id=777, ingress_coalesce=True,
            max_coalesce=max_coalesce)
    rs = mk(name="ing_seq", node_id=777, ingress_coalesce=False)
    for s in senders:
        s.set_neighbours([rc, rs])

    def entries_to(r):
        msgs = [m for m in transport.drain(r.addr)
                if isinstance(m, sync_proto.EntriesMsg)]
        for m in msgs:
            transport.send(r.addr, m)
        return len(msgs)

    from delta_crdt_ex_tpu.utils import jitcache

    dts: dict[str, list[float]] = {"coalesced": [], "sequential": []}
    pre_jit: dict = {}
    pre_tr1: dict = {}
    pre_tr2: dict = {}
    for rnd in range(rounds + 1):  # round 0 is jit/compile warmup
        if rnd == rounds - 1:
            pre_tr1 = _transfers_snapshot()
        if rnd == rounds:
            # entering the LAST measured round: every shape tier the
            # steady state uses must already be compiled
            pre_jit = jitcache.compile_counts()
            pre_tr2 = _transfers_snapshot()
        for i, s in enumerate(senders):
            for k in pools[i][rnd * keys_per_round:(rnd + 1) * keys_per_round]:
                s.mutate("add", [k, k])
        for s in senders:
            s.sync_to_all()
        for tag, r in (("coalesced", rc), ("sequential", rs)):
            n = entries_to(r)
            assert n >= n_senders, (tag, rnd, n)
            t0 = time.perf_counter()
            r.process_pending()
            if rnd > 0:
                dts[tag].append(time.perf_counter() - t0)
        for s in senders:
            transport.drain(s.addr)  # walk back-traffic is not the measurement

    # live parity gate: the speedup must not change observable state
    for c in (f.name for f in _dc.fields(BinnedStore)):
        assert np.array_equal(
            np.asarray(getattr(rc.state, c)), np.asarray(getattr(rs.state, c))
        ), f"coalesced/sequential state diverged: {c}"
    assert rc._seq == rs._seq

    # ISSUE 12 gate: the hot merge/mutate/extract roots compiled NOTHING
    # in the last round — zero steady-state compiles per shape bucket —
    # and the counter is visible on the /metrics export surface
    jit_counts = _jit_steady_gate(
        "ingest",
        ("merge_rows", "row_apply", "extract_own_delta"),
        pre_jit, jitcache.compile_counts(),
    )
    _jit_metrics_probe(("merge_rows",))
    # ISSUE 17 gate: per-round audited crossings are steady too
    transfers_per_round = _transfer_steady_gate(
        "ingest", pre_tr1, pre_tr2, _transfers_snapshot()
    )

    per_round = n_senders
    rate = lambda ds: per_round / statistics.median(ds)
    coal, seq = rate(dts["coalesced"]), rate(dts["sequential"])
    ing = rc.stats()["ingress"]
    log(
        f"ingest: coalesced {coal:.1f} vs sequential {seq:.1f} msgs/sec "
        f"({coal / seq:.2f}x; merges/dispatch "
        f"{ing['merges_per_dispatch']}, hist {ing['coalesce_depth_hist']})"
    )
    _emit({
        "metric": "runtime_ingest_merges_per_sec" + ("_smoke" if SMOKE else ""),
        "unit": "merges/sec",
        "stat": f"median_of_{rounds}_rounds",
        "value": round(coal, 2),
        "coalesced_merges_per_sec": round(coal, 2),
        "sequential_merges_per_sec": round(seq, 2),
        "coalesce_speedup": round(coal / seq, 3),
        "aggregate_merges_per_sec": {
            "coalesced": round(rounds * per_round / sum(dts["coalesced"]), 2),
            "sequential": round(rounds * per_round / sum(dts["sequential"]), 2),
        },
        "merges_per_dispatch": ing["merges_per_dispatch"],
        "coalesce_depth_hist": {str(k): v for k, v in ing["coalesce_depth_hist"].items()},
        "parity": "bit_for_bit_state_checked",
        "jit_compiles": jit_counts,
        "jit_steady_state": "zero_compiles_in_last_round",
        "transfers_per_round": transfers_per_round,
        "neighbours": n_senders,
        "rounds": rounds,
        "keys_per_round": keys_per_round,
        "tree_depth": depth,
        "max_coalesce": max_coalesce,
        "backend": "cpu",
        "topology": _topology(),
        "transfers": _transfers_snapshot(),
    })


# ---------------------------------------------------------------------------
# hierarchical anti-entropy (ISSUE 15: reduction-tree gossip)

def bench_tree():
    """``--tree``: propagation rounds + bytes-on-wire, reduction-tree
    gossip vs flat 64-neighbour gossip at 256 simulated peers.

    Two isolated universes run the IDENTICAL probe workload: a
    deepest-tier writer adds a fresh key, then global rounds tick (every
    replica syncs once, messages deliver to quiescence — one round = one
    sync interval of real time, intra-round delivery being the
    network-latency ≪ sync-interval regime). Flat gossip covers the 64
    direct neighbours in round 1 but transitive spread waits a round per
    generation of digest walks; the tree's relays re-emit coalesced
    merged slices at the end of every drain pass, so propagation
    cascades through the whole tree within the writer's round. Gates
    asserted IN-RUN: median propagation rounds ≥2× better than flat,
    total bytes-on-wire ≥1.5× better, canonical end-state parity
    bit-for-bit between every tree/flat replica pair, zero steady-state
    compiles on the relay merge/extraction roots. Host-bound topology
    effects: runs wherever invoked (no device claim dance). The flat
    legs ride the same artifact so the ratios are self-contained."""
    import pickle
    import statistics

    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock
    from delta_crdt_ex_tpu.runtime.transport import LocalTransport
    from delta_crdt_ex_tpu.utils import jitcache

    peers = 16 if SMOKE else 256
    # the flat baseline must fan out to FEWER than half the peers or
    # the per-replica coverage median degenerates to round 1 by
    # construction (64-of-256 is the real topology's shape; the smoke
    # scale keeps the same under-half proportion)
    flat_neighbours = min(4 if SMOKE else 64, peers - 1)
    fanout = 4 if SMOKE else 8
    probes = 2 if SMOKE else 3
    depth = 6
    max_rounds = 12

    class CountingTransport(LocalTransport):
        """LocalTransport with wire-byte accounting: every delivered
        message is costed at its pickled size (what a socket transport
        would ship), the cross-universe comparable byte metric."""

        def __init__(self):
            super().__init__()
            self.bytes = 0
            self.msgs = 0

        def send(self, addr, msg):
            ok = super().send(addr, msg)
            if ok:
                self.bytes += len(pickle.dumps(msg, protocol=4))
                self.msgs += 1
            return ok

    def build(tag, tree, obs=None):
        transport = CountingTransport()
        clock = LogicalClock()
        reps = [
            start_link(
                threaded=False, transport=transport, clock=clock,
                name=f"{tag}{i}", node_id=i + 1, capacity=512,
                obs=obs,
                # writer tables pre-sized for the whole membership:
                # slice writer tables flood gid knowledge through the
                # universe, and mid-probe R-tier growth would recompile
                # the hot roots DURING the measured rounds (a real
                # growth event, but not the steady state this gate
                # measures — production fleets saturate gid knowledge
                # in their first minutes)
                replica_capacity=2 * peers,
                tree_depth=depth, sync_timeout=600.0,
                tree_gossip=tree, tree_fanout=fanout,
            )
            for i in range(peers)
        ]
        addrs = [r.addr for r in reps]
        if tree:
            for r in reps:
                r.set_neighbours(addrs)
        else:
            # the flat baseline: 64 deterministic pseudo-random
            # neighbours per replica (the seed's 64-neighbour topology)
            rng = np.random.default_rng(7)
            for i, r in enumerate(reps):
                others = [a for j, a in enumerate(addrs) if j != i]
                picks = rng.choice(len(others), flat_neighbours, replace=False)
                r.set_neighbours([others[j] for j in sorted(picks)])
        return transport, reps

    def global_round(reps):
        for r in reps:
            r.sync_to_all()
        for _ in range(2000):
            if not sum(r.process_pending() for r in reps):
                return
        raise AssertionError("universe did not quiesce")

    def run_probes(tag, transport, reps, writer_idx):
        # settle membership/warmup traffic outside the measurement
        for _ in range(2):
            global_round(reps)
        cover_rounds: list[int] = []  # pooled per-(probe, replica)
        full_rounds: list[int] = []
        probe_bytes: list[int] = []
        probe_msgs: list[int] = []
        pre_jit = {}
        pre_tr: list = []  # ledger snapshots entering the last 2 probes
        per_peer: dict = {}  # peer addr -> [cover round per probe]
        for p in range(probes):
            if p >= probes - 2 and tag == "tree":
                pre_tr.append(_transfers_snapshot())
            if p == probes - 1 and tag == "tree":
                # entering the LAST measured probe of the LAST universe:
                # every steady-state shape must already be compiled
                pre_jit = jitcache.compile_counts()
            key = f"probe-{p}"
            writer = reps[writer_idx]
            writer.mutate("add", [key, p])
            covered = {writer_idx}
            b0, m0 = transport.bytes, transport.msgs
            rnd = 0
            while len(covered) < peers and rnd < max_rounds:
                rnd += 1
                global_round(reps)
                for i, r in enumerate(reps):
                    if i not in covered and r.read_keys([key]):
                        covered.add(i)
                        cover_rounds.append(rnd)
                        per_peer.setdefault(str(r.addr), []).append(rnd)
            assert len(covered) == peers, (
                f"{tag}: probe {p} never reached full coverage "
                f"({len(covered)}/{peers} after {max_rounds} rounds)"
            )
            full_rounds.append(rnd)
            probe_bytes.append(transport.bytes - b0)
            probe_msgs.append(transport.msgs - m0)
        return {
            "median_propagation_rounds": statistics.median(cover_rounds),
            "full_coverage_rounds": full_rounds,
            "bytes_per_probe": probe_bytes,
            "msgs_per_probe": probe_msgs,
            "bytes_total": sum(probe_bytes),
            "msgs_total": sum(probe_msgs),
            # the hand count the lag tracer must reproduce (ISSUE 17
            # satellite): per-peer coverage rounds, one entry per probe
            "cover_observations": len(cover_rounds),
            "cover_rounds_sum": sum(cover_rounds),
            "cover_rounds_by_peer": per_peer,
        }, pre_jit, pre_tr

    _stage(f"tree-gossip: {peers} peers, fanout {fanout} vs flat "
           f"{flat_neighbours}-neighbour")
    from delta_crdt_ex_tpu.runtime.metrics import Observability

    # lag tracer on the tree universe at sample_every=1: EVERY writer
    # commit is a sample, so the crdt_propagation_rounds histogram must
    # reproduce the hand count below exactly (ISSUE 17 satellite)
    obs_plane = Observability(lag_sample_every=1)
    flat_t, flat_reps = build("f", tree=False)
    tree_t, tree_reps = build("t", tree=True, obs=obs_plane)
    topo = tree_reps[0]._tree_refresh()
    # the honest worst case: the writer sits at the DEEPEST tier (same
    # index writes in the flat universe)
    writer_idx = max(
        range(peers), key=lambda i: topo.tier.get(tree_reps[i].addr, 0)
    )
    flat_stats, _, _ = run_probes("flat", flat_t, flat_reps, writer_idx)
    tree_stats, pre_jit, pre_tr = run_probes(
        "tree", tree_t, tree_reps, writer_idx
    )

    # ISSUE 12 gate: zero steady-state compiles on the relay merge /
    # re-emission roots across the last measured probe
    jit_counts = _jit_steady_gate(
        "tree",
        ("merge_rows", "extract_rows", "row_apply", "winners_for_keys"),
        pre_jit, jitcache.compile_counts(),
    )
    # ISSUE 17 gate: audited device-host crossings are steady per probe
    # (digest-ladder fetches excepted: the lazy level cache fills on
    # demand along whichever tree path the probe key hashed into)
    transfers_per_probe = _transfer_steady_gate(
        "tree", pre_tr[0], pre_tr[1], _transfers_snapshot(),
        demand_ok=("replica.digest_levels",),
    )

    # ISSUE 17 satellite: cross-check the hand-counted propagation
    # rounds against the dot-provenance lag tracer. Every probe commit
    # is sampled (sample_every=1); a peer lands an observation in
    # crdt_propagation_rounds when its applied watermark of the WRITER
    # advances — a provenance-bearing event (walk-equality ack / entries
    # carrying the writer's seq), which in tree gossip only the writer's
    # direct sync partners see (relays re-emit under their own
    # provenance). For every (writer, peer) pair the tracer covers, its
    # observation count and round total must reproduce the hand count
    # EXACTLY — global_round opens the writer's round before delivering
    # to quiescence, so commit and coverage bracket the same note_round
    # calls. A drift means the tracer's watermark events no longer see
    # what the read_keys probe sees.
    rounds_hist = obs_plane.lag.rounds
    writer_addr = str(tree_reps[writer_idx].addr)
    by_peer = tree_stats["cover_rounds_by_peer"]
    covered_pairs = [
        lb for lb in rounds_hist.label_sets() if lb[0] == writer_addr
    ]
    assert covered_pairs, (
        "lag tracer recorded no writer-origin coverage at "
        "sample_every=1 — the watermark events vanished"
    )
    tracer_count = 0
    tracer_sum = 0.0
    for lb in covered_pairs:
        hand = by_peer.get(lb[1])
        assert hand is not None, (
            f"lag tracer observed peer {lb[1]} the hand count never saw"
        )
        n, s = rounds_hist.count(lb), rounds_hist.sum(lb)
        assert n == len(hand), (
            f"peer {lb[1]}: tracer observations {n} != hand-counted "
            f"probes {len(hand)}"
        )
        assert s == float(sum(hand)), (
            f"peer {lb[1]}: tracer propagation-round total {s} != "
            f"hand-counted {sum(hand)} (rounds {hand})"
        )
        tracer_count += n
        tracer_sum += s
    obs_plane.close()

    # parity: both universes saw the same op stream — every replica
    # pair must agree canonically, bit for bit
    _stage("tree-gossip: canonical parity sweep")
    for _ in range(3):  # belt-and-braces full convergence
        global_round(flat_reps)
        global_round(tree_reps)
    want = tree_reps[0].canonical_state_bytes()
    for i in range(peers):
        ct = tree_reps[i].canonical_state_bytes()
        cf = flat_reps[i].canonical_state_bytes()
        assert ct == cf, f"tree/flat canonical state diverged at peer {i}"
        assert ct == want, f"tree universe did not converge at peer {i}"

    rounds_ratio = (
        flat_stats["median_propagation_rounds"]
        / tree_stats["median_propagation_rounds"]
    )
    bytes_ratio = flat_stats["bytes_total"] / tree_stats["bytes_total"]
    msgs_ratio = flat_stats["msgs_total"] / tree_stats["msgs_total"]
    assert rounds_ratio >= 2.0, (
        f"median propagation rounds: tree must be >=2x better, got "
        f"{rounds_ratio:.2f}x (flat "
        f"{flat_stats['median_propagation_rounds']}, tree "
        f"{tree_stats['median_propagation_rounds']})"
    )
    assert bytes_ratio >= 1.5, (
        f"bytes-on-wire: tree must be >=1.5x better, got {bytes_ratio:.2f}x"
    )

    relay_stats = [
        r.stats()["tree"] for r in tree_reps
        if r.stats()["tree"]["reemits"]
    ]
    folds = sum(s["msgs_folded"] for s in relay_stats)
    reemits = sum(s["reemits"] for s in relay_stats)
    log(
        f"tree: rounds {tree_stats['median_propagation_rounds']} vs flat "
        f"{flat_stats['median_propagation_rounds']} ({rounds_ratio:.1f}x), "
        f"bytes {tree_stats['bytes_total']} vs {flat_stats['bytes_total']} "
        f"({bytes_ratio:.1f}x), msgs ratio {msgs_ratio:.1f}x, "
        f"{reemits} re-emissions folding {folds} inbound frames"
    )
    _emit({
        "metric": "tree_gossip_propagation" + ("_smoke" if SMOKE else ""),
        "unit": "x_better_than_flat",
        "stat": f"median_over_{probes}_probes",
        "value": round(rounds_ratio, 3),
        "rounds_ratio": round(rounds_ratio, 3),
        "bytes_ratio": round(bytes_ratio, 3),
        "msgs_ratio": round(msgs_ratio, 3),
        "peers": peers,
        "tree_fanout": fanout,
        "tree_depth": topo.depth,
        "tree_root": str(topo.root),
        "writer_tier": int(topo.tier.get(tree_reps[writer_idx].addr, 0)),
        "flat_neighbours": flat_neighbours,
        "tree": tree_stats,
        "flat": flat_stats,
        "relay_reemits": reemits,
        "relay_msgs_folded": folds,
        "relay_folds_per_reemit": round(folds / reemits, 3) if reemits else 0.0,
        "parity": "bit_for_bit_canonical_state_checked_all_pairs",
        "jit_compiles": jit_counts,
        "jit_steady_state": "zero_compiles_in_last_probe",
        "transfers_per_probe": transfers_per_probe,
        "lag_tracer_cross_check": {
            "pairs_covered": len(covered_pairs),
            "observations": tracer_count,
            "rounds_sum": tracer_sum,
            "status": "exact_match_on_covered_pairs",
        },
        "backend": "cpu",
        "topology": _topology(),
        "transfers": _transfers_snapshot(),
    })


# ---------------------------------------------------------------------------
# log-shipping catch-up (ISSUE 4: serve WAL ranges instead of walking)

def bench_catchup():
    """``--catchup``: cold-peer rejoin, log-shipping vs digest-walk.

    One writer with a WAL; two receivers with EQUAL node ids so their
    final states are bit-comparable — one catching up via log shipping
    (``GetLogMsg`` range fetches), one via the classic digest walk. Per
    lag depth (just behind / mid-log / past the compaction horizon) the
    writer churns while both receivers are partitioned (sent slices are
    dropped in flight, so push cursors advance and the eager-delta leg
    cannot re-cover — the reconnect genuinely pays catch-up), then each
    receiver reconnects ALONE and the drive loop runs until the
    protocol's own convergence signal (the writer's ack watermark
    reaching its seq). Measured per mode: round trips, messages, wire
    bytes (pickled frame sizes), wall seconds. Parity is asserted
    in-run: bit-identical receiver state arrays (the lag script avoids
    the ctx-only corner — fresh adds + removes of pre-lag keys) and
    read equality with the writer. Host-bound protocol work, so it runs
    wherever invoked (no device claim dance)."""
    import dataclasses as _dc
    import pickle
    import shutil
    import tempfile

    from delta_crdt_ex_tpu import AWLWWMap
    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.models.binned import BinnedStore
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock
    from delta_crdt_ex_tpu.runtime.transport import LocalTransport

    depth = 10 if SMOKE else 12
    preload = 256 if SMOKE else 1500
    max_sync = 32 if SMOKE else 200  # the walk's per-round transfer bound
    # lag depths in ops: "just behind" is one busy sync interval's worth
    # (already past max_sync_size under heavy write load — the millions-
    # of-users reconnect shape), mid-log an order of magnitude more
    lag_depths = {
        "just_behind": 48 if SMOKE else 256,
        "mid_log": 256 if SMOKE else 2048,
        "past_horizon": 192 if SMOKE else 1024,
    }
    MAX_ROUNDS = 400

    # past_horizon: the writer's checkpoint compacts up to the
    # membership-retain bound, so the receiver's watermark lands BELOW
    # the horizon with a retained suffix of ~7/8 of the lag — the
    # realistic rejoin shape under membership-gated compaction (a
    # monitored peer's records are retained up to the bound). The
    # receiver must then choose: suffix chunks + prefix walk, or pure
    # walk. With the suffix dominating (ratio 7 >= the replica's
    # catchup_suffix_ratio 4) it streams the suffix and walks only the
    # short prefix.
    def lag_records(lag_ops):
        return lag_ops // 8 + (lag_ops // 8 + 3) // 4  # batches + removes

    def build_universe(tag, mode, log_shipping, lag_ops):
        """One isolated (transport, writer, receiver) world per mode:
        fixed node ids and a fresh logical clock make the two writers
        bit-identical given the identical script, so the receivers'
        final states are bit-comparable across universes with zero
        cross-talk between the measured runs."""
        root = tempfile.mkdtemp(prefix=f"catchup_{tag}_{mode}_")
        transport = LocalTransport()
        clock = LogicalClock()
        mk = lambda name, **kw: start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=(1 << depth) * 8, tree_depth=depth,
            sync_timeout=0.001, max_sync_size=max_sync, **kw,
        )
        if tag == "past_horizon":
            compaction = dict(
                membership_compaction=True,
                membership_retain=lag_records(lag_ops) * 7 // 8,
                # fine-grained segments: compaction reclaims whole
                # segments, so the horizon must be able to land mid-lag
                segment_bytes=4 << 10,
            )
        else:
            compaction = dict(
                membership_compaction=False,
                # realistic rolling segments: the range cursor then SKIPS
                # pre-watermark segments by their start_seq instead of
                # rescanning the whole history from one giant segment
                segment_bytes=64 << 10,
            )
        a = mk(
            f"cu_w_{tag}_{mode}", node_id=111, wal_dir=root, fsync_mode="none",
            compact_every=10**9, **compaction,
        )
        b = mk(f"cu_r_{tag}_{mode}", node_id=777, log_shipping=log_shipping)
        return root, transport, a, b

    # catch-up is a RECONNECT-over-a-network protocol: what it saves is
    # round trips, and an in-process zero-RTT loop hides exactly that
    # cost. Each non-empty delivery direction therefore pays one
    # simulated hop of link latency (default 10 ms ≈ a cross-zone hop;
    # override via BENCH_CATCHUP_LAT_S, 0 restores the raw CPU-only
    # numbers). Rounds/messages/bytes are latency-independent either way.
    LAT = float(os.environ.get("BENCH_CATCHUP_LAT_S", "0.01"))

    def drive_until_acked(transport, a, b, tag, timed=False):
        """Sync rounds + delivery until the protocol's own convergence
        signal: the writer's ack watermark reaching its seq (a walk
        equality or a completed catch-up stream — the same ack)."""
        t0 = time.perf_counter()
        rounds = msgs = nbytes = 0
        while a._ack_seq.get(b.addr, -1) != a._seq:
            a.sync_to_all()
            msgs_b = transport.drain(b.addr)
            if msgs_b and timed:
                time.sleep(LAT)  # one hop toward the receiver
            for m in msgs_b:
                msgs += 1
                if timed:
                    nbytes += len(pickle.dumps(m, protocol=4))
                b.handle(m)
            msgs_a = transport.drain(a.addr)
            if msgs_a and timed:
                time.sleep(LAT)  # one hop back to the writer
            for m in msgs_a:
                msgs += 1
                a.handle(m)
            if not msgs_b and not msgs_a:
                time.sleep(0.0015)  # idle tick: let the sync slot expire
            rounds += 1
            if rounds > MAX_ROUNDS:
                raise AssertionError(f"{tag}: no convergence in {rounds} rounds")
        return {
            "rounds": rounds,
            "messages": msgs,
            "to_receiver_bytes": nbytes,
            "wall_s": round(time.perf_counter() - t0, 6),
        }

    def run_mode(tag, mode, log_shipping, lag_ops):
        root, transport, a, b = build_universe(tag, mode, log_shipping, lag_ops)
        try:
            # prime: converge (walk mode needs several truncated rounds)
            # and seed the receiver's watermark
            a.set_neighbours([b])
            transport.pump()
            for s in range(0, preload, 64):
                a.mutate_batch(
                    "add", [[f"p{j}", j] for j in range(s, min(s + 64, preload))]
                )
            drive_until_acked(transport, a, b, f"{tag}/{mode}/prime")
            assert b.read() == a.read()
            assert b._applied_seq.get(a.addr) == a._seq

            # the lag: small batches build a real record suffix; fresh
            # adds + removes of pre-lag keys (bit-parity-safe workload)
            step = 8
            for s in range(0, lag_ops, step):
                a.mutate_batch(
                    "add", [[f"{tag}_{j}", j] for j in range(s, min(s + step, lag_ops))]
                )
                if (s // step) % 4 == 0:
                    a.mutate("remove", [f"p{(s // step) % preload}"])
            a.sync_to_all()
            transport.drain(b.addr)  # partition: slices lost in flight
            if tag == "past_horizon":
                # the writer compacts past the receiver's floor (up to
                # the membership-retain bound): the log can only serve
                # the retained suffix, the prefix must walk — and the
                # retained suffix must DOMINATE the prefix, or the peer
                # (correctly) skips the chunks and this tag would
                # measure walk-vs-walk
                a.checkpoint()
                horizon = a.stats()["wal"]["horizon"]
                w = b._applied_seq.get(a.addr, 0)
                assert horizon > w, "past_horizon: lag not past the horizon"
                assert a._seq - horizon >= b.catchup_suffix_ratio * (horizon - w), (
                    f"past_horizon: retained suffix {a._seq - horizon} does "
                    f"not dominate prefix {horizon - w}"
                )
            time.sleep(0.002)  # expire the in-flight sync slot

            # reconnect: the measured quantity
            chunks0 = b.stats()["catchup"]["chunks_applied"]
            res = drive_until_acked(transport, a, b, f"{tag}/{mode}", timed=True)
            res["chunks_applied_reconnect"] = (
                b.stats()["catchup"]["chunks_applied"] - chunks0
            )
            # serving-side transfer-padding accounting, captured before
            # the universe root (and with it the writer's WAL) goes away
            srv = a.stats()["catchup"]
            res["served"] = {
                k: srv[k]
                for k in (
                    "store", "chunks_served", "bytes_shipped",
                    "lanes_shipped", "entries_shipped", "chunk_fill_ratio",
                )
            }
            assert b.read() == a.read()
            return res, a, b
        finally:
            shutil.rmtree(root, ignore_errors=True)

    def run_depth(tag, lag_ops, repeats=1):
        """Each repeat rebuilds both universes from scratch; the
        reported wall time is the MEDIAN over repeats (single runs at
        the tens-of-ms scale flip on scheduler noise), rounds/bytes are
        deterministic and must agree across repeats."""
        import statistics

        runs = []
        for _rep in range(repeats):
            res_log, a1, b1 = run_mode(tag, "logship", True, lag_ops)
            res_walk, a2, b2 = run_mode(tag, "walk", False, lag_ops)
            runs.append((res_log, res_walk))
            # in-run parity gate (every repeat): identical scripts in
            # both universes must leave writers AND receivers bit-identical
            for c in (f.name for f in _dc.fields(BinnedStore)):
                assert np.array_equal(
                    np.asarray(getattr(a1.state, c)), np.asarray(getattr(a2.state, c))
                ), f"{tag}: writer universes diverged on {c} (bench bug)"
                assert np.array_equal(
                    np.asarray(getattr(b1.state, c)), np.asarray(getattr(b2.state, c))
                ), f"{tag}: log/walk receiver state diverged on {c}"
        cu = b1.stats()["catchup"]
        med = lambda rs: round(statistics.median(rs), 6)
        res_log = dict(runs[-1][0], wall_s=med([r[0]["wall_s"] for r in runs]))
        res_walk = dict(runs[-1][1], wall_s=med([r[1]["wall_s"] for r in runs]))
        log(
            f"catchup[{tag}]: log {res_log['rounds']} rounds "
            f"{res_log['wall_s']:.3f}s {res_log['to_receiver_bytes']}B "
            f"vs walk {res_walk['rounds']} rounds {res_walk['wall_s']:.3f}s "
            f"{res_walk['to_receiver_bytes']}B "
            f"(chunks {cu['chunks_applied']}, horizon_fb {cu['horizon_fallbacks']})"
        )
        return {
            "lag_ops": lag_ops,
            "repeats": repeats,
            "log_shipping": res_log,
            "digest_walk": res_walk,
            "chunks_applied": cu["chunks_applied"],
            "horizon_fallbacks": cu["horizon_fallbacks"],
            # per-store transfer-padding accounting (ISSUE 8 satellite:
            # the PR 4 "chunk bytes ~2x the walk's" finding is padding —
            # alive entries per shipped lane; 1.0 = dense extraction)
            "served": res_log["served"],
            "round_speedup": round(res_walk["rounds"] / max(res_log["rounds"], 1), 3),
            "wall_speedup": round(res_walk["wall_s"] / max(res_log["wall_s"], 1e-9), 3),
            "parity": "bit_for_bit_state_checked",
        }

    # discarded warmups, one per distinct lag size: extraction AND
    # grouped-merge compile tiers depend on the touched-row count, so
    # every measured depth must find its tiers already compiled
    for ops in sorted(set(lag_depths.values())):
        run_depth("jitwarm", ops)
    results = {tag: run_depth(tag, ops, repeats=3) for tag, ops in lag_depths.items()}
    for tag in ("just_behind", "mid_log"):
        r = results[tag]
        assert r["log_shipping"]["rounds"] < r["digest_walk"]["rounds"], (
            f"{tag}: log shipping must beat the walk on rounds"
        )
        assert r["log_shipping"]["wall_s"] < r["digest_walk"]["wall_s"], (
            f"{tag}: log shipping must beat the walk on wall time"
        )
    # ROADMAP follow-up (a): past the horizon the peer either streams a
    # DOMINANT retained suffix (this tag's shape — chunks must flow and
    # win rounds) or skips the chunks for the pure walk; never the
    # measured-0.8x chunks-plus-walk-on-everything shape
    ph = results["past_horizon"]
    assert ph["log_shipping"]["chunks_applied_reconnect"] > 0, (
        "past_horizon: dominant suffix must engage the clamped stream"
    )
    assert ph["round_speedup"] >= 1.0, (
        f"past_horizon: rounds ratio {ph['round_speedup']} < 1.0 — the "
        f"suffix-dominance mode decision regressed"
    )
    mid = results["mid_log"]
    _emit({
        "metric": "catchup_logship_round_speedup" + ("_smoke" if SMOKE else ""),
        "unit": "x (walk rounds / log rounds, mid_log depth)",
        "stat": "median_wall_of_3_repeats_per_depth",
        # bytes are UNCOMPRESSED pickled frames: log chunks carry padded
        # full-row slices (mostly zeros), which the TCP transport's
        # per-buffer compression probe shrinks 25x+ in real deployments
        "bytes_note": "uncompressed pickle; padded slices compress heavily on the wire",
        "value": mid["round_speedup"],
        "wall_speedup_mid_log": mid["wall_speedup"],
        "depths": results,
        "tree_depth": depth,
        "preload_keys": preload,
        "max_sync_size": max_sync,
        "link_latency_s_per_hop": LAT,
        "backend": "cpu",
        "topology": _topology(),
        "transfers": _transfers_snapshot(),
    })


# ---------------------------------------------------------------------------
# batched replica fleets (ISSUE 6: one vmapped dispatch serves N replicas)

def bench_fleet():
    """``--fleet``: aggregate ingress throughput, batched fleet vs N
    per-replica event loops, at 64/256/1024 simulated replicas on CPU.

    Topology per size N: N sender replicas, each pushing delta-interval
    ``EntriesMsg`` slices to one fleet member and one solo receiver
    (pairwise-equal node ids, identical streams). The measured quantity
    per round is draining all N receiver mailboxes: the solo universe
    runs N ``process_pending`` loops (one ``merge_rows`` dispatch per
    replica — today's one-loop-per-replica shape), the fleet drains all
    N into ONE vmapped kernel launch over a leading replica axis
    (``runtime/transition.fleet_merge_rows``). Walk back-traffic is
    filtered to entries (the ingest-bench methodology): merge
    throughput is the quantity, not digest-walk cost, which is
    identical per replica on both sides. Parity is asserted IN-RUN
    after the timed rounds: every fleet member's state arrays must be
    bit-identical to its solo twin's, and sequence numbers equal — the
    speedup is disqualified if it changes observable state. Host-bound
    dispatch amortisation is the measured effect, so this runs wherever
    invoked (no device claim dance)."""
    import dataclasses as _dc
    import statistics

    from delta_crdt_ex_tpu import AWLWWMap
    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.models.binned import BinnedStore
    from delta_crdt_ex_tpu.runtime import sync as sync_proto
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock
    from delta_crdt_ex_tpu.runtime.fleet import Fleet
    from delta_crdt_ex_tpu.runtime.transport import LocalTransport

    sizes = [8, 16] if SMOKE else [64, 256, 1024]
    rounds = 2 if SMOKE else 5
    keys_per_round = 2 if SMOKE else 4
    depth = 6  # 64 buckets per replica: the many-small-replicas shape
    cols = tuple(f.name for f in _dc.fields(BinnedStore))

    def entries_to(transport, addr):
        msgs = [
            m
            for m in transport.drain(addr)
            if isinstance(m, sync_proto.EntriesMsg)
        ]
        for m in msgs:
            transport.send(addr, m)
        return len(msgs)

    def run_size(n: int) -> dict:
        _stage(f"fleet size {n}: building {3 * n} replicas")
        transport = LocalTransport()
        clock = LogicalClock()
        mk = lambda **kw: start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=(1 << depth) * 16, tree_depth=depth, **kw,
        )
        senders = [mk(name=f"flt_s{n}_{i}") for i in range(n)]
        fleet = Fleet(
            [mk(name=f"flt_f{n}_{i}", node_id=10_000 + i) for i in range(n)]
        )
        solos = [mk(name=f"flt_o{n}_{i}", node_id=10_000 + i) for i in range(n)]
        for i, s in enumerate(senders):
            s.set_neighbours([fleet.replicas[i], solos[i]])

        from delta_crdt_ex_tpu.utils import jitcache

        dts: dict[str, list[float]] = {"fleet": [], "solo": []}
        pre_jit: dict = {}
        pre_tr1: dict = {}
        pre_tr2: dict = {}
        for rnd in range(rounds + 1):  # round 0 is jit/compile warmup
            if rnd == rounds - 1:
                pre_tr1 = _transfers_snapshot()
            if rnd == rounds:
                # entering the LAST measured round: the steady state's
                # shape buckets must all be warm
                pre_jit = jitcache.compile_counts()
                pre_tr2 = _transfers_snapshot()
            base = 1_000_003 * rnd
            for i, s in enumerate(senders):
                for j in range(keys_per_round):
                    k = base + i * 1000 + j
                    s.mutate("add", [k, k])
            for s in senders:
                s.sync_to_all()
            for r in fleet.replicas:
                assert entries_to(transport, r.addr) >= 1
            t0 = time.perf_counter()
            fleet.drain()
            if rnd > 0:
                dts["fleet"].append(time.perf_counter() - t0)
            for r in solos:
                assert entries_to(transport, r.addr) >= 1
            t0 = time.perf_counter()
            for r in solos:
                r.process_pending()
            if rnd > 0:
                dts["solo"].append(time.perf_counter() - t0)
            for s in senders:
                transport.drain(s.addr)  # walk back-traffic: not measured

        # in-run parity gate: the speedup must not change observable state
        for i in range(n):
            rf, rs = fleet.replicas[i], solos[i]
            assert rf._seq == rs._seq > 0, (n, i)
            for c in cols:
                assert np.array_equal(
                    np.asarray(getattr(rf.state, c)),
                    np.asarray(getattr(rs.state, c)),
                ), f"fleet/solo state diverged at size {n}, member {i}: {c}"

        # ISSUE 12 gate: the batched AND solo merge roots compiled
        # nothing during the last measured round — zero steady-state
        # compiles per shape bucket at this fleet size
        jit_counts = _jit_steady_gate(
            f"fleet size {n}",
            ("fleet_merge_rows", "merge_rows", "row_apply"),
            pre_jit, jitcache.compile_counts(),
        )
        # ISSUE 17 gate: per-round audited crossings steady too
        transfers_per_round = _transfer_steady_gate(
            f"fleet size {n}", pre_tr1, pre_tr2, _transfers_snapshot(),
            demand_ok=("replica.digest_levels",),
        )

        rate = lambda ds: n / statistics.median(ds)
        f_rate, s_rate = rate(dts["fleet"]), rate(dts["solo"])
        st = fleet.stats()
        out = {
            "replicas": n,
            "fleet_merges_per_sec": round(f_rate, 2),
            "solo_merges_per_sec": round(s_rate, 2),
            "speedup": round(f_rate / s_rate, 3),
            "aggregate_merges_per_sec": {
                "fleet": round(rounds * n / sum(dts["fleet"]), 2),
                "solo": round(rounds * n / sum(dts["solo"]), 2),
            },
            "avg_occupancy": st["avg_occupancy"],
            "occupancy_hist": {str(k): v for k, v in st["occupancy_hist"].items()},
            "ragged_fill_ratio": st["ragged_fill_ratio"],
            "fallbacks": st["fallbacks"],
            "parity": "bit_for_bit_state_checked",
            "jit_compiles": jit_counts,
            "jit_steady_state": "zero_compiles_in_last_round",
            "transfers_per_round": transfers_per_round,
        }
        log(
            f"fleet {n}: {f_rate:.1f} vs solo {s_rate:.1f} merges/sec "
            f"({out['speedup']}x; occupancy {st['avg_occupancy']}, "
            f"fill {st['ragged_fill_ratio']})"
        )
        return out

    results = {str(n): run_size(n) for n in sizes}
    gate = str(16 if SMOKE else 256)
    # the compile counter must also be visible on the export surface
    _jit_metrics_probe(("fleet_merge_rows",))

    # ---- egress leg (ISSUE 10): batched sync ticks vs N sync_to_all ----

    import pickle

    from delta_crdt_ex_tpu.runtime.clock import LogicalClock as _LClock
    from delta_crdt_ex_tpu.runtime.fleet import Fleet as _Fleet

    class _Sink:
        """Mailbox-only receiver: registered on the transport so sends
        route and monitors succeed, never handles anything — the egress
        bench measures the SENDING side only."""

        device = None

    def _norm_out(msg):
        """Address-free canonical body of one outbound sync message —
        the parity witness AND the wire-byte quantity (the twins differ
        only in names)."""
        if isinstance(msg, sync_proto.EntriesMsg):
            return (
                "entries", np.asarray(msg.buckets),
                {c: np.asarray(v) for c, v in msg.arrays.items()},
                msg.payloads,
            )
        if isinstance(msg, sync_proto.DiffMsg):
            return (
                "diff", msg.level, np.asarray(msg.idx),
                [np.asarray(b) for b in msg.blocks], msg.seq,
                msg.log_horizon,
            )
        return (type(msg).__name__,)

    def _norm_eq(a, b) -> bool:
        if type(a) is not type(b):
            return False
        if isinstance(a, np.ndarray):
            return a.shape == b.shape and bool(np.array_equal(a, b))
        if isinstance(a, dict):
            return set(a) == set(b) and all(_norm_eq(a[k], b[k]) for k in a)
        if isinstance(a, (tuple, list)):
            return len(a) == len(b) and all(map(_norm_eq, a, b))
        return a == b

    def run_egress_size(n: int) -> dict:
        _stage(f"fleet egress size {n}: building {2 * n} replicas")
        transport = LocalTransport()
        mk = lambda **kw: start_link(
            AWLWWMap, threaded=False, transport=transport, clock=_LClock(),
            capacity=(1 << depth) * 16, tree_depth=depth,
            # in-flight sync slots are cleared explicitly between rounds;
            # a wall-clock expiry landing between the fleet tick and the
            # solo loop (loaded host) would open a walk on one side only
            # and fail the parity gate spuriously
            sync_timeout=3600.0, **kw,
        )
        members = [mk(name=f"eg_f{n}_{i}", node_id=10_000 + i) for i in range(n)]
        solos = [mk(name=f"eg_o{n}_{i}", node_id=10_000 + i) for i in range(n)]
        for i in range(n):
            transport.register(f"eg_fr{n}_{i}", _Sink())
            transport.register(f"eg_or{n}_{i}", _Sink())
            members[i].set_neighbours([f"eg_fr{n}_{i}"])
            solos[i].set_neighbours([f"eg_or{n}_{i}"])
        fleet = _Fleet(members)

        dts: dict[str, list[float]] = {"fleet": [], "solo": []}
        msgs_per_tick = bytes_per_tick = 0
        for rnd in range(rounds + 1):  # round 0 is jit/compile warmup
            base = 1_000_003 * rnd
            for i in range(n):
                for j in range(keys_per_round):
                    k = base + i * 1000 + j
                    members[i].mutate("add", [k, k])
                    solos[i].mutate("add", [k, k])
            t0 = time.perf_counter()
            fleet.sync_tick()
            if rnd > 0:
                dts["fleet"].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            for r in solos:
                r.sync_to_all()
            if rnd > 0:
                dts["solo"].append(time.perf_counter() - t0)
            # in-run parity gate: every receiver pair's streams must be
            # canonically identical and byte-for-byte equal on the wire
            rnd_msgs = rnd_bytes = 0
            for i in range(n):
                fm = transport.drain(f"eg_fr{n}_{i}")
                om = transport.drain(f"eg_or{n}_{i}")
                assert len(fm) == len(om) > 0, (n, rnd, i)
                for a, b in zip(fm, om):
                    na, nb = _norm_out(a), _norm_out(b)
                    assert _norm_eq(na, nb), (n, rnd, i, na[0])
                    wa = len(pickle.dumps(na, protocol=4))
                    assert wa == len(pickle.dumps(nb, protocol=4))
                    rnd_msgs += 1
                    rnd_bytes += wa
                # clear in-flight slots identically: every round opens
                members[i]._outstanding.clear()
                members[i]._sync_open_seq.clear()
                solos[i]._outstanding.clear()
                solos[i]._sync_open_seq.clear()
            if rnd > 0:
                msgs_per_tick = rnd_msgs
                bytes_per_tick = rnd_bytes
        # cursor-state parity: the batched path advanced exactly what
        # the per-member loop did
        for i in range(n):
            for va, vb in zip(
                members[i]._push_cursor.values(), solos[i]._push_cursor.values()
            ):
                assert np.array_equal(va, vb), (n, i)
            assert list(members[i]._rm_cursor.values()) == list(
                solos[i]._rm_cursor.values()
            ), (n, i)

        rate = lambda ds: n / statistics.median(ds)
        f_rate, s_rate = rate(dts["fleet"]), rate(dts["solo"])
        eg = fleet.stats()["egress"]
        out = {
            "replicas": n,
            "fleet_member_syncs_per_sec": round(f_rate, 2),
            "solo_member_syncs_per_sec": round(s_rate, 2),
            "speedup": round(f_rate / s_rate, 3),
            "aggregate_member_syncs_per_sec": {
                "fleet": round(rounds * n / sum(dts["fleet"]), 2),
                "solo": round(rounds * n / sum(dts["solo"]), 2),
            },
            "messages_per_tick": msgs_per_tick,
            "wire_bytes_per_tick": bytes_per_tick,
            "egress_dispatches": eg["dispatches"],
            "avg_bucket_occupancy": eg["avg_bucket_occupancy"],
            "batched_jobs": eg["batched_jobs"],
            "solo_jobs": eg["solo_jobs"],
            "trees_batched": eg["trees_batched"],
            "parity": "bit_for_bit_wire_openers_cursors_checked",
        }
        log(
            f"fleet egress {n}: {f_rate:.1f} vs solo {s_rate:.1f} "
            f"member-syncs/sec ({out['speedup']}x; "
            f"{msgs_per_tick} msgs/{bytes_per_tick} B per tick, "
            f"bucket occupancy {eg['avg_bucket_occupancy']})"
        )
        return out

    def run_tcp_frame_demo(n: int) -> dict:
        """FleetFrameMsg aggregation over a real TCP hop: n members'
        pushes + openers to a co-located peer process ride one frame
        per endpoint per tick (LocalTransport has no frames, so the
        frames-per-tick quantity needs the real codec)."""
        from delta_crdt_ex_tpu.runtime.tcp_transport import TcpTransport

        ta, tb = TcpTransport(), TcpTransport()
        try:
            mk = lambda t, nm, nid: start_link(
                AWLWWMap, threaded=False, transport=t, clock=_LClock(),
                capacity=(1 << depth) * 16, tree_depth=depth, name=nm,
                node_id=nid, sync_timeout=3600.0,
            )
            members = [mk(ta, f"tcp_m{i}", 20_000 + i) for i in range(n)]
            peers = [mk(tb, f"tcp_p{i}", 30_000 + i) for i in range(n)]
            for i in range(n):
                members[i].set_neighbours([(f"tcp_p{i}", tb.endpoint)])
            fleet = _Fleet(members)
            for i in range(n):
                members[i].mutate("add", [i, i])
            fleet.sync_tick()  # primes the pooled connection + HELLO
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                with ta._lock:
                    conn = ta._conns.get(tb.endpoint)
                if conn is not None and conn.accepts_f:
                    break
                time.sleep(0.02)
            ticks = 3
            for rnd in range(1, ticks + 1):
                for i in range(n):
                    members[i].mutate("add", [rnd * 1000 + i, i])
                for m in members:
                    m._outstanding.clear()
                    m._sync_open_seq.clear()
                fleet.sync_tick()
            # convergence through the frames proves the decode path
            deadline = time.monotonic() + 30.0
            done = False
            while time.monotonic() < deadline and not done:
                for i in range(n):
                    for msg in tb.drain(f"tcp_p{i}"):
                        peers[i].handle(msg)
                done = all(
                    peers[i].read().get(rnd * 1000 + i) == i
                    for i in range(n)
                    for rnd in range(1, ticks + 1)
                )
                if not done:
                    time.sleep(0.02)
            assert done, "peers did not converge through fleet frames"
            eg = fleet.stats()["egress"]
            assert eg["frames"] >= ticks, eg
            out = {
                "replicas": n,
                "ticks": ticks,
                "frames": eg["frames"],
                "frame_members": eg["frame_members"],
                "members_per_frame": eg["members_per_frame"],
                "frames_per_tick": round(eg["frames"] / eg["ticks"], 3),
            }
            log(
                f"tcp frame demo {n}: {eg['frames']} frames, "
                f"{eg['members_per_frame']} members/frame"
            )
            return out
        finally:
            ta.close()
            tb.close()

    egress_results = {str(n): run_egress_size(n) for n in sizes}
    tcp_demo = run_tcp_frame_demo(sizes[0])

    import datetime as _dt

    from delta_crdt_ex_tpu.utils.devices import detected_topology

    egress_artifact = {
        "metric": "fleet_egress_member_syncs_per_sec" + ("_smoke" if SMOKE else ""),
        "topology": detected_topology(),
        "transfers": _transfers_snapshot(),
        "unit": "member-syncs/sec",
        "stat": f"median_of_{rounds}_rounds",
        "value": egress_results[gate]["fleet_member_syncs_per_sec"],
        "speedup_at_gate": egress_results[gate]["speedup"],
        "sizes": egress_results,
        "tcp_frame_demo": tcp_demo,
        "rounds": rounds,
        "keys_per_round": keys_per_round,
        "tree_depth": depth,
        "parity": "bit_for_bit_wire_openers_cursors_checked",
        "backend": "cpu",
        "utc": _dt.datetime.now(_dt.timezone.utc).isoformat(),
    }
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "results",
        f"fleet_egress_cpu_{_dt.date.today().strftime('%Y%m%d')}.json",
    )
    with open(out_path, "w") as f:
        json.dump(egress_artifact, f, indent=2)
        f.write("\n")
    log(f"fleet egress artifact written to {out_path}")

    _emit({
        "metric": "fleet_batched_merges_per_sec" + ("_smoke" if SMOKE else ""),
        "unit": "merges/sec",
        "stat": f"median_of_{rounds}_rounds",
        "value": results[gate]["fleet_merges_per_sec"],
        "speedup_at_gate": results[gate]["speedup"],
        "sizes": results,
        "egress": egress_artifact,
        "rounds": rounds,
        "keys_per_round": keys_per_round,
        "tree_depth": depth,
        "backend": "cpu",
    })


# ---------------------------------------------------------------------------
# mesh-sharded fleet (ISSUE 13)

def bench_fleet_mesh():
    """``--fleet --mesh``: the shard_map fleet + intra-mesh delivery
    plane vs the vmap fleet, at shard counts {1, 2, 4, 8} over 8 forced
    CPU devices (the same topology tier-1 runs under; a chip window
    reruns this unchanged and the artifact's ``topology`` field tells
    the two apart).

    Topology per shard count S: n members in ONE fleet gossiping
    pairwise among themselves — member i ↔ member i+n/2, so every
    co-mesh edge crosses half the mesh (rotation distance S/2: the
    plane MUST permute) and each member's writer set stabilises after
    one exchange (ring gossip would keep widening the combined-slice
    writer tier for ~n rounds and defeat the steady-state compile
    gate) — plus one external sink receiver per member (the
    TCP-fallback path, and the wire-parity witness). Each round times
    the batched egress tick (member-syncs/sec) and the ingress drain of
    the plane-delivered entries (aggregate merges/sec), mesh vs the
    vmap twin fed the identical script. Parity is asserted IN-RUN per
    round and at the end: sink streams canonically identical and
    byte-for-byte equal in pickled wire size, end states bit-identical,
    sequence numbers and in-flight ack slots equal. The ISSUE 12 gate
    rides along: entering the last measured round, the mesh entry roots
    (merge/extract/tree/ctr twins + the plane rotate) must compile
    NOTHING — steady state is warm per (bucket geometry × shard count).
    A hash-backend leg repeats the gate shard count for cross-backend
    parity. Artifact: ``benchmarks/results/fleet_mesh_cpu_<date>.json``.
    """
    import dataclasses as _dc
    import datetime as _dt
    import pickle
    import statistics

    from delta_crdt_ex_tpu import AWLWWMap
    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.runtime import sync as sync_proto
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock
    from delta_crdt_ex_tpu.runtime.fleet import Fleet
    from delta_crdt_ex_tpu.runtime.transport import LocalTransport
    from delta_crdt_ex_tpu.utils import jitcache
    from delta_crdt_ex_tpu.utils.devices import detected_topology, fleet_mesh

    topo = detected_topology()
    assert topo["global_devices"] >= 8, (
        f"mesh bench needs 8 devices (forced-CPU): {topo}"
    )

    n = 8 if SMOKE else 64
    rounds = 2 if SMOKE else 4
    keys_per_round = 2 if SMOKE else 4
    depth = 6
    shard_counts = [1, 2, 4, 8]

    class _Sink:
        """Mailbox-only receiver (the egress bench pattern): sends
        route, monitors succeed, nothing is handled."""

        device = None

    def _norm_out(msg):
        if isinstance(msg, sync_proto.EntriesMsg):
            return (
                "entries", np.asarray(msg.buckets),
                {c: np.asarray(v) for c, v in msg.arrays.items()},
                msg.payloads,
            )
        if isinstance(msg, sync_proto.DiffMsg):
            return (
                "diff", msg.level, np.asarray(msg.idx),
                [np.asarray(b) for b in msg.blocks], msg.seq,
                msg.log_horizon,
            )
        return (type(msg).__name__,)

    def _norm_eq(a, b) -> bool:
        if type(a) is not type(b):
            return False
        if isinstance(a, np.ndarray):
            return a.shape == b.shape and bool(np.array_equal(a, b))
        if isinstance(a, dict):
            return set(a) == set(b) and all(_norm_eq(a[k], b[k]) for k in a)
        if isinstance(a, (tuple, list)):
            return len(a) == len(b) and all(map(_norm_eq, a, b))
        return a == b

    def run_shards(
        store: str, shards: int, tag: str, narrow: bool = True
    ) -> dict:
        _stage(
            f"mesh fleet [{store}] shards={shards}"
            f"{'' if narrow else ' (legacy padded plane)'}: "
            f"building {2 * n} members"
        )
        transport = LocalTransport()
        mk = lambda nm, nid: start_link(
            AWLWWMap, threaded=False, transport=transport,
            clock=LogicalClock(), capacity=(1 << depth) * 16,
            tree_depth=depth, name=nm, node_id=nid, sync_timeout=3600.0,
            store=store,
        )
        fm = [mk(f"{tag}m{i}", 10_000 + i) for i in range(n)]
        vm = [mk(f"{tag}v{i}", 10_000 + i) for i in range(n)]
        for i in range(n):
            transport.register(f"{tag}mr{i}", _Sink())
            transport.register(f"{tag}vr{i}", _Sink())
            # one co-mesh partner half the mesh away (the plane path,
            # rotation distance S/2) + one external sink (the fallback
            # path + the wire-parity witness)
            fm[i].set_neighbours([fm[(i + n // 2) % n], f"{tag}mr{i}"])
            vm[i].set_neighbours([vm[(i + n // 2) % n], f"{tag}vr{i}"])
        f_mesh = Fleet(fm, mesh=fleet_mesh(shards), mesh_narrow=narrow)
        f_vmap = Fleet(vm)

        dts: dict[str, list[float]] = {
            "mesh_egress": [], "vmap_egress": [],
            "mesh_ingress": [], "vmap_ingress": [],
        }
        ingress_counts: list[int] = []
        wire_bytes = 0
        pre_jit: dict = {}
        mesh_roots = (
            "mesh_fleet_merge_rows", "mesh_fleet_interval_slices",
            "mesh_fleet_tree_from_leaves", "mesh_fleet_own_ctr_columns",
            "mesh_plane_rotate", "mesh_plane_exchange",
            "merge_rows", "row_apply",
        ) if store == "binned" else (
            "mesh_fleet_hash_merge_rows", "mesh_fleet_hash_interval_slices",
            "mesh_fleet_hash_row_counts", "mesh_fleet_hash_own_delta_counts",
            "mesh_fleet_tree_from_leaves", "mesh_fleet_own_ctr_columns",
            "mesh_plane_rotate", "mesh_plane_exchange",
        )
        pre_tr1: dict = {}
        pre_tr2: dict = {}
        for rnd in range(rounds + 1):  # round 0 is jit/compile warmup
            if rnd == rounds - 1:
                pre_tr1 = _transfers_snapshot()
            if rnd == rounds:
                pre_jit = jitcache.compile_counts()
                pre_tr2 = _transfers_snapshot()
            base = 1_000_003 * rnd
            for i in range(n):
                for j in range(keys_per_round):
                    k = base + i * 1000 + j
                    fm[i].mutate("add", [k, k])
                    vm[i].mutate("add", [k, k])
            t0 = time.perf_counter()
            f_mesh.sync_tick()
            if rnd > 0:
                dts["mesh_egress"].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            f_vmap.sync_tick()
            if rnd > 0:
                dts["vmap_egress"].append(time.perf_counter() - t0)
            # wire parity: the sinks' streams must be canonically equal
            # and byte-for-byte equal in pickled size
            rnd_bytes = 0
            for i in range(n):
                a_msgs = transport.drain(f"{tag}mr{i}")
                b_msgs = transport.drain(f"{tag}vr{i}")
                assert len(a_msgs) == len(b_msgs) > 0, (shards, rnd, i)
                for a, b in zip(a_msgs, b_msgs):
                    na, nb = _norm_out(a), _norm_out(b)
                    assert _norm_eq(na, nb), (shards, rnd, i, na[0])
                    wa = len(pickle.dumps(na, protocol=4))
                    assert wa == len(pickle.dumps(nb, protocol=4))
                    rnd_bytes += wa
            # ingress: drain the plane-delivered intra-mesh entries.
            # Walk back-traffic is filtered to entries first (the
            # bench_fleet methodology): merge throughput is the
            # quantity, and the walk's GetDiff full-row repairs carry
            # data-dependent wire tiers that would defeat the
            # zero-steady-state-compile gate with workload noise
            for r in fm + vm:
                kept = [
                    m
                    for m in transport.drain(r.addr)
                    if isinstance(m, sync_proto.EntriesMsg)
                ]
                for m in kept:
                    transport.send(r.addr, m)
            t0 = time.perf_counter()
            m_msgs = f_mesh.drain()
            if rnd > 0:
                dts["mesh_ingress"].append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            v_msgs = f_vmap.drain()
            if rnd > 0:
                dts["vmap_ingress"].append(time.perf_counter() - t0)
                ingress_counts.append(m_msgs)
                wire_bytes = rnd_bytes
            assert m_msgs == v_msgs > 0, (shards, rnd, m_msgs, v_msgs)
            for r in fm + vm:
                r._outstanding.clear()
                r._sync_open_seq.clear()

        # in-run parity gate: state bits, seq, ack slots
        cols = tuple(f.name for f in _dc.fields(type(fm[0].state)))
        for i in range(n):
            assert fm[i]._seq == vm[i]._seq > 0, (shards, i)
            assert len(fm[i]._outstanding) == len(vm[i]._outstanding)
            for c in cols:
                av, bv = getattr(fm[i].state, c), getattr(vm[i].state, c)
                if not hasattr(av, "shape"):
                    assert av == bv, (shards, i, c)
                    continue
                assert np.array_equal(np.asarray(av), np.asarray(bv)), (
                    f"mesh/vmap state diverged at shards={shards}, "
                    f"member {i}: {c}"
                )

        # ISSUE 12 gate: zero steady-state compiles on the mesh roots
        jit_counts = _jit_steady_gate(
            f"mesh fleet [{store}] shards={shards}", mesh_roots,
            pre_jit, jitcache.compile_counts(),
        )
        # ISSUE 17 gate: per-tick audited crossings steady (the ledger
        # aggregates both twins — meshplane.* sites isolate the plane)
        transfers_per_tick = _transfer_steady_gate(
            f"mesh fleet [{store}] shards={shards}",
            pre_tr1, pre_tr2, _transfers_snapshot(),
            demand_ok=("replica.digest_levels",),
        )

        rate = lambda ds: n / statistics.median(ds)
        st = f_mesh.stats()
        ms = st["mesh"]
        assert ms["enabled"] and ms["shards"] == shards
        assert ms["intra_entries"] > 0 and ms["fallback_entries"] > 0
        if shards > 1:
            assert ms["exchanges"] > 0 and ms["permuted_bytes"] > 0
        out = {
            "replicas": n,
            "shards": shards,
            "store": store,
            "mesh_member_syncs_per_sec": round(rate(dts["mesh_egress"]), 2),
            "vmap_member_syncs_per_sec": round(rate(dts["vmap_egress"]), 2),
            "aggregate_merges_per_sec": {
                "mesh": round(
                    sum(ingress_counts) / sum(dts["mesh_ingress"]), 2
                ),
                "vmap": round(
                    sum(ingress_counts) / sum(dts["vmap_ingress"]), 2
                ),
            },
            "egress_speedup_vs_vmap": round(
                rate(dts["mesh_egress"]) / rate(dts["vmap_egress"]), 3
            ),
            "ingress_msgs_per_round": ingress_counts[-1],
            "wire_bytes_per_tick": wire_bytes,
            "intra_entries": ms["intra_entries"],
            "fallback_entries": ms["fallback_entries"],
            "permuted_bytes": ms["permuted_bytes"],
            "exchanges": ms["exchanges"],
            "members_per_shard": ms["members_per_shard"],
            "jit_compiles": jit_counts,
            "jit_steady_state": "zero_compiles_in_last_round",
            "transfers_per_tick": transfers_per_tick,
            "plane_narrow": narrow,
            "parity": "bit_for_bit_state_wire_acks_checked",
        }
        log(
            f"mesh [{store}] shards={shards}: "
            f"{out['mesh_member_syncs_per_sec']} vs vmap "
            f"{out['vmap_member_syncs_per_sec']} member-syncs/sec "
            f"({out['egress_speedup_vs_vmap']}x; "
            f"{ms['intra_entries']} intra / {ms['fallback_entries']} "
            f"fallback entries, {ms['permuted_bytes']} B permuted)"
        )
        return out

    legs = {
        str(s): run_shards("binned", s, f"mzb{s}_") for s in shard_counts
    }
    # cross-backend parity at the gate shard count
    hash_leg = run_shards("hash", shard_counts[-1], "mzh_")

    # ---- ISSUE 17 retirement evidence: narrow vs legacy padded plane --
    # Re-run the gate shard count with the padded host round-trip
    # exchange (every leg above already proved the narrow plane's state
    # parity against the vmap twin). The ledger delta is the claim: the
    # narrow plane crosses the boundary ONCE per tick (dense rows,
    # meshplane.ship_dense) where the padded plane crossed twice per
    # exchange group with full [shards, depth, ...] buffers — strictly
    # fewer crossings AND strictly fewer bytes, same delivered state.
    legacy_leg = run_shards(
        "binned", shard_counts[-1], "mzl_", narrow=False
    )
    plane_delta = lambda leg: {
        s: d
        for s, d in leg["transfers_per_tick"].items()
        if s.startswith("meshplane.")
    }
    narrow_plane = plane_delta(legs[str(shard_counts[-1])])
    legacy_plane = plane_delta(legacy_leg)
    assert set(narrow_plane) == {"meshplane.ship_dense"}, narrow_plane
    assert set(legacy_plane) == {
        "meshplane.ship_padded", "meshplane.deliver_padded",
    }, legacy_plane
    sum_counts = lambda d: sum(v["count"] for v in d.values())
    sum_bytes = lambda d: sum(v["bytes"] for v in d.values())
    assert sum_counts(narrow_plane) < sum_counts(legacy_plane), (
        narrow_plane, legacy_plane,
    )
    assert sum_bytes(narrow_plane) < sum_bytes(legacy_plane), (
        narrow_plane, legacy_plane,
    )
    log(
        f"plane retirement: narrow {sum_counts(narrow_plane)} crossings "
        f"/ {sum_bytes(narrow_plane)} B per tick vs legacy "
        f"{sum_counts(legacy_plane)} / {sum_bytes(legacy_plane)} B"
    )

    # the mesh compile counter must ride the export surface too
    _jit_metrics_probe(("mesh_fleet_merge_rows", "mesh_plane_rotate"))

    artifact = {
        "metric": "fleet_mesh_member_syncs_per_sec" + ("_smoke" if SMOKE else ""),
        "unit": "member-syncs/sec",
        "stat": f"median_of_{rounds}_rounds",
        "value": legs[str(shard_counts[-1])]["mesh_member_syncs_per_sec"],
        "speedup_vs_vmap_at_gate": legs[str(shard_counts[-1])][
            "egress_speedup_vs_vmap"
        ],
        "shard_counts": legs,
        "hash_backend_gate": hash_leg,
        "plane_retirement": {
            "narrow_per_tick": narrow_plane,
            "legacy_per_tick": legacy_plane,
            "legacy_leg": legacy_leg,
            "crossings_per_tick": {
                "narrow": sum_counts(narrow_plane),
                "legacy": sum_counts(legacy_plane),
            },
            "bytes_per_tick": {
                "narrow": sum_bytes(narrow_plane),
                "legacy": sum_bytes(legacy_plane),
            },
            "status": "narrow_strictly_lower_with_state_parity",
        },
        "replicas": n,
        "rounds": rounds,
        "keys_per_round": keys_per_round,
        "tree_depth": depth,
        "topology": detected_topology(),
        "transfers": _transfers_snapshot(),
        "parity": "bit_for_bit_state_wire_acks_checked",
        "backend": "cpu",
        # honest finding (the PR 8 pattern): on forced-CPU virtual
        # devices every sharded dispatch pays per-shard argument
        # placement + per-partition execution that a resident-state TPU
        # mesh never sees — CPU numbers here pin PARITY and COMPILE
        # DISCIPLINE; the throughput claim waits for the chip window,
        # which reruns this leg unchanged (the topology field tells the
        # artifacts apart).
        "cpu_finding": (
            "sharded-dispatch placement overhead dominates on virtual "
            "CPU devices; mesh-vs-vmap throughput is not meaningful on "
            "this backend — parity and zero-steady-state-compile gates "
            "are the CPU-verifiable claims"
        ),
        "utc": _dt.datetime.now(_dt.timezone.utc).isoformat(),
    }
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "results",
        f"fleet_mesh_cpu_{_dt.date.today().strftime('%Y%m%d')}.json",
    )
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    log(f"fleet mesh artifact written to {out_path}")
    _emit(artifact)


# ---------------------------------------------------------------------------
# hash-table dot store vs binned store (ISSUE 8)

def bench_hashstore():
    """``--hashstore``: the open-addressing hash-table dot store against
    the binned row store — ingest merges/sec, growth-event counts, and
    extracted wire bytes, with the bit-for-bit parity gate asserted
    in-run.

    Three phases over two symmetric universes (hash↔hash and
    binned↔binned, one seeded script):

    1. **load** — N senders bulk-load ``BENCH_HASHSTORE_KEYS`` keys
       (default 1M; ``BENCH_SMOKE`` shrinks) and eager-push delta
       slices into one receiver per universe; measured: receiver drain
       merges/sec, growth events (binned tier promotions vs hash
       rehashes), and EntriesMsg wire bytes (the dense-extraction win).
    2. **steady state** — further rounds touch EXISTING keys only; the
       hash universe must report ZERO growth events (asserted: update
       churn reuses killed lanes — no tombstones — so no rehash stalls,
       the ROADMAP claim this backend exists for).
    3. **fleet** — a fleet of hash members at steady state: batched
       vmapped dispatches with zero growth events inside the batch
       (asserted).

    Parity gates (disqualify the speedup if violated): universe reads
    equal, receiver leaf digests + contexts bit-equal (digest equality
    ⇒ content equality), sequence numbers equal; plus a shared-sender
    leg where one binned writer feeds a hash receiver and a binned
    receiver with WALs — WAL segment BYTES and ack streams must be
    identical. Host-bound dispatch + transfer shape is the measured
    effect, so this runs wherever invoked (no device claim dance)."""
    import tempfile

    from delta_crdt_ex_tpu import AWLWWMap
    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.runtime import sync as sync_proto, telemetry
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock
    from delta_crdt_ex_tpu.runtime.fleet import Fleet
    from delta_crdt_ex_tpu.runtime.transport import LocalTransport

    n_senders = 4 if SMOKE else 16
    total_keys = int(
        os.environ.get("BENCH_HASHSTORE_KEYS", "2048" if SMOKE else "1000000")
    )
    steady_rounds = 2 if SMOKE else 5
    depth = 8 if SMOKE else 12  # receiver sync-index depth
    per_sender = total_keys // n_senders

    grown: dict[str, int] = {}
    growth_handler = lambda _e, _m, meta: grown.__setitem__(
        meta["name"], grown.get(meta["name"], 0) + 1
    )
    telemetry.attach(telemetry.CAPACITY_GROWN, growth_handler)

    def mk_universe(store: str):
        transport = LocalTransport()
        clock = LogicalClock()
        mk = lambda name, **kw: start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=2 * per_sender if "snd" in name else 2 * total_keys,
            tree_depth=depth, store=store, name=name, **kw,
        )
        # pinned node ids: the two universes must mint IDENTICAL dots
        # (writer gid is part of dot identity and of every entry hash)
        recv = mk(f"{store}_recv", node_id=4242)
        senders = [
            mk(f"{store}_snd{i}", node_id=1000 + i) for i in range(n_senders)
        ]
        for s in senders:
            s.set_neighbours([recv])
        return transport, senders, recv

    def drain_universe(transport, senders, recv, stats):
        """Push + drain until quiescent; accumulate time/messages/bytes."""
        while True:
            for s in senders:
                s.sync_to_all()
            msgs = [
                m
                for m in transport.drain(recv.addr)
                if isinstance(m, sync_proto.EntriesMsg)
            ]
            if not msgs:
                break
            stats["messages"] += len(msgs)
            stats["wire_bytes"] += sum(
                int(v.nbytes)
                for m in msgs
                for v in m.arrays.values()
                if hasattr(v, "nbytes")
            )
            for m in msgs:
                transport.send(recv.addr, m)
            t0 = time.perf_counter()
            recv.process_pending()
            stats["drain_s"] += time.perf_counter() - t0
            for s in senders:
                transport.drain(s.addr)  # walk back-traffic: not measured

    results: dict[str, dict] = {}
    universes: dict[str, tuple] = {}
    rng = np.random.default_rng(0)
    key_terms = rng.permutation(np.arange(1, total_keys + 1, dtype=np.int64))
    for store in ("hash", "binned"):
        _stage(f"hashstore: building {store} universe ({total_keys} keys)")
        transport, senders, recv = mk_universe(store)
        universes[store] = (transport, senders, recv)
        st = {
            "messages": 0, "wire_bytes": 0, "drain_s": 0.0,
            "load_growth": 0, "steady_growth": 0, "steady_messages": 0,
            "steady_drain_s": 0.0, "steady_wire_bytes": 0,
        }
        results[store] = st
        grown.clear()
        t_load = time.perf_counter()
        for i, s in enumerate(senders):
            shard = key_terms[i * per_sender : (i + 1) * per_sender]
            s.mutate_batch("add", [[int(k), int(k)] for k in shard])
        drain_universe(transport, senders, recv, st)
        st["load_wall_s"] = round(time.perf_counter() - t_load, 3)
        st["load_growth"] = sum(grown.values())
        # steady state: same keys, fresh values — no growth expected
        grown.clear()
        steady = {
            "messages": 0, "wire_bytes": 0, "drain_s": 0.0,
        }
        for rnd in range(steady_rounds):
            for i, s in enumerate(senders):
                shard = key_terms[i * per_sender : i * per_sender + 64]
                s.mutate_batch("add", [[int(k), int(k) + rnd + 1] for k in shard])
            drain_universe(transport, senders, recv, steady)
        st["steady_messages"] = steady["messages"]
        st["steady_drain_s"] = round(steady["drain_s"], 4)
        st["steady_wire_bytes"] = steady["wire_bytes"]
        st["steady_growth"] = sum(grown.values())
        st["drain_s"] = round(st["drain_s"], 4)
        st["merges_per_sec"] = round(st["messages"] / st["drain_s"], 2) if st["drain_s"] else 0.0
        st["steady_merges_per_sec"] = (
            round(st["steady_messages"] / st["steady_drain_s"], 2)
            if st["steady_drain_s"]
            else 0.0
        )
        log(
            f"hashstore[{store}]: load {st['messages']} msgs @ "
            f"{st['merges_per_sec']} merges/s, growth {st['load_growth']}; "
            f"steady {st['steady_merges_per_sec']} merges/s, growth "
            f"{st['steady_growth']}; wire {st['wire_bytes']} B"
        )

    # the phase-2 gate: steady-state churn must not grow the hash table
    assert results["hash"]["steady_growth"] == 0, (
        f"hash store grew {results['hash']['steady_growth']}x at steady state"
    )

    # ---- parity gate 1: symmetric universes agree exactly -------------
    _stage("hashstore: parity gate (reads + canonical state + seq)")
    h_recv, b_recv = universes["hash"][2], universes["binned"][2]
    assert h_recv.read() == b_recv.read(), "hash/binned reads diverged"
    assert h_recv._seq == b_recv._seq
    for col in ("leaf", "ctx_gid", "ctx_max"):
        assert np.array_equal(
            np.asarray(getattr(h_recv.state, col)),
            np.asarray(getattr(b_recv.state, col)),
        ), f"hash/binned receiver state diverged: {col}"

    # ---- parity gate 2: shared writer, WAL bytes + ack streams --------
    _stage("hashstore: parity gate (WAL bytes + acks, shared writer)")
    with tempfile.TemporaryDirectory() as tmp:
        transport = LocalTransport()
        clock = LogicalClock()
        wmk = lambda name, store, **kw: start_link(
            AWLWWMap, threaded=False, transport=transport, clock=clock,
            capacity=1024, tree_depth=8, store=store, name=name, **kw,
        )
        writer = wmk("par_w", "binned")
        rcv = {
            store: wmk(
                f"par_{store}", store, node_id=777,
                wal_dir=os.path.join(tmp, store), fsync_mode="none",
            )
            for store in ("hash", "binned")
        }
        writer.set_neighbours(list(rcv.values()))
        script = np.random.default_rng(7)
        for _ in range(4):
            for _ in range(24):
                k = int(script.integers(0, 64))
                if script.random() < 0.75:
                    writer.mutate("add", [k, int(script.integers(0, 99))])
                else:
                    writer.mutate("remove", [k])
            writer.sync_to_all()
            for r in rcv.values():
                r.process_pending()
            back = transport.drain(writer.addr)
            norm = lambda m: (
                type(m).__name__,
                getattr(m, "level", None),
                [b.tolist() for b in getattr(m, "blocks", [])] or None,
            )
            acks_h = [norm(m) for m in back if getattr(m, "frm", getattr(m, "clear_addr", None)) == rcv["hash"].addr]
            acks_b = [norm(m) for m in back if getattr(m, "frm", getattr(m, "clear_addr", None)) == rcv["binned"].addr]
            assert acks_h == acks_b, "hash/binned reply streams diverged"
            for m in back:
                writer.handle(m)
            for r in rcv.values():
                r.process_pending()
        assert rcv["hash"].read() == rcv["binned"].read()

        def wal_bytes(rep):
            out = b""
            for p in sorted(rep._wal.segment_paths()):
                with open(p, "rb") as f:
                    out += f.read()
            return out

        assert wal_bytes(rcv["hash"]) == wal_bytes(rcv["binned"]) != b"", (
            "hash/binned WAL bytes diverged"
        )

    # ---- phase 3: hash fleet at steady state --------------------------
    _stage("hashstore: fleet steady-state phase")
    fleet_n = 4 if SMOKE else 8
    transport = LocalTransport()
    clock = LogicalClock()
    fmk = lambda name, **kw: start_link(
        AWLWWMap, threaded=False, transport=transport, clock=clock,
        capacity=4096, tree_depth=8, store="hash", name=name, **kw,
    )
    members = [fmk(f"flt_m{i}") for i in range(fleet_n)]
    fsenders = [fmk(f"flt_s{i}") for i in range(fleet_n)]
    fleet = Fleet(members)
    for i, s in enumerate(fsenders):
        s.set_neighbours([members[i]])
        s.mutate_batch("add", [[j, j] for j in range(256)])  # warm capacity
        s.sync_to_all()
    for r in members:
        msgs = [m for m in transport.drain(r.addr) if isinstance(m, sync_proto.EntriesMsg)]
        for m in msgs:
            transport.send(r.addr, m)
    fleet.drain()
    grown.clear()
    for rnd in range(steady_rounds):
        for s in fsenders:
            s.mutate_batch("add", [[j, j + rnd + 1] for j in range(64)])
            s.sync_to_all()
        for r in members:
            msgs = [m for m in transport.drain(r.addr) if isinstance(m, sync_proto.EntriesMsg)]
            for m in msgs:
                transport.send(r.addr, m)
        fleet.drain()
        for s in fsenders:
            transport.drain(s.addr)
    fleet_growth = sum(grown.get(m.name, 0) for m in members)
    fstats = fleet.stats()
    assert fleet_growth == 0, "hash fleet member grew mid-batch at steady state"
    assert fstats["dispatches"] >= 1, "hash fleet never batched"
    for i, m in enumerate(members):
        assert len(m.read()) == 256, i
    telemetry.detach(telemetry.CAPACITY_GROWN, growth_handler)
    log(
        f"hashstore[fleet]: {fstats['dispatches']} batched dispatches, "
        f"occupancy {fstats['avg_occupancy']}, growth {fleet_growth}"
    )

    h, b = results["hash"], results["binned"]
    _emit({
        "metric": "hashstore_ingest_merges_per_sec" + ("_smoke" if SMOKE else ""),
        "unit": "merges/sec",
        "stat": "aggregate_load_drain",
        "value": h["merges_per_sec"],
        "keys": total_keys,
        "senders": n_senders,
        "tree_depth": depth,
        "hash": h,
        "binned": b,
        "ingest_ratio_hash_vs_binned": (
            round(h["merges_per_sec"] / b["merges_per_sec"], 3)
            if b["merges_per_sec"]
            else 0.0
        ),
        "wire_bytes_ratio_hash_vs_binned": (
            round(h["wire_bytes"] / b["wire_bytes"], 4) if b["wire_bytes"] else 0.0
        ),
        "growth_events": {
            "hash_load": h["load_growth"],
            "binned_load": b["load_growth"],
            "hash_steady": h["steady_growth"],
            "binned_steady": b["steady_growth"],
            "hash_fleet_steady": fleet_growth,
        },
        "fleet": {
            "members": fleet_n,
            "dispatches": fstats["dispatches"],
            "avg_occupancy": fstats["avg_occupancy"],
            "fallbacks": fstats["fallbacks"],
        },
        "parity": "reads+leaf+ctx+seq (symmetric) and wal_bytes+acks (shared writer), asserted in-run",
        "backend": "cpu",
        "topology": _topology(),
        "transfers": _transfers_snapshot(),
    })


# ---------------------------------------------------------------------------
# serving plane (ISSUE 14: bench.py --serve)


def _serve_distinct_bucket_batches(n_batches: int, batch: int, depth: int,
                                   tag: int) -> list:
    """Batches of ``batch`` integer keys whose buckets are pairwise
    DISTINCT within each batch — the deterministic-tier admission
    workload: every grouped commit of one batch lands on exactly the
    (u=pow2(batch), m=1) ``row_apply`` tier, so the steady-state
    compile gate measures shape discipline, not key-collision luck."""
    from delta_crdt_ex_tpu.utils.hashing import key_hash64_batch

    n_buckets = 1 << depth
    out = []
    cand = tag << 40  # distinct key universe per tag
    for _ in range(n_batches):
        seen: set = set()
        keys: list = []
        while len(keys) < batch:
            chunk = list(range(cand, cand + (1 << 14)))
            cand += 1 << 14
            hs = np.asarray(key_hash64_batch(chunk), np.uint64)
            for k, b in zip(chunk, (hs & np.uint64(n_buckets - 1)).tolist()):
                if b not in seen:
                    seen.add(b)
                    keys.append(k)
                    if len(keys) == batch:
                        break
        out.append(keys)
    return out


def _serve_warm_tiers(rep, commit: int, depth: int) -> None:
    """Pre-compile every ``row_apply``/read tier the serving legs can
    hit: admission windows vary in size with client timing, and a
    fresh (u, m) tier mid-measurement costs a multi-hundred-ms XLA
    compile that snowballs the admission backlog (measured: write p50
    went seconds without this). One throwaway replica of the same
    geometry warms the process-wide cache for every leg."""
    sizes = []
    u = 1
    while u <= commit:
        sizes.append(u)
        u *= 2
    batches = _serve_distinct_bucket_batches(len(sizes), commit, depth, tag=9)
    for size, batch in zip(sizes, batches):
        rep.apply_ops([("add", [int(k), 0]) for k in batch[:size]])
    # m tiers: one key duplicated m times inside a full distinct-bucket
    # batch (u stays at the top tier, max-per-bucket count is exactly m)
    for m, batch in zip(
        (2, 4, 8, 16), _serve_distinct_bucket_batches(4, commit, depth, 10)
    ):
        ops = [("add", [int(k), 0]) for k in batch[: commit - (m - 1)]]
        ops += [("add", [int(batch[0]), j]) for j in range(m - 1)]
        rep.apply_ops(ops)
    # bulk-read tiers (pow4 wire tiers for 4-key and 64-key reads)
    rep.read_keys([int(batches[0][0]), int(batches[0][1])])
    rep.read_keys([int(k) for k in batches[0]][:64])


def _serve_percentiles(samples: list) -> dict:
    a = np.asarray(samples, np.float64)
    return {
        "n": int(a.size),
        "p50_ms": round(float(np.percentile(a, 50)) * 1e3, 3),
        "p99_ms": round(float(np.percentile(a, 99)) * 1e3, 3),
        "max_ms": round(float(a.max()) * 1e3, 3),
    }


def _serve_harness(tiny: bool = False) -> dict:
    """The ``--serve`` open-loop load harness (ISSUE 14). Legs:

    A. grouped admission vs the per-op ``mutate`` loop at N concurrent
       clients (the aggregate-write-throughput headline; ≥3x gated in
       full mode);
    B. lock-free read proof: snapshot reads complete while the replica
       lock is HELD (the structural no-replica-lock claim);
    C. bit-for-bit parity vs an unloaded twin: the loaded front door's
       committed op groups replay through the same ``apply_ops``
       entrance on a twin — state bits, WAL bytes and seq must match;
    D. open-loop mixed read/mutate traffic against a FLEET at fixed
       arrival rates (Poisson arrivals, latency measured from the
       SCHEDULED arrival — coordinated omission cannot flatter the
       tail), p50/p99 per op class gated;
    E. overload spike: admission sheds explicitly, ``/healthz`` flips
       503 over live HTTP and recovers with the queue;
    F. zero steady-state compiles on the admission/read dispatch roots
       over a deterministic-tier drain round (full mode).

    ``tiny=True`` is the tier-1 smoke shape (seconds): it gates the
    parity assert and the /healthz overload flip; the throughput ratio
    and latency numbers are reported, not gated."""
    import dataclasses as _dc
    import itertools
    import shutil
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    from delta_crdt_ex_tpu.api import start_fleet, start_link
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock
    from delta_crdt_ex_tpu.runtime.metrics import Observability
    from delta_crdt_ex_tpu.runtime.serve import Overloaded
    from delta_crdt_ex_tpu.runtime.transport import LocalTransport
    from delta_crdt_ex_tpu.utils import jitcache

    depth = 8 if tiny else 10
    cap = (1 << depth) * (32 if tiny else 128)
    clients = 8 if tiny else 64
    per_client = 25 if tiny else 150
    commit = 64 if tiny else 256
    # arrival rates are calibrated per run against the box's measured
    # closed-loop capacity (shared CI hosts swing 2x run to run — a
    # fixed rate either undershoots or collapses): the LOW rate (30%)
    # is the gated regime, the HIGH rate (70%) is reported. The
    # beyond-capacity behaviour is leg E's story: admission SHEDS
    # instead of queueing.
    rate_fracs = (0.3,) if tiny else (0.3, 0.7)
    duration = 0.8 if tiny else 2.5
    rng = np.random.default_rng(7)
    res: dict = {"tiny": tiny, "clients": clients, "commit_ops": commit}

    transport = LocalTransport()
    mk = lambda name, **kw: start_link(
        threaded=False, transport=transport, name=name, capacity=cap,
        tree_depth=depth, **kw,
    )
    _stage("serve: warming admission/read kernel tiers")
    warm_rep = mk("serve_warm")
    _serve_warm_tiers(warm_rep, commit, depth)
    warm_rep.stop()

    # ---- leg A: grouped admission vs per-op mutate ---------------------
    _stage("serve leg A: grouped admission vs per-op mutate")
    rep_po = mk("serve_perop")
    rep_gr = mk("serve_group")
    fd = rep_gr.frontdoor(max_commit_ops=commit, max_pending_ops=1 << 30)
    pools = [
        rng.integers(1, 1 << 62, size=per_client, dtype=np.uint64).tolist()
        for _ in range(clients)
    ]

    def flood(target, pools_):
        threads = [
            threading.Thread(target=lambda p=p: [target(int(k)) for k in p])
            for p in pools_
        ]
        t0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return time.perf_counter() - t0

    # warmup flood (jit tiers for both entrances), then the measured one
    warm_pools = [
        rng.integers(1, 1 << 62, size=max(per_client // 4, 4),
                     dtype=np.uint64).tolist()
        for _ in range(clients)
    ]
    flood(lambda k: rep_po.mutate("add", [k, k]), warm_pools)
    flood(lambda k: fd.mutate("add", [k, k]), warm_pools)
    dt_po = flood(lambda k: rep_po.mutate("add", [k, k]), pools)
    dt_gr = flood(lambda k: fd.mutate("add", [k, k]), pools)
    n_ops = clients * per_client
    perop_rate, grouped_rate = n_ops / dt_po, n_ops / dt_gr
    speedup = grouped_rate / perop_rate
    st = fd.stats()
    log(
        f"serve admission: grouped {grouped_rate:.0f} vs per-op "
        f"{perop_rate:.0f} ops/sec ({speedup:.2f}x; ops/commit "
        f"{st['ops_per_commit']})"
    )
    res["admission"] = {
        "clients": clients,
        "ops": n_ops,
        "grouped_ops_per_sec": round(grouped_rate, 1),
        "per_op_ops_per_sec": round(perop_rate, 1),
        "speedup": round(speedup, 3),
        "ops_per_commit": st["ops_per_commit"],
        "commit_depth_hist": {
            str(k): v for k, v in st["commit_depth_hist"].items()
        },
    }
    if not tiny:
        assert speedup >= 3.0, (
            f"grouped admission speedup {speedup:.2f} < 3.0 gate"
        )
        assert st["ops_per_commit"] > 2.0, st

    # ---- leg B: reads are replica-lock-free ----------------------------
    _stage("serve leg B: lock-held snapshot reads")
    probe_keys = [int(pools[0][0]), int(pools[1][0])]
    fd.read_keys(probe_keys)  # warm the read tier
    rep_gr._lock.acquire()
    try:
        got: list = []

        def reader():
            for _ in range(20):
                got.append(len(fd.read_keys(probe_keys)))

        t = threading.Thread(target=reader)
        t.start()
        t.join(timeout=30)
        assert not t.is_alive() and len(got) == 20, (
            "snapshot reads blocked on the held replica lock"
        )
    finally:
        rep_gr._lock.release()
    res["lock_free_reads"] = {"reads_while_lock_held": 20}
    rep_po.stop()
    rep_gr.stop()

    # ---- leg C: bit-for-bit parity vs the unloaded twin ----------------
    _stage("serve leg C: loaded-vs-twin parity")
    root = tempfile.mkdtemp(prefix="servebench_")
    try:
        a = mk(
            "serve_par_a", node_id=4242, clock=LogicalClock(),
            wal_dir=os.path.join(root, "a"), fsync_mode="none",
        )
        fda = a.frontdoor(max_commit_ops=commit, max_pending_ops=1 << 30,
                          journal=True)
        par_pools = [
            rng.integers(1, 1 << 62, size=per_client, dtype=np.uint64).tolist()
            for _ in range(max(clients // 2, 2))
        ]
        flood(lambda k: fda.mutate("add", [k, k]), par_pools)
        fda.close()
        journal = fda.journal()
        b = mk(
            "serve_par_b", node_id=4242, clock=LogicalClock(),
            wal_dir=os.path.join(root, "b"), fsync_mode="none",
        )
        for group in journal:
            b.apply_ops(group)
        for c in (f.name for f in _dc.fields(a.model.Store)):
            va, vb = getattr(a.state, c), getattr(b.state, c)
            assert np.array_equal(np.asarray(va), np.asarray(vb)), (
                f"loaded/twin state diverged: {c}"
            )
        assert a._seq == b._seq, (a._seq, b._seq)

        def wal_bytes(rep):
            segs = sorted(
                os.path.join(rep._wal.directory, p)
                for p in os.listdir(rep._wal.directory)
            )
            return b"".join(open(s, "rb").read() for s in segs)

        wa, wb = wal_bytes(a), wal_bytes(b)
        assert wa == wb, (len(wa), len(wb))
        log(
            f"serve parity: state bit-identical, WAL {len(wa)} bytes "
            f"identical across {len(journal)} committed groups"
        )
        res["parity"] = {
            "groups": len(journal),
            "ops": sum(len(g) for g in journal),
            "wal_bytes": len(wa),
            "result": "bit_for_bit_state_and_wal",
        }
        a.stop()
        b.stop()
    finally:
        shutil.rmtree(root, ignore_errors=True)

    # ---- leg D: open-loop mixed traffic against a fleet ----------------
    _stage("serve leg D: open-loop fleet load")
    n_members = 2 if tiny else 4
    fleet = start_fleet(
        n_members, threaded=True,
        names=[f"serve_f{i}" for i in range(n_members)],
        capacity=cap, tree_depth=depth, sync_interval=0.25,
        sync_timeout=600.0,
    )
    # ring topology: gossip stays live under load without the full-mesh
    # fan-out saturating the shared fleet thread (which would starve
    # the admission workers of the member locks — measured: full mesh
    # at 50 ms intervals put write p50 at seconds)
    for i, rep in enumerate(fleet.replicas):
        rep.set_neighbours([fleet.replicas[(i + 1) % n_members]])
    ffd = fleet.frontdoor(max_commit_ops=commit, max_pending_ops=1 << 30)
    read_pool = [f"olr{j}" for j in range(64)]
    for j, k in enumerate(read_pool):
        ffd.mutate("add", [k, j])
    # warm every member's read tier and multi-op commit tiers (the load
    # phase must measure serving, not first-touch XLA compiles)
    ffd.read_keys(read_pool)
    warm_tickets = [
        ffd.mutate_async("add", [f"olw{j}", j]) for j in range(8 * commit)
    ]
    for tks in warm_tickets:
        for tk in tks:
            tk.result(120)
    workers = 6 if tiny else 16

    # closed-loop capacity calibration: the same 70/30 mix issued
    # back-to-back by the same worker pool — the box's serveable rate
    # this run, which the open-loop arrival schedule is sized against
    cal_end = time.perf_counter() + (0.5 if tiny else 1.0)
    cal_counts = [0] * workers

    def calibrate(idx):
        i = 0
        while time.perf_counter() < cal_end:
            if i % 10 < 7:
                ffd.read_keys([read_pool[(idx * 7 + i) % 64]])
            else:
                ffd.mutate("add", [f"cal{idx}/{i}", i], timeout=60)
            cal_counts[idx] += 1
            i += 1

    cal_threads = [
        threading.Thread(target=calibrate, args=(i,)) for i in range(workers)
    ]
    t_cal = time.perf_counter()
    for t in cal_threads:
        t.start()
    for t in cal_threads:
        t.join()
    capacity = sum(cal_counts) / (time.perf_counter() - t_cal)
    log(f"serve open-loop: calibrated capacity {capacity:.0f} mixed ops/sec")
    rates = [max(50, int(capacity * f)) for f in rate_fracs]
    res["open_loop"] = {
        "members": n_members,
        "calibrated_capacity_ops_per_sec": round(capacity, 1),
        "rates": {},
    }
    # phase list: one UNMEASURED soak at the top rate first — the
    # gossip path's wire-tier kernels (delta extraction, tree builds)
    # compile on first touch at load-dependent row tiers, and those
    # one-off several-hundred-ms stalls must land in warmup, not in a
    # measured p99 (the round-0 discipline every bench here follows)
    phases = [(rate_fracs[-1], rates[-1], False)] + [
        (f, r, True) for f, r in zip(rate_fracs, rates)
    ]
    for frac, rate, measured in phases:
        n = int(rate * duration)
        offs = np.cumsum(rng.exponential(1.0 / rate, size=n))
        kinds = rng.random(n) < 0.7  # 70% reads / 30% writes
        sched = [
            (
                float(offs[i]),
                "read" if kinds[i] else "write",
                (
                    [read_pool[j] for j in rng.integers(0, 64, 4)]
                    if kinds[i]
                    else [f"ol{rate}/{i}", i]
                ),
            )
            for i in range(n)
        ]
        counter = itertools.count()
        lat_read: list = []
        write_pending: list = []
        t0 = time.perf_counter() + 0.05

        def issue():
            while True:
                i = next(counter)
                if i >= n:
                    return
                t_arr, kind, payload = sched[i]
                now = time.perf_counter()
                if now < t0 + t_arr:
                    time.sleep(t0 + t_arr - now)
                if kind == "read":
                    ffd.read_keys(payload)
                    lat_read.append(time.perf_counter() - (t0 + t_arr))
                else:
                    tks = ffd.mutate_async("add", payload)
                    write_pending.append((tks, t0 + t_arr))

        threads = [threading.Thread(target=issue) for _ in range(workers)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=duration * 20 + 60)
        lat_write: list = []
        for tks, t_arr in write_pending:
            for tk in tks:
                tk.result(60)
            lat_write.append(max(tk.t_done for tk in tks) - t_arr)
        t_end = time.perf_counter()
        achieved = n / (t_end - t0)
        entry = {
            "capacity_fraction": frac,
            "target_ops_per_sec": rate,
            "achieved_ops_per_sec": round(achieved, 1),
            "read": _serve_percentiles(lat_read),
            "write": _serve_percentiles(lat_write),
        }
        if not measured:
            log(f"serve open-loop soak @{rate}/s done (unmeasured warmup)")
            continue
        res["open_loop"]["rates"][str(rate)] = entry
        log(
            f"serve open-loop @{rate}/s ({int(frac * 100)}% cap): achieved "
            f"{achieved:.0f}/s, read "
            f"p50/p99 {entry['read']['p50_ms']}/{entry['read']['p99_ms']} ms, "
            f"write p50/p99 {entry['write']['p50_ms']}/{entry['write']['p99_ms']} ms"
        )
        if not tiny and frac <= 0.5:
            # the gated regime (30% of this run's measured capacity):
            # open-loop arrival clocks mean queueing delay COUNTS, so
            # these tails are honest; the 70% leg is reported unguarded
            # (co-tenant noise at high utilisation is not our signal)
            assert entry["read"]["p99_ms"] <= 500.0, entry
            assert entry["write"]["p99_ms"] <= 2500.0, entry
            assert achieved >= 0.7 * rate, entry
    fleet.stop()

    # ---- leg E: overload spike, /healthz flip + recovery ---------------
    _stage("serve leg E: overload shed + healthz flip")
    plane = Observability()
    rep_ovl = start_link(
        threaded=False, transport=LocalTransport(), name="serve_ovl",
        capacity=cap, tree_depth=depth, obs=plane,
    )
    fd_ovl = rep_ovl.frontdoor(
        max_pending_ops=32, max_commit_ops=32, shed_health_hold=2.0,
    )
    for i in range(16):
        fd_ovl.mutate("add", [f"warm{i}", i])  # warm the commit tiers
    server = plane.serve(port=0)

    def healthz() -> int:
        try:
            with urllib.request.urlopen(server.url + "/healthz", timeout=15) as r:
                return r.status
        except urllib.error.HTTPError as e:
            return e.code

    assert healthz() == 200
    shed = [0]

    # the rate spike: concurrent clients submit far faster than the
    # admission worker can commit, the 32-op window fills, and the
    # excess sheds; the sticky shed_health_hold keeps the overload
    # observable on /healthz until the queue has drained AND the spike
    # stopped (then it recovers)
    def spike(i):
        for j in range(400):
            try:
                fd_ovl.mutate_async("add", [f"spike{i}/{j}", j])
            except Overloaded:
                shed[0] += 1

    threads = [threading.Thread(target=spike, args=(i,)) for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    code_during = healthz()
    assert shed[0] > 0, "spike never shed"
    assert code_during == 503, f"/healthz served {code_during} under overload"
    deadline = time.monotonic() + 30
    code_after = 0
    while time.monotonic() < deadline:
        code_after = healthz()
        if code_after == 200:
            break
        time.sleep(0.05)
    assert code_after == 200, "/healthz never recovered after the spike"
    sst = fd_ovl.stats()
    log(
        f"serve overload: shed {shed[0]} ops "
        f"({sst['shed_by_reason']}), healthz 200 -> 503 -> 200"
    )
    res["overload"] = {
        "spike_ops": 4 * 400,
        "shed_ops": shed[0],
        "shed_by_reason": sst["shed_by_reason"],
        "healthz_under_overload": code_during,
        "healthz_recovered": code_after,
    }
    rep_ovl.stop()
    plane.close()

    # ---- leg F: zero steady-state compiles + pinned transfer counts ----
    _stage("serve leg F: steady-state compile + transfer gates")
    rep_g = start_link(
        threaded=False, transport=LocalTransport(), name="serve_jit",
        capacity=cap, tree_depth=depth,
    )
    fdg = rep_g.frontdoor(max_commit_ops=commit, max_pending_ops=1 << 30)
    n_batches = 2 if tiny else 8
    rounds = [
        _serve_distinct_bucket_batches(n_batches, commit, depth, tag)
        for tag in (1, 2, 3, 4)
    ]
    probe = [int(rounds[0][0][0]), int(rounds[0][0][1])]

    sentinel = itertools.count(1 << 50)

    def drain_round(batches, with_reads):
        # preload whole full-size commits while the worker is
        # blocked on the replica lock: every grouped commit then
        # lands on exactly one (u, m=1) row_apply tier. A sentinel
        # op parks the worker INSIDE apply_ops (on the held lock)
        # first, so it cannot pop a partial prefix mid-preload.
        rep_g._lock.acquire()
        try:
            s = next(sentinel)
            fdg.mutate_async("add", [s, s])
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                with fdg._lock:
                    parked = not fdg._queue and fdg._pending_ops == 1
                if parked:
                    break
                time.sleep(0.001)
            tickets = [
                fdg.mutate_async("add", [int(k), int(k)])
                for batch in batches
                for k in batch
            ]
        finally:
            rep_g._lock.release()
        stop = threading.Event()

        def read_loop():
            while not stop.is_set():
                fdg.read_keys(probe)

        rt = threading.Thread(target=read_loop)
        if with_reads:
            rt.start()
        t0 = time.perf_counter()
        for tk in tickets:
            tk.result(120)
        dt = time.perf_counter() - t0
        if with_reads:
            stop.set()
            rt.join(timeout=10)
        return len(tickets) / dt

    fdg.read_keys(probe)  # warm the read tier
    drain_round(rounds[0], with_reads=not tiny)  # warm round
    if not tiny:
        pre_jit = jitcache.compile_counts()
        gate_rate = drain_round(rounds[1], with_reads=True)
        jit_counts = _jit_steady_gate(
            "serve",
            ("row_apply", "winners_for_keys"),
            pre_jit, jitcache.compile_counts(),
        )
        log(
            f"serve jit gate: zero steady-state compiles, drain "
            f"{gate_rate:.0f} ops/sec at {commit}-op commits"
        )
        res["jit"] = {
            "steady_state": "zero_compiles_in_gated_round",
            "drain_ops_per_sec": round(gate_rate, 1),
            "compiles": jit_counts,
        }
    # transfer pin (ISSUE 17): two aligned drain rounds with the read
    # loop OFF — read traffic is timing-dependent (however many probes
    # squeeze in while the drain runs), so the deterministic admission
    # plane is what gets pinned: identical commit structure per round
    # must cross the device boundary an identical number of times
    pre_tr1 = _transfers_snapshot()
    drain_round(rounds[2], with_reads=False)
    pre_tr2 = _transfers_snapshot()
    drain_round(rounds[3], with_reads=False)
    res["transfers_per_round"] = _transfer_steady_gate(
        "serve", pre_tr1, pre_tr2, _transfers_snapshot(),
        demand_ok=("replica.digest_levels",),
    )
    rep_g.stop()

    res["gates"] = {
        "admission_speedup_min": None if tiny else 3.0,
        "parity": "bit_for_bit_state_and_wal",
        "healthz_flip": "503_under_overload_then_200",
        "read_p99_ms_max": None if tiny else 1000.0,
        "jit_steady_state": None if tiny else "zero_compiles",
    }
    return res


def bench_serve():
    """``--serve``: the heavy-traffic serving-plane harness (ISSUE 14).
    Open-loop (fixed arrival rates), p50/p99 gated, grouped-admission
    speedup gated >=3x at 64 clients, shed/healthz flip/recovery and
    bit-for-bit loaded-vs-twin parity asserted in-run. Host-bound
    admission amortisation is the measured effect, so this runs
    wherever invoked (no device claim dance). Artifact:
    ``benchmarks/results/serve_cpu_<date>.json``."""
    import datetime

    res = _serve_harness(tiny=SMOKE)
    artifact = {
        "metric": "serve_admission_write_speedup" + ("_smoke" if SMOKE else ""),
        "unit": "x (grouped admission / per-op mutate aggregate ops/sec)",
        "stat": f"one_flood_of_{res['clients']}_clients",
        "value": res["admission"]["speedup"],
        **res,
        "backend": "cpu",
        "topology": _topology(),
        "transfers": _transfers_snapshot(),
        "utc": datetime.datetime.now(datetime.timezone.utc).isoformat(),
    }
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "results",
        f"serve_cpu_{datetime.date.today().strftime('%Y%m%d')}.json",
    )
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    log(f"serve artifact written to {out_path}")
    _emit(artifact)


# ---------------------------------------------------------------------------
# observability plane (ISSUE 9: bench.py --obs)

def bench_obs():
    """``--obs``: the observability plane's two in-run gates.

    1. **Overhead** — the 64-sender ingest topology (``--ingest``'s
       shape) built TWICE from the same seeds as isolated universes
       (the ``--catchup`` two-universe pattern): one bare, one with its
       receiver wired into a full
       :class:`~delta_crdt_ex_tpu.runtime.metrics.Observability` plane
       (registry + always-attached bridge + flight recorder + lag
       tracer + drain accounting). The bare universe runs ALL its
       rounds first, untimed — it exists for the parity gate AND to
       warm every jit shape the workload will hit (same seeds → same
       shapes, so the obs universe's timed rounds never pay a
       capacity-growth recompile; a two-universe timed comparison puts
       the multi-second compile inside whichever leg reaches the new
       shape first, a systematic skew an order of magnitude above the
       3% signal). Timing is then a within-universe A/B on the obs
       receiver alone: adjacent round PAIRS alternate the full plane
       on and off (bridge detached + the replica's plane hooks
       nulled — the disabled round runs the exact disabled-receiver
       code path, asserted handler-free), with the on/off order
       flipped every pair so cache/position effects cancel. The
       per-phase statistic is the ratio of per-leg MEDIAN round times
       over the interleaved samples (both modes sample every
       host-noise epoch and both orderings equally, and the median
       shrugs off spike rounds); the GATE takes the minimum over up to
       3 independent phases — host contamination is one-sided
       (scheduler spikes only ever slow a round), so the
       least-contaminated phase best estimates the plane's intrinsic
       cost: ``timeit``'s min-rationale applied at phase level, after
       single-phase estimates of either robust statistic swung ±8%
       between runs on this host while their floors agreed at ~1%
       (and read +31..62% on a real enabled-path regression — the
       accounting closures pinning ``res.state`` and defeating XLA
       buffer reuse — so the gate still turns red on a real cost).
       The obs rounds must ingest at ≥ 97% of the bare-round rate AND
       the two universes must finish bit-identical in state —
       observability must never change observable behaviour.
    2. **Lag tracer** — a 16-replica full-mesh gossip run on one plane:
       every replica commits local writes, gossips to convergence, and
       the dot-provenance tracer (zero wire changes: samples keyed on
       the ``(origin, seq)`` already stamped on round openers) must
       populate the per-peer convergence-lag histogram with non-zero
       samples for EVERY peer, with the crdtlint WIRE family green over
       the tree (0 findings — the trace really added no wire change).

    Emits ``benchmarks/results/obs_overhead_cpu_<date>.json``.
    """
    import dataclasses as _dc
    import datetime
    import statistics

    import jax

    from delta_crdt_ex_tpu import AWLWWMap
    from delta_crdt_ex_tpu.api import start_link
    from delta_crdt_ex_tpu.models.binned import BinnedStore
    from delta_crdt_ex_tpu.runtime import metrics as metrics_mod
    from delta_crdt_ex_tpu.runtime import sync as sync_proto
    from delta_crdt_ex_tpu.runtime.clock import LogicalClock
    from delta_crdt_ex_tpu.runtime.transport import LocalTransport
    from delta_crdt_ex_tpu.utils.hashing import key_hash64_batch

    # ---- gate 1: enabled-vs-disabled overhead on the ingest topology --
    # Steady-state update churn over a FIXED per-sender working set (the
    # soak-scenario ingest shape): every round rewrites the same keys
    # with fresh values, so per-round work, slice tiers, and coalesce
    # depth are constant from round 1 — no capacity growth, no tier
    # fragmentation, no mid-run recompiles. An insert-accumulating ramp
    # makes late rounds both slower and coalesce-hostile, which drowns
    # a 3% signal in regime drift rather than measuring the plane.
    n_senders = 8 if SMOKE else 64
    rounds = int(os.environ.get("BENCH_OBS_ROUNDS", 6 if SMOKE else 40))
    working_keys = 4 if SMOKE else 16  # per sender, rewritten every round
    depth = 7 if SMOKE else 10
    buckets = 1 << depth
    span = buckets // n_senders

    pools: list[list[int]] = [[] for _ in range(n_senders)]
    base = 0
    while min(len(p) for p in pools) < working_keys:
        cand = list(range(base, base + (1 << 16)))
        hs = np.asarray(key_hash64_batch(cand), np.uint64)
        owner = (hs & np.uint64(buckets - 1)).astype(np.int64) // span
        for k, o in zip(cand, owner.tolist()):
            if o < n_senders and len(pools[o]) < working_keys:
                pools[o].append(k)
        base += 1 << 16

    class _Leg:
        """One isolated same-seed universe: 64 senders fanning into one
        receiver (obs-wired or bare), advanced one round at a time."""

        def __init__(self, tag, obs_plane):
            self.transport = LocalTransport()
            clock = LogicalClock()
            mk = lambda **kw: start_link(
                AWLWWMap, threaded=False, transport=self.transport,
                clock=clock, capacity=buckets * 16, tree_depth=depth, **kw,
            )
            # deterministic writer ids: node_id defaults to
            # secrets.randbits, and ehash digests the writer gid —
            # random ids would make the two legs incomparable
            # bit-for-bit
            self.senders = [
                mk(name=f"{tag}_s{i}", node_id=1001 + 2 * i)
                for i in range(n_senders)
            ]
            extra = {"obs": obs_plane} if obs_plane is not None else {}
            self.recv = mk(name=f"{tag}_recv", node_id=777, **extra)
            for s in self.senders:
                s.set_neighbours([self.recv])

        def round(self, rnd) -> float:
            """Advance one fan-in round; returns the wall time of the
            receiver's drain (the timed region)."""
            for i, s in enumerate(self.senders):
                for k in pools[i]:
                    # fresh value every round: a real LWW update per key,
                    # constant row count
                    s.mutate("add", [k, (k << 8) | (rnd & 0xFF)])
            for s in self.senders:
                s.sync_to_all()
            msgs = [m for m in self.transport.drain(self.recv.addr)
                    if isinstance(m, sync_proto.EntriesMsg)]
            assert len(msgs) >= n_senders, (rnd, len(msgs))
            if os.environ.get("BENCH_OBS_DEBUG"):
                self.last_msgs = len(msgs)
                self.last_rows = sum(len(m.payloads) for m in msgs)
            for m in msgs:
                self.transport.send(self.recv.addr, m)
            # start the timer with an EMPTY device queue in BOTH modes:
            # enabled rounds' sender phase self-syncs via its accounting
            # readbacks, while bare rounds would otherwise carry the
            # senders' still-in-flight async dispatches INTO the timed
            # region — a mode-correlated skew that has nothing to do
            # with the receiver's ingest cost
            jax.block_until_ready([s.state for s in self.senders])
            jax.block_until_ready(self.recv.state)
            t0 = time.perf_counter()
            self.recv.process_pending()
            # the device compute lands INSIDE the timer in both modes:
            # the enabled rounds' SYNC_DONE accounting readback forces a
            # device sync a bare round would otherwise defer past the
            # timed region (async dispatch), which would masquerade as
            # plane overhead
            jax.block_until_ready(self.recv.state)
            dt = time.perf_counter() - t0
            for s in self.senders:
                self.transport.drain(s.addr)  # walk back-traffic: unmeasured
            return dt

    from delta_crdt_ex_tpu.runtime import telemetry

    for ev in telemetry.declared_events():
        assert not telemetry.has_handlers(ev), (
            f"telemetry handlers already attached for {ev} — the "
            "disabled rounds would not measure a disabled plane"
        )
    import gc

    plane = metrics_mod.Observability()
    plane.bridge.detach()

    # two isolated same-seed universes advanced in LOCKSTEP: the bare
    # one is the parity witness AND the jit warmer (same seeds hit the
    # same shapes, so the obs universe's timed rounds never pay a
    # capacity-growth recompile — multi-second compiles landing inside
    # one leg's timer were the dominant skew of a two-universe timed
    # comparison). Timing is a within-universe A/B on the obs receiver:
    # adjacent round pairs alternate the full plane on/off, order
    # flipped every pair. threaded=False — nothing else reads the
    # replica's plane hooks while the toggle swaps them (private-attr
    # poke is deliberate: the disabled rounds must run the exact
    # disabled-receiver code path, not a bridge-detached approximation)
    leg_off = _Leg("obsoff", None)
    leg_on = _Leg("obson", plane)
    rec = leg_on.recv
    hooks = (plane, leg_on.recv._lag, leg_on.recv.flight)

    def plane_on():
        plane.bridge.attach()
        rec._obs, rec._lag, rec.flight = hooks

    def plane_off():
        plane.bridge.detach()
        assert not telemetry.has_handlers(telemetry.SYNC_DONE)
        rec._obs, rec._lag, rec.flight = None, None, None

    pairs = rounds // 2
    leg_off.round(0)
    plane_on()
    leg_on.round(0)  # warmup round for both universes (handler paths too)
    plane_off()
    rnd = 1
    estimates: list[float] = []
    pair_medians: list[float] = []
    rates: list[tuple[float, float]] = []

    def measure_phase(start: int) -> tuple[list[float], list[float]]:
        """One A/B phase: 2×`pairs` rounds on the obs universe, the
        bare universe advanced through the SAME rounds first (lockstep
        for the parity gate + shape warming)."""
        plane_off()  # a previous phase may have ended on an ON round
        for r in range(start, start + 2 * pairs):
            assert not telemetry.has_handlers(telemetry.SYNC_DONE)
            leg_off.round(r)
        on: list[float] = []
        off: list[float] = []
        gc.collect()
        gc.disable()  # collections land between rounds, not in a timer
        try:
            for p in range(pairs):
                sides = [(plane_on, on), (plane_off, off)]
                if p % 2:
                    sides.reverse()
                for r, (toggle, dts) in zip(
                    (start + 2 * p, start + 2 * p + 1), sides
                ):
                    toggle()
                    gc.collect()
                    dts.append(leg_on.round(r))
                    if os.environ.get("BENCH_OBS_DEBUG"):
                        mode = "ON " if dts is on else "OFF"
                        ing = leg_on.recv.stats()["ingress"]
                        log(
                            f"  {mode} rnd{r}: {dts[-1] * 1e3:7.2f}ms "
                            f"msgs={leg_on.last_msgs} "
                            f"entries={leg_on.last_rows} "
                            f"dispatches={ing['dispatches']} "
                            f"messages={ing['messages']}"
                        )
        finally:
            gc.enable()
        return on, off

    # up to 3 independent measurement phases, gating on the MINIMUM
    # run-level estimate: host contamination is one-sided (scheduler
    # spikes only ever slow a round), so the least-contaminated phase
    # is the best estimate of the plane's intrinsic cost — timeit's
    # min-rationale applied at phase level, because on this shared box
    # single-phase estimates (leg-median ratio OR pair-ratio median)
    # each swung by ±8% between runs while their floors agreed at ~1%
    for _attempt in range(3):
        on_dts, off_dts = measure_phase(rnd)
        rnd += 2 * pairs
        est = statistics.median(on_dts) / statistics.median(off_dts) - 1.0
        estimates.append(est)
        pair_medians.append(statistics.median(
            on_dt / off_dt for on_dt, off_dt in zip(on_dts, off_dts)
        ) - 1.0)
        rate = lambda ds: n_senders / statistics.median(ds)
        rates.append((rate(on_dts), rate(off_dts)))
        if est < 0.03:
            break
    best = min(range(len(estimates)), key=lambda i: estimates[i])
    overhead, pair_median = estimates[best], pair_medians[best]
    on, off = rates[best]
    plane_on()  # leave the plane live for inspection below

    # parity: the plane must never change observable state (same-seed
    # isolated universes — deterministic clocks make them bit-comparable)
    for c in (f.name for f in _dc.fields(BinnedStore)):
        assert np.array_equal(
            np.asarray(getattr(leg_on.recv.state, c)),
            np.asarray(getattr(leg_off.recv.state, c)),
        ), f"obs-enabled/disabled state diverged: {c}"
    assert leg_on.recv._seq == leg_off.recv._seq

    log(
        f"obs overhead: enabled {on:.1f} vs disabled {off:.1f} merges/sec "
        f"(leg-median ratio {overhead * 100:+.2f}% cost, best of "
        f"{len(estimates)} phase(s) "
        f"[{', '.join(f'{e * 100:+.2f}%' for e in estimates)}] × "
        f"{pairs} pairs, pair-median {pair_median * 100:+.2f}%; gate < 3%)"
    )
    # THE gate: the plane's ingest-hot-path cost stays under 3%
    assert overhead < 0.03, (
        f"observability overhead {overhead * 100:.2f}% breaches the 3% gate "
        f"in every phase ({[round(e * 100, 2) for e in estimates]}% — "
        f"enabled {on:.1f} vs disabled {off:.1f} merges/sec)"
    )
    # and the bridge really consumed the run: the registry's merge
    # counter must cover every message drained in an enabled round
    sync_done = plane.registry.get("crdt_sync_done_total").value(
        (leg_on.recv.name,)
    )
    assert sync_done >= pairs * n_senders, sync_done

    # ---- gate 2: lag tracer populated in a 16-replica gossip run -----
    n_gossip = 4 if SMOKE else 16
    gossip_rounds = 4 if SMOKE else 6
    t2 = LocalTransport()
    plane2 = metrics_mod.Observability(lag_sample_every=1)
    reps = [
        start_link(
            AWLWWMap, threaded=False, transport=t2, clock=LogicalClock(),
            name=f"gossip{i}", obs=plane2, tree_depth=7, capacity=4096,
        )
        for i in range(n_gossip)
    ]
    for r in reps:
        r.set_neighbours([p for p in reps if p is not r])
    t2.pump()
    for rnd in range(gossip_rounds):
        for i, r in enumerate(reps):
            r.mutate("add", [f"g{i}_{rnd}", rnd])
        for _ in range(3):  # gossip to convergence + watermark advances
            for r in reps:
                r.sync_to_all()
            t2.pump()
    peers = plane2.lag.peers_seen()
    missing = {str(r.addr) for r in reps} - peers
    assert not missing, f"lag tracer has no samples for peers: {missing}"
    lag_counts = {
        "|".join(lb): plane2.lag.lag.count(lb)
        for lb in plane2.lag.lag.label_sets()
    }
    assert all(v > 0 for v in lag_counts.values())
    rounds_samples = sum(
        plane2.lag.rounds.count(lb) for lb in plane2.lag.rounds.label_sets()
    )
    log(
        f"obs lag tracer: {len(peers)}/{n_gossip} peers populated, "
        f"{sum(lag_counts.values())} lag samples, "
        f"{rounds_samples} propagation-round samples"
    )

    # ---- gate 3: zero wire changes (WIRE family green) ----------------
    from tools.crdtlint.engine import run_lint

    wire_new, _b, _a = run_lint(
        [__import__("pathlib").Path("delta_crdt_ex_tpu")],
        select={"WIRE001", "WIRE002", "WIRE003", "WIRE004", "WIRE005"},
    )
    assert wire_new == [], "WIRE family red:\n" + "\n".join(
        f.render() for f in wire_new
    )
    log("obs wire gate: crdtlint WIRE family green (0 findings)")

    artifact = {
        "metric": "obs_plane_overhead_pct" + ("_smoke" if SMOKE else ""),
        "unit": "percent",
        "stat": (
            f"min_over_{len(estimates)}_phases_of_leg_median_ratio_"
            f"over_{pairs}_interleaved_pairs"
        ),
        "value": round(overhead * 100, 3),
        "phase_estimates_pct": [round(e * 100, 3) for e in estimates],
        "pair_median_pct": round(pair_median * 100, 3),
        "enabled_merges_per_sec": round(on, 2),
        "disabled_merges_per_sec": round(off, 2),
        "gate_overhead_pct_max": 3.0,
        "neighbours": n_senders,
        "rounds": rounds,
        "parity": "bit_for_bit_state_checked",
        "lag_tracer": {
            "gossip_replicas": n_gossip,
            "peers_populated": len(peers),
            "lag_samples": sum(lag_counts.values()),
            "propagation_round_samples": rounds_samples,
            "wire_findings": 0,
        },
        "backend": "cpu",
        "topology": _topology(),
        "transfers": _transfers_snapshot(),
        "utc": datetime.datetime.now(datetime.timezone.utc).isoformat(),
    }
    out_path = os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "benchmarks", "results",
        f"obs_overhead_cpu_{datetime.date.today().strftime('%Y%m%d')}.json",
    )
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=2)
        f.write("\n")
    log(f"obs artifact written to {out_path}")
    _emit(artifact)


# ---------------------------------------------------------------------------
# Python baseline (BEAM stand-in; see module docstring)

def bench_python(seed=0):
    """Best of 3 identical passes: single-pass timings on this shared
    host vary ~1.7× with scheduler noise (observed 0.27–0.46 s for the
    same work), and the baseline must be measured at its strongest —
    the reported ratio should be conservative, not lucky. Each pass
    rebuilds state from the same seed so merges never see a pre-warmed
    context."""
    best = None
    for _ in range(3):
        dt, merges = _bench_python_once(seed)
        best = dt if best is None else min(best, dt)
    log(f"python baseline: {merges} merges in {best:.3f}s (best of 3)")
    return merges / best


def _bench_python_once(seed):
    L, rng, keys = make_workload(seed)

    # state: key -> ((valh, ts), (writer, ctr)); per-bucket context and
    # index, mirroring the semantic steps of one merge
    state = {}
    ctx = {}  # (bucket, writer) -> max ctr
    index = {}  # bucket -> digest accumulator
    bucket_of = (keys & np.uint64(L - 1)).astype(np.int64)
    counts = {}
    for i, k in enumerate(keys):
        kk = int(k)
        b = int(bucket_of[i])
        c = counts.get(b, 0) + 1
        counts[b] = c
        state[kk] = ((kk & 0xFFFFFFFF, i + 1), (11, c))
        ctx[(b, 11)] = c
        index[b] = index.get(b, 0) ^ hash((kk, 11, c))

    # identical delta stream (same generator protocol as the TPU side);
    # each baseline iteration merges one GROUP-slice join, like the TPU
    deltas = []
    next_ctr = {}
    ts0 = 1 << 20
    for _ in range(BASE_ITERS):
        dkeys = rng.integers(1, 1 << 63, size=GROUP * DELTA, dtype=np.uint64)
        entries = []
        for j, k in enumerate(dkeys):
            b = int(k) & (L - 1)
            c = next_ctr.get(b, 0) + 1
            next_ctr[b] = c
            entries.append((int(k), b, c, ts0 + j))
        ts0 += GROUP * DELTA
        deltas.append(entries)

    def merge(entries):
        # per-entry coverage check + insert + context union + index update
        for kk, b, c, ts in entries:
            if ctx.get((b, 22), 0) >= c:
                continue
            cur = state.get(kk)
            if cur is None or cur[0][1] <= ts:
                state[kk] = ((kk & 0xFFFFFFFF, ts), (22, c))
            index[b] = index.get(b, 0) ^ hash((kk, 22, c))
            ctx[(b, 22)] = c

    t0 = time.perf_counter()
    for entries in deltas:
        merge(entries)
    dt = time.perf_counter() - t0
    return dt, BASE_ITERS * GROUP


class Budget:
    """One shared wall-clock budget for the whole bench run.

    Every stage asks ``remaining()`` (optionally minus a reserve for the
    stages that MUST still run after it) instead of using its own
    unbounded timeout — this is what guarantees the labelled CPU
    fallback always gets its turn before any external timeout fires."""

    def __init__(self, total_s: float):
        self.t0 = time.monotonic()
        self.total = total_s

    def elapsed(self) -> float:
        return time.monotonic() - self.t0

    def remaining(self, reserve: float = 0.0) -> float:
        return max(0.0, self.total - self.elapsed() - reserve)


def _run_with_grace(cmd: list, timeout_s: float, env: dict | None = None):
    """subprocess with a SIGTERM-first watchdog.

    ``subprocess.run(timeout=...)`` SIGKILLs on expiry — and a SIGKILL
    to a process holding (or awaiting) the device claim is the exact
    hazard that preceded round 4's 9-hour pool outage. Terminate first
    so the child can unwind (emit its artifact, release the claim via
    normal teardown), escalate to kill only after a grace period.
    Returns ``(returncode | None, stdout, stderr, timed_out)``."""
    import subprocess

    proc = subprocess.Popen(
        cmd, env=env, stdout=subprocess.PIPE, stderr=subprocess.PIPE
    )
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
        return proc.returncode, stdout, stderr, False
    except subprocess.TimeoutExpired:
        proc.terminate()
        try:
            stdout, stderr = proc.communicate(timeout=30)
        except subprocess.TimeoutExpired:
            log("graceful stop timed out after 30s — escalating to SIGKILL")
            proc.kill()
            stdout, stderr = proc.communicate()
        return None, stdout, stderr, True


def _device_backend_usable(budget: Budget, reserve: float,
                           timeout_s: float, attempts: int) -> bool:
    """Probe whether the configured accelerator backend can initialise.

    Device init goes through an external claim that can hang indefinitely
    when the pool is wedged (a killed holder's grant can take a long time
    to expire) — probe in a subprocess with a watchdog, retrying so a
    recovering claim still gets picked up. The real bound is the BUDGET,
    not the attempt count: r01–r04 all fell back because fast
    UNAVAILABLE errors burned a small attempt cap in minutes while the
    pool recovered later in the driver window. The loop now keeps
    probing (each attempt logged) until ``budget`` minus ``reserve``
    (the time the device child + CPU fallback still need) runs out;
    ``attempts`` survives as an override cap for interactive use.
    """
    if os.environ.get("JAX_PLATFORMS", "") in ("cpu", ""):
        return True
    retry_sleep = float(os.environ.get("BENCH_CLAIM_RETRY_SLEEP", "60"))
    for attempt in range(attempts):
        probe_budget = min(timeout_s, budget.remaining(reserve))
        if probe_budget < 15:
            log(f"claim probe out of budget (remaining {budget.remaining():.0f}s, "
                f"reserve {reserve:.0f}s) — surrendering to fallback")
            return False
        rc, _out, err, timed_out = _run_with_grace(
            [sys.executable, "-c", "import jax; jax.devices()"], probe_budget
        )
        if timed_out:
            log(f"device claim probe timed out after {probe_budget:.0f}s "
                f"(attempt {attempt + 1}/{attempts}) — claim may be wedged")
            continue  # the timeout already consumed the attempt's patience
        if rc == 0:
            return True
        log(f"device claim probe failed (attempt {attempt + 1}/{attempts}): "
            f"{err.decode(errors='replace')[-300:]}")
        # fast UNAVAILABLE errors would burn all attempts in seconds —
        # space them out so a recovering claim can still be caught, but
        # never sleep past the budget
        if attempt + 1 < attempts:
            time.sleep(min(retry_sleep, budget.remaining(reserve)))
    return False


def _run_tpu_child(env: dict, timeout_s: float) -> dict | None:
    """Run the device side (``--tpu-child``) in a subprocess with a hard
    watchdog; returns the child's result dict or None. The child claims
    the device, so the parent never imports jax and cannot wedge."""
    if timeout_s < 30:
        log(f"device bench child skipped: only {timeout_s:.0f}s left in budget")
        return None
    def parse_last(stdout: bytes) -> dict | None:
        try:
            res = json.loads(stdout.decode().strip().splitlines()[-1])
            float(res["merges_per_sec"])
            return res
        except (ValueError, KeyError, IndexError):
            return None

    rc, stdout, stderr, timed_out = _run_with_grace(
        [sys.executable, os.path.abspath(__file__), "--tpu-child"],
        timeout_s,
        env=env,
    )
    sys.stderr.buffer.write(stderr or b"")
    if timed_out:
        log(f"device bench child exceeded {timeout_s:.0f}s watchdog — "
            "stopped (SIGTERM first: a mid-claim SIGKILL can wedge the "
            "pool grant)")
        # the child prints its PRIMARY line before the A/B tail: a stop
        # mid-A/B must not discard a completed measurement
        res = parse_last(stdout or b"")
        if res is not None:
            log("salvaged the child's pre-A/B primary line")
        return res
    if rc != 0:
        log(f"device bench child failed (exit {rc})")
        return None
    res = parse_last(stdout)
    if res is None:
        log(f"device bench child printed no result: {stdout[-300:]!r}")
    return res


_EMITTED = False


def _emit(obj: dict) -> None:
    """Print THE one JSON line, exactly once per process.

    The emitted flag flips only after the print completes: a SIGTERM
    landing mid-emission lets the handler's line still go out (the
    driver parses the LAST line, so a rare double emission is harmless;
    an empty stdout is not). Every line carries its emission time and
    the GROUP knob: the resume matrix's skip gate
    (``benchmarks.artifact``) classifies freshness by the embedded
    ``utc`` (file mtimes reset on checkout), and probe artifacts are
    meaningless without the grouping they measured."""
    global _EMITTED
    if _EMITTED:
        return
    import datetime

    obj.setdefault(
        "utc", datetime.datetime.now(datetime.timezone.utc).isoformat()
    )
    obj.setdefault("group", GROUP)
    print(json.dumps(obj), flush=True)
    _EMITTED = True


def _metric_name(fallback: bool) -> str:
    metric = (
        "awlwwmap_1m_key_64_neighbour_merges_per_sec"
        if not SMOKE
        else "awlwwmap_smoke_merges_per_sec"
    )
    return metric + ("_cpu_fallback" if fallback else "")


def main():
    if "--durability" in sys.argv:
        bench_durability()
        return
    if "--chaos" in sys.argv:
        bench_chaos()
        return
    if "--ingest" in sys.argv:
        bench_ingest()
        return
    if "--catchup" in sys.argv:
        bench_catchup()
        return
    if "--tree" in sys.argv:
        bench_tree()
        return
    if "--fleet" in sys.argv:
        if "--mesh" in sys.argv:
            # the whole mesh plane runs on 8 forced virtual CPU devices
            # (the tier-1 topology); must land before the first backend
            # initialisation, which is why it sits here and not in the
            # bench body
            from delta_crdt_ex_tpu.utils.devices import force_cpu_devices

            force_cpu_devices(8)
            bench_fleet_mesh()
        else:
            bench_fleet()
        return
    if "--hashstore" in sys.argv:
        bench_hashstore()
        return
    if "--obs" in sys.argv:
        bench_obs()
        return
    if "--serve" in sys.argv:
        bench_serve()
        return
    if "--tpu-child" in sys.argv:
        # SIGTERM → clean Python unwind (finalizers run, the device
        # claim is released through normal teardown); the default
        # handler would hard-kill the claim holder — the r4 wedge
        signal.signal(signal.SIGTERM, lambda s, f: sys.exit(1))

        def emit_child_line(stats, sec_failed, alt=None):
            import jax

            # the child names the backend it ACTUALLY ran on, so the
            # parent can never emit an accelerator-named metric for a
            # CPU run (e.g. invoking the bench under JAX_PLATFORMS=cpu)
            out = {**stats, "backend": jax.default_backend()}
            if sec_failed:
                out["secondary_assert_failed"] = True
            if alt is not None:
                alt_name, alt_stats = alt
                out["alt_layout"] = alt_name
                out["alt_merges_per_sec"] = round(alt_stats["merges_per_sec"], 2)
                out["alt_stat"] = alt_stats["stat"]
                out["alt_rate_min"] = alt_stats["call_rate_min"]
                out["alt_rate_max"] = alt_stats["call_rate_max"]
                out["alt_aggregate"] = alt_stats["aggregate_merges_per_sec"]
            print(json.dumps(out), flush=True)

        # the primary line goes out BEFORE the A/B tail (the parent
        # parses the LAST line, so the post-A/B line supersedes it; a
        # watchdog kill mid-A/B still leaves the primary measurement)
        stats, sec_failed, alt = bench_tpu(on_primary=emit_child_line)
        emit_child_line(stats, sec_failed, alt)
        return

    # ---- the artifact guarantee -------------------------------------
    # One wall-clock budget covers everything; the CPU fallback has a
    # reserved slice of it; and if ANYTHING still goes wrong (including
    # an external SIGTERM landing before we finish) a labelled JSON
    # line goes out anyway. BENCH_r02 died with no artifact — never again.
    budget = Budget(float(os.environ.get("BENCH_TOTAL_BUDGET", "1380")))
    fallback_reserve = float(os.environ.get("BENCH_FALLBACK_RESERVE", "480"))
    if os.environ.get("BENCH_NO_CPU_FALLBACK") == "1":
        fallback_reserve = 0.0
    # shared run state: the py baseline (for the last-resort line) and
    # whether the run was in its CPU-fallback leg — failure labels must
    # name the backend that was actually executing, not assume CPU
    run_state = {"py": None, "fallback": os.environ.get("BENCH_FORCED_CPU") == "1"}

    def _interrupted(signum, frame):
        if _EMITTED:
            # the artifact already went out whole — do not append even a
            # newline (tail -1 must keep finding the real line)
            raise SystemExit(1)
        log(f"signal {signum} received at +{budget.elapsed():.0f}s — emitting last-resort artifact")
        py = run_state["py"]
        # the signal may have landed mid-print of the normal line: start
        # on a fresh line so the driver's last-line parse always sees
        # complete JSON (a stray blank/partial line above is harmless)
        sys.stdout.write("\n")
        _emit({
            "metric": _metric_name(run_state["fallback"]) + "_interrupted",
            "value": 0.0,
            "unit": "merges/sec",
            "vs_baseline": 0.0,
            "error": f"interrupted by signal {signum} before completion",
            "py_baseline_merges_per_sec": py and round(py, 2),
        })
        sys.stdout.flush()
        raise SystemExit(1)

    signal.signal(signal.SIGTERM, _interrupted)
    signal.signal(signal.SIGINT, _interrupted)

    try:
        _main_measured(budget, fallback_reserve, run_state)
    except BaseException as e:  # noqa: BLE001 — artifact guarantee
        import traceback

        traceback.print_exc()
        if not _EMITTED:
            log(f"bench failed without artifact: {e!r} — emitting error line")
            _emit({
                "metric": _metric_name(run_state["fallback"]) + "_failed",
                "value": 0.0,
                "unit": "merges/sec",
                "vs_baseline": 0.0,
                "error": repr(e)[:300],
            })
        # the artifact is the contract: once the line is out, exit 0 so
        # the driver records it (failure is visible in the metric label)
        raise SystemExit(0) from e


def _main_measured(budget: Budget, fallback_reserve: float, run_state: dict):
    log(
        f"workload: {N_KEYS} keys, {NEIGHBOURS} neighbours, {DELTA}-entry "
        f"delta-interval slices, L=2^{TREE_DEPTH} buckets; "
        f"budget {budget.total:.0f}s (fallback reserve {fallback_reserve:.0f}s)"
    )
    py = bench_python()
    run_state["py"] = py

    # a wedged claim (killed holder's grant) can take tens of minutes to
    # expire — probe patiently, but only within the shared budget: the
    # attempt cap is set far above what the budget allows, so the probe
    # spends the WHOLE non-reserved window (~half the default budget)
    # waiting for a recovering pool instead of surrendering after three
    # fast failures (how r01–r04 all ended up cpu_fallback)
    claim_timeout = float(os.environ.get("BENCH_CLAIM_TIMEOUT", "240"))
    claim_attempts = int(os.environ.get("BENCH_CLAIM_ATTEMPTS", "99"))
    tpu_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "2400"))
    # the device child needs real time after a successful probe; keep it
    # out of the probe's spendable window too
    child_floor = 240.0

    res = None
    # run_state["fallback"] is the single source of truth for which
    # backend is executing — the failure labels in main() read it live
    if not run_state["fallback"] and _device_backend_usable(
        budget, fallback_reserve + child_floor, claim_timeout, claim_attempts
    ):
        env = dict(os.environ)
        if env.get("JAX_PLATFORMS") == "cpu":
            # an explicitly-CPU run must also bypass the axon boot hook,
            # or the child wedges on the remote claim it never needed
            env["PALLAS_AXON_POOL_IPS"] = ""
        res = _run_tpu_child(
            env, min(tpu_timeout, budget.remaining(fallback_reserve))
        )
        if res is None:
            log("ACCELERATOR RUN FAILED — see stage logs above")
        elif res.get("backend") == "cpu":
            # explicitly-CPU environment: the number is honest but must
            # carry the CPU label — never the accelerator metric name
            log("child ran on the CPU backend — labelling _cpu_fallback")
            run_state["fallback"] = True
            if os.environ.get("BENCH_NO_CPU_FALLBACK") == "1":
                # the no-fallback contract means a CPU number is useless
                # however it came about — fail fast here too
                raise SystemExit(
                    "child ran on CPU and BENCH_NO_CPU_FALLBACK=1"
                )
    if res is None and os.environ.get("BENCH_NO_CPU_FALLBACK") == "1":
        # interactive TPU sessions: a CPU number is useless, fail fast
        # (main() still guarantees an error-labelled artifact line)
        raise SystemExit("accelerator run failed and BENCH_NO_CPU_FALLBACK=1")
    if res is None:
        # loud, labelled CPU fallback: the artifact must never silently
        # pass off a CPU number as the accelerator result
        run_state["fallback"] = True
        log(f"falling back to CPU at +{budget.elapsed():.0f}s "
            f"({budget.remaining():.0f}s left; metric labelled _cpu_fallback)")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        # the fallback reserve is sized for ONE layout; the layout A/B
        # is chip evidence anyway (CPU measured a wash, BASELINE.md)
        env.setdefault("BENCH_AB", "0")
        if not SMOKE and budget.remaining() < fallback_reserve * 0.75:
            # not enough left for the full-config CPU run — a labelled
            # smoke number (with its own matched smoke baseline) still
            # beats an empty artifact: re-run the whole bench in smoke
            # mode and relay its artifact line verbatim
            log("budget too thin for full CPU fallback — relaying smoke run")
            import subprocess

            env["BENCH_SMOKE"] = "1"
            env["BENCH_FORCED_CPU"] = "1"
            env["BENCH_TOTAL_BUDGET"] = str(max(30.0, budget.remaining() - 15.0))
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__)],
                timeout=max(45.0, budget.remaining() - 5.0),
                env=env, capture_output=True,
            )
            sys.stderr.buffer.write(proc.stderr)
            _emit(json.loads(proc.stdout.decode().strip().splitlines()[-1]))
            return
        res = _run_tpu_child(env, max(30.0, budget.remaining() - 20.0))
        if res is None:
            raise SystemExit("bench failed on accelerator AND cpu")

    value = float(res["merges_per_sec"])
    layout = layout_name()
    line = {
        "metric": _metric_name(run_state["fallback"]),
        "unit": "merges/sec",
    }
    alt_won = False
    alt_v = res.get("alt_merges_per_sec")
    if alt_v is not None:
        # both layouts measured in one run: record both, headline the
        # better one (the layout field names which won)
        line[f"{layout}_merges_per_sec"] = round(value, 2)
        line[f"{res['alt_layout']}_merges_per_sec"] = round(float(alt_v), 2)
        if float(alt_v) > value:
            value, layout = float(alt_v), res["alt_layout"]
            alt_won = True
    # the measured side's spread (Benchee-grade honesty: the headline is
    # a median with its min/max alongside, so a single-pass noise
    # reading can't masquerade as the result); the per-call min/max
    # describe the PRIMARY layout, so drop them if the alt won — and
    # label the headline with the stat of the run it actually came from
    if alt_won:
        # the alternate's own spread/aggregate ride along (mirroring the
        # primary path below), so alt-headlined artifacts keep their
        # Benchee-grade honesty (ADVICE r5 low #2)
        if "alt_stat" in res:
            line["stat"] = res["alt_stat"]
        for src, dst in (
            ("alt_rate_min", "call_rate_min"),
            ("alt_rate_max", "call_rate_max"),
            ("alt_aggregate", "aggregate_merges_per_sec"),
        ):
            if src in res:
                line[dst] = res[src]
    else:
        if "stat" in res:
            line["stat"] = res["stat"]
        for k in ("call_rate_min", "call_rate_max", "aggregate_merges_per_sec"):
            if k in res:
                line[k] = res[k]
    line["value"] = round(value, 2)
    line["vs_baseline"] = round(value / py, 3)
    line["layout"] = layout
    if res.get("secondary_assert_failed"):
        # tier overflow in the GROUP=1 secondary is a correctness
        # signal — surface it in the artifact, not only in stderr
        line["secondary_assert_failed"] = True
    _emit(line)


if __name__ == "__main__":
    main()
