"""North-star benchmark: 1M-key AWLWWMap, 64-neighbour batched anti-entropy.

Measures **merges/sec**: one merge = joining a 512-entry delta slice into
a 1M-key replica state *and* updating its sync index (the reference's
``update_state_with_delta``: lattice join + MerkleMap puts,
``causal_crdt.ex:383-404``). The TPU path executes 64 such merges per
device call (the vmapped neighbour fan-in, ``parallel/batched_sync.py``).

Baseline: the reference publishes no numbers and Elixir/BEAM is not in
this image (BASELINE.md), so ``vs_baseline`` is measured against a lean
pure-Python dot-store implementation of the same semantic steps
(per-key dot-set join + context union + per-key index update) running
the identical workload single-threaded. It does O(delta) work per merge
— a deliberately *favourable* cost model for the baseline (BEAM's
persistent maps pay O(log n) per touched key plus actor overhead), so
the reported ratio is conservative.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": "merges/sec", "vs_baseline": N}

Env knobs: BENCH_SMOKE=1 shrinks sizes for CPU smoke runs.
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

SMOKE = os.environ.get("BENCH_SMOKE") == "1"

N_KEYS = 4096 if SMOKE else 1_000_000
CAPACITY = 8192 if SMOKE else 1 << 20
NEIGHBOURS = 4 if SMOKE else 64
DELTA = 128 if SMOKE else 512
TREE_DEPTH = 8 if SMOKE else 12
RCAP = 8
ITERS = 4 if SMOKE else 48
WARMUP = 2
BASE_ITERS = 8 if SMOKE else 200
# every iteration must be a real merge (fresh dots), not an idempotent
# re-join — pre-generate enough distinct deltas for both sides
N_DELTAS = max(ITERS + WARMUP, BASE_ITERS)

log = lambda *a: print(*a, file=sys.stderr, flush=True)


# ---------------------------------------------------------------------------
# workload construction (shared by both sides)

def make_workload(seed=0):
    rng = np.random.default_rng(seed)
    keys = rng.integers(1, 1 << 63, size=N_KEYS, dtype=np.uint64)
    deltas = []
    ctr0 = 1
    for d in range(N_DELTAS):
        dkeys = rng.integers(1, 1 << 63, size=DELTA, dtype=np.uint64)
        ctrs = np.arange(ctr0, ctr0 + DELTA, dtype=np.uint32)
        ctr0 += DELTA
        deltas.append((dkeys, ctrs))
    return keys, deltas


# ---------------------------------------------------------------------------
# TPU side

def bench_tpu(keys, deltas):
    import jax
    import jax.numpy as jnp

    from delta_crdt_ex_tpu.models.state import DotStore
    from delta_crdt_ex_tpu.ops.hashtree import leaf_digests
    from delta_crdt_ex_tpu.ops.join import join

    log(f"jax devices: {jax.devices()}")

    num_buckets = 1 << TREE_DEPTH

    def base_state(gid, keys, ctrs, capacity, slot=0):
        n = len(keys)
        bucket = (keys & np.uint64(num_buckets - 1)).astype(np.int64)
        ctx = np.zeros((num_buckets, RCAP), np.uint32)
        np.maximum.at(ctx, (bucket, np.full(n, slot)), ctrs)
        pad = capacity - n
        z = lambda a, dt: np.concatenate([a.astype(dt), np.zeros(pad, dt)])
        return DotStore(
            key=jnp.asarray(z(keys, np.uint64)),
            valh=jnp.asarray(z(ctrs, np.uint32)),
            ts=jnp.asarray(z(ctrs.astype(np.int64), np.int64)),
            node=jnp.zeros(capacity, jnp.int32),
            ctr=jnp.asarray(z(ctrs, np.uint32)),
            alive=jnp.asarray(np.concatenate([np.ones(n, bool), np.zeros(pad, bool)])),
            ctx_gid=jnp.zeros(RCAP, jnp.uint64).at[0].set(jnp.uint64(gid)),
            ctx_max=jnp.asarray(ctx),
        )

    # one replica state, replicated 64x on the neighbour axis
    ctrs = np.arange(1, N_KEYS + 1, dtype=np.uint32)
    one = base_state(11, keys, ctrs, CAPACITY)
    stacked = jax.tree_util.tree_map(
        lambda x: jnp.broadcast_to(x, (NEIGHBOURS,) + x.shape).copy(), one
    )

    # delta slices from a second writer (gid 22): fresh dots each iteration
    delta_states = [
        base_state(22, dk, dc, DELTA) for dk, dc in deltas
    ]

    @jax.jit
    def merge_step(stacked, delta):
        res = jax.vmap(join, in_axes=(0, None, None))(stacked, delta, None)
        # sync-index update (the MerkleMap.put analog): leaf digests refresh
        leaves = jax.vmap(lambda s: leaf_digests(s, TREE_DEPTH))(res.state)
        return res.state, res.ok, leaves

    # warmup / compile
    st = stacked
    for i in range(WARMUP):
        st, ok, leaves = merge_step(st, delta_states[i])
    ok.block_until_ready()
    assert bool(jnp.all(ok)), "capacity overflow in bench workload"
    log("tpu compile+warmup done")

    t0 = time.perf_counter()
    for i in range(ITERS):
        st, ok, leaves = merge_step(st, delta_states[WARMUP + i])
    leaves.block_until_ready()
    dt = time.perf_counter() - t0
    assert bool(jnp.all(ok))
    merges = ITERS * NEIGHBOURS
    log(f"tpu: {merges} merges in {dt:.3f}s")
    return merges / dt


# ---------------------------------------------------------------------------
# Python baseline (BEAM stand-in; see module docstring)

def bench_python(keys, deltas):
    num_buckets = 1 << TREE_DEPTH
    # state: key -> (pair=(valh, ts), dot=(node, ctr)); single-winner per key
    # (lean model of the nested dot store: the common case is one pair/dot
    # per key, which is what this workload produces)
    state = {}
    ctx = {11: 0}
    index = dict.fromkeys(range(num_buckets), 0)
    for i, k in enumerate(keys):
        kk = int(k)
        c = i + 1
        state[kk] = ((c, c), (11, c))
        ctx[11] = c
        index[kk & (num_buckets - 1)] ^= hash((kk, c))

    def merge(dkeys, dctrs):
        # per-key causal join + context union + index update
        changed = 0
        for k, c in zip(dkeys, dctrs):
            kk, cc = int(k), int(c)
            dot = (22, cc)
            cur = state.get(kk)
            covered = ctx.get(22, 0) >= cc
            if not covered:
                # s2 \ c1: incorporate the delta entry (LWW vs current)
                if cur is None or cur[0][1] <= cc:
                    state[kk] = ((cc, cc), dot)
                index[kk & (num_buckets - 1)] ^= hash((kk, cc))
                changed += 1
        # context union (per-node max over delta dots)
        top = int(dctrs[-1])
        if ctx.get(22, 0) < top:
            ctx[22] = top
        return changed

    t0 = time.perf_counter()
    n = 0
    for i in range(BASE_ITERS):
        dk, dc = deltas[i]
        merge(dk, dc)
        n += 1
    dt = time.perf_counter() - t0
    log(f"python baseline: {n} merges in {dt:.3f}s")
    return n / dt


def _device_backend_usable(timeout_s: float = 120.0) -> bool:
    """Probe whether the configured accelerator backend can initialise.

    Device init goes through an external claim that can hang indefinitely
    when the pool is wedged; probing in a subprocess with a watchdog keeps
    the bench from hanging the driver. Falls back to CPU (clearly
    labelled) when the accelerator is unreachable.
    """
    import subprocess

    if os.environ.get("JAX_PLATFORMS", "") in ("cpu", ""):
        return True
    try:
        proc = subprocess.run(
            [sys.executable, "-c", "import jax; jax.devices()"],
            timeout=timeout_s,
            capture_output=True,
        )
        return proc.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    fallback = os.environ.get("BENCH_FORCED_CPU") == "1"
    if not fallback and not _device_backend_usable():
        # the accelerator boot hook runs at interpreter start and taints
        # `import jax` in THIS process too — a clean re-exec with a
        # scrubbed env is the only reliable fallback
        log("accelerator backend unreachable — re-exec on CPU (labelled)")
        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        env["PALLAS_AXON_POOL_IPS"] = ""
        env["BENCH_FORCED_CPU"] = "1"
        os.execve(sys.executable, [sys.executable, os.path.abspath(__file__)], env)

    keys, deltas = make_workload()
    log(f"workload: {N_KEYS} keys, {NEIGHBOURS} neighbours, {DELTA}-entry deltas")
    py = bench_python(keys, deltas)
    tpu = bench_tpu(keys, deltas)
    metric = (
        "awlwwmap_1m_key_64_neighbour_merges_per_sec"
        if not SMOKE
        else "awlwwmap_smoke_merges_per_sec"
    )
    if fallback:
        metric += "_cpu_fallback"
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(tpu, 2),
                "unit": "merges/sec",
                "vs_baseline": round(tpu / py, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
