"""crdtlint command line.

Usage::

    python -m tools.crdtlint delta_crdt_ex_tpu            # lint, exit 1 on findings
    python -m tools.crdtlint delta_crdt_ex_tpu --write-baseline
    python -m tools.crdtlint delta_crdt_ex_tpu --baseline path.json
    python -m tools.crdtlint delta_crdt_ex_tpu --format github   # CI annotations
    python -m tools.crdtlint delta_crdt_ex_tpu --write-protocol-manifest
    python -m tools.crdtlint --list-rules

Exit codes: 0 clean (or fully suppressed), 1 unsuppressed findings,
2 usage/configuration error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.crdtlint.engine import Finding, load_baseline, run_lint, write_baseline

#: anchored beside this module, not the CWD: the installed ``crdtlint``
#: script must find the checked-in baseline from any working directory
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

RULE_CATALOG = [
    ("LOCK001", "access to a lock-guarded self._* attribute on a path that can "
                "run without the guarding lock held"),
    ("LOCK002", "lock acquisition-order cycle across methods/classes — two "
                "threads taking the locks in opposite orders deadlock"),
    ("LOCK003", "blocking call (fsync, socket I/O, sleep, Thread.join, "
                "Event.wait, block_until_ready, WAL segment roll) reachable "
                "while a lock is held"),
    ("RACE001", "shared mutable state (self._* attr or underscore module "
                "global) written on one thread root and accessed on another "
                "with no common lock and no happens-before edge"),
    ("RACE002", "mutable object captured by a thread-entry closure, mutated "
                "in the thread and used by the enclosing scope after start() "
                "(or vice versa) without join/handoff"),
    ("RACE003", "check-then-act on a version field: a lock-guarded monotone "
                "counter read outside its lock feeds a comparison before the "
                "lock is taken — stale by commit time"),
    ("RACE004", "attribute assigned after Thread.start() that the started "
                "thread reads — the init-race publication window"),
    ("RACE005", "lock-free iteration of a collection another thread root "
                "mutates (dict-changed-size / torn traversal)"),
    ("SYNC001", ".item()/.tolist()/int()/float()/np.asarray/device_get/"
                "block_until_ready inside a function reachable from a "
                "jax.jit / shard_map / pallas_call entry point"),
    ("SYNC002", "block_until_ready() in an op-library module (ops/, parallel/) "
                "— synchronisation belongs to the caller/bench harness"),
    ("PURE001", "join/merge/delta op mutates an argument pytree in place"),
    ("PURE002", "join/merge/delta op declares a module global"),
    ("PURE003", "join/merge/delta op calls time.*/random.*/secrets.* — "
                "nondeterministic joins diverge replica-to-replica"),
    ("DONATE001", "argument donated via donate_argnums/donate_argnames is read "
                  "again after the jitted call"),
    ("WIRE001", "wire message dataclass with no isinstance arm in any "
                "dispatch ladder — receivers raise on it"),
    ("WIRE002", "dispatch ladder arm that can never fire (class renamed/"
                "removed, or duplicated earlier in the ladder)"),
    ("WIRE003", "wire message field whose annotated type is not "
                "wire-serializable (plain data + numpy arrays only)"),
    ("WIRE004", "frame kind sent by a codec module but never compared on a "
                "receive path — peers drop it as unknown"),
    ("WIRE005", "wire message fields drifted from the checked-in protocol "
                "manifest (regenerate with --write-protocol-manifest after "
                "reviewing mixed-version compat)"),
    ("WAL001", "WAL record kind produced but missing a replay arm in the "
               "recovery dispatcher — durable records silently skipped"),
    ("WAL002", "WAL record kind produced without explicit serving "
               "classification in the log-shipping scan — catch-up silently "
               "degrades to the walk"),
    ("OBS001", "telemetry event declared without an emission site or without "
               "a metrics-bridge subscription row — the always-attached "
               "consumer drops it and its metrics read zero"),
    ("OBS002", "unguarded telemetry.execute in a hot-path module "
               "(replica/fleet/transports) — disabled telemetry still pays "
               "dict building there; guard with telemetry.has_handlers"),
    ("SHAPE001", "jit dispatch operand shaped by a raw data-dependent Python "
                 "size (len()-derived, never routed through a pow2/pow4 tier "
                 "or pad function) — unbounded recompiles"),
    ("SHAPE002", "static (hashable) argument at a jit call site outside the "
                 "closed geometry-key vocabulary — one fresh executable per "
                 "novel value"),
    ("LEAK001", "closure capturing a kernel-result pytree / Store / "
                "self.*state* escapes its defining scope (deferral list, "
                "attribute, telemetry) — pins superseded device buffers; "
                "narrow via default-arg capture of count/scalar leaves"),
    ("SPMD001", "shard_map-unsafe construct in a transition-contract module: "
                "host callback, Python branch on a replica-axis size, or "
                "axis-free reduction over the replica axis"),
    ("TRANSFER001", "device↔host crossing in a hot module (device_get/"
                    "device_put, np.asarray on a device value, .item()/"
                    ".tolist()/int()/float(), host iteration) that bypasses "
                    "the audited transfer-ledger shim (utils/transfers)"),
    ("TRANSFER002", "transfer-ledger site hygiene: non-literal site label, "
                    "duplicate label (counts would merge), or ghost label "
                    "(registered but never used)"),
    ("FAULT001", "torn-invariant window: commit-group writes (_seq/"
                 "_serve_pub/_outstanding/_ack_seq) with a raise-capable "
                 "durability/fault-point call interposed and no try/finally "
                 "restoring the group"),
    ("FAULT002", "bare/broad except in a hot module that neither re-raises, "
                 "logs, flight-records, nor reads the bound exception — "
                 "injected faults vanish into a wedged replica"),
    ("FAULT003", "commit-ordering violation: state published (_publish_serve/"
                 "_note_state_changed/_emit_diffs/_serve_pub store) before "
                 "the unit's WAL append — a crash in between loses work "
                 "readers already observed"),
    ("FAULT004", "terminal method (stop/close/crash/shutdown) that never "
                 "reaches a constructed resource's cleanup (Thread.join, "
                 "WalLog/socket close) — leaks on that path"),
    ("FAULT005", "fault-point label hygiene: non-literal faultpoint label, "
                 "label outside the SITES vocabulary, one label at two call "
                 "sites, or a SITES entry no call site uses"),
    ("SUPPRESS001", "stale allow[...] comment matching no finding (hygiene; "
                    "not itself suppressible)"),
    ("SUPPRESS002", "stale baseline entry matching no finding (hygiene; "
                    "not itself suppressible)"),
    ("SUPPRESS003", "expired allow[RULE expires=YYYY-MM-DD] comment — "
                    "re-justify with a new date or fix the finding "
                    "(hygiene; not itself suppressible)"),
]


def main(argv: list[str] | None = None) -> int:
    try:
        return _main(argv)
    except BrokenPipeError:
        # stdout went away mid-report (e.g. `crdtlint ... | head`): the
        # consumer saw a truncated report, so a gate must NOT read this
        # as clean — fail conservatively instead of crashing
        return 1


def _main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="crdtlint",
        description="AST-based static analysis for the delta-CRDT TPU runtime: "
        "lock discipline, JAX host-sync leaks, lattice-op purity, "
        "donation hygiene.",
    )
    parser.add_argument(
        "packages", nargs="*",
        help="package directories to lint (e.g. delta_crdt_ex_tpu)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help=f"baseline file of accepted pre-existing findings "
        f"(default: {DEFAULT_BASELINE} when it exists)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file; report every finding",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="record current unsuppressed findings into the baseline file "
        "and exit 0",
    )
    parser.add_argument(
        "--select", action="append", default=None, metavar="RULE",
        help="only run the given rule id(s) (repeatable; disables the "
        "stale-suppression hygiene pass, which needs a full run)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "--format", choices=("text", "github", "sarif"), default="text",
        help="finding output format: plain text (default), GitHub "
        "Actions ::error annotations for CI logs, or a SARIF 2.1.0 "
        "document on stdout for code-scanning upload",
    )
    parser.add_argument(
        "--manifest", type=Path, default=None,
        help="protocol manifest for the WIRE005 wire-compat lock "
        "(default: the checked-in protocol_manifest.json)",
    )
    parser.add_argument(
        "--write-protocol-manifest", action="store_true",
        help="record the current wire-message field lists into the "
        "protocol manifest and exit 0 (do this AFTER reviewing "
        "mixed-version wire compat for any changed message)",
    )
    parser.add_argument(
        "--no-hygiene", action="store_true",
        help="skip the stale-suppression hygiene pass (SUPPRESS001/2)",
    )
    parser.add_argument(
        "--jobs", type=int, default=1, metavar="N",
        help="run the rule families in N worker processes (findings and "
        "their order are identical to a serial run; sharding is per-rule, "
        "not per-file — most families are whole-project analyses)",
    )
    parser.add_argument(
        "--stats", action="store_true",
        help="print per-rule wall-clock timing after the report",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress the summary line (findings only)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in RULE_CATALOG:
            print(f"{rule:10s} {desc}")
        return 0

    if not args.packages:
        parser.error("at least one package directory is required")

    package_dirs: list[Path] = []
    for pkg in args.packages:
        p = Path(pkg)
        if not p.is_dir() or not (p / "__init__.py").exists():
            print(f"crdtlint: {pkg!r} is not a package directory", file=sys.stderr)
            return 2
        package_dirs.append(p)

    baseline_path = args.baseline or DEFAULT_BASELINE
    baseline = None
    if not args.no_baseline and not args.write_baseline and baseline_path.exists():
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, KeyError) as e:
            print(f"crdtlint: bad baseline {baseline_path}: {e}", file=sys.stderr)
            return 2

    select = set(args.select) if args.select else None
    if select:
        known = {rule for rule, _desc in RULE_CATALOG}
        bad = select - known
        if bad:
            # a typo'd selection must not turn the gate vacuously green
            print(
                f"crdtlint: unknown rule id(s) {sorted(bad)}; "
                f"known: {sorted(known)}",
                file=sys.stderr,
            )
            return 2
    if args.write_protocol_manifest:
        return _write_protocol_manifest(package_dirs, args.manifest)

    if args.jobs < 1:
        print("crdtlint: --jobs must be >= 1", file=sys.stderr)
        return 2
    rule_stats: dict[str, float] = {}
    new, baselined, allowed = run_lint(
        package_dirs, baseline=baseline, select=select,
        manifest=args.manifest,
        hygiene=not (args.no_hygiene or args.write_baseline),
        jobs=args.jobs,
        stats_out=rule_stats if args.stats else None,
    )

    if args.write_baseline:
        # hygiene meta-findings must never be WRITTEN as accepted debt
        entries = [f for f in new if not f.rule.startswith("SUPPRESS")]
        if select and baseline_path.exists():
            # a selective rewrite must not discard other rules' accepted
            # debt: carry over every baselined entry outside the selection
            kept = load_baseline(baseline_path)
            for (path, rule, message), count in kept.items():
                if rule not in select:
                    entries.extend(
                        Finding(path, 0, rule, message) for _ in range(count)
                    )
        write_baseline(baseline_path, entries)
        print(
            f"crdtlint: wrote {len(entries)} finding(s) to {baseline_path} "
            f"({len(allowed)} allow-commented occurrences left inline)"
        )
        return 0

    if args.format == "sarif":
        # one machine-readable document on stdout, nothing else: the
        # consumer is a code-scanning uploader, not a human
        print(_sarif_report(new))
        if args.stats or not args.quiet:
            print(
                f"crdtlint: {len(new)} finding(s) "
                f"({len(allowed)} allowed inline, {len(baselined)} baselined)",
                file=sys.stderr,
            )
        return 1 if new else 0
    for f in new:
        if args.format == "github":
            # GitHub Actions workflow-command annotation: renders the
            # finding inline on the PR diff from a plain CI log line
            print(
                f"::error file={f.path},line={max(f.line, 1)},"
                f"title=crdtlint {f.rule}::{f.message}"
            )
        else:
            print(f.render())
    if args.stats:
        total = sum(rule_stats.values())
        for name, dt in sorted(rule_stats.items(), key=lambda kv: -kv[1]):
            print(f"crdtlint: timing {name:24s} {dt * 1000:8.1f} ms")
        print(f"crdtlint: timing {'total':24s} {total * 1000:8.1f} ms")
    if not args.quiet:
        print(
            f"crdtlint: {len(new)} finding(s) "
            f"({len(allowed)} allowed inline, {len(baselined)} baselined)"
        )
    return 1 if new else 0


def _sarif_report(findings: list[Finding]) -> str:
    """SARIF 2.1.0 document for code-scanning UIs: rule metadata comes
    from the catalog (the single source the gate, --list-rules, and
    --select validate against), each finding one ``result`` keyed by
    ``ruleIndex`` into it."""
    import json

    rule_index = {rule: i for i, (rule, _desc) in enumerate(RULE_CATALOG)}
    rules = [
        {"id": rule, "shortDescription": {"text": desc}}
        for rule, desc in RULE_CATALOG
    ]
    results = [
        {
            "ruleId": f.rule,
            # findings can only carry catalogued rule ids (the select
            # validation enforces the same closed set)
            "ruleIndex": rule_index[f.rule],
            "level": "error",
            "message": {"text": f.message},
            "locations": [
                {
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.path},
                        # SUPPRESS002 baseline entries carry line 0;
                        # SARIF regions are 1-based
                        "region": {"startLine": max(f.line, 1)},
                    }
                }
            ],
        }
        for f in findings
    ]
    doc = {
        "version": "2.1.0",
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "runs": [
            {
                "tool": {
                    "driver": {
                        "name": "crdtlint",
                        "informationUri":
                            "https://example.invalid/tools/crdtlint",
                        "rules": rules,
                    }
                },
                "results": results,
            }
        ],
    }
    return json.dumps(doc, indent=2)


def _write_protocol_manifest(package_dirs: list[Path], manifest: Path | None) -> int:
    from tools.crdtlint.engine import Project
    from tools.crdtlint.rules.wire import (
        DEFAULT_MANIFEST,
        compute_manifest,
        load_manifest,
        write_manifest,
    )

    path = manifest or DEFAULT_MANIFEST
    try:
        packages = load_manifest(path).get("packages", {})
    except (FileNotFoundError, ValueError, AttributeError):
        packages = {}
    if not isinstance(packages, dict):
        packages = {}  # structurally mangled manifest: rebuild from scratch
    wrote = []
    for pkg in package_dirs:
        project = Project(pkg)
        stanza = compute_manifest(project)
        if stanza is None:
            print(
                f"crdtlint: {pkg} defines no wire-message protocol module; "
                f"nothing recorded", file=sys.stderr,
            )
            continue
        packages[project.package_name] = stanza
        wrote.append(project.package_name)
    write_manifest(path, packages)
    print(f"crdtlint: wrote protocol manifest for {wrote} to {path}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
