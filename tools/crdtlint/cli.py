"""crdtlint command line.

Usage::

    python -m tools.crdtlint delta_crdt_ex_tpu            # lint, exit 1 on findings
    python -m tools.crdtlint delta_crdt_ex_tpu --write-baseline
    python -m tools.crdtlint delta_crdt_ex_tpu --baseline path.json
    python -m tools.crdtlint --list-rules

Exit codes: 0 clean (or fully suppressed), 1 unsuppressed findings,
2 usage/configuration error.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from tools.crdtlint.engine import Finding, load_baseline, run_lint, write_baseline

#: anchored beside this module, not the CWD: the installed ``crdtlint``
#: script must find the checked-in baseline from any working directory
DEFAULT_BASELINE = Path(__file__).resolve().parent / "baseline.json"

RULE_CATALOG = [
    ("LOCK001", "access to a lock-guarded self._* attribute on a path that can "
                "run without the guarding lock held"),
    ("SYNC001", ".item()/.tolist()/int()/float()/np.asarray/device_get/"
                "block_until_ready inside a function reachable from a "
                "jax.jit / shard_map / pallas_call entry point"),
    ("SYNC002", "block_until_ready() in an op-library module (ops/, parallel/) "
                "— synchronisation belongs to the caller/bench harness"),
    ("PURE001", "join/merge/delta op mutates an argument pytree in place"),
    ("PURE002", "join/merge/delta op declares a module global"),
    ("PURE003", "join/merge/delta op calls time.*/random.*/secrets.* — "
                "nondeterministic joins diverge replica-to-replica"),
    ("DONATE001", "argument donated via donate_argnums/donate_argnames is read "
                  "again after the jitted call"),
]


def main(argv: list[str] | None = None) -> int:
    try:
        return _main(argv)
    except BrokenPipeError:
        # stdout went away mid-report (e.g. `crdtlint ... | head`): the
        # consumer saw a truncated report, so a gate must NOT read this
        # as clean — fail conservatively instead of crashing
        return 1


def _main(argv: list[str] | None = None) -> int:
    parser = argparse.ArgumentParser(
        prog="crdtlint",
        description="AST-based static analysis for the delta-CRDT TPU runtime: "
        "lock discipline, JAX host-sync leaks, lattice-op purity, "
        "donation hygiene.",
    )
    parser.add_argument(
        "packages", nargs="*",
        help="package directories to lint (e.g. delta_crdt_ex_tpu)",
    )
    parser.add_argument(
        "--baseline", type=Path, default=None,
        help=f"baseline file of accepted pre-existing findings "
        f"(default: {DEFAULT_BASELINE} when it exists)",
    )
    parser.add_argument(
        "--no-baseline", action="store_true",
        help="ignore any baseline file; report every finding",
    )
    parser.add_argument(
        "--write-baseline", action="store_true",
        help="record current unsuppressed findings into the baseline file "
        "and exit 0",
    )
    parser.add_argument(
        "--select", action="append", default=None, metavar="RULE",
        help="only run the given rule id(s) (repeatable)",
    )
    parser.add_argument(
        "--list-rules", action="store_true", help="print the rule catalog"
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true",
        help="suppress the summary line (findings only)",
    )
    args = parser.parse_args(argv)

    if args.list_rules:
        for rule, desc in RULE_CATALOG:
            print(f"{rule:10s} {desc}")
        return 0

    if not args.packages:
        parser.error("at least one package directory is required")

    package_dirs: list[Path] = []
    for pkg in args.packages:
        p = Path(pkg)
        if not p.is_dir() or not (p / "__init__.py").exists():
            print(f"crdtlint: {pkg!r} is not a package directory", file=sys.stderr)
            return 2
        package_dirs.append(p)

    baseline_path = args.baseline or DEFAULT_BASELINE
    baseline = None
    if not args.no_baseline and not args.write_baseline and baseline_path.exists():
        try:
            baseline = load_baseline(baseline_path)
        except (ValueError, KeyError) as e:
            print(f"crdtlint: bad baseline {baseline_path}: {e}", file=sys.stderr)
            return 2

    select = set(args.select) if args.select else None
    if select:
        known = {rule for rule, _desc in RULE_CATALOG}
        bad = select - known
        if bad:
            # a typo'd selection must not turn the gate vacuously green
            print(
                f"crdtlint: unknown rule id(s) {sorted(bad)}; "
                f"known: {sorted(known)}",
                file=sys.stderr,
            )
            return 2
    new, baselined, allowed = run_lint(
        package_dirs, baseline=baseline, select=select
    )

    if args.write_baseline:
        entries = list(new)
        if select and baseline_path.exists():
            # a selective rewrite must not discard other rules' accepted
            # debt: carry over every baselined entry outside the selection
            kept = load_baseline(baseline_path)
            for (path, rule, message), count in kept.items():
                if rule not in select:
                    entries.extend(
                        Finding(path, 0, rule, message) for _ in range(count)
                    )
        write_baseline(baseline_path, entries)
        print(
            f"crdtlint: wrote {len(entries)} finding(s) to {baseline_path} "
            f"({len(allowed)} allow-commented occurrences left inline)"
        )
        return 0

    for f in new:
        print(f.render())
    if not args.quiet:
        print(
            f"crdtlint: {len(new)} finding(s) "
            f"({len(allowed)} allowed inline, {len(baselined)} baselined)"
        )
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
